"""Exporters: JSONL event stream, Prometheus textfile, terminal summary.

All file emission is gated by the session's multi-host check (only
``process_index == 0`` writes — see ``obs.configure``); exporters
themselves are host-agnostic and never raise into the run.
"""

from __future__ import annotations

import json
import math
import os
from typing import Dict, Optional

from torchpruner_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)


class JsonlWriter:
    """Append JSON objects to ``path``, one per line, flushed per write
    (a crashed run keeps every event up to the crash).  The handle is
    opened once and held — not reopened per event.

    ``rotate_bytes > 0`` enables size-based rotation: when the file
    exceeds the cap after a write, it is renamed to ``path.1`` (existing
    ``path.1`` → ``path.2``, … up to ``backups``; the oldest falls off)
    and a fresh ``path`` is opened — long runs stop growing
    ``events.jsonl`` without bound.  Readers
    (``utils.profiling.load_span_events``) walk the rotated set oldest-
    first, so summaries still see the whole stream.  Off by default
    (0): tests and short runs keep the single-file layout."""

    def __init__(self, path: str, rotate_bytes: int = 0, backups: int = 3):
        self.path = path
        self.rotate_bytes = int(rotate_bytes or 0)
        self.backups = max(1, int(backups))
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "a")
        self._size = self._f.tell()

    def __call__(self, obj: dict):
        line = json.dumps(obj) + "\n"
        self._f.write(line)
        self._f.flush()
        self._size += len(line)
        if self.rotate_bytes and self._size > self.rotate_bytes:
            self._rotate()

    def _rotate(self):
        try:
            self._f.close()
            for i in range(self.backups - 1, 0, -1):
                src = f"{self.path}.{i}"
                if os.path.exists(src):
                    os.replace(src, f"{self.path}.{i + 1}")
            os.replace(self.path, self.path + ".1")
        except Exception:
            pass  # rotation failure must never kill the run
        self._f = open(self.path, "a")
        self._size = self._f.tell()

    def close(self):
        try:
            self._f.close()
        except Exception:
            pass


def prometheus_text(registry: MetricsRegistry) -> str:
    """Render the registry in the Prometheus text exposition format
    (counters as ``_total``-suffixed names they already carry, histograms
    as cumulative ``_bucket{le=...}`` series plus ``_sum``/``_count``)."""
    lines = []
    for m in registry:
        if isinstance(m, Counter):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} counter")
            lines.append(f"{m.name} {_fmt(m.value)}")
        elif isinstance(m, Gauge):
            if m.value is None:
                continue
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} gauge")
            lines.append(f"{m.name} {_fmt(m.value)}")
        elif isinstance(m, Histogram):
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} histogram")
            cum = 0
            for b, c in zip(m.buckets, m.counts):
                cum += c
                lines.append(f'{m.name}_bucket{{le="{_fmt(b)}"}} {cum}')
            cum += m.counts[-1]
            lines.append(f'{m.name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{m.name}_sum {_fmt(m.sum)}")
            lines.append(f"{m.name}_count {m.count}")
            if m.count:
                # bucket-estimated percentiles as companion gauges (a
                # textfile collector has no query engine to run
                # histogram_quantile, so the snapshot ships them)
                for k, v in m.percentiles().items():
                    lines.append(f"# TYPE {m.name}_{k} gauge")
                    lines.append(f"{m.name}_{k} {_fmt(v)}")
    return "\n".join(lines) + "\n"


def _fmt(v: float) -> str:
    if v is None or (isinstance(v, float) and math.isnan(v)):
        return "NaN"
    f = float(v)
    return repr(int(f)) if f == int(f) and abs(f) < 1e15 else repr(f)


def write_prometheus(registry: MetricsRegistry, path: str):
    """Atomic textfile write (node-exporter textfile-collector style:
    scrapers must never see a torn file)."""
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(prometheus_text(registry))
    os.replace(tmp, path)


def summary_table(
    phase_summary: Dict[str, Dict[str, float]],
    derived: Optional[Dict[str, Optional[float]]] = None,
    compile_totals: Optional[dict] = None,
    total_wall_s: Optional[float] = None,
) -> str:
    """The end-of-run terminal summary: per-phase wall/compile table plus
    the step-telemetry line.  ``total_wall_s`` (the root span / whole run)
    anchors the ``%`` column; per-phase rows are leaf-attributed (a parent
    span's own row excludes time its children claimed only in the sense
    that children get their own rows — the ``%`` column uses each row's
    total, so nested rows can sum past 100)."""
    lines = ["", "── observability summary " + "─" * 35]
    if phase_summary:
        w = max(len(n) for n in phase_summary)
        lines.append(
            f"{'phase':<{w}}  {'calls':>5}  {'wall s':>9}  {'%':>6}  "
            f"{'compile s':>9}  {'compiles':>8}"
        )
        denom = total_wall_s or sum(
            v["total_s"] for n, v in phase_summary.items()
        ) or 1.0
        for name, v in phase_summary.items():
            lines.append(
                f"{name:<{w}}  {v['calls']:>5d}  {v['total_s']:>9.3f}  "
                f"{100 * v['total_s'] / denom:>5.1f}%  "
                f"{v['compile_s']:>9.3f}  {int(v['compile_count']):>8d}"
            )
    if compile_totals:
        lines.append(
            f"compile: {compile_totals['compile_count']} compilations "
            f"({compile_totals['compile_s']:.3f}s), "
            f"{compile_totals['trace_count']} traces "
            f"({compile_totals['trace_s']:.3f}s)"
        )
    if derived and derived.get("steps"):
        parts = [f"steps {derived['steps']}",
                 f"step {1e3 * derived['step_time_mean_s']:.2f} ms"]
        if derived.get("step_time_p50_s") is not None:
            parts.append(
                "p50/p95/p99 "
                f"{1e3 * derived['step_time_p50_s']:.2f}/"
                f"{1e3 * derived['step_time_p95_s']:.2f}/"
                f"{1e3 * derived['step_time_p99_s']:.2f} ms")
        if derived.get("examples_per_s"):
            parts.append(f"{derived['examples_per_s']:.1f} ex/s")
        if derived.get("tokens_per_s"):
            parts.append(f"{derived['tokens_per_s']:.0f} tok/s")
        if derived.get("mfu") is not None:
            parts.append(f"MFU {100 * derived['mfu']:.1f}%")
        lines.append("train: " + ", ".join(parts))
    if total_wall_s is not None:
        lines.append(f"total wall: {total_wall_s:.3f}s")
    lines.append("─" * 60)
    return "\n".join(lines)

"""Compile-vs-execute accounting via ``jax.monitoring`` listeners.

Every jit compilation fires ``/jax/core/compile/backend_compile_duration``
and every (re)trace fires ``/jax/core/compile/jaxpr_trace_duration`` on
the thread doing the work.  Counting them during a run answers the
questions the static analyzer (tpu-lint) can only predict: how many
recompiles did this prune schedule actually trigger, and how many
seconds went to the compiler instead of the accelerator — attributed to
the phase (span) that paid them.

The listener registry is process-global in JAX, so :class:`CompileWatcher`
keeps exactly one listener registered between :meth:`start` and
:meth:`stop` and guards double-starts; the monitoring module is private
(``jax._src.monitoring``), so every touch is wrapped — on a JAX version
without it the watcher degrades to inert counters instead of failing.
"""

from __future__ import annotations

from typing import Callable, Optional

#: monitoring event key → (kind charged to spans, counter name)
_EVENTS = {
    "/jax/core/compile/backend_compile_duration":
        ("compile", "compile_count_total", "compile_seconds_total"),
    "/jax/core/compile/jaxpr_trace_duration":
        ("trace", "trace_count_total", "trace_seconds_total"),
    "/jax/core/compile/jaxpr_to_mlir_module_duration":
        (None, "lower_count_total", "lower_seconds_total"),
}


class CompileWatcher:
    """Counts compilations/retraces into ``registry`` and charges their
    seconds to the innermost active span of ``tracer``."""

    def __init__(self, registry, tracer=None):
        self.registry = registry
        self.tracer = tracer
        self._listener: Optional[Callable] = None
        for _, cname, sname in _EVENTS.values():
            registry.counter(cname)
            registry.counter(sname)

    def start(self):
        if self._listener is not None:
            return
        try:
            from jax._src import monitoring
        except Exception:
            return

        def listener(event: str, duration_secs: float, **kw):
            spec = _EVENTS.get(event)
            if spec is None:
                return
            kind, cname, sname = spec
            self.registry.counter(cname).inc()
            self.registry.counter(sname).inc(duration_secs)
            if kind is not None and self.tracer is not None:
                self.tracer.attribute_compile(kind, duration_secs)

        try:
            monitoring.register_event_duration_secs_listener(listener)
            self._listener = listener
        except Exception:
            self._listener = None

    def stop(self):
        if self._listener is None:
            return
        try:
            from jax._src import monitoring

            monitoring._unregister_event_duration_listener_by_callback(
                self._listener
            )
        except Exception:
            pass
        self._listener = None

    def counts(self) -> dict:
        """Current totals, rounded for reporting."""
        g = self.registry.counter
        return {
            "compile_count": int(g("compile_count_total").value),
            "compile_s": round(g("compile_seconds_total").value, 3),
            "trace_count": int(g("trace_count_total").value),
            "trace_s": round(g("trace_seconds_total").value, 3),
            "lower_count": int(g("lower_count_total").value),
            "lower_s": round(g("lower_seconds_total").value, 3),
        }

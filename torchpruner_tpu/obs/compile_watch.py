"""Compile-vs-execute accounting via ``jax.monitoring`` listeners.

Every jit compilation fires ``/jax/core/compile/backend_compile_duration``
and every (re)trace fires ``/jax/core/compile/jaxpr_trace_duration`` on
the thread doing the work.  Counting them during a run answers the
questions the static analyzer (tpu-lint) can only predict: how many
recompiles did this prune schedule actually trigger, and how many
seconds went to the compiler instead of the accelerator — attributed to
the phase (span) that paid them.

The listener registry is process-global in JAX, so :class:`CompileWatcher`
keeps exactly one listener registered between :meth:`start` and
:meth:`stop` and guards double-starts; the monitoring module is private
(``jax._src.monitoring``), so every touch is wrapped — on a JAX version
without it the watcher degrades to inert counters instead of failing.

**Per-executable attribution**: the monitoring events carry no function
name, but the dispatch logger's companion message ("Finished XLA
compilation of {fun_name} in {t} sec") does — so the watcher also
attaches a logging handler to ``jax._src.dispatch`` (lowering its level
to DEBUG for the session, restored on :meth:`stop`; the root handler's
WARNING threshold keeps the records off the console) and parses the
name out.  That turns "18 s went to the compiler" into "14 s of it was
``jit(train_step)``, recompiled 3×" — surfaced as the top-compilers
table in ``obs report``.
"""

from __future__ import annotations

import logging
import re
from typing import Callable, Dict, Optional

#: the jax logger whose messages name the compiled executable
_DISPATCH_LOGGER = "jax._src.dispatch"

_COMPILE_MSG = re.compile(
    r"Finished XLA compilation of (.+?) in ([0-9.eE+-]+) sec")


class _CompileLogHandler(logging.Handler):
    """Parses executable names + compile seconds out of the dispatch
    logger's messages into ``watcher.by_executable``."""

    def __init__(self, sink: Dict[str, Dict[str, float]]):
        super().__init__(level=logging.DEBUG)
        self.sink = sink

    def emit(self, record: logging.LogRecord) -> None:  # never raises
        try:
            m = _COMPILE_MSG.match(record.getMessage())
            if m:
                name, secs = m.group(1), float(m.group(2))
                agg = self.sink.setdefault(name,
                                           {"count": 0, "seconds": 0.0})
                agg["count"] += 1
                agg["seconds"] += secs
            # propagation is off while attached (the DEBUG level we
            # forced would spam the console) — records that were visible
            # BEFORE (jax_log_compiles logs at WARNING) still reach the
            # root handlers
            if record.levelno >= logging.WARNING:
                logging.getLogger().handle(record)
        except Exception:
            pass

#: monitoring event key → (kind charged to spans, counter name)
_EVENTS = {
    "/jax/core/compile/backend_compile_duration":
        ("compile", "compile_count_total", "compile_seconds_total"),
    "/jax/core/compile/jaxpr_trace_duration":
        ("trace", "trace_count_total", "trace_seconds_total"),
    "/jax/core/compile/jaxpr_to_mlir_module_duration":
        (None, "lower_count_total", "lower_seconds_total"),
}


class CompileWatcher:
    """Counts compilations/retraces into ``registry`` and charges their
    seconds to the innermost active span of ``tracer``."""

    def __init__(self, registry, tracer=None):
        self.registry = registry
        self.tracer = tracer
        self._listener: Optional[Callable] = None
        #: executable name -> {"count", "seconds"} (dispatch-logger
        #: attribution; empty when the logger path is unavailable)
        self.by_executable: Dict[str, Dict[str, float]] = {}
        self._log_handler: Optional[_CompileLogHandler] = None
        self._log_prior_level: Optional[int] = None
        for _, cname, sname in _EVENTS.values():
            registry.counter(cname)
            registry.counter(sname)

    def start(self):
        if self._listener is not None:
            return
        self._start_log_attribution()
        try:
            from jax._src import monitoring
        except Exception:
            return

        def listener(event: str, duration_secs: float, **kw):
            spec = _EVENTS.get(event)
            if spec is None:
                return
            kind, cname, sname = spec
            self.registry.counter(cname).inc()
            self.registry.counter(sname).inc(duration_secs)
            if kind is not None and self.tracer is not None:
                self.tracer.attribute_compile(kind, duration_secs)

        try:
            monitoring.register_event_duration_secs_listener(listener)
            self._listener = listener
        except Exception:
            self._listener = None

    def _start_log_attribution(self):
        if self._log_handler is not None:
            return
        try:
            logger = logging.getLogger(_DISPATCH_LOGGER)
            self._log_handler = _CompileLogHandler(self.by_executable)
            self._log_prior_level = logger.level
            self._log_prior_propagate = logger.propagate
            if not logger.isEnabledFor(logging.DEBUG):
                logger.setLevel(logging.DEBUG)
            logger.propagate = False  # handler forwards WARNING+ itself
            logger.addHandler(self._log_handler)
        except Exception:
            self._log_handler = None

    def stop(self):
        if self._log_handler is not None:
            try:
                logger = logging.getLogger(_DISPATCH_LOGGER)
                logger.removeHandler(self._log_handler)
                if self._log_prior_level is not None:
                    logger.setLevel(self._log_prior_level)
                logger.propagate = getattr(
                    self, "_log_prior_propagate", True)
            except Exception:
                pass
            self._log_handler = None
        if self._listener is None:
            return
        try:
            from jax._src import monitoring

            monitoring._unregister_event_duration_listener_by_callback(
                self._listener
            )
        except Exception:
            pass
        self._listener = None

    def top_compilers(self, n: int = 5) -> list:
        """The executables that paid the most compile seconds:
        ``[{"name", "count", "seconds"}, ...]``, most expensive first."""
        rows = [{"name": name, "count": int(v["count"]),
                 "seconds": round(v["seconds"], 3)}
                for name, v in self.by_executable.items()]
        rows.sort(key=lambda r: -r["seconds"])
        return rows[:n]

    def counts(self) -> dict:
        """Current totals, rounded for reporting."""
        g = self.registry.counter
        out = {
            "compile_count": int(g("compile_count_total").value),
            "compile_s": round(g("compile_seconds_total").value, 3),
            "trace_count": int(g("trace_count_total").value),
            "trace_s": round(g("trace_seconds_total").value, 3),
            "lower_count": int(g("lower_count_total").value),
            "lower_s": round(g("lower_seconds_total").value, 3),
        }
        if self.by_executable:
            out["by_executable"] = self.top_compilers()
        return out

"""Fleet-wide per-request tracing: stages, exemplars, latency budget.

The obs stack measures tail latency (``serve_ttft_seconds`` /
``serve_token_seconds`` histograms); this module explains it.  Every
accepted request carries one ``trace_id`` minted by the router at
acceptance, propagated to the serving replica inside the dispatch
payload, and every hop records **stage events** against it::

    accept → journal_flush → dispatch_wait (per attempt, incl. backoff)
           → replica_queue → admission → prefill → first_token
           → decode → complete
    (+ failover stages: redrive, swap_stall, shed)

Stages land in two places:

- **always** — per-stage aggregate histograms
  (``reqtrace_stage_<stage>_seconds``), which ride the ordinary metric
  shards, merge across replicas, and feed :func:`latency_budget` — the
  per-stage p50/p99 contributions to TTFT and E2E that reconcile
  against the measured serving histograms;
- **for exemplars only** — full-detail ``req_stage`` events in the
  session's ``events.jsonl``, later assembled into per-request
  cross-process waterfalls (``obs.trace_export`` /
  ``fleet.report.write_fleet_trace``).

Overhead is bounded by the exemplar policy: with
``sample_every > 1`` a request's stage events are BUFFERED in memory
and only flushed when the request is (a) a deterministic 1-in-N sample
(stable hash of the trace id, so every process in the fleet flushes
the SAME requests) or (b) among the slowest-K completions of its
window; everything else contributes to the aggregate histograms only.
``sample_every <= 1`` (the failover drill, short CI runs) switches to
EAGER emission — each stage event is written as it happens, so a
``kill -9``'d replica's partial trace survives on disk and the
assembled waterfall shows the dead attempt next to the redrive.

Everything degrades to (near) no-ops without an active obs session.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
import zlib
from typing import Any, Dict, List, Optional

from torchpruner_tpu import obs

#: flush full detail for 1 request in N (deterministic on the trace id;
#: <=1 = eager emission of every stage event)
DEFAULT_SAMPLE_EVERY = 16
#: per window, additionally flush the K slowest completions
DEFAULT_SLOWEST_K = 8
#: completions per slowest-K window
DEFAULT_WINDOW = 64
#: open-trace buffer cap — a leaked/never-finished trace must not grow
#: memory without bound (evictions are counted, oldest first)
MAX_OPEN_TRACES = 4096

#: env overrides (the serve/fleet CLIs also expose --trace-sample-every)
SAMPLE_EVERY_ENV = "TORCHPRUNER_REQTRACE_SAMPLE_EVERY"
SLOWEST_K_ENV = "TORCHPRUNER_REQTRACE_SLOWEST_K"
WINDOW_ENV = "TORCHPRUNER_REQTRACE_WINDOW"

#: replica-side stages whose durations sum to the measured TTFT
#: (``serve_ttft_seconds`` = arrival → first token on the replica):
#: queue wait, admit-batch bookkeeping, and the prefill program
TTFT_STAGES = ("replica_queue", "admission", "prefill")
#: stages whose durations are charged against the router-side E2E
#: (``reqtrace_e2e_seconds`` = accept → completion); the remainder is
#: reported as ``unattributed`` (transport, failed attempts on a dead
#: replica whose shard never shipped, scheduling gaps)
E2E_STAGES = ("journal_flush", "dispatch_wait", "swap_stall",
              "replica_queue", "admission", "prefill", "decode")

_ids = itertools.count()


def mint_trace_id(tag: str = "r") -> str:
    """A fleet-unique trace id: os pid + monotonic counter keeps ids
    from colliding across router restarts sharing a journal; ``tag``
    (usually the plane rid) keeps them greppable."""
    return f"tr-{tag}-{os.getpid():x}-{next(_ids):04x}"


def is_sampled(trace_id: str, sample_every: int) -> bool:
    """Deterministic 1-in-N exemplar membership — a stable hash of the
    trace id, so the router and every replica flush the SAME subset
    without coordination."""
    if sample_every <= 1:
        return True
    return zlib.crc32(trace_id.encode()) % int(sample_every) == 0


class ReqTraceRecorder:
    """Per-process stage recorder (see module docstring).  Thread-safe:
    stages arrive from the engine loop, HTTP handler threads, and the
    router's dispatch workers."""

    def __init__(self, sample_every: Optional[int] = None,
                 slowest_k: Optional[int] = None,
                 window: Optional[int] = None):
        def env_int(name, default):
            try:
                return int(os.environ.get(name, "") or default)
            except ValueError:
                return default

        self.sample_every = (env_int(SAMPLE_EVERY_ENV, DEFAULT_SAMPLE_EVERY)
                             if sample_every is None else int(sample_every))
        self.slowest_k = (env_int(SLOWEST_K_ENV, DEFAULT_SLOWEST_K)
                          if slowest_k is None else int(slowest_k))
        self.window = (env_int(WINDOW_ENV, DEFAULT_WINDOW)
                       if window is None else int(window))
        self._lock = threading.Lock()
        #: trace id -> buffered req_stage event dicts (sampled mode)
        self._open: Dict[str, List[dict]] = {}
        #: current slowest-K window: (e2e_s, trace_id, events, summary)
        self._window: List[tuple] = []
        self.evictions = 0

    @property
    def eager(self) -> bool:
        return self.sample_every <= 1

    def configure(self, *, sample_every: Optional[int] = None,
                  slowest_k: Optional[int] = None,
                  window: Optional[int] = None) -> None:
        # under the lock: recording threads consult these knobs while
        # mutating the slowest-K heap, so a reconfigure must not
        # interleave with an in-flight finish()
        with self._lock:
            if sample_every is not None:
                self.sample_every = int(sample_every)
            if slowest_k is not None:
                self.slowest_k = int(slowest_k)
            if window is not None:
                self.window = int(window)

    # -- recording -----------------------------------------------------------

    def stage(self, trace_id: Optional[str], stage: str,
              dur_s: float = 0.0, t_start: Optional[float] = None,
              **meta) -> None:
        """Record one stage against a trace.  ``dur_s`` feeds the
        always-on aggregate histogram (instant stages — dur 0 — feed a
        ``_total`` counter instead); the full event is emitted (eager)
        or buffered (sampled) for the waterfall.  No-op trace id = the
        request is untraced (single-replica serving without a fleet in
        front) — aggregates still record."""
        dur_s = float(dur_s or 0.0)
        if dur_s > 0.0:
            obs.observe(f"reqtrace_stage_{stage}_seconds", dur_s,
                        help=f"per-request {stage} stage duration "
                             "(reqtrace latency budget)")
        else:
            obs.inc(f"reqtrace_stage_{stage}_total",
                    help=f"per-request {stage} stage events (instant)")
        if not trace_id:
            return
        ev = {
            "event": "req_stage", "trace": trace_id, "stage": stage,
            "ts": (time.time() - dur_s) if t_start is None
            else float(t_start),
            "dur_s": round(dur_s, 9), "pid": os.getpid(),
            **meta,
        }
        if self.eager:
            obs.emit_event(ev)
            return
        with self._lock:
            buf = self._open.get(trace_id)
            if buf is None:
                if len(self._open) >= MAX_OPEN_TRACES:
                    self._open.pop(next(iter(self._open)))
                    self.evictions += 1
                    obs.inc("reqtrace_buffer_evictions_total",
                            help="open request traces evicted at the "
                                 "buffer cap (never finished)")
                buf = self._open[trace_id] = []
            buf.append(ev)

    def finish(self, trace_id: Optional[str], outcome: str = "complete",
               **meta) -> None:
        """Terminal transition for a trace: emits the ``req_trace``
        summary event and applies the exemplar policy to the buffered
        stage events.  ``meta`` usually carries ``e2e_s`` (router side)
        or ``ttft_s`` (replica side)."""
        obs.inc("reqtrace_requests_total",
                help="requests reaching a traced terminal state")
        if outcome != "complete":
            obs.inc(f"reqtrace_{outcome}_total",
                    help=f"traced requests ending {outcome}")
        if not trace_id:
            return
        summary = {
            "event": "req_trace", "trace": trace_id, "outcome": outcome,
            "ts": time.time(), "pid": os.getpid(), **meta,
        }
        if self.eager:
            obs.emit_event(summary)
            obs.inc("reqtrace_exemplars_total",
                    help="requests whose full stage detail was flushed "
                         "to the event stream")
            return
        with self._lock:
            buf = self._open.pop(trace_id, [])
        if is_sampled(trace_id, self.sample_every):
            self._flush_one(buf, summary, kind="sample")
            return
        rank = meta.get("e2e_s")
        if rank is None:
            rank = meta.get("ttft_s")  # slowest-K still ranks somehow
        if outcome == "complete" and rank is not None:
            with self._lock:
                self._window.append((float(rank), trace_id, buf,
                                     summary))
                full = len(self._window) >= self.window
            if full:
                self.flush_window()
            return
        # non-complete, unsampled: aggregates only
        obs.inc("reqtrace_agg_only_total",
                help="requests kept as aggregate histograms only "
                     "(not exemplars)")

    def _flush_one(self, buf: List[dict], summary: dict,
                   kind: str) -> None:
        for ev in buf:
            obs.emit_event(ev)
        obs.emit_event({**summary, "exemplar": kind})
        obs.inc("reqtrace_exemplars_total",
                help="requests whose full stage detail was flushed "
                     "to the event stream")

    def flush_window(self) -> int:
        """Close the current slowest-K window: flush the K slowest
        completions' full detail, drop the rest to aggregates-only.
        Returns how many exemplars were flushed."""
        with self._lock:
            window, self._window = self._window, []
        if not window:
            return 0
        window.sort(key=lambda t: -t[0])
        slow, rest = window[:self.slowest_k], window[self.slowest_k:]
        for e2e, _tid, buf, summary in slow:
            self._flush_one(buf, summary, kind="slow")
        if rest:
            obs.inc("reqtrace_agg_only_total", n=len(rest),
                    help="requests kept as aggregate histograms only "
                         "(not exemplars)")
        return len(slow)

    def close(self) -> None:
        """End-of-session flush: the partial window's slowest-K still
        become exemplars (a short run must not report zero)."""
        self.flush_window()
        with self._lock:
            self._open.clear()


_REC = ReqTraceRecorder()


def recorder() -> ReqTraceRecorder:
    return _REC


def configure(**kw) -> None:
    _REC.configure(**kw)


def stage(trace_id: Optional[str], name: str, dur_s: float = 0.0,
          t_start: Optional[float] = None, **meta) -> None:
    _REC.stage(trace_id, name, dur_s=dur_s, t_start=t_start, **meta)


def finish(trace_id: Optional[str], outcome: str = "complete",
           **meta) -> None:
    _REC.finish(trace_id, outcome=outcome, **meta)


def session_flush() -> None:
    """Flush pending exemplars (called by ``ObsSession.close`` before
    the event stream closes, and by drivers before trace assembly)."""
    _REC.close()


def reset(**kw) -> None:
    """Fresh recorder (tests)."""
    global _REC
    _REC = ReqTraceRecorder(**kw)


# -- the latency budget ------------------------------------------------------


def _hist_row(metrics: Dict[str, Any], stage: str) -> Optional[dict]:
    base = f"reqtrace_stage_{stage}_seconds"
    count = metrics.get(base + "_count")
    if not count:
        return None
    s = float(metrics.get(base + "_sum") or 0.0)
    row = {
        "stage": stage,
        "count": int(count),
        "sum_s": s,
        "mean_ms": 1e3 * s / count,
    }
    for q in ("p50", "p99"):
        v = metrics.get(f"{base}_{q}")
        if v is not None:
            row[f"{q}_ms"] = 1e3 * float(v)
    return row


def latency_budget(metrics: Dict[str, Any]) -> Optional[dict]:
    """Per-stage TTFT and E2E attribution from the (merged) metric
    snapshot — pure aggregate math, so it covers EVERY request, not
    just the flushed exemplars.

    - **TTFT budget**: ``replica_queue + admission + prefill`` stage
      sums against the measured ``serve_ttft_seconds`` histogram;
      ``recon_pct`` is the signed % gap between the budget sum and the
      measurement (the ≤10% reconciliation contract).
    - **E2E budget**: router + replica stage sums against the
      router-observed ``reqtrace_e2e_seconds``; the remainder
      (transport, attempts on a replica whose shard died with it) is
      the ``unattributed_pct`` row.

    ``None`` when the snapshot holds no stage histograms (an untraced
    run)."""
    ttft_rows = [r for r in (_hist_row(metrics, s) for s in TTFT_STAGES)
                 if r is not None]
    e2e_rows = [r for r in (_hist_row(metrics, s) for s in E2E_STAGES)
                if r is not None]
    if not ttft_rows and not e2e_rows:
        return None

    def block(rows, measured_sum, measured_count):
        out: Dict[str, Any] = {"stages": rows}
        budget_sum = sum(r["sum_s"] for r in rows)
        measured_mean = (measured_sum / measured_count
                         if measured_count else None)
        out["budget_mean_ms"] = (
            1e3 * budget_sum / max(r["count"] for r in rows)
            if rows else None)
        out["measured_mean_ms"] = (1e3 * measured_mean
                                   if measured_mean is not None else None)
        if measured_sum:
            for r in rows:
                r["pct"] = 100.0 * r["sum_s"] / measured_sum
            out["recon_pct"] = 100.0 * (budget_sum - measured_sum) \
                / measured_sum
        return out

    ttft = block(ttft_rows,
                 float(metrics.get("serve_ttft_seconds_sum") or 0.0),
                 int(metrics.get("serve_ttft_seconds_count") or 0))
    e2e = block(e2e_rows,
                float(metrics.get("reqtrace_e2e_seconds_sum") or 0.0),
                int(metrics.get("reqtrace_e2e_seconds_count") or 0))
    if e2e.get("recon_pct") is not None:
        # stage sums can only undershoot an E2E that includes transport:
        # report the gap as the unattributed share of the budget
        e2e["unattributed_pct"] = max(0.0, -e2e["recon_pct"])
    return {"ttft": ttft, "e2e": e2e}


def install_budget_gauges(budget: Optional[dict]) -> None:
    """Land the budget as gauges on the active session so ``obs diff``
    gates them (``ttft_stage_<stage>_pct`` / ``reqtrace_*``)."""
    if not budget:
        return
    ttft = budget.get("ttft") or {}
    for row in ttft.get("stages") or []:
        if row.get("pct") is not None:
            obs.gauge_set(f"ttft_stage_{row['stage']}_pct", row["pct"],
                          help=f"{row['stage']} share of measured TTFT "
                               "(reqtrace latency budget)")
    if ttft.get("recon_pct") is not None:
        obs.gauge_set("reqtrace_ttft_recon_pct", ttft["recon_pct"],
                      help="signed % gap between the TTFT stage-budget "
                           "sum and the measured TTFT histogram")
    e2e = budget.get("e2e") or {}
    if e2e.get("unattributed_pct") is not None:
        obs.gauge_set("reqtrace_e2e_unattributed_pct",
                      e2e["unattributed_pct"],
                      help="share of router-observed E2E not claimed by "
                           "any recorded stage")

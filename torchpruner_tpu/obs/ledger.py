"""Pruning provenance: the per-round run ledger.

The telemetry PR (obs/) answers *how fast* a run went; this module
answers *what the run decided and what it cost*: which rows each round
pruned, by what score margin, and how accuracy/params/FLOPs moved —
the evidence artifact the attribution→prune→retrain loop needs
(JaxPruner's per-layer sparsity reporting, arXiv:2304.14082; the TPU
structured-pruning study's per-round FLOPs provenance, arXiv:2107.04191).

Two files under the session's ``obs_dir``:

- ``ledger.jsonl`` — one JSON record per line, appended as the run
  progresses (a killed run keeps every committed round).  Record kinds:
  ``round`` (the headline prune-round record), ``scores`` (per-site
  attribution score distributions), ``prune`` (the concrete decision:
  site + dropped rows), ``epoch`` (training trajectory), ``sweep_layer``
  (robustness-sweep panel summaries).
- ``report.json`` — the end-of-run bundle (``ObsSession.close``): all
  ledger records plus derived step metrics, phase summary, compile
  totals, and the (cross-host merged) metric snapshot.  ``obs report`` /
  ``obs diff`` consume this file.

Resume contract: the recorder's CURRENT-RUN view (``records()``, what
``report.json`` bundles) starts empty each session — a fresh run that
happens to reuse an ``--obs-dir`` reports its OWN rounds, never a
predecessor's (the same contract as ``events.jsonl``'s ``obs_init``
markers).  Continuation is explicit: a resumed driver calls
:meth:`backfill_rounds` / :meth:`backfill_epochs` with the PR 4
``RunManifest``'s committed history, and the recorder then ADOPTS the
matching prior-session records from disk (keeping their full payload —
e.g. a staged score distribution — without rewriting them) and writes
plain backfill records only for rounds the obs dir never saw.  Either
way a kill-9 → resume yields one continuous ledger: round records
neither duplicated nor lost (CI-asserted next to the chaos smoke).
"""

from __future__ import annotations

import json
import math
import os
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

LEDGER_FILENAME = "ledger.jsonl"
REPORT_FILENAME = "report.json"
REPORT_VERSION = 1

#: cap on stored dropped-row indices per prune record — a full LLM FFN
#: round can drop tens of thousands of rows; the ledger keeps the first
#: ROWS_CAP plus the true count (``n_rows``) and a truncation flag
ROWS_CAP = 4096


def score_distribution(scores, drop: Optional[Sequence[int]] = None,
                       tie_frac: float = 0.05) -> Dict[str, Any]:
    """Compact distribution of one round's attribution scores.

    Always: ``n``, ``p1``/``p50``/``p99``, ``mean``/``std``/``min``/``max``.
    With ``drop`` (the pruned indices): ``kept_min`` (lowest surviving
    score), ``pruned_max`` (highest removed score), ``margin`` (their
    gap — negative when the policy removed a unit scoring above a kept
    one, e.g. the all-negative policy with bucketing), and ``near_ties``
    — units within ``tie_frac`` of the score span of the decision
    boundary, the count of rows whose fate a small score perturbation
    would flip (high near-tie counts mean the round's decision is noise-
    sensitive and two runs may legitimately diverge there).
    """
    s = np.asarray(scores, dtype=np.float64).reshape(-1)
    if s.size == 0:
        return {"n": 0}
    out: Dict[str, Any] = {
        "n": int(s.size),
        "p1": float(np.percentile(s, 1)),
        "p50": float(np.percentile(s, 50)),
        "p99": float(np.percentile(s, 99)),
        "mean": float(np.mean(s)),
        "std": float(np.std(s)),
        "min": float(np.min(s)),
        "max": float(np.max(s)),
    }
    if drop is None:
        return out
    drop = np.unique(np.asarray(drop, dtype=np.int64).reshape(-1))
    drop = drop[(drop >= 0) & (drop < s.size)]
    keep_mask = np.ones(s.size, dtype=bool)
    keep_mask[drop] = False
    out["n_pruned"] = int(drop.size)
    out["n_kept"] = int(s.size - drop.size)
    if drop.size == 0 or drop.size == s.size:
        return out
    kept_min = float(np.min(s[keep_mask]))
    pruned_max = float(np.max(s[drop]))
    boundary = 0.5 * (kept_min + pruned_max)
    span = out["p99"] - out["p1"]
    eps = tie_frac * span if span > 0 else tie_frac * (abs(boundary) + 1e-12)
    out["kept_min"] = kept_min
    out["pruned_max"] = pruned_max
    out["margin"] = kept_min - pruned_max
    out["near_ties"] = int(np.sum(np.abs(s - boundary) <= eps))
    return out


def _dedup_key(rec: Dict[str, Any]) -> Optional[Tuple]:
    """The identity under which a record is written at most once.
    ``None`` = always write (informational events may legitimately
    repeat, e.g. a re-scored target after a kill before its prune
    anchor).  Records stamped with a ``trial_id`` (campaign trials
    sharing an obs dir) key per trial — concurrent runs' same-named
    rounds must coexist, not dedup each other; un-stamped records keep
    their pre-campaign identity (``trial_id`` is just ``None``)."""
    ev = rec.get("event")
    tid = rec.get("trial_id")
    if ev == "round":
        # round index in the key: iterative schedules prune the SAME
        # layer in several rounds, and each must ledger separately
        return ("round", tid, rec.get("target"), rec.get("round"))
    if ev == "sweep_layer":
        return ("sweep_layer", tid, rec.get("layer"))
    if ev == "epoch":
        return ("epoch", tid, rec.get("epoch"))
    if ev == "trial":
        # one status transition per trial per run view (a resumed driver
        # may re-announce) — keyed on the transition, not the payload
        return ("trial", tid, rec.get("status"))
    return None


def load_ledger(path: str) -> List[Dict[str, Any]]:
    """Parse a ``ledger.jsonl`` (torn/malformed lines skipped — the tail
    of a SIGKILLed run)."""
    records: List[Dict[str, Any]] = []
    if not os.path.exists(path):
        return records
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except ValueError:
                continue
            if isinstance(rec, dict):
                records.append(rec)
    return records


class ProvenanceRecorder:
    """Appends provenance records to ``obs_dir/ledger.jsonl`` with
    resume-safe dedup (see module docstring).  All ``record_*`` methods
    are crash-tolerant by construction: each record is one flushed line,
    so the ledger always holds every round committed before a kill."""

    def __init__(self, obs_dir: str):
        self.obs_dir = obs_dir
        self.path = os.path.join(obs_dir, LEDGER_FILENAME)
        os.makedirs(obs_dir, exist_ok=True)
        #: stamped onto every subsequent record (``set_context``): the
        #: campaign driver sets ``trial_id``/``campaign_id`` here so a
        #: shared obs dir's records stay groupable per trial
        self.context: Dict[str, Any] = {}
        #: dedup keys of records in THIS run's view
        self._seen: set = set()
        #: this run's records (report.json's source) — starts empty
        self._records: List[Dict[str, Any]] = []
        #: prior sessions' keyed records (last occurrence wins),
        #: available for explicit adoption on resume
        self._prior: Dict[Tuple, Dict[str, Any]] = {}
        for rec in load_ledger(self.path):
            key = _dedup_key(rec)
            if key is not None:
                self._prior[key] = rec
        self._f = open(self.path, "a")

    # -- core --------------------------------------------------------------

    def set_context(self, **fields) -> None:
        """Install fields stamped onto every later record (``None``
        values clear).  The campaign driver's satellite: with
        ``trial_id``/``campaign_id`` stamped, ``obs report`` on a
        shared obs dir groups rounds per trial instead of dedup-mixing
        concurrent runs."""
        for k, v in fields.items():
            if v is None:
                self.context.pop(k, None)
            else:
                self.context[k] = v

    def record(self, rec: Dict[str, Any]) -> bool:
        """Write one record (dedup-checked against THIS run's view).
        Returns False when this run already holds a record of the same
        identity."""
        rec = dict(rec)
        for k, v in self.context.items():
            rec.setdefault(k, v)
        key = _dedup_key(rec)
        if key is not None and key in self._seen:
            return False
        rec.setdefault("ts", time.time())
        try:
            self._f.write(json.dumps(sanitize(rec), default=_jsonable)
                          + "\n")
            self._f.flush()
        except Exception:  # the ledger must never kill the run
            return False
        if key is not None:
            self._seen.add(key)
        self._records.append(rec)
        return True

    def adopt(self, key: Tuple) -> bool:
        """Pull a PRIOR session's record (by dedup key) into this run's
        view — the resume bridge: the record keeps its full payload and
        is NOT rewritten to disk (it is already there)."""
        rec = self._prior.get(key)
        if rec is None or (key in self._seen):
            return False
        self._seen.add(key)
        self._records.append(rec)
        return True

    def close(self) -> None:
        try:
            self._f.close()
        except Exception:
            pass

    # -- typed records -----------------------------------------------------

    def record_scores(self, site: str, scores, *, method: str = "",
                      run: int = 0, layer: str = "") -> bool:
        """Per-site attribution score distribution (raw scores are NOT
        stored — only the compact distribution)."""
        return self.record({
            "event": "scores", "site": site, "layer": layer or site,
            "method": method, "run": int(run),
            "dist": score_distribution(scores),
        })

    def record_prune(self, target: str, drop, n_units: int, *,
                     simulate: bool = False) -> bool:
        """The concrete prune decision: site + the dropped row indices."""
        rows = [int(d) for d in np.asarray(drop).reshape(-1)[:ROWS_CAP]]
        n_rows = int(np.asarray(drop).reshape(-1).size)
        return self.record({
            "event": "prune", "target": target, "rows": rows,
            "n_rows": n_rows, "rows_truncated": n_rows > ROWS_CAP,
            "n_units_before": int(n_units),
            "fraction": (n_rows / n_units if n_units else 0.0),
            "simulate": bool(simulate),
        })

    def record_round(self, *, target: str, **fields) -> bool:
        """The headline per-round record (prune_retrain round): decision
        + score distribution + pre/post eval + cost snapshot.  Deduped on
        ``target`` — a resumed run re-reporting a committed round is a
        no-op."""
        return self.record({"event": "round", "target": target, **fields})

    def record_epoch(self, *, epoch: int, **fields) -> bool:
        return self.record({"event": "epoch", "epoch": int(epoch), **fields})

    def record_sweep_layer(self, *, layer: str, **fields) -> bool:
        return self.record({"event": "sweep_layer", "layer": layer,
                            **fields})

    def backfill_rounds(self, records: Sequence[Dict[str, Any]]) -> int:
        """Rehydrate round records from a RunManifest's ``records`` list
        (PruneStepRecord dicts) on resume.  A round the obs dir already
        holds is ADOPTED with its original payload (score distribution
        intact); one committed before the manifest but unseen by this
        obs dir (fresh ``--obs-dir``) is written as a ``backfilled``
        record.  Returns how many landed in this run's view."""
        n = 0
        for i, r in enumerate(records):
            target = r.get("layer") or r.get("target")
            if target is None:
                continue
            if self.adopt(("round", self.context.get("trial_id"),
                           target, i)):
                n += 1
                continue
            wrote = self.record_round(
                target=target, round=i, backfilled=True,
                n_dropped=r.get("n_dropped"),
                pre={"loss": r.get("pre_loss"), "acc": r.get("pre_acc")},
                post={"loss": r.get("post_loss"), "acc": r.get("post_acc")},
                params=r.get("n_params"), widths=r.get("widths"),
                prune_time=r.get("prune_time"),
            )
            n += int(wrote)
        return n

    def backfill_epochs(self, records: Sequence[Dict[str, Any]]) -> int:
        """Same as :meth:`backfill_rounds` for training-epoch history."""
        n = 0
        for r in records:
            if "epoch" not in r:
                continue
            if self.adopt(("epoch", self.context.get("trial_id"),
                           int(r["epoch"]))):
                n += 1
                continue
            n += int(self.record_epoch(backfilled=True, **r))
        return n

    # -- views -------------------------------------------------------------

    def records(self, event: Optional[str] = None) -> List[Dict[str, Any]]:
        if event is None:
            return list(self._records)
        return [r for r in self._records if r.get("event") == event]

    def rounds(self) -> List[Dict[str, Any]]:
        return self.records("round")


def build_report(*, run_meta: Optional[Dict[str, Any]] = None,
                 records: Optional[List[Dict[str, Any]]] = None,
                 derived: Optional[Dict[str, Any]] = None,
                 phases: Optional[Dict[str, Any]] = None,
                 compiles: Optional[Dict[str, Any]] = None,
                 metrics: Optional[Dict[str, float]] = None,
                 wall_s: Optional[float] = None,
                 profile: Optional[Dict[str, Any]] = None
                 ) -> Dict[str, Any]:
    """Assemble the ``report.json`` payload — ONE schema whether built
    live at session close or reconstructed offline by ``obs report``
    from ``ledger.jsonl`` + ``events.jsonl``.  ``profile`` is the
    kernel-profiling payload (obs.profile) minus its bulky raw timeline
    — profile.json keeps the full record."""
    records = records or []

    def picked(ev):
        return [r for r in records if r.get("event") == ev]

    prof = None
    if profile:
        prof = {k: v for k, v in profile.items() if k != "hbm"}
        hbm = profile.get("hbm") or {}
        prof["hbm"] = {k: v for k, v in hbm.items() if k != "timeline"}
    return {
        "version": REPORT_VERSION,
        "written_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        "run": dict(run_meta or {}),
        "rounds": picked("round"),
        "epochs": picked("epoch"),
        "sweep_layers": picked("sweep_layer"),
        "scores": picked("scores"),
        "prunes": picked("prune"),
        "serve": picked("serve"),
        "plan": picked("plan"),
        "trials": picked("trial"),
        "frontier": picked("frontier"),
        "reqtrace": picked("reqtrace"),
        "incidents": picked("incident"),
        "anomalies": picked("anomaly"),
        "derived": dict(derived or {}),
        "phases": dict(phases or {}),
        "compiles": dict(compiles or {}),
        "metrics": dict(metrics or {}),
        "wall_s": wall_s,
        **({"profile": prof} if prof else {}),
    }


def write_report(report: Dict[str, Any], path: str) -> None:
    """Atomic durable write (the shared tmp + fsync + replace dance):
    ``obs diff`` against a run killed mid-close must see the previous
    complete report or none.  Non-finite floats become ``null`` — the
    file must parse under STRICT JSON (jq, JavaScript), not just
    Python's ``NaN`` extension."""
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    atomic_write_json(path, sanitize(report), indent=1, default=_jsonable)


def sanitize(v):
    """Recursively coerce a record to strict-JSON-safe values: numpy
    scalars/arrays to Python, non-finite floats to ``None``."""
    if isinstance(v, dict):
        return {k: sanitize(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [sanitize(x) for x in v]
    if isinstance(v, np.ndarray):
        return sanitize(v.tolist())
    if isinstance(v, (bool, np.bool_)):
        return bool(v)
    if isinstance(v, (int, np.integer)):
        return int(v)
    if isinstance(v, (float, np.floating)):
        f = float(v)
        return f if math.isfinite(f) else None
    return v


def _jsonable(v):
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    if isinstance(v, np.ndarray):
        return v.tolist()
    return str(v)

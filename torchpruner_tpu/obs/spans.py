"""Span tracer — nested, named phases with a JSONL event stream.

A :class:`Span` is one timed phase of a run (``attribution`` → ``plan`` →
``apply_plan`` → ``shard`` → ``retrain`` → ``eval``).  Spans nest: the
tracer keeps a per-thread stack, so a ``retrain`` span opened inside a
``prune_retrain`` span records its parent id, and the end-of-run summary
can attribute wall time to the innermost phase without double counting.

Each span also enters a ``jax.profiler.TraceAnnotation`` of the same
name, so the phases show up as named regions in XLA/XProf traces captured
with ``--profile`` — the runtime JSONL stream and the device trace share
one vocabulary and can be joined offline (``utils.trace_analysis``
``--spans``).

Event schema (one JSON object per line, ``event`` discriminates)::

    {"event": "span_begin", "span": "s000001", "name": "retrain",
     "parent": "s000000", "depth": 1, "ts": <unix seconds>, ...meta}
    {"event": "span_end", "span": "s000001", "name": "retrain",
     "parent": "s000000", "depth": 1, "ts": ..., "dur_s": 12.3,
     "compile_count": 2, "compile_s": 1.8, "trace_count": 3, ...meta}

Compile attribution (``compile_*`` fields) is filled in by
:class:`~torchpruner_tpu.obs.compile_watch.CompileWatcher` calling
:meth:`SpanTracer.attribute_compile` — each jit compilation charges the
innermost span active on the compiling thread, surfacing at runtime the
"silent retrace" hazards tpu-lint can only predict statically.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

#: keep at most this many finished SpanRecords in memory (aggregates in
#: ``SpanTracer.totals`` are exact regardless — the cap only bounds the
#: per-span detail kept for programmatic access, e.g. bench leg rows)
MAX_RECORDS = 4096


@dataclass
class SpanRecord:
    """One finished (or active) span."""

    id: str
    name: str
    parent: Optional[str]
    depth: int
    t_start: float          # time.time() (wall, for the event stream)
    meta: Dict[str, Any] = field(default_factory=dict)
    t_mono: float = 0.0     # perf_counter() at start (for durations)
    tid: int = 0            # OS thread id (Perfetto track)
    dur_s: float = 0.0
    compile_count: int = 0
    compile_s: float = 0.0
    trace_count: int = 0


class _Stack(threading.local):
    def __init__(self):
        self.spans: List[SpanRecord] = []


class SpanTracer:
    """Allocates span ids, keeps the per-thread span stack, aggregates
    per-name wall time, and emits begin/end events to ``sink``.

    ``sink`` is any ``callable(dict)`` (usually a
    :class:`~torchpruner_tpu.obs.exporters.JsonlWriter`); ``None`` keeps
    everything in memory only.  ``annotate=False`` skips the
    ``jax.profiler.TraceAnnotation`` (tests, non-JAX contexts).
    """

    def __init__(self, sink: Optional[Callable[[dict], None]] = None,
                 annotate: bool = True):
        self.sink = sink
        #: optional second consumer of the event stream (the HBM sampler
        #: hooks span edges here) — same never-raise contract as sink
        self.extra_sink: Optional[Callable[[dict], None]] = None
        self.annotate = annotate
        self._lock = threading.Lock()
        self._counter = 0
        self._stack = _Stack()
        #: finished spans, newest last (bounded by MAX_RECORDS)
        self.records: List[SpanRecord] = []
        #: exact per-name aggregates over ALL finished spans:
        #: name -> {"total_s", "calls", "compile_count", "compile_s",
        #:          "trace_count"}
        self.totals: Dict[str, Dict[str, float]] = {}

    # -- span lifecycle ----------------------------------------------------

    def _next_id(self) -> str:
        with self._lock:
            self._counter += 1
            return f"s{self._counter:06d}"

    def current(self) -> Optional[SpanRecord]:
        st = self._stack.spans
        return st[-1] if st else None

    def current_id(self) -> Optional[str]:
        rec = self.current()
        return rec.id if rec else None

    def span(self, name: str, **meta) -> "_SpanCtx":
        """``with tracer.span("retrain", target="fc1"): ...``"""
        return _SpanCtx(self, name, meta)

    def _begin(self, name: str, meta: dict) -> SpanRecord:
        parent = self.current()
        rec = SpanRecord(
            id=self._next_id(), name=name,
            parent=parent.id if parent else None,
            depth=len(self._stack.spans),
            t_start=time.time(), meta=dict(meta),
            t_mono=time.perf_counter(),
            tid=threading.get_native_id(),
        )
        self._stack.spans.append(rec)
        self._emit({
            "event": "span_begin", "span": rec.id, "name": rec.name,
            "parent": rec.parent, "depth": rec.depth, "ts": rec.t_start,
            "tid": rec.tid,
            **rec.meta,
        })
        return rec

    def _end(self, rec: SpanRecord):
        rec.dur_s = time.perf_counter() - rec.t_mono
        st = self._stack.spans
        if st and st[-1] is rec:
            st.pop()
        else:  # mis-nested exit (generator abandoned mid-span): best effort
            try:
                st.remove(rec)
            except ValueError:
                pass
        with self._lock:
            if len(self.records) < MAX_RECORDS:
                self.records.append(rec)
            agg = self.totals.setdefault(rec.name, {
                "total_s": 0.0, "calls": 0, "compile_count": 0,
                "compile_s": 0.0, "trace_count": 0,
            })
            agg["total_s"] += rec.dur_s
            agg["calls"] += 1
            agg["compile_count"] += rec.compile_count
            agg["compile_s"] += rec.compile_s
            agg["trace_count"] += rec.trace_count
        self._emit({
            "event": "span_end", "span": rec.id, "name": rec.name,
            "parent": rec.parent, "depth": rec.depth, "ts": time.time(),
            "tid": rec.tid,
            "dur_s": round(rec.dur_s, 6),
            "compile_count": rec.compile_count,
            "compile_s": round(rec.compile_s, 6),
            "trace_count": rec.trace_count,
            **rec.meta,
        })

    def _emit(self, event: dict):
        if self.sink is not None:
            try:
                self.sink(event)
            except Exception:  # an exporter failure must never kill the run
                pass
        if self.extra_sink is not None:
            try:
                self.extra_sink(event)
            except Exception:
                pass

    # -- compile attribution ----------------------------------------------

    def attribute_compile(self, kind: str, dur_s: float):
        """Charge one compile/trace event to the innermost active span on
        this thread (called by ``CompileWatcher``'s monitoring listener,
        which runs synchronously on the compiling thread)."""
        rec = self.current()
        if rec is None:
            return
        if kind == "compile":
            rec.compile_count += 1
            rec.compile_s += dur_s
        elif kind == "trace":
            rec.trace_count += 1

    # -- summaries ---------------------------------------------------------

    def phase_summary(self) -> Dict[str, Dict[str, float]]:
        """Per-name aggregates, ordered by total wall time descending."""
        with self._lock:
            items = sorted(self.totals.items(),
                           key=lambda kv: -kv[1]["total_s"])
            return {k: dict(v) for k, v in items}

    def find(self, span_id: str) -> Optional[SpanRecord]:
        with self._lock:
            for rec in self.records:
                if rec.id == span_id:
                    return rec
        return None


class _SpanCtx:
    """The context manager returned by :meth:`SpanTracer.span`."""

    __slots__ = ("tracer", "name", "meta", "rec", "_ann")

    def __init__(self, tracer: SpanTracer, name: str, meta: dict):
        self.tracer = tracer
        self.name = name
        self.meta = meta
        self.rec: Optional[SpanRecord] = None
        self._ann = None

    def __enter__(self) -> SpanRecord:
        self.rec = self.tracer._begin(self.name, self.meta)
        if self.tracer.annotate:
            try:
                import jax.profiler

                self._ann = jax.profiler.TraceAnnotation(self.name)
                self._ann.__enter__()
            except Exception:
                self._ann = None
        return self.rec

    def __exit__(self, exc_type, exc, tb):
        if self._ann is not None:
            try:
                self._ann.__exit__(exc_type, exc, tb)
            except Exception:
                pass
        if self.rec is not None:
            self.tracer._end(self.rec)
        return False

"""Changepoint detection over the windowed time-series.

The PR 17 delta windows (``obs.timeseries``) give every process a
per-interval history of its registry: counter deltas, gauge samples,
histogram bucket deltas.  This module turns that history into *openable
facts*: per-metric rolling **median/MAD z-scores** with hysteresis — K
consecutive deviant windows open an anomaly, K recovered windows close
it — so a single noisy window never pages anyone and a sustained shift
is one anomaly, not one per window.

Two entry points, same math:

- **Online**: :class:`AnomalyDetector` rides the recorder's window
  emission (``TimeseriesRecorder.on_window``).  The per-step hot path is
  untouched — detection runs only when a window is actually emitted
  (once per interval), walks the window's signals, and is bounded by
  registry size, inside the existing <10 ms tick budget.  Opened
  anomalies are ledgered (``event: "anomaly"``) and handed to the
  incident correlator (``obs.incident``) as triggers.
- **Offline**: :func:`detect_anomalies` replays ``metrics_ts.jsonl`` /
  ``metrics_ts_fleet.jsonl`` (per-process series separated before
  scoring, warmup excluded via ``split_warmup``) — the reconstruction
  path ``obs incident DIR`` uses on a kill -9'd run's artifacts.

Signals per window (:func:`window_signals`): every histogram's
per-window p99 (``<name>_p99``), and the per-second rate of counters on
the spike watchlist (``<name>_rate`` — deadline expiries, sheds, SLO
breach/burn counts: the "fleet deadline/shed spike" trigger class).
Gauges are deliberately *not* scored by default (scraped gauges are
evidence for the correlator, not alert inputs) — opt in per-run via
``TORCHPRUNER_ANOMALY_GAUGES`` (comma-separated prefixes).

Tuning knobs (all env-overridable): ``TORCHPRUNER_ANOMALY_Z`` (deviance
threshold, default 8 robust-z), ``TORCHPRUNER_ANOMALY_K`` (hysteresis,
default 3 windows), ``TORCHPRUNER_ANOMALY_MIN_HISTORY`` (windows before
a signal is scored, default 8 — the online warmup exclusion).
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from torchpruner_tpu.obs.timeseries import (
    TS_FLEET_FILENAME,
    WARMUP_FRAC,
    _quantile_from_buckets,
    load_series,
    split_warmup,
)

#: robust-z deviance threshold (MAD-scaled; 8 ≈ "way outside anything
#: the baseline produced", chosen so CPU-smoke jitter never trips it)
Z_THRESHOLD = 8.0
#: hysteresis: K consecutive deviant windows open, K recovered close
HYSTERESIS_K = 3
#: windows of history a signal needs before it is scored at all — the
#: online warmup exclusion (offline additionally drops split_warmup's
#: first quarter)
MIN_HISTORY = 8
#: rolling-baseline bound per signal
HISTORY = 64
#: recovered means back inside this fraction of the open threshold
#: (an anomaly must not flap shut on a value barely under the line)
RECOVER_FRAC = 0.5

Z_ENV = "TORCHPRUNER_ANOMALY_Z"
K_ENV = "TORCHPRUNER_ANOMALY_K"
MIN_HISTORY_ENV = "TORCHPRUNER_ANOMALY_MIN_HISTORY"
GAUGES_ENV = "TORCHPRUNER_ANOMALY_GAUGES"

#: counters whose per-window rate is a spike signal (prefix match) —
#: the "fleet deadline/shed spike" trigger class plus the serve-side
#: breach/burn counts
WATCH_COUNTER_PREFIXES = (
    "fleet_deadline_exceeded", "fleet_shed", "fleet_failover",
    "serve_slo_breach", "slo_burn_alerts",
)


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, "") or default)
    except ValueError:
        return default


def window_signals(window: Dict[str, Any],
                   gauge_prefixes: Tuple[str, ...] = ()
                   ) -> Dict[str, float]:
    """Flatten one ``ts_window`` into the scalar signals the detector
    scores: histogram per-window p99s, watchlist counter rates, and
    (opt-in) gauge samples."""
    out: Dict[str, float] = {}
    dur = window.get("dur_s") or 0.0
    for name, h in (window.get("hist") or {}).items():
        if "le" not in h:
            continue
        q = _quantile_from_buckets(h["le"], h.get("c") or [], 0.99)
        if q is not None:
            out[f"{name}_p99"] = q
    if dur > 0:
        for name, v in (window.get("counters") or {}).items():
            if name.startswith(WATCH_COUNTER_PREFIXES):
                out[f"{name}_rate"] = v / dur
    if gauge_prefixes:
        for name, v in (window.get("gauges") or {}).items():
            if name.startswith(gauge_prefixes):
                out[name] = float(v)
    return out


class RollingMAD:
    """Rolling median/MAD robust z-score for one signal."""

    __slots__ = ("values", "min_history", "median", "mad")

    def __init__(self, history: int = HISTORY,
                 min_history: int = MIN_HISTORY):
        self.values: deque = deque(maxlen=history)
        self.min_history = max(2, int(min_history))
        self.median: Optional[float] = None
        self.mad: Optional[float] = None

    def push(self, v: float) -> Optional[float]:
        """Score ``v`` against the history (``None`` while warming up),
        THEN admit it — a spike must not absorb itself into its own
        baseline.  The MAD is floored at 5% of |median| so a perfectly
        flat baseline doesn't turn every epsilon into infinity."""
        z = None
        if len(self.values) >= self.min_history:
            xs = sorted(self.values)
            n = len(xs)
            m = (xs[n // 2] if n % 2
                 else 0.5 * (xs[n // 2 - 1] + xs[n // 2]))
            devs = sorted(abs(x - m) for x in xs)
            mad = (devs[n // 2] if n % 2
                   else 0.5 * (devs[n // 2 - 1] + devs[n // 2]))
            scale = max(1.4826 * mad, 0.05 * abs(m), 1e-9)
            self.median, self.mad = m, mad
            z = (v - m) / scale
        self.values.append(float(v))
        return z


class AnomalyDetector:
    """Hysteresis changepoint detector over emitted windows (module
    docstring).  One per process, owned by ``ObsSession``; every mutable
    field is written under ``self._lock`` (``observe_window`` is called
    from the recorder's tick AND the offline replay).  ``on_open`` /
    ``on_close`` callbacks run OUTSIDE the lock."""

    def __init__(self, *, z_threshold: Optional[float] = None,
                 k: Optional[int] = None,
                 min_history: Optional[int] = None,
                 history: int = HISTORY,
                 gauge_prefixes: Optional[Tuple[str, ...]] = None,
                 proc: Optional[str] = None,
                 on_open: Optional[Callable[[dict], None]] = None,
                 on_close: Optional[Callable[[dict], None]] = None):
        if z_threshold is None:
            z_threshold = _env_float(Z_ENV, Z_THRESHOLD)
        if k is None:
            k = int(_env_float(K_ENV, HYSTERESIS_K))
        if min_history is None:
            min_history = int(_env_float(MIN_HISTORY_ENV, MIN_HISTORY))
        if gauge_prefixes is None:
            raw = os.environ.get(GAUGES_ENV, "")
            gauge_prefixes = tuple(
                p.strip() for p in raw.split(",") if p.strip())
        self.z_threshold = float(z_threshold)
        self.k = max(1, int(k))
        self.min_history = max(2, int(min_history))
        self.history = int(history)
        self.gauge_prefixes = gauge_prefixes
        self.proc = proc
        self.on_open = on_open
        self.on_close = on_close
        self._lock = threading.Lock()
        self._trackers: Dict[str, RollingMAD] = {}
        self._deviant: Dict[str, int] = {}
        self._recovered: Dict[str, int] = {}
        self._open: Dict[str, dict] = {}
        #: every anomaly ever opened (open ones mutate in place on close)
        self.anomalies: List[dict] = []
        self._seq = 0
        #: bounded (ts, gauges) history — the correlator's before/after
        #: gauge-delta evidence source (router scrape history rides the
        #: router process's windows)
        self.gauge_history: deque = deque(maxlen=256)

    # -- the per-window pass -------------------------------------------------

    def observe_window(self, window: Dict[str, Any]) -> List[dict]:
        """Score one emitted window; returns the anomalies it opened or
        closed (already applied to detector state)."""
        signals = window_signals(window, self.gauge_prefixes)
        ts = window.get("ts") or 0.0
        seq = window.get("seq")
        opened: List[dict] = []
        closed: List[dict] = []
        with self._lock:
            if window.get("gauges"):
                self.gauge_history.append((ts, dict(window["gauges"])))
            for name, v in signals.items():
                tr = self._trackers.get(name)
                if tr is None:
                    tr = self._trackers[name] = RollingMAD(
                        self.history, self.min_history)
                z = tr.push(v)
                if z is None:
                    continue
                if abs(z) >= self.z_threshold:
                    self._recovered[name] = 0
                    n = self._deviant.get(name, 0) + 1
                    self._deviant[name] = n
                    if name not in self._open and n >= self.k:
                        self._seq += 1
                        a = {
                            "event": "anomaly",
                            "anomaly_id": "anom-%s%d" % (
                                (self.proc + "-") if self.proc else "",
                                self._seq),
                            "metric": name,
                            "state": "open",
                            "opened_ts": round(ts, 6),
                            "opened_seq": seq,
                            "z": round(z, 3),
                            "value": round(v, 9),
                            "baseline_median": tr.median,
                            "baseline_mad": tr.mad,
                            "windows_deviant": n,
                        }
                        if self.proc:
                            a["proc"] = self.proc
                        self._open[name] = a
                        self.anomalies.append(a)
                        opened.append(a)
                elif abs(z) <= self.z_threshold * RECOVER_FRAC:
                    self._deviant[name] = 0
                    a = self._open.get(name)
                    if a is not None:
                        r = self._recovered.get(name, 0) + 1
                        self._recovered[name] = r
                        if r >= self.k:
                            a["state"] = "closed"
                            a["closed_ts"] = round(ts, 6)
                            a["closed_seq"] = seq
                            del self._open[name]
                            closed.append(a)
                else:
                    # the dead band between recover and open thresholds
                    # feeds neither streak — hysteresis must not flap
                    self._deviant[name] = 0
                    self._recovered[name] = 0
        for a in opened:
            if self.on_open is not None:
                try:
                    self.on_open(a)
                except Exception:
                    pass
        for a in closed:
            if self.on_close is not None:
                try:
                    self.on_close(a)
                except Exception:
                    pass
        return opened + closed

    # -- views ---------------------------------------------------------------

    def open_anomalies(self) -> List[dict]:
        with self._lock:
            return list(self._open.values())

    def counts(self) -> Dict[str, int]:
        with self._lock:
            return {"opened": len(self.anomalies),
                    "open": len(self._open)}

    def gauges_between(self, t0: float, t1: float
                       ) -> List[Tuple[float, Dict[str, float]]]:
        """Gauge snapshots with ``t0 <= ts <= t1`` (correlator input)."""
        with self._lock:
            return [(ts, g) for ts, g in self.gauge_history
                    if t0 <= ts <= t1]


# -- offline -----------------------------------------------------------------


def detect_series(windows: List[Dict[str, Any]], *,
                  proc: Optional[str] = None,
                  warmup_frac: float = WARMUP_FRAC,
                  **kw) -> List[dict]:
    """Replay one process's windows through a fresh detector, warmup
    excluded the same way ``series_summary`` splits it."""
    _, steady = split_warmup(windows, warmup_frac)
    det = AnomalyDetector(proc=proc, **kw)
    for w in steady:
        det.observe_window(w)
    return det.anomalies


def detect_anomalies(run_dir: str, *, warmup_frac: float = WARMUP_FRAC,
                     **kw) -> List[dict]:
    """Offline changepoint pass over a run dir: the fleet-merged stream
    when present (``metrics_ts_fleet.jsonl``, already on the router
    clock — per-process series are separated before scoring so one
    replica's shift never pollutes another's baseline), else the
    process-local ``metrics_ts.jsonl``."""
    out: List[dict] = []
    fleet = os.path.join(run_dir, TS_FLEET_FILENAME)
    if os.path.exists(fleet):
        _, windows = load_series(fleet)
        by_proc: Dict[str, List[dict]] = {}
        for w in windows:
            by_proc.setdefault(str(w.get("proc") or "proc0"),
                               []).append(w)
        for proc in sorted(by_proc):
            out.extend(detect_series(by_proc[proc], proc=proc,
                                     warmup_frac=warmup_frac, **kw))
    else:
        _, windows = load_series(run_dir)
        out.extend(detect_series(windows, warmup_frac=warmup_frac, **kw))
    out.sort(key=lambda a: (a.get("opened_ts") or 0.0,
                            a.get("anomaly_id") or ""))
    return out

"""``obs report`` / ``obs diff`` — render, compare, and gate run ledgers.

The CLI the ledger exists for::

    python -m torchpruner_tpu obs report logs/obs
    python -m torchpruner_tpu obs diff logs/obs_a logs/obs_b \
        --gate results/obs_gates_ci.json

``report`` renders one run's ledger (round decisions, score margins,
accuracy/params trajectory, step/MFU/compile summary) as a markdown
table.  ``diff`` compares two runs — runtime scalars (step time, MFU,
compile seconds, step count), per-round accuracy matched by target, and
score-distribution drift — and with ``--gate`` exits non-zero naming
every violated tolerance, which is what turns a bench/CI run into a
regression gate instead of a number someone has to eyeball.

Gate file format (JSON)::

    {
      "step_time_mean_s": {"max_increase_pct": 25},
      "mfu":              {"max_decrease_pct": 10},
      "compile_s":        {"max_increase": 30},
      "steps":            {"max_increase_pct": 50},
      "round_post_acc":   {"max_decrease": 0.05},
      "score_p50_drift":  {"max": 0.25},
      "missing_rounds":   {"max": 0}
    }

Scalar gates read the run-level diff; ``round_*`` and
``score_p50_drift`` apply per matched round (worst round reported);
``missing_rounds`` fires when run B lost rounds run A had.  Unknown
gate names are themselves violations — a typo must not silently
disable a gate.

Per-kernel gates (the profile subsystem, ``obs.profile``) are scalar
gates over dynamic names: ``kernel_<base>_ms`` (ms per step) and
``kernel_<base>_pct`` (share of attributed op time), e.g.
``"kernel_dot_ms": {"max_increase_pct": 60}`` — which fails a run whose
matmul kernel regressed even when the total-step gate stays green.  A
scalar present in only one run renders as an informational "not
comparable" row and is skipped by gates unless the spec sets
``"require": true``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import Any, Dict, List, Optional

from torchpruner_tpu.obs.ledger import (
    LEDGER_FILENAME,
    REPORT_FILENAME,
    build_report,
    load_ledger,
)

_EPS = 1e-12

#: run-level scalar metrics a diff compares; ``better`` orients the
#: pct sign convention in the rendered table ("higher"/"lower")
_SCALARS = {
    "step_time_mean_s": "lower",
    "step_time_p50_s": "lower",
    "mfu": "higher",
    "examples_per_s": "higher",
    "compile_s": "lower",
    "compile_count": "lower",
    "steps": "same",
    "wall_s": "lower",
    # serving-engine latency/throughput (serve/ runs; absent elsewhere,
    # and gates on absent metrics skip unless they set "require")
    "serve_ttft_p50_s": "lower",
    "serve_ttft_p99_s": "lower",
    "serve_token_p50_s": "lower",
    "serve_token_p99_s": "lower",
    "serve_tokens_per_s": "higher",
    "serve_completed": "same",
}

#: dynamic scalar families: any metric matching one of these prefixes
#: participates in diff/gating even though its exact name depends on
#: the run (per-kernel scalars are named after the compiled ops;
#: ``zero_*`` are the ZeRO weight-update-sharding A/B gauges from
#: experiments.zero_bench / the bench ``zero`` leg; ``predicted_*``
#: are the static cost model's step/comm predictions plus the
#: prediction-vs-measured drift rows computed in ``_scalars_of``)
#: ``plan_*`` are the auto-parallelism planner's candidate/winner
#: gauges (analysis/planner.py); ``frontier_*`` / ``search_*`` are the
#: sparsity-search campaign's frontier scalars (best accuracy at fixed
#: FLOPs buckets, point/early-stop counts — search/frontier.py), the
#: gates CI holds frontier regressions with; ``fleet_*`` are the
#: multi-replica serving plane's failover/redrive/shed counters and
#: replica gauges (fleet/router.py), gated by the CI failover drill
#: (``reqtrace_*`` / ``ttft_stage_*`` are the distributed request
#: tracer's latency-budget and assembly scalars — per-stage TTFT
#: share, budget-vs-measured reconciliation, cross-process waterfall
#: counts; obs/reqtrace.py + fleet/report.py, gated by the CI drill)
#: (``ts_*`` are the windowed time-series recorder's window-count /
#: cadence gauges and ``slo_burn_*`` the multi-window burn-rate
#: gauges — obs/timeseries.py + serve/slo.py, gated by the CI fleet
#: drill)
#: (``serve_prefix_*`` / ``serve_kv_pages_shared*`` are the Serve v2
#: prefix-sharing cache's hit/publish/evict counters and shared-page
#: gauges — serve/allocator.py + serve/engine.py, emitted only with
#: ``--prefix-pages`` on, gated by the CI prefix smoke; the fleet's
#: ``fleet_affinity_*`` ride the existing ``fleet_`` prefix)
#: (``workload_*`` are the scenario replayer's submitted/shed/retry/
#: hedge/abandoned counters — fleet/workload.py; ``tenant_*`` the
#: multi-tenant QoS plane's per-tenant completion/shed/preemption
#: counters and SLO-percentile gauges — serve/scheduler.py +
#: fleet/router.py; ``scale_*`` the autoscaling supervisor's decision
#: counters and replica/rung gauges — fleet/supervisor.py; all three
#: gated by the CI autoscale chaos drill)
#: (``anomaly_*`` / ``incident_*`` are the changepoint detector's and
#: incident correlator's close-time count/score gauges — obs/anomaly.py
#: + obs/incident.py; gated exact-zero on the clean fleet run and
#: exact-one on the planted-cause CI drill)
_DYNAMIC_SCALAR_PREFIXES = ("kernel_", "serve_slo_breach", "zero_",
                            "predicted_", "plan_", "frontier_",
                            "search_", "fleet_", "reqtrace_",
                            "ttft_stage_", "serve_queue_wait",
                            "host_lint_", "ts_", "slo_burn_",
                            "serve_prefix_", "serve_kv_pages_shared",
                            "workload_", "tenant_", "scale_",
                            "anomaly_", "incident_")
_DYNAMIC_EXTRA = ("profile_coverage", "profile_windows_total",
                  "profile_steps_total")


def _dynamic_scalars(metrics: Dict[str, Any]) -> Dict[str, Optional[float]]:
    out: Dict[str, Optional[float]] = {}
    for k, v in (metrics or {}).items():
        if k.startswith(_DYNAMIC_SCALAR_PREFIXES) or k in _DYNAMIC_EXTRA:
            out[k] = _finite(v)
    return out


#: ``tenant_<name>_<field>`` scalar suffixes the per-tenant QoS table
#: regroups (tenant names may themselves contain underscores, so the
#: parse is suffix-anchored, never split-on-underscore)
_TENANT_FIELDS = ("accepted_fleet", "completed_fleet", "shed_fleet",
                  "deadline_exceeded_fleet", "ttft_p50_s", "ttft_p99_s",
                  "e2e_p50_s", "e2e_p99_s", "preempted_total",
                  "completed_total", "shed_total")


def _tenant_table(metrics: Dict[str, Any]) -> List[tuple]:
    """``[(tenant, {field: value})]`` rebuilt from the ``tenant_*``
    scalars — the report's per-tenant SLO breakdown source."""
    rows: Dict[str, Dict[str, float]] = {}
    for k, v in (metrics or {}).items():
        if not k.startswith("tenant_"):
            continue
        for f in _TENANT_FIELDS:
            if k.endswith("_" + f):
                name = k[len("tenant_"):-(len(f) + 1)]
                if name:
                    val = _finite(v)
                    if val is not None:
                        rows.setdefault(name, {})[f] = val
                break
    return sorted(rows.items())


def load_run(run_dir: str) -> Dict[str, Any]:
    """A run's report dict: ``report.json`` when the session closed
    cleanly, otherwise reconstructed from whatever survived
    (``ledger.jsonl`` + ``events.jsonl`` + metric shards) — a SIGKILLed
    run must still be reportable/diffable.  Also accepts a report FILE
    directly (a committed golden ``results/obs_report_*.json``)."""
    if os.path.isfile(run_dir):
        with open(run_dir) as f:
            report = json.load(f)
        report["_dir"] = os.path.dirname(run_dir)
        return report
    path = os.path.join(run_dir, REPORT_FILENAME)
    if os.path.exists(path):
        with open(path) as f:
            report = json.load(f)
        report["_dir"] = run_dir
        return report

    records = _dedupe_last(
        load_ledger(os.path.join(run_dir, LEDGER_FILENAME)))
    phases: Dict[str, Any] = {}
    events_path = os.path.join(run_dir, "events.jsonl")
    if os.path.exists(events_path):
        from torchpruner_tpu.utils.profiling import span_phase_summary

        phases = span_phase_summary(events_path)
    metrics: Dict[str, float] = {}
    from torchpruner_tpu.obs.aggregate import load_shards, merge_shards

    shards = load_shards(run_dir)
    if shards:
        metrics = merge_shards(shards).snapshot()
    derived = {
        "steps": metrics.get("steps_total"),
        "step_time_mean_s": (
            metrics["step_time_seconds_sum"] / metrics["step_time_seconds_count"]
            if metrics.get("step_time_seconds_count") else None),
        "step_time_p50_s": metrics.get("step_time_seconds_p50"),
        "step_time_p95_s": metrics.get("step_time_seconds_p95"),
        "step_time_p99_s": metrics.get("step_time_seconds_p99"),
        "mfu": metrics.get("mfu"),
        "examples_per_s": metrics.get("examples_per_s"),
    }
    compiles = {
        "compile_count": metrics.get("compile_count_total"),
        "compile_s": metrics.get("compile_seconds_total"),
    }
    profile = None
    try:
        from torchpruner_tpu.obs.profile import load_profile

        profile = load_profile(run_dir)
    except Exception:
        profile = None
    report = build_report(records=records, derived=derived, phases=phases,
                          compiles=compiles, metrics=metrics,
                          profile=profile)
    report["run"]["reconstructed"] = True
    report["_dir"] = run_dir
    if not records and not phases and not metrics:
        raise FileNotFoundError(
            f"{run_dir!r} holds no report.json, ledger.jsonl, "
            "events.jsonl, or metric shards — not an obs run directory")
    return report


def _dedupe_last(records):
    """Keyed records (rounds/epochs/sweep layers) deduped keeping the
    LAST occurrence — a multi-session ledger (kill → resume) can hold a
    round twice; the reconstruction must count it once.  Un-keyed
    records pass through."""
    from torchpruner_tpu.obs.ledger import _dedup_key

    out, by_key = [], {}
    for rec in records:
        key = _dedup_key(rec)
        if key is None:
            out.append(rec)
        elif key in by_key:
            by_key[key].clear()
            by_key[key].update(rec)  # replace in place, keep position
        else:
            by_key[key] = dict(rec)
            out.append(by_key[key])
    return out


def _scalars_of(report: Dict[str, Any]) -> Dict[str, Optional[float]]:
    derived = report.get("derived") or {}
    compiles = report.get("compiles") or {}
    metrics = report.get("metrics") or {}
    out = {
        "step_time_mean_s": derived.get("step_time_mean_s"),
        "step_time_p50_s": derived.get("step_time_p50_s"),
        "mfu": _finite(derived.get("mfu")),
        "examples_per_s": derived.get("examples_per_s"),
        "compile_s": compiles.get("compile_s"),
        "compile_count": compiles.get("compile_count"),
        "steps": derived.get("steps"),
        "wall_s": report.get("wall_s"),
        # serving histograms land in the metric snapshot as bucket-
        # estimated percentiles (metrics.Histogram.percentiles)
        "serve_ttft_p50_s": metrics.get("serve_ttft_seconds_p50"),
        "serve_ttft_p99_s": metrics.get("serve_ttft_seconds_p99"),
        "serve_token_p50_s": metrics.get("serve_token_seconds_p50"),
        "serve_token_p99_s": metrics.get("serve_token_seconds_p99"),
        "serve_tokens_per_s": metrics.get("serve_gen_tokens_per_s"),
        "serve_completed": metrics.get("serve_completed_total"),
        # per-kernel profile scalars (kernel_<base>_ms / _pct) ride in
        # dynamically — their names depend on the compiled program
        **_dynamic_scalars(metrics),
    }
    # prediction-vs-measured drift: the static cost model's predicted
    # step/token time against what the run measured, as a signed % —
    # the row `obs diff` renders (and the capture script's staged lint
    # leg gates at <30% on-chip).  Computed here so SIGKILLed runs
    # reconstructed from shards get it too.
    pred = _finite(metrics.get("predicted_step_ms"))
    meas = out.get("step_time_p50_s")
    if pred is not None and meas:
        out["predicted_vs_measured_step_pct"] = (
            100.0 * (pred - 1e3 * meas) / (1e3 * meas))
    pred_d = _finite(metrics.get("predicted_step_ms_decode"))
    meas_d = out.get("serve_token_p50_s")
    if pred_d is not None and meas_d:
        out["predicted_vs_measured_decode_pct"] = (
            100.0 * (pred_d - 1e3 * meas_d) / (1e3 * meas_d))
    # HBM drift: the static watermark prediction against the device
    # HIGH-WATER gauge (peak_bytes_in_use — an instantaneous end-of-run
    # reading has already freed the activation peak and would show a
    # large spurious drift).  TPU/GPU only: memory_stats() is absent on
    # CPU, where the predicted gauge still rides the diff alone.
    pred_h = _finite(metrics.get("predicted_hbm_bytes_per_chip"))
    meas_h = max(
        (v for k, v in (metrics or {}).items()
         if k.startswith("hbm_bytes_peak_device")
         and _finite(v) is not None), default=None)
    if pred_h is not None and meas_h:
        out["predicted_vs_measured_hbm_pct"] = (
            100.0 * (pred_h - meas_h) / meas_h)
    return out


def _finite(v) -> Optional[float]:
    import math

    if v is None:
        return None
    v = float(v)
    return v if math.isfinite(v) else None


# -- report rendering --------------------------------------------------------


def format_report(report: Dict[str, Any]) -> str:
    """Markdown rendering of one run's ledger."""
    lines: List[str] = []
    run = report.get("run") or {}
    title = run.get("experiment") or run.get("name") or \
        report.get("_dir") or "run"
    lines.append(f"# obs report — {title}")
    lines.append("")
    sc = _scalars_of(report)
    bits = []
    if sc["steps"]:
        bits.append(f"steps {int(sc['steps'])}")
    if sc["step_time_mean_s"]:
        bits.append(f"step {1e3 * sc['step_time_mean_s']:.2f} ms mean")
    d = report.get("derived") or {}
    if d.get("step_time_p50_s") is not None:
        bits.append(
            f"p50/p95/p99 {1e3 * d['step_time_p50_s']:.2f}/"
            f"{1e3 * d['step_time_p95_s']:.2f}/"
            f"{1e3 * d['step_time_p99_s']:.2f} ms")
    if sc["mfu"] is not None:
        bits.append(f"MFU {100 * sc['mfu']:.1f}%")
    if sc["examples_per_s"]:
        bits.append(f"{sc['examples_per_s']:.1f} ex/s")
    if sc["compile_s"] is not None:
        bits.append(f"compile {sc['compile_s']:.2f}s"
                    f"/{int(sc['compile_count'] or 0)}")
    if sc["wall_s"]:
        bits.append(f"wall {sc['wall_s']:.1f}s")
    if bits:
        lines.append("run: " + ", ".join(bits))
        lines.append("")

    # static cost model: predicted vs measured, per program (the train
    # step compares against step-time p50, decode against per-token p50)
    metrics = report.get("metrics") or {}
    preds = []
    for key, label, meas_key, meas_scale in (
        ("predicted_step_ms", "step", "step_time_p50_s", 1e3),
        ("predicted_step_ms_decode", "decode", "serve_token_p50_s", 1e3),
        ("predicted_step_ms_capture", "capture", None, None),
        ("predicted_step_ms_prefill", "prefill", None, None),
    ):
        p = _finite(metrics.get(key))
        if p is None:
            continue
        comm = _finite(metrics.get(key.replace("predicted_step_ms",
                                               "predicted_comm_ms")))
        bit = f"{label} {p:.3f} ms predicted"
        if comm:
            bit += f" ({comm:.3f} ms comm)"
        # the drift itself comes from _scalars_of — ONE formula, shared
        # with the obs-diff scalar the capture script gates on
        m = sc.get(meas_key) if meas_key else None
        drift = sc.get({"step": "predicted_vs_measured_step_pct",
                        "decode": "predicted_vs_measured_decode_pct"}
                       .get(label))
        if m and drift is not None:
            bit += f" vs {meas_scale * m:.3f} ms measured ({drift:+.0f}%)"
        preds.append(bit)
    pred_hbm = _finite(metrics.get("predicted_hbm_bytes_per_chip"))
    if pred_hbm is not None:
        bit = f"hbm {pred_hbm / 2**30:.3f} GiB/chip predicted"
        if sc.get("predicted_vs_measured_hbm_pct") is not None:
            bit += f" ({sc['predicted_vs_measured_hbm_pct']:+.0f}% " \
                   f"vs peak watermark)"
        preds.append(bit)
    if preds:
        lines.append("cost model: " + ", ".join(preds))
        lines.append("")

    # auto-parallelism planner (analysis/planner.py): the chosen config,
    # its predicted margins, and the winner's probe drift
    plans = report.get("plan") or []
    if plans:
        p = plans[-1]
        bits = []
        if p.get("winner"):
            bits.append(f"winner `{p['winner']}`")
        if p.get("margin_over_runner_up_pct") is not None:
            bits.append(
                f"{p['margin_over_runner_up_pct']:+.1f}% over runner-up")
        if p.get("margin_over_baseline_pct") is not None:
            bits.append(f"{p['margin_over_baseline_pct']:+.1f}% over "
                        f"baseline `{p.get('baseline')}`")
        if p.get("feasible") is not None:
            bits.append(f"{p['feasible']}/{p.get('candidates')} "
                        f"candidates feasible")
        wp = p.get("winner_predicted") or {}
        if wp.get("step_ms") is not None:
            bits.append(f"predicted {wp['step_ms']:.3f} ms/step "
                        f"({wp.get('bound', '?')}-bound)")
        probe = p.get("winner_probe") or {}
        if probe.get("drift_pct") is not None:
            bits.append(f"probe drift {probe['drift_pct']:+.0f}%"
                        + (" GATED" if probe.get("gated") else ""))
        lines.append("plan: " + ", ".join(bits))
        lines.append("")

    # sparsity-search campaign frontier (search/frontier.py): the non-
    # dominated point table with dominated / early-stopped / excluded
    # counts — the section `obs report` renders for a campaign obs dir
    fronts = report.get("frontier") or []
    if fronts:
        fr = fronts[-1]
        c = fr.get("counts") or {}
        lines.append(
            f"frontier: {c.get('completed', 0)} point(s), "
            f"{c.get('non_dominated', 0)} non-dominated, "
            f"{c.get('dominated', 0)} dominated, "
            f"{c.get('early_stopped', 0)} early-stopped, "
            f"{c.get('excluded', 0)} excluded"
            + (f" (digest {str(fr.get('digest') or '')[:12]})"
               if fr.get("digest") else ""))
        nd = [p for p in (fr.get("points") or [])
              if p.get("non_dominated")]
        if nd:
            lines.append("")
            lines.append("| trial | acc | flops | params | ckpt digest "
                         "| ledger run |")
            lines.append("|---|---|---|---|---|---|")
            for p in sorted(nd, key=lambda p: p.get("flops") or 0):
                lines.append(
                    f"| `{p.get('trial_id')}` | {_f(p.get('accuracy'))} "
                    f"| {_f(p.get('flops'), '.3g')} "
                    f"| {_i(p.get('params'))} "
                    f"| {str(p.get('checkpoint_digest') or '')[:12]} "
                    f"| {p.get('ledger_run_id') or ''} |")
        buckets = fr.get("buckets") or {}
        if buckets:
            lines.append("")
            lines.append("buckets: " + ", ".join(
                f"{k.replace('frontier_best_acc_flops_le_', '<=')}"
                f"={_f(v)}" for k, v in sorted(buckets.items())))
        lines.append("")

    rounds = report.get("rounds") or []
    if rounds:
        # rounds stamped with trial ids (a campaign's shared obs dir)
        # group per trial — the column only appears when it means
        # something
        trialed = any(r.get("trial_id") for r in rounds)
        trial_col = "| trial " if trialed else ""
        lines.append(f"{trial_col}| round | target | method | dropped "
                     "| pre acc | post acc | Δacc | params | margin "
                     "| near ties |")
        lines.append("|---" * (10 + int(trialed)) + "|")
        if trialed:
            rounds = sorted(
                rounds, key=lambda r: (str(r.get("trial_id") or ""),
                                       r.get("round") or 0))
        for i, r in enumerate(rounds):
            pre = (r.get("pre") or {})
            post = (r.get("post") or {})
            sd = r.get("score_dist") or {}
            dacc = (post.get("acc") - pre.get("acc")
                    if post.get("acc") is not None
                    and pre.get("acc") is not None else None)
            tcell = f"| `{r.get('trial_id') or ''}` " if trialed else ""
            lines.append(
                f"{tcell}| {r.get('round', i)} | {r.get('target')} "
                f"| {r.get('method', '')} | {_i(r.get('n_dropped'))} "
                f"| {_f(pre.get('acc'))} | {_f(post.get('acc'))} "
                f"| {_f(dacc, '+.4f')} | {_i(r.get('params'))} "
                f"| {_f(sd.get('margin'))} | {_i(sd.get('near_ties'))} |")
        lines.append("")

    epochs = report.get("epochs") or []
    if epochs:
        last = epochs[-1]
        lines.append(
            f"epochs: {len(epochs)} "
            f"(final test acc {_f(last.get('test_acc'))}, "
            f"loss {_f(last.get('test_loss'))})")
        lines.append("")

    serve = report.get("serve") or []
    sc_serve = {k: v for k, v in sc.items()
                if k.startswith("serve_") and v is not None}
    if serve or sc_serve:
        bits = []
        if sc.get("serve_completed") is not None:
            bits.append(f"requests {int(sc['serve_completed'])}")
        if sc.get("serve_tokens_per_s") is not None:
            bits.append(f"{sc['serve_tokens_per_s']:.1f} gen tok/s")
        if sc.get("serve_ttft_p50_s") is not None:
            bits.append(
                f"TTFT p50/p99 {1e3 * sc['serve_ttft_p50_s']:.2f}/"
                f"{1e3 * (sc.get('serve_ttft_p99_s') or 0):.2f} ms")
        if sc.get("serve_token_p50_s") is not None:
            bits.append(
                f"per-token p50/p99 {1e3 * sc['serve_token_p50_s']:.2f}/"
                f"{1e3 * (sc.get('serve_token_p99_s') or 0):.2f} ms")
        lines.append("serve: " + (", ".join(bits) if bits
                                  else "(no latency metrics)"))
        swaps = [r for r in serve if r.get("kind") == "hot_swap"]
        for r in swaps:
            lines.append(
                f"- hot-swap at step {_i(r.get('at_step'))}: "
                f"{r.get('checkpoint') or ''} "
                f"(digest {str(r.get('new_digest') or '')[:12]})")
        summaries = [r for r in serve if r.get("kind") == "summary"]
        if summaries:
            s = summaries[-1]
            lines.append(
                f"- admits {_i(s.get('admits'))}, evictions "
                f"{_i(s.get('evictions'))}, drained "
                f"{_i(s.get('requests_drained'))}, swaps "
                f"{_i(s.get('swaps'))}, checkpoint digest "
                f"{str(s.get('checkpoint_digest') or '')[:12]}")
        lines.append("")

    # per-tenant QoS / SLO breakdown (serve/scheduler.py +
    # fleet/router.py): the `tenant_<name>_*` scalars regrouped into
    # one table per tenant — completions, sheds (throttle / quota /
    # tier), preemptions, and the router-observed latency percentiles
    tenant_rows = _tenant_table(metrics)
    if tenant_rows:
        lines.append("tenants (QoS breakdown, fleet-observed):")
        lines.append("")
        lines.append("| tenant | accepted | completed | shed "
                     "| preempted | deadline | TTFT p50/p99 ms "
                     "| e2e p50/p99 ms |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for t, row in tenant_rows:
            def ms(key):
                v = row.get(key)
                return f"{1e3 * v:.1f}" if v is not None else ""
            done = row.get("completed_fleet", row.get("completed_total"))
            shed = row.get("shed_fleet", row.get("shed_total"))
            lines.append(
                f"| {t} | {_i(row.get('accepted_fleet'))} "
                f"| {_i(done)} | {_i(shed)} "
                f"| {_i(row.get('preempted_total'))} "
                f"| {_i(row.get('deadline_exceeded_fleet'))} "
                f"| {ms('ttft_p50_s')}/{ms('ttft_p99_s')} "
                f"| {ms('e2e_p50_s')}/{ms('e2e_p99_s')} |")
        lines.append("")

    # autoscaling supervisor (fleet/supervisor.py): every ledgered
    # scale decision with its triggering signal — decision BEFORE
    # effect, so this table exists even for a run that died mid-action
    decisions = [r for r in serve
                 if r.get("kind") == "scale_decision"]
    if decisions:
        ups = sum(r.get("action") == "scale_up" for r in decisions)
        downs = sum(r.get("action") == "scale_down" for r in decisions)
        degrades = sum(r.get("action") == "degrade" for r in decisions)
        recovers = sum(r.get("action") == "recover" for r in decisions)
        lines.append(f"autoscale: {len(decisions)} decision(s) — "
                     f"{ups} up, {downs} down, {degrades} degrade, "
                     f"{recovers} recover")
        for r in decisions[:12]:
            trig = r.get("trigger") or {}
            bit = (f"- t+{_f(r.get('t_s'), '.1f')}s "
                   f"**{r.get('action')}**")
            if r.get("rung"):
                bit += f" → rung `{r['rung']}`"
            if r.get("replica"):
                bit += f" ({r['replica']})"
            bit += (f": queue age {_f(trig.get('queue_age_s'), '.2f')}s,"
                    f" pending {_i(trig.get('pending'))}, "
                    f"{_i(trig.get('live'))}/{_i(trig.get('replicas'))}"
                    f" live, breach {_f(trig.get('breach_frac'), '.2f')}")
            cap = r.get("capacity") or {}
            if cap.get("predicted_tok_s") is not None:
                bit += (f" (predicted +{_f(cap['predicted_tok_s'], '.0f')}"
                        f" tok/s per replica)")
            if r.get("correlation_id"):
                bit += f" [corr {r['correlation_id']}]"
            lines.append(bit)
        lines.append("")

    # incidents (obs/incident.py): every ledgered incident with its
    # trigger and top-ranked suspect — the postmortem headline; the
    # full evidence table is `obs incident DIR`
    incidents = report.get("incidents") or []
    anomalies = report.get("anomalies") or []
    if incidents or anomalies:
        opened = [a for a in anomalies if a.get("state") == "open"]
        lines.append(f"incidents: {len(incidents)} — "
                     f"{len(anomalies)} anomaly record(s), "
                     f"{len(opened)} still open at close")
        for inc in incidents[:8]:
            trig = inc.get("trigger") or {}
            bit = (f"- **{inc.get('incident_id')}** {inc.get('kind')}"
                   + (f" ({trig.get('metric')})" if trig.get("metric")
                      else "")
                   + (f" on {trig.get('replica')}"
                      if trig.get("replica") else ""))
            top = inc.get("top_suspect") or {}
            if top:
                bit += (f" → top suspect `{top.get('class')}`"
                        + (f" on {top.get('replica')}"
                           if top.get("replica") else "")
                        + f" (score {_f(top.get('score'), '.3f')})")
            absorbed = inc.get("triggers_absorbed") or 0
            if absorbed:
                bit += f", {absorbed} trigger(s) absorbed"
            if inc.get("tenants"):
                bit += f", tenants: {', '.join(inc['tenants'])}"
            lines.append(bit)
        lines.append("")

    # request-trace latency budget (obs/reqtrace.py): per-stage TTFT /
    # E2E attribution that reconciles against the measured histograms.
    # Preferred source is the ledger `reqtrace` record (the fleet drill
    # writes budget + exemplars); without one the budget is recomputed
    # from the metric snapshot, so a plain serve run renders it too.
    rt_records = report.get("reqtrace") or []
    budget = (rt_records[-1].get("budget") if rt_records else None)
    if budget is None:
        from torchpruner_tpu.obs.reqtrace import latency_budget

        budget = latency_budget(metrics)
    if budget:
        ttft = budget.get("ttft") or {}
        e2e = budget.get("e2e") or {}
        bits = []
        if ttft.get("measured_mean_ms") is not None:
            bits.append(f"TTFT measured {ttft['measured_mean_ms']:.2f} "
                        f"ms mean")
        if ttft.get("recon_pct") is not None:
            bits.append(f"stage budget reconciles {ttft['recon_pct']:+.1f}%")
        if e2e.get("unattributed_pct") is not None:
            bits.append(f"E2E unattributed "
                        f"{e2e['unattributed_pct']:.1f}%")
        lines.append("latency budget: " + (", ".join(bits) or "(stages)"))
        lines.append("")
        lines.append("| stage | p50 ms | p99 ms | mean ms | % TTFT "
                     "| % E2E |")
        lines.append("|---|---|---|---|---|---|")
        e2e_pct = {r["stage"]: r.get("pct")
                   for r in e2e.get("stages") or []}
        ttft_pct = {r["stage"]: r.get("pct")
                    for r in ttft.get("stages") or []}
        seen = []
        for r in (ttft.get("stages") or []) + (e2e.get("stages") or []):
            if r["stage"] in seen:
                continue
            seen.append(r["stage"])
            lines.append(
                f"| {r['stage']} | {_f(r.get('p50_ms'), '.3f')} "
                f"| {_f(r.get('p99_ms'), '.3f')} "
                f"| {_f(r.get('mean_ms'), '.3f')} "
                f"| {_f(ttft_pct.get(r['stage']), '.1f')} "
                f"| {_f(e2e_pct.get(r['stage']), '.1f')} |")
        lines.append("")
    exemplars = (rt_records[-1].get("exemplars") if rt_records else None)
    if exemplars:
        lines.append(f"slowest-{len(exemplars)} exemplar waterfalls "
                     "(cross-process; pid 0 = router):")
        for ex in exemplars:
            flow = " → ".join(
                f"{s['stage']}"
                + (f" {s['dur_ms']:.1f}ms" if s.get("dur_ms") else "")
                + (f"@p{s['pid']}" if s.get("pid") is not None else "")
                for s in ex.get("stages") or [])
            lines.append(
                f"- `{ex.get('trace')}` e2e {_f(ex.get('e2e_ms'), '.1f')}"
                f" ms, ttft {_f(ex.get('ttft_ms'), '.1f')} ms, "
                f"{ex.get('attempts', 0)} attempt(s)"
                + (" [redriven]" if ex.get("redrive") else "")
                + f": {flow}")
        lines.append("")

    # timeline: the windowed time-series' warmup-vs-steady-state split
    # (obs/timeseries.py) — read from the run dir next to the ledger;
    # committed golden report FILES have no series and skip the section
    run_dir = report.get("_dir")
    if run_dir and os.path.isdir(run_dir):
        from torchpruner_tpu.obs import timeseries as ts_mod

        try:
            _, windows = ts_mod.load_series(run_dir)
        except Exception:
            windows = []
        if len(windows) >= 2:
            tsum = ts_mod.series_summary(windows)
            lines.append(
                f"timeline: {tsum['windows']} window(s) "
                f"({tsum['warmup_windows']} warmup / "
                f"{tsum['steady_windows']} steady-state; steady span "
                f"{tsum['steady_span_s']:.1f}s)")
            rows = [r for r in tsum["hist"]
                    if r.get("warmup") or r.get("steady")]
            if rows:
                lines.append("")
                lines.append("| histogram | warmup p50/p99 ms "
                             "| steady p50/p99 ms | steady mean ms "
                             "| steady n |")
                lines.append("|---|---|---|---|---|")

                def _pp(seg):
                    if not seg:
                        return ""
                    return (f"{_f(1e3 * seg['p50'], '.3f')}/"
                            f"{_f(1e3 * seg['p99'], '.3f')}"
                            if seg.get("p50") is not None else "")

                for r in rows:
                    st = r.get("steady") or {}
                    lines.append(
                        f"| {r['name']} | {_pp(r.get('warmup'))} "
                        f"| {_pp(st)} "
                        f"| {_f(1e3 * st['mean'], '.3f') if st.get('mean') is not None else ''} "
                        f"| {_i(st.get('n'))} |")
            rates = tsum.get("steady_rates_per_s") or {}
            if rates:
                top = sorted(rates.items(), key=lambda kv: -kv[1])[:6]
                lines.append("")
                lines.append("steady-state rates: " + ", ".join(
                    f"{k} {v:.2f}/s" for k, v in top))
            lines.append("")

    profile = report.get("profile") or {}
    kernels = profile.get("kernels") or []
    if kernels:
        lines.append(
            f"profile: {len(profile.get('windows') or [])} capture "
            f"window(s), {profile.get('steps_profiled') or 0} steps"
            + (f", coverage {100 * profile['coverage']:.0f}%"
               if profile.get("coverage") is not None else ""))
        lines.append("")
        lines.append("| kernel | category | ms/step | % step | bound |")
        lines.append("|---|---|---|---|---|")
        for k in kernels[:8]:
            rf = k.get("roofline") or {}
            lines.append(
                f"| `{k.get('kernel')}` | {k.get('category')} "
                f"| {_f(k.get('ms_per_step'))} "
                f"| {_f(k.get('pct_of_step'), '.1f')} "
                f"| {rf.get('bound', '')} |")
        lines.append("")

    top_compilers = (report.get("compiles") or {}).get("by_executable")
    if top_compilers:
        lines.append("| top compilers (executable) | compiles | s |")
        lines.append("|---|---|---|")
        for c in top_compilers:
            lines.append(f"| `{c.get('name')}` | {_i(c.get('count'))} "
                         f"| {_f(c.get('seconds'), '.3f')} |")
        lines.append("")

    sweeps = report.get("sweep_layers") or []
    if sweeps:
        lines.append("| sweep layer | methods | best method | best auc |")
        lines.append("|---|---|---|---|")
        for s in sweeps:
            methods = s.get("methods") or {}
            best = None
            if methods:
                best = min(methods.items(),
                           key=lambda kv: kv[1].get("auc_mean", float("inf")))
            lines.append(
                f"| {s.get('layer')} | {len(methods)} "
                f"| {best[0] if best else ''} "
                f"| {_f(best[1].get('auc_mean')) if best else ''} |")
        lines.append("")
    if not rounds and not epochs and not sweeps and not serve \
            and not sc_serve and not kernels and not fronts:
        lines.append("(no ledger records)")
    return "\n".join(lines)


def _f(v, fmt: str = ".4f") -> str:
    if v is None:
        return ""
    try:
        return format(float(v), fmt)
    except (TypeError, ValueError):
        return str(v)


def _i(v) -> str:
    return "" if v is None else str(int(v))


# -- diff --------------------------------------------------------------------


def _rounds_by_label(report: Dict[str, Any]) -> Dict[str, Dict[str, Any]]:
    """Rounds keyed by a stable label: the target name, with a ``#k``
    suffix from the second occurrence on (iterative schedules), and a
    ``<trial_id>/`` prefix when the record carries a campaign trial
    stamp — concurrent trials' same-named rounds in one shared obs dir
    must diff trial-for-trial, never cross-match."""
    out: Dict[str, Dict[str, Any]] = {}
    seen: Dict[str, int] = {}
    for r in (report.get("rounds") or []):
        target = str(r.get("target"))
        if r.get("trial_id"):
            target = f"{r['trial_id']}/{target}"
        k = seen.get(target, 0)
        seen[target] = k + 1
        out[target if k == 0 else f"{target}#{k}"] = r
    return out


def diff_runs(a: Dict[str, Any], b: Dict[str, Any]) -> Dict[str, Any]:
    """Structured diff of two run reports: run-level scalar deltas,
    per-round deltas matched by target, and round-set changes."""
    sa, sb = _scalars_of(a), _scalars_of(b)
    scalars: Dict[str, Any] = {}
    dynamic = [k for k in {**sa, **sb} if k not in _SCALARS]
    for name in list(_SCALARS) + sorted(dynamic):
        va, vb = sa.get(name), sb.get(name)
        if va is None and vb is None:
            continue
        entry: Dict[str, Any] = {"a": va, "b": vb}
        if va is not None and vb is not None:
            entry["delta"] = vb - va
            entry["pct"] = (100.0 * (vb - va) / abs(va)
                            if abs(va) > _EPS else None)
        else:
            # present in only one run (a pre-kernel-era baseline, a
            # train run diffed against a serve run): informational, not
            # an error — gates on it skip unless they set "require"
            entry["note"] = ("not comparable (only in "
                             + ("A" if vb is None else "B") + ")")
        scalars[name] = entry

    # rounds matched by target AND per-target occurrence order, so an
    # iterative schedule (same layer pruned in several rounds) pairs
    # round-for-round; labels stay the bare target for the common
    # one-round-per-layer case and gain a #k suffix on repeats
    ra = _rounds_by_label(a)
    rb = _rounds_by_label(b)
    rounds: Dict[str, Any] = {}
    for target in ra:
        if target not in rb:
            continue
        pa, pb = ra[target], rb[target]
        entry = {}
        for which in ("pre", "post"):
            aa = (pa.get(which) or {}).get("acc")
            bb = (pb.get(which) or {}).get("acc")
            if aa is not None and bb is not None:
                entry[f"{which}_acc_delta"] = bb - aa
        for key in ("n_dropped", "params"):
            if pa.get(key) is not None and pb.get(key) is not None:
                entry[f"{key}_delta"] = pb[key] - pa[key]
        da = pa.get("score_dist") or {}
        db = pb.get("score_dist") or {}
        if da.get("p50") is not None and db.get("p50") is not None:
            span = abs(da.get("p99", 0) - da.get("p1", 0))
            entry["score_p50_drift"] = (
                abs(db["p50"] - da["p50"]) / (span + _EPS))
        if da.get("margin") is not None and db.get("margin") is not None:
            entry["margin_delta"] = db["margin"] - da["margin"]
        rounds[target] = entry
    return {
        "scalars": scalars,
        "rounds": rounds,
        "missing_rounds": sorted(t for t in ra if t not in rb),
        "added_rounds": sorted(t for t in rb if t not in ra),
    }


def format_diff(d: Dict[str, Any]) -> str:
    lines = ["# obs diff (B vs A)", ""]
    if d["scalars"]:
        lines.append("| metric | A | B | Δ | Δ% |")
        lines.append("|---|---|---|---|---|")
        for name, e in d["scalars"].items():
            pct = e.get("pct")
            delta = _f(e.get("delta"), "+.6g") \
                if e.get("delta") is not None else (e.get("note") or "")
            lines.append(
                f"| {name} | {_f(e.get('a'), '.6g')} "
                f"| {_f(e.get('b'), '.6g')} "
                f"| {delta} "
                f"| {_f(pct, '+.1f') + '%' if pct is not None else ''} |")
        lines.append("")
    if d["rounds"]:
        lines.append("| round target | Δpre acc | Δpost acc "
                     "| Δdropped | p50 drift |")
        lines.append("|---|---|---|---|---|")
        for target, e in d["rounds"].items():
            lines.append(
                f"| {target} | {_f(e.get('pre_acc_delta'), '+.4f')} "
                f"| {_f(e.get('post_acc_delta'), '+.4f')} "
                f"| {_i(e.get('n_dropped_delta')) or '0'} "
                f"| {_f(e.get('score_p50_drift'), '.3f')} |")
        lines.append("")
    if d["missing_rounds"]:
        lines.append(f"rounds missing in B: {', '.join(d['missing_rounds'])}")
    if d["added_rounds"]:
        lines.append(f"rounds only in B: {', '.join(d['added_rounds'])}")
    return "\n".join(lines)


# -- gates -------------------------------------------------------------------


def check_gates(d: Dict[str, Any],
                gates: Dict[str, Dict[str, float]]) -> List[Dict[str, Any]]:
    """Evaluate a gate file against a diff; returns violation dicts
    (empty = pass).  See module docstring for the format."""
    violations: List[Dict[str, Any]] = []

    def fail(gate, detail, value=None, limit=None):
        violations.append({"gate": gate, "detail": detail,
                           "value": value, "limit": limit})

    for gate, spec in gates.items():
        if not isinstance(spec, dict):
            fail(gate, f"malformed gate spec {spec!r}")
            continue
        if gate in _SCALARS or gate.startswith(_DYNAMIC_SCALAR_PREFIXES) \
                or gate in _DYNAMIC_EXTRA:
            e = d["scalars"].get(gate)
            if e is None or e.get("delta") is None:
                # absent on one side: only fail when the gate demands
                # presence (a CPU run has no MFU; gating it would make
                # every CPU diff red)
                if spec.get("require", False):
                    fail(gate, "metric absent from one or both runs")
                elif e is None and gate not in _SCALARS \
                        and not spec.get("optional", False):
                    # a DYNAMIC gate naming a metric NEITHER run has is
                    # almost certainly a typo (kernel_dto_ms) — the
                    # unknown-gate invariant must hold for these too;
                    # "optional": true opts a speculative gate out
                    fail(gate, "names a metric absent from both runs "
                               "(typo? set \"optional\": true if this "
                               "kernel may legitimately be missing)")
                continue
            delta, pct = e["delta"], e.get("pct")
            if "max_increase" in spec and delta > spec["max_increase"]:
                fail(gate, f"increased by {delta:.6g} "
                           f"(limit {spec['max_increase']:.6g})",
                     delta, spec["max_increase"])
            if "max_decrease" in spec and -delta > spec["max_decrease"]:
                fail(gate, f"decreased by {-delta:.6g} "
                           f"(limit {spec['max_decrease']:.6g})",
                     -delta, spec["max_decrease"])
            if "max_increase_pct" in spec and pct is not None \
                    and pct > spec["max_increase_pct"]:
                fail(gate, f"increased {pct:.1f}% "
                           f"(limit {spec['max_increase_pct']:.1f}%)",
                     pct, spec["max_increase_pct"])
            if "max_decrease_pct" in spec and pct is not None \
                    and -pct > spec["max_decrease_pct"]:
                fail(gate, f"decreased {-pct:.1f}% "
                           f"(limit {spec['max_decrease_pct']:.1f}%)",
                     -pct, spec["max_decrease_pct"])
        elif gate in ("round_pre_acc", "round_post_acc"):
            key = gate.replace("round_", "") + "_delta"
            lim = spec.get("max_decrease")
            for target, e in d["rounds"].items():
                delta = e.get(key)
                if lim is not None and delta is not None and -delta > lim:
                    fail(gate, f"{target}: accuracy fell {-delta:.4f} "
                               f"(limit {lim:.4f})", -delta, lim)
        elif gate == "score_p50_drift":
            lim = spec.get("max")
            for target, e in d["rounds"].items():
                drift = e.get("score_p50_drift")
                if lim is not None and drift is not None and drift > lim:
                    fail(gate, f"{target}: score p50 drifted "
                               f"{drift:.3f}× the A-run score span "
                               f"(limit {lim})", drift, lim)
        elif gate in ("missing_rounds", "added_rounds"):
            lim = spec.get("max", 0)
            n = len(d[gate])
            if n > lim:
                fail(gate, f"{n} {gate.replace('_', ' ')} "
                           f"({', '.join(d[gate])}; limit {lim})", n, lim)
        else:
            fail(gate, "unknown gate name (typos must not silently "
                       "disable a gate)")
    return violations


# -- CLI ---------------------------------------------------------------------


def obs_main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="torchpruner_tpu obs",
        description="render / diff / gate run ledgers (obs report, "
                    "obs diff)",
    )
    sub = p.add_subparsers(dest="cmd", required=True)
    pr = sub.add_parser("report", help="render one run's ledger")
    pr.add_argument("dir", help="obs dir (report.json / ledger.jsonl) "
                                "or a report.json file")
    pr.add_argument("--json", action="store_true",
                    help="emit the raw report JSON instead of markdown")
    pr.add_argument("--md", metavar="PATH",
                    help="additionally write the markdown table to PATH")
    pd = sub.add_parser("diff", help="diff two runs (B vs A)")
    pd.add_argument("dir_a")
    pd.add_argument("dir_b")
    pd.add_argument("--gate", metavar="PATH",
                    help="tolerances JSON; exit 1 naming each violated "
                         "gate")
    pd.add_argument("--json", action="store_true",
                    help="emit the raw diff JSON instead of markdown")
    pp = sub.add_parser(
        "profile",
        help="render a run's per-kernel profile (capture windows -> "
             "ranked op table, roofline positions, HBM watermarks)")
    pp.add_argument("dir", help="obs dir (profile.json / profile/ "
                                "windows) or a profile.json/report.json "
                                "file")
    pp.add_argument("--top", type=int, default=25)
    pp.add_argument("--json", action="store_true",
                    help="emit the raw profile JSON instead of markdown")
    pw = sub.add_parser(
        "watch",
        help="live terminal view of a run's windowed metric "
             "time-series (metrics_ts.jsonl — obs.timeseries): newest "
             "window's histogram percentiles, counter rates, gauges")
    pw.add_argument("dir", help="obs dir being written by a live run "
                                "(or a finished one)")
    pw.add_argument("--interval", type=float, default=2.0,
                    help="redraw cadence, seconds")
    pw.add_argument("--once", action="store_true",
                    help="render one frame and exit (CI smoke)")
    pi = sub.add_parser(
        "incident",
        help="postmortem timeline: ledgered (or offline-reconstructed) "
             "incidents with ranked root-cause suspects, anomaly "
             "windows, gauge deltas, and slowest-request exemplars "
             "(obs.anomaly + obs.incident; exits 1 on an unexplained "
             "SLO burn)")
    pi.add_argument("dir", help="obs dir (single run or fleet router "
                                "dir with metrics_ts_fleet.jsonl)")
    pi.add_argument("--lookback", type=float, default=0.0,
                    help="correlation horizon in seconds (default: "
                         "TORCHPRUNER_INCIDENT_LOOKBACK_S or 120)")
    pi.add_argument("--json", action="store_true",
                    help="emit the raw incident/anomaly JSON instead "
                         "of the markdown postmortem")
    args = p.parse_args(argv)

    if args.cmd == "incident":
        from torchpruner_tpu.obs.incident import incident_main

        return incident_main(args)

    if args.cmd == "watch":
        from torchpruner_tpu.obs.timeseries import watch as ts_watch

        return ts_watch(args.dir, interval_s=args.interval,
                        once=args.once)

    if args.cmd == "profile":
        from torchpruner_tpu.obs.profile import format_profile, load_profile

        profile = load_profile(args.dir)
        if profile is None:
            print(f"{args.dir!r} holds no profile.json and no "
                  "profile/window_* captures — run with "
                  "--profile-every/--profile-steps (or POST /profile "
                  "on the serve frontend) to capture one",
                  file=sys.stderr)
            return 2
        if args.json:
            print(json.dumps(profile))
        else:
            print(format_profile(profile, top=args.top))
        return 0

    if args.cmd == "report":
        try:
            report = load_run(args.dir)
        except FileNotFoundError as e:
            print(str(e), file=sys.stderr)
            return 2
        text = format_report(report)
        if args.json:
            report.pop("_dir", None)
            print(json.dumps(report))
        else:
            print(text)
        if args.md:
            with open(args.md, "w") as f:
                f.write(text + "\n")
        return 0

    try:
        a, b = load_run(args.dir_a), load_run(args.dir_b)
    except FileNotFoundError as e:
        print(str(e), file=sys.stderr)
        return 2
    d = diff_runs(a, b)
    if args.json:
        print(json.dumps(d))
    else:
        print(format_diff(d))
    if args.gate:
        with open(args.gate) as f:
            gates = json.load(f)
        violations = check_gates(d, gates)
        for v in violations:
            print(f"GATE VIOLATION [{v['gate']}]: {v['detail']}",
                  file=sys.stderr)
        if violations:
            return 1
        print(f"gates OK ({len(gates)} checked)", file=sys.stderr)
    return 0


def newest_report(results_dir: str, match: str = "") -> Optional[str]:
    """Newest committed ``obs_report_*<match>*.json`` in ``results_dir``
    by name order (names embed dates, and mtime is meaningless after a
    checkout) — what bench auto-diffs a fresh run against."""
    import glob as _glob

    pattern = os.path.join(results_dir, f"obs_report_*{match}*.json")
    candidates = sorted(_glob.glob(pattern))
    return candidates[-1] if candidates else None


if __name__ == "__main__":
    sys.exit(obs_main())

"""Perfetto / Chrome-trace export of the obs span stream.

Converts ``events.jsonl`` span begin/end events into the Trace Event
Format (the JSON schema both ``chrome://tracing`` and
``ui.perfetto.dev`` open natively), so a TPU run's runtime phases can be
inspected on the same timeline UI as the XLA profiler's device tracks —
drag ``trace.json`` into Perfetto next to the XProf capture and the
``retrain`` / ``capture_fill`` / ``checkpoint_write`` spans line up
against the device stream.

Mapping:

- ``span_begin`` → a ``"ph": "B"`` event, ``span_end`` → ``"ph": "E"``
  (duration events; nesting reconstructs the flame from B/E pairing).
- ``ts`` is microseconds.  Begin uses the event's wall-clock ``ts``;
  end uses ``begin + dur_s`` (the monotonic duration) when available,
  so NTP steps between begin and end cannot produce a negative slice.
  Timestamps are additionally clamped monotonic per track — the format
  requires it, and a torn stream must still open.
- ``pid`` is the JAX process index (from the session's ``obs_init``
  marker), ``tid`` the OS thread id the span ran on (span events carry
  ``tid``; streams from before that field land on tid 0).
- A ``span_begin`` with no matching ``span_end`` (SIGKILL mid-phase)
  gets a synthetic ``E`` at the last seen timestamp of its track, so
  the B/E pairing always balances.
- span metadata (``target``, ``method``, …) rides in ``args``.

**Profiler merge**: when the run holds capture windows
(``obs.profile``), their per-op events are merged onto dedicated
tracks (one tid per window, offset at :data:`PROFILE_TID_BASE` so span
tids can never collide) as complete ``X`` events.  The profiler's
internal clock is unrelated to wall time, so each window's ops are
shifted onto the window's recorded wall start — the kernel slices line
up under the runtime span that contained the window, one timeline for
"which phase" and "which op".
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

TRACE_FILENAME = "trace.json"

#: profiler-derived op tracks start here (span tids are OS thread ids,
#: which Linux caps well below this)
PROFILE_TID_BASE = 1 << 30

#: per-request waterfall tracks start here (one tid per assembled
#: request trace; below PROFILE_TID_BASE, far above OS thread ids)
REQTRACE_TID_BASE = 1 << 29

_CORE_KEYS = frozenset({
    "event", "span", "name", "parent", "depth", "ts", "dur_s", "tid",
    "compile_count", "compile_s", "trace_count",
})


def trace_events_from_spans(events: List[dict],
                            pid_override: Optional[int] = None,
                            process_label: Optional[str] = None,
                            shift_s: float = 0.0) -> List[dict]:
    """Trace Event Format list from parsed obs events (the output of
    ``utils.profiling.load_span_events``).

    Cross-process merge hooks (``fleet.report.write_fleet_trace``):
    ``pid_override``/``process_label`` place this stream on its own
    named pid row (a fleet trace holds router + N replica streams, so
    the obs_init-derived index — every replica is its own process 0 —
    cannot be the pid), and ``shift_s`` is added to every wall-clock
    timestamp (the clock-offset alignment estimated from the health
    monitor's request/response timestamps)."""
    out: List[dict] = []
    pid = 0
    host = None
    for ev in events:
        if ev.get("event") == "obs_init":
            pid = int(ev.get("process_index", 0) or 0)
            host = ev.get("pid")
            break
    if pid_override is not None:
        pid = int(pid_override)
    label = process_label or f"torchpruner process {pid}"
    out.append({
        "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
        "args": {"name": label + (f" (os pid {host})" if host else "")},
    })

    last_ts: Dict[int, float] = {}   # per-tid monotonic clamp (µs)
    open_spans: Dict[str, dict] = {}  # span id -> emitted B event

    def clamp(tid: int, ts_us: float) -> float:
        ts_us = max(ts_us, last_ts.get(tid, 0.0))
        last_ts[tid] = ts_us
        return ts_us

    def args_of(ev: dict) -> Dict[str, Any]:
        extra = {k: v for k, v in ev.items() if k not in _CORE_KEYS}
        for k in ("compile_count", "compile_s", "trace_count"):
            if ev.get(k):
                extra[k] = ev[k]
        extra["span"] = ev.get("span")
        return extra

    for ev in events:
        kind = ev.get("event")
        if kind not in ("span_begin", "span_end"):
            continue
        tid = int(ev.get("tid", 0) or 0)
        name = str(ev.get("name", "?"))
        sid = ev.get("span")
        if kind == "span_begin":
            b = {
                "ph": "B", "name": name, "cat": "obs",
                "pid": pid, "tid": tid,
                "ts": clamp(tid, (float(ev.get("ts", 0.0)) + shift_s)
                            * 1e6),
                "args": args_of(ev),
            }
            out.append(b)
            if sid is not None:
                open_spans[sid] = b
        else:
            b = open_spans.pop(sid, None)
            if b is None:
                continue  # end without begin (rotated-away) — skip
            dur_s = ev.get("dur_s")
            ts_us = (b["ts"] + float(dur_s) * 1e6 if dur_s is not None
                     else (float(ev.get("ts", 0.0)) + shift_s) * 1e6)
            out.append({
                "ph": "E", "name": name, "cat": "obs",
                "pid": pid, "tid": b["tid"],
                "ts": clamp(b["tid"], ts_us),
                "args": args_of(ev),
            })
    # close any span the run never closed (kill mid-phase), innermost
    # first so the B/E nesting stays balanced per track
    for sid, b in sorted(open_spans.items(), reverse=True):
        out.append({
            "ph": "E", "name": b["name"], "cat": "obs",
            "pid": pid, "tid": b["tid"],
            "ts": clamp(b["tid"], b["ts"]),
            "args": {"span": sid, "torn": True},
        })
    return out


def profile_trace_events(profile_dir: str, pid: int = 0) -> List[dict]:
    """Profiler-derived op events for the Perfetto merge: each capture
    window's filtered op events (``trace_analysis.file_op_events``) as
    complete ``X`` events on its own stable tid
    (``PROFILE_TID_BASE + window index``), time-shifted so the window's
    first op lands at the window's recorded wall start — aligning the
    profiler's internal clock with the span stream's wall clock.
    Timestamps are clamped monotonic per track (the schema contract).
    Empty (never raises) without windows."""
    from torchpruner_tpu.obs.profile.capture import scan_windows
    from torchpruner_tpu.utils.trace_analysis import (
        file_op_events,
        find_trace_files,
    )

    out: List[dict] = []
    try:
        windows = scan_windows(profile_dir)
    except Exception:
        return out
    for w in windows:
        try:
            files = find_trace_files(w["dir"], latest_run=True)
            ops: List[dict] = []
            for f in files:
                ops.extend(file_op_events(f))
        except Exception:
            continue
        if not ops:
            continue
        tid = PROFILE_TID_BASE + int(w.get("index", 0))
        out.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": f"XLA ops (profile window "
                             f"{int(w.get('index', 0))})"},
        })
        t0_trace = min(op["ts"] for op in ops)
        t0_wall_us = float(w.get("t_start_unix") or 0.0) * 1e6
        last = 0.0
        for op in sorted(ops, key=lambda o: o["ts"]):
            ts = t0_wall_us + (op["ts"] - t0_trace)
            ts = max(ts, last)
            last = ts
            out.append({
                "ph": "X", "name": op["name"], "cat": "xla_op",
                "pid": pid, "tid": tid, "ts": ts, "dur": op["dur"],
                "args": {"window": int(w.get("index", 0))},
            })
    return out


# -- cross-process assembly (the fleet's merged trace) -----------------------
#
# A "stream" is one process's parsed event list plus its placement:
#   {"name": "replica0", "pid": 1, "events": [...], "shift_s": -0.0012}
# ``shift_s`` maps the stream's wall clock onto the reference (router)
# clock — estimated from the health monitor's request/response
# timestamps (fleet.report.collect_streams).


def merged_trace_events(streams: List[dict]) -> List[dict]:
    """Span B/E events of every stream on one timeline, each stream on
    its own pid.  B/E pairing is per-stream (span ids never cross a
    process), so duplicate span names across pids cannot mis-pair; a
    stream torn by a SIGKILL gets its open spans closed synthetically
    (the per-stream contract of :func:`trace_events_from_spans`);
    timestamps stay monotonic per (pid, tid) after the clock shift."""
    out: List[dict] = []
    for st in streams:
        out.extend(trace_events_from_spans(
            st.get("events") or [],
            pid_override=st.get("pid"),
            process_label=st.get("name"),
            shift_s=float(st.get("shift_s") or 0.0)))
    return out


def assemble_request_traces(streams: List[dict]) -> Dict[str, dict]:
    """Group ``req_stage`` / ``req_trace`` events from every stream into
    per-request traces on the reference clock::

        {trace_id: {"stages": [{"stage", "ts", "dur_s", "pid", ...}],
                    "pids": [...], "outcome": str|None,
                    "e2e_s": float|None, "ttft_s": float|None,
                    "attempts": int, "redrive": bool, "torn": bool}}

    Stages are sorted by aligned start time; a trace with stage events
    but no terminal ``req_trace`` summary from ANY process (the request
    died with its replica before redrive completed it elsewhere) is
    marked ``torn``.  When several processes report a summary, any
    ``complete`` wins the outcome and the LONGEST ``e2e_s`` is kept
    (the router's accept→complete subsumes a replica's local
    submit→done)."""
    traces: Dict[str, dict] = {}

    def entry(tid: str) -> dict:
        t = traces.get(tid)
        if t is None:
            t = traces[tid] = {
                "stages": [], "pids": set(), "outcome": None,
                "e2e_s": None, "ttft_s": None, "attempts": 0,
                "redrive": False, "torn": True,
            }
        return t

    for st in streams:
        pid = int(st.get("pid") or 0)
        shift = float(st.get("shift_s") or 0.0)
        for ev in st.get("events") or []:
            kind = ev.get("event")
            if kind == "req_stage":
                t = entry(str(ev.get("trace")))
                stage = {k: v for k, v in ev.items()
                         if k not in ("event", "trace")}
                stage["ts"] = float(ev.get("ts") or 0.0) + shift
                stage["pid"] = pid
                t["stages"].append(stage)
                t["pids"].add(pid)
                if ev.get("attempt"):
                    t["attempts"] = max(t["attempts"],
                                        int(ev["attempt"]))
                if ev.get("stage") == "redrive":
                    t["redrive"] = True
            elif kind == "req_trace":
                t = entry(str(ev.get("trace")))
                t["pids"].add(pid)
                # any process's "complete" wins the outcome; the e2e is
                # the LONGEST reported span (the router's accept ->
                # complete subsumes a replica's local submit -> done)
                if t["outcome"] is None or ev.get("outcome") == "complete":
                    t["outcome"] = ev.get("outcome")
                if ev.get("e2e_s") is not None:
                    t["e2e_s"] = max(t["e2e_s"] or 0.0,
                                     float(ev["e2e_s"]))
                if ev.get("ttft_s") is not None:
                    # earliest-finishing summary wins the TTFT: on a
                    # redrive/hedge the plane keeps the FIRST
                    # completion, so a later (abandoned) attempt's
                    # slower ttft must not overwrite the served one
                    ts = float(ev.get("ts") or 0.0) + shift
                    if t.get("_ttft_ts") is None or ts < t["_ttft_ts"]:
                        t["ttft_s"] = float(ev["ttft_s"])
                        t["_ttft_ts"] = ts
                t["torn"] = False
    for t in traces.values():
        t["stages"].sort(key=lambda s: s["ts"])
        t["pids"] = sorted(t["pids"])
        t.pop("_ttft_ts", None)
    return traces


def reqtrace_trace_events(traces: Dict[str, dict]) -> List[dict]:
    """Per-request waterfall tracks for the merged Perfetto trace: one
    tid per assembled request (``REQTRACE_TID_BASE`` + index, ordered
    by first stage time), each stage a complete ``X`` slice (instant
    stages become ``i`` markers) ON THE PID OF THE PROCESS THAT
    RECORDED IT — so one request's row visibly hops router → replica
    (→ survivor, on a redrive).  Start times are clamped monotonic per
    (pid, tid), the format contract."""
    out: List[dict] = []
    order = sorted(traces.items(),
                   key=lambda kv: (kv[1]["stages"][0]["ts"]
                                   if kv[1]["stages"] else 0.0, kv[0]))
    last_ts: Dict[tuple, float] = {}
    for i, (trace_id, t) in enumerate(order):
        tid = REQTRACE_TID_BASE + i
        for pid in t["pids"]:
            out.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": tid,
                "args": {"name": f"req {trace_id}"
                                 + (" [torn]" if t.get("torn") else "")},
            })
        for s in t["stages"]:
            pid = s["pid"]
            ts_us = float(s["ts"]) * 1e6
            key = (pid, tid)
            ts_us = max(ts_us, last_ts.get(key, 0.0))
            last_ts[key] = ts_us
            dur_us = float(s.get("dur_s") or 0.0) * 1e6
            args = {k: v for k, v in s.items()
                    if k not in ("ts", "dur_s", "pid")}
            args["trace"] = trace_id
            base = {"name": str(s.get("stage", "?")), "cat": "reqtrace",
                    "pid": pid, "tid": tid, "ts": ts_us, "args": args}
            if dur_us > 0:
                out.append({**base, "ph": "X", "dur": dur_us})
            else:
                out.append({**base, "ph": "i", "s": "t"})
    return out


def write_merged_trace(streams: List[dict], out_path: str,
                       traces: Optional[Dict[str, dict]] = None) -> str:
    """ONE ``trace.json`` for a multi-process run: every stream's span
    flame on its own pid plus (when ``traces`` is given) the assembled
    per-request waterfall tracks.  Returns the written path."""
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    events = merged_trace_events(streams)
    if traces is None:
        traces = assemble_request_traces(streams)
    events.extend(reqtrace_trace_events(traces))
    atomic_write_json(out_path, {
        "traceEvents": events,
        "displayTimeUnit": "ms",
    }, indent=None)
    return out_path


def write_trace(events_jsonl: str, out_path: Optional[str] = None,
                profile_dir: Optional[str] = None) -> str:
    """Convert an ``events.jsonl`` (rotation-aware, latest session only —
    ``load_span_events``'s contract) into ``trace.json`` next to it (or
    at ``out_path``), merging profiler capture windows from
    ``profile_dir`` when present.  Returns the written path."""
    from torchpruner_tpu.utils.profiling import load_span_events

    events = load_span_events(events_jsonl)
    if out_path is None:
        out_path = os.path.join(os.path.dirname(events_jsonl) or ".",
                                TRACE_FILENAME)
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    trace_events = trace_events_from_spans(events)
    if profile_dir and os.path.isdir(profile_dir):
        pid = 0
        for ev in events:
            if ev.get("event") == "obs_init":
                pid = int(ev.get("process_index", 0) or 0)
                break
        trace_events.extend(profile_trace_events(profile_dir, pid=pid))
    payload = {
        "traceEvents": trace_events,
        "displayTimeUnit": "ms",
    }
    atomic_write_json(out_path, payload, indent=None)
    return out_path

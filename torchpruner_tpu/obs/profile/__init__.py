"""Kernel-level continuous profiling (the obs layer's microscope).

The spans/metrics stack (PRs 2+5) says *which phase* a run spent its
wall-clock in; this package says *which compiled ops* — per-kernel
step-time attribution from programmatic ``jax.profiler`` capture
windows, roofline-positioned, HBM-tracked, and gateable:

- :mod:`~torchpruner_tpu.obs.profile.capture` — capture windows on a
  step cadence / on demand (``ContinuousProfiler``);
- :mod:`~torchpruner_tpu.obs.profile.kernels` — trace → ranked
  per-kernel table (ms/step, % of step, launch count, roofline
  position) + ``kernel_*`` gate scalars + ``profile.json``;
- :mod:`~torchpruner_tpu.obs.profile.hbm` — allocation watermark per
  span phase with a fragmentation estimate.

Drivers enable it with ``obs.configure(obs_dir, profile_every=N)``
(CLI ``--profile-every``), read it with
``python -m torchpruner_tpu obs profile <dir>``, and gate it with the
``kernel_<name>_ms`` scalars in ``obs diff --gate`` — which is how a
kernel regression fails CI even when the total step time stays green.
"""

from torchpruner_tpu.obs.profile.capture import (
    ContinuousProfiler,
    OneShotCapture,
    scan_windows,
)
from torchpruner_tpu.obs.profile.hbm import HbmSampler
from torchpruner_tpu.obs.profile.kernels import (
    base_kernel_name,
    build_profile,
    format_profile,
    kernel_gauges,
    kernel_scalar_name,
    kernel_table,
    load_profile,
    top_rows,
)

__all__ = [
    "ContinuousProfiler", "HbmSampler", "OneShotCapture", "scan_windows",
    "base_kernel_name", "build_profile", "format_profile",
    "kernel_gauges", "kernel_scalar_name", "kernel_table",
    "load_profile", "top_rows",
]

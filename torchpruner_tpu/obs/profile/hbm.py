"""HBM timeline: allocation watermarks per span phase.

:class:`HbmSampler` rides the span stream (the tracer's extra sink):
every ``span_begin``/``span_end`` edge takes one bounded-cost sample of
``device.memory_stats()`` per local device — live bytes, the
allocator's peak, and a **fragmentation estimate**
``1 - largest_free_block / free_bytes`` when the runtime exposes block
stats.  Off-accelerator (the CPU smoke) the devices report no stats and
the sampler falls back to host RSS, so the timeline is never empty and
the same assertions run in CI.

Why span edges and not a poller thread: phases are exactly the
boundaries where allocation regimes change (a prune shrinks params, a
quant swap shrinks weights, a prefill grows a cache), so the watermark
*per phase* is the delta a prune/quant variant is judged on — and edges
need no extra thread, no clock, and throttle naturally (a minimum
inter-sample interval guards pathological span churn like per-request
serve spans).

The timeline lands in ``profile.json`` under ``hbm`` and renders in
``obs profile`` as a per-phase watermark table.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

#: minimum seconds between samples — span churn (per-request serve
#: spans) must not turn every edge into a memory_stats() syscall storm
MIN_SAMPLE_INTERVAL_S = 0.02

MAX_SAMPLES = 4096


def _device_sample() -> Dict[str, Dict[str, float]]:
    """Per-device live/peak/fragmentation snapshot (empty off-TPU)."""
    out: Dict[str, Dict[str, float]] = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            rec: Dict[str, float] = {}
            for key in ("bytes_in_use", "peak_bytes_in_use",
                        "bytes_limit", "largest_free_block_bytes",
                        "largest_alloc_size"):
                if stats.get(key) is not None:
                    rec[key] = float(stats[key])
            if not rec:
                continue
            limit = rec.get("bytes_limit")
            in_use = rec.get("bytes_in_use")
            largest_free = rec.get("largest_free_block_bytes")
            if limit and in_use is not None and largest_free is not None:
                free = max(limit - in_use, 1.0)
                rec["fragmentation"] = max(0.0, 1.0 - largest_free / free)
            out[f"device{d.id}"] = rec
    except Exception:
        pass
    return out


def _host_rss_bytes() -> Optional[float]:
    try:
        with open("/proc/self/statm") as f:
            return float(f.read().split()[1]) * 4096.0
    except (OSError, ValueError, IndexError):
        try:
            import resource

            return float(resource.getrusage(
                resource.RUSAGE_SELF).ru_maxrss) * 1024.0
        except Exception:
            return None


class HbmSampler:
    """Span-edge memory sampler (see module docstring)."""

    def __init__(self, emit=None, max_samples: int = MAX_SAMPLES):
        self.emit = emit
        self.max_samples = max_samples
        self.timeline: List[Dict[str, Any]] = []
        self._t_last = 0.0

    def on_event(self, ev: dict) -> None:
        """Tracer extra-sink hook: sample at span edges."""
        kind = ev.get("event")
        if kind not in ("span_begin", "span_end"):
            return
        now = time.perf_counter()
        if now - self._t_last < MIN_SAMPLE_INTERVAL_S \
                or len(self.timeline) >= self.max_samples:
            return
        self._t_last = now
        devices = _device_sample()
        sample: Dict[str, Any] = {
            "ts": ev.get("ts", time.time()),
            "phase": ev.get("name", "?"),
            "edge": "begin" if kind == "span_begin" else "end",
        }
        if devices:
            sample["devices"] = devices
            in_use = [v.get("bytes_in_use") for v in devices.values()
                      if v.get("bytes_in_use") is not None]
            if in_use:
                sample["bytes_in_use_max"] = max(in_use)
            frags = [v.get("fragmentation") for v in devices.values()
                     if v.get("fragmentation") is not None]
            if frags:
                sample["fragmentation_max"] = max(frags)
        else:
            rss = _host_rss_bytes()
            if rss is None:
                return
            sample["host_rss_bytes"] = rss
            sample["bytes_in_use_max"] = rss
        self.timeline.append(sample)
        if self.emit is not None:
            try:
                self.emit({"event": "hbm_sample", **sample})
            except Exception:
                pass

    def summary(self) -> Dict[str, Any]:
        """Per-phase watermark table + the raw (bounded) timeline."""
        phases: Dict[str, Dict[str, Any]] = {}
        for s in self.timeline:
            b = s.get("bytes_in_use_max")
            if b is None:
                continue
            agg = phases.setdefault(s["phase"], {
                "peak_bytes": b, "first_bytes": b, "last_bytes": b,
                "fragmentation": s.get("fragmentation_max"),
                "samples": 0,
            })
            agg["peak_bytes"] = max(agg["peak_bytes"], b)
            agg["last_bytes"] = b
            if s.get("fragmentation_max") is not None:
                agg["fragmentation"] = max(
                    agg["fragmentation"] or 0.0, s["fragmentation_max"])
            agg["samples"] += 1
        for agg in phases.values():
            agg["delta_bytes"] = int(agg["last_bytes"]
                                     - agg["first_bytes"])
            agg["peak_bytes"] = int(agg["peak_bytes"])
            agg.pop("first_bytes", None)
            agg.pop("last_bytes", None)
        peak = max((s.get("bytes_in_use_max", 0.0)
                    for s in self.timeline), default=None)
        return {
            "phases": phases,
            "peak_bytes": (int(peak) if peak else None),
            "source": ("device" if any("devices" in s
                                       for s in self.timeline)
                       else "host_rss"),
            "timeline": self.timeline[-512:],
        }

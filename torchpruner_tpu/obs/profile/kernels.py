"""Per-op/per-kernel step-time attribution from profiler captures.

A capture window (``obs.profile.capture.ContinuousProfiler``) is a
``jax.profiler`` trace of a few consecutive steps.  This module turns
one or more windows into the table the obs layer was missing: which
compiled ops the step actually spent its milliseconds in, normalized
per step, ranked, and positioned on the roofline — so "the step got
2 ms slower" becomes "``dot`` went from bf16 to f32 and doubled".

Attribution pipeline:

- ``utils.trace_analysis.summarize_trace`` parses the window's
  ``*.trace.json.gz`` into per-op totals (device op tracks on TPU,
  XLA thunk events on the CPU backend), with runtime noise filtered.
- Op names are normalized to a stable **base kernel name**
  (``dot.4`` / ``dot.17.clone`` → ``dot``; ``fusion.12`` → ``fusion``)
  so tables from different compilations of the same program line up —
  XLA's numeric suffixes are compilation accidents, not identities.
- Times divide by the steps the window covered → **ms per step**, the
  unit the per-kernel gates compare (window length cancels out).
- ``coverage`` = summed op ms ÷ the step span measured by the obs step
  telemetry over the same window — the sanity number that says whether
  the trace actually explains the step (host gaps and untraced runtime
  time push it below 1; ops overlapping across device cores push it
  above).
- Each ranked kernel gets a **roofline position**
  (``utils.flops.roofline_position``): step FLOPs
  (``StepTelemetry.flops_per_step``) are attributed to compute-category
  ops (matmul/convolution) proportional to their time; weight-traffic
  bytes (3× param bytes per training step: read fwd, read bwd, write
  update) likewise — deliberately erring low (activations excluded), a
  savings gauge convention shared with ``prefix_flops_estimate``.

The ranked table is exported two ways: ``profile.json`` (full rows,
per window and merged) and ``kernel_<base>_ms`` / ``kernel_<base>_pct``
gauges in the session metrics — which is what lets ``obs diff --gate``
fail CI on a kernel regression that an unchanged total step time hides.
"""

from __future__ import annotations

import os
import re
from typing import Any, Dict, List, Optional

#: categories whose ops execute model FLOPs (the roofline's compute side)
COMPUTE_CATEGORIES = ("matmul", "convolution")

#: how many ranked kernels become ``kernel_*`` gauges (bounds the metric
#: namespace; the full table lives in profile.json)
MAX_KERNEL_GAUGES = 12


def base_kernel_name(name: str) -> str:
    """Stable kernel identity across compilations: strip XLA's numeric
    instance suffixes and ``.clone``/``.remat`` decorations, keep the op
    family (``dot``, ``fusion``, ``loop_convolution_fusion``, ...)."""
    base = re.sub(r"\.(\d+|clone|remat)", "", name)
    base = re.sub(r"[^0-9A-Za-z_]+", "_", base).strip("_")
    return base or "op"


def kernel_scalar_name(base: str, unit: str = "ms") -> str:
    return f"kernel_{base}_{unit}"


def summarize_window(window_dir: str, top: int = 200) -> Optional[Dict]:
    """Raw per-op summary of one capture window's trace files (None when
    the window holds no parseable trace — a torn capture)."""
    from torchpruner_tpu.utils.trace_analysis import summarize_trace

    try:
        return summarize_trace(window_dir, top=top, latest_run=False)
    except (FileNotFoundError, ValueError, OSError):
        return None


def merge_ops(summaries: List[Dict]) -> Dict[str, Dict[str, Any]]:
    """Fold the windows' ``top_ops`` into per-base-kernel totals:
    ``{base: {"ms", "count", "category", "ops": {raw names}}}``."""
    out: Dict[str, Dict[str, Any]] = {}
    for s in summaries:
        for op in s.get("top_ops", []):
            base = base_kernel_name(op.get("name", ""))
            agg = out.setdefault(base, {
                "ms": 0.0, "count": 0, "category": op.get("category",
                                                          "other"),
                "ops": set(),
            })
            agg["ms"] += float(op.get("ms", 0.0))
            agg["count"] += int(op.get("count", 0))
            agg["ops"].add(op.get("name", ""))
    return out


def kernel_table(merged: Dict[str, Dict[str, Any]], *,
                 steps: int,
                 step_time_s: Optional[float] = None,
                 flops_per_step: Optional[float] = None,
                 param_bytes: Optional[float] = None,
                 peak_flops: Optional[float] = None,
                 peak_bw: Optional[float] = None,
                 top: int = 25) -> List[Dict[str, Any]]:
    """The ranked per-kernel rows: name, ms/step, % of the attributed
    total, launch count/step, and a roofline position per kernel."""
    from torchpruner_tpu.utils.flops import roofline_position

    steps = max(1, int(steps))
    total_ms = sum(v["ms"] for v in merged.values()) or 1.0
    compute_ms = sum(v["ms"] for v in merged.values()
                     if v["category"] in COMPUTE_CATEGORIES)
    rows: List[Dict[str, Any]] = []
    for base, v in sorted(merged.items(), key=lambda kv: -kv[1]["ms"]):
        ms_per_step = v["ms"] / steps
        t_s = ms_per_step / 1e3
        share = (v["ms"] / compute_ms) \
            if compute_ms and v["category"] in COMPUTE_CATEGORIES else 0.0
        flops = (flops_per_step * share) if flops_per_step else None
        # weight traffic only (see module docstring) — errs low
        bytes_moved = (3.0 * param_bytes * share) if param_bytes else None
        rows.append({
            "kernel": base,
            "category": v["category"],
            "ms_per_step": round(ms_per_step, 4),
            "pct_of_step": round(100.0 * v["ms"] / total_ms, 1),
            "launches_per_step": round(v["count"] / steps, 2),
            "ops": sorted(v["ops"])[:8],
            "roofline": roofline_position(
                flops, bytes_moved, t_s,
                peak_flops=peak_flops, peak_bw=peak_bw),
        })
        if len(rows) >= top:
            break
    # coverage: do the attributed op milliseconds explain the measured
    # step span? (host gaps push it < 1, multi-core overlap pushes > 1)
    if step_time_s:
        measured_ms = step_time_s * 1e3
        for r in rows:
            r["pct_of_measured_step"] = round(
                100.0 * r["ms_per_step"] / measured_ms, 1)
    return rows


def build_profile(windows: List[Dict[str, Any]], *,
                  flops_per_step: Optional[float] = None,
                  param_bytes: Optional[float] = None,
                  peak_flops: Optional[float] = None,
                  peak_bw: Optional[float] = None,
                  hbm: Optional[Dict[str, Any]] = None,
                  telemetry_step_s: Optional[float] = None,
                  top: int = 25) -> Dict[str, Any]:
    """Assemble the ``profile.json`` payload from closed capture-window
    records (``ContinuousProfiler.windows``): the merged ranked kernel
    table, per-window summaries, coverage vs the telemetry-measured step
    span, and the HBM timeline."""
    summaries, used = [], []
    steps = 0
    step_seconds = 0.0
    step_times: List[float] = []
    for w in windows:
        s = summarize_window(w["dir"])
        if s is None:
            continue
        summaries.append(s)
        used.append({k: w.get(k) for k in
                     ("index", "dir", "steps", "step_seconds",
                      "t_start_unix", "wall_s", "on_demand")})
        used[-1]["op_ms"] = s.get("total_ms")
        steps += int(w.get("steps") or 0)
        step_seconds += float(w.get("step_seconds") or 0.0)
        step_times.extend(w.get("step_times") or [])
    merged = merge_ops(summaries)
    # the per-step denominator, in preference order: the session
    # telemetry's p50 over ALL steps (mostly un-profiled — in-window
    # steps carry the trace collector's own overhead, large on CPU),
    # else the MEDIAN in-window step time (one epoch-boundary step
    # with eval + retrace rolled into its return-to-return dt would
    # dominate a mean), else the plain mean
    if telemetry_step_s:
        step_time_s: Optional[float] = float(telemetry_step_s)
    elif step_times:
        step_time_s = float(sorted(step_times)[len(step_times) // 2])
    else:
        step_time_s = (step_seconds / steps) if steps else None
    rows = kernel_table(
        merged, steps=steps or 1, step_time_s=step_time_s,
        flops_per_step=flops_per_step, param_bytes=param_bytes,
        peak_flops=peak_flops, peak_bw=peak_bw, top=top)
    total_op_ms = sum(s.get("total_ms", 0.0) for s in summaries)
    coverage = (total_op_ms / (steps * step_time_s * 1e3)) \
        if steps and step_time_s else None
    by_category: Dict[str, float] = {}
    for s in summaries:
        for cat, ms in (s.get("by_category") or {}).items():
            by_category[cat] = by_category.get(cat, 0.0) + ms
    return {
        "windows": used,
        "steps_profiled": steps,
        "step_time_mean_s": (round(step_time_s, 6) if step_time_s
                             else None),
        "op_ms_total": round(total_op_ms, 3),
        "coverage": (round(coverage, 3) if coverage is not None else None),
        "by_category": {k: round(v, 3) for k, v in
                        sorted(by_category.items(), key=lambda kv: -kv[1])},
        "kernels": rows,
        "hbm": hbm or {},
        "peaks": {"peak_flops": peak_flops, "peak_bw": peak_bw},
    }


def kernel_gauges(profile: Dict[str, Any],
                  registry) -> Dict[str, float]:
    """Install the per-kernel gate scalars into ``registry``:
    ``kernel_<base>_ms`` (ms per step) and ``kernel_<base>_pct`` (share
    of attributed op time) for the top :data:`MAX_KERNEL_GAUGES` rows,
    plus the profile headline gauges.  Returns what was set."""
    out: Dict[str, float] = {}
    for r in profile.get("kernels", [])[:MAX_KERNEL_GAUGES]:
        out[kernel_scalar_name(r["kernel"], "ms")] = r["ms_per_step"]
        out[kernel_scalar_name(r["kernel"], "pct")] = r["pct_of_step"]
    if profile.get("coverage") is not None:
        out["profile_coverage"] = profile["coverage"]
    out["profile_windows_total"] = float(len(profile.get("windows", [])))
    if profile.get("steps_profiled"):
        out["profile_steps_total"] = float(profile["steps_profiled"])
    for name, v in out.items():
        help_ = ""
        if name.startswith("kernel_"):
            help_ = ("per-kernel step-time attribution from profiler "
                     "capture windows (ms per step / % of attributed "
                     "op time)")
        registry.gauge(name, help_).set(v)
    return out


def top_rows(window_dir: str, *, steps: int = 1, top: int = 5,
             flops_per_step: Optional[float] = None,
             param_bytes: Optional[float] = None) -> List[Dict[str, Any]]:
    """Compact top-N kernel rows for ONE capture directory — what the
    bench legs attach next to their timing rows.  Empty on a torn or
    op-less capture (never raises)."""
    try:
        s = summarize_window(window_dir)
        if s is None:
            return []
        peak_flops = peak_bw = None
        try:
            import jax

            from torchpruner_tpu.utils import flops as F

            dev = jax.devices()[0]
            peak_flops = F.peak_bf16_flops(dev)
            peak_bw = F.peak_hbm_bw(dev)
        except Exception:
            pass
        rows = kernel_table(
            merge_ops([s]), steps=steps, flops_per_step=flops_per_step,
            param_bytes=param_bytes, peak_flops=peak_flops,
            peak_bw=peak_bw, top=top)
        return [{
            "kernel": r["kernel"], "category": r["category"],
            "ms_per_step": r["ms_per_step"],
            "pct_of_step": r["pct_of_step"],
            "bound": r["roofline"]["bound"],
            "pct_peak_flops": (round(r["roofline"]["pct_peak_flops"], 2)
                               if r["roofline"]["pct_peak_flops"]
                               is not None else None),
        } for r in rows]
    except Exception:  # profiling must never fail a bench leg
        return []


# -- rendering ---------------------------------------------------------------


def _fmt(v, fmt=".3f"):
    return format(v, fmt) if isinstance(v, (int, float)) else ""


def format_profile(profile: Dict[str, Any], top: Optional[int] = None
                   ) -> str:
    """Markdown rendering of a profile payload (the ``obs profile``
    CLI's output)."""
    lines: List[str] = ["# kernel profile"]
    bits = []
    if profile.get("windows"):
        bits.append(f"{len(profile['windows'])} capture window(s)")
    if profile.get("steps_profiled"):
        bits.append(f"{profile['steps_profiled']} steps")
    if profile.get("step_time_mean_s"):
        bits.append(f"step {1e3 * profile['step_time_mean_s']:.3f} ms")
    if profile.get("op_ms_total") is not None:
        bits.append(f"op time {profile['op_ms_total']:.1f} ms")
    if profile.get("coverage") is not None:
        bits.append(f"coverage {100 * profile['coverage']:.0f}% of "
                    "measured step span")
    if bits:
        lines += ["", ", ".join(bits)]
    rows = profile.get("kernels", [])[: top or None]
    if rows:
        lines += ["", "| kernel | category | ms/step | % step | "
                      "launches/step | bound | % peak FLOP/s | "
                      "intensity (FLOP/B) |",
                  "|---|---|---|---|---|---|---|---|"]
        for r in rows:
            rf = r.get("roofline") or {}
            lines.append(
                f"| `{r['kernel']}` | {r['category']} "
                f"| {_fmt(r['ms_per_step'])} | {_fmt(r['pct_of_step'], '.1f')} "
                f"| {_fmt(r['launches_per_step'], '.2f')} "
                f"| {rf.get('bound', '')} "
                f"| {_fmt(rf.get('pct_peak_flops'), '.2f')} "
                f"| {_fmt(rf.get('intensity_flops_per_byte'), '.1f')} |")
    else:
        lines += ["", "(no kernel rows — no capture windows, or the "
                      "traces held no op events)"]
    cats = profile.get("by_category") or {}
    if cats:
        lines += ["", "| category | ms |", "|---|---|"]
        for cat, ms in cats.items():
            lines.append(f"| {cat} | {ms:.1f} |")
    hbm = profile.get("hbm") or {}
    phases = hbm.get("phases") or {}
    if phases:
        lines += ["", "| phase (HBM watermark) | peak bytes | Δ bytes "
                      "| frag est | samples |", "|---|---|---|---|---|"]
        for name, v in phases.items():
            lines.append(
                f"| {name} | {int(v.get('peak_bytes') or 0)} "
                f"| {int(v.get('delta_bytes') or 0):+d} "
                f"| {_fmt(v.get('fragmentation'), '.3f')} "
                f"| {v.get('samples', 0)} |")
    return "\n".join(lines)


def load_profile(run_dir: str) -> Optional[Dict[str, Any]]:
    """A run's profile payload: ``profile.json`` when the session closed
    cleanly, else re-parsed from whatever ``profile/window_*`` capture
    dirs survived (a SIGKILLed run must still be profileable).  Also
    accepts the profile.json FILE directly, or a report.json carrying a
    ``profile`` block."""
    import json

    if os.path.isfile(run_dir):
        with open(run_dir) as f:
            payload = json.load(f)
        return payload.get("profile", payload)
    path = os.path.join(run_dir, "profile.json")
    if os.path.exists(path):
        with open(path) as f:
            return json.load(f)
    report = os.path.join(run_dir, "report.json")
    if os.path.exists(report):
        with open(report) as f:
            prof = json.load(f).get("profile")
        if prof:
            return prof
    from torchpruner_tpu.obs.profile.capture import scan_windows

    windows = scan_windows(os.path.join(run_dir, "profile"))
    if not windows:
        return None
    return build_profile(windows)

"""Programmatic ``jax.profiler`` capture windows on a step cadence.

:class:`ContinuousProfiler` owns the capture state machine of one obs
session.  Two ways a window opens:

- **cadence** — ``every_steps > 0``: every N recorded steps, the next
  step boundary starts a ``window_steps``-step capture (the
  ``obs.record_step`` hot path ticks the profiler: one int compare when
  idle, so instrumented loops pay nothing between windows);
- **on-demand** — :meth:`request_window` (the ``obs profile``-era CLI
  flag, the serve frontend's ``POST /profile``): the next step boundary
  opens one window regardless of cadence.  With no step loop running
  (an idle serving engine), :meth:`tick` from any loop boundary works
  the same.

The capture itself is ``jax.profiler.start_trace`` /``stop_trace`` —
start is cheap (enables the collector); stop serializes the trace to
the window dir.  Both run at a step boundary on the caller's thread:
the stop cost is real but bounded by the window length, charged to a
``profile_capture`` span so it shows up attributed instead of smearing
into the next step's time.  The step loop itself is never paused —
steps inside a window run exactly as outside it.

Each window lands in ``<dir>/window_<k>/`` with a ``window.json``
sidecar (steps covered, their summed step-seconds from the telemetry
stopwatch, wall timestamps) — what joins the trace's op table back to
the span stream and lets ``kernel_table`` normalize per step.
"""

from __future__ import annotations

import glob
import json
import os
import time
from typing import Any, Dict, List, Optional

WINDOW_META = "window.json"

#: hard cap on windows per session — continuous profiling must bound
#: its disk/parse cost even on week-long runs (oldest evidence wins;
#: raise via ContinuousProfiler(max_windows=...))
DEFAULT_MAX_WINDOWS = 16


class ContinuousProfiler:
    """See module docstring.  ``emit`` is an optional ``callable(dict)``
    (the session's JSONL event writer) that receives
    ``profile_window_begin`` / ``profile_window_end`` markers."""

    def __init__(self, profile_dir: str, *, every_steps: int = 0,
                 window_steps: int = 3,
                 max_windows: int = DEFAULT_MAX_WINDOWS,
                 emit=None, tracer=None):
        self.profile_dir = profile_dir
        self.every_steps = max(0, int(every_steps))
        self.window_steps = max(1, int(window_steps))
        self.max_windows = max(1, int(max_windows))
        self.emit = emit
        self.tracer = tracer
        #: closed windows: {"index","dir","steps","step_seconds",
        #: "t_start_unix","wall_s","on_demand"}
        self.windows: List[Dict[str, Any]] = []
        self._steps_seen = 0
        self._want_window = False
        self._open: Optional[Dict[str, Any]] = None
        self._failed = False  # a start_trace failure disables profiling

    # -- the step hook (hot path) -------------------------------------------

    def on_step(self, dt_s: float = 0.0) -> None:
        """One recorded step.  Opens/advances/closes windows at step
        boundaries; between windows it is one increment + compare."""
        self._steps_seen += 1
        if self._open is not None:
            self._open["steps"] += 1
            self._open["step_seconds"] += float(dt_s or 0.0)
            self._open["step_times"].append(round(float(dt_s or 0.0), 9))
            if self._open["steps"] >= self._open["target_steps"]:
                self._stop_window()
            return
        if self._want_window:
            self._start_window(on_demand=True)
            return
        if self.every_steps and len(self.windows) < self.max_windows \
                and self._steps_seen % self.every_steps == 0:
            self._start_window(on_demand=False)

    def tick(self) -> None:
        """A loop boundary that is not a step (an idle serving engine):
        lets an on-demand request open — and a stale window close — even
        when no steps are flowing."""
        if self._open is not None:
            # no steps arrived; close once the wall budget is well past
            # (window_steps at 1 s/step is a generous idle bound)
            if time.perf_counter() - self._open["t_mono"] \
                    > max(1.0, self.window_steps):
                self._stop_window()
        elif self._want_window:
            self._start_window(on_demand=True)

    def request_window(self) -> bool:
        """Arm one on-demand window (CLI / serve endpoint).  Returns
        False when a window is already open/armed, the session's
        window cap is reached, or profiling is disabled by an earlier
        failure — a True MUST mean a capture will actually happen."""
        if self._failed or self._open is not None or self._want_window \
                or len(self.windows) >= self.max_windows:
            return False
        self._want_window = True
        return True

    @property
    def active(self) -> bool:
        return self._open is not None

    # -- window lifecycle ---------------------------------------------------

    def _span(self, name, **meta):
        import contextlib

        if self.tracer is None:
            return contextlib.nullcontext()
        return self.tracer.span(name, **meta)

    def _start_window(self, on_demand: bool) -> None:
        self._want_window = False
        if self._failed or len(self.windows) >= self.max_windows:
            return
        index = len(self.windows)
        wdir = os.path.join(self.profile_dir, f"window_{index:03d}")
        try:
            import jax

            os.makedirs(wdir, exist_ok=True)
            with self._span("profile_capture", window=index, edge="start"):
                jax.profiler.start_trace(wdir)
        except Exception:
            # another trace already active (--profile), or an unwritable
            # dir: disable rather than retry-fail every N steps
            self._failed = True
            return
        self._open = {
            "index": index, "dir": wdir, "steps": 0, "step_seconds": 0.0,
            "step_times": [], "target_steps": self.window_steps,
            "t_start_unix": time.time(), "t_mono": time.perf_counter(),
            "on_demand": on_demand,
        }
        self._emit_marker("profile_window_begin", self._open)

    def _stop_window(self) -> None:
        w, self._open = self._open, None
        if w is None:
            return
        try:
            import jax

            with self._span("profile_capture", window=w["index"],
                            edge="stop"):
                jax.profiler.stop_trace()
        except Exception:
            pass  # keep whatever the collector already flushed
        w["wall_s"] = round(time.perf_counter() - w.pop("t_mono"), 6)
        w.pop("target_steps", None)
        self.windows.append(w)
        try:
            with open(os.path.join(w["dir"], WINDOW_META), "w") as f:
                json.dump({k: v for k, v in w.items() if k != "dir"}, f)
        except OSError:
            pass
        self._emit_marker("profile_window_end", w)

    def _emit_marker(self, event: str, w: Dict[str, Any]) -> None:
        if self.emit is None:
            return
        try:
            self.emit({
                "event": event, "ts": time.time(), "window": w["index"],
                "steps": w.get("steps", 0), "on_demand": w["on_demand"],
            })
        except Exception:
            pass

    def close(self) -> List[Dict[str, Any]]:
        """Stop any open window; returns the closed-window records."""
        if self._open is not None:
            self._stop_window()
        return self.windows


class OneShotCapture:
    """One profiler capture window around an already-measured workload,
    writing the top-N per-kernel rows (``kernels.top_rows``) into
    ``row["kernels"]`` — how the bench legs and ``flash_sweep`` attach
    op-level evidence next to their headline timings.  Runs AFTER the
    timed section so trace overhead never pollutes the timing; any
    failure (a trace already active under ``--profile``, parse errors)
    degrades to no row, never an error.  ``steps`` may be reassigned
    inside the block (``win.steps = engine.steps - steps0``) when the
    step count is only known afterwards::

        with OneShotCapture(result, steps=K):
            fn()          # one representative dispatch, fenced
    """

    def __init__(self, row: Dict[str, Any], steps: int = 1, top: int = 5,
                 flops_per_step: Optional[float] = None,
                 key: str = "kernels"):
        self.row, self.steps, self.top = row, max(1, steps), top
        self.flops_per_step = flops_per_step
        self.key = key
        self._dir: Optional[str] = None

    def __enter__(self) -> "OneShotCapture":
        import shutil
        import tempfile

        try:
            import jax

            self._dir = tempfile.mkdtemp(prefix="kernel_capture_")
            jax.profiler.start_trace(self._dir)
        except Exception:
            # start failed (another trace active under --profile): the
            # tmpdir must not leak — one per bench leg / sweep point
            if self._dir is not None:
                shutil.rmtree(self._dir, ignore_errors=True)
            self._dir = None
        return self

    def __exit__(self, exc_type, exc, tb):
        import shutil

        if self._dir is None:
            return False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            pass
        try:
            if exc_type is None:
                from torchpruner_tpu.obs.profile.kernels import top_rows

                rows = top_rows(self._dir, steps=max(1, int(self.steps)),
                                top=self.top,
                                flops_per_step=self.flops_per_step)
                if rows:
                    self.row[self.key] = rows
        except Exception:  # profiling must never fail the measurement
            pass
        finally:
            shutil.rmtree(self._dir, ignore_errors=True)
        return False


def scan_windows(profile_dir: str) -> List[Dict[str, Any]]:
    """Rebuild window records from ``window_*/window.json`` sidecars (or
    bare window dirs, for a run killed before the sidecar landed) — the
    offline path ``obs profile`` uses when the session never closed."""
    out: List[Dict[str, Any]] = []
    for wdir in sorted(glob.glob(os.path.join(profile_dir, "window_*"))):
        if not os.path.isdir(wdir):
            continue
        rec: Dict[str, Any] = {"dir": wdir, "steps": 0,
                               "step_seconds": 0.0, "step_times": [],
                               "on_demand": False, "index": len(out)}
        meta = os.path.join(wdir, WINDOW_META)
        if os.path.exists(meta):
            try:
                with open(meta) as f:
                    rec.update(json.load(f))
            except (OSError, ValueError):
                pass
        rec["dir"] = wdir
        out.append(rec)
    return out

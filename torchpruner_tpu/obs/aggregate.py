"""Cross-host metric aggregation via per-process shard files.

The obs session gates FILE exporters on ``process_index == 0``, which
used to mean every non-zero process's counters/gauges simply vanished —
a pod run reported 1/N of its examples and none of the other hosts' HBM
pressure.  The fix is filesystem-mediated (no collective, no network
dependency at teardown, kill-safe): every process with an ``obs_dir``
writes its registry as ``metrics.shard<i>.json`` at close, and process 0
merges whatever shards are present before exporting ``metrics.prom`` /
``report.json``.

Merge semantics (per metric name):

- **counters** — summed (work is partitioned, totals add).
- **gauges** — merged value is the MAX across shards (worst-case
  semantics: HBM high-water, grad norm); when shards disagree a
  companion ``<name>_min`` gauge carries the MIN, so the spread is
  visible without a per-host series explosion.
- **histograms** — bucket-wise count sum + sum/count/min/max combine
  (all sessions share the same bucket boundaries; a shard with foreign
  buckets is kept un-merged under its own name suffix rather than
  silently mis-binned).

Shard files are atomic (tmp + replace) and carry the writing process's
index, so a straggler re-writing its shard after the merge only affects
the NEXT export, never tears the current one.
"""

from __future__ import annotations

import glob
import json
import os
import re
from typing import Any, Dict, List, Optional

from torchpruner_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

SHARD_PATTERN = "metrics.shard*.json"


def shard_path(obs_dir: str, process_index: int) -> str:
    return os.path.join(obs_dir, f"metrics.shard{process_index}.json")


def registry_to_shard(registry: MetricsRegistry,
                      process_index: int) -> Dict[str, Any]:
    counters: Dict[str, Any] = {}
    gauges: Dict[str, Any] = {}
    hists: Dict[str, Any] = {}
    for m in registry:
        if isinstance(m, Counter):
            counters[m.name] = {"value": m.value, "help": m.help}
        elif isinstance(m, Gauge):
            if m.value is not None:
                gauges[m.name] = {"value": m.value, "help": m.help}
        elif isinstance(m, Histogram):
            hists[m.name] = {
                "help": m.help,
                "buckets": list(m.buckets),
                "counts": list(m.counts),
                "sum": m.sum,
                "count": m.count,
                "min": (None if m.count == 0 else m.min),
                "max": (None if m.count == 0 else m.max),
            }
    return {
        "process_index": int(process_index),
        "counters": counters,
        "gauges": gauges,
        "histograms": hists,
    }


def write_shard(registry: MetricsRegistry, obs_dir: str,
                process_index: int) -> str:
    """Atomic durable per-process shard write (the shared tmp + fsync +
    replace helper); returns the path."""
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    path = shard_path(obs_dir, process_index)
    atomic_write_json(path, registry_to_shard(registry, process_index),
                      indent=None)
    return path


#: how long the emitter waits at close for peer processes' shards
#: (seconds; every process closes at the same program point, so the
#: peers' writes are normally milliseconds behind — the cap only
#: matters when a peer died)
SHARD_WAIT_ENV = "TORCHPRUNER_OBS_SHARD_WAIT_S"


def wait_for_peer_shards(obs_dir: str, process_index: int,
                         timeout_s: Optional[float] = None) -> bool:
    """Bounded wait for every OTHER process's shard file before the
    emitter merges — without it a multi-host close would usually merge
    before the workers' writes land and export host 0's metrics only
    (the exact symptom the shards exist to fix).  Returns True when all
    peers' shards are present; merging proceeds either way (a crashed
    peer must not block the export forever)."""
    import time

    try:
        import jax

        n = jax.process_count()
    except Exception:
        n = 1
    if n <= 1:
        return True
    if timeout_s is None:
        try:
            timeout_s = float(os.environ.get(SHARD_WAIT_ENV, "15") or 15)
        except ValueError:
            timeout_s = 15.0
    peers = [shard_path(obs_dir, i) for i in range(n)
             if i != process_index]
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if all(os.path.exists(p) for p in peers):
            return True
        time.sleep(0.05)
    return all(os.path.exists(p) for p in peers)


def clear_stale_shards(obs_dir: str) -> None:
    """Delete shard files left by a PREVIOUS session of this obs dir —
    called by the emitter at session INIT (shards are only written at
    close, so anything present when a new session opens is stale; a
    dead run's shard from a larger process count would otherwise be
    merged into the new run's export, double-counting)."""
    for path in glob.glob(os.path.join(obs_dir, SHARD_PATTERN)):
        try:
            os.unlink(path)
        except OSError:
            pass


def load_shards(obs_dir: str) -> List[Dict[str, Any]]:
    """Every parseable shard in ``obs_dir``, ordered by process index.
    Unreadable/torn shards are skipped (merging must never fail the
    export)."""
    shards = []
    for path in sorted(glob.glob(os.path.join(obs_dir, SHARD_PATTERN))):
        if not re.search(r"metrics\.shard\d+\.json$", path):
            continue
        try:
            with open(path) as f:
                shard = json.load(f)
        except Exception:
            continue
        if isinstance(shard, dict):
            shards.append(shard)
    shards.sort(key=lambda s: s.get("process_index", 0))
    return shards


def merge_shards(shards: List[Dict[str, Any]]) -> MetricsRegistry:
    """The merged registry (see module docstring for per-type rules)."""
    reg = MetricsRegistry()
    gauge_seen: Dict[str, List[float]] = {}
    for shard in shards:
        for name, c in shard.get("counters", {}).items():
            reg.counter(name, c.get("help", "")).inc(float(c.get("value", 0)))
        for name, g in shard.get("gauges", {}).items():
            v = g.get("value")
            if v is None:
                continue
            gauge_seen.setdefault(name, []).append(float(v))
            cur = reg.gauge(name, g.get("help", ""))
            if cur.value is None or _max_nan_safe(float(v), cur.value):
                cur.set(v)
        for name, h in shard.get("histograms", {}).items():
            buckets = tuple(h.get("buckets", ()))
            cur = reg.get(name)
            if isinstance(cur, Histogram) and cur.buckets != buckets:
                # foreign bucket layout: keep it separate, never mis-bin
                name = f"{name}_p{shard.get('process_index', 0)}"
                cur = None
            hist = reg.histogram(name, h.get("help", ""), buckets=buckets)
            counts = h.get("counts", [])
            if len(counts) == len(hist.counts):
                hist.counts = [a + int(b)
                               for a, b in zip(hist.counts, counts)]
            hist.sum += float(h.get("sum", 0.0))
            hist.count += int(h.get("count", 0))
            if h.get("min") is not None:
                hist.min = min(hist.min, float(h["min"]))
            if h.get("max") is not None:
                hist.max = max(hist.max, float(h["max"]))
    # gauge spread: a companion _min where shards actually disagree
    for name, vals in gauge_seen.items():
        if len(vals) > 1 and min(vals) != max(vals):
            reg.gauge(name + "_min",
                      "min across process shards (max is the primary "
                      "series)").set(min(vals))
    return reg


def _max_nan_safe(new: float, cur: float) -> bool:
    """True when ``new`` should replace ``cur`` under max-merge (a NaN
    never beats a real value; a real value always beats NaN)."""
    import math

    if math.isnan(new):
        return False
    if math.isnan(cur):
        return True
    return new > cur


def merged_registry(obs_dir: str,
                    local: Optional[MetricsRegistry] = None,
                    process_index: int = 0) -> MetricsRegistry:
    """The export-time entry point: merge every shard in ``obs_dir``;
    when no shard for ``process_index`` is on disk yet, ``local`` stands
    in for it (the common single-host case where close() merges before
    any other process existed)."""
    shards = load_shards(obs_dir)
    if local is not None and not any(
            s.get("process_index") == process_index for s in shards):
        shards.append(registry_to_shard(local, process_index))
        shards.sort(key=lambda s: s.get("process_index", 0))
    return merge_shards(shards)

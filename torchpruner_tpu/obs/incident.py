"""Automated root-cause correlation: triggers → ranked incidents.

The telemetry plane measures everything (windowed time-series, burn-rate
alerts, request traces, a ledger of every consequential action) but the
join — "the SLO burned at t; what *changed*?" — was a human's job.  This
module automates it.  On any trigger:

- a **burn alert** from ``serve/slo.py`` (routed through the
  ``obs.record_serve`` hook, so serve AND fleet frontends get it for
  free whenever ``--obs-dir`` is set),
- an **anomaly open** from ``obs.anomaly`` (which also covers the
  fleet deadline/shed **counter spikes** — those are watchlist rate
  signals),

the correlator assembles an ``incident`` ledger record: the triggering
window span, every candidate-cause ledger event inside a ±lookback
horizon (swap, scale decision, rung climb, preemption, chaos injection,
checkpoint restore), per-replica gauge deltas from the router scrape
history (the ``fleet_replica_*`` gauges riding the router's windows),
the slowest-K reqtrace exemplars, and the affected tenants.  Each
candidate is **ranked** by a deterministic score::

    score = temporal_proximity × event_class_prior × replica_match

so the top suspect is an auditable claim — the three factors are in the
record, reproducible from the same artifacts.  Triggers landing within
the lookback of an existing incident are ABSORBED into it (one fault,
one incident — not one per symptom).

Offline, :func:`assemble_run_incidents` rebuilds the same incidents
from a run dir's artifacts alone (ledger + time-series + reqtrace
record) — the ``python -m torchpruner_tpu obs incident DIR`` path,
which works on a kill -9'd run because every input flushes per line.
Fleet dirs (``metrics_ts_fleet.jsonl`` present) route through
``fleet.report.assemble_fleet_incidents`` so the assembly happens on
the router clock.

Tuning: ``TORCHPRUNER_INCIDENT_LOOKBACK_S`` (default 120 s — matched to
the slow burn window, so a fault old enough to still be burning the
slow budget is still in the horizon).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

#: ± candidate horizon around a trigger; also the absorb window.
#: Matches serve/slo.py's SLOW_WINDOW_S: a cause old enough to have
#: aged out of the slow burn window has aged out of suspicion too.
LOOKBACK_S = 120.0
LOOKBACK_ENV = "TORCHPRUNER_INCIDENT_LOOKBACK_S"

#: ledger kinds that OPEN incidents when they ride obs.record_serve
TRIGGER_KINDS = ("slo_burn",)

#: event-class priors: how plausible a cause this class of event is,
#: before looking at timing or placement.  A planted fault (chaos) is
#: the strongest claim; a burn alert is usually the symptom, not the
#: cause, so it ranks last.
EVENT_PRIORS = {
    "chaos_injection": 1.0,
    "hot_swap": 0.9,
    "scale_decision": 0.8,
    "preemption": 0.7,
    "restore": 0.65,
    "checkpoint_restore": 0.65,
    "anomaly": 0.5,
    "slo_breach": 0.35,
    "slo_burn": 0.3,
}
DEFAULT_PRIOR = 0.4

#: ledger records that are never causes (summaries / render payloads /
#: training-loop records)
_EXCLUDE_EVENTS = frozenset((
    "incident", "reqtrace", "round", "epoch", "sweep", "score",
    "prune", "trial", "frontier", "plan", "clock_offset",
))
_EXCLUDE_KINDS = frozenset(("fleet_drill", "scenario_drill", "summary"))

#: suspect-detail fields worth carrying into the evidence line
_EVIDENCE_FIELDS = ("action", "rung", "metric", "checkpoint",
                    "slow_steps_ms", "slow_replica_ms", "chaos",
                    "at_dispatch", "burn_fast", "burn_slow", "reason",
                    "correlation_id", "step")


def default_lookback_s() -> float:
    try:
        return float(os.environ.get(LOOKBACK_ENV, "") or LOOKBACK_S)
    except ValueError:
        return LOOKBACK_S


def classify(rec: Dict[str, Any]) -> str:
    """Event class of a ledger record for the prior table."""
    if rec.get("event") == "serve":
        return str(rec.get("kind") or "serve")
    return str(rec.get("event") or "unknown")


def replica_of(rec: Dict[str, Any]) -> Optional[str]:
    for key in ("replica", "name", "proc"):
        v = rec.get(key)
        if isinstance(v, str) and v:
            return v
    return None


def replica_hint(metric: str) -> Optional[str]:
    """``fleet_replica_<name>_<gauge>`` → ``<name>`` (the router's
    sanitized per-replica gauge naming) — lets an anomaly on a scraped
    gauge carry a replica for the match factor."""
    prefix = "fleet_replica_"
    if not metric.startswith(prefix):
        return None
    tail = metric[len(prefix):]
    for suffix in ("_state_code", "_scrape_rtt_s", "_occupancy",
                   "_queue_depth"):
        if tail.endswith(suffix):
            return tail[:-len(suffix)] or None
    return None


def score_candidate(rec: Dict[str, Any], trigger_ts: float,
                    trigger_replica: Optional[str],
                    lookback_s: float) -> Optional[Tuple[float, dict]]:
    """``None`` outside the horizon, else ``(score, factors)`` — the
    factors ride the suspect record so the rank is auditable."""
    ts = rec.get("ts")
    if ts is None:
        return None
    dt = float(ts) - trigger_ts
    if abs(dt) > lookback_s:
        return None
    proximity = max(0.05, 1.0 - abs(dt) / lookback_s)
    prior = EVENT_PRIORS.get(classify(rec), DEFAULT_PRIOR)
    rep = replica_of(rec)
    if trigger_replica and rep:
        match = 1.0 if rep == trigger_replica else 0.25
    else:
        match = 0.5
    score = proximity * prior * match
    return round(score, 6), {"proximity": round(proximity, 4),
                             "prior": prior, "replica_match": match,
                             "dt_s": round(dt, 3)}


def _evidence_line(rec: Dict[str, Any], cls: str, dt: float) -> str:
    rep = replica_of(rec) or "fleet"
    bits = []
    for f in _EVIDENCE_FIELDS:
        v = rec.get(f)
        if v is not None and not isinstance(v, (dict, list)):
            s = str(v)
            bits.append(f"{f}={s[:48]}")
    detail = (": " + ", ".join(bits)) if bits else ""
    return f"{cls} on {rep} at {dt:+.1f}s{detail}"


def _is_trigger_echo(rec: Dict[str, Any], trigger: Dict[str, Any]) -> bool:
    """The trigger's own ledger record (and its fleet re-record) must
    not rank as its own cause."""
    if classify(rec) != trigger.get("kind"):
        return False
    if trigger.get("replica") and replica_of(rec) \
            and replica_of(rec) != trigger["replica"]:
        return False
    ts, tts = rec.get("ts"), trigger.get("ts")
    # re-records are stamped later (drill epilogue); match on the
    # carried-over original timestamp too
    for cand in (ts, rec.get("burn_ts")):
        if cand is not None and tts is not None \
                and abs(float(cand) - float(tts)) <= 2.0:
            return True
    return classify(rec) == "slo_burn" and trigger.get("kind") == "slo_burn"


def rank_suspects(records: List[dict], trigger: Dict[str, Any],
                  lookback_s: float, cap: int = 12) -> List[dict]:
    """Every candidate-cause ledger event in the horizon, scored and
    ranked — deterministic (ties broken by time then class)."""
    trigger_ts = float(trigger.get("ts") or 0.0)
    trigger_replica = trigger.get("replica")
    out: List[dict] = []
    for rec in records:
        if rec.get("event") in _EXCLUDE_EVENTS \
                or rec.get("kind") in _EXCLUDE_KINDS:
            continue
        if _is_trigger_echo(rec, trigger):
            continue
        scored = score_candidate(rec, trigger_ts, trigger_replica,
                                 lookback_s)
        if scored is None:
            continue
        score, factors = scored
        cls = classify(rec)
        out.append({
            "score": score,
            "class": cls,
            "replica": replica_of(rec),
            "ts": round(float(rec["ts"]), 6),
            "factors": factors,
            "evidence": _evidence_line(rec, cls, factors["dt_s"]),
        })
    out.sort(key=lambda s: (-s["score"], s["ts"], s["class"]))
    for i, s in enumerate(out[:cap]):
        s["rank"] = i + 1
    return out[:cap]


def gauge_deltas(history: List[Tuple[float, Dict[str, float]]],
                 trigger_ts: float, lookback_s: float,
                 prefixes: Tuple[str, ...] = ("fleet_replica_",),
                 cap: int = 16) -> Dict[str, dict]:
    """Per-replica gauge deltas from the scrape history: median of each
    ``fleet_replica_*`` gauge before vs after the trigger, largest
    relative movers first."""
    before: Dict[str, List[float]] = {}
    after: Dict[str, List[float]] = {}
    for ts, gauges in history:
        if not (trigger_ts - lookback_s <= ts <= trigger_ts + lookback_s):
            continue
        dst = before if ts < trigger_ts else after
        for name, v in gauges.items():
            if name.startswith(prefixes):
                dst.setdefault(name, []).append(float(v))

    def med(xs: List[float]) -> float:
        xs = sorted(xs)
        n = len(xs)
        return xs[n // 2] if n % 2 else 0.5 * (xs[n // 2 - 1]
                                               + xs[n // 2])

    out: Dict[str, dict] = {}
    for name in before:
        if name not in after:
            continue
        b, a = med(before[name]), med(after[name])
        delta = a - b
        if abs(delta) <= max(1e-9, 0.02 * abs(b)):
            continue
        out[name] = {"before": round(b, 6), "after": round(a, 6),
                     "delta": round(delta, 6)}
    ranked = sorted(out.items(),
                    key=lambda kv: -abs(kv[1]["delta"])
                    / max(1e-9, abs(kv[1]["before"])))
    return dict(ranked[:cap])


def affected_tenants(metrics: Dict[str, Any]) -> List[str]:
    """Tenants with sheds / deadline expiries / preemptions in the
    per-tenant breakdown gauges (``tenant_<name>_<field>``)."""
    from torchpruner_tpu.obs.report import _tenant_table

    out = []
    for name, row in _tenant_table(metrics):
        if (row.get("shed_fleet") or row.get("shed_total")
                or row.get("deadline_exceeded_fleet")
                or row.get("preempted_total")):
            out.append(name)
    return out


def assemble_incident(trigger: Dict[str, Any], records: List[dict], *,
                      incident_id: str,
                      lookback_s: Optional[float] = None,
                      gauge_history: Optional[List[Tuple[float, dict]]]
                      = None,
                      exemplars: Optional[List[dict]] = None,
                      tenants: Optional[List[str]] = None,
                      anomalies: Optional[List[str]] = None
                      ) -> Dict[str, Any]:
    """One trigger + the run's evidence → one incident record (the
    ledger schema ``obs incident`` / ``obs report`` render)."""
    if lookback_s is None:
        lookback_s = default_lookback_s()
    ts = float(trigger.get("ts") or 0.0)
    suspects = rank_suspects(records, trigger, lookback_s)
    inc: Dict[str, Any] = {
        "event": "incident",
        "incident_id": incident_id,
        "ts": round(ts, 6),
        "kind": trigger.get("kind"),
        "trigger": {k: v for k, v in trigger.items()
                    if not isinstance(v, (dict, list))},
        "span": {"t0": round(ts - lookback_s, 6),
                 "t1": round(ts + lookback_s, 6)},
        "lookback_s": lookback_s,
        "suspects": suspects,
        "triggers_absorbed": 0,
    }
    if suspects:
        top = suspects[0]
        inc["top_suspect"] = {"class": top["class"],
                              "replica": top["replica"],
                              "score": top["score"]}
    if gauge_history:
        deltas = gauge_deltas(gauge_history, ts, lookback_s)
        if deltas:
            inc["gauge_deltas"] = deltas
    if exemplars:
        inc["exemplars"] = exemplars[:4]
    if tenants:
        inc["tenants"] = tenants
    if anomalies:
        inc["anomalies"] = anomalies
    return inc


class IncidentCorrelator:
    """The online half: owned by ``ObsSession``, fed by the
    ``record_serve`` hook (burn alerts) and the anomaly detector's
    ``on_open``.  All mutable state under ``self._lock``; evidence
    reads (ledger records, detector history, registry snapshot) happen
    outside it."""

    def __init__(self, *, ledger=None, registry=None, detector=None,
                 lookback_s: Optional[float] = None,
                 proc: Optional[str] = None):
        self.ledger = ledger
        self.registry = registry
        self.detector = detector
        self.lookback_s = (default_lookback_s() if lookback_s is None
                           else float(lookback_s))
        self.proc = proc
        self._lock = threading.Lock()
        self.incidents: List[dict] = []
        self._seq = 0

    def trigger(self, *, kind: str, ts: Optional[float] = None,
                metric: Optional[str] = None,
                replica: Optional[str] = None,
                anomaly_id: Optional[str] = None,
                **detail) -> Optional[dict]:
        """Open (or absorb into) an incident.  Returns the new incident
        record, or ``None`` when the trigger was absorbed."""
        ts = float(ts) if ts is not None else time.time()
        with self._lock:
            last = self.incidents[-1] if self.incidents else None
            if last is not None \
                    and abs(ts - last["ts"]) <= self.lookback_s:
                last["triggers_absorbed"] += 1
                if anomaly_id:
                    last.setdefault("anomalies", [])
                    if anomaly_id not in last["anomalies"]:
                        last["anomalies"].append(anomaly_id)
                return None
            self._seq += 1
            iid = "inc-%s%d" % ((self.proc + "-") if self.proc else "",
                                self._seq)
        trig = {"kind": kind, "ts": ts, "metric": metric,
                "replica": replica,
                **{k: v for k, v in detail.items()
                   if not isinstance(v, (dict, list))}}
        if replica is None and metric:
            trig["replica"] = replica_hint(metric)
        records = []
        if self.ledger is not None:
            try:
                records = list(self.ledger.records())
            except Exception:
                records = []
        gauge_history = None
        anomalies = None
        if self.detector is not None:
            gauge_history = self.detector.gauges_between(
                ts - self.lookback_s, ts + self.lookback_s)
            anomalies = [a["anomaly_id"] for a in self.detector.anomalies
                         if abs((a.get("opened_ts") or 0.0) - ts)
                         <= self.lookback_s]
            if anomaly_id and anomaly_id not in (anomalies or []):
                (anomalies or []).append(anomaly_id)
        exemplars = None
        for rec in reversed(records):
            if rec.get("event") == "reqtrace" and rec.get("exemplars"):
                exemplars = rec["exemplars"]
                break
        tenants = None
        if self.registry is not None:
            try:
                tenants = affected_tenants(self.registry.snapshot()) \
                    or None
            except Exception:
                tenants = None
        inc = assemble_incident(
            trig, records, incident_id=iid, lookback_s=self.lookback_s,
            gauge_history=gauge_history, exemplars=exemplars,
            tenants=tenants, anomalies=anomalies)
        with self._lock:
            self.incidents.append(inc)
        if self.ledger is not None:
            try:
                self.ledger.record(inc)
            except Exception:
                pass
        return inc

    def active_id(self, now: Optional[float] = None) -> Optional[str]:
        """The correlation id a scale decision should carry: the
        incident still inside its lookback, else the oldest still-open
        anomaly, else ``None``."""
        now = time.time() if now is None else now
        with self._lock:
            if self.incidents \
                    and now - self.incidents[-1]["ts"] <= self.lookback_s:
                return self.incidents[-1]["incident_id"]
        if self.detector is not None:
            opens = self.detector.open_anomalies()
            if opens:
                return opens[0].get("anomaly_id")
        return None

    def finalize(self, registry) -> None:
        """Close-time gauges (before the shard ships): incident /
        anomaly counts ride ``obs diff`` via the ``incident_*`` /
        ``anomaly_*`` dynamic prefixes — always set, so the clean-run
        false-positive gate compares 0 against 0 instead of skipping."""
        with self._lock:
            incidents = list(self.incidents)
        registry.gauge("incident_count",
                       help="incidents opened by the correlator "
                            "(absorbed triggers excluded)"
                       ).set(float(len(incidents)))
        top = max((i.get("top_suspect", {}).get("score") or 0.0
                   for i in incidents), default=0.0)
        registry.gauge("incident_top_suspect_score",
                       help="best suspect score over all incidents "
                            "(0 = none)").set(round(top, 6))
        absorbed = sum(i.get("triggers_absorbed", 0) for i in incidents)
        registry.gauge("incident_absorbed_triggers",
                       help="triggers folded into an existing incident "
                            "instead of opening a new one"
                       ).set(float(absorbed))
        if self.detector is not None:
            c = self.detector.counts()
            registry.gauge("anomaly_count",
                           help="anomalies opened by the changepoint "
                                "detector").set(float(c["opened"]))
            registry.gauge("anomaly_open_count",
                           help="anomalies still open at session close"
                           ).set(float(c["open"]))


# -- offline -----------------------------------------------------------------


def correlate(triggers: List[dict], records: List[dict], *,
              lookback_s: Optional[float] = None,
              gauge_history: Optional[List[Tuple[float, dict]]] = None,
              exemplars: Optional[List[dict]] = None,
              tenants: Optional[List[str]] = None,
              id_prefix: str = "") -> List[dict]:
    """The offline coalescing loop: time-sorted triggers folded into
    incidents exactly like the online correlator would."""
    if lookback_s is None:
        lookback_s = default_lookback_s()
    incidents: List[dict] = []
    for trig in sorted(triggers, key=lambda t: t.get("ts") or 0.0):
        ts = float(trig.get("ts") or 0.0)
        if incidents and abs(ts - incidents[-1]["ts"]) <= lookback_s:
            incidents[-1]["triggers_absorbed"] += 1
            aid = trig.get("anomaly_id")
            if aid:
                incidents[-1].setdefault("anomalies", [])
                if aid not in incidents[-1]["anomalies"]:
                    incidents[-1]["anomalies"].append(aid)
            continue
        iid = f"inc-{id_prefix}{len(incidents) + 1}"
        incidents.append(assemble_incident(
            trig, records, incident_id=iid, lookback_s=lookback_s,
            gauge_history=gauge_history, exemplars=exemplars,
            tenants=tenants,
            anomalies=[trig["anomaly_id"]]
            if trig.get("anomaly_id") else None))
    return incidents


def triggers_of(records: List[dict],
                anomalies: List[dict]) -> List[dict]:
    """Trigger dicts from a run's artifacts: ledgered burn alerts plus
    (offline-detected) anomaly opens."""
    out: List[dict] = []
    for rec in records:
        if rec.get("event") == "serve" and rec.get("kind") == "slo_burn":
            out.append({
                "kind": "slo_burn",
                # re-records carry the original burn time as burn_ts
                "ts": rec.get("burn_ts") or rec.get("ts"),
                "metric": rec.get("metric"),
                "replica": replica_of(rec),
                "burn_fast": rec.get("burn_fast"),
                "burn_slow": rec.get("burn_slow"),
            })
    for a in anomalies:
        out.append({
            "kind": "anomaly",
            "ts": a.get("opened_ts"),
            "metric": a.get("metric"),
            "replica": a.get("proc") if str(a.get("proc") or ""
                                           ).startswith("replica")
            else replica_hint(a.get("metric") or ""),
            "anomaly_id": a.get("anomaly_id"),
            "z": a.get("z"),
        })
    return [t for t in out if t.get("ts") is not None]


def assemble_run_incidents(run_dir: str,
                           lookback_s: Optional[float] = None
                           ) -> Dict[str, Any]:
    """Offline reconstruction for a SINGLE-process run dir (fleet dirs
    route through ``fleet.report.assemble_fleet_incidents``): re-derive
    triggers from the ledger + time-series and correlate.  Returns
    ``{"incidents", "anomalies", "burns", "records"}``."""
    from torchpruner_tpu.obs.anomaly import detect_anomalies
    from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger
    from torchpruner_tpu.obs.timeseries import load_series

    path = os.path.join(run_dir, LEDGER_FILENAME)
    records = load_ledger(path) if os.path.exists(path) else []
    try:
        anomalies = detect_anomalies(run_dir)
    except Exception:
        anomalies = []
    try:
        _, windows = load_series(run_dir)
    except Exception:
        windows = []
    gauge_history = [(w.get("ts") or 0.0, w["gauges"])
                     for w in windows if w.get("gauges")]
    exemplars = None
    for rec in reversed(records):
        if rec.get("event") == "reqtrace" and rec.get("exemplars"):
            exemplars = rec["exemplars"]
            break
    tenants = affected_tenants(windows[-1]["gauges"]) \
        if windows and windows[-1].get("gauges") else []
    burns = [r for r in records
             if r.get("event") == "serve" and r.get("kind") == "slo_burn"]
    incidents = correlate(
        triggers_of(records, anomalies), records,
        lookback_s=lookback_s, gauge_history=gauge_history,
        exemplars=exemplars, tenants=tenants or None)
    return {"incidents": incidents, "anomalies": anomalies,
            "burns": burns, "records": records}


# -- postmortem rendering ----------------------------------------------------


_SPARK = "▁▂▃▄▅▆▇█"


def sparkline(values: List[float]) -> str:
    if not values:
        return ""
    lo, hi = min(values), max(values)
    if hi - lo < 1e-12:
        return _SPARK[0] * len(values)
    return "".join(_SPARK[min(7, int(8 * (v - lo) / (hi - lo)))]
                   for v in values)


def signal_series(windows: List[dict], metric: str,
                  proc: Optional[str] = None,
                  cap: int = 64) -> List[Tuple[float, float]]:
    """``(ts, value)`` series for one detector signal name from raw
    windows (``<hist>_p99`` / ``<counter>_rate`` / gauge name)."""
    from torchpruner_tpu.obs.timeseries import _quantile_from_buckets

    out: List[Tuple[float, float]] = []
    for w in windows:
        if proc is not None and (w.get("proc") or "router") != proc:
            continue
        ts = w.get("ts") or 0.0
        v: Optional[float] = None
        if metric.endswith("_p99"):
            h = (w.get("hist") or {}).get(metric[:-len("_p99")])
            if h and "le" in h:
                v = _quantile_from_buckets(h["le"], h.get("c") or [],
                                           0.99)
        elif metric.endswith("_rate"):
            c = (w.get("counters") or {}).get(metric[:-len("_rate")])
            dur = w.get("dur_s") or 0.0
            if c is not None and dur > 0:
                v = c / dur
        else:
            g = (w.get("gauges") or {}).get(metric)
            if g is not None:
                v = float(g)
        if v is not None:
            out.append((ts, v))
    return out[-cap:]


#: SLO metric key → the window signal that plots it
_SLO_SIGNALS = {"token": "serve_token_seconds_p99",
                "ttft": "serve_ttft_seconds_p99"}


def format_postmortem(incidents: List[dict], *,
                      anomalies: Optional[List[dict]] = None,
                      windows: Optional[List[dict]] = None,
                      title: str = "run",
                      reconstructed: bool = False) -> str:
    """The ``obs incident`` markdown: per incident — trigger, timeline,
    ranked suspects with evidence lines, gauge deltas, anomaly plot
    data, affected tenants, exemplars."""
    lines = [f"# obs incident — {title}", ""]
    lines.append(f"{len(incidents)} incident(s), "
                 f"{len(anomalies or [])} anomal(y/ies)"
                 + (" (reconstructed offline from artifacts)"
                    if reconstructed else ""))
    lines.append("")
    if not incidents:
        lines.append("(no incidents — no burn alert fired and no "
                     "anomaly opened)")
        return "\n".join(lines)
    for inc in incidents:
        trig = inc.get("trigger") or {}
        head = f"## {inc.get('incident_id')} — {inc.get('kind')}"
        if trig.get("metric"):
            head += f" ({trig['metric']})"
        if trig.get("replica"):
            head += f" on {trig['replica']}"
        lines.append(head)
        lines.append("")
        span = inc.get("span") or {}
        lines.append(
            f"- trigger at ts {inc.get('ts')}, window span "
            f"[{span.get('t0')}, {span.get('t1')}] "
            f"(lookback ±{inc.get('lookback_s')}s), "
            f"{inc.get('triggers_absorbed', 0)} trigger(s) absorbed")
        if trig.get("burn_fast") is not None:
            lines.append(f"- burn rates at trigger: fast "
                         f"{trig['burn_fast']}x, slow "
                         f"{trig.get('burn_slow')}x")
        if inc.get("tenants"):
            lines.append("- affected tenants: "
                         + ", ".join(inc["tenants"]))
        if inc.get("anomalies"):
            lines.append("- correlated anomalies: "
                         + ", ".join(inc["anomalies"]))
        lines.append("")
        suspects = inc.get("suspects") or []
        if suspects:
            lines.append("| rank | score | class | replica | Δt s "
                         "| evidence |")
            lines.append("|---|---|---|---|---|---|")
            for s in suspects:
                lines.append(
                    f"| {s.get('rank')} | {s.get('score'):.4f} "
                    f"| {s.get('class')} | {s.get('replica') or ''} "
                    f"| {s['factors'].get('dt_s'):+.1f} "
                    f"| {s.get('evidence')} |")
            lines.append("")
        else:
            lines.append("(no candidate causes in the horizon — "
                         "unexplained)")
            lines.append("")
        deltas = inc.get("gauge_deltas") or {}
        if deltas:
            lines.append("gauge deltas (router scrape history, median "
                         "before → after trigger):")
            for name, d in deltas.items():
                lines.append(f"- {name}: {d['before']} → {d['after']} "
                             f"(Δ{d['delta']:+g})")
            lines.append("")
        # anomaly plot data: the triggering signal's window series
        metric = trig.get("metric")
        signal = _SLO_SIGNALS.get(metric or "", metric)
        if windows and signal:
            series = signal_series(windows, signal,
                                   proc=trig.get("replica"))
            if not series:
                series = signal_series(windows, signal)
            if len(series) >= 2:
                vals = [v for _, v in series]
                lines.append(
                    f"plot {signal}"
                    + (f" ({trig['replica']})" if trig.get("replica")
                       else "")
                    + f": {sparkline(vals)} "
                    f"[min {min(vals):.4g}, max {max(vals):.4g}, "
                    f"{len(vals)} windows]")
                lines.append("")
        exemplars = inc.get("exemplars") or []
        if exemplars:
            lines.append("slowest exemplars overlapping the window:")
            for ex in exemplars:
                lines.append(
                    f"- `{ex.get('trace')}` e2e {ex.get('e2e_ms')} ms, "
                    f"ttft {ex.get('ttft_ms')} ms, "
                    f"{ex.get('attempts', 0)} attempt(s)"
                    + (" [redriven]" if ex.get("redrive") else ""))
            lines.append("")
    return "\n".join(lines)


# -- CLI ---------------------------------------------------------------------


def incident_main(args) -> int:
    """``obs incident DIR``: render the run's incidents — ledgered ones
    when the session closed cleanly, reconstructed from artifacts
    otherwise.  Exit 1 on an unexplained burn (a burn alert with no
    incident covering it)."""
    from torchpruner_tpu.obs.timeseries import TS_FLEET_FILENAME

    run_dir = args.dir
    lookback = args.lookback if args.lookback > 0 else None
    fleet = os.path.exists(os.path.join(run_dir, TS_FLEET_FILENAME))
    if fleet:
        from torchpruner_tpu.fleet.report import (
            assemble_fleet_incidents,
        )

        out = assemble_fleet_incidents(run_dir, lookback_s=lookback)
    else:
        out = assemble_run_incidents(run_dir, lookback_s=lookback)

    ledgered = [r for r in out["records"]
                if r.get("event") == "incident"]
    reconstructed = not ledgered
    incidents = ledgered or out["incidents"]
    try:
        from torchpruner_tpu.obs.timeseries import load_series

        _, windows = load_series(
            os.path.join(run_dir, TS_FLEET_FILENAME) if fleet
            else run_dir)
    except Exception:
        windows = []
    if args.json:
        print(json.dumps({"incidents": incidents,
                          "anomalies": out["anomalies"],
                          "reconstructed": reconstructed}))
    else:
        print(format_postmortem(
            incidents, anomalies=out["anomalies"], windows=windows,
            title=run_dir, reconstructed=reconstructed))
    # the unexplained-burn contract: every burn alert must fall inside
    # some incident's span
    unexplained = 0
    for b in out["burns"]:
        bts = b.get("burn_ts") or b.get("ts")
        if bts is None:
            continue
        if not any((i.get("span") or {}).get("t0", 1e99) <= bts
                   <= (i.get("span") or {}).get("t1", -1e99)
                   for i in incidents):
            unexplained += 1
    if unexplained:
        print(f"UNEXPLAINED BURN: {unexplained} burn alert(s) outside "
              "every incident window", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    p = argparse.ArgumentParser(prog="torchpruner_tpu obs incident")
    p.add_argument("dir")
    p.add_argument("--lookback", type=float, default=0.0)
    p.add_argument("--json", action="store_true")
    sys.exit(incident_main(p.parse_args()))

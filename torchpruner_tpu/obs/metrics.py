"""Counters / gauges / histograms and low-overhead step telemetry.

The registry is deliberately tiny — names are flat strings (Prometheus
conventions: ``_total`` counters, ``_seconds`` durations, base-unit
gauges), values are floats, and the per-step hot path does no I/O, no
locking beyond a plain attribute store, and no derived math.  Everything
expensive (examples/s, tokens/s, MFU) is computed once at export time
from the accumulated sums, so instrumenting a millisecond-scale compiled
step costs microseconds (asserted in tests/test_obs.py's timing guard).
"""

from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

#: default histogram buckets for step wall time (seconds) — log-spaced
#: from 100 µs (tiny CPU smoke steps) to 100 s (cold pod-scale steps)
STEP_TIME_BUCKETS = tuple(
    round(10.0 ** (e / 2.0), 6) for e in range(-8, 5)
)


class Counter:
    """Monotonic float counter."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name, self.help, self.value = name, help, 0.0

    def inc(self, n: float = 1.0):
        self.value += n


class Gauge:
    """Last-write-wins value."""

    __slots__ = ("name", "help", "value")

    def __init__(self, name: str, help: str = ""):
        self.name, self.help, self.value = name, help, None

    def set(self, v: float):
        self.value = float(v)


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus-style)."""

    __slots__ = ("name", "help", "buckets", "counts", "sum", "count",
                 "min", "max")

    def __init__(self, name: str, help: str = "",
                 buckets: Tuple[float, ...] = STEP_TIME_BUCKETS):
        self.name, self.help = name, help
        self.buckets = tuple(buckets)
        self.counts = [0] * (len(self.buckets) + 1)  # +inf tail
        self.sum = 0.0
        self.count = 0
        self.min = math.inf
        self.max = -math.inf

    def observe(self, v: float):
        self.observe_n(v, 1)

    def observe_n(self, v: float, n: int):
        """``n`` identical observations in one call (a ``multi_step``
        dispatch of K optimizer steps records K per-step times at once)."""
        self.sum += v * n
        self.count += n
        if v < self.min:
            self.min = v
        if v > self.max:
            self.max = v
        for i, b in enumerate(self.buckets):
            if v <= b:
                self.counts[i] += n
                return
        self.counts[-1] += n

    @property
    def mean(self) -> float:
        return self.sum / self.count if self.count else 0.0

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile from the bucket counts (linear
        interpolation inside the landing bucket, the standard Prometheus
        ``histogram_quantile`` estimator) — clamped to the observed
        min/max so a wide bucket cannot report a value outside what was
        actually seen.  ``None`` on an empty histogram."""
        if not self.count:
            return None
        target = q * self.count
        cum = 0
        prev_bound = min(self.min, self.buckets[0] if self.buckets else
                         self.min)
        for i, b in enumerate(self.buckets):
            c = self.counts[i]
            if cum + c >= target:
                if c:
                    lo = prev_bound if i else min(self.min, b)
                    frac = (target - cum) / c
                    v = lo + frac * (b - lo)
                else:
                    v = b
                return float(min(max(v, self.min), self.max))
            cum += c
            prev_bound = b
        # +Inf tail: everything above the last finite bound
        return float(self.max)

    def percentiles(self) -> Dict[str, Optional[float]]:
        """The p50/p95/p99 trio every exporter surfaces (snapshot,
        stderr table, Prometheus gauges)."""
        return {"p50": self.quantile(0.50), "p95": self.quantile(0.95),
                "p99": self.quantile(0.99)}


class MetricsRegistry:
    """Name → metric store; create-on-first-use accessors."""

    def __init__(self):
        self._metrics: Dict[str, object] = {}

    def counter(self, name: str, help: str = "") -> Counter:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = Counter(name, help)
        return m

    def gauge(self, name: str, help: str = "") -> Gauge:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = Gauge(name, help)
        return m

    def histogram(self, name: str, help: str = "",
                  buckets: Tuple[float, ...] = STEP_TIME_BUCKETS
                  ) -> Histogram:
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = Histogram(name, help, buckets)
        return m

    def __iter__(self):
        return iter(self._metrics.values())

    def get(self, name: str):
        return self._metrics.get(name)

    def snapshot(self) -> Dict[str, float]:
        """Flat scalar view (histograms as ``name_sum``/``name_count``
        plus ``name_p50``/``name_p95``/``name_p99`` when non-empty)."""
        out: Dict[str, float] = {}
        for m in self:
            if isinstance(m, Histogram):
                out[m.name + "_sum"] = m.sum
                out[m.name + "_count"] = m.count
                if m.count:
                    for k, v in m.percentiles().items():
                        out[f"{m.name}_{k}"] = v
            elif m.value is not None:
                out[m.name] = m.value
        return out


# -- step telemetry ---------------------------------------------------------


def train_flops_per_step(forward_flops: float) -> float:
    """Training-step FLOPs from a *forward* FLOPs count (e.g.
    ``utils.flops.model_cost`` at the training batch size): backward ≈ 2×
    forward, so fwd+bwd ≈ 3× — the standard MFU accounting (PaLM appendix
    B; the optimizer update is O(params), negligible next to the
    matmuls)."""
    return 3.0 * forward_flops


@dataclass
class StepTelemetry:
    """Accumulates per-step wall time / examples / tokens, derives
    throughput and MFU at export.

    ``flops_per_step`` is the *training* FLOPs of one optimizer step
    (use :func:`train_flops_per_step` on a forward count);
    ``peak_flops`` the accelerator's spec-sheet peak
    (``utils.flops.peak_bf16_flops``).  Either may be absent — MFU is
    then reported as ``None`` rather than guessed.

    The per-step hot path is :meth:`on_step` — a handful of float adds
    and one histogram insert, no allocation beyond the call frame.
    """

    registry: MetricsRegistry
    flops_per_step: Optional[float] = None
    peak_flops: Optional[float] = None
    _hist: Histogram = field(default=None, repr=False)
    _steps: Counter = field(default=None, repr=False)
    _examples: Counter = field(default=None, repr=False)
    _tokens: Counter = field(default=None, repr=False)
    _flops: Counter = field(default=None, repr=False)

    def __post_init__(self):
        r = self.registry
        self._hist = r.histogram(
            "step_time_seconds", "train-step wall time (return-to-return "
            "within a stepping streak, so async device time surfaced by "
            "the caller's fence rolls into the next step's interval)")
        self._steps = r.counter("steps_total", "optimizer steps")
        self._examples = r.counter("examples_total", "training examples")
        self._tokens = r.counter("tokens_total", "training tokens (LM)")
        self._flops = r.counter(
            "model_flops_total", "model FLOPs executed by recorded steps "
            "(flops_per_step at the time each step ran)")

    def configure(self, *, flops_per_step: Optional[float] = None,
                  peak_flops: Optional[float] = None):
        if flops_per_step is not None:
            self.flops_per_step = float(flops_per_step)
        if peak_flops is not None:
            self.peak_flops = float(peak_flops)

    def on_step(self, dt_s: float, examples: int,
                tokens: Optional[int] = None, steps: int = 1):
        """``steps > 1``: one dispatch covering K optimizer steps
        (``Trainer.multi_step``) — ``dt_s`` is the whole dispatch,
        recorded as K equal per-step observations."""
        self._hist.observe_n(dt_s / steps, steps)
        self._steps.inc(steps)
        self._examples.inc(examples)
        if tokens:
            self._tokens.inc(tokens)
        if self.flops_per_step:
            # accumulate per step, not at export: flops_per_step is
            # re-aimed after every prune (the model shrinks), and the
            # final value must not retroactively reprice earlier steps
            self._flops.inc(self.flops_per_step * steps)

    def on_grad_norm(self, gnorm: float):
        self.registry.gauge(
            "grad_norm", "global gradient norm (opt-in)").set(gnorm)

    # -- derived -----------------------------------------------------------

    def derive(self) -> Dict[str, Optional[float]]:
        """Throughput/MFU from the accumulated sums.  Also writes the
        derived values back into the registry as gauges so exporters see
        them without knowing this class."""
        h = self._hist
        wall = h.sum
        pcts = h.percentiles() if h.count else {}
        out: Dict[str, Optional[float]] = {
            "steps": h.count,
            "step_time_mean_s": h.mean,
            "step_time_min_s": (h.min if h.count else None),
            "step_time_max_s": (h.max if h.count else None),
            "step_time_p50_s": pcts.get("p50"),
            "step_time_p95_s": pcts.get("p95"),
            "step_time_p99_s": pcts.get("p99"),
            "examples_per_s": (self._examples.value / wall if wall else None),
            "tokens_per_s": (
                self._tokens.value / wall
                if wall and self._tokens.value else None),
            "mfu": None,
        }
        if self._flops.value and self.peak_flops and wall:
            out["mfu"] = self._flops.value / wall / self.peak_flops
        # gauges are written unconditionally so the textfile schema is
        # stable across platforms: 0 for absent throughput, NaN for an
        # MFU whose denominators are unknown (no peak spec off-TPU)
        r = self.registry
        r.gauge("examples_per_s", "training examples per second").set(
            out["examples_per_s"] or 0.0)
        r.gauge("tokens_per_s", "training tokens per second").set(
            out["tokens_per_s"] or 0.0)
        r.gauge("mfu", "model-FLOPs utilization (achieved/peak)").set(
            out["mfu"] if out["mfu"] is not None else float("nan"))
        return out


def record_device_memory(registry: MetricsRegistry) -> Dict[str, int]:
    """Best-effort per-device live-bytes gauges (``memory_stats()`` is
    TPU/GPU-only; absent stats leave the gauges untouched).  Returns the
    bytes read, keyed ``hbm_bytes_in_use{device}``.  Alongside the
    instantaneous gauge, ``hbm_bytes_peak_device{d}`` records the
    runtime's ``peak_bytes_in_use`` high-water mark — the quantity the
    static HBM watermark prediction is compared against (the
    ``predicted_vs_measured_hbm_pct`` drift scalar; an end-of-run
    instantaneous reading has already freed the activation peak)."""
    out: Dict[str, int] = {}
    try:
        import jax

        for d in jax.local_devices():
            stats = getattr(d, "memory_stats", lambda: None)()
            if not stats:
                continue
            b = stats.get("bytes_in_use")
            if b is None:
                continue
            name = f"hbm_bytes_in_use_device{d.id}"
            registry.gauge(name, "live device bytes").set(b)
            out[name] = int(b)
            peak = stats.get("peak_bytes_in_use")
            if peak is not None:
                registry.gauge(f"hbm_bytes_peak_device{d.id}",
                               "device bytes high-water mark").set(peak)
    except Exception:
        pass
    return out

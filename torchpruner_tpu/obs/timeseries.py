"""Windowed metric time-series: the registry over *time*, on disk.

Everything in ``obs.metrics`` is cumulative — one number per run,
exported at close.  Autoscaling decisions (ROADMAP 2c), burn-rate
alerting, and warmup-vs-steady-state analysis all need the *history*:
what was the p99 in THIS 1-second window, what was the queue depth 30
seconds ago.  :class:`TimeseriesRecorder` provides it without a second
instrumentation surface: on an interval cadence it walks the existing
registry and appends one **delta snapshot** per window to
``metrics_ts.jsonl``:

- counters as per-window deltas (zero deltas omitted — idle counters
  cost nothing on disk);
- gauges as point-in-time samples;
- histograms as **bucket-count deltas** plus sum/count deltas, so a
  reader can reconstruct per-window p50/p99 with the same estimator
  the cumulative ``Histogram.quantile`` uses.  Bucket bounds (``le``)
  ship once per histogram, on first appearance.

Durability contract (the PR 16 host lint's): the stream is append-only
through ``JsonlWriter`` (open-once, flush-per-line, size-bounded
rotation to ``metrics_ts.jsonl.1`` …), so a ``kill -9`` mid-run leaves
a parseable prefix — :func:`load_series` skips a torn final line the
way ``obs.ledger.load_ledger`` does.

The hot-path cost is one clock read + compare per ``maybe_tick`` call
(the per-step hook); the actual registry walk runs once per interval
and is bounded by registry size, not step rate — the tests pin both
(<1% of a 1 Hz window per tick, like the PR 2 <100 µs/step guard).

Readers: :func:`load_series` (rotation-aware, torn-line-tolerant),
:func:`aggregate_windows` / :func:`window_quantile` (per-window or
per-segment percentiles from bucket deltas), :func:`series_summary`
(the warmup-vs-steady-state split ``obs report`` renders and bench's
serve/fleet legs report steady-state numbers from), and
:func:`format_watch` / :func:`watch` — the ``obs watch DIR`` live
terminal view.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

from torchpruner_tpu.obs.exporters import JsonlWriter
from torchpruner_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
)

TS_FILENAME = "metrics_ts.jsonl"
#: the fleet-merged stream (fleet/report.py:merge_timeseries) — every
#: process's windows on the router clock, stamped with proc/pid
TS_FLEET_FILENAME = "metrics_ts_fleet.jsonl"

#: env overrides: window cadence in seconds (0 disables the recorder)
#: and the per-file rotation cap in bytes
TS_INTERVAL_ENV = "TORCHPRUNER_TS_INTERVAL_S"
TS_ROTATE_ENV = "TORCHPRUNER_TS_ROTATE_BYTES"

#: default rotation cap: ~4 MiB/file × (1 live + 3 backups) bounds a
#: week-long 1 Hz recording to ~16 MiB per process
DEFAULT_ROTATE_BYTES = 4 * 2 ** 20

#: fraction of a run's windows treated as warmup by the summary split
#: (compile + cache-fill dominated; the steady-state segment is what
#: bench reports and regressions gate on)
WARMUP_FRAC = 0.25


class TimeseriesRecorder:
    """See module docstring.  One per process, owned by ``ObsSession``;
    every mutable field is written under ``self._lock`` (torn windows
    from concurrent tickers would corrupt the delta baselines)."""

    def __init__(self, registry: MetricsRegistry, obs_dir: str,
                 interval_s: float = 1.0,
                 rotate_bytes: int = DEFAULT_ROTATE_BYTES,
                 backups: int = 3):
        self.registry = registry
        self.path = os.path.join(obs_dir, TS_FILENAME)
        self.interval_s = max(0.05, float(interval_s))
        self._lock = threading.Lock()
        self._writer = JsonlWriter(self.path, rotate_bytes=rotate_bytes,
                                   backups=backups)
        self._seq = 0
        self._closed = False
        #: optional per-window hook (the anomaly detector's
        #: ``observe_window``) — invoked OUTSIDE the lock with the
        #: just-emitted window record, exceptions swallowed; not fired
        #: for the forced final window at close (its partial span skews
        #: rate signals)
        self.on_window = None
        self._last_window: Optional[Dict[str, Any]] = None
        #: delta baselines: counter values / histogram (counts, sum,
        #: count) as of the last emitted window
        self._prev_counters: Dict[str, float] = {}
        self._prev_hist: Dict[str, Tuple[List[int], float, int]] = {}
        self._le_emitted: set = set()
        t0 = time.time()
        self._last_ts = t0
        #: read UNLOCKED on the per-step hot path (maybe_tick); written
        #: only in __init__ and under the lock in _tick_locked
        self._next_due = t0 + self.interval_s
        self._writer({"kind": "ts_meta", "v": 1, "pid": os.getpid(),
                      "t0": round(t0, 6),
                      "interval_s": self.interval_s})

    # -- hot path ------------------------------------------------------------

    def maybe_tick(self, now: Optional[float] = None) -> bool:
        """The per-step / per-loop-iteration hook: one clock read and a
        compare when no window is due (the 99.9% case)."""
        t = time.time() if now is None else now
        if t < self._next_due:
            return False
        with self._lock:
            # re-check under the lock: two threads racing past the
            # unlocked gate must not emit two near-empty windows
            if t < self._next_due or self._closed:
                return False
            emitted = self._tick_locked(t)
        self._fire_on_window()
        return emitted

    def tick(self, now: Optional[float] = None) -> bool:
        """Force a window now (the final flush at session close)."""
        t = time.time() if now is None else now
        with self._lock:
            if self._closed:
                return False
            emitted = self._tick_locked(t)
        self._fire_on_window()
        return emitted

    def _fire_on_window(self) -> None:
        cb, rec = self.on_window, self._last_window
        if cb is None or rec is None:
            return
        try:
            cb(rec)
        except Exception:
            pass  # a broken detector must never kill the recorder

    # -- the window ----------------------------------------------------------

    def _tick_locked(self, t: float) -> bool:
        counters: Dict[str, float] = {}
        gauges: Dict[str, float] = {}
        hists: Dict[str, Dict[str, Any]] = {}
        for m in self.registry:
            if isinstance(m, Counter):
                d = m.value - self._prev_counters.get(m.name, 0.0)
                if d:
                    counters[m.name] = round(d, 9)
                    self._prev_counters[m.name] = m.value
            elif isinstance(m, Gauge):
                if m.value is not None and math.isfinite(m.value):
                    gauges[m.name] = round(m.value, 9)
            elif isinstance(m, Histogram):
                # snapshot the mutable fields once; concurrent observes
                # may tear count-vs-counts within a window, but the
                # stored baseline is exactly what was emitted, so the
                # deltas telescope back to the truth next window
                counts = list(m.counts)
                h_sum, h_count = m.sum, m.count
                pc, ps, pn = self._prev_hist.get(
                    m.name, ([0] * len(counts), 0.0, 0))
                dn = h_count - pn
                if dn <= 0:
                    continue
                entry: Dict[str, Any] = {
                    "n": dn,
                    "sum": round(h_sum - ps, 9),
                    "c": [a - b for a, b in zip(counts, pc)],
                }
                if m.name not in self._le_emitted:
                    entry["le"] = list(m.buckets)
                    self._le_emitted.add(m.name)
                hists[m.name] = entry
                self._prev_hist[m.name] = (counts, h_sum, h_count)
        self._seq += 1
        rec: Dict[str, Any] = {
            "kind": "ts_window", "seq": self._seq,
            "ts": round(t, 6),
            "dur_s": round(max(0.0, t - self._last_ts), 6),
        }
        if counters:
            rec["counters"] = counters
        if gauges:
            rec["gauges"] = gauges
        if hists:
            rec["hist"] = hists
        self._writer(rec)
        self._last_window = rec
        self._last_ts = t
        self._next_due = t + self.interval_s
        return True

    # -- teardown ------------------------------------------------------------

    @property
    def windows_total(self) -> int:
        return self._seq

    def close(self) -> None:
        """Final forced window, ``ts_*`` gauges into the registry (they
        ride the metric shard into report.json and ``obs diff``), file
        closed.  Idempotent."""
        with self._lock:
            if self._closed:
                return
            self._tick_locked(time.time())
            self._closed = True
            self._writer.close()
        self.registry.gauge(
            "ts_windows_total",
            "time-series windows recorded (obs/timeseries.py)"
        ).set(float(self._seq))
        self.registry.gauge(
            "ts_interval_s", "time-series window cadence (seconds)"
        ).set(self.interval_s)


# -- readers -----------------------------------------------------------------


def series_paths(path: str) -> List[str]:
    """The rotation set oldest-first: ``path.N`` … ``path.1``, ``path``."""
    out = []
    i = 1
    while os.path.exists(f"{path}.{i}"):
        i += 1
    for j in range(i - 1, 0, -1):
        out.append(f"{path}.{j}")
    if os.path.exists(path):
        out.append(path)
    return out


def load_series(run_dir_or_path: str
                ) -> Tuple[Dict[str, Any], List[Dict[str, Any]]]:
    """``(meta, windows)`` from an obs dir (or a ``metrics_ts.jsonl``
    path directly), walking rotated files oldest-first.  A torn final
    line (kill -9 mid-write) is skipped, like ``load_ledger``; the
    bucket bounds each histogram shipped once are re-attached to every
    window's entry so consumers never chase the first occurrence."""
    path = run_dir_or_path
    if os.path.isdir(path):
        path = os.path.join(path, TS_FILENAME)
    meta: Dict[str, Any] = {}
    windows: List[Dict[str, Any]] = []
    le: Dict[str, List[float]] = {}
    for p in series_paths(path):
        try:
            with open(p) as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        continue  # torn write at a kill point
                    if not isinstance(rec, dict):
                        continue
                    kind = rec.get("kind")
                    if kind == "ts_meta":
                        meta = rec
                    elif kind == "ts_window":
                        for name, h in (rec.get("hist") or {}).items():
                            if "le" in h:
                                le[name] = h["le"]
                            elif name in le:
                                h["le"] = le[name]
                        windows.append(rec)
        except OSError:
            continue
    return meta, windows


def _quantile_from_buckets(bounds: List[float], counts: List[int],
                           q: float) -> Optional[float]:
    """The ``Histogram.quantile`` estimator over a window's bucket
    deltas (no min/max clamp — per-window extremes aren't recorded, so
    the lower bound of the first bucket is taken as 0)."""
    total = sum(counts)
    if total <= 0:
        return None
    target = q * total
    cum = 0
    prev = 0.0
    for i, b in enumerate(bounds):
        c = counts[i] if i < len(counts) else 0
        if cum + c >= target:
            if c:
                return float(prev + (target - cum) / c * (b - prev))
            return float(b)
        cum += c
        prev = b
    return float(bounds[-1]) if bounds else None


def window_quantile(window: Dict[str, Any], name: str,
                    q: float) -> Optional[float]:
    """Estimated q-quantile of histogram ``name`` within one window."""
    h = (window.get("hist") or {}).get(name)
    if not h or "le" not in h:
        return None
    return _quantile_from_buckets(h["le"], h.get("c") or [], q)


def aggregate_windows(windows: List[Dict[str, Any]], name: str
                      ) -> Optional[Dict[str, Any]]:
    """Sum histogram ``name``'s bucket deltas across ``windows`` —
    ``{"le", "c", "n", "sum"}`` — so a segment (e.g. the steady-state
    half of a run) gets one percentile estimate, not a mean of
    per-window estimates."""
    bounds: Optional[List[float]] = None
    counts: Optional[List[int]] = None
    n = 0
    total = 0.0
    for w in windows:
        h = (w.get("hist") or {}).get(name)
        if not h:
            continue
        if bounds is None and "le" in h:
            bounds = h["le"]
            counts = [0] * (len(bounds) + 1)
        if counts is None:
            continue
        for i, c in enumerate(h.get("c") or []):
            if i < len(counts):
                counts[i] += c
        n += h.get("n") or 0
        total += h.get("sum") or 0.0
    if bounds is None or not n:
        return None
    return {"le": bounds, "c": counts, "n": n, "sum": total}


def segment_percentiles(windows: List[Dict[str, Any]], name: str
                        ) -> Optional[Dict[str, Optional[float]]]:
    """p50/p99/mean of histogram ``name`` over a window segment."""
    agg = aggregate_windows(windows, name)
    if agg is None:
        return None
    return {
        "p50": _quantile_from_buckets(agg["le"], agg["c"], 0.50),
        "p99": _quantile_from_buckets(agg["le"], agg["c"], 0.99),
        "mean": (agg["sum"] / agg["n"] if agg["n"] else None),
        "n": agg["n"],
    }


def split_warmup(windows: List[Dict[str, Any]],
                 warmup_frac: float = WARMUP_FRAC
                 ) -> Tuple[List[Dict[str, Any]], List[Dict[str, Any]]]:
    """``(warmup, steady)`` — the first ``warmup_frac`` of windows
    (at least one, when there are ≥2) vs the rest."""
    if len(windows) < 2:
        return [], list(windows)
    k = max(1, int(len(windows) * warmup_frac))
    if k >= len(windows):
        k = len(windows) - 1
    return windows[:k], windows[k:]


def series_summary(windows: List[Dict[str, Any]],
                   warmup_frac: float = WARMUP_FRAC) -> Dict[str, Any]:
    """The warmup-vs-steady-state table ``obs report`` renders: per
    recorded histogram, p50/p99/mean for each segment, plus segment
    wall spans and counter rates over the steady segment."""
    warm, steady = split_warmup(windows, warmup_frac)
    names: List[str] = []
    for w in windows:
        for name in (w.get("hist") or {}):
            if name not in names:
                names.append(name)
    rows = []
    for name in names:
        rows.append({
            "name": name,
            "warmup": segment_percentiles(warm, name),
            "steady": segment_percentiles(steady, name),
        })

    def span(ws):
        return round(sum(w.get("dur_s") or 0.0 for w in ws), 3)

    rates: Dict[str, float] = {}
    steady_span = span(steady)
    if steady_span > 0:
        totals: Dict[str, float] = {}
        for w in steady:
            for k, v in (w.get("counters") or {}).items():
                totals[k] = totals.get(k, 0.0) + v
        rates = {k: round(v / steady_span, 6) for k, v in totals.items()}
    return {
        "windows": len(windows),
        "warmup_windows": len(warm),
        "steady_windows": len(steady),
        "warmup_span_s": span(warm),
        "steady_span_s": span(steady),
        "hist": rows,
        "steady_rates_per_s": rates,
    }


def steady_state_percentiles(run_dir: str, name: str,
                             min_windows: int = 3
                             ) -> Optional[Dict[str, Optional[float]]]:
    """Steady-state-segment p50/p99/mean of one histogram, straight
    from a run dir — what bench's serve/fleet legs report instead of
    whole-run means.  ``None`` when the run recorded too few windows
    for the split to mean anything (bench then falls back)."""
    _, windows = load_series(run_dir)
    if len(windows) < min_windows:
        return None
    _, steady = split_warmup(windows)
    return segment_percentiles(steady, name)


# -- obs watch ---------------------------------------------------------------


def format_watch(run_dir: str, tail: int = 1) -> str:
    """One refresh of the live view: the newest window's gauge board,
    counter rates, and per-window histogram percentiles."""
    try:
        meta, windows = load_series(run_dir)
    except Exception:
        windows = []
        meta = {}
    if not windows:
        return (f"obs watch — {run_dir}\n"
                f"(no {TS_FILENAME} windows yet)")
    w = windows[-1]
    age = time.time() - (w.get("ts") or 0.0)
    dur = w.get("dur_s") or 0.0
    lines = [
        f"obs watch — {run_dir}",
        f"window #{w.get('seq')}  age {age:.1f}s  span {dur:.2f}s"
        f"  ({len(windows)} windows, pid {meta.get('pid', '?')})",
        "",
    ]
    hists = w.get("hist") or {}
    if hists:
        lines.append(f"{'histogram':<32}{'n':>8}{'p50 ms':>12}"
                     f"{'p99 ms':>12}{'mean ms':>12}")
        for name in sorted(hists):
            h = hists[name]
            p50 = window_quantile(w, name, 0.50)
            p99 = window_quantile(w, name, 0.99)
            mean = (h["sum"] / h["n"]) if h.get("n") else None

            def ms(v):
                return f"{1e3 * v:.3f}" if v is not None else "-"

            lines.append(f"{name:<32}{h.get('n', 0):>8}"
                         f"{ms(p50):>12}{ms(p99):>12}{ms(mean):>12}")
        lines.append("")
    counters = w.get("counters") or {}
    if counters and dur > 0:
        lines.append(f"{'counter':<44}{'Δ':>10}{'rate/s':>12}")
        for name in sorted(counters):
            lines.append(f"{name:<44}{counters[name]:>10.6g}"
                         f"{counters[name] / dur:>12.2f}")
        lines.append("")
    gauges = w.get("gauges") or {}
    if gauges:
        lines.append(f"{'gauge':<44}{'value':>22}")
        for name in sorted(gauges):
            lines.append(f"{name:<44}{gauges[name]:>22.6g}")
    alert_lines = _watch_alerts(run_dir)
    if alert_lines:
        lines.append("")
        lines.extend(alert_lines)
    return "\n".join(lines)


def _watch_alerts(run_dir: str, tail: int = 6) -> List[str]:
    """The live incidents/alerts pane: the ledger tail's anomaly /
    incident / burn-alert records (the ledger flushes per line, so the
    pane is current to the last event even mid-run)."""
    if not os.path.isdir(run_dir):
        return []
    from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger

    try:
        led = load_ledger(os.path.join(run_dir, LEDGER_FILENAME))
    except Exception:
        return []
    alerts = [r for r in led
              if r.get("event") in ("anomaly", "incident")
              or (r.get("event") == "serve"
                  and r.get("kind") == "slo_burn")]
    if not alerts:
        return []
    lines = [f"incidents / alerts ({len(alerts)} total, last {tail})"]
    for r in alerts[-tail:]:
        ev = r.get("event")
        if ev == "incident":
            top = r.get("top_suspect") or {}
            lines.append(
                f"  INCIDENT {r.get('incident_id')} ({r.get('kind')})"
                f"  top suspect: {top.get('class', '?')}"
                f" on {top.get('replica') or 'fleet'}"
                f" score {top.get('score', 0.0):.3f}")
        elif ev == "anomaly":
            z = r.get("z")
            lines.append(
                f"  ANOMALY  {r.get('anomaly_id')} {r.get('state')}"
                f"  {r.get('metric')}"
                + (f" z={z:.1f}" if isinstance(z, (int, float)) else ""))
        else:
            lines.append(
                f"  BURN     {r.get('replica') or ''}:{r.get('metric')}"
                f"  fast {r.get('burn_fast')}x"
                f" slow {r.get('burn_slow')}x")
    return lines


def watch(run_dir: str, interval_s: float = 2.0,
          once: bool = False, out=None) -> int:
    """The ``obs watch DIR`` loop: redraw every ``interval_s`` until
    interrupted.  ``once`` renders a single frame (CI smoke)."""
    import sys

    out = out or sys.stdout
    try:
        while True:
            frame = format_watch(run_dir)
            if not once:
                out.write("\x1b[2J\x1b[H")  # clear + home
            out.write(frame + "\n")
            out.flush()
            if once:
                return 0
            time.sleep(max(0.2, interval_s))
    except KeyboardInterrupt:
        return 0

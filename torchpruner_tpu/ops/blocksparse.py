"""MXU-aligned block-sparse matmul — structured sparsity the kernel can
actually skip.

Mask-based (simulated) pruning holds dropped units at zero without
changing shapes (core/masking.py), which keeps the compile bill bounded
— but a dense matmul over a half-zero weight still pays full FLOPs and
full HBM traffic, so the FLOPs gauge drops while ms/step doesn't (the
exact gap ROADMAP item 2 names).  Per "Structured Model Pruning of
Convolutional Networks on TPUs" (PAPERS.md), sparsity only pays when it
is aligned to the hardware tiles.  This kernel consumes sparsity at
128-lane block granularity:

- the weight's kept input-row blocks and kept output-column blocks are
  STATIC index lists (``in_keep`` / ``out_keep``, derived from the same
  drop indices as ``prune``/``drop_masks`` via
  :func:`keep_blocks_from_drop` — or from block-granular scoring,
  ``score_drop_indices(granularity=128)``);
- the grid runs over kept blocks ONLY — the block index lists ride the
  TPU scalar-prefetch path (``PrefetchScalarGridSpec``) into the block
  index maps, so dropped blocks are neither fetched from HBM nor fed to
  the MXU.  50% structured sparsity halves both the weight traffic and
  the matmul FLOPs, not just the counters;
- dropped output columns are never written by the grid; a trailing
  ``where`` pins them to exact 0.0 (the mask-semantics contract).

The custom VJP keeps the sparsity through training: dx contracts only
kept output blocks and emits only kept input blocks; dw computes only
the kept (in x out) blocks (dropped-block gradients are exactly zero,
which is also what ``masked_update`` would enforce).  A pattern change
(a new prune round) changes the static lists and recompiles — the same
bounded-shape economics as bucketed structural pruning.

``BlockSparseWeight`` wraps a (D, F) weight with its keep lists as a
pytree node (the QTensor pattern): ``quant.qdot`` dispatches it, so a
Dense/GatedDense apply — training forward AND backward — rides the
kernel with no layer-code changes.  Interpreter mode on CPU; shapes or
masks that don't block-align fall back to the dense XLA matmul (the
weight's zeros make that numerically equivalent, just not faster).
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "BlockSparseWeight", "blocksparse_matmul", "keep_blocks_from_drop",
    "keep_blocks_from_mask", "DEFAULT_BLOCK",
]

#: weight-block edge: 128 matches the vector-lane width (and the
#: ``bucket_drop`` lane bucket), so kept blocks tile the MXU cleanly
DEFAULT_BLOCK = 128
MAX_ROW_BLOCK = 256
MIN_ROW_BLOCK = 8


def keep_blocks_from_drop(n: int, drop: Sequence[int],
                          block: int = DEFAULT_BLOCK
                          ) -> Optional[Tuple[int, ...]]:
    """Kept-block indices for a width-``n`` axis with ``drop``ped units,
    or None when the pattern is not block-aligned (some block is only
    partially dropped) or the axis doesn't tile."""
    if n % block:
        return None
    dropped = np.zeros(n, bool)
    dropped[np.asarray(list(drop), np.int64)] = True
    per = dropped.reshape(n // block, block)
    full = per.all(axis=1)
    if not np.array_equal(per.any(axis=1), full):
        return None  # partially-dropped block: mask-only semantics
    return tuple(int(i) for i in np.flatnonzero(~full))


def keep_blocks_from_mask(unit_mask, block: int = DEFAULT_BLOCK
                          ) -> Optional[Tuple[int, ...]]:
    """Kept-block indices from a 0/1 keep mask over one axis (None when
    not block-aligned)."""
    m = np.asarray(unit_mask).astype(bool)
    if m.ndim != 1 or m.size % block:
        return None
    per = m.reshape(m.size // block, block)
    kept = per.all(axis=1)
    if not np.array_equal(per.any(axis=1), kept):
        return None
    return tuple(int(i) for i in np.flatnonzero(kept))


def _row_block(R: int) -> int:
    """Largest row-block <= MAX_ROW_BLOCK dividing R (0: no clean
    blocking — XLA fallback)."""
    for bb in range(min(MAX_ROW_BLOCK, R), MIN_ROW_BLOCK - 1, -1):
        if R % bb == 0:
            return bb
    return 0


def _unit_mask(n: int, keep: Tuple[int, ...], block: int):
    blk = jnp.arange(n, dtype=jnp.int32) // block
    return jnp.isin(blk, jnp.asarray(keep, jnp.int32))


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# --------------------------------------------------------------------------
# kernels: one shared accumulate-over-t body, three grid layouts
# --------------------------------------------------------------------------


def _mm_kernel(ii_ref, oo_ref, a_ref, b_ref, o_ref, acc, *, nt, dims):
    """Grid (i, j, t): accumulate ``dot_general(a, b, dims)`` over the
    contraction stream t into f32 scratch; write at the last step."""
    t = pl.program_id(2)

    @pl.when(t == 0)
    def _init():
        acc[...] = jnp.zeros_like(acc)

    acc[...] += jax.lax.dot_general(
        a_ref[...], b_ref[...], (dims, ((), ())),
        preferred_element_type=jnp.float32)

    @pl.when(t == nt - 1)
    def _out():
        o_ref[...] = acc[...].astype(o_ref.dtype)


def _call(a, b, out_shape, out_dtype, grid, amap, bmap, omap, ablk, bblk,
          oblk, ii, oo, dims):
    nt = grid[2]
    return pl.pallas_call(
        functools.partial(_mm_kernel, nt=nt, dims=dims),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=grid,
            in_specs=[pl.BlockSpec(ablk, amap), pl.BlockSpec(bblk, bmap)],
            out_specs=pl.BlockSpec(oblk, omap),
            scratch_shapes=[pltpu.VMEM(oblk, jnp.float32)],
        ),
        out_shape=jax.ShapeDtypeStruct(out_shape, out_dtype),
        interpret=_interpret(),
    )(jnp.asarray(ii, jnp.int32), jnp.asarray(oo, jnp.int32), a, b)


@functools.partial(jax.jit,
                   static_argnames=("in_keep", "out_keep", "block", "bb"))
def _bs_fwd(x, w, in_keep, out_keep, block, bb):
    """(R, D) @ (D, F) over kept blocks -> (R, F); dropped output
    columns pinned to 0."""
    R, D = x.shape
    F = w.shape[1]
    out_dtype = jnp.result_type(x.dtype, w.dtype)
    y = _call(
        x, w, (R, F), out_dtype,
        grid=(R // bb, len(out_keep), len(in_keep)),
        amap=lambda i, j, t, ii, oo: (i, ii[t]),
        bmap=lambda i, j, t, ii, oo: (ii[t], oo[j]),
        omap=lambda i, j, t, ii, oo: (i, oo[j]),
        ablk=(bb, block), bblk=(block, block), oblk=(bb, block),
        ii=in_keep, oo=out_keep, dims=((1,), (0,)))
    nF = len(out_keep) * block
    if nF == F:
        return y
    return jnp.where(_unit_mask(F, out_keep, block)[None, :], y,
                     jnp.zeros((), out_dtype))


@functools.partial(jax.jit,
                   static_argnames=("in_keep", "out_keep", "block", "bb"))
def _bs_dx(g, w, in_keep, out_keep, block, bb):
    """(R, F) @ (D, F)^T over kept blocks -> (R, D), contracting F
    in-kernel (no materialized transpose); dropped input columns 0."""
    R, F = g.shape
    D = w.shape[0]
    dx = _call(
        g, w, (R, D), g.dtype,
        grid=(R // bb, len(in_keep), len(out_keep)),
        amap=lambda i, j, t, ii, oo: (i, oo[t]),
        bmap=lambda i, j, t, ii, oo: (ii[j], oo[t]),
        omap=lambda i, j, t, ii, oo: (i, ii[j]),
        ablk=(bb, block), bblk=(block, block), oblk=(bb, block),
        ii=in_keep, oo=out_keep, dims=((1,), (1,)))
    if len(in_keep) * block == D:
        return dx
    return jnp.where(_unit_mask(D, in_keep, block)[None, :], dx,
                     jnp.zeros((), g.dtype))


@functools.partial(jax.jit,
                   static_argnames=("in_keep", "out_keep", "block", "bb",
                                    "w_dtype"))
def _bs_dw(x, g, in_keep, out_keep, block, bb, w_dtype):
    """x^T (R, D) x g (R, F) -> (D, F), only kept (in x out) blocks
    computed, the rest exactly 0 (dropped weights receive no update)."""
    R, D = x.shape
    F = g.shape[1]
    dw = _call(
        x, g, (D, F), jnp.dtype(w_dtype),
        grid=(len(in_keep), len(out_keep), R // bb),
        amap=lambda i, j, t, ii, oo: (t, ii[i]),
        bmap=lambda i, j, t, ii, oo: (t, oo[j]),
        omap=lambda i, j, t, ii, oo: (ii[i], oo[j]),
        ablk=(bb, block), bblk=(bb, block), oblk=(block, block),
        ii=in_keep, oo=out_keep, dims=((0,), (0,)))
    if len(in_keep) * block == D and len(out_keep) * block == F:
        return dw
    mask = (_unit_mask(D, in_keep, block)[:, None]
            & _unit_mask(F, out_keep, block)[None, :])
    return jnp.where(mask, dw, jnp.zeros((), jnp.dtype(w_dtype)))


# --------------------------------------------------------------------------
# custom-vjp core + public API
# --------------------------------------------------------------------------


@functools.partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _bs_mm(x, w, in_keep, out_keep, block, bb):
    return _bs_fwd(x, w, in_keep, out_keep, block, bb)


def _bs_mm_fwd(x, w, in_keep, out_keep, block, bb):
    return _bs_fwd(x, w, in_keep, out_keep, block, bb), (x, w)


def _bs_mm_bwd(in_keep, out_keep, block, bb, res, g):
    x, w = res
    dx = _bs_dx(g.astype(x.dtype), w, in_keep, out_keep, block, bb)
    dw = _bs_dw(x, g.astype(x.dtype), in_keep, out_keep, block, bb,
                w.dtype)
    return dx, dw


_bs_mm.defvjp(_bs_mm_fwd, _bs_mm_bwd)


def blocksparse_matmul(x, w, *, in_keep: Optional[Sequence[int]] = None,
                       out_keep: Optional[Sequence[int]] = None,
                       block: int = DEFAULT_BLOCK):
    """``x (..., D) @ w (D, F) -> (..., F)`` computing only the kept
    ``block x block`` weight blocks (None = all blocks on that axis — a
    dense blocked matmul on the same machinery, the bench's
    apples-to-apples dense baseline).  Differentiable; dropped blocks
    contribute (and receive) exactly zero.  Falls back to the dense XLA
    matmul when the shapes or row count don't block cleanly — callers
    keep the weight's dropped blocks zeroed, so the fallback is
    numerically equivalent."""
    D = x.shape[-1]
    F = w.shape[1]
    lead = x.shape[:-1]
    R = int(np.prod(lead)) if lead else 1
    bb = _row_block(R)
    ok = (D % block == 0 and F % block == 0 and bb > 0
          and w.ndim == 2)
    if not ok:
        return x @ w
    ik = tuple(range(D // block)) if in_keep is None \
        else tuple(int(i) for i in in_keep)
    ok2 = tuple(range(F // block)) if out_keep is None \
        else tuple(int(i) for i in out_keep)
    if not ik or not ok2:
        # everything dropped on one axis: the result is exactly zero
        return jnp.zeros(lead + (F,), jnp.result_type(x.dtype, w.dtype))
    y = _bs_mm(x.reshape(R, D), w, ik, ok2, int(block), bb)
    return y.reshape(lead + (F,))


@jax.tree_util.register_pytree_node_class
@dataclass
class BlockSparseWeight:
    """A (D, F) matmul weight carrying its block-sparsity pattern.

    ``w`` holds the DENSE buffer with dropped blocks at zero (the same
    tensor masked training updates); ``in_keep``/``out_keep`` are the
    kept-block index tuples (None = dense on that axis) and are STATIC —
    pattern changes retrace, value changes don't.  ``quant.qdot``
    dispatches instances through :func:`blocksparse_matmul`, so any
    Dense/GatedDense apply site picks the kernel up from the params
    pytree alone (see ``masking.blocksparse_params``)."""

    w: jnp.ndarray
    in_keep: Optional[Tuple[int, ...]] = None
    out_keep: Optional[Tuple[int, ...]] = None
    block: int = DEFAULT_BLOCK

    def tree_flatten(self):
        return ((self.w,), (self.in_keep, self.out_keep, self.block))

    @classmethod
    def tree_unflatten(cls, aux, children):
        in_keep, out_keep, block = aux
        return cls(children[0], in_keep, out_keep, block)

    @property
    def shape(self):
        return self.w.shape

    @property
    def dtype(self):
        return self.w.dtype

    def dense(self) -> jnp.ndarray:
        """The dense (masked) buffer — the reference-path view."""
        return self.w

    def matmul(self, x):
        return blocksparse_matmul(
            x, self.w, in_keep=self.in_keep, out_keep=self.out_keep,
            block=self.block)

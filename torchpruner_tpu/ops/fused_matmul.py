"""Fused dequant matmul — int8 AND packed-int4 weights unpacked in VMEM.

Decode is HBM-bound on weight bytes (PERF.md serving table), so the
lever is bytes READ per token.  The int8 XLA formulation (ops/quant.py)
RELIES on fusion: the weight's only producer is a unary convert, and
XLA *usually* fuses it into the dot's operand read — but "usually" is
not a contract, and the round-4/5 decode rows carry exactly that
uncertainty (the int4_bench stale-evidence note).  This kernel makes
the fusion structural for both widths: the packed/int8 block is DMA'd
to VMEM as integer bytes, widened (and for int4, nibble-unpacked)
in-register, and fed to the MXU — HBM traffic is the integer bytes,
guaranteed, with the per-output-channel scale optionally fused onto the
output block's last accumulation step.

Layouts follow ops/int4_matmul.py: int4 packs value pairs along the
contracted axis (byte ``k`` of column ``f`` = ``w[2k, f]`` low nibble,
``w[2k+1, f]`` high); int8 is the plain (D, F) payload.  Scales are
symmetric per-output-channel (ops/quant.py), applied to the matmul
output — exact, since only input axes contract.  Interpreter mode on
CPU; shapes that don't tile fall back to an unpack-then-matmul XLA path
that is numerically identical (just not bandwidth-saving).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from torchpruner_tpu.ops.int4_matmul import (
    DEFAULT_BLOCK_D,
    DEFAULT_BLOCK_F,
    _fit_block,
    _pick_row_block,
    unpack_int4,
)

__all__ = ["dequant_matmul", "int8_kernel_active", "INT8_KERNEL"]

#: int8 routing policy for quant.qdot: None = auto (kernel on TPU, the
#: convert-fusion XLA path elsewhere — the interpreter would only slow
#: CPU decode), True/False force.  Parity tests force True so tier-1
#: exercises the kernel.
INT8_KERNEL: Optional[bool] = None

#: scale rows are tiled to 8 sublanes so the scale block is a clean
#: (8, lane) TPU tile; the kernel reads row 0
_SCALE_SUBLANES = 8


def int8_kernel_active() -> bool:
    if INT8_KERNEL is not None:
        return INT8_KERNEL
    return jax.default_backend() == "tpu"


def _kernel(x_ref, w_ref, o_ref, s_ref=None, *, bits, nk):
    k = pl.program_id(2)                              # contraction step
    wp = w_ref[...]                                   # int8 block
    if bits == 4:
        # Mosaic has no int8 vector shifts — widen to i32 in-register
        # (VMEM already paid the packed bytes) and sign-extend the
        # nibbles with i32 shifts
        wi = wp.astype(jnp.int32)
        lo = (wi << 28) >> 28
        hi = wi >> 4
        wv = (jnp.stack([lo, hi], axis=1)
              .reshape(wp.shape[0] * 2, wp.shape[1])
              .astype(jnp.bfloat16))
    else:
        wv = wp.astype(jnp.bfloat16)
    part = jnp.dot(x_ref[...].astype(jnp.bfloat16), wv,
                   preferred_element_type=jnp.float32)

    @pl.when(k == 0)
    def _init():
        o_ref[...] = part

    @pl.when(k != 0)
    def _acc():
        o_ref[...] += part

    if s_ref is not None:
        @pl.when(k == nk - 1)
        def _scale():
            o_ref[...] *= s_ref[0:1, :]


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.jit,
                   static_argnames=("bits", "block_d", "block_f"))
def dequant_matmul(x, q, scale=None, *, bits: int = 8,
                   block_d: int = DEFAULT_BLOCK_D,
                   block_f: int = DEFAULT_BLOCK_F):
    """``x (B, D) @ dequant(q) (D, F) [* scale (F,)] -> (B, F)`` f32.

    ``q`` is the int8 payload — ``(D, F)`` for ``bits=8``, the
    pack_int4 ``(D//2, F)`` layout for ``bits=4``.  ``scale`` (per
    output channel, float32) is fused onto the output block inside the
    kernel when given.  Falls back to the XLA unpack-then-matmul path
    when the shapes don't tile (numerics identical; no bandwidth win).
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    B, D = x.shape
    F = q.shape[1]
    pack = 2 if bits == 4 else 1
    if q.shape[0] * pack != D:
        raise ValueError(
            f"payload rows {q.shape[0]} != D/{pack} = {D // pack}")
    block_b = _pick_row_block(B)
    block_d = _fit_block(D, block_d, even=(bits == 4))
    block_f = _fit_block(F, block_f)
    ok = block_b > 0 and block_d > 0 and block_f > 0
    if not ok:
        wv = unpack_int4(q) if bits == 4 else q
        y = jnp.dot(x.astype(jnp.bfloat16), wv.astype(jnp.bfloat16),
                    preferred_element_type=jnp.float32)
        if scale is not None:
            y = y * scale[None, :]
        return y
    in_specs = [
        pl.BlockSpec((block_b, block_d), lambda i, j, k: (i, k)),
        pl.BlockSpec((block_d // pack, block_f), lambda i, j, k: (k, j)),
    ]
    args = [x, q]
    nk = D // block_d
    if scale is not None:
        in_specs.append(
            pl.BlockSpec((_SCALE_SUBLANES, block_f),
                         lambda i, j, k: (0, j)))
        args.append(jnp.broadcast_to(
            scale.astype(jnp.float32)[None, :], (_SCALE_SUBLANES, F)))

    # pallas_call passes refs as (inputs..., outputs...): build the
    # positional adapter for the optional scale operand
    if scale is not None:
        def body(x_ref, w_ref, s_ref, o_ref):
            _kernel(x_ref, w_ref, o_ref, s_ref, bits=bits, nk=nk)
    else:
        def body(x_ref, w_ref, o_ref):
            _kernel(x_ref, w_ref, o_ref, None, bits=bits, nk=nk)

    return pl.pallas_call(
        body,
        # contraction (k) innermost so the (i, j) output block stays
        # resident across its accumulation steps
        grid=(B // block_b, F // block_f, nk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((block_b, block_f),
                               lambda i, j, k: (i, j)),
        out_shape=jax.ShapeDtypeStruct((B, F), jnp.float32),
        interpret=_interpret(),
    )(*args)

"""Weight-only int8 quantization for the serving path.

Decode reads every parameter once per generated token, so KV-cache
generation is HBM-bandwidth-bound (PERF.md, ``llama_decode`` leg) — the
reference framework has no inference path at all, and on TPU the lever
that matters is BYTES READ, not FLOPs.  Symmetric per-output-channel
int8 weights halve the weight traffic vs bf16 (4× vs f32); compute
stays in the activation dtype.

The dequantization is formulated so XLA keeps the int8 tensor in HBM:

    y = (x @ convert(q, x.dtype)) * scale        # NOT  x @ (q * scale)

Per-OUTPUT-channel scales commute with the contraction (only input axes
are contracted), so scaling the matmul's output is exact — and the
weight's only producer is a unary ``convert``, which XLA fuses into the
dot's operand read (a ``q * scale`` weight would materialize a full
dequantized copy when fusion declines the multiply).

Composition with pruning: quantize AFTER structural pruning (the
serving order — prune, fine-tune, quantize, deploy).  ``prune()``
refuses pytrees containing :class:`QTensor` leaves rather than silently
slicing ``q`` and ``scale`` along mismatched axes.  Tensor-parallel
sharding rules likewise predate quantization — quantize the unsharded
serving replica (sharded params fall back to replicated placement).

No reference equivalent (the reference is training-side only); the
technique is standard weight-only PTQ (Dettmers et al., 2022, at the
per-channel granularity TPU serving stacks use).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp

__all__ = ["QTensor", "quantize_tensor", "quantize_params",
           "dequantize_params", "wval", "oscale", "qdot"]


@jax.tree_util.register_pytree_node_class
@dataclass
class QTensor:
    """Symmetric per-output-channel integer weight: ``w ≈ q * scale``.

    ``bits=8`` (default): ``q`` has the original weight's shape (int8).
    ``bits=4``: ``q`` stores two values per int8 byte, packed pairwise
    along ``pack_axis`` (an even-length contracted axis), so the packed
    axis has HALF the logical length — half the bytes at rest and half
    the HBM residency of int8.  ``wval`` unpacks (an elementwise
    producer; see ops/int4_matmul.py for the fused-unpack kernel that
    also halves the bytes READ).  ``scale`` (float32) has the logical
    rank with the contracted INPUT axes (``in_axes``, static) reduced
    to size 1 — so dequantization broadcasts exactly, for any
    input-axis position (Dense's leading input, MoE's middle one).
    """

    q: jnp.ndarray             # int8 payload (packed when bits=4)
    scale: jnp.ndarray         # f32, w.shape with in_axes -> 1
    in_axes: Tuple[int, ...]   # static: which axes a matmul contracts
    bits: int = 8              # static: 8 (plain) or 4 (packed pairs)
    pack_axis: int = 0         # static: the axis pairs pack along

    # pytree protocol: arrays are children, the rest static aux data
    def tree_flatten(self) -> Tuple[tuple, tuple]:
        return ((self.q, self.scale),
                (tuple(self.in_axes), self.bits, self.pack_axis))

    @classmethod
    def tree_unflatten(cls, aux, children) -> "QTensor":
        if all(isinstance(a, int) for a in aux):
            # pre-int4 aux format: the bare in_axes tuple (checkpoints /
            # treedefs serialized before bits/pack_axis existed)
            in_axes, bits, pack_axis = aux, 8, 0
        else:
            in_axes, bits, pack_axis = aux
        return cls(children[0], children[1], tuple(in_axes), bits,
                   pack_axis)

    @property
    def shape(self):
        """The LOGICAL weight shape (unpacked)."""
        if self.bits == 4:
            s = list(self.q.shape)
            s[self.pack_axis] *= 2
            return tuple(s)
        return self.q.shape

    @property
    def dtype(self):  # the STORAGE dtype; compute happens in x.dtype
        return self.q.dtype

    def unpacked(self) -> jnp.ndarray:
        """The logical int8 payload (identity for bits=8)."""
        if self.bits != 4:
            return self.q
        from torchpruner_tpu.ops.int4_matmul import unpack_int4

        moved = jnp.moveaxis(self.q, self.pack_axis, 0)
        flat = unpack_int4(moved.reshape(moved.shape[0], -1))
        return jnp.moveaxis(
            flat.reshape((moved.shape[0] * 2,) + moved.shape[1:]),
            0, self.pack_axis)

    def dequantize(self, dtype=jnp.float32) -> jnp.ndarray:
        """Materialized ``q * scale`` (tests / export — NOT the serving
        path, which scales matmul outputs instead)."""
        return self.unpacked().astype(dtype) * self.scale.astype(dtype)

    def out_scale(self) -> jnp.ndarray:
        """The scale with input axes squeezed out: the shape of the
        OUTPUT axes, for trailing-broadcast multiplication onto a
        matmul/einsum result (:func:`oscale`)."""
        return jnp.squeeze(self.scale, axis=tuple(self.in_axes))


def quantize_tensor(w, in_axes: Union[int, Tuple[int, ...]] = 1,
                    *, bits: int = 8) -> QTensor:
    """Symmetric integer weight with one scale per output channel
    (max-abs / ``2**(bits-1) - 1``) over the contracted ``in_axes`` (an
    int means that many LEADING axes); zero-channels get scale 1 so
    ``q = 0`` round-trips exactly.  ``bits=4`` packs value pairs along
    the first even-length contracted axis (raises if none is)."""
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    w = jnp.asarray(w)
    if isinstance(in_axes, int):
        in_axes = tuple(range(in_axes))
    sym = float(2 ** (bits - 1) - 1)
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=in_axes,
                   keepdims=True)
    scale = jnp.where(amax > 0, amax / sym, 1.0)
    q = jnp.round(w.astype(jnp.float32) / scale).astype(jnp.int8)
    if bits == 8:
        return QTensor(q, scale.astype(jnp.float32), tuple(in_axes))
    from torchpruner_tpu.ops.int4_matmul import pack_int4

    pack_axis = next((a for a in in_axes if w.shape[a] % 2 == 0), None)
    if pack_axis is None:
        raise ValueError(
            f"int4 needs an even-length contracted axis to pack; "
            f"shape {w.shape}, in_axes {in_axes}")
    moved = jnp.moveaxis(q, pack_axis, 0)
    packed = pack_int4(moved.reshape(moved.shape[0], -1)).reshape(
        (moved.shape[0] // 2,) + moved.shape[1:])
    return QTensor(jnp.moveaxis(packed, 0, pack_axis),
                   scale.astype(jnp.float32), tuple(in_axes), 4,
                   pack_axis)


def wval(w, dtype):
    """The tensor a matmul/einsum should consume: the integer payload
    (nibble-unpacked for bits=4) converted to the activation dtype for
    :class:`QTensor` — a unary/elementwise producer chain XLA fuses or
    materializes per step — and the weight itself otherwise."""
    return w.unpacked().astype(dtype) if isinstance(w, QTensor) else w


def qdot(x, w):
    """``x ·₀ w``: contract ``x``'s trailing axis with ``w``'s LEADING
    axis — the Dense/GatedDense matmul site, and (for 3-D weights like
    attention's ``wq (d, H, Dh)``) the einsum ``...d,dhk->...hk``.

    Kernel dispatch by the weight's pytree type:

    - :class:`~torchpruner_tpu.ops.blocksparse.BlockSparseWeight` rides
      the block-sparse Pallas matmul — only kept 128-blocks are fetched
      and multiplied, forward and backward (custom VJP);
    - bits=4 :class:`QTensor` packed along the leading axis routes
      through the fused dequant kernel (ops/fused_matmul.py) with the
      output axes flattened for the kernel and restored after — packing
      pairs along axis 0 stay adjacent under a trailing-axes flatten,
      so the nibble layout is unchanged;
    - bits=8 :class:`QTensor` takes the same fused kernel when
      ``fused_matmul.int8_kernel_active()`` (default: on TPU) — the
      structural version of the convert-into-dot fusion the XLA
      formulation merely hopes for; elsewhere it consumes
      :func:`wval`'s convert-only producer.

    The caller applies :func:`oscale` as usual (the kernels run
    unscaled here; scale fusion is for direct ``dequant_matmul`` use).
    """
    from torchpruner_tpu.ops.blocksparse import BlockSparseWeight

    if isinstance(w, BlockSparseWeight):
        return w.matmul(x)
    if (isinstance(w, QTensor) and w.in_axes == (0,)
            and x.dtype == jnp.bfloat16
            and (w.bits == 4 and w.pack_axis == 0
                 or w.bits == 8 and _int8_kernel_active())):
        from torchpruner_tpu.ops.fused_matmul import dequant_matmul

        lead = x.shape[:-1]
        rest = w.shape[1:]  # logical output axes (possibly > 1 of them)
        y = dequant_matmul(x.reshape((-1, x.shape[-1])),
                           w.q.reshape((w.q.shape[0], -1)), bits=w.bits)
        return y.reshape(lead + rest).astype(x.dtype)
    wv = wval(w, x.dtype)
    if wv.ndim > 2:
        return jnp.tensordot(x, wv, axes=(x.ndim - 1, 0))
    return x @ wv


def _int8_kernel_active() -> bool:
    from torchpruner_tpu.ops.fused_matmul import int8_kernel_active

    return int8_kernel_active()


def oscale(y, w):
    """Apply ``w``'s output-channel scale to a matmul output ``y``
    whose TRAILING axes are ``w``'s output axes (every standard apply
    site) — the exact dequantization for per-output-channel symmetric
    quantization; identity for unquantized weights.  Sites where the
    output axes are not trailing (the MoE sparse-dispatch buffers, same
    rank as the weight) multiply by ``w.scale`` directly instead."""
    if not isinstance(w, QTensor):
        return y
    return y * w.out_scale().astype(y.dtype)


#: layer-type -> {param key: contracted input axes}.  Norm scales/biases
#: and conv kernels stay in float (convs are compute-bound at serving
#: batch sizes; the win is the big matmuls); the MoE router too (tiny,
#: and its softmax is precision-sensitive).
_QUANT_KEYS = {
    "Dense": {"w": (0,)},
    "GatedDense": {"wg": (0,), "wu": (0,)},
    "MultiHeadAttention": {"wq": (0,), "wk": (0,), "wv": (0,),
                           "wo": (0, 1)},
    # wg/wu (E, D, F) contract D -> per-(expert, channel) scales.  wo
    # (E, F, D) must use ONE scale per output d SHARED across experts:
    # the dense formulation's bsef,efd->bsd einsum contracts e, so a
    # per-expert wo scale could not be factored out of the output (the
    # price is a coarser wo quantization when experts' magnitudes
    # diverge; wg/wu keep per-expert granularity)
    "MoE": {"wg": (1,), "wu": (1,), "wo": (0, 1)},
}


def quantize_params(model, params, *, layers: Optional[Sequence[str]] = None,
                    bits: int = 8):
    """Quantize the matmul weights of ``model``'s Dense / GatedDense /
    attention / MoE layers (biases, norms, embeddings, convs and
    routers stay float).  Returns a NEW params pytree with
    :class:`QTensor` leaves, servable by ``model.apply`` / ``generate``
    directly.  ``layers`` restricts to the named layer paths
    (``"block1_ffn/gate"`` style for nested layers).

    ``bits=8`` is the bandwidth configuration (the int8 payload feeds
    the dot directly).  ``bits=4`` HALVES the weights' bytes at rest —
    the capacity lever: a 2× bigger model per chip's HBM — at the cost
    of an unpack per use (the fused bandwidth kernel is
    ops/int4_matmul.py) and int4 precision.

    Quantize AFTER pruning: this is the deploy step of the
    prune → fine-tune → quantize pipeline (examples/04).
    """
    wanted = set(layers) if layers is not None else None
    matched: set = set()
    out = _quantize_walk(model.layers, params, (), wanted, matched, bits)
    if wanted is not None and wanted - matched:
        # a typo'd layer name must not silently deploy unquantized
        raise KeyError(
            f"quantize_params: no quantizable layer matched "
            f"{sorted(wanted - matched)} (quantizable: Dense, GatedDense, "
            f"attention, MoE; nested paths spell as 'block/child')"
        )
    return out


def _quantize_walk(specs, params, prefix: Tuple[str, ...], wanted, matched,
                   bits: int = 8):
    from torchpruner_tpu.core import layers as L

    out = dict(params)
    for spec in specs:
        name = spec.name
        if isinstance(spec, L.COMPOSITE_TYPES):
            if name in out:
                out[name] = _quantize_walk(
                    spec.body + spec.shortcut, out[name],
                    prefix + (name,), wanted, matched, bits)
            continue
        keys = _QUANT_KEYS.get(type(spec).__name__)
        full = "/".join(prefix + (name,))
        if keys is None or (wanted is not None and full not in wanted) \
                or name not in out:
            continue
        matched.add(full)
        p = dict(out[name])
        for key, in_axes in keys.items():
            if key in p and not isinstance(p[key], QTensor):
                p[key] = quantize_tensor(p[key], in_axes=in_axes,
                                         bits=bits)
        out[name] = p
    return out


def dequantize_params(params):
    """Materialize every :class:`QTensor` back to float (round-trip
    testing / exporting to an unquantized consumer)."""
    return _dequant_tree(params)


def _dequant_tree(t):
    if isinstance(t, QTensor):
        return t.dequantize()
    if isinstance(t, dict):
        return {k: _dequant_tree(v) for k, v in t.items()}
    if isinstance(t, (list, tuple)):
        return type(t)(_dequant_tree(v) for v in t)
    return t

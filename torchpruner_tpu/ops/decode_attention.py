"""Decode-shaped attention: q_len=1 against a long static KV cache.

The continuous-batching decode step (serve/engine.py) and ``generate``'s
scanned sampling loop both attend ONE query per sequence against the
whole ``(B, max_len, H, Dh)`` cache.  The einsum path scores every
cache position — including the unwritten future — then masks: for a
slot sitting at position ``pos`` that reads ``max_len / (pos+1)`` times
the bytes it needs, and decode is HBM-bandwidth-bound.  This kernel
streams the cache in KV-position blocks and STOPS at each row's own
``pos``:

- grid ``(B, H, T // block)``, scalar-prefetched per-row positions: the
  KV block index maps clamp past-``pos`` steps to the last live block,
  so skipped steps re-address the previous block and fetch nothing —
  bytes read scale with ``pos``, not ``max_len``;
- online-softmax scratch carried across the block dimension (the grid
  iterates it innermost), f32 accumulation, one output write per
  ``(batch, head)``.

**Bit-stability contract** (the serve ``--verify`` path): a row's
result depends only on its real positions ``0..pos`` and the block
partition.  The block size is a deterministic function of the CACHE
length alone (``decode_block``), so two programs over the same
``max_len`` — the engine's slot step and a solo ``generate`` replay —
produce bit-identical rows regardless of batch size, neighbouring
slots, or stale K/V left by a previous slot occupant (masked positions
contribute exactly 0.0).  Replays must therefore share the serving
cache length (``generate(..., max_len=engine.max_len)``), exactly as
the frontend's ``--verify`` does.

Shapes that don't block (cache length with no power-of-two factor >= 8)
fall back to the masked-einsum path — also a deterministic function of
the cache length, so the contract holds there too.  Interpreter mode on
CPU keeps tier-1 on the real kernel code.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from torchpruner_tpu.ops import autotune

_NEG_INF = -1e30

#: decode block cap: positions per KV block (sublane axis of the block)
MAX_DECODE_BLOCK = 128
MIN_DECODE_BLOCK = 8

#: kill switch (and test hook): None = auto (kernel wherever it blocks),
#: False = always einsum
ENABLE = True


def decode_block(T: int) -> int | None:
    """The KV block size for a cache of length ``T`` — the largest
    power-of-two divisor of ``T`` in [8, 128], or a tuned override that
    divides ``T``.  A function of T ALONE (never of batch or pos): the
    bit-stability contract above hangs on every program over the same
    cache length choosing the same block boundaries."""
    bk = 1
    while T % (bk * 2) == 0 and bk * 2 <= MAX_DECODE_BLOCK:
        bk *= 2
    if bk < MIN_DECODE_BLOCK:
        return None
    return bk


def _tuned_block(T: int, Dh: int, dtype) -> int | None:
    """Tuned block if one is recorded AND divides T, else the default."""
    bk = decode_block(T)
    tuned = autotune.lookup(autotune.KIND_DECODE, Dh, T, dtype)
    if tuned and T % tuned[0] == 0 and tuned[0] >= MIN_DECODE_BLOCK:
        return int(tuned[0])
    return bk


def kernel_active(T: int, Dh: int, dtype) -> bool:
    """True when :func:`decode_attention` would run the Pallas kernel
    for a q_len=1 step at this cache geometry — the ONE dispatch
    predicate, shared with ``serve.engine``'s
    ``serve_decode_kernel_active`` gauge so the reported path can never
    diverge from the executed one (incl. tuned-block overrides)."""
    return bool(ENABLE and _tuned_block(T, Dh, dtype) is not None
                and not _multichip_tpu())


def xla_decode_attention(q, k_cache, v_cache, pos):
    """The masked-einsum reference (and non-blocking fallback): scores
    against the whole cache, positions ``> pos`` masked.  ``q`` is
    ``(B, s, H, Dh)`` (s >= 1 — also the prefill path), ``pos`` scalar
    or ``(B,)``."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum(
        "bqhk,bthk->bhqt", q, k_cache, preferred_element_type=jnp.float32
    ) * scale
    t = jnp.arange(k_cache.shape[1])
    if jnp.ndim(pos) > 0:
        q_pos = pos[:, None] + jnp.arange(q.shape[1])[None, :]  # (B, s)
        mask = (t[None, None, :] <= q_pos[:, :, None])[:, None]
    else:
        q_pos = pos + jnp.arange(q.shape[1])
        mask = (t[None, :] <= q_pos[:, None])[None, None]
    s = jnp.where(mask, s, _NEG_INF)
    w = jax.nn.softmax(s, axis=-1).astype(v_cache.dtype)
    return jnp.einsum("bhqt,bthk->bqhk", w, v_cache)


def _decode_kernel(pos_ref, q_ref, k_ref, v_ref, o_ref, m_s, l_s, acc_s,
                   *, block, n_blocks):
    b = pl.program_id(0)
    kb = pl.program_id(2)
    pos = pos_ref[b]
    n_run = lax.div(pos, block) + 1  # blocks holding positions <= pos

    @pl.when(kb == 0)
    def _init():
        m_s[...] = jnp.full_like(m_s, _NEG_INF)
        l_s[...] = jnp.zeros_like(l_s)
        acc_s[...] = jnp.zeros_like(acc_s)

    @pl.when(kb < n_run)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)        # (1, Dh)
        k = k_ref[0, :, 0].astype(jnp.float32)     # (block, Dh)
        v = v_ref[0, :, 0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * (1.0 / math.sqrt(q.shape[-1]))         # (1, block)
        t = kb * block + lax.broadcasted_iota(jnp.int32, (1, block), 1)
        s = jnp.where(t <= pos, s, _NEG_INF)
        m, l, acc = m_s[...], l_s[...], acc_s[...]
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        m_s[...] = m_new
        l_s[...] = alpha * l + p.sum(axis=1, keepdims=True)
        acc_s[...] = acc * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(kb == n_blocks - 1)
    def _out():
        o_ref[0, 0] = (acc_s[...] / l_s[...]).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("block", "interpret"))
def _decode_call(q, k_cache, v_cache, pos, block, interpret):
    B, _, H, Dh = q.shape
    T = k_cache.shape[1]
    n_blocks = T // block

    def q_map(b, h, kb, pos_ref):
        return (b, 0, h, 0)

    def kv_map(b, h, kb, pos_ref):
        # clamp past-pos steps to the last live block: same index as the
        # previous step -> the pipeline fetches nothing for them
        n_run = lax.div(pos_ref[b], block) + 1
        return (b, jnp.minimum(kb, n_run - 1), h, 0)

    return pl.pallas_call(
        functools.partial(_decode_kernel, block=block, n_blocks=n_blocks),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(B, H, n_blocks),
            in_specs=[
                pl.BlockSpec((1, 1, 1, Dh), q_map),
                pl.BlockSpec((1, block, 1, Dh), kv_map),
                pl.BlockSpec((1, block, 1, Dh), kv_map),
            ],
            out_specs=pl.BlockSpec((1, 1, 1, Dh), q_map),
            scratch_shapes=[
                pltpu.VMEM((1, 1), jnp.float32),
                pltpu.VMEM((1, 1), jnp.float32),
                pltpu.VMEM((1, Dh), jnp.float32),
            ],
        ),
        # the einsum path's context dtype is the CACHE dtype (softmax
        # weights cast to it before the value contraction); match it so
        # the kernel is a drop-in for the scan-carried logits dtype
        out_shape=jax.ShapeDtypeStruct(q.shape, v_cache.dtype),
        interpret=interpret,
    )(pos, q, k_cache, v_cache)


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _multichip_tpu() -> bool:
    # under multi-chip GSPMD the Mosaic custom call has no partitioning
    # rule — TP/sharded decode keeps the einsum path until a shard_map
    # wrapper lands (single-chip serving, the common case, takes the
    # kernel; on CPU the interpreter lowers to partitionable lax ops)
    return jax.default_backend() == "tpu" and len(jax.devices()) > 1


def decode_attention(q, k_cache, v_cache, pos):
    """One decode step's attention: ``q (B, 1, H, Dh)`` against
    ``k_cache/v_cache (B, T, H, Dh)`` at per-row positions ``pos``
    (``(B,)`` int32, or a scalar applied to every row).  Returns
    ``(B, 1, H, Dh)`` in the cache dtype.

    Dispatches the Pallas kernel when the cache length blocks cleanly
    (see :func:`decode_block`); otherwise — and under multi-chip GSPMD
    or ``ENABLE=False`` — the masked-einsum path."""
    B, s, H, Dh = q.shape
    T = k_cache.shape[1]
    if s != 1 or not kernel_active(T, Dh, k_cache.dtype):
        return xla_decode_attention(q, k_cache, v_cache, pos)
    block = _tuned_block(T, Dh, k_cache.dtype)
    if jnp.ndim(pos) == 0:
        pos = jnp.full((B,), pos, jnp.int32)
    return _decode_call(q, k_cache, v_cache, pos.astype(jnp.int32),
                        block, _interpret())

"""int4 pack/unpack/quantize helpers + the int4 matmul entry point.

The Pallas kernel itself was generalized to int8 AND int4 with a fused
output scale — it lives in ops/fused_matmul.py; :func:`int4_matmul`
stays as the packed-int4 entry point over it.

Why a kernel: decode is HBM-bound on weight bytes (PERF.md serving
table — int8 already buys 1.33×), and int4 halves the bytes again, but
ONLY if the unpack never round-trips through HBM.  XLA cannot fuse a
nibble-unpack (shift/mask + interleave-reshape) into a dot's operand
read, so an XLA-level int4 path materializes the full-size weight and
spends MORE bandwidth than it saves; storing ``jnp.int4`` arrays is no
better (unpacked in HBM — measured 1 byte/element — and int4 jit
arguments crash the tunnelled backend outright).  The kernel reads the
PACKED (two values per byte) block into VMEM, sign-extends the nibbles
in-register, and feeds the MXU — HBM sees half the int8 bytes.

Layout: values pair along the contracted (input) axis — byte ``k`` of
column ``f`` holds ``w[2k, f]`` in its low nibble and ``w[2k+1, f]`` in
the high one — so a ``(bd//2, bf)`` packed block unpacks to a
``(bd, bf)`` operand with the lane (minor) axis untouched.

Scales follow ops/quant.py's convention: symmetric per-OUTPUT-channel,
applied to the matmul result (exact, since only input axes contract).
On CPU the kernel runs in interpreter mode (tests); shapes that don't
tile fall back to an unpack-then-matmul XLA path that is numerically
identical (just not bandwidth-saving).
"""

from __future__ import annotations

import jax.numpy as jnp

__all__ = ["pack_int4", "unpack_int4", "quantize_int4", "int4_matmul"]

#: default tile sizes: bd rows of the contracted axis (bd//2 packed
#: bytes), bf output lanes.  512×512 unpacked bf16 = 512 KB of VMEM.
DEFAULT_BLOCK_D = 512
DEFAULT_BLOCK_F = 512

#: leading (row) axis tiling: up to this many rows ride in one block
#: (decode steps are tiny); above it the rows are tiled too, so a
#: prefill through a bits=4 model (e.g. B8 × S2048 = 16384 rows) keeps
#: the x-block + f32 accumulator inside VMEM instead of failing Mosaic.
MAX_UNTILED_ROWS = 1024
DEFAULT_BLOCK_B = 256


def pack_int4(q):
    """Pack int8 values in [-8, 7] pairwise along axis 0: ``(D, F)`` →
    ``(D//2, F)`` with ``out[k] = (q[2k] & 0xF) | (q[2k+1] << 4)``."""
    if q.shape[0] % 2:
        raise ValueError(f"input axis {q.shape[0]} must be even to pack")
    lo = q[0::2] & 0x0F
    hi = (q[1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.int8)


def unpack_int4(p):
    """Inverse of :func:`pack_int4`: ``(D//2, F)`` int8 → ``(D, F)``
    sign-extended int8 in [-8, 7]."""
    lo = ((p << 4).astype(jnp.int8)) >> 4   # low nibble, sign-extended
    hi = p >> 4                             # arithmetic shift sign-extends
    return jnp.stack([lo, hi], axis=1).reshape(-1, p.shape[-1])


def quantize_int4(w, *, sym_max: int = 7):
    """Symmetric per-output-channel int4: ``(packed, scale)`` with
    ``w ≈ unpack(packed) * scale`` — ``w`` is ``(D, F)`` (input axis
    leading, like Dense kernels), ``scale`` is ``(F,)`` float32.
    Zero-channels get scale 1 so ``q = 0`` round-trips exactly."""
    absmax = jnp.max(jnp.abs(w), axis=0)
    scale = jnp.where(absmax > 0, absmax / sym_max, 1.0).astype(jnp.float32)
    q = jnp.clip(jnp.round(w / scale), -sym_max, sym_max).astype(jnp.int8)
    return pack_int4(q), scale


def _pick_row_block(B: int) -> int:
    """Largest row-block <= MAX_UNTILED_ROWS that divides ``B`` (the
    whole count for decode-sized B); 0 when only degenerate tilings
    exist (< 8 rows per block — prime-ish huge B), routing to the XLA
    fallback instead of a one-row-per-grid-step kernel."""
    if B <= MAX_UNTILED_ROWS:
        return B
    for bb in range(MAX_UNTILED_ROWS, 7, -1):
        if B % bb == 0:
            return bb
    return 0


def _fit_block(n: int, preferred: int, *, lane_multiple: int = 128,
               even: bool = False) -> int:
    """``preferred`` when it divides ``n``, else the largest
    lane-aligned block that does (vocab-sized axes are rarely powers of
    two: Llama-3's lm_head F = 128256 = 256 × 501 needs block 256, not
    the 512 default); 0 when none divides — XLA fallback."""
    for b in (preferred, 384, 256, 128):
        if b <= n and n % b == 0 and b % lane_multiple == 0 \
                and (not even or b % 2 == 0):
            return b
    return 0


def int4_matmul(x, packed, scale=None, *, block_d: int = DEFAULT_BLOCK_D,
                block_f: int = DEFAULT_BLOCK_F):
    """``x (B, D) @ (unpack(packed) (D, F) * scale (F,)) -> (B, F)`` f32.

    ``packed`` is :func:`pack_int4`'s ``(D//2, F)`` int8.  Thin wrapper
    over the generalized int4/int8 kernel (ops/fused_matmul.py), which
    also FUSES the per-output-channel scale onto the output block; the
    XLA unpack-then-matmul fallback for non-tiling shapes is numerically
    identical (no bandwidth win)."""
    from torchpruner_tpu.ops.fused_matmul import dequant_matmul

    return dequant_matmul(x, packed, scale, bits=4, block_d=block_d,
                          block_f=block_f)

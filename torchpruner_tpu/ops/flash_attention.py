"""Flash attention for TPU.

Placeholder implementation: numerically identical XLA path.  Replaced by a
Pallas kernel (same signature) — see this module's history; the public entry
point is :func:`flash_attention` and callers never depend on the backend.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp


def flash_attention(q, k, v, *, causal: bool = False):
    """Attention on ``(B, S, H, Dh)`` q/k/v (K/V already at H heads)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bshk,bthk->bhst", q, k) * scale
    if causal:
        S = q.shape[1]
        neg = jnp.finfo(logits.dtype).min
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, neg)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthk->bshk", w, v)

"""Flash attention for TPU — Pallas forward AND backward kernels, plus a
blocked lax formulation for non-TPU backends.

The hot op of the transformer families (ViT/BERT/Llama head pruning,
BASELINE.json configs 3-5).  No path ever materializes the ``(S, S)``
score matrix:

- **Forward** (Dao et al., 2022): the grid runs over ``(batch, heads,
  query blocks)``; each program streams KV blocks through VMEM with the
  numerically-stable running ``(max, sum, acc)`` update, and additionally
  writes the per-query log-sum-exp (LSE) used by the backward.  Causal
  masking is TWO-PHASE: KV blocks entirely below the diagonal run an
  unmasked body, only diagonal-straddling blocks pay the mask compare —
  and blocks entirely above the diagonal are skipped outright.
- **Backward** (FlashAttention-2): two kernels sharing the forward's LSE,
  with ``delta = rowsum(dO * O)`` recomputed in-kernel per query block
  (no host-visible (B, H, S) delta tensor).  Both kernels stream their
  inner operand through a 4th GRID dimension with an f32 VMEM scratch
  accumulator, so VMEM residency is O(block), independent of S — the
  round-4 whole-sequence VMEM specs (K/V + the lane-broadcast LSE/delta
  rows at 32k = 40 MB in one kernel) are what made the 32k backward fail
  remote compilation, and why ``FLASH_BWD_XLA_MIN_S`` existed.  With the
  re-blocking that fallback is RETIRED (default None); set
  ``TORCHPRUNER_FLASH_BWD_XLA_MIN_S`` to re-arm it if a backend still
  refuses (scripts/capture_tpu.sh's staged flash leg re-validates the
  32k backward at the next tunnel window).
- **Non-TPU backends** run the SAME blocked online-softmax algorithm as
  straight lax ops (``_lax_flash``) instead of the Pallas interpreter:
  the interpreter exists to test kernel code, not to win benchmarks,
  while the blocked lax form beats the quadratic einsum on CPU caches
  (measured 1.2-4x on the bench shapes).  Tests force the interpreter
  path via ``FORCE_PALLAS`` so tier-1 still exercises the real kernels.

Block sizes come from the caller, else the persisted autotune cache
(ops/autotune.py), else measured defaults.  Matmuls are
``preferred_element_type=float32`` so bf16 inputs still accumulate in
f32 on the MXU.  Inputs whose sequence length doesn't block cleanly
(min block 8) fall back to the XLA einsum path in both directions.
"""

from __future__ import annotations

import functools
import math
import os

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from torchpruner_tpu.ops import autotune

_NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
#: the lax (non-TPU) path favors bigger tiles: block overhead is loop
#: trips, not VMEM, and 512 measured best on the CPU bench shapes
LAX_DEFAULT_BLOCK = 512
MIN_BLOCK = 8  # below this the kernel degrades to tiny-tile scalar work
_LANE = 128  # TPU lane width: minor dim of the LSE row layout

#: tests set True to route non-TPU calls through the Pallas kernels in
#: interpreter mode (the parity suite's job); the production non-TPU
#: path is the blocked lax formulation
FORCE_PALLAS = False

#: RETIRED fallback, kept as an env-armed escape hatch: the 32k remote-
#: compile failure (PERF.md flash S-sweep, HTTP 500) traced to the old
#: backward's whole-sequence VMEM block specs; the re-blocked backward
#: bounds VMEM at O(block).  Arm via TORCHPRUNER_FLASH_BWD_XLA_MIN_S=N
#: to make the vjp recompute gradients through the XLA path at S >= N
#: again (quadratic temp memory in the backward only).
_env_min_s = os.environ.get("TORCHPRUNER_FLASH_BWD_XLA_MIN_S", "")
FLASH_BWD_XLA_MIN_S: int | None = int(_env_min_s) if _env_min_s else None


def _xla_attention(q, k, v, *, causal: bool):
    """Reference einsum path on (B, S, H, Dh); also the non-blocking
    shapes' fallback (forward and, via autodiff, backward)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum(
        "bshk,bthk->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        # bottom-right-aligned causal mask: query i sees keys
        # j <= i + (Sk - Sq).  Equals tril for self-attention; for a
        # query chunk against a longer KV prefix (chunked prefill) the
        # chunk's last query sees the whole prefix.
        mask = (jnp.arange(Sk)[None, :]
                <= jnp.arange(Sq)[:, None] + (Sk - Sq))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    w = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthk->bshk", w, v)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None,
                *, scale, causal, block_q, block_k):
    """One (batch, head, query-block) program: stream KV blocks with the
    online-softmax running state carried through ``fori_loop``; emit the
    normalized output block and (when a backward will follow) its LSE
    row.  Inference calls omit ``lse_ref`` — no wasted HBM writes.

    Causal runs two phases: an unmasked loop over the KV blocks whose
    every key is visible to every query row of this block, then a
    masked loop over the (at most ``block_q // block_k + 1``) blocks
    straddling the diagonal.  Blocks above the diagonal never run."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # (block_q, Dh)
    dh = q.shape[-1]
    S = k_ref.shape[2]
    n_kv = S // block_k

    def body(j, carry, masked):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if masked:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, dh), jnp.float32)
    if causal:
        # blocks whose LAST key position <= this q block's FIRST query
        # position need no mask; blocks past the diagonal are skipped
        n_run = jnp.minimum(
            lax.div((qi + 1) * block_q + block_k - 1, block_k), n_kv)
        n_full = jnp.minimum(lax.div(qi * block_q + 1, block_k), n_run)
        carry = lax.fori_loop(
            0, n_full, functools.partial(body, masked=False),
            (m0, l0, acc0))
        m, l, acc = lax.fori_loop(
            n_full, n_run, functools.partial(body, masked=True), carry)
    else:
        m, l, acc = lax.fori_loop(
            0, n_kv, functools.partial(body, masked=False), (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        # LSE row broadcast across the 128-lane minor dim: TPU block shapes
        # need the last two dims tileable to (sublane, lane), so a bare
        # (1, 1, block_q) block is not lowerable — same layout the
        # reference TPU kernel uses for its l/m outputs.
        lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (block_q, _LANE))


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "with_lse"),
)
def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, with_lse=True):
    """(B, H, S, Dh) layout in; returns (out, lse) with lse (B, H, S, 128)
    f32 (the per-query LSE broadcast across the minor lane dim), or
    (out, None) when ``with_lse=False`` (inference: skip the LSE writes).

    K/V ride whole-sequence VMEM blocks (fetched ONCE per (batch, head)
    — the index map is q-block-invariant, so the pipeline never
    refetches); chip-proven to S=32k bf16 (8 MB)."""
    B, H, S, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    grid = (B, H, S // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out_specs = [
        pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((B, H, S, Dh), q.dtype)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, _LANE), lambda b, h, i: (b, h, i, 0))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, S, _LANE), jnp.float32)
        )
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else (res[0], None)


# --------------------------------------------------------------------------
# backward kernels — 4D grids, O(block) VMEM
# --------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref, dq_ref,
               dq_acc, delta_s, *, scale, causal, block_q, block_k, n_kv):
    """Grid (B, H, q blocks, KV blocks): the KV stream is the 4th grid
    dimension; dQ accumulates in f32 VMEM scratch and is written once at
    the last KV step.  ``delta = rowsum(dO * O)`` is computed in-kernel
    at the first step — no precomputed (B, H, S, lane) delta tensor."""
    qi = pl.program_id(2)
    j = pl.program_id(3)
    if causal:
        n_run = jnp.minimum(
            lax.div((qi + 1) * block_q + block_k - 1, block_k), n_kv)
    else:
        n_run = n_kv

    @pl.when(j == 0)
    def _init():
        dq_acc[...] = jnp.zeros_like(dq_acc)
        delta_s[...] = jnp.sum(
            do_ref[0, 0].astype(jnp.float32) * o_ref[0, 0].astype(jnp.float32),
            axis=-1, keepdims=True)

    @pl.when(j < n_run)
    def _accumulate():
        q = q_ref[0, 0].astype(jnp.float32)       # (block_q, Dh)
        do = do_ref[0, 0].astype(jnp.float32)     # (block_q, Dh)
        lse = lse_ref[0, 0, :, 0:1]               # (block_q, 1)
        k = k_ref[0, 0].astype(jnp.float32)       # (block_k, Dh)
        v = v_ref[0, 0].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)                          # masked rows -> 0
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta_s[...]) * scale
        dq_acc[...] += lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(j == n_kv - 1)
    def _out():
        dq_ref[0, 0] = dq_acc[...].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, o_ref, do_ref, lse_ref,
                dk_ref, dv_ref, dk_acc, dv_acc,
                *, scale, causal, block_q, block_k, n_q):
    """Grid (B, H, KV blocks, q blocks): the query stream is the 4th
    grid dimension; dK/dV accumulate in f32 VMEM scratch.  Causal skips
    query blocks entirely above this KV block's diagonal (their index
    maps clamp to the first contributing block, so skipped steps fetch
    nothing new)."""
    ki = pl.program_id(2)
    qi = pl.program_id(3)
    i_start = lax.div(ki * block_k, block_q) if causal else 0

    @pl.when(qi == 0)
    def _init():
        dk_acc[...] = jnp.zeros_like(dk_acc)
        dv_acc[...] = jnp.zeros_like(dv_acc)

    @pl.when(qi >= i_start)
    def _accumulate():
        k = k_ref[0, 0].astype(jnp.float32)       # (block_k, Dh)
        v = v_ref[0, 0].astype(jnp.float32)
        q = q_ref[0, 0].astype(jnp.float32)       # (block_q, Dh)
        do = do_ref[0, 0].astype(jnp.float32)
        lse = lse_ref[0, 0, :, 0:1]               # (block_q, 1)
        delta = jnp.sum(
            do * o_ref[0, 0].astype(jnp.float32), axis=-1, keepdims=True)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_acc[...] += lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk_acc[...] += lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    @pl.when(qi == n_q - 1)
    def _out():
        dk_ref[0, 0] = dk_acc[...].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_acc[...].astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    """(B, H, S, Dh) layout; returns (dq, dk, dv).  ``lse`` may arrive
    single-lane (the vjp residual) — it is re-broadcast to the 128-lane
    kernel layout here (one (B, H, S, 128) f32 temp; the per-kernel
    VMEM cost stays one (block, 128) tile)."""
    from jax.experimental.pallas import tpu as pltpu

    B, H, S, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    n_q, n_kv = S // block_q, S // block_k
    lse = jnp.broadcast_to(lse, (B, H, S, _LANE))

    qblk = pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i, j: (b, h, i, 0))
    qrow = pl.BlockSpec((1, 1, block_q, _LANE),
                        lambda b, h, i, j: (b, h, i, 0))

    def kv_j(b, h, i, j):
        # clamp the KV stream index to the causal range so skipped steps
        # re-address the previous block (no DMA) instead of fetching
        # blocks the kernel will never read
        if causal:
            n_run = jnp.minimum(
                lax.div((i + 1) * block_q + block_k - 1, block_k), n_kv)
            return (b, h, jnp.minimum(j, n_run - 1), 0)
        return (b, h, j, 0)

    kblk_j = pl.BlockSpec((1, 1, block_k, Dh), kv_j)

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_kv=n_kv),
        grid=(B, H, n_q, n_kv),
        in_specs=[qblk, kblk_j, kblk_j, qblk, qblk, qrow],
        out_specs=qblk,
        out_shape=jax.ShapeDtypeStruct((B, H, S, Dh), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, Dh), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, o, do, lse)

    def q_i(b, h, i, j):
        if causal:
            return (b, h, jnp.maximum(j, lax.div(i * block_k, block_q)), 0)
        return (b, h, j, 0)

    qblk_i = pl.BlockSpec((1, 1, block_q, Dh), q_i)
    qrow_i = pl.BlockSpec((1, 1, block_q, _LANE),
                          lambda b, h, i, j, _m=q_i: _m(b, h, i, j))
    kblk = pl.BlockSpec((1, 1, block_k, Dh), lambda b, h, i, j: (b, h, i, 0))

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, n_q=n_q),
        grid=(B, H, n_kv, n_q),
        in_specs=[qblk_i, kblk, kblk, qblk_i, qblk_i, qrow_i],
        out_specs=[kblk, kblk],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, Dh), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, Dh), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, Dh), jnp.float32),
            pltpu.VMEM((block_k, Dh), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, o, do, lse)
    return dq, dk, dv


# --------------------------------------------------------------------------
# blocked lax path (non-TPU backends)
# --------------------------------------------------------------------------


def _lax_flash(q, k, v, causal: bool, block_q: int, block_k: int):
    """The SAME blocked online-softmax algorithm as the Pallas forward,
    written in plain lax ops — the production non-TPU execution.  The
    backward differentiates through the scan (memory O(S^2 x Dh /
    block_k) — bounded by the block count, not linear like the Pallas
    kernel, but far below the einsum's O(S^2) score tensor and measured
    1.2-4x faster than the einsum grad step on CPU bench shapes).
    Operates on (B, S, H, Dh)."""
    B, S, H, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    nq, nk = S // block_q, S // block_k
    # (B, H, nblocks, block, Dh) f32 working layout
    qf = jnp.moveaxis(q, 2, 1).astype(jnp.float32).reshape(
        B, H, nq, block_q, Dh)
    kf = jnp.moveaxis(k, 2, 1).astype(jnp.float32).reshape(
        B, H, nk, block_k, Dh)
    vf = jnp.moveaxis(v, 2, 1).astype(jnp.float32).reshape(
        B, H, nk, block_k, Dh)
    # scan operand layout: KV block index leading
    ks = jnp.moveaxis(kf, 2, 0)  # (nk, B, H, block_k, Dh)
    vs = jnp.moveaxis(vf, 2, 0)

    def per_qblock(qi: int):
        qblk = qf[:, :, qi]  # (B, H, block_q, Dh)

        def body(carry, inp):
            m, l, acc = carry
            j, kblk, vblk = inp
            s = jnp.einsum("bhqd,bhkd->bhqk", qblk, kblk,
                           preferred_element_type=jnp.float32) * scale
            if causal:
                qpos = qi * block_q + jnp.arange(block_q)[:, None]
                kpos = j * block_k + jnp.arange(block_k)[None, :]
                s = jnp.where((qpos >= kpos)[None, None], s, _NEG_INF)
            m_new = jnp.maximum(m, s.max(-1, keepdims=True))
            p = jnp.exp(s - m_new)
            alpha = jnp.exp(m - m_new)
            l_new = alpha * l + p.sum(-1, keepdims=True)
            acc_new = acc * alpha + jnp.einsum(
                "bhqk,bhkd->bhqd", p, vblk,
                preferred_element_type=jnp.float32)
            return (m_new, l_new, acc_new), None

        # causal: blocks entirely above the diagonal are not scanned
        n_run = nk if not causal else min(
            nk, ((qi + 1) * block_q + block_k - 1) // block_k)
        m0 = jnp.full((B, H, block_q, 1), _NEG_INF, jnp.float32)
        l0 = jnp.zeros((B, H, block_q, 1), jnp.float32)
        a0 = jnp.zeros((B, H, block_q, Dh), jnp.float32)
        (m, l, acc), _ = lax.scan(
            body, (m0, l0, a0),
            (jnp.arange(n_run), ks[:n_run], vs[:n_run]))
        return acc / l

    out = jnp.stack([per_qblock(i) for i in range(nq)], axis=2)
    return jnp.moveaxis(out.reshape(B, H, S, Dh), 1, 2).astype(q.dtype)


# --------------------------------------------------------------------------
# dispatch + custom VJP
# --------------------------------------------------------------------------


def _pick_blocks(S: int, block_q: int = None, block_k: int = None):
    """Largest clean blocking <= default (or the requested sizes); None if
    S doesn't block.

    The halving loops always terminate at 1 (everything divides S), so the
    real fallback condition is a *minimum* block size: an awkward length
    like 2047 would otherwise run the kernel with (1, 1) tiles — B*H*S grid
    programs each doing an S-iteration loop over 1x1 tiles — instead of
    taking the intended XLA path.
    """
    bq = min(block_q or DEFAULT_BLOCK_Q, S)
    while bq > 1 and S % bq:
        bq //= 2
    bk = min(block_k or DEFAULT_BLOCK_K, S)
    while bk > 1 and S % bk:
        bk //= 2
    if bq < MIN_BLOCK or bk < MIN_BLOCK:
        return None
    return bq, bk


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, block_q, block_k):
    # primal (inference) path: no backward will consume an LSE, so the
    # kernel skips the (B, H, S, 128) LSE writes entirely
    blocks = _pick_blocks(q.shape[1], block_q, block_k)
    if blocks is None:
        return _xla_attention(q, k, v, causal=causal)
    bq, bk = blocks
    qt, kt, vt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v))
    out, _ = _flash_fwd(qt, kt, vt, causal, bq, bk, _interpret(),
                        with_lse=False)
    return jnp.moveaxis(out, 1, 2)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    blocks = _pick_blocks(q.shape[1], block_q, block_k)
    if blocks is None:
        return _xla_attention(q, k, v, causal=causal), (q, k, v, None, None)
    if FLASH_BWD_XLA_MIN_S is not None \
            and q.shape[1] >= FLASH_BWD_XLA_MIN_S:
        # env-armed escape hatch (see FLASH_BWD_XLA_MIN_S): flash
        # forward, gradients recomputed through the XLA path
        out = _flash_attention(q, k, v, causal, block_q, block_k)
        return out, (q, k, v, None, None)
    bq, bk = blocks
    # (B, S, H, Dh) -> (B, H, S, Dh) for clean per-(batch, head) blocking
    qt, kt, vt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v))
    out, lse = _flash_fwd(qt, kt, vt, causal, bq, bk, _interpret())
    out = jnp.moveaxis(out, 1, 2)
    # residual `out` is the SAME array that flows on as the activation, so
    # autodiff keeps one copy, not an extra (B, H, S, Dh) transpose.  The
    # kernel emits LSE broadcast across 128 lanes (TPU layout); keep only
    # one lane as the residual — the backward re-broadcasts — so the
    # forward-to-backward HBM cost stays O(S), not O(S * 128).
    return out, (q, k, v, out, lse[..., :1])


def _flash_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    if lse is None:  # non-blocking shapes: differentiate the XLA path
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal=causal),
            q, k, v,
        )
        return vjp(g)
    bq, bk = _pick_blocks(q.shape[1], block_q, block_k)
    qt, kt, vt, ot, gt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v, o, g))
    dq, dk, dv = _flash_bwd(qt, kt, vt, ot, lse, gt, causal, bq, bk,
                            _interpret())
    return tuple(jnp.moveaxis(t, 1, 2) for t in (dq, dk, dv))


_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = None, block_k: int = None):
    """Attention on ``(B, S, H, Dh)`` q/k/v (K/V already at H heads).

    ``block_q``/``block_k`` override the tile sizes; otherwise the
    persisted autotune cache (ops/autotune.py, keyed per head-dim /
    seq-bucket / dtype / platform) is consulted, falling back to the
    measured defaults: (128, 128), with ``block_k`` rising to 256 at
    S >= 8192 (results/flash_sweep_tpu_*: S=16384 grad step 184.5 ms at
    128/128 vs 165.9 ms at 128/256).  Larger KV blocks amortize
    per-block loop overhead when S is long and VMEM allows (q/k/v
    blocks + f32 accumulators must fit in ~16 MB).

    Dispatch: TPU runs the Pallas kernels; other backends run the same
    blocked algorithm as lax ops (``FORCE_PALLAS`` routes them through
    the kernels in interpreter mode — the parity-test configuration)."""
    # the kernel's grid is built from q's sequence length, so it only
    # supports self-attention shapes; differing K/V length (cross
    # attention) computes through the XLA path instead of silently
    # truncating keys past q.shape[1]
    if k.shape[1] != q.shape[1]:
        return _xla_attention(q, k, v, causal=causal)
    S, Dh = q.shape[1], q.shape[-1]
    if block_q is None and block_k is None:
        tuned = autotune.lookup(autotune.KIND_FLASH, Dh, S, q.dtype)
        if tuned:
            block_q, block_k = tuned
    if jax.default_backend() == "tpu" or FORCE_PALLAS:
        # block_k tiles the K/V sequence axis (== q's here)
        if block_k is None and S >= 8192 and S % 256 == 0:
            block_k = 256
        return _flash_attention(q, k, v, causal, block_q, block_k)
    blocks = _pick_blocks(S, block_q or LAX_DEFAULT_BLOCK,
                          block_k or LAX_DEFAULT_BLOCK)
    if blocks is None:
        return _xla_attention(q, k, v, causal=causal)
    bq, bk = blocks
    if block_q is None:
        # bound the unrolled q-block programs (trace/compile size):
        # double the q block while it still divides S — but never
        # second-guess a caller- or cache-pinned block_q, or the tuner
        # would record winners it didn't actually run
        while S // bq > 32 and S % (bq * 2) == 0:
            bq *= 2
    return _lax_flash(q, k, v, causal, bq, bk)

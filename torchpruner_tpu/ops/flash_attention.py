"""Flash attention for TPU — Pallas forward kernel with online softmax.

The hot op of the transformer families (ViT/BERT/Llama head pruning,
BASELINE.json configs 3-5).  The forward never materializes the ``(S, S)``
score matrix: the grid runs over ``(batch, heads, query blocks)`` and each
program streams KV blocks from VMEM with the numerically-stable running
``(max, sum, acc)`` update (Dao et al., 2022).  Matmuls are
``preferred_element_type=float32`` so bf16 inputs still accumulate in f32 on
the MXU.

The backward is a ``custom_vjp`` that recomputes attention with the XLA
einsum path and differentiates that — O(S^2) memory in the backward only.
Inputs whose shapes don't block cleanly (sequence not divisible by the block
size) fall back to the XLA path entirely; on CPU the kernel runs in
interpreter mode so tests exercise the same code path as TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128


def _xla_attention(q, k, v, *, causal: bool):
    """Reference einsum path on (B, S, H, Dh); also the backward's recompute."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum(
        "bshk,bthk->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        S = q.shape[1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    w = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthk->bshk", w, v)


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, *, scale, causal, block_q, block_k):
    """One (batch, head, query-block) program: stream KV blocks with the
    online-softmax running state carried through ``fori_loop``."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # (block_q, Dh)
    dh = q.shape[-1]
    S = k_ref.shape[2]
    n_kv = S // block_k
    if causal:
        # skip KV blocks entirely above the diagonal
        n_run = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        n_run = jnp.minimum(n_run, n_kv)
    else:
        n_run = n_kv

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, dh), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_run, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_fwd(q, k, v, causal, block_q, block_k, interpret):
    """(B, H, S, Dh) layout in, same out."""
    B, H, S, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    grid = (B, H, S // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
        out_shape=jax.ShapeDtypeStruct((B, H, S, Dh), q.dtype),
        interpret=interpret,
    )(q, k, v)


MIN_BLOCK = 8  # below this the kernel degrades to tiny-tile scalar work


def _pick_blocks(S: int):
    """Largest clean blocking <= default; None if S doesn't block.

    The halving loops always terminate at 1 (everything divides S), so the
    real fallback condition is a *minimum* block size: an awkward length
    like 2047 would otherwise run the kernel with (1, 1) tiles — B*H*S grid
    programs each doing an S-iteration loop over 1x1 tiles — instead of
    taking the intended XLA path.
    """
    bq = min(DEFAULT_BLOCK_Q, S)
    while bq > 1 and S % bq:
        bq //= 2
    bk = min(DEFAULT_BLOCK_K, S)
    while bk > 1 and S % bk:
        bk //= 2
    if bq < MIN_BLOCK or bk < MIN_BLOCK:
        return None
    return bq, bk


@functools.partial(jax.custom_vjp, nondiff_argnums=(3,))
def _flash_attention(q, k, v, causal):
    blocks = _pick_blocks(q.shape[1])
    if blocks is None:
        return _xla_attention(q, k, v, causal=causal)
    bq, bk = blocks
    interpret = jax.default_backend() != "tpu"
    # (B, S, H, Dh) -> (B, H, S, Dh) for clean per-(batch, head) blocking
    qt, kt, vt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v))
    out = _flash_fwd(qt, kt, vt, causal, bq, bk, interpret)
    return jnp.moveaxis(out, 1, 2)


def _flash_vjp_fwd(q, k, v, causal):
    return _flash_attention(q, k, v, causal), (q, k, v)


def _flash_vjp_bwd(causal, res, g):
    q, k, v = res
    _, vjp = jax.vjp(
        lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal=causal), q, k, v
    )
    return vjp(g)


_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False):
    """Attention on ``(B, S, H, Dh)`` q/k/v (K/V already at H heads)."""
    return _flash_attention(q, k, v, causal)

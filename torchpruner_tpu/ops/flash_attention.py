"""Flash attention for TPU — Pallas forward AND backward kernels.

The hot op of the transformer families (ViT/BERT/Llama head pruning,
BASELINE.json configs 3-5).  Neither direction ever materializes the
``(S, S)`` score matrix:

- **Forward** (Dao et al., 2022): the grid runs over ``(batch, heads,
  query blocks)``; each program streams KV blocks through VMEM with the
  numerically-stable running ``(max, sum, acc)`` update, and additionally
  writes the per-query log-sum-exp (LSE) used by the backward.
- **Backward** (FlashAttention-2): two kernels sharing the forward's LSE
  and the precomputed ``delta = rowsum(dO * O)``.  The dQ kernel runs over
  query blocks streaming KV; the dK/dV kernel runs over KV blocks streaming
  queries.  Probabilities are *recomputed* blockwise from LSE — O(S * Dh)
  memory total, vs the O(S^2) score tensor a recompute-through-XLA backward
  materializes.

Matmuls are ``preferred_element_type=float32`` so bf16 inputs still
accumulate in f32 on the MXU.  Causal masking skips whole blocks strictly
above (dQ) / below (dK/dV) the diagonal.  Inputs whose sequence length
doesn't block cleanly (min block 8) fall back to the XLA einsum path in
both directions; on CPU the kernels run in interpreter mode so tests
exercise the same code path as TPU.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

_NEG_INF = -1e30

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
MIN_BLOCK = 8  # below this the kernel degrades to tiny-tile scalar work
_LANE = 128  # TPU lane width: minor dim of the LSE/delta row layout

#: at/above this sequence length the flash BACKWARD kernel's remote
#: compilation fails on the tunnelled single-chip backend (HTTP 500 —
#: PERF.md flash S-sweep; the forward compiles and runs at 32k).  The
#: vjp then recomputes gradients through the XLA path instead, keeping
#: 32k-token training WORKING at quadratic temp cost in the backward
#: only.  Set to None to always use the flash backward (e.g. on a
#: directly-attached chip); multi-device 32k training should prefer
#: ring/Ulysses sequence parallelism (parallel/sp.py), which shards S
#: before attention ever sees the full length.
FLASH_BWD_XLA_MIN_S: int | None = 32768


def _xla_attention(q, k, v, *, causal: bool):
    """Reference einsum path on (B, S, H, Dh); also the non-blocking
    shapes' fallback (forward and, via autodiff, backward)."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum(
        "bshk,bthk->bhst", q, k, preferred_element_type=jnp.float32
    ) * scale
    if causal:
        Sq, Sk = q.shape[1], k.shape[1]
        # bottom-right-aligned causal mask: query i sees keys
        # j <= i + (Sk - Sq).  Equals tril for self-attention; for a
        # query chunk against a longer KV prefix (chunked prefill) the
        # chunk's last query sees the whole prefix.
        mask = (jnp.arange(Sk)[None, :]
                <= jnp.arange(Sq)[:, None] + (Sk - Sq))
        logits = jnp.where(mask[None, None], logits, _NEG_INF)
    w = jax.nn.softmax(logits, axis=-1).astype(v.dtype)
    return jnp.einsum("bhst,bthk->bshk", w, v)


# --------------------------------------------------------------------------
# forward kernel
# --------------------------------------------------------------------------


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref=None,
                *, scale, causal, block_q, block_k):
    """One (batch, head, query-block) program: stream KV blocks with the
    online-softmax running state carried through ``fori_loop``; emit the
    normalized output block and (when a backward will follow) its LSE
    row.  Inference calls omit ``lse_ref`` — no wasted HBM writes."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)  # (block_q, Dh)
    dh = q.shape[-1]
    S = k_ref.shape[2]
    n_kv = S // block_k
    if causal:
        # skip KV blocks entirely above the diagonal
        n_run = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        n_run = jnp.minimum(n_run, n_kv)
    else:
        n_run = n_kv

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_new = jnp.maximum(m, s.max(axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + p.sum(axis=1, keepdims=True)
        acc_new = acc * alpha + lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, dh), jnp.float32)
    m, l, acc = lax.fori_loop(0, n_run, body, (m0, l0, acc0))
    o_ref[0, 0] = (acc / l).astype(o_ref.dtype)
    if lse_ref is not None:
        # LSE row broadcast across the 128-lane minor dim: TPU block shapes
        # need the last two dims tileable to (sublane, lane), so a bare
        # (1, 1, block_q) block is not lowerable — same layout the
        # reference TPU kernel uses for its l/m outputs.
        lse_ref[0, 0] = jnp.broadcast_to(m + jnp.log(l), (block_q, _LANE))


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "interpret", "with_lse"),
)
def _flash_fwd(q, k, v, causal, block_q, block_k, interpret, with_lse=True):
    """(B, H, S, Dh) layout in; returns (out, lse) with lse (B, H, S, 128)
    f32 (the per-query LSE broadcast across the minor lane dim), or
    (out, None) when ``with_lse=False`` (inference: skip the LSE writes)."""
    B, H, S, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    grid = (B, H, S // block_q)
    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k,
    )
    out_specs = [
        pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
    ]
    out_shape = [jax.ShapeDtypeStruct((B, H, S, Dh), q.dtype)]
    if with_lse:
        out_specs.append(
            pl.BlockSpec((1, 1, block_q, _LANE), lambda b, h, i: (b, h, i, 0))
        )
        out_shape.append(
            jax.ShapeDtypeStruct((B, H, S, _LANE), jnp.float32)
        )
    res = pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0)),
            pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0)),
            pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        interpret=interpret,
    )(q, k, v)
    return (res[0], res[1]) if with_lse else (res[0], None)


# --------------------------------------------------------------------------
# backward kernels
# --------------------------------------------------------------------------


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               *, scale, causal, block_q, block_k):
    """One (batch, head, query-block) program: stream KV blocks,
    recompute P from LSE, accumulate dQ = sum_j dS_j K_j * scale."""
    qi = pl.program_id(2)
    q = q_ref[0, 0].astype(jnp.float32)       # (block_q, Dh)
    do = do_ref[0, 0].astype(jnp.float32)     # (block_q, Dh)
    lse = lse_ref[0, 0, :, 0:1]               # (block_q, 1)
    delta = delta_ref[0, 0, :, 0:1]           # (block_q, 1)
    dh = q.shape[-1]
    S = k_ref.shape[2]
    n_kv = S // block_k
    if causal:
        n_run = lax.div((qi + 1) * block_q + block_k - 1, block_k)
        n_run = jnp.minimum(n_run, n_kv)
    else:
        n_run = n_kv

    def body(j, dq):
        k = k_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, 0, pl.dslice(j * block_k, block_k), :].astype(jnp.float32)
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale
        if causal:
            qpos = qi * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = j * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)                          # masked rows -> 0
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        return dq + lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    dq = lax.fori_loop(0, n_run, body, jnp.zeros((block_q, dh), jnp.float32))
    dq_ref[0, 0] = dq.astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, *, scale, causal, block_q, block_k):
    """One (batch, head, KV-block) program: stream query blocks,
    recompute P from LSE, accumulate dV = sum_i P_i^T dO_i and
    dK = sum_i dS_i^T Q_i * scale."""
    ki = pl.program_id(2)
    k = k_ref[0, 0].astype(jnp.float32)       # (block_k, Dh)
    v = v_ref[0, 0].astype(jnp.float32)       # (block_k, Dh)
    dh = k.shape[-1]
    S = q_ref.shape[2]
    n_q = S // block_q
    # causal: the first query block whose last position reaches this KV
    # block's first position; earlier blocks are entirely masked
    i_start = lax.div(ki * block_k, block_q) if causal else 0

    def body(i, carry):
        dk, dv = carry
        q = q_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        do = do_ref[0, 0, pl.dslice(i * block_q, block_q), :].astype(jnp.float32)
        lse = lse_ref[0, 0, pl.dslice(i * block_q, block_q), 0:1]  # (bq, 1)
        delta = delta_ref[0, 0, pl.dslice(i * block_q, block_q), 0:1]
        s = lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale  # (block_q, block_k)
        if causal:
            qpos = i * block_q + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            kpos = ki * block_k + lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1
            )
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv = dv + lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * scale
        dk = dk + lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return dk, dv

    z = jnp.zeros((block_k, dh), jnp.float32)
    dk, dv = lax.fori_loop(i_start, n_q, body, (z, z))
    dk_ref[0, 0] = dk.astype(dk_ref.dtype)
    dv_ref[0, 0] = dv.astype(dv_ref.dtype)


@functools.partial(jax.jit, static_argnames=("causal", "block_q", "block_k", "interpret"))
def _flash_bwd(q, k, v, o, lse, do, causal, block_q, block_k, interpret):
    """(B, H, S, Dh) layout; returns (dq, dk, dv)."""
    B, H, S, Dh = q.shape
    scale = 1.0 / math.sqrt(Dh)
    # LSE arrives as the single-lane residual; restore the lane layout
    lse = jnp.broadcast_to(lse, (B, H, S, _LANE))
    # delta rows live in the same broadcast-across-lanes layout as LSE
    delta = jnp.broadcast_to(
        jnp.sum(
            do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1
        )[..., None],
        (B, H, S, _LANE),
    )

    seq_spec = pl.BlockSpec((1, 1, S, Dh), lambda b, h, i: (b, h, 0, 0))
    row_full = pl.BlockSpec((1, 1, S, _LANE), lambda b, h, i: (b, h, 0, 0))
    qblk = pl.BlockSpec((1, 1, block_q, Dh), lambda b, h, i: (b, h, i, 0))
    qrow = pl.BlockSpec((1, 1, block_q, _LANE), lambda b, h, i: (b, h, i, 0))
    kblk = pl.BlockSpec((1, 1, block_k, Dh), lambda b, h, i: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(B, H, S // block_q),
        in_specs=[qblk, seq_spec, seq_spec, qblk, qrow, qrow],
        out_specs=qblk,
        out_shape=jax.ShapeDtypeStruct((B, H, S, Dh), q.dtype),
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k),
        grid=(B, H, S // block_k),
        in_specs=[seq_spec, kblk, kblk, seq_spec, row_full, row_full],
        out_specs=[kblk, kblk],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, Dh), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, Dh), v.dtype),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


# --------------------------------------------------------------------------
# dispatch + custom VJP
# --------------------------------------------------------------------------


def _pick_blocks(S: int, block_q: int = None, block_k: int = None):
    """Largest clean blocking <= default (or the requested sizes); None if
    S doesn't block.

    The halving loops always terminate at 1 (everything divides S), so the
    real fallback condition is a *minimum* block size: an awkward length
    like 2047 would otherwise run the kernel with (1, 1) tiles — B*H*S grid
    programs each doing an S-iteration loop over 1x1 tiles — instead of
    taking the intended XLA path.
    """
    bq = min(block_q or DEFAULT_BLOCK_Q, S)
    while bq > 1 and S % bq:
        bq //= 2
    bk = min(block_k or DEFAULT_BLOCK_K, S)
    while bk > 1 and S % bk:
        bk //= 2
    if bq < MIN_BLOCK or bk < MIN_BLOCK:
        return None
    return bq, bk


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_attention(q, k, v, causal, block_q, block_k):
    # primal (inference) path: no backward will consume an LSE, so the
    # kernel skips the (B, H, S, 128) LSE writes entirely
    blocks = _pick_blocks(q.shape[1], block_q, block_k)
    if blocks is None:
        return _xla_attention(q, k, v, causal=causal)
    bq, bk = blocks
    qt, kt, vt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v))
    out, _ = _flash_fwd(qt, kt, vt, causal, bq, bk, _interpret(),
                        with_lse=False)
    return jnp.moveaxis(out, 1, 2)


def _flash_vjp_fwd(q, k, v, causal, block_q, block_k):
    blocks = _pick_blocks(q.shape[1], block_q, block_k)
    if blocks is None:
        return _xla_attention(q, k, v, causal=causal), (q, k, v, None, None)
    if FLASH_BWD_XLA_MIN_S is not None \
            and q.shape[1] >= FLASH_BWD_XLA_MIN_S:
        # flash FORWARD (compiles and runs at 32k — 58.4 ms, 0 MB temp,
        # PERF.md S-sweep), but the backward kernel's remote compilation
        # 500s on the tunnelled backend at this length; hand the vjp the
        # lse=None residual so the backward recomputes through the XLA
        # path — 32k-token training works at XLA's quadratic temp cost
        # in the backward only (measured viable: 121.7 ms / 13.3 GB).
        out = _flash_attention(q, k, v, causal, block_q, block_k)
        return out, (q, k, v, None, None)
    bq, bk = blocks
    # (B, S, H, Dh) -> (B, H, S, Dh) for clean per-(batch, head) blocking
    qt, kt, vt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v))
    out, lse = _flash_fwd(qt, kt, vt, causal, bq, bk, _interpret())
    out = jnp.moveaxis(out, 1, 2)
    # residual `out` is the SAME array that flows on as the activation, so
    # autodiff keeps one copy, not an extra (B, H, S, Dh) transpose.  The
    # kernel emits LSE broadcast across 128 lanes (TPU layout); keep only
    # one lane as the residual — the backward re-broadcasts — so the
    # forward-to-backward HBM cost stays O(S), not O(S * 128).
    return out, (q, k, v, out, lse[..., :1])


def _flash_vjp_bwd(causal, block_q, block_k, res, g):
    q, k, v, o, lse = res
    if lse is None:  # non-blocking shapes: differentiate the XLA path
        _, vjp = jax.vjp(
            lambda q_, k_, v_: _xla_attention(q_, k_, v_, causal=causal),
            q, k, v,
        )
        return vjp(g)
    bq, bk = _pick_blocks(q.shape[1], block_q, block_k)
    qt, kt, vt, ot, gt = (jnp.moveaxis(t, 2, 1) for t in (q, k, v, o, g))
    dq, dk, dv = _flash_bwd(qt, kt, vt, ot, lse, gt, causal, bq, bk,
                            _interpret())
    return tuple(jnp.moveaxis(t, 1, 2) for t in (dq, dk, dv))


_flash_attention.defvjp(_flash_vjp_fwd, _flash_vjp_bwd)


def flash_attention(q, k, v, *, causal: bool = False,
                    block_q: int = None, block_k: int = None):
    """Attention on ``(B, S, H, Dh)`` q/k/v (K/V already at H heads).

    ``block_q``/``block_k`` override the tile sizes — larger KV blocks
    amortize per-block loop overhead when S is long and VMEM allows
    (q/k/v blocks + f32 accumulators must fit in ~16 MB).  Defaults:
    (128, 128), except ``block_k`` rises to 256 at S >= 8192 — the
    measured on-chip optimum (results/flash_sweep_tpu_*: S=16384 grad
    step 184.5 ms at 128/128 vs 165.9 ms at 128/256)."""
    # the kernel's grid is built from q's sequence length, so it only
    # supports self-attention shapes; differing K/V length (cross
    # attention) computes through the XLA path instead of silently
    # truncating keys past q.shape[1]
    if k.shape[1] != q.shape[1]:
        return _xla_attention(q, k, v, causal=causal)
    # block_k tiles the K/V sequence axis (== q's here)
    if block_k is None and k.shape[1] >= 8192 and k.shape[1] % 256 == 0:
        block_k = 256
    return _flash_attention(q, k, v, causal, block_q, block_k)

"""Kernel smoke bench: parity asserts + timed micro-measurements that
export ``kernel_*`` gate scalars.

CI's per-kernel regression gate needs numbers that exist on every run,
on CPU, in seconds — the profiler's per-op tables cover workloads, but
the NEW kernels (flash retune, decode attention, block-sparse, fused
dequant) deserve a direct harness: each kernel is timed around its
jitted call on a small fixed shape set, asserted against its reference
path, and exported as ``kernel_<name>_ms`` / ``kernel_<name>_speedup_*``
gauges into the obs session — which land in ``report.json`` and ride
``obs diff --gate`` exactly like the profiler's dynamic kernel scalars
(results/obs_gates_profile_ci.json, golden
results/obs_report_golden_kernels_cpu.json).

Also the autotune round-trip check: a tune is recorded, the in-memory
cache dropped, and the persisted JSON must serve the same blocks back
(the tune→persist→reload contract that makes tuning a one-time cost).

Test hook: ``TORCHPRUNER_KERNEL_PLANT_BLOCK=<n>`` forces the
block-sparse measurement onto that block edge — planting a REAL
regression (pathological tiling) that the kernel gate must catch; CI
drills it.

Run: ``python -m torchpruner_tpu.ops.kernel_bench [--smoke]
[--obs-dir DIR]``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

from torchpruner_tpu.ops.autotune import _time_ms


def _flash_rows(smoke: bool, iters: int) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops import flash_attention as F

    # S=1024/Dh64 even for smoke: the einsum's S^2 f32 scores fall out
    # of cache there, so the blocked path's win is decisive (~4x) and
    # the speedup gauge is stable enough to gate; smaller S is noise
    B, S, H, Dh = (1, 1024, 4, 64) if smoke else (2, 2048, 4, 64)
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q, k, v = (jax.random.normal(kk, (B, S, H, Dh), jnp.bfloat16)
               for kk in ks)

    def grad_of(fn):
        def loss(q_, k_, v_):
            return jnp.sum(fn(q_, k_, v_, causal=True).astype(jnp.float32))
        return jax.jit(jax.grad(loss, argnums=(0, 1, 2)))

    flash_ms = _time_ms(grad_of(F.flash_attention), q, k, v, iters=iters)
    xla_ms = _time_ms(grad_of(F._xla_attention), q, k, v, iters=iters)
    # parity through the interpret-mode Pallas kernels (tiny shape):
    # tier-1's guarantee that the real kernel code ran today
    qs, ks_, vs = (t[:, :64] for t in (q, k, v))
    prev, F.FORCE_PALLAS = F.FORCE_PALLAS, True
    try:
        got = F.flash_attention(qs, ks_, vs, causal=True)
    finally:
        F.FORCE_PALLAS = prev
    ref = F._xla_attention(qs, ks_, vs, causal=True)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(ref, np.float32),
        atol=3e-2)
    return {
        "flash_grad_ms": round(flash_ms, 3),
        "flash_xla_grad_ms": round(xla_ms, 3),
        "flash_speedup_vs_xla": round(xla_ms / flash_ms, 3),
        "shape": f"B{B} S{S} H{H} Dh{Dh} bf16 causal",
    }


def _decode_rows(smoke: bool, iters: int) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops import decode_attention as DA

    B, T, H, Dh = (2, 128, 2, 16) if smoke else (8, 1024, 8, 64)
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = jax.random.normal(ks[0], (B, 1, H, Dh))
    kc = jax.random.normal(ks[1], (B, T, H, Dh))
    vc = jax.random.normal(ks[2], (B, T, H, Dh))
    pos = jnp.asarray([(i * T) // (B + 1) + 3 for i in range(B)], jnp.int32)
    kern = jax.jit(DA.decode_attention)
    ref = jax.jit(DA.xla_decode_attention)
    got, want = kern(q, kc, vc, pos), ref(q, kc, vc, pos)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=1e-5)
    return {
        "decode_ms": round(_time_ms(kern, q, kc, vc, pos, iters=iters), 3),
        "decode_xla_ms": round(
            _time_ms(ref, q, kc, vc, pos, iters=iters), 3),
        "decode_block": DA.decode_block(T),
        "shape": f"B{B} T{T} H{H} Dh{Dh}",
    }


def _blocksparse_rows(smoke: bool, iters: int) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops import blocksparse as BS

    block = int(os.environ.get("TORCHPRUNER_KERNEL_PLANT_BLOCK", 0)) \
        or (64 if smoke else 128)
    R, D, F = (128, 512, 512) if smoke else (512, 2048, 2048)
    x = jax.random.normal(jax.random.PRNGKey(2), (R, D), jnp.bfloat16)
    w = np.array(
        jax.random.normal(jax.random.PRNGKey(3), (D, F)), np.float32)
    # 50% structured sparsity on both axes, block-aligned
    in_keep = tuple(range(0, D // block, 2))
    out_keep = tuple(range(0, F // block, 2))
    for b in range(D // block):
        if b not in in_keep:
            w[b * block:(b + 1) * block] = 0
    for b in range(F // block):
        if b not in out_keep:
            w[:, b * block:(b + 1) * block] = 0
    wb = jnp.asarray(w, jnp.bfloat16)

    sparse = jax.jit(lambda x_, w_: BS.blocksparse_matmul(
        x_, w_, in_keep=in_keep, out_keep=out_keep, block=block))
    dense_kernel = jax.jit(lambda x_, w_: BS.blocksparse_matmul(
        x_, w_, block=block))  # all blocks: same machinery, no skipping
    dense_xla = jax.jit(lambda x_, w_: x_ @ w_)
    got, want = sparse(x, wb), dense_xla(x, wb)
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(want, np.float32),
                               atol=0.5, rtol=0.05)  # bf16 sums
    s_ms = _time_ms(sparse, x, wb, iters=iters)
    d_ms = _time_ms(dense_kernel, x, wb, iters=iters)
    return {
        "blocksparse_ms": round(s_ms, 3),
        "blocksparse_dense_ms": round(d_ms, 3),
        "blocksparse_speedup_vs_dense": round(d_ms / s_ms, 3),
        "blocksparse_xla_dense_ms": round(
            _time_ms(dense_xla, x, wb, iters=iters), 3),
        "block": block,
        "shape": f"R{R} D{D} F{F} 50% blocks",
    }


def _dequant_rows(smoke: bool, iters: int) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops.fused_matmul import dequant_matmul
    from torchpruner_tpu.ops.int4_matmul import quantize_int4, unpack_int4
    from torchpruner_tpu.ops.quant import quantize_tensor

    B, D, F = (4, 256, 256) if smoke else (8, 2048, 2048)
    rng = np.random.default_rng(0)
    w = jnp.asarray(rng.normal(size=(D, F)).astype(np.float32))
    x = jnp.asarray(rng.normal(size=(B, D)).astype(np.float32))
    qt = quantize_tensor(w, in_axes=1)
    p4, s4 = quantize_int4(w)
    k8 = jax.jit(lambda x_, q_, s_: dequant_matmul(x_, q_, s_, bits=8))
    k4 = jax.jit(lambda x_, q_, s_: dequant_matmul(x_, q_, s_, bits=4))
    got8 = k8(x, qt.q, qt.out_scale())
    ref8 = jnp.dot(x.astype(jnp.bfloat16), qt.q.astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) \
        * qt.out_scale()[None]
    np.testing.assert_allclose(np.asarray(got8), np.asarray(ref8),
                               rtol=1e-4, atol=1e-3)
    got4 = k4(x, p4, s4)
    ref4 = jnp.dot(x.astype(jnp.bfloat16),
                   unpack_int4(p4).astype(jnp.bfloat16),
                   preferred_element_type=jnp.float32) * s4[None]
    np.testing.assert_allclose(np.asarray(got4), np.asarray(ref4),
                               rtol=1e-4, atol=1e-3)
    return {
        "dequant_int8_ms": round(
            _time_ms(k8, x, qt.q, qt.out_scale(), iters=iters), 3),
        "dequant_int4_ms": round(_time_ms(k4, x, p4, s4, iters=iters), 3),
        "shape": f"B{B} D{D} F{F}",
    }


def _autotune_roundtrip(smoke: bool) -> dict:
    """Tune a tiny flash shape, drop the in-memory cache, and require
    the persisted JSON to serve the same blocks back."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.ops import autotune
    from torchpruner_tpu.ops import flash_attention as F

    S, Dh = 256, 16
    ks = jax.random.split(jax.random.PRNGKey(4), 3)
    q, k, v = (jax.random.normal(kk, (1, S, 2, Dh)) for kk in ks)

    def run(blocks):
        bq, bk = blocks
        fn = jax.jit(lambda a, b, c: F.flash_attention(
            a, b, c, causal=True, block_q=bq, block_k=bk))
        return lambda: fn(q, k, v)

    blocks = autotune.autotune(
        autotune.KIND_FLASH, Dh, S, q.dtype, run=run,
        candidates=((64, 64), (128, 128), (64, 128)),
        defaults=(128, 128), force=True, iters=2, warmup=1)
    autotune.reset()  # force a reload from the persisted JSON
    reloaded = autotune.lookup(autotune.KIND_FLASH, Dh, S, q.dtype)
    assert reloaded == tuple(blocks), (reloaded, blocks)
    path = autotune.cache_path()
    assert os.path.exists(path), path
    with open(path) as f:
        entries = json.load(f)
    return {"tuned_blocks": list(blocks), "cache_path": path,
            "cache_entries": len(entries)}


def run(smoke: bool = False, obs_dir: str | None = None,
        iters: int | None = None) -> dict:
    from torchpruner_tpu import obs

    iters = iters or (3 if smoke else 5)
    session = obs.configure(obs_dir) if obs_dir else None
    out = {"smoke": smoke}
    try:
        with obs.span("kernel_bench"):
            out["autotune"] = _autotune_roundtrip(smoke)
            out["flash"] = _flash_rows(smoke, iters)
            out["decode"] = _decode_rows(smoke, iters)
            out["blocksparse"] = _blocksparse_rows(smoke, iters)
            out["dequant"] = _dequant_rows(smoke, iters)
        for section in ("flash", "decode", "blocksparse", "dequant"):
            for key, val in out[section].items():
                if (isinstance(val, (int, float))
                        and not key.endswith("block")):
                    obs.gauge_set(
                        f"kernel_{key}", float(val),
                        help="ops/kernel_bench micro-measurement")
    finally:
        if session is not None:
            session.close()
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--smoke", action="store_true")
    ap.add_argument("--obs-dir", default="")
    ap.add_argument("--iters", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args(argv)
    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")
    out = run(smoke=args.smoke, obs_dir=args.obs_dir or None,
              iters=args.iters or None)
    print(json.dumps(out, indent=1))
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Kernel block-size autotuner with a persisted tuning cache.

The flash/decode/matmul kernels' block sizes trade per-block loop
overhead against VMEM residency, and the optimum moves with head dim,
sequence length, and dtype (results/flash_sweep_tpu_*: S=16384 grad
step 184.5 ms at 128/128 vs 165.9 ms at 128/256).  Hand-pinned
constants lose that fight one shape at a time — the round-4 bench had
the flash kernel at 0.983x XLA precisely because its tiles were tuned
for a different S.  This module makes the choice a *measured* one:

- ``lookup(kind, head_dim, S, dtype)`` consults a JSON tuning cache
  keyed per ``(kind, head_dim, seq bucket, dtype, platform)``; a miss
  returns None and the caller's heuristic defaults apply.
- ``autotune(...)`` times a candidate grid through the caller's real
  dispatch path (the same jitted fn the workload runs), records the
  winner, and persists the cache.
- The cache file lives NEXT TO the jax persistent compile cache
  (``<compile-cache-dir>/pallas_autotune.json``; override with
  ``TORCHPRUNER_TUNE_CACHE``) — the two caches share a lifecycle: both
  are per-machine measured artifacts that make repeated shapes cheap.

On non-TPU backends the kernels run in interpreter mode, where block
timing measures the interpreter, not the hardware — so ``autotune``
records the interpreter-mode DEFAULTS instead of timing unless
``force=True`` (tests force it to exercise the full tune→persist→load
round trip on a tiny shape set).
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Callable, Dict, Optional, Sequence, Tuple

ENV_VAR = "TORCHPRUNER_TUNE_CACHE"

#: kernel families the cache distinguishes (callers may add their own)
KIND_FLASH = "flash"        # fused train attention, fwd+bwd grad step
KIND_FLASH_FWD = "flash_fwd"  # inference-only forward
KIND_DECODE = "decode"      # q_len=1 paged-KV decode attention
KIND_MATMUL = "matmul"      # block-sparse / dequant matmul tiles

_lock = threading.Lock()
_cache: Optional[Dict[str, dict]] = None
_cache_file: Optional[str] = None


def cache_path() -> str:
    """The tuning-cache JSON location: ``$TORCHPRUNER_TUNE_CACHE`` if
    set, else ``pallas_autotune.json`` next to the jax persistent
    compile cache (falling back to the compile cache's own default
    directory when jax has no cache dir configured)."""
    env = os.environ.get(ENV_VAR)
    if env:
        return env
    cache_dir = None
    try:
        import jax

        cache_dir = jax.config.jax_compilation_cache_dir
    except Exception:  # noqa: BLE001 - config shape varies across jax
        cache_dir = None
    if not cache_dir:
        from torchpruner_tpu.utils.compilation_cache import _DEFAULT

        cache_dir = _DEFAULT
    return os.path.join(cache_dir, "pallas_autotune.json")


def seq_bucket(S: int) -> int:
    """Power-of-two sequence bucket in [256, 65536] — shapes inside one
    bucket share a tuning entry (and, with width bucketing, a bounded
    compile bill)."""
    b = 256
    while b < S and b < 65536:
        b *= 2
    return b


def _key(kind: str, head_dim: int, S: int, dtype, platform: str) -> str:
    import jax.numpy as jnp

    return (f"{kind}:dh{int(head_dim)}:s{seq_bucket(int(S))}"
            f":{jnp.dtype(dtype).name}:{platform}")


def _platform() -> str:
    import jax

    return jax.default_backend()


def _load(path: str) -> Dict[str, dict]:
    try:
        with open(path) as f:
            data = json.load(f)
        return data if isinstance(data, dict) else {}
    except (OSError, ValueError):
        return {}


def _entries() -> Dict[str, dict]:
    """The in-memory cache, loaded once per (process, cache file)."""
    global _cache, _cache_file
    path = cache_path()
    with _lock:
        if _cache is None or _cache_file != path:
            _cache = _load(path)
            _cache_file = path
        return _cache


def reset() -> None:
    """Drop the in-memory view (tests switch cache files via env)."""
    global _cache, _cache_file
    with _lock:
        _cache, _cache_file = None, None


def lookup(kind: str, head_dim: int, S: int, dtype,
           platform: Optional[str] = None) -> Optional[Tuple[int, ...]]:
    """The tuned block sizes for this shape family, or None (caller
    defaults apply)."""
    entry = _entries().get(
        _key(kind, head_dim, S, dtype, platform or _platform()))
    if not entry:
        return None
    blocks = entry.get("blocks")
    return tuple(int(b) for b in blocks) if blocks else None


def record(kind: str, head_dim: int, S: int, dtype,
           blocks: Sequence[int], *, ms: Optional[float] = None,
           platform: Optional[str] = None, persist: bool = True) -> str:
    """Store (and by default persist) a tuning decision; returns the
    cache key.  Writes are atomic (tmp + replace) so a killed tune run
    cannot tear the file for later readers."""
    key = _key(kind, head_dim, S, dtype, platform or _platform())
    entries = _entries()
    with _lock:
        entries[key] = {
            "blocks": [int(b) for b in blocks],
            "ms": None if ms is None else round(float(ms), 4),
            "at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
        }
        if persist:
            path = cache_path()
            try:
                os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    json.dump(entries, f, indent=1, sort_keys=True)
                os.replace(tmp, path)
            except OSError:
                pass  # the cache is an optimization, never a failure
    return key


def _time_ms(fn: Callable, *args, iters: int = 5, warmup: int = 2) -> float:
    import jax

    for _ in range(warmup):
        jax.block_until_ready(fn(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters * 1e3


def autotune(kind: str, head_dim: int, S: int, dtype, *,
             run: Callable[[Tuple[int, ...]], Callable],
             candidates: Sequence[Tuple[int, ...]],
             defaults: Tuple[int, ...],
             force: bool = False, iters: int = 5,
             warmup: int = 2) -> Tuple[int, ...]:
    """Measure ``run(blocks)()`` for each candidate, record the winner.

    ``run`` maps a block tuple to a zero-arg (pre-bound) callable that
    executes the kernel-bearing computation; a candidate that raises is
    skipped (e.g. tiles that overflow VMEM fail at compile time — that
    is the tuner's job to discover, not the caller's to predict).  On
    non-TPU backends without ``force``, records and returns
    ``defaults`` (interpreter timing is meaningless).
    """
    if _platform() != "tpu" and not force:
        record(kind, head_dim, S, dtype, defaults)
        return defaults
    best: Optional[Tuple[int, ...]] = None
    best_ms = float("inf")
    for cand in candidates:
        try:
            fn = run(tuple(int(c) for c in cand))
            ms = _time_ms(fn, iters=iters, warmup=warmup)
        except Exception:  # noqa: BLE001 - un-lowerable candidate
            continue
        if ms < best_ms:
            best, best_ms = tuple(int(c) for c in cand), ms
    if best is None:
        record(kind, head_dim, S, dtype, defaults)
        return defaults
    record(kind, head_dim, S, dtype, best, ms=best_ms)
    return best

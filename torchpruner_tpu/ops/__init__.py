"""TPU kernels and fused ops (Pallas where warranted, XLA otherwise)."""

"""TPU kernels and fused ops (Pallas where warranted, XLA otherwise).

- flash_attention: train-shaped fused attention (Pallas fwd+bwd on TPU,
  blocked lax elsewhere), block sizes from the autotune cache
- decode_attention: q_len=1 paged-KV decode kernel (serve/generate)
- blocksparse: MXU-aligned block-sparse matmul over pruned-block masks
- fused_matmul: int8/int4 dequant-in-VMEM matmul with fused scale
- quant: weight-only QTensor quantization + the qdot dispatch hub
- autotune: per-(kind, head-dim, seq-bucket, dtype) persisted tuning
"""

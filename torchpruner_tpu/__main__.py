"""Command-line driver: ``python -m torchpruner_tpu``.

The CLI the reference never had (its drivers are notebooks and a phantom
``args`` object — SURVEY.md §2.8, §5.6).  Runs a named preset or a JSON
config through the prune-retrain loop or the layerwise-robustness sweep.

Examples::

    python -m torchpruner_tpu --preset llama3_ffn_taylor --smoke
    python -m torchpruner_tpu --config my_experiment.json
    python -m torchpruner_tpu --list
    python -m torchpruner_tpu --lint llama3_ffn_taylor
    python -m torchpruner_tpu --lint my_experiment.json --lint-plan plan.json
    python -m torchpruner_tpu vgg16_layerwise --plan auto --plan-probe 2
    python -m torchpruner_tpu vgg16_layerwise --plan report
    python -m torchpruner_tpu serve llama3_ffn_taylor --smoke --synthetic 16
    python -m torchpruner_tpu fleet llama_tiny --cpu --replicas 3 --synthetic 18
    python -m torchpruner_tpu search digits_smoke --jobs 2
    python -m torchpruner_tpu lint-host torchpruner_tpu/
    python -m torchpruner_tpu obs report logs/fleet/obs   # latency budget
    python -m torchpruner_tpu obs report logs/obs
    python -m torchpruner_tpu obs watch logs/obs       # live time-series
    python -m torchpruner_tpu obs incident logs/fleet/obs  # postmortem
    python -m torchpruner_tpu --preset mnist_mlp_shapley --smoke \\
        --obs-dir logs/obs --profile-every 20
    python -m torchpruner_tpu obs profile logs/obs
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "obs":
        # ledger tooling: `python -m torchpruner_tpu obs report DIR` /
        # `obs diff A B [--gate tolerances.json]` / `obs watch DIR`
        # (obs.report; watch renders the live time-series)
        from torchpruner_tpu.obs.report import obs_main

        return obs_main(argv[1:])
    if argv and argv[0] == "serve":
        # continuous-batching inference engine on the pruned decode path
        # (serve.frontend): `python -m torchpruner_tpu serve <preset>
        # [--synthetic N | --http PORT | --stdin] ...`
        from torchpruner_tpu.serve.frontend import serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "fleet":
        # fault-tolerant multi-replica serving plane (fleet.frontend):
        # `python -m torchpruner_tpu fleet <preset> --replicas 3
        # [--synthetic N | --http PORT] ...` — health-checked router
        # over N serve replicas, durable request journal, kill -9
        # failover drills
        from torchpruner_tpu.fleet.frontend import fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "lint-host":
        # tpu-lint pass 6 standalone: `python -m torchpruner_tpu
        # lint-host [paths ...] [--waivers FILE] [--json OUT]` — the
        # host-side concurrency/durability scan needs no preset, no
        # model, no XLA, so CI can run it against the whole package
        from torchpruner_tpu.analysis.host_lint import host_lint_main

        return host_lint_main(argv[1:])
    if argv and argv[0] == "search":
        # Pareto sparsity-search campaign driver (search.driver):
        # `python -m torchpruner_tpu search <campaign> [--jobs N]
        # [--campaign-dir DIR]` — concurrent prune-retrain trials with
        # cost-model pre-pricing, dominance early-stop, and a resumable
        # frontier.json artifact
        from torchpruner_tpu.search.driver import search_main

        return search_main(argv[1:])
    p = argparse.ArgumentParser(
        prog="torchpruner_tpu",
        description="TPU-native structured pruning experiments "
                    "(subcommands: obs report/diff/watch — run-ledger tooling; "
                    "serve — continuous-batching inference engine; "
                    "fleet — fault-tolerant multi-replica serving plane; "
                    "search — Pareto sparsity-search campaign driver; "
                    "lint-host — host-side concurrency/durability "
                    "lint, no preset needed)",
    )
    p.add_argument(
        "target", nargs="?", default=None,
        help="preset name or config JSON path (positional shorthand "
             "for --preset / --config; e.g. `python -m torchpruner_tpu "
             "vgg16_layerwise --plan auto`)",
    )
    p.add_argument("--preset", help="named preset (see --list)")
    p.add_argument("--config", help="path to an ExperimentConfig JSON")
    p.add_argument(
        "--smoke", action="store_true",
        help="miniature model/data variants (CPU-friendly smoke run)",
    )
    p.add_argument(
        "--cpu", action="store_true", help="force the CPU backend"
    )
    p.add_argument(
        "--list", action="store_true", help="list presets and exit"
    )
    p.add_argument(
        "--lint", metavar="PRESET_OR_JSON", nargs="?", const="",
        default=None,
        help="run the tpu-lint static analyzer (plan / sharding / jaxpr "
             "passes, CPU-only abstract evaluation) over a preset name or "
             "config JSON path — or over --preset/--config when given "
             "bare — print the findings report, and exit nonzero on "
             "error-severity findings",
    )
    p.add_argument(
        "--lint-plan", metavar="PATH",
        help="with --lint: validate this JSON-serialized PrunePlan "
             "against the config's model instead of the graph-derived "
             "groups (see core.plan.plan_to_dict for the schema)",
    )
    p.add_argument(
        "--plan", choices=("auto", "report"), default=None,
        help="auto-parallelism planner (analysis/planner.py): 'auto' "
             "searches mesh shape × zero/fsdp/tp × batch × accum × "
             "remat for the config's model, prices every candidate "
             "with the static cost model (predicted step time + HBM "
             "watermark), discards over-budget or lint-failing "
             "candidates loudly, and prints the ranked table; 'report' "
             "re-renders a previously written plan artifact",
    )
    p.add_argument(
        "--plan-probe", metavar="K", type=int, default=0,
        help="with --plan auto: validate the top-K candidates with "
             "short measured probes (a real trainer stepped a few "
             "times), drift-gated against the prediction",
    )
    p.add_argument(
        "--plan-out", metavar="PATH",
        help="plan artifact path (default logs/plan_<config>.json); "
             "--plan report reads the same path",
    )
    p.add_argument(
        "--plan-devices", metavar="N", type=int, default=None,
        help="with --plan auto: target device count to plan for "
             "(default: the config mesh's size, else this host's "
             "device count)",
    )
    p.add_argument(
        "--no-compilation-cache", action="store_true",
        help="disable the persistent XLA compilation cache",
    )
    p.add_argument(
        "--profile", metavar="DIR",
        help="capture a jax.profiler trace of the run into DIR "
             "(view in XProf/TensorBoard)",
    )
    p.add_argument(
        "--obs-dir", metavar="DIR",
        help="write runtime telemetry into DIR: events.jsonl (span/phase "
             "stream), metrics.prom (Prometheus textfile), ledger.jsonl "
             "+ report.json (per-round prune provenance; see `obs "
             "report`), and trace.json (open in ui.perfetto.dev); the "
             "end-of-run summary prints to stderr either way",
    )
    p.add_argument(
        "--no-obs", action="store_true",
        help="disable runtime telemetry entirely (no spans, no step "
             "metrics, no compile accounting, no summary)",
    )
    p.add_argument(
        "--profile-every", metavar="N", type=int, default=None,
        help="with --obs-dir: continuous kernel profiling — open a "
             "jax.profiler capture window every N recorded steps; the "
             "windows land in <obs-dir>/profile/ and render with "
             "`obs profile <obs-dir>` (ranked per-kernel step-time "
             "table, roofline positions, HBM watermarks)",
    )
    p.add_argument(
        "--profile-steps", metavar="K", type=int, default=None,
        help="steps per capture window (default 3)",
    )
    p.add_argument(
        "--dump-config", metavar="PATH",
        help="write the resolved config JSON to PATH and exit",
    )
    p.add_argument(
        "--resume", metavar="DIR",
        help="resilient run directory (resilience.RunManifest + "
             "digest-verified checkpoints): a fresh DIR starts a "
             "preemption-safe run recording into it; an existing one "
             "resumes mid-round (train: exact epoch/step/data-cursor; "
             "prune_retrain: mid-retrain of the interrupted target; "
             "robustness: first unfinished layer)",
    )
    p.add_argument(
        "--checkpoint-every", metavar="N", type=int, default=None,
        help="with --resume: checkpoint every N optimizer steps "
             "(prune_retrain: additionally after every retrain epoch); "
             "default 0 = round/epoch boundaries only",
    )
    p.add_argument(
        "--chaos", metavar="JSON_OR_PATH",
        help="deterministic fault injection (resilience.chaos), e.g. "
             "'{\"nan_at_step\": 5, \"kill_at_step\": 12}' — for "
             "recovery-path testing; also via TORCHPRUNER_CHAOS env",
    )
    p.add_argument(
        "--zero", action="store_true",
        help="ZeRO-style cross-replica weight-update sharding on the "
             "configured mesh's data axis (cfg.zero override): optimizer "
             "state shards 1/N per chip, gradients reduce-scatter, the "
             "update applies locally, params all-gather — needs a mesh "
             "with a 'data' axis in the config",
    )
    args = p.parse_args(argv)

    if args.target:
        # positional shorthand: `python -m torchpruner_tpu <preset>`
        if args.preset or args.config:
            p.error("give the experiment either positionally or via "
                    "--preset/--config, not both")
        if args.target.endswith(".json"):
            args.config = args.target
        else:
            args.preset = args.target
    if args.lint_plan and args.lint is None:
        p.error("--lint-plan only makes sense together with --lint")
    if args.plan is not None and args.lint is not None:
        p.error("--plan and --lint are separate modes — run them "
                "one at a time")
    if args.plan is None and (args.plan_probe or args.plan_out
                              or args.plan_devices):
        p.error("--plan-probe/--plan-out/--plan-devices only make "
                "sense together with --plan")
    if args.obs_dir and args.no_obs:
        p.error("--obs-dir and --no-obs are mutually exclusive")
    if args.profile_every is not None and not args.obs_dir:
        p.error("--profile-every needs --obs-dir (the capture windows "
                "live under it)")

    if args.list:
        from torchpruner_tpu.experiments.presets import PRESETS

        for name, fn in PRESETS.items():
            print(f"{name:26s} {fn.__doc__.splitlines()[0]}")
        return 0

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    if not args.no_compilation_cache:
        from torchpruner_tpu.utils.compilation_cache import (
            enable_persistent_cache,
        )

        enable_persistent_cache()

    from torchpruner_tpu.utils.config import ExperimentConfig

    if args.lint is not None and args.lint:
        # --lint <preset-name-or-config-path> names its own target
        if args.lint.endswith(".json"):
            cfg = ExperimentConfig.from_json(args.lint)
        else:
            from torchpruner_tpu.experiments.presets import get_preset

            cfg = get_preset(args.lint, smoke=args.smoke)
    elif args.config:
        cfg = ExperimentConfig.from_json(args.config)
    elif args.preset:
        from torchpruner_tpu.experiments.presets import get_preset

        cfg = get_preset(args.preset, smoke=args.smoke)
    else:
        p.error(
            "one of --preset / --config / --list / --lint PRESET is "
            "required"
        )

    if args.zero:
        if "data" not in (cfg.mesh or {}):
            p.error("--zero needs a config mesh with a 'data' axis "
                    "(e.g. \"mesh\": {\"data\": 4, \"model\": 2})")
        cfg.zero = True

    if args.plan is not None:
        import contextlib

        from torchpruner_tpu.analysis import planner

        obs = None
        if args.plan == "auto" and args.obs_dir and not args.no_obs:
            # a plan run under --obs-dir lands plan_* gauges + the
            # ledger `plan` record so `obs report`/`obs diff` carry it
            from torchpruner_tpu import obs

            obs.configure(args.obs_dir)
            obs.annotate_run(experiment=cfg.name, kind="plan",
                             model=cfg.model, method=cfg.method)
        try:
            ctx = obs.span("plan", experiment=cfg.name) \
                if obs is not None else contextlib.nullcontext()
            with ctx:
                rc = planner.plan_main(cfg, args)
        finally:
            if obs is not None:
                obs.shutdown(print_to=sys.stderr)
        return rc

    if args.lint is not None:
        from torchpruner_tpu.analysis import lint_config

        plans = None
        if args.lint_plan:
            from torchpruner_tpu.core.plan import plan_from_dict

            with open(args.lint_plan) as f:
                plans = [plan_from_dict(json.load(f))]
        report = lint_config(cfg, plans=plans)
        print(report.format())
        return 0 if report.ok else 1

    if args.resume:
        cfg.run_dir = args.resume
    if args.checkpoint_every is not None:
        cfg.checkpoint_every_steps = args.checkpoint_every
    if args.chaos:
        from torchpruner_tpu.resilience.chaos import ChaosConfig

        import dataclasses as _dc

        # validate up front; stash as plain knobs so --dump-config
        # round-trips and the drivers install it themselves
        cfg.chaos = _dc.asdict(ChaosConfig.from_any(args.chaos))
    else:
        import os as _os

        if _os.environ.get("TORCHPRUNER_CHAOS"):
            from torchpruner_tpu.resilience import chaos as _chaos_mod

            _chaos_mod.configure(None)  # reads the env var

    if args.dump_config:
        cfg.to_json(args.dump_config)
        print(f"wrote {args.dump_config}")
        return 0

    import contextlib

    profile_ctx = contextlib.nullcontext()
    if args.profile:
        from torchpruner_tpu.utils import profiling

        profile_ctx = profiling.trace(args.profile)

    obs = None
    if not args.no_obs:
        from torchpruner_tpu import obs

        obs.configure(args.obs_dir, profile_every=args.profile_every,
                      profile_steps=args.profile_steps)
        obs.annotate_run(experiment=cfg.name, kind=cfg.experiment,
                         model=cfg.model, method=cfg.method,
                         resumed=bool(args.resume))

    run_ctx = obs.span("run", experiment=cfg.name,
                       experiment_kind=cfg.experiment) \
        if obs is not None else contextlib.nullcontext()
    try:
        _run_experiment(cfg, profile_ctx, run_ctx)
    finally:
        # a crashed run is exactly when the telemetry matters: flush the
        # summary/exporters (and unregister the compile listener) on
        # every exit path
        if obs is not None:
            obs.shutdown(print_to=sys.stderr)
            if args.obs_dir:
                print(f"telemetry written to {args.obs_dir}",
                      file=sys.stderr)
    if args.profile:
        print(f"profiler trace written to {args.profile}", file=sys.stderr)
    return 0


def _run_experiment(cfg, profile_ctx, run_ctx) -> None:
    with profile_ctx, run_ctx:
        from torchpruner_tpu import obs as _obs

        if _obs.get() is not None:
            # static cost model (analysis/cost_model.py): predict this
            # config's step/decode/capture programs up front so the
            # run's report.json carries predicted_step_ms /
            # predicted_comm_ms next to what gets measured (obs diff
            # renders the drift).  Best-effort and param-budgeted;
            # TORCHPRUNER_COST_PREDICT=0 opts out.
            from torchpruner_tpu.analysis import cost_model

            cost_model.record_config_predictions(cfg)
        if cfg.experiment == "robustness":
            from torchpruner_tpu.experiments.robustness import (
                run_robustness_config,
            )

            summary = run_robustness_config(cfg)
            print(json.dumps(summary))
        elif cfg.experiment == "train_robustness":
            from torchpruner_tpu.experiments.robustness import (
                run_train_robustness,
            )

            summary = run_train_robustness(cfg)
            print(json.dumps(summary))
        elif cfg.experiment == "train":
            from torchpruner_tpu.experiments.train_model import run_train

            _trainer, history = run_train(cfg)
            last = history[-1] if history else None
            print(json.dumps({
                "experiment": cfg.name,
                "epochs": len(history),
                "final_test_acc": last["test_acc"] if last else None,
                "final_test_loss": last["test_loss"] if last else None,
            }))
        else:
            from torchpruner_tpu.experiments.prune_retrain import (
                run_prune_retrain,
            )

            history = run_prune_retrain(cfg)
            last = history[-1] if history else None
            print(json.dumps({
                "experiment": cfg.name,
                "steps": len(history),
                "final_acc": last.post_acc if last else None,
                "final_params": last.n_params if last else None,
            }))


if __name__ == "__main__":
    sys.exit(main())

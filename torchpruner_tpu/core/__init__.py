"""Core abstractions: layer specs, segmented models, pruning plans, pruner."""

"""Functional pruner: ``prune`` maps (model, params, state, opt_state) to new,
smaller pytrees plus an updated static model spec.

The reference mutates live tensors in place and relies on object identity so
training "just continues" (reference torchpruner/pruner/pruner.py:94-115,
README "on-the-fly").  Under XLA the honest equivalent is re-instantiation:
new static shapes, one retrace/recompile per prune step — accepted and
measured as part of the workflow (SURVEY.md §7 "Recompilation economics").
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core import graph as G
from torchpruner_tpu.core.plan import (
    Consumer,
    ParamSlice,
    PruneGroup,
    PrunePlan,
    apply_plan,
)
from torchpruner_tpu.core.segment import SegmentedModel


@dataclass
class PruneResult:
    model: SegmentedModel
    params: Any
    state: Any = None
    opt_state: Any = None

    def __iter__(self):  # allow tuple-unpacking
        return iter((self.model, self.params, self.state, self.opt_state))


def plan_for_group(model: SegmentedModel, group: PruneGroup) -> PrunePlan:
    """Resolve a PruneGroup against a model into a concrete plan.

    Slice table (cf. reference pruner.py:59-92, extended to the
    transformer-era layer vocabulary):
      - target Dense: ``w`` axis 1, ``b`` axis 0; target Conv: ``w`` axis 3,
        ``b`` axis 0; target GatedDense: ``wg``/``wu`` axis 1, ``bg``/``bu``
        axis 0  (out-pruning)
      - target MultiHeadAttention (query-head pruning): ``wq`` head axis 1,
        ``wo`` head axis 0, ``bq`` axis 0; plus ``wk``/``wv``/``bk``/``bv``
        when KV heads match query heads (non-GQA)
      - attached BatchNorm: ``scale``/``bias`` params axis 0 and
        ``mean``/``var`` state axis 0; LayerNorm ``scale``/``bias`` axis 0;
        RMSNorm ``scale`` axis 0  (in-pruning)
      - consumers: Dense ``w``/GatedDense ``wg``+``wu`` axis 0, Conv ``w``
        axis 2, attention ``wq``/``wk``/``wv`` axis 0, with flatten fan-out
        (in-pruning)
    """
    target = model.layer(group.target)
    tpath = L.parse_path(group.target)
    n = L.n_units(target)
    slices = []
    if isinstance(target, L.Dense):
        slices += [
            ParamSlice(tpath + ("w",), axis=1),
            ParamSlice(tpath + ("b",), axis=0, optional=True),
        ]
    elif isinstance(target, L.Conv):
        slices += [
            ParamSlice(tpath + ("w",), axis=3),
            ParamSlice(tpath + ("b",), axis=0, optional=True),
        ]
    elif isinstance(target, L.GatedDense):
        slices += [
            ParamSlice(tpath + ("wg",), axis=1),
            ParamSlice(tpath + ("wu",), axis=1),
            ParamSlice(tpath + ("bg",), axis=0, optional=True),
            ParamSlice(tpath + ("bu",), axis=0, optional=True),
        ]
    elif isinstance(target, L.MultiHeadAttention):
        slices += [
            ParamSlice(tpath + ("wq",), axis=1),
            ParamSlice(tpath + ("wo",), axis=0),
            ParamSlice(tpath + ("bq",), axis=0, optional=True),
        ]
        if target.kv_heads == target.num_heads and target.kv_group is None:
            slices += [
                ParamSlice(tpath + ("wk",), axis=1),
                ParamSlice(tpath + ("wv",), axis=1),
                ParamSlice(tpath + ("bk",), axis=0, optional=True),
                ParamSlice(tpath + ("bv",), axis=0, optional=True),
            ]
    elif isinstance(target, L.MoE):
        # expert pruning: router column + the expert's weight planes
        slices += [
            ParamSlice(tpath + ("router",), axis=1),
            ParamSlice(tpath + ("wg",), axis=0),
            ParamSlice(tpath + ("wu",), axis=0),
            ParamSlice(tpath + ("wo",), axis=0),
        ]
    else:
        raise TypeError(
            f"cannot out-prune {type(target).__name__} {group.target!r}"
        )
    for bn in group.attached_bn:
        f = bn.fan_out
        npath = L.parse_path(bn.layer)
        spec = model.layer(bn.layer)
        if isinstance(spec, L.BatchNorm):
            slices += [
                ParamSlice(npath + ("scale",), axis=0, fan_out=f),
                ParamSlice(npath + ("bias",), axis=0, fan_out=f),
                ParamSlice(
                    npath + ("mean",), axis=0, fan_out=f, collection="state"
                ),
                ParamSlice(
                    npath + ("var",), axis=0, fan_out=f, collection="state"
                ),
            ]
        elif isinstance(spec, L.LayerNorm):
            slices += [
                ParamSlice(npath + ("scale",), axis=0, fan_out=f),
                ParamSlice(npath + ("bias",), axis=0, fan_out=f, optional=True),
            ]
        elif isinstance(spec, L.RMSNorm):
            slices.append(ParamSlice(npath + ("scale",), axis=0, fan_out=f))
        else:
            raise TypeError(
                f"unknown attached norm {type(spec).__name__} {bn.layer!r}"
            )
    for c in group.consumers:
        slices.append(
            ParamSlice(
                L.parse_path(c.layer) + (c.param,), axis=c.axis, fan_out=c.fan_out
            )
        )
    return PrunePlan(n_units=n, slices=tuple(slices))


def prune(
    model: SegmentedModel,
    params,
    layer: Union[str, PruneGroup],
    drop: Sequence[int],
    *,
    state=None,
    opt_state=None,
) -> PruneResult:
    """Prune units ``drop`` from prunable layer ``layer`` (or an explicit
    group), cascading into attached BN/Dropout and consumer layers.

    Equivalent of ``Pruner.prune_model`` (reference pruner.py:21-57) with the
    cascade resolved statically instead of via NaN propagation, and optimizer
    state sliced for *any* optax optimizer rather than SGD only.

    Aliasing note: leaves the plan does not touch are returned *unchanged*
    (shared buffers, not copies).  Training the pruned result with a
    donating step (``Trainer.step`` donates params/opt_state) therefore
    invalidates those leaves in the SOURCE pytree too — hold
    ``jax.tree.map(jnp.copy, params)`` if you need the pre-prune model
    alive afterwards (examples/04 demonstrates this).
    """
    from torchpruner_tpu.ops.quant import QTensor

    if any(isinstance(leaf, QTensor)
           for leaf in jax.tree.leaves(
               params, is_leaf=lambda x: isinstance(x, QTensor))):
        raise ValueError(
            "params contain int8 QTensor weights — prune BEFORE "
            "quantizing (the deploy order is prune → fine-tune → "
            "quantize; slicing q/scale along mismatched axes would "
            "corrupt the weights silently)"
        )
    from torchpruner_tpu import obs

    group = layer if isinstance(layer, PruneGroup) else G.group_for(model, layer)
    drop = np.unique(np.asarray(drop, dtype=np.int64).reshape(-1))
    # provenance: the concrete decision (site + rows) goes to the run
    # ledger before the plan is applied, so even a run that dies inside
    # apply_plan leaves a record of what it was about to remove
    obs.record_prune(group.target, drop,
                     L.n_units(model.layer(group.target)))
    with obs.span("plan", target=group.target):
        plan = plan_for_group(model, group)
    with obs.span("apply_plan", target=group.target, n_drop=len(drop)):
        new_params, new_state, new_opt = apply_plan(
            plan, drop, params, state=state, opt_state=opt_state
        )
        new_model = pruned_model_spec(model, group, drop)
    return PruneResult(new_model, new_params, new_state, new_opt)


def pruned_model_spec(
    model: SegmentedModel, group: PruneGroup, drop: Sequence[int]
) -> SegmentedModel:
    """The static model spec after pruning ``drop`` units of ``group``:
    smaller target width, rescaled dropout rates.  Pure shape arithmetic
    (no arrays touched) — ``prune`` uses it on the real pytrees, and the
    static analyzer (analysis/sharding_lint.py) uses it to recompute
    post-prune shapes without materializing a parameter."""
    target = model.layer(group.target)
    dropped = set(int(d) for d in np.asarray(drop).reshape(-1).tolist())
    keep = [u for u in range(L.n_units(target)) if u not in dropped]
    new_model = model.replace_layer(group.target, L.pruned_spec(target, keep))
    for d_name in group.attached_dropout:
        d = model.layer(d_name)
        # Preserve expected active-unit count (reference pruner.py:117-127).
        new_rate = d.rate * (1.0 - len(dropped) / L.n_units(target))
        new_model = new_model.replace_layer(
            d_name, dataclasses.replace(d, rate=new_rate)
        )
    return new_model


def bucket_drop(
    scores: np.ndarray, drop: np.ndarray, bucket: int
) -> np.ndarray:
    """Shrink ``drop`` so the KEPT unit count is a multiple of ``bucket``,
    un-dropping the highest-scoring dropped units first.

    TPU rationale (SURVEY.md §7 "recompilation economics"): vector lanes are
    128 wide and sublanes 8 deep, so widths that are multiples of 8/128 tile
    the MXU/VPU cleanly, and bucketing bounds how many distinct shapes a
    prune schedule can visit — with the persistent compilation cache, a
    bounded shape set means a bounded total compile bill.  Rounding the kept
    count *up* is the conservative direction: it only retains units the
    policy would have removed, never removes ones it would have kept.
    """
    if bucket <= 1:
        return drop
    n = len(scores)
    keep_n = n - len(drop)
    target_keep = min(n, -(-max(keep_n, 1) // bucket) * bucket)
    n_undrop = target_keep - keep_n
    if n_undrop <= 0:
        return drop
    order = np.argsort(scores[drop])  # ascending score over dropped units
    keep_back = drop[order[len(drop) - n_undrop:]]
    return np.setdiff1d(drop, keep_back)


def prune_by_scores(
    model: SegmentedModel,
    params,
    layer: str,
    scores: np.ndarray,
    *,
    policy: Union[str, Callable[[np.ndarray], np.ndarray]] = "negative",
    fraction: float = 0.5,
    bucket: int = 1,
    state=None,
    opt_state=None,
) -> PruneResult:
    """Score→indices policy + prune in one call.

    The reference deliberately leaves this policy in user code
    (``np.argwhere(attr < 0)``, SURVEY.md §1); this helper packages the two
    common policies while :func:`prune` keeps the raw-indices API.

    - ``policy="negative"``: drop all units with score < 0
    - ``policy="fraction"``: drop the lowest-scoring ``fraction`` of units
    - callable: ``policy(scores) -> drop indices``
    - ``bucket``: round the kept width UP to a multiple (8 or 128 keeps
      TPU tiling clean and bounds recompile diversity; see
      :func:`bucket_drop`)
    """
    drop = score_drop_indices(scores, policy=policy, fraction=fraction,
                              bucket=bucket)
    return prune(model, params, layer, drop, state=state, opt_state=opt_state)


def score_drop_indices(
    scores: np.ndarray,
    *,
    policy: Union[str, Callable[[np.ndarray], np.ndarray]] = "negative",
    fraction: float = 0.5,
    bucket: int = 1,
    granularity: int = 1,
) -> np.ndarray:
    """The scores→drop-indices policy of :func:`prune_by_scores` alone —
    shared with mask-based simulated pruning so both modes drop the exact
    same units.

    ``granularity > 1`` makes the decision BLOCK-structured: scores are
    pooled (mean) into consecutive blocks of that many units, the policy
    ranks blocks, and whole blocks drop together.  At 128 (the vector-
    lane width) the resulting masks are exactly the shape the block-
    sparse matmul (ops/blocksparse.py) can skip — structured sparsity
    the kernel turns into step time, per "Structured Model Pruning of
    Convolutional Networks on TPUs" (PAPERS.md).  The kept width is a
    multiple of ``granularity`` by construction, so ``bucket`` is
    implied (and ignored) for buckets dividing the granularity."""
    scores = np.asarray(scores)
    if granularity > 1:
        n = len(scores)
        if n % granularity:
            raise ValueError(
                f"granularity {granularity} does not divide the "
                f"{n}-unit axis")
        if bucket > 1 and granularity % bucket:
            raise ValueError(
                f"bucket {bucket} does not divide granularity "
                f"{granularity}: block-structured drops keep widths in "
                f"multiples of the granularity, which cannot honor "
                f"this bucket")
        block_scores = scores.reshape(-1, granularity).mean(axis=1)
        bdrop = score_drop_indices(block_scores, policy=policy,
                                   fraction=fraction, bucket=1)
        return np.sort(
            (bdrop[:, None] * granularity
             + np.arange(granularity)[None, :]).reshape(-1)
        ).astype(np.int64)
    if callable(policy):
        # np.unique: a callable may return duplicates, which would make
        # bucket_drop miscount the kept width (keep_n = n - len(drop)).
        drop = np.unique(np.asarray(policy(scores), dtype=np.int64))
    elif policy == "negative":
        drop = np.argwhere(scores < 0).flatten()
    elif policy == "fraction":
        k = int(len(scores) * fraction)
        drop = np.argsort(scores)[:k]
    else:
        raise ValueError(f"unknown policy {policy!r}")
    if len(drop) >= len(scores):
        drop = drop[: len(scores) - 1]  # never remove a whole layer
    return bucket_drop(scores, np.asarray(drop, dtype=np.int64), bucket)


class Pruner:
    """Stateful convenience wrapper mirroring the reference's ``Pruner`` API
    (reference pruner.py:14-57) over the functional core: holds the current
    ``(model, params, state, opt_state)`` bundle and replaces them on each
    ``prune_model`` call."""

    def __init__(self, model: SegmentedModel, params, state=None, opt_state=None):
        self.model = model
        self.params = params
        self.state = state
        self.opt_state = opt_state

    def prune_model(
        self,
        layer: Union[str, PruneGroup],
        indices: Sequence[int],
    ) -> PruneResult:
        res = prune(
            self.model,
            self.params,
            layer,
            indices,
            state=self.state,
            opt_state=self.opt_state,
        )
        self.model, self.params, self.state, self.opt_state = res
        return res

"""SegmentedModel — the core abstraction of the framework.

A model is an immutable, ordered pipeline of layer specs.  Any contiguous
*segment* of the pipeline is itself a pure function, so the reference's
``forward_partial(x, from_module, to_module)`` convention (reference
torchpruner/attributions/attributions.py:70-89, experiments/models/cifar10.py:39-59)
becomes first-class: ``model.apply(..., from_layer=a, to_layer=b)`` runs the
segment *after* ``a`` up to and including ``b``, and :func:`segment_fn` hands
back a cached, jit-compatible closure for any segment.

Being a frozen dataclass of frozen dataclasses, a ``SegmentedModel`` is
hashable: it keys jit/compile caches, and pruning produces a *new* spec whose
segments recompile at the new static shapes — the XLA-honest equivalent of the
reference's in-place tensor surgery.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L


@dataclass(frozen=True)
class SegmentedModel:
    """An ordered pipeline of layer specs with named layers.

    ``input_shape`` excludes the batch dimension and is channels-last
    (e.g. ``(28, 28, 1)`` or ``(784,)``).
    """

    layers: Tuple[L.LayerSpec, ...]
    input_shape: Tuple[int, ...]

    def __post_init__(self):
        names = [l.name for l in self.layers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate layer names in {names}")

    # -- introspection ------------------------------------------------------

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(l.name for l in self.layers)

    def layer(self, name: str) -> L.LayerSpec:
        for l in self.layers:
            if l.name == name:
                return l
        raise KeyError(name)

    def index(self, name: str) -> int:
        for i, l in enumerate(self.layers):
            if l.name == name:
                return i
        raise KeyError(name)

    @functools.cached_property
    def shapes(self) -> Tuple[Tuple[Tuple[int, ...], Tuple[int, ...]], ...]:
        """Per-layer ``(in_shape, out_shape)`` (batch dim excluded), inferred
        statically from the specs — the metadata the reference obtains
        dynamically with its NaN-trick forward (reference pruner.py:170-185)."""
        out = []
        shape = tuple(self.input_shape)
        for spec in self.layers:
            out_shape = L.out_shape(spec, shape)
            out.append((shape, out_shape))
            shape = out_shape
        return tuple(out)

    def out_shape(self, name: Optional[str] = None) -> Tuple[int, ...]:
        """Output shape (batch excluded) of layer ``name`` (default: last)."""
        if name is None:
            return self.shapes[-1][1]
        return self.shapes[self.index(name)][1]

    # -- functional init / apply -------------------------------------------

    def init(self, key, dtype=jnp.float32):
        """Initialize ``(params, state)`` pytrees:
        ``params[layer_name][param_name]`` / ``state[layer_name][stat_name]``.
        Layers without params/state are omitted from the dicts."""
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        shape = tuple(self.input_shape)
        for spec in self.layers:
            key, sub = jax.random.split(key)
            p, s, shape = L.init_layer(spec, sub, shape, dtype)
            if p:
                params[spec.name] = p
            if s:
                state[spec.name] = s
        return params, state

    def apply(
        self,
        params,
        x,
        *,
        state=None,
        train: bool = False,
        rng=None,
        from_layer: Optional[str] = None,
        to_layer: Optional[str] = None,
        unit_mask: Optional[Tuple[str, Any]] = None,
        capture: Optional[str] = None,
    ):
        """Run the segment after ``from_layer`` through ``to_layer`` inclusive.

        - ``from_layer=None`` starts at the input; otherwise ``x`` must be the
          *output* of ``from_layer`` (reference forward_partial semantics).
        - ``unit_mask=(name, vec)`` multiplies the output of layer ``name`` by
          ``vec`` along the last (unit) axis — the functional replacement for
          the reference's masking forward hook (reference
          shapley_values.py:92-99).
        - ``capture=name`` additionally returns the activation at ``name``.

        Returns ``(y, new_state)``, or ``(y, new_state, captured)`` when
        ``capture`` is given.
        """
        state = state if state is not None else {}
        start = 0 if from_layer is None else self.index(from_layer) + 1
        stop = len(self.layers) if to_layer is None else self.index(to_layer) + 1
        if start >= stop and not (start == stop == len(self.layers)):
            if from_layer is not None and to_layer is not None:
                raise ValueError(
                    f"empty segment: from {from_layer!r} to {to_layer!r}"
                )
        new_state = dict(state)
        captured = None
        for spec in self.layers[start:stop]:
            p = params.get(spec.name, {})
            s = state.get(spec.name, {})
            if rng is not None:
                rng, sub = jax.random.split(rng)
            else:
                sub = None
            x, s2 = L.apply_layer(spec, p, s, x, train=train, rng=sub)
            if unit_mask is not None and spec.name == unit_mask[0]:
                x = x * unit_mask[1]
            if s2 is not s and s2:
                new_state[spec.name] = s2
            if capture is not None and spec.name == capture:
                captured = x
        if capture is not None:
            return x, new_state, captured
        return x, new_state

    # -- pruning-adjacent helpers ------------------------------------------

    def replace_layer(self, name: str, new_spec: L.LayerSpec) -> "SegmentedModel":
        new_layers = tuple(
            new_spec if l.name == name else l for l in self.layers
        )
        return SegmentedModel(new_layers, self.input_shape)

    def widths(self) -> Dict[str, int]:
        """Current unit count of every prunable layer — the architecture
        metadata a checkpoint must carry (SURVEY.md §5.4)."""
        return {
            l.name: l.features
            for l in self.layers
            if isinstance(l, L.PRUNABLE_TYPES)
        }


def init_model(model: SegmentedModel, seed: int = 0, dtype=jnp.float32):
    """Convenience: init from an integer seed."""
    return model.init(jax.random.PRNGKey(seed), dtype)


@functools.lru_cache(maxsize=512)
def segment_fn(
    model: SegmentedModel,
    from_layer: Optional[str] = None,
    to_layer: Optional[str] = None,
    train: bool = False,
):
    """A cached pure closure for a model segment:
    ``fn(params, state, x) -> (y, new_state)``.

    Cached on the (hashable) model spec so repeated calls reuse one traced
    function object — jit caches stay warm across attribution passes and only
    invalidate when pruning produces a new spec.
    """

    def fn(params, state, x):
        return model.apply(
            params, x, state=state, train=train,
            from_layer=from_layer, to_layer=to_layer,
        )

    return fn

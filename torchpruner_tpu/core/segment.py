"""SegmentedModel — the core abstraction of the framework.

A model is an immutable, ordered pipeline of layer specs.  Any contiguous
*segment* of the pipeline is itself a pure function, so the reference's
``forward_partial(x, from_module, to_module)`` convention (reference
torchpruner/attributions/attributions.py:70-89, experiments/models/cifar10.py:39-59)
becomes first-class: ``model.apply(..., from_layer=a, to_layer=b)`` runs the
segment *after* ``a`` up to and including ``b``, and :func:`segment_fn` hands
back a cached, jit-compatible closure for any segment.

Composite layers (:class:`~torchpruner_tpu.core.layers.Residual`) nest
sub-pipelines; their children are addressed by ``"block/child"`` path strings
everywhere a layer name is accepted for instrumentation (masking, capture,
perturbation, pruning targets).  Segment *boundaries* (``from_layer`` /
``to_layer``) stay at the top level — a block is the unit of sequential
composition, which is what keeps prefix/suffix reuse well-defined under
residual connections.

Being a frozen dataclass of frozen dataclasses, a ``SegmentedModel`` is
hashable: it keys jit/compile caches, and pruning produces a *new* spec whose
segments recompile at the new static shapes — the XLA-honest equivalent of the
reference's in-place tensor surgery.
"""

from __future__ import annotations

import functools
from dataclasses import dataclass
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L


@dataclass(frozen=True)
class SegmentedModel:
    """An ordered pipeline of layer specs with named layers.

    ``input_shape`` excludes the batch dimension and is channels-last
    (e.g. ``(28, 28, 1)``, ``(784,)``, or ``(seq_len,)`` for token models).
    ``input_dtype`` names the element type example inputs should use
    (``"float32"`` activations or ``"int32"`` token ids).
    """

    layers: Tuple[L.LayerSpec, ...]
    input_shape: Tuple[int, ...]
    input_dtype: str = "float32"

    def __post_init__(self):
        names = [l.name for l in self.layers]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate layer names in {names}")

    # -- introspection ------------------------------------------------------

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(l.name for l in self.layers)

    def layer(self, name: str) -> L.LayerSpec:
        """Resolve a (possibly nested, ``"block/child"``) layer path."""
        path = L.parse_path(name)
        spec = None
        layers = self.layers
        for part in path:
            spec = None
            for l in layers:
                if l.name == part:
                    spec = l
                    break
            if spec is None:
                raise KeyError(name)
            layers = (
                spec.body + spec.shortcut
                if isinstance(spec, L.Residual)
                else ()
            )
        return spec

    def index(self, name: str) -> int:
        """Top-level index of a layer (segment boundaries are top-level)."""
        for i, l in enumerate(self.layers):
            if l.name == name:
                return i
        raise KeyError(name)

    def top_level_of(self, name: str) -> str:
        """The top-level layer containing (or equal to) ``name``."""
        top = L.parse_path(name)[0]
        self.index(top)  # raises KeyError if absent
        return top

    @functools.cached_property
    def shapes(self) -> Tuple[Tuple[Tuple[int, ...], Tuple[int, ...]], ...]:
        """Per-layer ``(in_shape, out_shape)`` (batch dim excluded), inferred
        statically from the specs — the metadata the reference obtains
        dynamically with its NaN-trick forward (reference pruner.py:170-185)."""
        return L.seq_shapes(self.layers, self.input_shape)

    def out_shape(self, name: Optional[str] = None) -> Tuple[int, ...]:
        """Output shape (batch excluded) of layer ``name`` (default: last).
        Accepts nested paths."""
        if name is None:
            return self.shapes[-1][1]
        _, out = self._resolve_shapes(L.parse_path(name))
        return out

    def in_shape(self, name: str) -> Tuple[int, ...]:
        """Input shape (batch excluded) of (possibly nested) layer ``name``."""
        inp, _ = self._resolve_shapes(L.parse_path(name))
        return inp

    def site_shape(self, name: str) -> Tuple[int, ...]:
        """Per-example shape of the activation at ``name``'s *unit site* —
        what taps (mask/perturb/capture) act on, unit axis last.  Equals the
        output shape except for attention, whose site is the pre-projection
        head context ``(S, Dh, H)``."""
        path = L.parse_path(name)
        inp, _ = self._resolve_shapes(path)
        return L.unit_site_shape(self.layer(name), inp)

    def _resolve_shapes(self, path: Tuple[str, ...]):
        """(in_shape, out_shape) of the layer at ``path``."""
        layers = self.layers
        in_shape = tuple(self.input_shape)
        for depth, part in enumerate(path):
            found = None
            for spec, (i_shape, o_shape) in zip(
                layers, L.seq_shapes(layers, in_shape)
            ):
                if spec.name == part:
                    found = (spec, i_shape, o_shape)
                    break
            if found is None:
                raise KeyError("/".join(path))
            spec, i_shape, o_shape = found
            if depth == len(path) - 1:
                return i_shape, o_shape
            if not isinstance(spec, L.Residual):
                raise KeyError("/".join(path))
            # descend: body and shortcut both start from the block input
            nxt = path[depth + 1]
            if any(l.name == nxt for l in spec.body):
                layers = spec.body
            else:
                layers = spec.shortcut
            in_shape = i_shape
        raise KeyError("/".join(path))

    # -- functional init / apply -------------------------------------------

    def init(self, key, dtype=jnp.float32):
        """Initialize ``(params, state)`` pytrees:
        ``params[layer_name][param_name]`` / ``state[layer_name][stat_name]``
        (nested one level per composite block).  Layers without params/state
        are omitted from the dicts."""
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        shape = tuple(self.input_shape)
        for spec in self.layers:
            key, sub = jax.random.split(key)
            p, s, shape = L.init_layer(spec, sub, shape, dtype)
            if p:
                params[spec.name] = p
            if s:
                state[spec.name] = s
        return params, state

    def example_input(self, batch: int = 2, seed: int = 0):
        """A random batch with the model's input shape/dtype (the reference's
        ``_run_forward`` random input, reference pruner.py:170-185)."""
        key = jax.random.PRNGKey(seed)
        shape = (batch,) + tuple(self.input_shape)
        if self.input_dtype.startswith("int"):
            vocab = 2
            for spec in self.layers:
                if isinstance(spec, L.Embedding):
                    vocab = spec.vocab_size
                    break
            return jax.random.randint(key, shape, 0, vocab, jnp.int32)
        return jax.random.normal(key, shape, jnp.float32)

    def apply(
        self,
        params,
        x,
        *,
        state=None,
        train: bool = False,
        rng=None,
        from_layer: Optional[str] = None,
        to_layer: Optional[str] = None,
        unit_mask: Optional[Tuple[str, Any]] = None,
        perturb: Optional[Tuple[str, Any]] = None,
        capture: Optional[str] = None,
        captures: Optional[Sequence[str]] = None,
        collect_aux: bool = False,
        remat: bool = False,
    ):
        """Run the segment after ``from_layer`` through ``to_layer`` inclusive.

        - ``from_layer=None`` starts at the input; otherwise ``x`` must be the
          *output* of ``from_layer`` (reference forward_partial semantics).
          Segment boundaries are top-level layer names.
        - ``unit_mask=(site, vec)`` multiplies the activation at ``site`` by
          ``vec`` along the last (unit) axis — the functional replacement for
          the reference's masking forward hook (reference
          shapley_values.py:92-99).  ``site`` may be a nested path; for
          attention layers the site is the per-head context (head axis last).
        - ``perturb=(site, delta)`` adds ``delta`` at the site — differentiate
          w.r.t. ``delta`` at zero for activation-gradient attributions.
        - ``capture=site`` additionally returns the activation at the site.
        - ``captures=(site, ...)`` additionally returns ``{site: activation}``
          for EVERY listed site from the same single forward — the one-pass
          multi-site capture behind the sweep engine (see
          :func:`capture_fn`).
        - ``collect_aux=True`` additionally returns the auxiliary training
          losses emitted by layers (MoE load balancing) as
          ``{layer_path: scalar}`` — empty for models without such layers.
        - ``remat=True`` checkpoints each composite block (recompute-in-
          backward; see ``layers.apply_seq``) — the training-memory lever
          for deep transformer stacks.

        Returns ``(y, new_state)``; with ``capture`` also the captured
        activation; with ``captures`` also the site→activation dict; with
        ``collect_aux`` also the aux-loss dict (in that order when several
        are requested).
        """
        state = state if state is not None else {}
        start = 0 if from_layer is None else self.index(from_layer) + 1
        stop = len(self.layers) if to_layer is None else self.index(to_layer) + 1
        if start >= stop and not (start == stop == len(self.layers)):
            if from_layer is not None and to_layer is not None:
                raise ValueError(
                    f"empty segment: from {from_layer!r} to {to_layer!r}"
                )
        taps = None
        if (unit_mask is not None or perturb is not None
                or capture is not None or captures or collect_aux):
            taps = L.Taps(unit_mask=unit_mask, perturb=perturb,
                          capture=capture, collect_aux=collect_aux,
                          multi_capture=tuple(captures) if captures else ())
        y, new_state = L.apply_seq(
            self.layers[start:stop], params, state, x,
            train=train, rng=rng, taps=taps, remat=remat,
        )
        # merge: untouched layers keep their previous state entries
        merged = dict(state)
        merged.update(new_state)
        out = (y, merged)
        if capture is not None:
            out = out + (taps.captured,)
        if captures:
            out = out + (taps.captures,)
        if collect_aux:
            out = out + (taps.aux,)
        return out

    # -- pruning-adjacent helpers ------------------------------------------

    def replace_layer(self, name: str, new_spec: L.LayerSpec) -> "SegmentedModel":
        """Replace the (possibly nested) layer at path ``name``."""
        path = L.parse_path(name)
        new_layers = _replace_in(self.layers, path, new_spec)
        return SegmentedModel(new_layers, self.input_shape, self.input_dtype)

    def widths(self) -> Dict[str, int]:
        """Current unit count of every prunable layer (nested paths included)
        — the architecture metadata a checkpoint must carry (SURVEY.md §5.4)."""
        out: Dict[str, int] = {}

        def walk(layers, prefix):
            for l in layers:
                path = prefix + (l.name,)
                if isinstance(l, L.Residual):
                    walk(l.body, path)
                    walk(l.shortcut, path)
                elif isinstance(l, L.PRUNABLE_TYPES):
                    out["/".join(path)] = L.n_units(l)

        walk(self.layers, ())
        return out


def _replace_in(layers: Tuple[L.LayerSpec, ...], path, new_spec):
    out = []
    head, rest = path[0], path[1:]
    found = False
    for l in layers:
        if l.name == head:
            found = True
            if not rest:
                out.append(new_spec)
            else:
                if not isinstance(l, L.Residual):
                    raise KeyError("/".join(path))
                import dataclasses as _dc

                if any(c.name == rest[0] for c in l.body):
                    l = _dc.replace(l, body=_replace_in(l.body, rest, new_spec))
                else:
                    l = _dc.replace(
                        l, shortcut=_replace_in(l.shortcut, rest, new_spec)
                    )
                out.append(l)
        else:
            out.append(l)
    if not found:
        raise KeyError("/".join(path))
    return tuple(out)


def init_model(model: SegmentedModel, seed: int = 0, dtype=jnp.float32):
    """Convenience: init from an integer seed."""
    return model.init(jax.random.PRNGKey(seed), dtype)


@functools.lru_cache(maxsize=512)
def segment_fn(
    model: SegmentedModel,
    from_layer: Optional[str] = None,
    to_layer: Optional[str] = None,
    train: bool = False,
):
    """A cached pure closure for a model segment:
    ``fn(params, state, x) -> (y, new_state)``.

    Cached on the (hashable) model spec so repeated calls reuse one traced
    function object — jit caches stay warm across attribution passes and only
    invalidate when pruning produces a new spec.
    """

    def fn(params, state, x):
        return model.apply(
            params, x, state=state, train=train,
            from_layer=from_layer, to_layer=to_layer,
        )

    return fn


@functools.lru_cache(maxsize=128)
def capture_fn(model: SegmentedModel, sites: Tuple[str, ...],
               train: bool = False):
    """ONE compiled multi-site capture program:
    ``fn(params, state, x) -> {site: activation}``.

    Runs the forward once, incrementally (``z_{k+1} = segment_k→k+1(z_k)``
    is exactly what a single forward computes), emitting the activation at
    every requested site — so a sweep that previously paid L prefix
    programs and O(L²) prefix layer-forwards pays one program and O(L).
    The forward stops at the deepest top-level layer containing a site;
    layers past it are never computed.

    Cached on the hashable ``(model, sites)`` so every metric × run × batch
    of a sweep reuses one traced function object — with a fixed batch
    shape this compiles exactly once per params version (a ragged tail
    batch adds one more executable, hence the CI bound of ≤ 2).
    """
    if not sites:
        raise ValueError("capture_fn needs at least one site")
    stop = max(model.index(model.top_level_of(s)) for s in sites)
    to_layer = model.layers[stop].name

    @jax.jit
    def fn(params, state, x):
        _, _, caps = model.apply(
            params, x, state=state, train=train,
            to_layer=to_layer, captures=sites,
        )
        return caps

    return fn

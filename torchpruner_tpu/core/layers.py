"""Layer specifications and their functional init/apply rules.

Design notes (TPU-first):

- All activation layouts are **channels-last** (``NHWC`` for images, ``(B, F)``
  for vectors, ``(B, S, D)`` for sequences).  The prunable *unit* axis is
  therefore always the **last** axis of a layer's unit-site activation, so unit
  masking, Shapley scans and flatten fan-out maps are uniform across Dense,
  Conv, GatedDense and attention-head sites.  (The reference library works on
  torch's ``NCHW`` and hardcodes "dim 1" everywhere, e.g. reference
  torchpruner/pruner/pruner.py:129-168; channels-last is both the natural JAX
  convention and what XLA tiles best onto the MXU.)
- Layer specs are frozen, hashable dataclasses.  A model spec is static data:
  it can key jit caches, and *changing* it (pruning!) naturally triggers
  retracing at the new shapes.  Composite specs (:class:`Residual`) nest other
  specs; nested layers are addressed by ``"block/child"`` path strings.
- Parameters and mutable state (BatchNorm running statistics) are plain
  pytrees ``{layer_name: {param_name: array}}``, nested one level per
  composite block; apply rules are pure functions
  ``(spec, params, state, x) -> (y, new_state)``.
- :class:`Taps` carries the attribution instrumentation — unit masking
  (functional replacement for the reference's masking forward hook, reference
  shapley_values.py:92-99), additive perturbation (for activation-gradient
  metrics via ``jax.vjp``) and activation capture — addressed by site path,
  working at any nesting depth.

Parameter layouts:

- Dense: ``w`` is ``(in, out)``, ``b`` is ``(out,)``.  Out-prune = axis 1 of
  ``w`` / axis 0 of ``b``; in-prune = axis 0 of ``w``.
- Conv: ``w`` is ``HWIO``, ``b`` is ``(out,)``.  Out-prune = axis 3; in-prune
  = axis 2.  (Reference prunes torch ``OIHW`` axis 0 / axis 1, reference
  pruner.py:81-85.)
- BatchNorm: ``scale``/``bias`` params and ``mean``/``var`` state, all
  ``(features,)`` — in-pruned along axis 0 (reference pruner.py:86-90).
- LayerNorm/RMSNorm: ``scale`` (and LayerNorm ``bias``) ``(features,)`` —
  in-pruned along axis 0 when their producer is pruned.
- MultiHeadAttention: ``wq`` ``(d, H, Dh)``, ``wk``/``wv`` ``(d, KV, Dh)``,
  ``wo`` ``(H, Dh, d_out)``; biases ``bq`` ``(H, Dh)``, ``bk``/``bv``
  ``(KV, Dh)``, ``bo`` ``(d_out,)``.  The prunable unit is the **query head**:
  head-prune = axis 1 of ``wq`` / axis 0 of ``wo`` (+ ``wk``/``wv`` axis 1
  when ``KV == H``); the block's *output width* is unchanged, so head pruning
  never cascades outside the attention layer.  In-prune (producer width
  change) = axis 0 of ``wq``/``wk``/``wv``.
- GatedDense (SwiGLU-style): ``wg``/``wu`` ``(in, features)``, ``bg``/``bu``
  ``(features,)``.  Out-prune = axis 1 of both mats; in-prune = axis 0.
- Embedding: ``emb`` ``(vocab, features)``; PosEmbed: ``emb``
  ``(max_len, features)``.
"""

from __future__ import annotations

import dataclasses
import math
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from torchpruner_tpu.ops.quant import QTensor, oscale, qdot, wval

# ---------------------------------------------------------------------------
# Layer specs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Dense:
    """Fully-connected layer. Prunable (out units = features)."""

    name: str
    features: int
    use_bias: bool = True


@dataclass(frozen=True)
class Conv:
    """2-D convolution, NHWC/HWIO. Prunable (out units = channels)."""

    name: str
    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"  # "SAME" | "VALID"
    use_bias: bool = True


@dataclass(frozen=True)
class BatchNorm:
    """Batch normalization over the last axis; functional running stats.

    ``decay`` is the running-average retention factor:
    ``new_running = decay * running + (1 - decay) * batch_stat``.
    """

    name: str
    decay: float = 0.9
    eps: float = 1e-5


@dataclass(frozen=True)
class LayerNorm:
    """Layer normalization over the last axis (transformer blocks)."""

    name: str
    eps: float = 1e-5
    use_bias: bool = True


@dataclass(frozen=True)
class RMSNorm:
    """RMS normalization over the last axis (Llama-family blocks)."""

    name: str
    eps: float = 1e-6


#: Activation function registry. Mirrors the reference's ACTIVATIONS set
#: (reference torchpruner/utils/graph.py:6) for evaluation-point shifting.
ACTIVATION_FNS: dict = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leaky_relu": jax.nn.leaky_relu,  # slope 0.01, same default as torch
    "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "identity": lambda x: x,
}


@dataclass(frozen=True)
class Activation:
    name: str
    fn: str = "relu"

    def __post_init__(self):
        if self.fn not in ACTIVATION_FNS:
            raise ValueError(f"unknown activation {self.fn!r}")


@dataclass(frozen=True)
class Pool:
    """2-D max/avg pooling on NHWC."""

    name: str
    kind: str = "max"  # "max" | "avg"
    window: Tuple[int, int] = (2, 2)
    strides: Optional[Tuple[int, int]] = None  # default: == window
    padding: str = "VALID"  # "VALID" | "SAME"


@dataclass(frozen=True)
class GlobalPool:
    """Global pooling / token selection:

    - ``"avg"``: NHWC -> (B, C) spatial mean (ResNet final pool)
    - ``"seq_mean"``: (B, S, D) -> (B, D) mean over the sequence
    - ``"cls"``: (B, S, D) -> (B, D) first-token select (BERT/ViT CLS)
    """

    name: str
    kind: str = "avg"


@dataclass(frozen=True)
class Flatten:
    """Flatten all non-batch axes, row-major: (B,H,W,C) -> (B, H*W*C).

    With channels-last, channel ``c`` of the input maps to flat indices
    ``{p * C + c : p in range(H*W)}`` — the fan-out map used when a pruned
    conv channel cascades into a Dense consumer (the case the reference
    discovers with its NaN trick, reference tests/test_pruner.py:83-92).
    """

    name: str


@dataclass(frozen=True)
class Reshape:
    """Reshape non-batch dims to ``shape`` (one ``-1`` allowed).  E.g. the
    ViT patch-grid -> token-sequence step: ``(B,h,w,C) -> (B, h*w, C)``."""

    name: str
    shape: Tuple[int, ...]


@dataclass(frozen=True)
class Dropout:
    """Dropout. ``rate`` is the drop probability; rescaled on pruning so the
    expected number of active units is preserved (reference pruner.py:117-127).
    """

    name: str
    rate: float = 0.5


@dataclass(frozen=True)
class Embedding:
    """Token embedding lookup: int tokens ``(..., S)`` -> ``(..., S, d)``."""

    name: str
    vocab_size: int
    features: int


@dataclass(frozen=True)
class PosEmbed:
    """Learned positional embedding added to a ``(B, S, d)`` sequence."""

    name: str
    max_len: int


@dataclass(frozen=True)
class ClsToken:
    """Prepend a learned classification token: ``(B, S, d) -> (B, S+1, d)``
    (ViT/BERT-style; pair with ``GlobalPool("...", "cls")`` at the head)."""

    name: str


@dataclass(frozen=True)
class MultiHeadAttention:
    """Multi-head (optionally grouped-query) self-attention on ``(B, S, d)``.

    Prunable: the unit is the **query head** (``n_units = num_heads``); its
    unit site is the pre-output-projection head context, exposed to taps in
    ``(B, S, Dh, H)`` layout (head axis last) so masking/capture/attribution
    are uniform with channel sites.  ``num_kv_heads < num_heads`` gives GQA
    (Llama-3 style); KV projections are then shared across query-head groups
    and are only sliced by head pruning when ``num_kv_heads == num_heads``.

    ``impl`` selects the attention core: ``"auto"`` (Pallas flash kernel on
    TPU, reference einsum elsewhere), ``"xla"``, or ``"flash"``.
    """

    name: str
    num_heads: int
    head_dim: int
    num_kv_heads: Optional[int] = None  # None -> num_heads
    out_features: Optional[int] = None  # None -> input width
    causal: bool = False
    rope: bool = False
    rope_theta: float = 10000.0
    use_bias: bool = False
    impl: str = "auto"  # "auto" | "xla" | "flash"
    #: per-query-head KV-head assignment.  None = uniform grouping
    #: (head h -> KV head h // (H / KV)).  Pruning query heads of a GQA
    #: layer makes the grouping irregular; the surviving heads' original
    #: assignments are recorded here (set by ``pruned_spec``).
    kv_group: Optional[Tuple[int, ...]] = None

    @property
    def kv_heads(self) -> int:
        return self.num_kv_heads if self.num_kv_heads is not None else self.num_heads

    def head_kv_index(self) -> Tuple[int, ...]:
        """KV head consumed by each query head."""
        if self.kv_group is not None:
            return self.kv_group
        rep = self.num_heads // self.kv_heads
        return tuple(h // rep for h in range(self.num_heads))


@dataclass(frozen=True)
class GatedDense:
    """Gated linear unit ``act(x @ wg) * (x @ wu)`` (SwiGLU with
    ``fn="silu"``).  Prunable (out units = features); the Llama FFN hidden
    layer, pruned with its ``wo`` consumer inside the block."""

    name: str
    features: int
    fn: str = "silu"
    use_bias: bool = False

    def __post_init__(self):
        if self.fn not in ACTIVATION_FNS:
            raise ValueError(f"unknown activation {self.fn!r}")


@dataclass(frozen=True)
class MoE:
    """Mixture-of-experts SwiGLU FFN on ``(B, S, d)`` (Mixtral-style).

    Router picks ``top_k`` of ``n_experts``; gates are the softmax over the
    selected logits.  Two compute formulations, selected by ``dispatch``:

    - ``"dense"``: every expert's contribution weighted by its (mostly
      zero) gate — simple, exactly differentiable, expert-parallel by pure
      sharding (partition the expert axis of ``wg``/``wu``/``wo`` over a
      mesh axis; XLA inserts the reduction).  FLOPs are ``E/top_k`` times
      the useful work.
    - ``"sparse"``: capacity-based gather/scatter dispatch.  Token-expert
      pairs are grouped by expert (stable argsort), gathered into per-
      expert buffers of static capacity
      ``C = ceil(tokens * top_k / E * capacity_factor)``, run through the
      three expert matmuls at ``(E, C, ·)``, and scattered back weighted
      by their gates — per-token FLOPs scale with ``top_k/E``, all shapes
      static.  Pairs beyond an expert's capacity are dropped (contribution
      zero), the standard GShard/Switch trade; ``capacity_factor >=
      n_experts/top_k`` guarantees no drops (then C = tokens) and bit-
      equivalence with the dense formulation.

    Prunable: the unit is the **expert** (``n_units = n_experts``); the unit
    site is the gate tensor ``(B, S, E)`` in both formulations, so
    attribution metrics score expert utility and pruning removes whole
    experts (router column + expert weights)."""

    name: str
    n_experts: int
    ffn_dim: int
    top_k: int = 2
    fn: str = "silu"
    dispatch: str = "dense"
    capacity_factor: float = 1.25

    def __post_init__(self):
        if self.fn not in ACTIVATION_FNS:
            raise ValueError(f"unknown activation {self.fn!r}")
        if not (1 <= self.top_k <= self.n_experts):
            raise ValueError(
                f"top_k {self.top_k} out of range [1, {self.n_experts}]"
            )
        if self.dispatch not in ("dense", "sparse"):
            raise ValueError(f"unknown dispatch {self.dispatch!r}")
        if self.capacity_factor <= 0:
            raise ValueError("capacity_factor must be positive")


@dataclass(frozen=True)
class Residual:
    """Residual block: ``y = body(x) + shortcut(x)`` (identity shortcut when
    ``shortcut`` is empty).  ``body``/``shortcut`` are nested sequential
    pipelines whose layers are addressed ``"resname/childname"``; pruning
    recurses into them (core/graph.py) with the block's *output* width pinned
    (the residual stream), exactly like the model's own output layer."""

    name: str
    body: Tuple[Any, ...]
    shortcut: Tuple[Any, ...] = ()

    def __post_init__(self):
        names = [l.name for l in self.body] + [l.name for l in self.shortcut]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate child names in Residual {self.name!r}")

    def child(self, name: str):
        for l in self.body + self.shortcut:
            if l.name == name:
                return l
        raise KeyError(f"{self.name}/{name}")


LayerSpec = Any  # union of the above dataclasses

#: can be out-pruned. Dense/Conv match the reference (reference pruner.py:11);
#: GatedDense, MultiHeadAttention (query heads) and MoE (experts) are the
#: transformer-era additions the BASELINE.json configs require.
PRUNABLE_TYPES = (Dense, Conv, GatedDense, MultiHeadAttention, MoE)
#: in-pruned alongside a producer (reference pruner.py:11 lists Dropout and
#: BatchNorm; LayerNorm/RMSNorm are their transformer equivalents).
ATTACHABLE_TYPES = (BatchNorm, Dropout, LayerNorm, RMSNorm)
#: composite specs containing nested pipelines.
COMPOSITE_TYPES = (Residual,)


# ---------------------------------------------------------------------------
# Shapes
# ---------------------------------------------------------------------------


def _kaiming(key, shape, fan_in, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fan_in)


def _reshape_target(shape: Tuple[int, ...], in_shape: Tuple[int, ...]):
    size = 1
    for d in in_shape:
        size *= d
    if shape.count(-1) > 1:
        raise ValueError(f"Reshape allows one -1, got {shape}")
    if -1 in shape:
        known = 1
        for d in shape:
            if d != -1:
                known *= d
        if size % known:
            raise ValueError(f"cannot reshape {in_shape} to {shape}")
        return tuple(size // known if d == -1 else d for d in shape)
    return tuple(shape)


def out_shape(spec: LayerSpec, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    """The single source of truth for per-layer output shapes (batch dim
    excluded) — used by init, by ``SegmentedModel.shapes``, and by the
    pruning-graph fan-out computation."""
    if isinstance(spec, Dense):
        return tuple(in_shape[:-1]) + (spec.features,)
    if isinstance(spec, Conv):
        h, w = in_shape[0], in_shape[1]
        oh, ow = _conv_out_hw((h, w), spec)
        return (oh, ow, spec.features)
    if isinstance(spec, Pool):
        strides = spec.strides or spec.window
        if spec.padding == "SAME":
            oh = -(-in_shape[0] // strides[0])
            ow = -(-in_shape[1] // strides[1])
        else:
            oh = (in_shape[0] - spec.window[0]) // strides[0] + 1
            ow = (in_shape[1] - spec.window[1]) // strides[1] + 1
        return (oh, ow) + tuple(in_shape[2:])
    if isinstance(spec, GlobalPool):
        return (in_shape[-1],)
    if isinstance(spec, Flatten):
        size = 1
        for d in in_shape:
            size *= d
        return (size,)
    if isinstance(spec, Reshape):
        return _reshape_target(spec.shape, in_shape)
    if isinstance(spec, Embedding):
        return tuple(in_shape) + (spec.features,)
    if isinstance(spec, ClsToken):
        return (in_shape[0] + 1,) + tuple(in_shape[1:])
    if isinstance(spec, MultiHeadAttention):
        d_out = spec.out_features if spec.out_features is not None else in_shape[-1]
        return tuple(in_shape[:-1]) + (d_out,)
    if isinstance(spec, GatedDense):
        return tuple(in_shape[:-1]) + (spec.features,)
    if isinstance(spec, MoE):
        return tuple(in_shape)
    if isinstance(spec, Residual):
        return seq_out_shape(spec.body, in_shape)
    return tuple(in_shape)


def seq_out_shape(layers, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    shape = tuple(in_shape)
    for spec in layers:
        shape = out_shape(spec, shape)
    return shape


def seq_shapes(layers, in_shape: Tuple[int, ...]):
    """Per-layer ``(in_shape, out_shape)`` for a sequential pipeline."""
    out = []
    shape = tuple(in_shape)
    for spec in layers:
        o = out_shape(spec, shape)
        out.append((shape, o))
        shape = o
    return tuple(out)


def unit_site_shape(spec: LayerSpec, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    """Per-example shape of the activation at a layer's *unit site* — the
    tensor taps act on, with the unit axis last.  For most layers this is the
    output; for attention it is the head context in ``(S, Dh, H)`` layout."""
    if isinstance(spec, MultiHeadAttention):
        S = in_shape[0]
        return (S, spec.head_dim, spec.num_heads)
    if isinstance(spec, MoE):
        return (in_shape[0], spec.n_experts)  # the gate tensor (S, E)
    return out_shape(spec, in_shape)


# ---------------------------------------------------------------------------
# init rules: (spec, key, in_shape) -> (params, state, out_shape)
# in_shape/out_shape exclude the batch dimension.
# ---------------------------------------------------------------------------


def init_layer(spec: LayerSpec, key, in_shape: Tuple[int, ...], dtype=jnp.float32):
    """Initialize one layer. Returns ``(params, state, out_shape)``; ``params``
    / ``state`` are ``{}`` for parameter-free / stateless layers."""
    if isinstance(spec, Dense):
        if len(in_shape) < 1:
            raise ValueError(
                f"Dense {spec.name!r} expects >=1D input, got shape {in_shape}"
            )
        kw, _ = jax.random.split(key)
        fan_in = in_shape[-1]
        params = {"w": _kaiming(kw, (fan_in, spec.features), fan_in, dtype)}
        if spec.use_bias:
            params["b"] = jnp.zeros((spec.features,), dtype)
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, Conv):
        if len(in_shape) != 3:
            raise ValueError(
                f"Conv {spec.name!r} expects HWC input, got shape {in_shape}"
            )
        h, w, c = in_shape
        kh, kw_ = spec.kernel_size
        fan_in = kh * kw_ * c
        k1, _ = jax.random.split(key)
        params = {"w": _kaiming(k1, (kh, kw_, c, spec.features), fan_in, dtype)}
        if spec.use_bias:
            params["b"] = jnp.zeros((spec.features,), dtype)
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, BatchNorm):
        f = in_shape[-1]
        params = {"scale": jnp.ones((f,), dtype), "bias": jnp.zeros((f,), dtype)}
        state = {"mean": jnp.zeros((f,), dtype), "var": jnp.ones((f,), dtype)}
        return params, state, in_shape

    if isinstance(spec, LayerNorm):
        f = in_shape[-1]
        params = {"scale": jnp.ones((f,), dtype)}
        if spec.use_bias:
            params["bias"] = jnp.zeros((f,), dtype)
        return params, {}, tuple(in_shape)

    if isinstance(spec, RMSNorm):
        f = in_shape[-1]
        return {"scale": jnp.ones((f,), dtype)}, {}, tuple(in_shape)

    if isinstance(spec, Embedding):
        params = {
            "emb": jax.random.normal(
                key, (spec.vocab_size, spec.features), dtype
            ) * 0.02
        }
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, ClsToken):
        f = in_shape[-1]
        params = {"tok": jax.random.normal(key, (f,), dtype) * 0.02}
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, PosEmbed):
        f = in_shape[-1]
        if in_shape[0] > spec.max_len:
            raise ValueError(
                f"PosEmbed {spec.name!r}: sequence {in_shape[0]} exceeds "
                f"max_len {spec.max_len}"
            )
        params = {"emb": jax.random.normal(key, (spec.max_len, f), dtype) * 0.02}
        return params, {}, tuple(in_shape)

    if isinstance(spec, MultiHeadAttention):
        d = in_shape[-1]
        H, KV, Dh = spec.num_heads, spec.kv_heads, spec.head_dim
        if H % KV:
            raise ValueError(
                f"MHA {spec.name!r}: num_heads {H} not divisible by "
                f"num_kv_heads {KV}"
            )
        d_out = spec.out_features if spec.out_features is not None else d
        kq, kk, kv, ko = jax.random.split(key, 4)
        s_in = 1.0 / math.sqrt(d)
        s_out = 1.0 / math.sqrt(H * Dh)
        params = {
            "wq": jax.random.normal(kq, (d, H, Dh), dtype) * s_in,
            "wk": jax.random.normal(kk, (d, KV, Dh), dtype) * s_in,
            "wv": jax.random.normal(kv, (d, KV, Dh), dtype) * s_in,
            "wo": jax.random.normal(ko, (H, Dh, d_out), dtype) * s_out,
        }
        if spec.use_bias:
            params["bq"] = jnp.zeros((H, Dh), dtype)
            params["bk"] = jnp.zeros((KV, Dh), dtype)
            params["bv"] = jnp.zeros((KV, Dh), dtype)
            params["bo"] = jnp.zeros((d_out,), dtype)
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, GatedDense):
        fan_in = in_shape[-1]
        kg, ku = jax.random.split(key)
        params = {
            "wg": _kaiming(kg, (fan_in, spec.features), fan_in, dtype),
            "wu": _kaiming(ku, (fan_in, spec.features), fan_in, dtype),
        }
        if spec.use_bias:
            params["bg"] = jnp.zeros((spec.features,), dtype)
            params["bu"] = jnp.zeros((spec.features,), dtype)
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, MoE):
        d = in_shape[-1]
        E, F = spec.n_experts, spec.ffn_dim
        kr, kg, ku, ko = jax.random.split(key, 4)
        params = {
            "router": jax.random.normal(kr, (d, E), dtype) / jnp.sqrt(d),
            "wg": _kaiming(kg, (E, d, F), d, dtype),
            "wu": _kaiming(ku, (E, d, F), d, dtype),
            "wo": jax.random.normal(ko, (E, F, d), dtype) / jnp.sqrt(F),
        }
        return params, {}, tuple(in_shape)

    if isinstance(spec, Residual):
        params: Dict[str, Any] = {}
        state: Dict[str, Any] = {}
        shape = tuple(in_shape)
        for child in spec.body:
            key, sub = jax.random.split(key)
            p, s, shape = init_layer(child, sub, shape, dtype)
            if p:
                params[child.name] = p
            if s:
                state[child.name] = s
        sc_shape = tuple(in_shape)
        for child in spec.shortcut:
            key, sub = jax.random.split(key)
            p, s, sc_shape = init_layer(child, sub, sc_shape, dtype)
            if p:
                params[child.name] = p
            if s:
                state[child.name] = s
        if spec.shortcut and sc_shape != shape:
            raise ValueError(
                f"Residual {spec.name!r}: body out {shape} != shortcut out "
                f"{sc_shape}"
            )
        if not spec.shortcut and shape != tuple(in_shape):
            raise ValueError(
                f"Residual {spec.name!r}: identity shortcut needs body out "
                f"{shape} == in {tuple(in_shape)}"
            )
        return params, state, shape

    if isinstance(spec, (Pool, GlobalPool, Flatten, Reshape, Activation, Dropout)):
        return {}, {}, out_shape(spec, in_shape)

    raise TypeError(f"unknown layer spec {type(spec)}")


def _conv_out_hw(hw, spec: Conv):
    h, w = hw
    sh, sw = spec.strides
    if spec.padding == "SAME":
        return -(-h // sh), -(-w // sw)
    kh, kw = spec.kernel_size
    return (h - kh) // sh + 1, (w - kw) // sw + 1


# ---------------------------------------------------------------------------
# Taps — attribution instrumentation addressed by site path
# ---------------------------------------------------------------------------


def parse_path(name) -> Tuple[str, ...]:
    """``"block/child"`` -> ``("block", "child")``; tuples pass through."""
    if isinstance(name, tuple):
        return name
    return tuple(name.split("/"))


class Taps:
    """Per-trace instrumentation: unit masking, additive perturbation,
    activation capture at named sites (paths), and auxiliary-loss
    collection (MoE load balancing).  Created fresh per ``apply`` call, so
    the side-slots are trace-local and jit-safe.

    ``multi_capture`` records the activation at EVERY listed site into
    ``captures`` (path string → array) in one forward — the primitive
    behind the one-pass sweep capture (attributions.base.ActivationCache):
    one compiled program emits all eval-site activations instead of L
    prefix programs recomputing them."""

    __slots__ = ("unit_mask", "perturb", "capture", "captured",
                 "multi_capture", "captures", "collect_aux", "aux")

    def __init__(self, unit_mask=None, perturb=None, capture=None,
                 collect_aux=False, multi_capture=()):
        self.unit_mask = (
            None if unit_mask is None else (parse_path(unit_mask[0]), unit_mask[1])
        )
        self.perturb = (
            None if perturb is None else (parse_path(perturb[0]), perturb[1])
        )
        self.capture = None if capture is None else parse_path(capture)
        self.captured = None
        self.multi_capture = frozenset(
            parse_path(p) for p in multi_capture
        )
        self.captures = {}  # {path string: activation} per capture site
        self.collect_aux = collect_aux
        self.aux = {}  # {path string: scalar} per collecting layer

    def empty(self) -> bool:
        return (
            self.unit_mask is None
            and self.perturb is None
            and self.capture is None
            and not self.multi_capture
        )

    def at_site(self, path: Tuple[str, ...], y):
        """Apply mask/perturb and record capture if ``path`` is a tap site.
        ``y`` must have the unit axis last."""
        if self.unit_mask is not None and self.unit_mask[0] == path:
            y = y * self.unit_mask[1]
        if self.perturb is not None and self.perturb[0] == path:
            y = y + self.perturb[1]
        if self.capture == path:
            self.captured = y
        if path in self.multi_capture:
            self.captures["/".join(path)] = y
        return y


# ---------------------------------------------------------------------------
# apply rules: (spec, params, state, x, train, rng, taps, path) -> (y, state')
# ---------------------------------------------------------------------------


def apply_seq(
    layers,
    params,
    state,
    x,
    *,
    train: bool = False,
    rng=None,
    taps: Optional[Taps] = None,
    prefix: Tuple[str, ...] = (),
    remat: bool = False,
):
    """Run a sequential pipeline of layers.  The shared runner behind
    ``SegmentedModel.apply`` and ``Residual`` bodies: threads state and rng,
    and applies output-site taps after every non-attention layer (attention
    handles its own head site internally).

    ``remat=True`` wraps each composite block (``Residual``) in
    ``jax.checkpoint``: the backward recomputes the block's forward instead
    of saving its internals — activation memory per block drops to the
    block boundaries, the standard trade for training transformer stacks
    at long sequence length.  Only applies when no taps instrument the
    forward (attribution capture escapes a remat region by object
    mutation, which is unsound under recomputation — scoring never needs
    remat)."""
    state = state if state is not None else {}
    new_state = dict(state)
    for spec in layers:
        p = params.get(spec.name, {}) if params else {}
        s = state.get(spec.name, {})
        if rng is not None:
            rng, sub = jax.random.split(rng)
        else:
            sub = None
        path = prefix + (spec.name,)
        if (
            remat
            and isinstance(spec, Residual)
            and (taps is None or taps.empty())
        ):
            # aux losses (MoE balancing) survive rematerialization by being
            # block OUTPUTS: the checkpointed closure collects them into a
            # fresh Taps and returns the dict (a pytree), so the backward
            # recomputation stays sound — unlike capture, which escapes by
            # object mutation and therefore disables remat
            collect = taps is not None and taps.collect_aux

            def block(p_, s_, x_, r_, _spec=spec, _path=path):
                t = Taps(collect_aux=True) if collect else None
                y_, st_ = apply_layer(
                    _spec, p_, s_, x_, train=train, rng=r_, taps=t,
                    path=_path,
                )
                return y_, st_, (t.aux if collect else {})

            x, s2, aux = jax.checkpoint(block)(p, s, x, sub)
            if collect:
                taps.aux.update(aux)
        else:
            x, s2 = apply_layer(
                spec, p, s, x, train=train, rng=sub, taps=taps, path=path
            )
        if (
            taps is not None
            and not taps.empty()
            and not isinstance(spec, (MultiHeadAttention, MoE))
        ):
            x = taps.at_site(path, x)  # attention/MoE tap their own
            # internal unit sites (head context / gates)
        if s2 is not s and s2:
            new_state[spec.name] = s2
    return x, new_state


def _rope(x, theta: float, offset=0):
    """Rotary position embedding on ``(B, S, H, Dh)`` (Su et al., 2021).
    ``offset`` shifts the absolute positions — the KV-cache decode path
    (generate.py) embeds a length-1 sequence at position ``pos``.  A
    rank-1 ``offset`` of shape ``(B,)`` gives every sequence its OWN
    shift — the continuous-batching slot array, where each slot sits at
    a different decode position."""
    S, Dh = x.shape[1], x.shape[-1]
    half = Dh // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    if jnp.ndim(offset) > 0:  # per-slot offsets: (B,) -> (B, S, half)
        pos = (jnp.asarray(offset, jnp.float32)[:, None]
               + jnp.arange(S, dtype=jnp.float32)[None, :])
        ang = pos[..., None] * freqs  # (B, S, half)
        cos = jnp.cos(ang)[:, :, None, :].astype(x.dtype)
        sin = jnp.sin(ang)[:, :, None, :].astype(x.dtype)
    else:
        pos = offset + jnp.arange(S, dtype=jnp.float32)
        ang = pos[:, None] * freqs[None, :]  # (S, half)
        cos = jnp.cos(ang)[None, :, None, :].astype(x.dtype)
        sin = jnp.sin(ang)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)


#: ``impl="auto"`` crossover: below this sequence length the XLA einsum
#: path wins on TPU — measured at S=2048 (bench ``flash_attention`` leg,
#: TPU v5 lite: flash 73.7 ms vs XLA 72.1 ms grad step, speedup 0.979) —
#: and its O(S²) temp memory is still affordable (768 MB at S=2048).
#: Above it the flash kernel's O(S·Dh) backward memory is the point:
#: 48.6 MB vs the quadratic XLA buffer that grows 16× per 4× S and OOMs
#: long-context training.  Revisit with experiments/flash_sweep.py when
#: longer-S on-chip numbers land.
FLASH_AUTO_MIN_S = 4096


def attention_core(q, k, v, *, causal: bool, impl: str = "auto"):
    """Scaled-dot-product attention core on ``(B, S, H, Dh)`` tensors
    (K/V already expanded to H heads).  ``impl="auto"`` picks the XLA
    einsum path except on TPU at ``S >= FLASH_AUTO_MIN_S``, where the
    Pallas flash kernel's linear-in-S memory earns its keep
    (torchpruner_tpu/ops/flash_attention.py)."""
    if impl == "auto":
        impl = ("flash" if jax.default_backend() == "tpu"
                and q.shape[1] >= FLASH_AUTO_MIN_S else "xla")
    if impl == "flash":
        from torchpruner_tpu.ops.flash_attention import flash_attention

        return flash_attention(q, k, v, causal=causal)
    scale = 1.0 / math.sqrt(q.shape[-1])
    logits = jnp.einsum("bshk,bthk->bhst", q, k) * scale
    if causal:
        S = q.shape[1]
        neg = jnp.finfo(logits.dtype).min
        mask = jnp.tril(jnp.ones((S, S), bool))
        logits = jnp.where(mask[None, None], logits, neg)
    w = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("bhst,bthk->bshk", w, v)


def apply_layer(
    spec: LayerSpec,
    params,
    state,
    x,
    *,
    train: bool = False,
    rng=None,
    taps: Optional[Taps] = None,
    path: Tuple[str, ...] = (),
):
    """Apply one layer. Pure; returns ``(y, new_state)``.

    Matmul weights may be int8 :class:`~torchpruner_tpu.ops.quant.QTensor`
    leaves (weight-only serving quantization): the dot consumes the int8
    payload converted to the activation dtype, and the per-output-channel
    scale is applied to the OUTPUT — exact for symmetric per-out-channel
    quantization, and the convert-only producer keeps the weight int8 in
    HBM (ops/quant.py).
    """
    if isinstance(spec, Dense):
        y = oscale(qdot(x, params["w"]), params["w"])
        if "b" in params:
            y = y + params["b"]
        return y, state

    if isinstance(spec, Conv):
        y = lax.conv_general_dilated(
            x,
            params["w"],
            window_strides=spec.strides,
            padding=spec.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if "b" in params:
            y = y + params["b"]
        return y, state

    # Norms compute in f32 regardless of the activation dtype and cast the
    # result back — the canonical mixed-precision policy: a bf16 batch's
    # statistics and the BN running-stat EMA would otherwise round small
    # increments (|Δ| < 2^-8 of the running value) to zero, silently
    # freezing the statistics over a long bf16 run.
    if isinstance(spec, BatchNorm):
        xf = x.astype(jnp.float32)
        if train:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(xf, axes)
            var = jnp.var(xf, axes)
            new_state = {
                "mean": spec.decay * state["mean"].astype(jnp.float32)
                + (1 - spec.decay) * mean,
                "var": spec.decay * state["var"].astype(jnp.float32)
                + (1 - spec.decay) * var,
            }
        else:
            mean = state["mean"].astype(jnp.float32)
            var = state["var"].astype(jnp.float32)
            new_state = state
        inv = lax.rsqrt(var + spec.eps)
        y = (xf - mean) * inv * params["scale"].astype(jnp.float32) + params[
            "bias"
        ].astype(jnp.float32)
        return y.astype(x.dtype), new_state

    if isinstance(spec, LayerNorm):
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=-1, keepdims=True)
        var = jnp.var(xf, axis=-1, keepdims=True)
        y = (xf - mean) * lax.rsqrt(var + spec.eps) * params[
            "scale"
        ].astype(jnp.float32)
        if "bias" in params:
            y = y + params["bias"].astype(jnp.float32)
        return y.astype(x.dtype), state

    if isinstance(spec, RMSNorm):
        xf = x.astype(jnp.float32)
        ms = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
        y = xf * lax.rsqrt(ms + spec.eps) * params["scale"].astype(
            jnp.float32
        )
        return y.astype(x.dtype), state

    if isinstance(spec, Activation):
        return ACTIVATION_FNS[spec.fn](x), state

    if isinstance(spec, Pool):
        strides = spec.strides or spec.window
        window = (1,) + tuple(spec.window) + (1,)
        strides_ = (1,) + tuple(strides) + (1,)
        if spec.kind == "max":
            y = lax.reduce_window(
                x, -jnp.inf, lax.max, window, strides_, spec.padding
            )
        elif spec.kind == "avg":
            y = lax.reduce_window(
                x, 0.0, lax.add, window, strides_, spec.padding
            )
            if spec.padding == "SAME":
                # divide by the number of *valid* elements per window
                counts = lax.reduce_window(
                    jnp.ones_like(x), 0.0, lax.add, window, strides_, "SAME"
                )
                y = y / counts
            else:
                y = y / (spec.window[0] * spec.window[1])
        else:
            raise ValueError(f"unknown pool kind {spec.kind!r}")
        return y, state

    if isinstance(spec, GlobalPool):
        if spec.kind == "avg":
            return jnp.mean(x, axis=tuple(range(1, x.ndim - 1))), state
        if spec.kind == "seq_mean":
            return jnp.mean(x, axis=1), state
        if spec.kind == "cls":
            return x[:, 0], state
        raise ValueError(f"unknown global pool kind {spec.kind!r}")

    if isinstance(spec, Flatten):
        return x.reshape(x.shape[0], -1), state

    if isinstance(spec, Reshape):
        target = _reshape_target(spec.shape, x.shape[1:])
        return x.reshape((x.shape[0],) + target), state

    if isinstance(spec, Dropout):
        if not train or spec.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError(f"Dropout {spec.name!r} needs an rng in train mode")
        keep = 1.0 - spec.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0), state

    if isinstance(spec, Embedding):
        return jnp.take(params["emb"], x, axis=0), state

    if isinstance(spec, ClsToken):
        tok = jnp.broadcast_to(
            params["tok"], (x.shape[0], 1, x.shape[-1])
        ).astype(x.dtype)
        return jnp.concatenate([tok, x], axis=1), state

    if isinstance(spec, PosEmbed):
        S = x.shape[-2]
        return x + params["emb"][:S], state

    if isinstance(spec, MultiHeadAttention):
        H, KV = spec.num_heads, spec.kv_heads
        # impl "ring"/"ulysses" = sequence parallelism: this rule is then
        # running under shard_map with the sequence dim sharded over a
        # "seq" mesh axis (parallel/sp.py's trainer), so RoPE needs the
        # GLOBAL position offset of this shard and the attention core is
        # the SP collective path.
        sp = spec.impl in ("ring", "ulysses")
        rope_offset = 0
        if sp:
            if taps is not None and not taps.empty():
                raise NotImplementedError(
                    "attribution taps under sequence parallelism — score "
                    "with a single-device or DP/TP placement instead"
                )
            try:
                rope_offset = lax.axis_index("seq") * x.shape[1]
            except NameError as e:
                raise RuntimeError(
                    f"attention {spec.name!r} has impl={spec.impl!r} "
                    f"(sequence parallelism) but is running outside "
                    f"shard_map with a 'seq' axis — use SPTrainer for "
                    f"training, or convert back with "
                    f"sp_model(model, 'auto') for single-device "
                    f"apply/scoring/generation"
                ) from e
        # qdot contracts x's trailing axis with the weight's leading one
        # (== einsum bsd,dhk->bshk) and routes int4 weights through the
        # fused-unpack kernel (ops/quant.qdot)
        q = oscale(qdot(x, params["wq"]), params["wq"])
        k = oscale(qdot(x, params["wk"]), params["wk"])
        v = oscale(qdot(x, params["wv"]), params["wv"])
        if "bq" in params:
            q = q + params["bq"]
            k = k + params["bk"]
            v = v + params["bv"]
        if spec.rope:
            q = _rope(q, spec.rope_theta, offset=rope_offset)
            k = _rope(k, spec.rope_theta, offset=rope_offset)
        if KV != H or spec.kv_group is not None:
            idx = jnp.asarray(spec.head_kv_index())
            k = jnp.take(k, idx, axis=2)
            v = jnp.take(v, idx, axis=2)
        if sp:
            from torchpruner_tpu.parallel.ring import ring_attention_local
            from torchpruner_tpu.parallel.ulysses import (
                ulysses_attention_local,
            )

            local = (ring_attention_local if spec.impl == "ring"
                     else ulysses_attention_local)
            ctx = local(q, k, v, axis="seq", causal=spec.causal)
        else:
            ctx = attention_core(q, k, v, causal=spec.causal, impl=spec.impl)
        if taps is not None and not taps.empty():
            # head unit site: (B, S, Dh, H) — head axis last, uniform with
            # channel sites for masking/capture/attribution.
            zh = jnp.moveaxis(ctx, 2, 3)
            zh = taps.at_site(path, zh)
            ctx = jnp.moveaxis(zh, 3, 2)
        y = oscale(jnp.einsum("bshk,hkd->bsd", ctx,
                              wval(params["wo"], ctx.dtype)), params["wo"])
        if "bo" in params:
            y = y + params["bo"]
        return y, state

    if isinstance(spec, GatedDense):
        g = oscale(qdot(x, params["wg"]), params["wg"])
        u = oscale(qdot(x, params["wu"]), params["wu"])
        if "bg" in params:
            g = g + params["bg"]
            u = u + params["bu"]
        return ACTIVATION_FNS[spec.fn](g) * u, state

    if isinstance(spec, MoE):
        E = spec.n_experts
        raw_logits = x @ params["router"]  # (B, S, E)
        logits = raw_logits
        if spec.top_k < E:
            # keep the top-k logits per token; softmax over those only
            kth = jnp.sort(logits, axis=-1)[..., E - spec.top_k]
            neg = jnp.finfo(logits.dtype).min
            logits = jnp.where(logits >= kth[..., None], logits, neg)
        routing = jax.nn.softmax(logits, axis=-1)  # (B, S, E)
        gates = routing
        if taps is not None and not taps.empty():
            gates = taps.at_site(path, gates)  # expert unit site
        if (
            taps is not None and taps.collect_aux and train
            and spec.top_k < E
        ):
            # With dense routing (top_k == E) the loss is a gradient-free
            # constant 1.0 (f uniform, sum(P)=1), so collecting it would
            # make moe_aux_weight>0 silently do nothing — skip instead.
            # Switch/Mixtral load-balancing loss: E * sum_e f_e * P_e with
            # f_e the dispatch fraction (top-k membership / top_k) and P_e
            # the mean FULL-softmax router probability; equals 1.0 when
            # perfectly balanced, grows as experts collapse
            full_p = jax.nn.softmax(raw_logits, axis=-1)
            chosen = (routing > 0).astype(jnp.float32)
            f = jnp.mean(chosen, axis=(0, 1)) / spec.top_k
            p_mean = jnp.mean(full_p, axis=(0, 1))
            taps.aux["/".join(path)] = E * jnp.sum(f * p_mean)
        if spec.dispatch == "sparse" and spec.top_k < E:
            # routing decisions come from the PRE-tap gates: ablating an
            # expert through the tap zeroes its contribution (dense
            # semantics) without letting zero-gate filler pairs leak into
            # other experts' capacity
            return _moe_sparse(spec, params, x, routing, gates), state
        g = oscale(jnp.einsum("bsd,edf->bsef", x,
                              wval(params["wg"], x.dtype)), params["wg"])
        u = oscale(jnp.einsum("bsd,edf->bsef", x,
                              wval(params["wu"], x.dtype)), params["wu"])
        h = ACTIVATION_FNS[spec.fn](g) * u  # (B, S, E, F)
        y = oscale(jnp.einsum(
            "bsef,efd->bsd", h * gates[..., None],
            wval(params["wo"], h.dtype)), params["wo"])
        return y, state

    if isinstance(spec, Residual):
        r_body = r_sc = None
        if rng is not None:
            r_body, r_sc = jax.random.split(rng)
        y, body_state = apply_seq(
            spec.body, params, state, x,
            train=train, rng=r_body, taps=taps, prefix=path,
        )
        if spec.shortcut:
            sc, sc_state = apply_seq(
                spec.shortcut, params, state, x,
                train=train, rng=r_sc, taps=taps, prefix=path,
            )
            new_state = dict(body_state)
            for name, s in sc_state.items():
                if name not in (c.name for c in spec.body):
                    new_state[name] = s
        else:
            sc = x
            new_state = body_state
        return y + sc, new_state

    raise TypeError(f"unknown layer spec {type(spec)}")


def _moe_sparse(spec: MoE, params, x, routing, gates):
    """Capacity-based sparse expert dispatch (see :class:`MoE`).

    Shapes are fully static: ``P = tokens * top_k`` token-expert pairs are
    stable-sorted by expert, each pair's slot within its expert computed
    from an exclusive prefix sum of expert loads, pairs beyond the static
    capacity ``C`` routed to a shed slot that is sliced off.  The expert
    matmuls run at ``(E, C, ·)`` — per-token FLOPs scale with ``top_k/E``
    instead of the dense formulation's every-expert-every-token.  The
    gather/scatter is differentiable (scatter-add transposes to gather), so
    gradients match the dense path exactly whenever nothing is dropped.

    ``routing`` (pre-instrumentation gates) decides WHICH experts each
    token visits; ``gates`` (possibly tapped/ablated by attribution
    instrumentation) only WEIGHTS the contributions — so unit-mask
    ablation behaves exactly as in the dense formulation.
    """
    B, S, d = x.shape
    E, K = spec.n_experts, spec.top_k
    N = B * S
    xf = x.reshape(N, d)
    rf = routing.reshape(N, E)
    # the K nonzero routing gates per token (the softmax zeroed the rest);
    # top_k on those values reproduces the routing choice made on logits
    _, top_e = lax.top_k(rf, K)  # (N, K)
    P = N * K
    e_flat = top_e.reshape(P)
    t_flat = jnp.repeat(jnp.arange(N), K)
    g_flat = gates.reshape(N, E)[t_flat, e_flat]  # tapped weights
    C = min(N, int(math.ceil(N * K / E * spec.capacity_factor)))

    order = jnp.argsort(e_flat, stable=True)  # group pairs by expert
    e_s, g_s, t_s = e_flat[order], g_flat[order], t_flat[order]
    counts = jnp.bincount(e_flat, length=E)
    starts = jnp.cumsum(counts) - counts  # exclusive prefix: group offsets
    pos = jnp.arange(P) - starts[e_s]  # slot within the expert's buffer
    keep = pos < C
    slot = jnp.where(keep, pos, C)  # shed slot C is sliced off below

    buf = (
        jnp.zeros((E, C + 1, d), xf.dtype).at[e_s, slot].set(xf[t_s])[:, :C]
    )

    # int8 expert planes: the (E, C, ·) buffers have the WEIGHT's rank,
    # so the keepdims scale multiplies positionally (oscale's trailing-
    # broadcast form would misalign E against C) — exact because each
    # scale element is constant across its expert's contraction
    def _scaled(y, w):
        return y * w.scale.astype(y.dtype) if isinstance(w, QTensor) else y

    g = _scaled(jnp.einsum("ecd,edf->ecf", buf,
                           wval(params["wg"], buf.dtype)), params["wg"])
    u = _scaled(jnp.einsum("ecd,edf->ecf", buf,
                           wval(params["wu"], buf.dtype)), params["wu"])
    h = ACTIVATION_FNS[spec.fn](g) * u  # (E, C, F)
    out = _scaled(jnp.einsum("ecf,efd->ecd", h,
                             wval(params["wo"], h.dtype)), params["wo"])
    contrib = out[e_s, jnp.minimum(slot, C - 1)] * jnp.where(
        keep, g_s, 0.0
    )[:, None]
    y = jnp.zeros((N, d), out.dtype).at[t_s].add(contrib)
    return y.reshape(B, S, d)


# ---------------------------------------------------------------------------
# Prunable-unit helpers
# ---------------------------------------------------------------------------


def n_units(spec: LayerSpec) -> int:
    """Number of prunable output units of a prunable layer."""
    if isinstance(spec, (Dense, Conv, GatedDense)):
        return spec.features
    if isinstance(spec, MultiHeadAttention):
        return spec.num_heads
    if isinstance(spec, MoE):
        return spec.n_experts
    raise TypeError(f"{type(spec).__name__} has no prunable units")


def with_features(spec: LayerSpec, features: int) -> LayerSpec:
    """Return a copy of a prunable spec with a new unit count."""
    if isinstance(spec, (Dense, Conv, GatedDense)):
        return dataclasses.replace(spec, features=features)
    if isinstance(spec, MoE):
        return dataclasses.replace(
            spec, n_experts=features, top_k=min(spec.top_k, features)
        )
    if isinstance(spec, MultiHeadAttention):
        if spec.kv_group is not None:
            raise ValueError(
                f"MHA {spec.name!r} has an irregular kv_group; resize it "
                "with pruned_spec(spec, keep) so the grouping stays valid"
            )
        kv = features if spec.kv_heads == spec.num_heads else spec.num_kv_heads
        return dataclasses.replace(spec, num_heads=features, num_kv_heads=kv)
    raise TypeError(f"{type(spec).__name__} has no feature count")


def pruned_spec(spec: LayerSpec, keep) -> LayerSpec:
    """The spec after keeping exactly the units ``keep`` (sorted indices).
    Unlike :func:`with_features` this sees *which* units survive — needed for
    GQA attention, where pruning query heads makes the head->KV-group mapping
    irregular and it must be recorded on the spec."""
    keep = list(keep)
    if isinstance(spec, MultiHeadAttention):
        if spec.kv_heads == spec.num_heads and spec.kv_group is None:
            # non-GQA: KV heads sliced alongside query heads, mapping stays
            # the identity
            return dataclasses.replace(
                spec, num_heads=len(keep), num_kv_heads=len(keep)
                if spec.num_kv_heads is not None else None,
            )
        group = spec.head_kv_index()
        return dataclasses.replace(
            spec,
            num_heads=len(keep),
            kv_group=tuple(group[h] for h in keep),
        )
    return with_features(spec, len(keep))

"""Layer specifications and their functional init/apply rules.

Design notes (TPU-first):

- All activation layouts are **channels-last** (``NHWC`` for images, ``(B, F)``
  for vectors).  The prunable *unit* axis is therefore always the **last** axis
  of an activation, so unit masking, Shapley scans and flatten fan-out maps are
  uniform across Dense and Conv layers.  (The reference library works on torch's
  ``NCHW`` and hardcodes "dim 1" everywhere, e.g. reference
  torchpruner/pruner/pruner.py:129-168; channels-last is both the natural JAX
  convention and what XLA tiles best onto the MXU.)
- Layer specs are frozen, hashable dataclasses.  A model spec is static data:
  it can key jit caches, and *changing* it (pruning!) naturally triggers
  retracing at the new shapes.
- Parameters and mutable state (BatchNorm running statistics) are plain
  pytrees ``{layer_name: {param_name: array}}``; apply rules are pure
  functions ``(spec, params, state, x) -> (y, new_state)``.

Parameter layouts:

- Dense: ``w`` is ``(in, out)``, ``b`` is ``(out,)``.  Out-prune = axis 1 of
  ``w`` / axis 0 of ``b``; in-prune = axis 0 of ``w``.
- Conv: ``w`` is ``HWIO``, ``b`` is ``(out,)``.  Out-prune = axis 3; in-prune
  = axis 2.  (Reference prunes torch ``OIHW`` axis 0 / axis 1, reference
  pruner.py:81-85.)
- BatchNorm: ``scale``/``bias`` params and ``mean``/``var`` state, all
  ``(features,)`` — in-pruned along axis 0 (reference pruner.py:86-90).
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

# ---------------------------------------------------------------------------
# Layer specs
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Dense:
    """Fully-connected layer. Prunable (out units = features)."""

    name: str
    features: int
    use_bias: bool = True


@dataclass(frozen=True)
class Conv:
    """2-D convolution, NHWC/HWIO. Prunable (out units = channels)."""

    name: str
    features: int
    kernel_size: Tuple[int, int] = (3, 3)
    strides: Tuple[int, int] = (1, 1)
    padding: str = "SAME"  # "SAME" | "VALID"
    use_bias: bool = True


@dataclass(frozen=True)
class BatchNorm:
    """Batch normalization over the last axis; functional running stats.

    ``decay`` is the running-average retention factor:
    ``new_running = decay * running + (1 - decay) * batch_stat``.
    """

    name: str
    decay: float = 0.9
    eps: float = 1e-5


#: Activation function registry. Mirrors the reference's ACTIVATIONS set
#: (reference torchpruner/utils/graph.py:6) for evaluation-point shifting.
ACTIVATION_FNS: dict = {
    "relu": jax.nn.relu,
    "relu6": jax.nn.relu6,
    "leaky_relu": jax.nn.leaky_relu,  # slope 0.01, same default as torch
    "sigmoid": jax.nn.sigmoid,
    "softplus": jax.nn.softplus,
    "tanh": jnp.tanh,
    "gelu": jax.nn.gelu,
    "silu": jax.nn.silu,
    "identity": lambda x: x,
}


@dataclass(frozen=True)
class Activation:
    name: str
    fn: str = "relu"

    def __post_init__(self):
        if self.fn not in ACTIVATION_FNS:
            raise ValueError(f"unknown activation {self.fn!r}")


@dataclass(frozen=True)
class Pool:
    """2-D max/avg pooling on NHWC."""

    name: str
    kind: str = "max"  # "max" | "avg"
    window: Tuple[int, int] = (2, 2)
    strides: Optional[Tuple[int, int]] = None  # default: == window


@dataclass(frozen=True)
class Flatten:
    """Flatten all non-batch axes, row-major: (B,H,W,C) -> (B, H*W*C).

    With channels-last, channel ``c`` of the input maps to flat indices
    ``{p * C + c : p in range(H*W)}`` — the fan-out map used when a pruned
    conv channel cascades into a Dense consumer (the case the reference
    discovers with its NaN trick, reference tests/test_pruner.py:83-92).
    """

    name: str


@dataclass(frozen=True)
class Dropout:
    """Dropout. ``rate`` is the drop probability; rescaled on pruning so the
    expected number of active units is preserved (reference pruner.py:117-127).
    """

    name: str
    rate: float = 0.5


LayerSpec = Any  # union of the above dataclasses

PRUNABLE_TYPES = (Dense, Conv)  # can be out-pruned (reference pruner.py:11)
ATTACHABLE_TYPES = (BatchNorm, Dropout)  # in-pruned alongside a producer


# ---------------------------------------------------------------------------
# init rules: (spec, key, in_shape) -> (params, state, out_shape)
# in_shape/out_shape exclude the batch dimension.
# ---------------------------------------------------------------------------


def _kaiming(key, shape, fan_in, dtype):
    return jax.random.normal(key, shape, dtype) * jnp.sqrt(2.0 / fan_in)


def out_shape(spec: LayerSpec, in_shape: Tuple[int, ...]) -> Tuple[int, ...]:
    """The single source of truth for per-layer output shapes (batch dim
    excluded) — used by init, by ``SegmentedModel.shapes``, and by the
    pruning-graph fan-out computation."""
    if isinstance(spec, Dense):
        return (spec.features,)
    if isinstance(spec, Conv):
        h, w = in_shape[0], in_shape[1]
        oh, ow = _conv_out_hw((h, w), spec)
        return (oh, ow, spec.features)
    if isinstance(spec, Pool):
        strides = spec.strides or spec.window
        oh = (in_shape[0] - spec.window[0]) // strides[0] + 1
        ow = (in_shape[1] - spec.window[1]) // strides[1] + 1
        return (oh, ow) + tuple(in_shape[2:])
    if isinstance(spec, Flatten):
        size = 1
        for d in in_shape:
            size *= d
        return (size,)
    return tuple(in_shape)


def init_layer(spec: LayerSpec, key, in_shape: Tuple[int, ...], dtype=jnp.float32):
    """Initialize one layer. Returns ``(params, state, out_shape)``; ``params``
    / ``state`` are ``{}`` for parameter-free / stateless layers."""
    if isinstance(spec, Dense):
        if len(in_shape) != 1:
            raise ValueError(
                f"Dense {spec.name!r} expects flat input, got shape {in_shape}"
            )
        kw, _ = jax.random.split(key)
        params = {"w": _kaiming(kw, (in_shape[0], spec.features), in_shape[0], dtype)}
        if spec.use_bias:
            params["b"] = jnp.zeros((spec.features,), dtype)
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, Conv):
        if len(in_shape) != 3:
            raise ValueError(
                f"Conv {spec.name!r} expects HWC input, got shape {in_shape}"
            )
        h, w, c = in_shape
        kh, kw_ = spec.kernel_size
        fan_in = kh * kw_ * c
        k1, _ = jax.random.split(key)
        params = {"w": _kaiming(k1, (kh, kw_, c, spec.features), fan_in, dtype)}
        if spec.use_bias:
            params["b"] = jnp.zeros((spec.features,), dtype)
        return params, {}, out_shape(spec, in_shape)

    if isinstance(spec, BatchNorm):
        f = in_shape[-1]
        params = {"scale": jnp.ones((f,), dtype), "bias": jnp.zeros((f,), dtype)}
        state = {"mean": jnp.zeros((f,), dtype), "var": jnp.ones((f,), dtype)}
        return params, state, in_shape

    if isinstance(spec, (Pool, Flatten, Activation, Dropout)):
        return {}, {}, out_shape(spec, in_shape)

    raise TypeError(f"unknown layer spec {type(spec)}")


def _conv_out_hw(hw, spec: Conv):
    h, w = hw
    sh, sw = spec.strides
    if spec.padding == "SAME":
        return -(-h // sh), -(-w // sw)
    kh, kw = spec.kernel_size
    return (h - kh) // sh + 1, (w - kw) // sw + 1


# ---------------------------------------------------------------------------
# apply rules: (spec, params, state, x, train, rng) -> (y, new_state)
# ---------------------------------------------------------------------------


def apply_layer(
    spec: LayerSpec,
    params,
    state,
    x,
    *,
    train: bool = False,
    rng=None,
):
    """Apply one layer. Pure; returns ``(y, new_state)``."""
    if isinstance(spec, Dense):
        y = x @ params["w"]
        if "b" in params:
            y = y + params["b"]
        return y, state

    if isinstance(spec, Conv):
        y = lax.conv_general_dilated(
            x,
            params["w"],
            window_strides=spec.strides,
            padding=spec.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        if "b" in params:
            y = y + params["b"]
        return y, state

    if isinstance(spec, BatchNorm):
        if train:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x, axes)
            var = jnp.var(x, axes)
            new_state = {
                "mean": spec.decay * state["mean"] + (1 - spec.decay) * mean,
                "var": spec.decay * state["var"] + (1 - spec.decay) * var,
            }
        else:
            mean, var = state["mean"], state["var"]
            new_state = state
        inv = lax.rsqrt(var + spec.eps)
        y = (x - mean) * inv * params["scale"] + params["bias"]
        return y, new_state

    if isinstance(spec, Activation):
        return ACTIVATION_FNS[spec.fn](x), state

    if isinstance(spec, Pool):
        strides = spec.strides or spec.window
        window = (1,) + tuple(spec.window) + (1,)
        strides_ = (1,) + tuple(strides) + (1,)
        if spec.kind == "max":
            y = lax.reduce_window(x, -jnp.inf, lax.max, window, strides_, "VALID")
        elif spec.kind == "avg":
            y = lax.reduce_window(x, 0.0, lax.add, window, strides_, "VALID")
            y = y / (spec.window[0] * spec.window[1])
        else:
            raise ValueError(f"unknown pool kind {spec.kind!r}")
        return y, state

    if isinstance(spec, Flatten):
        return x.reshape(x.shape[0], -1), state

    if isinstance(spec, Dropout):
        if not train or spec.rate == 0.0:
            return x, state
        if rng is None:
            raise ValueError(f"Dropout {spec.name!r} needs an rng in train mode")
        keep = 1.0 - spec.rate
        mask = jax.random.bernoulli(rng, keep, x.shape)
        return jnp.where(mask, x / keep, 0.0), state

    raise TypeError(f"unknown layer spec {type(spec)}")


def n_units(spec: LayerSpec) -> int:
    """Number of prunable output units of a prunable layer."""
    if isinstance(spec, (Dense, Conv)):
        return spec.features
    raise TypeError(f"{type(spec).__name__} has no prunable units")


def with_features(spec: LayerSpec, features: int) -> LayerSpec:
    """Return a copy of a prunable spec with a new unit count."""
    if isinstance(spec, (Dense, Conv)):
        return dataclasses.replace(spec, features=features)
    raise TypeError(f"{type(spec).__name__} has no feature count")

"""Pruning-graph inference and the NaN-propagation oracle.

The reference has three separate pruning-graph sources (hand-written
``get_vgg_pruning_graph``, a notebook re-implementation, and a hardcoded model
method — reference torchpruner/utils/graph.py:37-61, experiments/models/
fmnist.py:68-73), and discovers cascade indices dynamically by injecting NaNs
and running a forward pass (reference pruner/pruner.py:21-57).

Here there is ONE graph API, derived statically from the model spec (we own
the layer vocabulary, so cascades are computable), with the NaN trick kept as
an *oracle* used by tests to validate the static analysis — it runs eagerly in
jnp, outside jit, exactly because NaN-propagation is data-dependent control
flow XLA should never see.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.plan import AttachedNorm, Consumer, PruneGroup
from torchpruner_tpu.core.segment import SegmentedModel

#: Activations that evaluation-point shifting may skip over — mirrors the
#: reference's ACTIVATIONS set (reference torchpruner/utils/graph.py:6).
SHIFTABLE_ACTIVATIONS = frozenset(
    {"relu", "relu6", "leaky_relu", "sigmoid", "softplus", "tanh"}
)


def find_best_evaluation_layer(model: SegmentedModel, name: str) -> str:
    """Walk forward from ``name`` while the next layer is a BatchNorm or a
    shiftable activation; return the last such layer.  Scoring there measures
    units where pruning will actually cut — after BN + nonlinearity
    (reference torchpruner/utils/graph.py:9-34)."""
    i = model.index(name)
    best = name
    for spec in model.layers[i + 1:]:
        if isinstance(spec, L.BatchNorm) or (
            isinstance(spec, L.Activation) and spec.fn in SHIFTABLE_ACTIVATIONS
        ):
            best = spec.name
        else:
            break
    return best


def pruning_graph(
    model: SegmentedModel, include_output: bool = False
) -> Tuple[PruneGroup, ...]:
    """Derive the prune groups of a sequential model, in forward order.

    Each Dense/Conv starts a group; following BatchNorm/Dropout layers attach
    to it; the next Dense/Conv becomes its consumer, with the in-axis and
    fan-out determined by the layers in between (Flatten introduces the
    spatial fan-out).  The reference builds the same structure by scanning
    ``model.modules()`` (reference utils/graph.py:37-61) and then *re-derives*
    the index maps at prune time with NaNs; here the fan-out is static.

    ``include_output=False`` drops the final group (the classifier head),
    matching the reference convention of never pruning the output layer
    (reference utils/graph.py:59-61).
    """
    shapes = model.shapes
    groups = []
    current: Optional[dict] = None  # mutable build of the open group

    for i, spec in enumerate(model.layers):
        if isinstance(spec, L.PRUNABLE_TYPES):
            if current is not None:
                fan_out = current["fan_out"]
                axis = 0 if isinstance(spec, L.Dense) else 2
                current["consumers"].append(
                    Consumer(layer=spec.name, param="w", axis=axis, fan_out=fan_out)
                )
                groups.append(_close(current))
            current = {
                "target": spec.name,
                "bn": [],
                "dropout": [],
                "consumers": [],
                "fan_out": 1,
            }
        elif current is not None:
            if isinstance(spec, L.BatchNorm):
                current["bn"].append(
                    AttachedNorm(spec.name, fan_out=current["fan_out"])
                )
            elif isinstance(spec, L.Dropout):
                current["dropout"].append(spec.name)
            elif isinstance(spec, L.Flatten):
                in_shape = shapes[i][0]
                spatial = 1
                for d in in_shape[:-1]:
                    spatial *= d
                current["fan_out"] *= spatial
            # Activation / Pool: transparent for unit identity.

    if current is not None:
        groups.append(_close(current))
    if not include_output and groups and not groups[-1].consumers:
        groups = groups[:-1]
    return tuple(groups)


def group_for(model: SegmentedModel, layer: str) -> PruneGroup:
    """The prune group whose target is ``layer`` (output layer included)."""
    for g in pruning_graph(model, include_output=True):
        if g.target == layer:
            return g
    raise KeyError(f"{layer!r} is not a prunable layer of this model")


def _close(build: dict) -> PruneGroup:
    return PruneGroup(
        target=build["target"],
        attached_bn=tuple(build["bn"]),
        attached_dropout=tuple(build["dropout"]),
        consumers=tuple(build["consumers"]),
    )


# ---------------------------------------------------------------------------
# NaN oracle (validator for the static graph; reference pruner.py:21-57)
# ---------------------------------------------------------------------------


def nan_cascade_oracle(
    model: SegmentedModel,
    params,
    state,
    target: str,
    drop: Sequence[int],
    batch: int = 2,
    seed: int = 0,
) -> Dict[str, Tuple[np.ndarray, int]]:
    """Empirically discover cascade indices by NaN propagation.

    Injects NaN at the dropped unit positions of ``target``'s output, runs the
    model eagerly (eval mode, no jit), and reports for every *prunable or
    normalizing* downstream layer the NaN-tainted input positions along its
    unit axis, as ``{layer_name: (in_indices, original_len)}`` — the same
    contract as the reference's ``_detect_nan_hook`` (reference
    pruner.py:146-168).  Used in tests to validate :func:`pruning_graph`.
    """
    x = jax.random.normal(
        jax.random.PRNGKey(seed), (batch,) + tuple(model.input_shape)
    )
    drop = jnp.asarray(np.asarray(drop, dtype=np.int64))
    report: Dict[str, Tuple[np.ndarray, int]] = {}
    ti = model.index(target)

    detect_types = (L.Dense, L.Conv, L.BatchNorm, L.Dropout)
    for i, spec in enumerate(model.layers):
        if i > ti and isinstance(spec, detect_types):
            flat = x
            if flat.ndim > 2:
                # sum out batch + spatial, keep the trailing unit axis
                flat = flat.reshape(flat.shape[0], -1, flat.shape[-1])
            summed = jnp.sum(flat, axis=tuple(range(flat.ndim - 1)))
            nan_idx = np.asarray(jnp.nonzero(jnp.isnan(summed))[0])
            if nan_idx.size:
                report[spec.name] = (nan_idx, int(summed.shape[0]))
        p = params.get(spec.name, {})
        s = state.get(spec.name, {}) if state else {}
        x, _ = L.apply_layer(spec, p, s, x, train=False)
        if i == ti and drop.size:
            x = x.at[..., drop].set(jnp.nan)
    return report

"""Pruning-graph inference and the NaN-propagation oracle.

The reference has three separate pruning-graph sources (hand-written
``get_vgg_pruning_graph``, a notebook re-implementation, and a hardcoded model
method — reference torchpruner/utils/graph.py:37-61, experiments/models/
fmnist.py:68-73), and discovers cascade indices dynamically by injecting NaNs
and running a forward pass (reference pruner/pruner.py:21-57).

Here there is ONE graph API, derived statically from the model spec (we own
the layer vocabulary, so cascades are computable), with the NaN trick kept as
an *oracle* used by tests to validate the static analysis — it runs eagerly in
jnp, outside jit, exactly because NaN-propagation is data-dependent control
flow XLA should never see.

Composite blocks extend the same rules recursively:

- a :class:`~torchpruner_tpu.core.layers.Residual` body/shortcut is walked
  like a sequential model; a producer whose consumer lies within the same
  chain is prunable, while a producer whose output reaches the residual *sum*
  has its width pinned by the skip connection and is excluded — the block-level
  analog of never pruning the model's output layer (reference
  utils/graph.py:59-61);
- attention heads (:class:`MultiHeadAttention`) and GLU channels
  (:class:`GatedDense`) form groups whose surgery stays inside the layer/block
  (head pruning never changes the block's output width), so they are always
  prunable;
- a producer immediately *preceding* a projection-shortcut Residual (e.g. a
  ResNet stem conv) cascades into the first prunable layer of both the body
  and the shortcut chains.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.plan import AttachedNorm, Consumer, PruneGroup
from torchpruner_tpu.core.segment import SegmentedModel

#: Activations that evaluation-point shifting may skip over — mirrors the
#: reference's ACTIVATIONS set (reference torchpruner/utils/graph.py:6).
SHIFTABLE_ACTIVATIONS = frozenset(
    {"relu", "relu6", "leaky_relu", "sigmoid", "softplus", "tanh"}
)

#: width-changing prunable producers (attention heads are handled separately:
#: head pruning leaves the layer's output width unchanged).
_CHANNEL_PRODUCERS = (L.Dense, L.Conv, L.GatedDense)


def find_best_evaluation_layer(model: SegmentedModel, name: str) -> str:
    """Walk forward from ``name`` while the next layer is a Batch/Layer/RMS
    norm or a shiftable activation; return the last such layer.  Scoring there
    measures units where pruning will actually cut — after norm + nonlinearity
    (reference torchpruner/utils/graph.py:9-34).  Works inside Residual bodies
    for nested paths; attention/GLU targets are their own evaluation site."""
    path = L.parse_path(name)
    spec = model.layer(name)
    if isinstance(spec, (L.MultiHeadAttention, L.GatedDense, L.MoE)):
        return name
    if len(path) == 1:
        siblings = model.layers
    else:
        parent = model.layer("/".join(path[:-1]))
        siblings = parent.body if any(
            l.name == path[-1] for l in parent.body
        ) else parent.shortcut
    idx = next(i for i, l in enumerate(siblings) if l.name == path[-1])
    best = path[-1]
    for nxt in siblings[idx + 1:]:
        if isinstance(nxt, (L.BatchNorm, L.LayerNorm, L.RMSNorm)) or (
            isinstance(nxt, L.Activation) and nxt.fn in SHIFTABLE_ACTIVATIONS
        ):
            best = nxt.name
        else:
            break
    return "/".join(path[:-1] + (best,))


def pruning_graph(
    model: SegmentedModel, include_output: bool = False
) -> Tuple[PruneGroup, ...]:
    """Derive the prune groups of a model, in forward order, recursing into
    composite blocks.

    Each Dense/Conv/GatedDense starts a width-changing group; following
    norm/Dropout layers attach to it; the next prunable layer becomes its
    consumer, with the in-axis and fan-out determined by the layers in between
    (Flatten introduces the spatial fan-out).  MultiHeadAttention layers form
    self-contained head groups.  The reference builds the sequential version
    of this by scanning ``model.modules()`` (reference utils/graph.py:37-61)
    and then *re-derives* the index maps at prune time with NaNs; here the
    fan-out is static.

    ``include_output=False`` drops the final top-level group (the classifier
    head), matching the reference convention of never pruning the output layer
    (reference utils/graph.py:59-61).  Groups whose producer feeds a residual
    sum are always excluded (width pinned by the skip connection).
    """
    groups: List[PruneGroup] = []
    open_group = _walk(model.layers, (), tuple(model.input_shape), groups)
    if include_output and open_group is not None:
        groups.append(_close(open_group))
    return tuple(groups)


def group_for(model: SegmentedModel, layer: str) -> PruneGroup:
    """The prune group whose target is ``layer`` (output layer included)."""
    for g in pruning_graph(model, include_output=True):
        if g.target == layer:
            return g
    raise KeyError(f"{layer!r} is not a prunable layer of this model")


def _join(prefix: Tuple[str, ...], name: str) -> str:
    return "/".join(prefix + (name,))


def _consumer_entries(spec: L.LayerSpec, path: str, fan_out: int):
    """Consumer slices when ``spec``'s *input* width shrinks, or ``None``
    when the consumer cannot safely absorb an input-width change — its
    output width *follows* its input width (attention with
    ``out_features=None``; MoE, whose output dim is ``wo``'s last axis) —
    in which case the producer is width-pinned, exactly like a producer
    feeding a residual sum."""
    if isinstance(spec, L.Dense):
        return [Consumer(path, "w", axis=0, fan_out=fan_out)]
    if isinstance(spec, L.Conv):
        return [Consumer(path, "w", axis=2, fan_out=fan_out)]
    if isinstance(spec, L.GatedDense):
        return [
            Consumer(path, "wg", axis=0, fan_out=fan_out),
            Consumer(path, "wu", axis=0, fan_out=fan_out),
        ]
    if isinstance(spec, L.MultiHeadAttention):
        if spec.out_features is None:
            return None  # output width tied to input width — pin
        return [
            Consumer(path, "wq", axis=0, fan_out=fan_out),
            Consumer(path, "wk", axis=0, fan_out=fan_out),
            Consumer(path, "wv", axis=0, fan_out=fan_out),
        ]
    if isinstance(spec, L.MoE):
        return None  # output width tied to input width — pin
    raise TypeError(f"{type(spec).__name__} cannot consume")


def _walk(
    layers: Tuple[L.LayerSpec, ...],
    prefix: Tuple[str, ...],
    in_shape: Tuple[int, ...],
    groups: List[PruneGroup],
) -> Optional[dict]:
    """Walk one sequential scope; append closed groups to ``groups``; return
    the group still open at scope end (its producer's output is the scope
    output), or None."""
    shapes = L.seq_shapes(layers, in_shape)
    current: Optional[dict] = None

    for i, spec in enumerate(layers):
        path = _join(prefix, spec.name)

        if isinstance(spec, (L.MultiHeadAttention, L.MoE)):
            if current is not None:
                entries = _consumer_entries(spec, path, current["fan_out"])
                if entries is None:
                    current = None  # width pinned by the consumer's output
                else:
                    current["consumers"] += entries
                    groups.append(_close(current))
                    current = None
            # self-contained head/expert group: output width unchanged
            groups.append(PruneGroup(target=path))

        elif isinstance(spec, _CHANNEL_PRODUCERS):
            if current is not None:
                current["consumers"] += _consumer_entries(
                    spec, path, current["fan_out"]
                )
                groups.append(_close(current))
            current = {
                "target": path,
                "bn": [],
                "dropout": [],
                "consumers": [],
                "fan_out": 1,
            }

        elif isinstance(spec, L.Residual):
            if current is not None:
                if _consume_into_residual(
                    spec, prefix + (spec.name,), current
                ):
                    groups.append(_close(current))
                # else: output feeds an identity skip — width pinned, drop
                current = None
            block_in = shapes[i][0]
            body_open = _walk(
                spec.body, prefix + (spec.name,), block_in, groups
            )
            # body-final producer feeds the residual sum: width pinned, drop
            if spec.shortcut:
                _walk(spec.shortcut, prefix + (spec.name,), block_in, groups)

        elif current is not None:
            if isinstance(spec, (L.BatchNorm, L.LayerNorm, L.RMSNorm)):
                current["bn"].append(
                    AttachedNorm(path, fan_out=current["fan_out"])
                )
            elif isinstance(spec, L.Dropout):
                current["dropout"].append(path)
            elif isinstance(spec, L.Flatten):
                spatial = 1
                for d in shapes[i][0][:-1]:
                    spatial *= d
                current["fan_out"] *= spatial
            elif isinstance(spec, L.Reshape):
                if shapes[i][1][-1] != shapes[i][0][-1]:
                    # unit identity lost (channels folded) — conservative drop
                    current = None
            elif isinstance(spec, (L.Embedding, L.PosEmbed, L.ClsToken)):
                current = None  # unit identity lost (added params share the
                # producer's channel width but are not sliced with it)
            # Activation / Pool / GlobalPool: transparent for unit identity.

    return current


def _consume_into_residual(
    res: L.Residual, res_prefix: Tuple[str, ...], group: dict
) -> bool:
    """Try to cascade an open producer group into a Residual block it feeds.

    Possible only with a projection shortcut (an identity skip pins the
    producer's width); both the body and the shortcut chains must begin with
    (norms/transparent layers followed by) a prunable consumer.  Mutates
    ``group`` with the discovered attachments/consumers on success."""
    if not res.shortcut:
        return False
    bn, consumers = [], []
    for chain in (res.body, res.shortcut):
        found = False
        for spec in chain:
            path = _join(res_prefix, spec.name)
            if isinstance(spec, (L.BatchNorm, L.LayerNorm, L.RMSNorm)):
                bn.append(AttachedNorm(path, fan_out=group["fan_out"]))
            elif isinstance(spec, (L.Activation, L.Pool, L.GlobalPool)):
                pass  # transparent
            elif isinstance(
                spec, _CHANNEL_PRODUCERS + (L.MultiHeadAttention, L.MoE)
            ):
                entries = _consumer_entries(spec, path, group["fan_out"])
                if entries is None:
                    return False  # consumer's output width follows input
                consumers += entries
                found = True
                break
            else:
                return False  # nested block / reshape before a consumer
        if not found:
            return False
    group["bn"] += bn
    group["consumers"] += consumers
    return True


def _close(build: dict) -> PruneGroup:
    return PruneGroup(
        target=build["target"],
        attached_bn=tuple(build["bn"]),
        attached_dropout=tuple(build["dropout"]),
        consumers=tuple(build["consumers"]),
    )


# ---------------------------------------------------------------------------
# NaN oracle (validator for the static graph; reference pruner.py:21-57)
# ---------------------------------------------------------------------------


def nan_cascade_oracle(
    model: SegmentedModel,
    params,
    state,
    target: str,
    drop: Sequence[int],
    batch: int = 2,
    seed: int = 0,
) -> Dict[str, Tuple[np.ndarray, int]]:
    """Empirically discover cascade indices by NaN propagation (flat
    top-level models; composite models are validated by prune-vs-mask
    equivalence instead — see tests/test_blocks.py).

    Injects NaN at the dropped unit positions of ``target``'s output, runs the
    model eagerly (eval mode, no jit), and reports for every *prunable or
    normalizing* downstream layer the NaN-tainted input positions along its
    unit axis, as ``{layer_name: (in_indices, original_len)}`` — the same
    contract as the reference's ``_detect_nan_hook`` (reference
    pruner.py:146-168).  Used in tests to validate :func:`pruning_graph`.
    """
    x = jax.random.normal(
        jax.random.PRNGKey(seed), (batch,) + tuple(model.input_shape)
    )
    drop = jnp.asarray(np.asarray(drop, dtype=np.int64))
    report: Dict[str, Tuple[np.ndarray, int]] = {}
    ti = model.index(target)

    detect_types = (L.Dense, L.Conv, L.BatchNorm, L.Dropout)
    for i, spec in enumerate(model.layers):
        if i > ti and isinstance(spec, detect_types):
            flat = x
            if flat.ndim > 2:
                # sum out batch + spatial, keep the trailing unit axis
                flat = flat.reshape(flat.shape[0], -1, flat.shape[-1])
            summed = jnp.sum(flat, axis=tuple(range(flat.ndim - 1)))
            nan_idx = np.asarray(jnp.nonzero(jnp.isnan(summed))[0])
            if nan_idx.size:
                report[spec.name] = (nan_idx, int(summed.shape[0]))
        p = params.get(spec.name, {})
        s = state.get(spec.name, {}) if state else {}
        x, _ = L.apply_layer(spec, p, s, x, train=False)
        if i == ti and drop.size:
            x = x.at[..., drop].set(jnp.nan)
    return report

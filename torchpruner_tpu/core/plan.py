"""Pruning plans — declarative descriptions of structural surgery.

The reference *discovers* what to slice at run time with its NaN trick and
mutates live tensors in place (reference torchpruner/pruner/pruner.py:21-115).
Here the same knowledge is a static datatype:

- a :class:`ParamSlice` names one array (by pytree path), the axis holding the
  unit dimension, and a ``fan_out`` factor for flattened consumers;
- a :class:`PruneGroup` bundles the slices implied by pruning one producer
  layer: its own out-params, attached BatchNorm/Dropout, and consumer
  in-params;
- :func:`apply_plan` executes the slices functionally with ``jnp.take`` over
  arbitrary pytrees (params, BN state, optax optimizer state).

Plans are model-family-agnostic: sequential ``SegmentedModel`` graphs are
*inferred* (core/graph.py), while non-sequential families (transformer FFN /
attention-head pruning) declare their groups explicitly with pytree paths.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Sequence, Tuple

import jax
import numpy as np
import jax.numpy as jnp

Path = Tuple[Any, ...]  # keys into a nested-dict pytree


class PlanError(ValueError):
    """A plan does not fit the pytrees it is being applied to.

    Raised by :func:`apply_plan`'s pre-flight — the plan-lint pass of the
    static analyzer (analysis/plan_lint.py) run over the actual trees —
    so the message names the offending pytree path, axis and check
    (instead of whatever ``jnp.take``/``KeyError`` would surface deep in
    the slicing loop).  ``findings`` carries the structured records.
    """

    def __init__(self, findings):
        self.findings = tuple(findings)
        super().__init__(
            "plan does not fit the provided pytrees:\n"
            + "\n".join("  " + f.format() for f in self.findings)
        )


@dataclass(frozen=True)
class ParamSlice:
    """Slice one array along ``axis``, keeping the rows for surviving units.

    ``fan_out > 1`` means each producer unit ``u`` owns ``fan_out`` contiguous
    *strided* positions ``{p * n_units + u}`` along the axis — the
    channels-last flatten map (a conv channel fanning out into H*W inputs of a
    Dense consumer; the case the reference resolves dynamically in
    tests/test_pruner.py:83-92).

    ``collection`` selects which pytree the path indexes: ``"params"`` or
    ``"state"`` (BatchNorm running statistics).
    """

    path: Path
    axis: int
    fan_out: int = 1
    collection: str = "params"
    #: optional slices (e.g. a bias that may be absent with use_bias=False)
    #: are skipped silently; any other unresolvable path is an error.
    optional: bool = False


@dataclass(frozen=True)
class Consumer:
    """A downstream layer whose *input* units cascade from the target."""

    layer: str
    param: str = "w"
    axis: int = 0
    fan_out: int = 1


@dataclass(frozen=True)
class AttachedNorm:
    """A normalization layer sliced alongside the target.  ``fan_out > 1``
    when the norm sits after a Flatten (its feature axis then holds
    ``fan_out`` positions per producer unit)."""

    layer: str
    fan_out: int = 1


@dataclass(frozen=True)
class PruneGroup:
    """Everything that must change when units of ``target`` are pruned."""

    target: str
    attached_bn: Tuple[AttachedNorm, ...] = ()
    attached_dropout: Tuple[str, ...] = ()
    consumers: Tuple[Consumer, ...] = ()


@dataclass(frozen=True)
class PrunePlan:
    """A fully-resolved set of slices for one prune step.

    ``n_units`` is the producer's current width; ``slices`` all refer to unit
    indices in ``range(n_units)``.
    """

    n_units: int
    slices: Tuple[ParamSlice, ...]


def keep_indices(n_units: int, drop: Sequence[int]) -> np.ndarray:
    """Complement of ``drop`` in ``range(n_units)`` (sorted). Mirrors the
    boolean-mask construction in reference pruner.py:100-105."""
    mask = np.ones(n_units, dtype=bool)
    drop = np.unique(np.asarray(drop, dtype=np.int64))
    if drop.size:
        if drop.min() < 0 or drop.max() >= n_units:
            raise IndexError(
                f"drop indices out of range [0, {n_units}): {drop}"
            )
        mask[drop] = False
    return np.arange(n_units)[mask]


def expand_keep(keep: np.ndarray, n_units: int, fan_out: int) -> np.ndarray:
    """Expand unit keep-indices through a fan-out map: kept positions are
    ``{p * n_units + u : p in range(fan_out), u in keep}``, sorted ascending
    (which preserves the original memory order of a channels-last flatten)."""
    if fan_out == 1:
        return keep
    return (np.arange(fan_out)[:, None] * n_units + keep[None, :]).reshape(-1)


def _get_path(tree, path: Path):
    node = tree
    for k in path:
        node = node[k]
    return node


def _set_path(tree, path: Path, value):
    """Functional set: returns a copy of ``tree`` with ``tree[path] = value``.
    Works on nested dicts / lists / tuples."""
    if not path:
        return value
    k, rest = path[0], path[1:]
    if isinstance(tree, dict):
        new = dict(tree)
        new[k] = _set_path(tree[k], rest, value)
        return new
    if isinstance(tree, (list, tuple)):
        seq = list(tree)
        seq[k] = _set_path(seq[k], rest, value)
        return type(tree)(seq) if not isinstance(tree, list) else seq
    raise TypeError(f"cannot set path {path} in {type(tree)}")


def apply_plan(
    plan: PrunePlan,
    drop: Sequence[int],
    params,
    state=None,
    opt_state=None,
):
    """Execute a plan: slice every listed array, plus any matching arrays in
    the optimizer state (momentum / Adam moments / anything params-shaped —
    strictly more general than the reference's SGD-only optimizer pruning,
    reference pruner/opt_pruner.py:4-19).

    Returns ``(params', state', opt_state')`` (the latter two may be None if
    not given).

    Pre-flight: the analyzer's plan-lint pass runs over the given trees
    first (pure shape arithmetic, works under tracing), and any
    error-severity finding raises :class:`PlanError` naming the pytree
    path, axis and check — before a single array is touched.  Severities
    follow ``analysis.severity_config``: a check downgraded below error
    (or ignored) there no longer raises here either.
    """
    from torchpruner_tpu.analysis.findings import active_severity
    from torchpruner_tpu.analysis.plan_lint import lint_plan

    problems = [f for f in lint_plan(plan, params, state)
                if active_severity(f.check, f.severity) == "error"]
    if problems:
        raise PlanError(problems)

    keep = keep_indices(plan.n_units, drop)

    # (path -> (axis, expanded keep, old_shape)) for optimizer-state matching.
    param_slices: Dict[Tuple[str, ...], Tuple[int, np.ndarray, Tuple[int, ...]]] = {}

    new_params, new_state = params, state
    for s in plan.slices:
        tree = new_params if s.collection == "params" else new_state
        if tree is None:
            continue  # optional slice (lint guarantees non-optional exist)
        try:
            arr = _get_path(tree, s.path)
        except (KeyError, IndexError, TypeError):
            continue  # e.g. bias absent (use_bias=False): optional
        idx = expand_keep(keep, plan.n_units, s.fan_out)
        sliced = jnp.take(arr, idx, axis=s.axis)
        if s.collection == "params":
            param_slices[tuple(str(k) for k in s.path)] = (s.axis, idx, arr.shape)
            new_params = _set_path(new_params, s.path, sliced)
        else:
            new_state = _set_path(new_state, s.path, sliced)

    new_opt_state = opt_state
    if opt_state is not None:
        new_opt_state = _slice_opt_state(opt_state, param_slices)
    return new_params, new_state, new_opt_state


def plan_to_dict(plan: PrunePlan) -> dict:
    """JSON-safe dict form of a plan (CLI ``--lint-plan`` files)."""
    return {
        "n_units": plan.n_units,
        "slices": [
            {
                "path": list(s.path),
                "axis": s.axis,
                "fan_out": s.fan_out,
                "collection": s.collection,
                "optional": s.optional,
            }
            for s in plan.slices
        ],
    }


def plan_from_dict(d: dict) -> PrunePlan:
    """Inverse of :func:`plan_to_dict`; pytree path keys come back as the
    JSON types (strings / ints)."""
    return PrunePlan(
        n_units=int(d["n_units"]),
        slices=tuple(
            ParamSlice(
                path=tuple(s["path"]),
                axis=int(s["axis"]),
                fan_out=int(s.get("fan_out", 1)),
                collection=s.get("collection", "params"),
                optional=bool(s.get("optional", False)),
            )
            for s in d["slices"]
        ),
    )


def key_path_str(path) -> str:
    """Human ``a/b/c`` form of a ``tree_flatten_with_path`` key path —
    the ONE spelling of pytree paths shared by the analyzer's findings
    and the inline ``shard_params`` warning, so severity overrides and
    log messages always name the same string."""
    return "/".join(_key_name(k) for k in path)


def _key_name(k) -> str:
    """Human key for a tree_flatten_with_path key entry."""
    if isinstance(k, jax.tree_util.DictKey):
        return str(k.key)
    if isinstance(k, jax.tree_util.SequenceKey):
        return str(k.idx)
    if isinstance(k, jax.tree_util.GetAttrKey):
        return str(k.name)
    if isinstance(k, jax.tree_util.FlattenedIndexKey):
        return str(k.key)
    return str(k)


def _slice_opt_state(opt_state, param_slices):
    """Slice every optimizer-state leaf whose pytree path *ends with* a pruned
    parameter's path and whose shape matches the pre-slice parameter shape.

    Optax states mirror the params tree (e.g. ``TraceState.trace['fc1']['w']``,
    ``ScaleByAdamState.mu[...]``), so suffix-matching the path plus a shape
    check identifies exactly the params-like leaves; scalars like Adam's
    ``count`` fall through untouched.
    """
    leaves, treedef = jax.tree_util.tree_flatten_with_path(opt_state)
    new_leaves = []
    for path, leaf in leaves:
        names = tuple(_key_name(k) for k in path)
        replaced = leaf
        if hasattr(leaf, "shape"):
            for ppath, (axis, idx, old_shape) in param_slices.items():
                if (
                    len(names) >= len(ppath)
                    and names[-len(ppath):] == ppath
                    and tuple(leaf.shape) == tuple(old_shape)
                ):
                    replaced = jnp.take(leaf, idx, axis=axis)
                    break
        new_leaves.append(replaced)
    return jax.tree_util.tree_unflatten(treedef, new_leaves)

"""Mask-based (simulated) pruning — the no-recompile complement to
structural surgery.

Structural pruning (core/pruner.py) changes static shapes, which retraces
and recompiles every jitted computation (SURVEY.md §7 "recompilation
economics").  During *exploration* — sweeping ratios, iterating schedules,
fine-tuning toward a sparsity target — that bill can dominate.  This module
keeps shapes fixed instead: the SAME slices a structural prune would remove
(derived from the same ``PrunePlan``) are held at zero by masking the
parameters and, during training, the optimizer updates (an optax
transform, the JaxPruner-style integration point).  One final
:func:`~torchpruner_tpu.core.pruner.prune` with the same indices
materializes the mask into genuinely smaller tensors for deployment.

Forward equivalence with real pruning holds exactly in eval mode: masked
units produce zero activations, masked consumer rows null their
contributions, masked norm scale/bias zero the channel — verified against
``prune()`` in tests/test_masking.py.
"""

from __future__ import annotations

from typing import Any, Dict, Sequence, Tuple, Union

import jax
import numpy as np
import jax.numpy as jnp
import optax

from torchpruner_tpu.core import graph as G
from torchpruner_tpu.core.plan import PruneGroup
from torchpruner_tpu.core.pruner import plan_for_group
from torchpruner_tpu.core.segment import SegmentedModel


def _set_path(tree, path: Tuple[str, ...], value):
    if len(path) == 1:
        return {**tree, path[0]: value}
    return {**tree, path[0]: _set_path(tree.get(path[0], {}), path[1:], value)}


def _get_path(tree, path: Tuple[str, ...]):
    for k in path:
        if tree is None or k not in tree:
            return None
        tree = tree[k]
    return tree


def drop_masks(
    model: SegmentedModel,
    params,
    drops: Dict[Union[str, PruneGroup], Sequence[int]],
    *,
    state=None,
):
    """Binary (1.0 = keep) masks for the exact parameter/state slices a
    structural prune of ``drops`` (``{layer: unit indices}``) would remove.

    Returns ``(param_masks, state_masks)`` shaped like ``params`` /
    ``state`` (missing optional entries skipped).  Fan-out (conv -> flatten
    -> dense) and attached-norm slices come from the same ``PrunePlan`` as
    real surgery, so the two stay in lockstep by construction.
    """
    param_masks = jax.tree_util.tree_map(jnp.ones_like, params)
    state_masks = (
        jax.tree_util.tree_map(jnp.ones_like, state)
        if state is not None else None
    )
    for layer, drop in drops.items():
        group = layer if isinstance(layer, PruneGroup) else G.group_for(
            model, layer
        )
        plan = plan_for_group(model, group)
        drop = np.unique(np.asarray(drop, dtype=np.int64).reshape(-1))
        for s in plan.slices:
            tree, masks = (
                (params, param_masks) if s.collection == "params"
                else (state, state_masks)
            )
            leaf = _get_path(tree, s.path)
            if leaf is None:
                if s.optional:
                    continue
                raise KeyError(f"missing {'/'.join(s.path)}")
            # fan_out positions are STRIDED {p * n_units + u} (channels-
            # last flatten map — plan.ParamSlice), matching expand_keep
            idx = (
                np.concatenate([
                    p * plan.n_units + drop for p in range(s.fan_out)
                ])
                if s.fan_out > 1 else drop
            )
            mask = _get_path(masks, s.path)
            mask = mask.at[
                (slice(None),) * s.axis + (jnp.asarray(idx),)
            ].set(0.0)
            if s.collection == "params":
                param_masks = _set_path(param_masks, s.path, mask)
            else:
                state_masks = _set_path(state_masks, s.path, mask)
    return param_masks, state_masks


def apply_masks(tree, masks):
    """``tree * masks`` leafwise (masks=None is the identity)."""
    if masks is None:
        return tree
    return jax.tree_util.tree_map(lambda t, m: t * m.astype(t.dtype),
                                  tree, masks)


def blocksparse_params(
    model: SegmentedModel,
    params,
    drops: Dict[Union[str, PruneGroup], Sequence[int]],
    *,
    block: int = 128,
):
    """Wrap the 2-D matmul weights a masked prune of ``drops`` zeroes in
    :class:`~torchpruner_tpu.ops.blocksparse.BlockSparseWeight`, so the
    Dense/GatedDense apply sites (``quant.qdot``) run the block-sparse
    kernel — dropped 128-blocks neither fetched nor multiplied, forward
    and backward — instead of dense-multiplying zeros.

    Call on ALREADY-MASKED params (``apply_masks`` first; the wrapped
    buffer is the masked one, so the XLA fallback stays numerically
    equivalent).  Slices whose drop pattern is not block-aligned (use
    ``score_drop_indices(granularity=block)`` to make it so), non-2-D
    weights (attention/conv), and fan-out slices keep plain mask
    semantics — correct, just not faster.  Returns new params; the
    wrapping is metadata only (same buffers), so re-wrapping inside a
    jitted step costs nothing per step.
    """
    from torchpruner_tpu.ops.blocksparse import (
        BlockSparseWeight,
        keep_blocks_from_drop,
    )

    sites: Dict[Tuple[str, ...], Dict[str, Tuple[int, ...]]] = {}
    for layer, drop in drops.items():
        group = layer if isinstance(layer, PruneGroup) else G.group_for(
            model, layer
        )
        plan = plan_for_group(model, group)
        drop = np.unique(np.asarray(drop, dtype=np.int64).reshape(-1))
        keep = keep_blocks_from_drop(plan.n_units, drop, block)
        if keep is None or len(keep) * block == plan.n_units:
            continue  # unaligned pattern or nothing dropped
        for s in plan.slices:
            if s.collection != "params" or s.fan_out > 1:
                continue
            leaf = _get_path(params, s.path)
            if leaf is None or getattr(leaf, "ndim", 0) != 2 \
                    or s.axis > 1 \
                    or leaf.shape[s.axis] != plan.n_units:
                continue
            entry = sites.setdefault(s.path, {})
            entry["out_keep" if s.axis == 1 else "in_keep"] = keep
    out = params
    for path, kw in sites.items():
        leaf = _get_path(out, path)
        if isinstance(leaf, BlockSparseWeight):
            continue
        out = _set_path(out, path, BlockSparseWeight(
            leaf, kw.get("in_keep"), kw.get("out_keep"), block))
    return out


def masked_update(param_masks) -> optax.GradientTransformation:
    """Optax transform pinning masked parameters at zero through training
    (the JaxPruner-style sparsity-in-the-optimizer integration): chain it
    AFTER the inner optimizer so each step's update is masked — with the
    parameters masked once at the start, masked entries then stay exactly
    zero under any first-order update (masked grads/momentum can flow, but
    the masked update never moves the parameter).

    Use::

        masks, _ = drop_masks(model, params, {"conv5": idx}, state=state)
        tx = optax.chain(optax.adam(1e-3), masked_update(masks))
        params = apply_masks(params, masks)   # zero once up front
    """

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, opt_state, params=None):
        del params
        return apply_masks(updates, param_masks), opt_state

    return optax.GradientTransformation(init, update)

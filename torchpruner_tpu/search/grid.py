"""Campaign grids: what a sparsity-search campaign runs.

A *campaign* is a set of prune-retrain *trials* over one base experiment
config — per-layer prune fractions × attribution method × schedule
(finetune epochs / LR), per "Adaptive Activation-based Structured
Pruning"'s searched sparsity ratios and JaxPruner's sparsity-config
sweep axis (PAPERS.md).  A :class:`CampaignSpec` comes from a named
preset (:data:`CAMPAIGNS`) or a JSON config file, and resolves into an
ordered list of :class:`TrialSpec`, each a deterministic set of
``ExperimentConfig`` field overrides on the base.

Determinism is the load-bearing property: trial ids, the enumeration
order, and the spec digest are pure functions of the spec, so a resumed
campaign re-derives the identical trial set (the driver refuses a
campaign dir whose recorded digest disagrees) and the chaos drill can
assert an interrupted-then-resumed campaign's frontier is identical to
an uninterrupted one.
"""

from __future__ import annotations

import dataclasses
import hashlib
import itertools
import json
import os
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

#: ExperimentConfig fields a trial may override — the campaign's search
#: vocabulary.  Everything else (model, dataset, run_dir, chaos, ...)
#: belongs to the base config or the driver; an unknown override is a
#: loud config error, not a silently ignored knob.
TRIAL_FIELDS = (
    "method", "method_kwargs", "reduction", "policy", "fraction",
    "layer_fractions", "bucket", "target_filter", "prune_order",
    "finetune_epochs", "lr", "lr_schedule", "momentum", "weight_decay",
    "optimizer", "batch_size", "accum_steps", "score_examples",
    "score_dtype", "compute_dtype", "seed",
)


@dataclass(frozen=True)
class TrialSpec:
    """One trial: a deterministic id plus config overrides on the base."""

    trial_id: str
    overrides: Dict[str, Any]

    def label(self) -> str:
        bits = []
        for k in ("method", "fraction", "layer_fractions",
                  "finetune_epochs", "lr"):
            if k in self.overrides:
                bits.append(f"{k}={self.overrides[k]}")
        return ", ".join(bits) or "(base config)"


@dataclass
class CampaignSpec:
    """The campaign: base config + trial grid + search policy knobs."""

    name: str = "campaign"
    #: preset name or ExperimentConfig JSON path the trials override
    base: str = "mnist_mlp_shapley"
    smoke: bool = False
    #: overrides applied to EVERY trial (before per-trial overrides)
    common: Dict[str, Any] = field(default_factory=dict)
    #: cartesian grid: ExperimentConfig field -> list of values
    axes: Dict[str, List[Any]] = field(default_factory=dict)
    #: explicit extra trials: override dicts (optional "id" names them)
    trials: List[Dict[str, Any]] = field(default_factory=list)
    #: concurrent worker processes (CLI --jobs overrides)
    jobs: int = 2
    #: early-stop policy: a running trial whose every partial
    #: accuracy-at-FLOPs point is Pareto-dominated by the completed
    #: frontier past ``margin`` (absolute accuracy) is cancelled at its
    #: next checkpoint boundary; ``min_rounds`` partial points must
    #: exist before the rule may fire (a trial with no committed round
    #: is never judged)
    early_stop: Dict[str, Any] = field(
        default_factory=lambda: {"margin": 0.1, "min_rounds": 1})
    #: the frontier filter's accuracy near-tie margin (a completed point
    #: is flagged dominated only when beaten by MORE than this) —
    #: deliberately smaller than the early-stop confidence margin: the
    #: filter labels an artifact, the stop cancels live work
    frontier_margin: float = 0.02
    #: frontier FLOPs buckets as fractions of the DENSE model's forward
    #: FLOPs — the ``frontier_best_acc_flops_le_<pct>pct`` gate scalars
    flops_buckets: List[float] = field(
        default_factory=lambda: [0.25, 0.5, 0.75, 1.0])
    #: pre-pricing cost gate: exclude a candidate whose predicted trial
    #: wall exceeds this many seconds (None = off)
    max_trial_predicted_s: Optional[float] = None
    #: relative twin of the absolute gate: exclude a candidate whose
    #: predicted trial wall exceeds this multiple of the candidate-set
    #: MEDIAN (None = off) — robust across hosts whose absolute
    #: cost-model constants differ
    max_trial_cost_ratio: Optional[float] = None
    #: per-chip HBM headroom fraction for the watermark gate (the same
    #: 0.85 the planner uses)
    hbm_headroom: float = 0.85
    #: virtual devices per worker process (CPU mesh-slice emulation:
    #: XLA_FLAGS --xla_force_host_platform_device_count); 0 = inherit
    trial_devices: int = 0
    #: mid-retrain checkpoint cadence handed to every trial (optimizer
    #: steps; 0 = round/epoch boundaries only — early-stop still lands
    #: at retrain-epoch boundaries via the preemption poll)
    checkpoint_every_steps: int = 0
    #: worker attempts per trial before it is marked failed (a crashed
    #: attempt resumes cursor-exact from the trial's RunManifest)
    max_attempts: int = 3

    # -- construction ------------------------------------------------------

    @classmethod
    def from_any(cls, spec) -> "CampaignSpec":
        """Named campaign preset, JSON file path, dict, or CampaignSpec."""
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            if spec in CAMPAIGNS:
                return CAMPAIGNS[spec]()
            if os.path.exists(spec):
                with open(spec) as f:
                    spec = json.load(f)
            else:
                raise KeyError(
                    f"unknown campaign {spec!r}: not a preset "
                    f"({sorted(CAMPAIGNS)}) and not a config file path")
        if not isinstance(spec, dict):
            raise TypeError(f"cannot build a CampaignSpec from {spec!r}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(f"unknown campaign keys: {sorted(unknown)}")
        return cls(**spec)

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    def digest(self) -> str:
        """Content digest of the search-relevant spec — the identity a
        resumed campaign must match (``jobs``/``trial_devices`` are
        execution knobs, not search identity: a resume may legitimately
        run wider or narrower)."""
        d = self.to_dict()
        for k in ("jobs", "trial_devices", "max_attempts"):
            d.pop(k, None)
        blob = json.dumps(d, sort_keys=True, separators=(",", ":"))
        return hashlib.sha256(blob.encode()).hexdigest()

    @property
    def campaign_id(self) -> str:
        return f"{self.name}-{self.digest()[:8]}"

    # -- enumeration -------------------------------------------------------

    def enumerate_trials(self) -> List[TrialSpec]:
        """The deterministic trial list: the axes' cartesian product (in
        axis-insertion order) followed by the explicit ``trials``.
        Duplicate override sets collapse to the first occurrence."""
        out: List[TrialSpec] = []
        seen = set()

        def add(overrides: Dict[str, Any], tid: Optional[str] = None):
            overrides = {**self.common, **overrides}
            unknown = set(overrides) - set(TRIAL_FIELDS)
            if unknown:
                raise ValueError(
                    f"trial overrides {sorted(unknown)} are not in the "
                    f"campaign search vocabulary (TRIAL_FIELDS); put "
                    f"base-config fields in the base preset/config")
            key = json.dumps(overrides, sort_keys=True, default=str)
            if key in seen:
                return
            seen.add(key)
            out.append(TrialSpec(
                trial_id=tid or f"t{len(out):02d}_{_slug(overrides)}",
                overrides=overrides))

        axes = {k: list(v) for k, v in self.axes.items()}
        if axes:
            for combo in itertools.product(*axes.values()):
                add(dict(zip(axes.keys(), combo)))
        for t in self.trials:
            t = dict(t)
            tid = t.pop("id", None)
            add(t, tid=f"t{len(out):02d}_{tid}" if tid else None)
        if not out:
            raise ValueError(
                f"campaign {self.name!r} enumerates no trials — give it "
                f"axes and/or explicit trials")
        ids = [t.trial_id for t in out]
        if len(set(ids)) != len(ids):
            raise ValueError(f"duplicate trial ids: {sorted(ids)}")
        return out

    # -- materialization ---------------------------------------------------

    def base_config(self):
        """The resolved base ExperimentConfig (preset or JSON path).
        Campaign trials run the prune-retrain loop — that is the
        experiment whose rounds carry the accuracy/FLOPs points the
        frontier is made of — so the base's ``experiment`` is forced."""
        from torchpruner_tpu.utils.config import ExperimentConfig

        if self.base.endswith(".json"):
            cfg = ExperimentConfig.from_json(self.base)
        else:
            from torchpruner_tpu.experiments.presets import get_preset

            cfg = get_preset(self.base, smoke=self.smoke)
        if cfg.experiment != "prune_retrain":
            cfg = dataclasses.replace(cfg, experiment="prune_retrain")
        return cfg

    def trial_config(self, trial: TrialSpec, trial_dir: str):
        """``trial`` as a runnable, resumable ExperimentConfig rooted in
        ``trial_dir`` (RunManifest + checkpoints + CSV log live there;
        the trial's obs dir is ``<trial_dir>/obs``)."""
        cfg = self.base_config()
        over = dict(trial.overrides)
        for key in ("target_filter",):  # JSON lists -> config tuples
            if key in over:
                over[key] = tuple(over[key])
        cfg = dataclasses.replace(cfg, **over)
        return dataclasses.replace(
            cfg,
            name=f"{self.name}:{trial.trial_id}",
            run_dir=trial_dir,
            checkpoint_every_steps=self.checkpoint_every_steps,
            log_path=os.path.join(trial_dir, "log.csv"),
        )


#: slug abbreviations for the common axes (anything else contributes a
#: short stable hash so distinct override sets never collide on id)
_SLUG_KEYS = {"method": "", "fraction": "f", "finetune_epochs": "ft",
              "lr": "lr", "bucket": "b", "seed": "s"}


def _slug(overrides: Dict[str, Any]) -> str:
    bits, rest = [], {}
    for k in sorted(overrides):
        v = overrides[k]
        if k in _SLUG_KEYS:
            v = str(v).replace(".", "p").replace("/", "_")
            bits.append(f"{_SLUG_KEYS[k]}{v}")
        elif v not in ({}, (), [], None):
            rest[k] = v
    if rest:
        blob = json.dumps(rest, sort_keys=True, default=str)
        bits.append(hashlib.sha256(blob.encode()).hexdigest()[:6])
    return "_".join(bits)[:48] or "base"


# ---------------------------------------------------------------------------
# campaign presets
# ---------------------------------------------------------------------------


def digits_smoke() -> CampaignSpec:
    """The CI/smoke campaign: the untrained-digits MLP recipe searched
    over method × fraction × schedule — 9 candidates, of which the
    cost-model pre-pricing excludes one BY NAME (a 512-epoch schedule,
    caught by the relative predicted-cost gate before anything
    compiles), one diverging-LR trial is Pareto-dominated mid-run and
    early-stopped at a checkpoint boundary, and the rest land on the
    accuracy-vs-FLOPs frontier.  Runs end to end on one CPU in ~a
    minute; deterministic by seed, so the chaos drill can assert an
    interrupted campaign reproduces the identical frontier."""
    return CampaignSpec(
        name="digits_smoke",
        base="mnist_mlp_shapley",
        smoke=True,
        common={"policy": "fraction", "finetune_epochs": 1, "lr": 0.05,
                "method_kwargs": {}},
        axes={
            "method": ["weight_norm", "random"],
            "fraction": [0.25, 0.5, 0.75],
        },
        trials=[
            # per-layer fractions: the first hidden layer pruned gently,
            # the second hard — the per-layer-ratio search axis
            {"id": "layerwise", "method": "weight_norm", "fraction": 0.5,
             "layer_fractions": {"fc1": 0.25, "fc2": 0.625}},
            # a diverging schedule: same sparsity as the healthy
            # fraction=0.5 trials but LR far past stable — its partial
            # accuracy collapses to chance, so the completed frontier
            # dominates it by a wide margin and the driver cancels it
            # mid-retrain (4 epochs/round keeps it alive long enough to
            # be judged)
            {"id": "doomed_lr", "method": "random", "fraction": 0.5,
             "finetune_epochs": 4, "lr": 3.0},
            # the pre-pricing victim: a 512-epoch retrain schedule whose
            # predicted wall is ~512x the grid median — excluded by the
            # cost gate before any program compiles
            {"id": "over_budget", "method": "weight_norm",
             "fraction": 0.5, "finetune_epochs": 512},
        ],
        jobs=2,
        early_stop={"margin": 0.15, "min_rounds": 1},
        flops_buckets=[0.25, 0.5, 0.75, 1.0],
        max_trial_cost_ratio=16.0,
    )


CAMPAIGNS: Dict[str, Callable[[], CampaignSpec]] = {
    "digits_smoke": digits_smoke,
}


def campaign_names() -> tuple:
    return tuple(CAMPAIGNS)

"""Pareto sparsity-search campaigns (ROADMAP item 4).

``search/`` turns the one-experiment prune-retrain loop into a
*campaign*: a grid of trials (per-layer prune fractions × attribution
method × schedule) pre-priced by the static cost model, scheduled
concurrently across worker processes on the preemption-safe resume
machinery, early-stopped when Pareto-dominated, and distilled into an
accuracy-vs-FLOPs ``frontier.json`` with full provenance per point.

- :mod:`~torchpruner_tpu.search.grid` — campaign specs and trial
  enumeration (``CampaignSpec``, named presets);
- :mod:`~torchpruner_tpu.search.pricing` — staged pre-pricing gates
  (config validity → predicted HBM → predicted trial cost);
- :mod:`~torchpruner_tpu.search.frontier` — dominance rules, the
  frontier artifact, its digest, gauges, and rendering;
- :mod:`~torchpruner_tpu.search.driver` — the campaign driver, worker
  entry point, and ``python -m torchpruner_tpu search`` CLI.
"""

from torchpruner_tpu.search.frontier import (
    build_frontier,
    curve_dominated,
    dominates,
    format_frontier,
    frontier_digest,
    pareto_flags,
)
from torchpruner_tpu.search.grid import (
    CAMPAIGNS,
    CampaignSpec,
    TrialSpec,
    campaign_names,
)

__all__ = [
    "CAMPAIGNS", "CampaignSpec", "TrialSpec", "campaign_names",
    "build_frontier", "curve_dominated", "dominates", "format_frontier",
    "frontier_digest", "pareto_flags",
]

"""The campaign driver: concurrent prune-retrain trials, cost-model
pre-pricing, dominance early-stop, and the resumable frontier artifact.

``python -m torchpruner_tpu search <campaign>`` runs a whole
attribution→prune→retrain *campaign* (ROADMAP item 4): the trial grid is
priced statically before anything compiles (search/pricing.py), the
survivors run concurrently as worker *processes* (each trial a full
resilient prune-retrain run on the PR 4 machinery: RunManifest +
digest-verified checkpoints + its own obs ledger), the driver polls the
live ledgers and cancels trials whose partial accuracy-at-FLOPs curve is
Pareto-dominated by the completed frontier past a confidence margin
(SIGTERM → the trial snapshots at its next checkpoint boundary — the
preemption path reused as cooperative cancellation), and the outcome
lands as ``frontier.json`` (search/frontier.py) with one provenance
record per point.

Durability model (everything kill -9-safe):

- ``campaign.json`` — the campaign manifest, atomically replaced on
  every state change.  Trial statuses move
  ``pending → running → done | early_stopped | failed`` (plus
  ``excluded`` from pricing and the transient
  ``early_stop_requested``); pricing decisions and early-stop decisions
  are recorded BEFORE they take effect, so a killed driver resumes with
  the same exclusions and the same stops — the decisions, not the
  timing, are the durable truth.
- each trial dir is a PR 4 resilient run dir: a worker killed mid-round
  resumes cursor-exact; a driver killed mid-campaign re-queues its
  running trials, which resume the same way.
- ``frontier.json`` is rewritten (atomically) after every trial
  completion — the campaign's partial result is always on disk — and
  its ``frontier_digest`` covers only deterministic content, so an
  interrupted-then-resumed campaign reproduces the identical artifact
  (CI-asserted by the chaos drill).

Worker processes claim trials with ``flock`` locks (auto-released on
any death), so a resumed driver can never double-run a trial an
orphaned worker still holds.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import signal
import subprocess
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from torchpruner_tpu.search import frontier as frontier_mod
from torchpruner_tpu.search.grid import CampaignSpec, campaign_names
from torchpruner_tpu.search.pricing import format_exclusions, price_campaign

MANIFEST_NAME = "campaign.json"
GRID_NAME = "grid.json"
FRONTIER_NAME = "frontier.json"
RESULT_NAME = "result.json"

#: worker exit codes the driver interprets
EXIT_PREEMPTED = 3
EXIT_LOCKED = 4

#: how long a SIGTERMed worker gets to reach its next checkpoint
#: boundary before escalation to SIGKILL (it resumes nothing — the
#: early-stop decision is already durable)
STOP_GRACE_S = 120.0

#: respawn backoff after a worker found its trial flock still held (an
#: orphan from a killed driver) — without it the driver would launch a
#: full interpreter against the lock every poll
LOCK_RETRY_S = 5.0


@dataclass
class SearchChaos:
    """Driver-side fault injection for the CI chaos drill: SIGKILL the
    driver AND its workers at a deterministic campaign position —
    'mid-trial' (after the K-th completion, while others run) and
    'mid-early-stop' (right after an early-stop decision is recorded
    but before the worker dies)."""

    kill_after_trials: int = -1
    kill_on_early_stop: bool = False

    @classmethod
    def from_any(cls, spec) -> "SearchChaos":
        if spec is None:
            return cls()
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            spec = json.loads(spec)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(f"unknown search chaos keys: "
                             f"{sorted(unknown)} (known: {sorted(known)})")
        return cls(**spec)


@dataclass
class CampaignManifest:
    """Durable campaign position — the work-queue's source of truth."""

    version: int = 1
    kind: str = "search"
    name: str = "campaign"
    campaign_id: str = ""
    spec_digest: str = ""
    #: trial_id -> {"overrides", "status", "pricing", "attempts",
    #:              "result", "early_stop"}
    trials: Dict[str, Dict[str, Any]] = field(default_factory=dict)
    status: str = "running"
    resumes: int = 0

    @staticmethod
    def path_in(campaign_dir: str) -> str:
        return os.path.join(os.path.abspath(campaign_dir), MANIFEST_NAME)

    @classmethod
    def load(cls, campaign_dir: str) -> "CampaignManifest":
        from torchpruner_tpu.resilience.manifest import read_json

        raw = read_json(cls.path_in(campaign_dir))
        known = {f.name for f in dataclasses.fields(cls)}
        m = cls(**{k: v for k, v in raw.items() if k in known})
        if m.kind != "search":
            raise ValueError(
                f"{campaign_dir!r} holds a {m.kind!r} manifest — not a "
                f"search campaign dir")
        return m

    def save(self, campaign_dir: str) -> None:
        from torchpruner_tpu.resilience.manifest import atomic_write_json

        atomic_write_json(self.path_in(campaign_dir),
                          dataclasses.asdict(self))


def trial_dir(campaign_dir: str, tid: str) -> str:
    return os.path.join(os.path.abspath(campaign_dir), "trials", tid)


def trial_obs_dir(campaign_dir: str, tid: str) -> str:
    return os.path.join(trial_dir(campaign_dir, tid), "obs")


def _flock(path: str):
    """Exclusive non-blocking lock (None when already held elsewhere) —
    released by the OS on ANY process death, which is exactly the
    orphan-safety a kill -9 drill needs."""
    import fcntl

    os.makedirs(os.path.dirname(path), exist_ok=True)
    f = open(path, "w")
    try:
        fcntl.flock(f.fileno(), fcntl.LOCK_EX | fcntl.LOCK_NB)
    except OSError:
        f.close()
        return None
    f.write(str(os.getpid()))
    f.flush()
    return f


# ---------------------------------------------------------------------------
# worker (one trial, one process)
# ---------------------------------------------------------------------------


def run_trial_worker(campaign_dir: str, tid: str) -> int:
    """Run ONE trial to completion (or a preemption boundary) in this
    process: the full resilient prune-retrain loop with its own obs
    session, every ledger record stamped with ``trial_id`` /
    ``campaign_id``, and a ``result.json`` (atomic) carrying the
    frontier point's provenance — final accuracy/FLOPs/params, the
    committed checkpoint's content digest, and the ledger run id."""
    from torchpruner_tpu import obs
    from torchpruner_tpu.experiments.prune_retrain import run_prune_retrain
    from torchpruner_tpu.resilience.manifest import (
        RunManifest,
        atomic_write_json,
        read_json,
    )
    from torchpruner_tpu.search.grid import TrialSpec

    campaign_dir = os.path.abspath(campaign_dir)
    spec = CampaignSpec.from_any(
        read_json(os.path.join(campaign_dir, GRID_NAME)))
    manifest = CampaignManifest.load(campaign_dir)
    if tid not in manifest.trials:
        print(f"[search] unknown trial {tid!r}", file=sys.stderr)
        return 2
    tdir = trial_dir(campaign_dir, tid)
    lock = _flock(os.path.join(tdir, "lock"))
    if lock is None:
        print(f"[search] trial {tid} is locked by a live worker",
              file=sys.stderr)
        return EXIT_LOCKED
    st = manifest.trials[tid]
    trial = TrialSpec(trial_id=tid, overrides=st.get("overrides") or {})
    cfg = spec.trial_config(trial, tdir)
    ledger_run_id = f"{spec.campaign_id}:{tid}"

    t0 = time.perf_counter()
    session = obs.configure(trial_obs_dir(campaign_dir, tid))
    obs.annotate_run(experiment=cfg.name, kind="prune_retrain",
                     model=cfg.model, method=cfg.method,
                     trial_id=tid, campaign_id=spec.campaign_id,
                     run_id=ledger_run_id)
    obs.set_trial(tid, campaign_id=spec.campaign_id)
    # the pre-pricing already predicted this trial's step/HBM numbers —
    # land them as the standard gauges without recompiling the twin
    pricing = st.get("pricing") or {}
    for key, gauge in (("predicted_step_ms", "predicted_step_ms"),
                       ("predicted_comm_ms", "predicted_comm_ms"),
                       ("predicted_hbm_bytes_per_chip",
                        "predicted_hbm_bytes_per_chip")):
        if pricing.get(key) is not None:
            obs.gauge_set(gauge, pricing[key],
                          help="search pre-pricing prediction")
    try:
        with obs.span("trial", trial=tid, campaign=spec.campaign_id):
            history = run_prune_retrain(cfg, verbose=False)
    finally:
        derived = session.derived() if session else {}
    m = RunManifest.load(tdir) if RunManifest.exists_in(tdir) else None
    if m is None or m.status != "done":
        obs.shutdown(print_to=sys.stderr)
        return EXIT_PREEMPTED if m is not None \
            and m.status == "preempted" else 1

    last = history[-1] if history else None
    rounds = (session.ledger.records("round")
              if session and session.ledger else [])
    flops = next((r.get("flops") for r in reversed(rounds)
                  if r.get("flops") is not None), None)
    # the per-round (flops, acc) curve — what the driver's rung-matched
    # dominance check judges running trials against
    curve = [[float(r["flops"]), float((r.get("post") or {})["acc"])]
             for r in rounds
             if r.get("flops") is not None
             and (r.get("post") or {}).get("acc") is not None]
    digest = None
    if m.checkpoint:
        try:
            spec_json = read_json(
                os.path.join(tdir, m.checkpoint, "spec.json"))
            digest = spec_json.get("digest")
        except Exception:  # noqa: BLE001 — provenance is best-effort
            digest = None
    result = {
        "trial_id": tid,
        "campaign_id": spec.campaign_id,
        "ledger_run_id": ledger_run_id,
        "final_acc": float(last.post_acc) if last else None,
        "final_loss": float(last.post_loss) if last else None,
        "params": int(last.n_params) if last else None,
        "flops": flops,
        "widths": dict(last.widths) if last else None,
        "curve": curve,
        "rounds": len(history),
        "checkpoint": m.checkpoint,
        "checkpoint_digest": digest,
        "obs_dir": trial_obs_dir(campaign_dir, tid),
        # volatile measurements (kept out of the frontier digest)
        "step_time_mean_s": derived.get("step_time_mean_s"),
        "wall_s": round(time.perf_counter() - t0, 3),
    }
    atomic_write_json(os.path.join(tdir, RESULT_NAME), result)
    obs.shutdown(print_to=sys.stderr)
    return 0


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def _partial_points(obs_dir: str,
                    cache: Optional[Dict[str, Tuple[int, list]]] = None
                    ) -> List[Tuple[float, float]]:
    """A running trial's committed (flops, accuracy) round points, read
    from its LIVE ledger (torn tails skipped — the file is mid-write by
    another process, which is the point).  ``cache`` (keyed by path,
    holding ``(size, points)``) skips the re-parse while the file has
    not grown — the driver polls ~2×/s and a long trial's ledger holds
    thousands of non-round records."""
    from torchpruner_tpu.obs.ledger import LEDGER_FILENAME, load_ledger

    path = os.path.join(obs_dir, LEDGER_FILENAME)
    try:
        size = os.path.getsize(path)
    except OSError:
        size = -1
    if cache is not None and path in cache and cache[path][0] == size:
        return cache[path][1]
    pts = []
    for r in load_ledger(path):
        if r.get("event") != "round":
            continue
        a = (r.get("post") or {}).get("acc")
        f = r.get("flops")
        if a is not None and f is not None:
            pts.append((float(f), float(a)))
    if cache is not None:
        cache[path] = (size, pts)
    return pts


def _dense_flops(spec: CampaignSpec) -> Optional[float]:
    """Forward FLOPs of the unpruned base model — the denominator of
    the frontier's FLOPs buckets.  Deterministic shape math (the same
    ``model_cost`` the round records use)."""
    try:
        from torchpruner_tpu.core.segment import init_model
        from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY
        from torchpruner_tpu.utils.flops import model_cost

        model = MODEL_REGISTRY[spec.base_config().model][0]()
        params, state = init_model(model, seed=0)
        _, flops = model_cost(model, params, state)
        return float(flops) if flops else None
    except Exception:  # noqa: BLE001
        return None


def _worker_env(spec: CampaignSpec, slot: int, cpu: bool) -> Dict[str, str]:
    """Per-worker environment: mesh-slice isolation.  ``cpu`` campaigns
    give each worker ``trial_devices`` VIRTUAL devices
    (``xla_force_host_platform_device_count``); accelerator campaigns
    give each worker slot a disjoint chip slice via
    ``TPU_VISIBLE_DEVICES`` and STRIP any driver-level
    ``JAX_PLATFORMS`` override — the recommended on-chip invocation
    runs the driver itself chip-less (``JAX_PLATFORMS=cpu``: pricing is
    static), and a worker inheriting that var would silently run its
    trial on CPU.  No backend probe here: the driver must never
    initialize an accelerator (that would hold the very chips the
    workers need)."""
    env = dict(os.environ)
    k = spec.trial_devices
    if not cpu:
        # the driver always runs chip-less (search_main forces the cpu
        # platform) and may itself be under a JAX_PLATFORMS=cpu prefix —
        # an accelerator worker inheriting either would silently run its
        # trial on CPU, so the override never propagates
        env.pop("JAX_PLATFORMS", None)
    if not k:
        return env
    flags = " ".join(f for f in env.get("XLA_FLAGS", "").split()
                     if "xla_force_host_platform_device_count" not in f)
    if cpu:
        env["XLA_FLAGS"] = (
            f"{flags} --xla_force_host_platform_device_count={k}").strip()
    else:
        if flags:
            env["XLA_FLAGS"] = flags
        else:
            env.pop("XLA_FLAGS", None)
        env["TPU_VISIBLE_DEVICES"] = ",".join(
            str(slot * k + j) for j in range(k))
    return env


def run_campaign(spec: CampaignSpec, campaign_dir: str, *,
                 jobs: Optional[int] = None, cpu: bool = False,
                 poll_s: float = 0.5, chaos: Optional[SearchChaos] = None,
                 frontier_out: Optional[str] = None,
                 verbose: bool = True) -> Dict[str, Any]:
    """The campaign loop: price → schedule → poll/early-stop → frontier.
    Returns the final frontier dict.  Safe to kill -9 at any instant and
    re-invoke with the same ``campaign_dir``."""
    from torchpruner_tpu import obs
    from torchpruner_tpu.resilience.manifest import (
        RunManifest,
        atomic_write_json,
        read_json,
    )

    campaign_dir = os.path.abspath(campaign_dir)
    os.makedirs(campaign_dir, exist_ok=True)
    chaos = chaos or SearchChaos()
    jobs = jobs or spec.jobs
    lock = _flock(os.path.join(campaign_dir, "driver.lock"))
    if lock is None:
        raise RuntimeError(
            f"another campaign driver is live on {campaign_dir!r} "
            f"(driver.lock held)")

    trials = spec.enumerate_trials()
    resuming = os.path.exists(CampaignManifest.path_in(campaign_dir))
    if resuming:
        manifest = CampaignManifest.load(campaign_dir)
        if manifest.spec_digest != spec.digest():
            raise ValueError(
                f"campaign dir {campaign_dir!r} was created from a "
                f"different grid (digest {manifest.spec_digest[:12]} != "
                f"{spec.digest()[:12]}) — resuming would change the "
                f"trial set; use a fresh directory")
        manifest.resumes += 1
        obs.inc("search_campaign_resumes_total",
                help="campaign drivers resumed from campaign.json")
    else:
        manifest = CampaignManifest(
            name=spec.name, campaign_id=spec.campaign_id,
            spec_digest=spec.digest(),
            trials={t.trial_id: {"overrides": dict(t.overrides),
                                 "status": "pending", "attempts": 0}
                    for t in trials})
        atomic_write_json(os.path.join(campaign_dir, GRID_NAME),
                          spec.to_dict())
    manifest.status = "running"

    def log(msg: str) -> None:
        if verbose:
            print(f"[search:{spec.name}] {msg}", flush=True)

    # -- resume reconciliation: decisions are durable, timing is not ----
    for tid, st in manifest.trials.items():
        if st["status"] == "early_stop_requested":
            # the stop decision was recorded before the kill — finalize
            # it; whatever the orphan worker managed to commit is moot
            _finalize_early_stop(manifest, tid, log)
        elif st["status"] == "running":
            # a driver death mid-flight: the worker may have finished,
            # died, or still be running (its flock shows).  A finished
            # trial's result is adopted; anything else re-queues and
            # resumes cursor-exact on the trial's own RunManifest.
            res_path = os.path.join(trial_dir(campaign_dir, tid),
                                    RESULT_NAME)
            tdir = trial_dir(campaign_dir, tid)
            done = (os.path.exists(res_path)
                    and RunManifest.exists_in(tdir)
                    and RunManifest.load(tdir).status == "done")
            if done:
                st["status"] = "done"
                st["result"] = read_json(res_path)
                log(f"{tid}: adopted a completed result from the "
                    f"previous driver")
            else:
                st["status"] = "pending"
    manifest.save(campaign_dir)

    # -- pre-pricing (once; exclusions are durable across resumes) ------
    unpriced = [t for t in trials
                if "pricing" not in manifest.trials[t.trial_id]]
    if unpriced:
        with obs.span("search_pricing", campaign=spec.campaign_id):
            pricing = price_campaign(spec, unpriced, campaign_dir)
        for tid, p in pricing.items():
            st = manifest.trials[tid]
            st["pricing"] = p
            if p["excluded_by"]:
                st["status"] = "excluded"
                obs.record_trial(trial_id=tid, status="excluded",
                                 excluded_by=p["excluded_by"],
                                 reasons=p["reasons"])
        manifest.save(campaign_dir)
        excl = format_exclusions(pricing)
        if excl:
            log("pre-pricing exclusions:\n" + excl)
    n_excluded = sum(1 for st in manifest.trials.values()
                     if st["status"] == "excluded")
    obs.gauge_set("search_candidates_total", len(manifest.trials),
                  help="search: enumerated trial candidates")
    obs.gauge_set("search_excluded_total", n_excluded,
                  help="search: candidates excluded by pre-pricing")

    # -- deterministic queue: cheapest predicted trials first, so the
    # frontier anchors exist before expensive trials need judging ------
    def cost_key(tid: str):
        p = manifest.trials[tid].get("pricing") or {}
        return (p.get("predicted_trial_s") or float("inf"), tid)

    queue = sorted(
        (tid for tid, st in manifest.trials.items()
         if st["status"] == "pending"), key=cost_key)
    log(f"{len(manifest.trials)} candidate(s): {len(queue)} queued, "
        f"{n_excluded} excluded, "
        f"{sum(1 for s in manifest.trials.values() if s['status'] == 'done')} "
        f"already done (resume #{manifest.resumes})")

    procs: Dict[str, subprocess.Popen] = {}
    stop_deadline: Dict[str, float] = {}
    slot_of: Dict[str, int] = {}
    #: trial_id -> monotonic time before which it must not respawn
    #: (flock backoff: an orphan worker from a killed driver may hold a
    #: trial for minutes — respawning every poll would busy-loop full
    #: interpreter launches against the lock)
    defer: Dict[str, float] = {}
    #: ledger-poll cache: path -> (file size, parsed round points)
    ledger_cache: Dict[str, Tuple[int, list]] = {}
    completions = 0

    def chaos_kill() -> None:
        """The drill's kill -9: workers first (no orphans to fight the
        resumed driver), then the driver itself — no cleanup, no
        goodbye, exactly what a preempted VM gets."""
        for p in procs.values():
            try:
                p.kill()
            except Exception:  # noqa: BLE001
                pass
        os.kill(os.getpid(), signal.SIGKILL)

    def spawn(tid: str) -> None:
        st = manifest.trials[tid]
        st["status"] = "running"
        st["attempts"] = st.get("attempts", 0) + 1
        manifest.save(campaign_dir)
        slot = next(i for i in range(jobs) if i not in slot_of.values())
        slot_of[tid] = slot
        cmd = [sys.executable, "-m", "torchpruner_tpu", "search",
               "--campaign-dir", campaign_dir, "--run-trial", tid]
        if cpu:
            cmd.append("--cpu")
        # worker output appends to a per-trial log across attempts — a
        # failed trial's traceback must survive for diagnosis (the same
        # loud-by-contract rule the pricing exclusions follow)
        tdir = trial_dir(campaign_dir, tid)
        os.makedirs(tdir, exist_ok=True)
        logf = open(os.path.join(tdir, "worker.log"), "a")
        procs[tid] = subprocess.Popen(
            cmd, env=_worker_env(spec, slot, cpu),
            stdout=logf, stderr=subprocess.STDOUT)
        logf.close()  # the child holds its own descriptor
        log(f"{tid}: started (attempt {st['attempts']}, slot {slot}, "
            f"pid {procs[tid].pid})")

    def completed_curves() -> List[List[Tuple[float, float]]]:
        """Completed trials' per-round (flops, acc) curves — the rungs
        the dominance check matches running trials against."""
        curves = []
        for st in manifest.trials.values():
            r = st.get("result") or {}
            if st["status"] == "done" and r.get("curve"):
                curves.append([(float(f), float(a))
                               for f, a in r["curve"]])
        return curves

    def results() -> Dict[str, Dict[str, Any]]:
        return {tid: st["result"] for tid, st in manifest.trials.items()
                if st["status"] == "done" and st.get("result")}

    dense = _dense_flops(spec)
    margin = float(spec.early_stop.get("margin", 0.1))
    min_rounds = int(spec.early_stop.get("min_rounds", 1))
    out_path = frontier_out or os.path.join(campaign_dir, FRONTIER_NAME)

    def write_partial_frontier() -> Dict[str, Any]:
        f = frontier_mod.build_frontier(
            spec=spec, manifest=manifest, results=results(),
            dense_flops=dense, margin=spec.frontier_margin)
        frontier_mod.write_frontier(f, out_path)
        return f

    with obs.span("search_schedule", campaign=spec.campaign_id):
        while queue or procs:
            while queue and len(procs) < jobs:
                now = time.monotonic()
                ready = [t for t in queue if now >= defer.get(t, 0.0)]
                if not ready:
                    break  # every queued trial is backing off a lock
                queue.remove(ready[0])
                spawn(ready[0])

            time.sleep(poll_s)

            # -- reap finished workers --------------------------------
            for tid in [t for t, p in procs.items()
                        if p.poll() is not None]:
                rc = procs.pop(tid).returncode
                slot_of.pop(tid, None)
                stop_deadline.pop(tid, None)
                st = manifest.trials[tid]
                tdir = trial_dir(campaign_dir, tid)
                rm_status = (RunManifest.load(tdir).status
                             if RunManifest.exists_in(tdir) else "")
                res_path = os.path.join(tdir, RESULT_NAME)
                if st["status"] == "early_stop_requested":
                    # the recorded decision WINS even when the worker
                    # raced to completion before the SIGTERM landed —
                    # the resume path finalizes the same way, so an
                    # interrupted and an uninterrupted campaign can
                    # never disagree about this trial's fate
                    _finalize_early_stop(manifest, tid, log)
                    manifest.save(campaign_dir)
                elif rc == 0 and rm_status == "done" \
                        and os.path.exists(res_path):
                    st["status"] = "done"
                    st["result"] = read_json(res_path)
                    completions += 1
                    obs.inc("search_trials_completed_total",
                            help="search: trials run to completion")
                    r = st["result"]
                    obs.record_trial(
                        trial_id=tid, status="done",
                        accuracy=r.get("final_acc"), flops=r.get("flops"),
                        params=r.get("params"),
                        checkpoint_digest=r.get("checkpoint_digest"))
                    log(f"{tid}: done (acc "
                        f"{r.get('final_acc')}, params {r.get('params')})")
                    manifest.save(campaign_dir)
                    write_partial_frontier()
                    if chaos.kill_after_trials >= 0 \
                            and completions >= chaos.kill_after_trials:
                        chaos_kill()
                elif rm_status == "preempted" or rc == EXIT_LOCKED:
                    # an external preemption (or a still-locked trial):
                    # back to the queue, it resumes cursor-exact — and
                    # it is not a crash, so it must not burn an attempt
                    st["attempts"] = max(0, st.get("attempts", 1) - 1)
                    st["status"] = "pending"
                    queue.append(tid)
                    queue.sort(key=cost_key)
                    if rc == EXIT_LOCKED:
                        defer[tid] = time.monotonic() + LOCK_RETRY_S
                    manifest.save(campaign_dir)
                    log(f"{tid}: preempted/locked (rc {rc}) — requeued")
                else:
                    if st.get("attempts", 0) >= spec.max_attempts:
                        st["status"] = "failed"
                        st["exit_code"] = rc
                        obs.inc("search_trials_failed_total",
                                help="search: trials failed past the "
                                     "attempt budget")
                        obs.record_trial(trial_id=tid, status="failed",
                                         exit_code=rc)
                        log(f"{tid}: FAILED (rc {rc}, "
                            f"{st['attempts']} attempts) — see "
                            f"{os.path.join(tdir, 'worker.log')}")
                    else:
                        st["status"] = "pending"
                        queue.append(tid)
                        queue.sort(key=cost_key)
                        log(f"{tid}: crashed (rc {rc}) — requeued "
                            f"(attempt {st['attempts']}/"
                            f"{spec.max_attempts})")
                    manifest.save(campaign_dir)

            # -- dominance early-stop over the LIVE ledgers -----------
            front = completed_curves()
            for tid, proc in procs.items():
                st = manifest.trials[tid]
                if st["status"] == "early_stop_requested":
                    if time.monotonic() > stop_deadline.get(
                            tid, float("inf")):
                        proc.kill()  # boundary never came; decision holds
                    continue
                partial = _partial_points(
                    trial_obs_dir(campaign_dir, tid), ledger_cache)
                if frontier_mod.curve_dominated(
                        partial, front, margin=margin,
                        min_points=min_rounds):
                    # decision BEFORE signal: the stop must survive a
                    # driver kill between these two lines
                    st["status"] = "early_stop_requested"
                    st["early_stop"] = {
                        "at_points": len(partial),
                        "margin": margin,
                        "reason": "partial accuracy-at-FLOPs curve "
                                  "Pareto-dominated by the completed "
                                  "frontier past the confidence margin",
                    }
                    manifest.save(campaign_dir)
                    log(f"{tid}: dominated after {len(partial)} "
                        f"round(s) — cancelling at the next checkpoint "
                        f"boundary")
                    if chaos.kill_on_early_stop:
                        chaos_kill()
                    proc.send_signal(signal.SIGTERM)
                    stop_deadline[tid] = time.monotonic() + STOP_GRACE_S

    # -- final frontier --------------------------------------------------
    fr = write_partial_frontier()
    frontier_mod.record_obs(fr)
    # the counters must reflect the WHOLE campaign even when part of it
    # ran under a pre-kill driver process (counters are per-process):
    # top each up to the frontier's authoritative count
    for counter, n, hlp in (
        ("search_trials_early_stopped_total",
         fr["counts"]["early_stopped"],
         "search: trials early-stopped as Pareto-dominated"),
        ("search_trials_completed_total", fr["counts"]["completed"],
         "search: trials run to completion"),
        ("search_trials_failed_total", fr["counts"]["failed"],
         "search: trials failed past the attempt budget"),
    ):
        already = obs.counter_value(counter)
        if n > already:
            obs.inc(counter, n - already, help=hlp)
    manifest.status = "done"
    manifest.save(campaign_dir)
    log(f"frontier written to {out_path} "
        f"(digest {fr['frontier_digest'][:12]})")
    return fr


def _finalize_early_stop(manifest: CampaignManifest, tid: str, log) -> None:
    from torchpruner_tpu import obs

    st = manifest.trials[tid]
    st["status"] = "early_stopped"
    obs.inc("search_trials_early_stopped_total",
            help="search: trials early-stopped as Pareto-dominated")
    obs.record_trial(trial_id=tid, status="early_stopped",
                     **(st.get("early_stop") or {}))
    log(f"{tid}: early-stopped (dominated)")


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------


def search_main(argv=None) -> int:
    """``python -m torchpruner_tpu search <campaign> [...]`` — see
    README 'Sparsity search campaigns'."""
    p = argparse.ArgumentParser(
        prog="torchpruner_tpu search",
        description="Pareto sparsity-search campaign driver: concurrent "
                    "prune-retrain trials with cost-model pre-pricing, "
                    "dominance early-stop, and a resumable frontier "
                    "artifact",
    )
    p.add_argument("campaign", nargs="?", default=None,
                   help=f"campaign preset ({', '.join(campaign_names())}) "
                        f"or a campaign-spec JSON path")
    p.add_argument("--campaign-dir", metavar="DIR",
                   help="campaign working dir (campaign.json, trials/, "
                        "frontier.json); an existing dir RESUMES the "
                        "campaign.  Default logs/search_<name>")
    p.add_argument("--jobs", type=int, default=None,
                   help="concurrent trial worker processes "
                        "(default: the spec's)")
    p.add_argument("--cpu", action="store_true",
                   help="force the CPU backend (driver and workers)")
    p.add_argument("--smoke", action="store_true",
                   help="smoke-size the base config (campaign presets "
                        "choose their own default)")
    p.add_argument("--poll-s", type=float, default=0.5,
                   help="driver poll cadence for reaping workers and "
                        "scanning live ledgers for dominance")
    p.add_argument("--trial-devices", type=int, default=None,
                   help="devices per worker (overrides the spec): CPU "
                        "hosts get that many virtual devices; TPU hosts "
                        "slice disjoint chips per worker via "
                        "TPU_VISIBLE_DEVICES (run the driver itself "
                        "with JAX_PLATFORMS=cpu so it holds no chips)")
    p.add_argument("--frontier-out", metavar="PATH",
                   help="frontier artifact path "
                        "(default <campaign-dir>/frontier.json)")
    p.add_argument("--chaos", metavar="JSON",
                   help="driver-side fault injection for the CI drill, "
                        "e.g. '{\"kill_after_trials\": 2}' or "
                        "'{\"kill_on_early_stop\": true}'")
    p.add_argument("--report", action="store_true",
                   help="re-render an existing frontier.json and exit")
    p.add_argument("--run-trial", metavar="TRIAL_ID",
                   help="(internal) worker mode: run one trial of "
                        "--campaign-dir in this process")
    args = p.parse_args(argv)

    if args.cpu:
        import jax

        jax.config.update("jax_platforms", "cpu")

    if args.run_trial:
        if not args.campaign_dir:
            p.error("--run-trial needs --campaign-dir")
        return run_trial_worker(args.campaign_dir, args.run_trial)

    if args.report:
        # re-rendering needs only the artifact path — never the spec
        path = args.frontier_out or (
            os.path.join(args.campaign_dir, FRONTIER_NAME)
            if args.campaign_dir else None)
        if path is None and args.campaign:
            spec = CampaignSpec.from_any(args.campaign)
            path = os.path.join("logs", f"search_{spec.name}",
                                FRONTIER_NAME)
        if path is None:
            p.error("--report needs --campaign-dir, --frontier-out, or "
                    "a campaign name to locate frontier.json")
        with open(path) as f:
            print(frontier_mod.format_frontier(json.load(f)))
        return 0

    if not args.campaign:
        p.error("give a campaign preset name or spec JSON path "
                f"(presets: {', '.join(campaign_names())})")
    # the DRIVER is chip-less by construction: pricing/enumeration are
    # static (deterministic CPU cost constants off-accelerator, see
    # PERF.md "Campaign protocol"), and a driver holding accelerator
    # chips would starve the very workers it schedules — workers reach
    # the accelerator through their own env (no JAX_PLATFORMS override,
    # per-slot TPU_VISIBLE_DEVICES when --trial-devices slices)
    import jax

    jax.config.update("jax_platforms", "cpu")
    spec = CampaignSpec.from_any(args.campaign)
    if args.smoke:
        spec = dataclasses.replace(spec, smoke=True)
    if args.trial_devices is not None:
        # an execution knob, not search identity (excluded from the
        # spec digest like jobs) — a resume may re-slice freely
        spec = dataclasses.replace(spec, trial_devices=args.trial_devices)
    campaign_dir = args.campaign_dir or os.path.join(
        "logs", f"search_{spec.name}")

    from torchpruner_tpu import obs

    obs.configure(os.path.join(campaign_dir, "obs"))
    obs.annotate_run(experiment=spec.name, kind="search",
                     campaign_id=spec.campaign_id, base=spec.base)
    try:
        with obs.span("search", campaign=spec.campaign_id):
            fr = run_campaign(
                spec, campaign_dir, jobs=args.jobs, cpu=args.cpu,
                poll_s=args.poll_s,
                chaos=SearchChaos.from_any(args.chaos),
                frontier_out=args.frontier_out)
    finally:
        obs.shutdown(print_to=sys.stderr)
    print(frontier_mod.format_frontier(fr))
    if not fr["points"]:
        print("no trial completed — see the per-candidate exclusion "
              "reasons and trial statuses in campaign.json",
              file=sys.stderr)
        return 1
    return 0

"""Pre-pricing: staged static gates over a campaign's trial candidates.

The planner's rule (PR 11), applied to the trial grid: **price every
candidate before compiling anything it would run**, cheapest check
first, and make every exclusion loud — an excluded candidate stays in
the campaign manifest and the frontier artifact with its reasons, never
silently dropped.

Stages (each fills the candidate's pricing record and may exclude):

1. ``config`` — pure validation, no jax: the overrides must build a
   valid ``ExperimentConfig``; fractions must be in ``[0, 1)``; every
   ``layer_fractions`` key must match a prunable target (an override
   that matches nothing would silently search a point it never ran).
2. ``hbm`` — pure shape math: the predicted per-chip HBM watermark
   (``utils.flops.predicted_hbm_bytes_per_chip`` — the dense model, an
   upper bound for every later round) against ``hbm_headroom`` of
   ``utils.flops.hbm_capacity()`` (``TORCHPRUNER_PLAN_HBM_BYTES``
   overrides, same env as the planner's CI drill).
3. ``cost`` — the pass-5 roofline (one abstract-aval train-step compile
   per DISTINCT program shape, shared across every trial that differs
   only in method/fraction/LR): predicted step time × steps/epoch ×
   finetune epochs × prune rounds = the predicted trial wall, gated
   absolutely (``max_trial_predicted_s``) and relative to the candidate
   set's median (``max_trial_cost_ratio``).  The predicted wall covers
   the retrain steps — the term that separates schedules; scoring/eval
   overhead is shared by every candidate and irrelevant to the gate.

The surviving candidates' ``predicted_step_ms`` / ``predicted_trial_s``
also seed the driver's deterministic queue order (cheapest first, so
likely frontier anchors complete before expensive trials need judging)
and land as gauges in each trial's report.json without recompiling.
"""

from __future__ import annotations

import statistics
from typing import Any, Dict, List, Optional, Tuple

from torchpruner_tpu.search.grid import CampaignSpec, TrialSpec

#: shared compiles across the candidate set, keyed by the fields that
#: change the train-step program's shape/placement (method/fraction/LR
#: don't)
_PROGRAM_KEY_FIELDS = ("model", "batch_size", "accum_steps", "partition",
                       "zero", "compute_dtype", "remat", "optimizer")


def _program_key(cfg) -> Tuple:
    return tuple(getattr(cfg, f) for f in _PROGRAM_KEY_FIELDS) \
        + (tuple(sorted((cfg.mesh or {}).items())),)


def _predict_step_ms(cfg, model, cache: Dict[Tuple, Any]) -> Optional[Dict]:
    """Pass-5 prediction for the trial's train step (cached across
    candidates sharing the program shape).  None when the program
    doesn't build or exceeds the compile budget — the gate then skips
    rather than excludes (absence of a prediction is not evidence of
    cost)."""
    key = _program_key(cfg)
    if key in cache:
        return cache[key]
    pred = None
    try:
        from torchpruner_tpu.analysis import cost_model
        from torchpruner_tpu.analysis.collective_lint import build_programs

        records, _ = build_programs(cfg, model, programs=("train_step",))
        train = next((r for r in records if r.name == "train_step"), None)
        p = cost_model.predict_record(train) if train is not None else None
        if p is not None:
            pred = {"step_ms": p.step_ms, "comm_ms": p.comm_ms,
                    "bound": p.bound, "device_kind": p.device_kind}
    except Exception as e:  # noqa: BLE001 — fault-isolated pricing
        pred = {"error": f"{type(e).__name__}: {e}"}
    cache[key] = pred
    return pred


def _steps_per_epoch(cfg, cache: Dict[str, int]) -> int:
    """Optimizer steps per retrain epoch — dataset length over batch
    (dataset lengths cached; the campaign's trials share a base)."""
    from torchpruner_tpu.experiments.prune_retrain import MODEL_REGISTRY

    ds = cfg.dataset if cfg.dataset != "synthetic" \
        else MODEL_REGISTRY[cfg.model][1]
    if ds not in cache:
        from torchpruner_tpu.data import load_dataset

        cache[ds] = len(load_dataset(ds, "train", seed=cfg.seed))
    return max(1, cache[ds] // max(1, cfg.batch_size))


def price_campaign(spec: CampaignSpec, trials: List[TrialSpec],
                   campaign_dir: str) -> Dict[str, Dict[str, Any]]:
    """Run the staged gates over every trial; returns
    ``{trial_id: pricing}`` where pricing carries ``feasible``,
    ``excluded_by`` (None | "config" | "hbm" | "cost"), ``reasons``,
    and the predicted numbers the driver's queue order and the trial
    workers' gauges reuse."""
    import os

    import jax.numpy as jnp

    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.experiments.prune_retrain import (
        MODEL_REGISTRY,
        filter_targets,
        make_optimizer,
    )
    from torchpruner_tpu.utils.flops import (
        hbm_capacity,
        predicted_hbm_bytes_per_chip,
    )

    out: Dict[str, Dict[str, Any]] = {}
    base = spec.base_config()
    model = MODEL_REGISTRY[base.model][0]()
    all_targets = [g.target for g in pruning_graph(model)]
    hbm_budget = hbm_capacity()
    program_cache: Dict[Tuple, Any] = {}
    spe_cache: Dict[str, int] = {}

    for trial in trials:
        pricing: Dict[str, Any] = {"feasible": False, "excluded_by": None,
                                   "reasons": []}
        out[trial.trial_id] = pricing

        def exclude(stage: str, reason: str, p=pricing):
            p["excluded_by"] = p["excluded_by"] or stage
            p["reasons"].append(reason)

        # -- stage 1: config validity (no jax) --------------------------
        try:
            cfg = spec.trial_config(
                trial, os.path.join(campaign_dir, "trials",
                                    trial.trial_id))
        except Exception as e:  # noqa: BLE001 — invalid override = data
            exclude("config", f"invalid config: {type(e).__name__}: {e}")
            continue
        targets = filter_targets(all_targets, cfg)
        if not targets:
            exclude("config",
                    f"target_filter {cfg.target_filter} matches no "
                    f"prunable target of {cfg.model} ({all_targets})")
            continue
        # layer_fractions are validated by ExperimentConfig itself; the
        # bare `fraction` field is not, and a null/non-numeric override
        # must exclude THIS candidate loudly, never crash the campaign
        try:
            fracs = {"fraction": cfg.fraction, **cfg.layer_fractions}
            bad = {k: v for k, v in fracs.items()
                   if not 0.0 <= float(v) < 1.0}
        except (TypeError, ValueError):
            exclude("config",
                    f"non-numeric prune fraction: {cfg.fraction!r}")
            continue
        if bad and cfg.policy == "fraction":
            exclude("config",
                    f"prune fraction(s) outside [0, 1): {bad}")
            continue
        dead = [k for k in cfg.layer_fractions
                if not any(k in t for t in targets)]
        if dead:
            exclude("config",
                    f"layer_fractions key(s) {dead} match no prunable "
                    f"target ({targets}) — the override would never "
                    f"apply")
            continue
        pricing["n_rounds"] = len(targets)

        # -- stage 2: predicted HBM watermark (pure shape math) ----------
        try:
            data = max(1, (cfg.mesh or {}).get("data", 1))
            watermark = predicted_hbm_bytes_per_chip(
                model, cfg.mesh or {},
                partition=cfg.partition, zero=cfg.zero,
                tx=make_optimizer(cfg),
                batch_per_chip=max(1, cfg.batch_size // data
                                   // max(1, cfg.accum_steps)),
                compute_dtype=jnp.bfloat16
                if cfg.compute_dtype == "bfloat16" else None,
                remat=cfg.remat,
            )
            pricing["predicted_hbm_bytes_per_chip"] = int(watermark)
            pricing["hbm_budget_bytes"] = int(hbm_budget)
            if watermark > hbm_budget * spec.hbm_headroom:
                exclude(
                    "hbm",
                    f"predicted HBM watermark "
                    f"{watermark / 2**30:.3f} GiB/chip exceeds "
                    f"{100 * spec.hbm_headroom:.0f}% of the "
                    f"{hbm_budget / 2**30:.2f} GiB budget")
                continue
        except Exception as e:  # noqa: BLE001
            exclude("hbm", f"HBM pricing failed: {type(e).__name__}: {e}")
            continue

        # -- stage 3a: roofline step time (shared compiles) --------------
        pred = _predict_step_ms(cfg, model, program_cache)
        if pred and "step_ms" in pred:
            spe = _steps_per_epoch(cfg, spe_cache)
            pricing.update({
                "predicted_step_ms": pred["step_ms"],
                "predicted_comm_ms": pred["comm_ms"],
                "bound": pred["bound"],
                "steps_per_epoch": spe,
                "predicted_trial_s": (
                    pred["step_ms"] / 1e3 * spe
                    * max(1, cfg.finetune_epochs) * len(targets)),
            })
        elif pred and "error" in pred:
            pricing["cost_note"] = pred["error"]
        pricing["feasible"] = True  # provisional: the ratio gate below
        # still sees the whole candidate set

    # -- stage 3b: trial-cost gates (need the whole set for the median) --
    costs = [p["predicted_trial_s"] for p in out.values()
             if p.get("predicted_trial_s") is not None]
    median = statistics.median(costs) if costs else None
    for tid, pricing in out.items():
        if pricing["excluded_by"] or "predicted_trial_s" not in pricing:
            continue
        cost = pricing["predicted_trial_s"]
        if spec.max_trial_predicted_s is not None \
                and cost > spec.max_trial_predicted_s:
            pricing["feasible"] = False
            pricing["excluded_by"] = "cost"
            pricing["reasons"].append(
                f"predicted trial wall {cost:.1f}s exceeds the "
                f"{spec.max_trial_predicted_s:.1f}s budget "
                f"(predicted {pricing['predicted_step_ms']:.3f} ms/step "
                f"x {pricing['steps_per_epoch']} steps/epoch x "
                f"{pricing['n_rounds']} round(s))")
        if spec.max_trial_cost_ratio is not None and median \
                and cost > spec.max_trial_cost_ratio * median:
            pricing["feasible"] = False
            pricing["excluded_by"] = pricing["excluded_by"] or "cost"
            pricing["reasons"].append(
                f"predicted trial wall {cost:.1f}s is "
                f"{cost / median:.0f}x the candidate-set median "
                f"({median:.1f}s; limit "
                f"{spec.max_trial_cost_ratio:.0f}x)")
    return out


def format_exclusions(pricing: Dict[str, Dict[str, Any]]) -> str:
    """The loud per-candidate exclusion list — printed by the driver and
    asserted by the CI/capture smoke ('excludes >=1 candidate by
    name')."""
    lines = []
    for tid, p in pricing.items():
        if p["excluded_by"]:
            lines.append(f"- `{tid}` [{p['excluded_by']}]: "
                         + "; ".join(p["reasons"]))
    return "\n".join(lines)

"""Pareto frontier over accuracy-vs-FLOPs trial points, and the
dominance rules the campaign driver early-stops with.

Conventions (the two objectives):

- **accuracy** — maximize (final test accuracy of the pruned+retrained
  checkpoint);
- **flops** — minimize (forward FLOPs of the pruned model, from the
  same ``utils.flops.model_cost`` every round record carries).

``q`` *dominates* ``p`` at margin ``m`` iff ``q.flops <= p.flops`` and
``q.acc >= p.acc + m``, strictly better in at least one coordinate.
The margin plays two roles:

- the **near-tie margin** of the frontier filter (the same role the
  ledger's ``near_ties`` plays for prune decisions): a point within the
  margin of a better one is a legitimate run-to-run coin flip, so it
  stays on the frontier rather than being knocked off by noise;
- the **confidence margin** of the early-stop rule
  (:func:`curve_dominated`): a running trial is cancelled only when
  EVERY point of its partial curve is beaten by MORE than the margin at
  a MATCHED round index of some completed trial's curve — a trial whose
  later points could come back within the margin is never stopped
  (property-tested in tests/test_search.py).

Everything here is pure data → data (order-independent, no jax), so the
dominance logic is testable in isolation and the frontier artifact is a
deterministic function of the campaign outcome: ``frontier_digest``
hashes the deterministic core (points' provenance/accuracy/flops, the
early-stopped and excluded sets, the bucket scalars) and is what the
chaos drill compares between an interrupted-then-resumed campaign and
an uninterrupted one.  Volatile measurements (wall seconds, step-time
means) ride in the artifact but stay out of the digest.
"""

from __future__ import annotations

import hashlib
import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: default dominance margin (absolute accuracy) — the near-tie band
#: within which two points are treated as a tie, mirroring the ledger's
#: tie_frac-of-span convention at typical accuracy spans
DEFAULT_MARGIN = 0.02

Point = Tuple[float, float]  # (flops, acc)


def dominates(q: Point, p: Point, *, margin: float = 0.0) -> bool:
    """True iff ``q`` dominates ``p`` at the accuracy near-tie
    ``margin``.  FLOPs are exact (deterministic shape math), accuracy
    is the noisy axis, so the margin applies to accuracy only:

    - ``q`` beats ``p`` by MORE than ``margin`` accuracy at no more
      FLOPs, or
    - ``q`` matches-or-beats ``p``'s accuracy at strictly fewer FLOPs.

    With ``margin == 0`` this is classic Pareto dominance (exact ties
    dominate nothing); with ``margin > 0`` a point within the margin of
    a same-or-more-FLOPs rival is a legitimate run-to-run coin flip and
    survives.  The early-stop predicate (:func:`curve_dominated`)
    deliberately does NOT use the equal-accuracy branch — only a
    beyond-margin accuracy gap may cancel a running trial."""
    qf, qa = q
    pf, pa = p
    if qf > pf:
        return False
    return qa - pa > margin or (qa >= pa and qf < pf)


def pareto_flags(points: Sequence[Point], *,
                 margin: float = DEFAULT_MARGIN) -> List[bool]:
    """Per-point non-dominated flags (same order as ``points``) — a
    point is knocked off the frontier only when some other point beats
    it by more than the near-tie ``margin`` in accuracy at no more
    FLOPs, or matches its accuracy at strictly fewer FLOPs beyond the
    margin.  Order-independent by construction: each flag is a
    quantifier over the whole set."""
    flags = []
    for i, p in enumerate(points):
        flags.append(not any(
            dominates(q, p, margin=margin)
            for j, q in enumerate(points) if j != i))
    return flags


def curve_dominated(partial: Sequence[Point],
                    curves: Sequence[Sequence[Point]], *,
                    margin: float, min_points: int = 1) -> bool:
    """The early-stop predicate: is this running trial's partial
    accuracy-at-FLOPs curve Pareto-dominated by the completed trials'
    curves past the confidence margin?

    The comparison is **rung-matched** (the successive-halving rule):
    the trial's point after round ``k`` is judged against the completed
    trials' points after their OWN round ``k`` — never against their
    final points.  In an iterative prune-retrain loop accuracy climbs
    with every retrained round, so comparing a round-1 point against a
    fully-retrained final would cull every late-starting trial; at a
    matched rung the comparison is budget-for-budget fair, and a trial
    whose later rounds could catch back up within the margin is never
    stopped (the property the isolation tests pin).

    True only when the trial has at least ``min_points`` committed
    round points and EVERY point ``k`` is beaten by some completed
    curve's point ``k`` by MORE than ``margin`` accuracy at no more
    FLOPs."""
    if len(partial) < max(1, min_points) or not curves:
        return False
    return all(
        any(len(c) > k and c[k][0] <= pf and c[k][1] - pa > margin
            for c in curves)
        for k, (pf, pa) in enumerate(partial))


# ---------------------------------------------------------------------------
# the frontier artifact
# ---------------------------------------------------------------------------


def bucket_scalars(points: Sequence[Dict[str, Any]], dense_flops: float,
                   buckets: Sequence[float]) -> Dict[str, float]:
    """``frontier_best_acc_flops_le_<pct>pct`` — best accuracy among
    points at or under each FLOPs bucket (fractions of the dense
    model's forward FLOPs).  These are the dynamic scalars ``obs diff``
    gates frontier regressions with: 'best accuracy at fixed FLOPs
    buckets' is comparable across campaigns even when the exact trial
    points move."""
    out: Dict[str, float] = {}
    for b in buckets:
        accs = [p["accuracy"] for p in points
                if p.get("accuracy") is not None
                and p.get("flops") is not None
                and p["flops"] <= b * dense_flops]
        if accs:
            out[f"frontier_best_acc_flops_le_{int(round(100 * b))}pct"] = \
                max(accs)
    return out


def build_frontier(*, spec, manifest, results: Dict[str, Dict[str, Any]],
                   dense_flops: Optional[float],
                   margin: float = DEFAULT_MARGIN) -> Dict[str, Any]:
    """Assemble the frontier artifact from completed trial results.

    ``results`` maps trial_id → the worker's ``result.json`` payload
    (accuracy/flops/params + checkpoint digest + ledger run id).  Every
    point carries full provenance; the non-dominated flags and bucket
    scalars derive from the deterministic (accuracy, flops) pairs only,
    so the artifact's digest is invariant to scheduling and to where in
    a trial an early stop landed."""
    points: List[Dict[str, Any]] = []
    for tid in sorted(results):
        r = results[tid]
        st = manifest.trials.get(tid, {})
        points.append({
            "trial_id": tid,
            "config": dict(st.get("overrides") or {}),
            "accuracy": r.get("final_acc"),
            "loss": r.get("final_loss"),
            "flops": r.get("flops"),
            "params": r.get("params"),
            "rounds": r.get("rounds"),
            "checkpoint": r.get("checkpoint"),
            "checkpoint_digest": r.get("checkpoint_digest"),
            "ledger_run_id": r.get("ledger_run_id"),
            "obs_dir": r.get("obs_dir"),
            "predicted_step_ms":
                (st.get("pricing") or {}).get("predicted_step_ms"),
            "predicted_trial_s":
                (st.get("pricing") or {}).get("predicted_trial_s"),
            # volatile (measured) — excluded from the digest
            "measured": {
                "step_time_mean_s": r.get("step_time_mean_s"),
                "wall_s": r.get("wall_s"),
            },
        })
    xy = [(p["flops"], p["accuracy"]) for p in points]
    usable = [i for i, (f, a) in enumerate(xy)
              if f is not None and a is not None]
    flags = pareto_flags([xy[i] for i in usable], margin=margin)
    for i, p in enumerate(points):
        p["non_dominated"] = bool(flags[usable.index(i)]) \
            if i in usable else False

    by_status: Dict[str, List[str]] = {}
    for tid in sorted(manifest.trials):
        by_status.setdefault(
            manifest.trials[tid].get("status", "pending"), []).append(tid)
    excluded = [
        {"trial_id": tid,
         "excluded_by": (manifest.trials[tid].get("pricing") or {})
         .get("excluded_by"),
         "reasons": (manifest.trials[tid].get("pricing") or {})
         .get("reasons", [])}
        for tid in by_status.get("excluded", [])
    ]
    frontier = {
        "version": 1,
        "campaign": spec.name,
        "campaign_id": spec.campaign_id,
        "base": spec.base,
        "margin": margin,
        "dense_flops": dense_flops,
        "points": points,
        "counts": {
            "trials": len(manifest.trials),
            "completed": len(points),
            "non_dominated": sum(1 for p in points if p["non_dominated"]),
            "dominated": sum(1 for p in points if not p["non_dominated"]),
            "early_stopped": len(by_status.get("early_stopped", [])),
            "excluded": len(excluded),
            "failed": len(by_status.get("failed", [])),
        },
        "early_stopped": by_status.get("early_stopped", []),
        "excluded": excluded,
        "buckets": (bucket_scalars(points, dense_flops, spec.flops_buckets)
                    if dense_flops else {}),
    }
    frontier["frontier_digest"] = frontier_digest(frontier)
    return frontier


#: per-point keys outside the digest: measurements are wall-clock
#: volatile, obs_dir is an absolute path, and the checkpoint NAME embeds
#: the commit counter (an interrupted trial commits more often than an
#: uninterrupted one) — its CONTENT digest is what must reproduce
_VOLATILE_POINT_KEYS = ("measured", "obs_dir", "checkpoint")


def frontier_digest(frontier: Dict[str, Any]) -> str:
    """sha256 over the deterministic core — what the chaos drill
    compares.  Drops volatile per-point measurements and any top-level
    timing; everything else (provenance included) must reproduce."""
    core = {
        "campaign_id": frontier["campaign_id"],
        "margin": frontier["margin"],
        "dense_flops": frontier["dense_flops"],
        "points": [
            {k: v for k, v in p.items() if k not in _VOLATILE_POINT_KEYS}
            for p in frontier["points"]
        ],
        "counts": frontier["counts"],
        "early_stopped": frontier["early_stopped"],
        "excluded": frontier["excluded"],
        "buckets": frontier["buckets"],
    }
    blob = json.dumps(core, sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(blob.encode()).hexdigest()


def write_frontier(frontier: Dict[str, Any], path: str) -> None:
    from torchpruner_tpu.obs.ledger import sanitize
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    atomic_write_json(path, sanitize(frontier), indent=1)


def record_obs(frontier: Dict[str, Any]) -> None:
    """Campaign telemetry: ``frontier_*`` gauges (dynamic-scalar prefix
    in ``obs diff``) + one ledger ``frontier`` record rendered by
    ``obs report``'s frontier section.  Best-effort by the usual
    contract."""
    try:
        from torchpruner_tpu import obs

        if obs.get() is None:
            return
        c = frontier["counts"]
        obs.gauge_set("frontier_points_total", c["completed"],
                      help="search: completed trial points")
        obs.gauge_set("frontier_nondominated_total", c["non_dominated"],
                      help="search: non-dominated frontier points")
        obs.gauge_set("frontier_early_stopped_total", c["early_stopped"],
                      help="search: trials early-stopped as dominated")
        obs.gauge_set("frontier_excluded_total", c["excluded"],
                      help="search: candidates excluded by pre-pricing")
        accs = [p["accuracy"] for p in frontier["points"]
                if p.get("accuracy") is not None]
        if accs:
            obs.gauge_set("frontier_best_acc", max(accs),
                          help="search: best completed-trial accuracy")
        for name, v in frontier["buckets"].items():
            obs.gauge_set(name, v,
                          help="search: best accuracy at the FLOPs bucket")
        obs.record_frontier(
            campaign=frontier["campaign"],
            campaign_id=frontier["campaign_id"],
            digest=frontier["frontier_digest"],
            counts=dict(c),
            buckets=dict(frontier["buckets"]),
            points=[{k: p.get(k) for k in
                     ("trial_id", "accuracy", "flops", "params",
                      "non_dominated", "checkpoint_digest",
                      "ledger_run_id")}
                    for p in frontier["points"]],
            early_stopped=list(frontier["early_stopped"]),
            excluded=[e["trial_id"] for e in frontier["excluded"]],
        )
    except Exception:  # noqa: BLE001 — telemetry never kills a campaign
        pass


def format_frontier(frontier: Dict[str, Any]) -> str:
    """Markdown rendering: the ranked point table (non-dominated first,
    then by FLOPs), counts, buckets, and the loud exclusion list."""
    c = frontier["counts"]
    lines = [
        f"frontier: {frontier['campaign']} "
        f"({c['completed']} point(s), {c['non_dominated']} non-dominated, "
        f"{c['early_stopped']} early-stopped, {c['excluded']} excluded, "
        f"{c['failed']} failed; digest "
        f"{frontier['frontier_digest'][:12]})",
        "",
    ]
    pts = sorted(frontier["points"],
                 key=lambda p: (not p["non_dominated"],
                                p.get("flops") or 0))
    if pts:
        lines.append("| trial | acc | flops | params | frontier "
                     "| ckpt digest | ledger run |")
        lines.append("|---|---|---|---|---|---|---|")
        for p in pts:
            lines.append(
                f"| `{p['trial_id']}` "
                f"| {_fmt(p.get('accuracy'), '.4f')} "
                f"| {_fmt(p.get('flops'), '.3g')} "
                f"| {_fmt(p.get('params'), 'd')} "
                f"| {'*' if p['non_dominated'] else 'dominated'} "
                f"| {str(p.get('checkpoint_digest') or '')[:12]} "
                f"| {p.get('ledger_run_id') or ''} |")
        lines.append("")
    if frontier["buckets"]:
        lines.append("buckets: " + ", ".join(
            f"{k.replace('frontier_best_acc_flops_le_', '<=')}"
            f"={v:.4f}" for k, v in sorted(frontier["buckets"].items())))
        lines.append("")
    if frontier["early_stopped"]:
        lines.append("early-stopped (dominated): "
                     + ", ".join(f"`{t}`"
                                 for t in frontier["early_stopped"]))
    if frontier["excluded"]:
        lines.append("excluded by pre-pricing:")
        for e in frontier["excluded"]:
            lines.append(f"- `{e['trial_id']}` [{e['excluded_by']}]: "
                         + "; ".join(e["reasons"]))
    return "\n".join(lines).rstrip() + "\n"


def _fmt(v, fmt) -> str:
    if v is None:
        return ""
    try:
        return format(int(v) if fmt == "d" else float(v), fmt)
    except (TypeError, ValueError):
        return str(v)

"""Llama-family decoder — the "Llama-3-8B FFN channel pruning + fine-tune
(pjit FSDP)" config of BASELINE.json.

Pre-norm decoder (Touvron et al., 2023; Llama-3 uses GQA): token embedding,
``depth`` blocks of ``Residual[RMSNorm, causal GQA attention with RoPE]`` +
``Residual[RMSNorm, SwiGLU, down-proj]``, final RMSNorm, LM head.

The FFN channel-pruning target is each block's
:class:`~torchpruner_tpu.core.layers.GatedDense` (``wg``/``wu`` hidden
channels) pruned with its ``wo`` down-projection consumer inside the body —
the group the static graph derives for GLU chains.  Attention-head groups
are also exposed (GQA-aware: surviving query heads keep their original KV
assignments via ``kv_group``).
"""

from __future__ import annotations

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def llama(
    *,
    vocab_size: int = 128256,
    dim: int = 4096,
    depth: int = 32,
    num_heads: int = 32,
    num_kv_heads: int = 8,
    head_dim: int = 128,
    ffn_dim: int = 14336,
    rope_theta: float = 500000.0,
    seq_len: int = 2048,
) -> SegmentedModel:
    layers: list = [L.Embedding("tok_emb", vocab_size, dim)]
    for i in range(1, depth + 1):
        attn_body = (
            L.RMSNorm("norm"),
            L.MultiHeadAttention(
                "attn", num_heads=num_heads, head_dim=head_dim,
                num_kv_heads=num_kv_heads, out_features=dim,
                causal=True, rope=True, rope_theta=rope_theta,
            ),
        )
        ffn_body = (
            L.RMSNorm("norm"),
            L.GatedDense("gate", ffn_dim, fn="silu"),
            L.Dense("down", dim, use_bias=False),
        )
        layers += [
            L.Residual(f"block{i}_attn", attn_body),
            L.Residual(f"block{i}_ffn", ffn_body),
        ]
    layers += [
        L.RMSNorm("final_norm"),
        L.Dense("lm_head", vocab_size, use_bias=False),
    ]
    return SegmentedModel(tuple(layers), (seq_len,), input_dtype="int32")


def llama_moe(
    *,
    vocab_size: int = 32000,
    dim: int = 4096,
    depth: int = 32,
    num_heads: int = 32,
    num_kv_heads: int = 8,
    head_dim: int = 128,
    ffn_dim: int = 14336,
    n_experts: int = 8,
    top_k: int = 2,
    rope_theta: float = 1e6,
    seq_len: int = 2048,
    dispatch: str = "dense",
    capacity_factor: float = 1.25,
) -> SegmentedModel:
    """Mixtral-style sparse-MoE decoder: the dense FFN replaced by a
    top-k-routed expert mixture.  The expert axis is the prunable unit
    (attribution-driven *expert pruning*) and the expert-parallel sharding
    axis (``partition="tp"``)."""
    layers: list = [L.Embedding("tok_emb", vocab_size, dim)]
    for i in range(1, depth + 1):
        layers += [
            L.Residual(f"block{i}_attn", (
                L.RMSNorm("norm"),
                L.MultiHeadAttention(
                    "attn", num_heads=num_heads, head_dim=head_dim,
                    num_kv_heads=num_kv_heads, out_features=dim,
                    causal=True, rope=True, rope_theta=rope_theta,
                ),
            )),
            L.Residual(f"block{i}_moe", (
                L.RMSNorm("norm"),
                L.MoE("experts", n_experts, ffn_dim, top_k=top_k,
                      dispatch=dispatch, capacity_factor=capacity_factor),
            )),
        ]
    layers += [
        L.RMSNorm("final_norm"),
        L.Dense("lm_head", vocab_size, use_bias=False),
    ]
    return SegmentedModel(tuple(layers), (seq_len,), input_dtype="int32")


def llama_moe_tiny(
    *,
    vocab_size: int = 256,
    dim: int = 32,
    depth: int = 2,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    ffn_dim: int = 32,
    n_experts: int = 4,
    top_k: int = 2,
    seq_len: int = 16,
    dispatch: str = "dense",
    capacity_factor: float = 1.25,
) -> SegmentedModel:
    """Miniature MoE decoder — tests / CPU smoke / multi-chip dryruns."""
    return llama_moe(
        vocab_size=vocab_size, dim=dim, depth=depth, num_heads=num_heads,
        num_kv_heads=num_kv_heads, head_dim=dim // num_heads,
        ffn_dim=ffn_dim, n_experts=n_experts, top_k=top_k,
        rope_theta=10000.0, seq_len=seq_len, dispatch=dispatch,
        capacity_factor=capacity_factor,
    )


def llama3_8b(seq_len: int = 2048) -> SegmentedModel:
    """Llama-3-8B: 32 blocks, dim 4096, 32 query / 8 KV heads, FFN 14336,
    vocab 128256 — the BASELINE.json FSDP fine-tune target.  ~8.0B params."""
    return llama(seq_len=seq_len)


def mfu_llama(seq_len: int = 1024) -> SegmentedModel:
    """~200M-param Llama (dim 1024 × depth 8, 32k vocab) whose FLOPs are
    large MXU-shaped matmuls — the MFU-ceiling probe shared by bench.py's
    ``mfu_llama`` leg and ``experiments.step_trace --model mfu_llama``
    (one definition so the stopwatch and the trace profile the same
    program)."""
    return llama(
        vocab_size=32000, dim=1024, depth=8, num_heads=8, num_kv_heads=8,
        head_dim=128, ffn_dim=4096, seq_len=seq_len,
    )


def llama_tiny(
    *,
    vocab_size: int = 256,
    dim: int = 32,
    depth: int = 2,
    num_heads: int = 4,
    num_kv_heads: int = 2,
    ffn_dim: int = 64,
    seq_len: int = 16,
) -> SegmentedModel:
    """Miniature Llama with the full block structure (GQA + RoPE + SwiGLU)
    — tests / CPU smoke / multi-chip dryruns."""
    return llama(
        vocab_size=vocab_size, dim=dim, depth=depth, num_heads=num_heads,
        num_kv_heads=num_kv_heads, head_dim=dim // num_heads,
        ffn_dim=ffn_dim, rope_theta=10000.0, seq_len=seq_len,
    )

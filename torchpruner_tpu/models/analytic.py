"""Analytic ``max_model`` fixture — hand-weighted 2→4→1 ReLU net computing
``max(x1, x2)`` on four symmetric inputs, with exactly derivable ground-truth
attributions (the crown-jewel fixture of the reference test suite, reference
torchpruner/tests/test_attributions.py:19-45).

Hidden units (columns of w1): A = relu(-x1/2 + x2/2), B = relu(x1 - x2),
C = relu(x1 + x2), D = relu(x1 + x2).  Output = A + B/2 + C/2 + w_D·D, which
equals max(x1, x2) when w_D = 0 (version 1).  Version 2 gives the redundant
unit D a small negative outgoing weight (-0.1), making its
sensitivity/Taylor/Shapley attributions nonzero and hand-checkable.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def max_model(version: int = 1):
    """Returns ``(model, params, x, y)``.

    The four symmetric data points and expected ground truths (with MSE loss,
    batch size 1, reduction "mean"):
      weight-norm [1, 2, 2, 2]; APoZ [.5, .5, 1, 1]; sensitivity/taylor all 0
      (version 1) / [.2, .1, .2, .04] and [.1, .1, .5, .1] (version 2);
      Shapley ≈ [0.37, 0.37, 1.7, 0.0] (version 1, sv_samples→∞).
    """
    x = np.array([[0, 1], [1, 0], [1, 2], [2, 1]], dtype=np.float32)
    y = np.max(x, axis=1, keepdims=True).astype(np.float32)

    w1 = np.array(
        [[-0.5, 1.0, 1.0, 1.0],
         [0.5, -1.0, 1.0, 1.0]],
        dtype=np.float32,
    )  # (in=2, out=4) — columns are units A, B, C, D
    w_d = 0.0 if version == 1 else -0.1
    w2 = np.array([[1.0], [0.5], [0.5], [w_d]], dtype=np.float32)  # (4, 1)

    model = SegmentedModel(
        layers=(
            L.Dense("fc1", 4, use_bias=False),
            L.Activation("act1", "relu"),
            L.Dense("fc2", 1, use_bias=False),
        ),
        input_shape=(2,),
    )
    params = {"fc1": {"w": jnp.asarray(w1)}, "fc2": {"w": jnp.asarray(w2)}}
    return model, params, jnp.asarray(x), jnp.asarray(y)


def max_model_batches(batch_size: int = 1):
    """The fixture's dataset as a list of (x, y) batches (the reference uses
    a batch-size-1 DataLoader, test_attributions.py:73-76)."""
    _, _, x, y = max_model()
    return [
        (x[i : i + batch_size], y[i : i + batch_size])
        for i in range(0, x.shape[0], batch_size)
    ]

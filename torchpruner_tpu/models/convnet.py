"""Fashion-MNIST convnet (reference experiments/models/fmnist.py:12-66):
2 × [Conv-BN-ReLU-MaxPool] → Flatten → FC4096-BN-ReLU → FC10.

``linearize=True`` swaps ReLUs for identity and MaxPool for AvgPool — the
reference's ablation switch for studying the linearized network (reference
fmnist.py:44-66).  Here it simply builds a different (still hashable) spec.
"""

from __future__ import annotations

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def digits_convnet() -> SegmentedModel:
    """The fmnist family at sklearn-digits scale (8x8x1 real scans): the
    conv-BN-ReLU-pool parity model for the trained-robustness protocol on
    always-available real data (experiments/parity.py) — the reference's
    sweep runs on a conv+BN VGG16; this is the same layer vocabulary where
    CPU-trainable minutes suffice."""
    layers = (
        L.Conv("conv1", 16, kernel_size=(3, 3), padding="SAME"),
        L.BatchNorm("bn1"),
        L.Activation("act1", "relu"),
        L.Pool("pool1", "max", (2, 2)),
        L.Conv("conv2", 32, kernel_size=(3, 3), padding="SAME"),
        L.BatchNorm("bn2"),
        L.Activation("act2", "relu"),
        L.Pool("pool2", "max", (2, 2)),
        L.Flatten("flatten"),
        L.Dense("fc1", 128),
        L.BatchNorm("bn3"),
        L.Activation("act3", "relu"),
        L.Dense("out", 10),
    )
    return SegmentedModel(layers, (8, 8, 1))


def fmnist_convnet(linearize: bool = False) -> SegmentedModel:
    act = "identity" if linearize else "relu"
    pool = "avg" if linearize else "max"
    layers = (
        L.Conv("conv1", 32, kernel_size=(5, 5), padding="SAME"),
        L.BatchNorm("bn1"),
        L.Activation("act1", act),
        L.Pool("pool1", pool, (2, 2)),
        L.Conv("conv2", 64, kernel_size=(5, 5), padding="SAME"),
        L.BatchNorm("bn2"),
        L.Activation("act2", act),
        L.Pool("pool2", pool, (2, 2)),
        L.Flatten("flatten"),
        L.Dense("fc1", 4096),
        L.BatchNorm("bn3"),
        L.Activation("act3", act),
        L.Dense("out", 10),
    )
    return SegmentedModel(layers, (28, 28, 1))

"""ResNet family — the "ResNet-50 / ImageNet structured filter pruning"
config of BASELINE.json.

The reference has no residual models (its zoo is FC nets + VGG16,
reference experiments/models/ — SURVEY.md §2.6); ResNet is the first
BASELINE.json capability target beyond reference parity.  Blocks are
:class:`~torchpruner_tpu.core.layers.Residual` specs, so the pruning graph
falls out of the same static analysis as everything else
(torchpruner_tpu/core/graph.py): convs feeding the residual sum are
width-pinned, interior convs prune with their in-block consumers, and a
stem conv feeding a projection-shortcut block cascades into both chains.

Taylor-criterion filter pruning on these models is the TPU-native analog of
the reference's conv-channel pruning (reference pruner.py:81-85) — same
surgery, derived statically instead of via the NaN trick.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def _basic_block(name: str, width: int, in_width: int, stride: int) -> L.Residual:
    """3x3 -> 3x3 residual block (ResNet-18/20/34)."""
    body = (
        L.Conv("conv1", width, (3, 3), (stride, stride), use_bias=False),
        L.BatchNorm("bn1"),
        L.Activation("relu1", "relu"),
        L.Conv("conv2", width, (3, 3), use_bias=False),
        L.BatchNorm("bn2"),
    )
    shortcut: Tuple[L.LayerSpec, ...] = ()
    if stride != 1 or in_width != width:
        shortcut = (
            L.Conv("proj", width, (1, 1), (stride, stride), use_bias=False),
            L.BatchNorm("proj_bn"),
        )
    return L.Residual(name, body, shortcut)


def _bottleneck(name: str, width: int, in_width: int, stride: int) -> L.Residual:
    """1x1 -> 3x3 -> 1x1(4x) bottleneck block (ResNet-50/101/152)."""
    out_width = 4 * width
    body = (
        L.Conv("conv1", width, (1, 1), use_bias=False),
        L.BatchNorm("bn1"),
        L.Activation("relu1", "relu"),
        L.Conv("conv2", width, (3, 3), (stride, stride), use_bias=False),
        L.BatchNorm("bn2"),
        L.Activation("relu2", "relu"),
        L.Conv("conv3", out_width, (1, 1), use_bias=False),
        L.BatchNorm("bn3"),
    )
    shortcut: Tuple[L.LayerSpec, ...] = ()
    if stride != 1 or in_width != out_width:
        shortcut = (
            L.Conv("proj", out_width, (1, 1), (stride, stride), use_bias=False),
            L.BatchNorm("proj_bn"),
        )
    return L.Residual(name, body, shortcut)


def _resnet(
    stage_blocks: Sequence[int],
    bottleneck: bool,
    n_classes: int,
    input_shape: Tuple[int, int, int],
    stem_width: int = 64,
    deep_stem_pool: bool = True,
    width_multiplier: float = 1.0,
) -> SegmentedModel:
    def w(x: int) -> int:
        return max(1, int(x * width_multiplier))

    make = _bottleneck if bottleneck else _basic_block
    expansion = 4 if bottleneck else 1
    layers: list = []
    if deep_stem_pool:
        layers += [
            L.Conv("stem", w(stem_width), (7, 7), (2, 2), use_bias=False),
            L.BatchNorm("stem_bn"),
            L.Activation("stem_relu", "relu"),
            L.Pool("stem_pool", "max", (3, 3), (2, 2), "SAME"),
        ]
    else:  # CIFAR stem: single 3x3, no pool
        layers += [
            L.Conv("stem", w(stem_width), (3, 3), use_bias=False),
            L.BatchNorm("stem_bn"),
            L.Activation("stem_relu", "relu"),
        ]
    in_width = w(stem_width)
    for si, n_blocks in enumerate(stage_blocks):
        width = w(stem_width * (2 ** si))
        for bi in range(n_blocks):
            stride = 2 if (si > 0 and bi == 0) else 1
            layers.append(
                make(f"stage{si + 1}_block{bi + 1}", width, in_width, stride)
            )
            layers.append(
                L.Activation(f"stage{si + 1}_block{bi + 1}_relu", "relu")
            )
            in_width = width * expansion
    layers += [
        L.GlobalPool("avgpool", "avg"),
        L.Dense("out", n_classes),
    ]
    return SegmentedModel(tuple(layers), input_shape)


def resnet50(
    n_classes: int = 1000,
    input_shape: Tuple[int, int, int] = (224, 224, 3),
    width_multiplier: float = 1.0,
) -> SegmentedModel:
    """ResNet-50: [3,4,6,3] bottleneck stages, the ImageNet filter-pruning
    target (Taylor criterion, BASELINE.json config 2)."""
    return _resnet(
        (3, 4, 6, 3), True, n_classes, input_shape,
        width_multiplier=width_multiplier,
    )


def resnet18(
    n_classes: int = 1000,
    input_shape: Tuple[int, int, int] = (224, 224, 3),
    width_multiplier: float = 1.0,
) -> SegmentedModel:
    """ResNet-18: [2,2,2,2] basic-block stages."""
    return _resnet(
        (2, 2, 2, 2), False, n_classes, input_shape,
        width_multiplier=width_multiplier,
    )


def resnet20_cifar(
    n_classes: int = 10,
    input_shape: Tuple[int, int, int] = (32, 32, 3),
    width_multiplier: float = 1.0,
) -> SegmentedModel:
    """CIFAR ResNet-20 (He et al. §4.2): 3x3 stem (width 16), three stages of
    three basic blocks at widths 16/32/64 — the small residual model used by
    tests and CPU smoke runs."""
    return _resnet(
        (3, 3, 3), False, n_classes, input_shape,
        stem_width=16, deep_stem_pool=False,
        width_multiplier=width_multiplier,
    )

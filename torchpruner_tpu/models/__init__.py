"""Model zoo: segmented models mirroring the reference's experiments/models/
plus the analytic test fixture."""

from torchpruner_tpu.models.analytic import max_model
from torchpruner_tpu.models.mlp import mnist_fc, cifar10_fc
from torchpruner_tpu.models.convnet import fmnist_convnet
from torchpruner_tpu.models.vgg import vgg16_bn

__all__ = ["max_model", "mnist_fc", "cifar10_fc", "fmnist_convnet", "vgg16_bn"]

"""Model zoo.

Reference parity (experiments/models/): the analytic ``max_model`` fixture,
MNIST/CIFAR FC nets, the FMNIST convnet, and CIFAR VGG16-bn.  Beyond parity,
the BASELINE.json capability targets: ResNet (filter pruning), ViT (head +
MLP pruning), BERT (Linear pruning), and Llama (FFN channel pruning)."""

from torchpruner_tpu.models.analytic import max_model
from torchpruner_tpu.models.mlp import fc_net, mnist_fc, cifar10_fc, digits_fc
from torchpruner_tpu.models.convnet import digits_convnet, fmnist_convnet
from torchpruner_tpu.models.vgg import vgg16_bn
from torchpruner_tpu.models.resnet import resnet18, resnet20_cifar, resnet50
from torchpruner_tpu.models.vit import vit, vit_b16, vit_tiny
from torchpruner_tpu.models.bert import bert, bert_base, bert_tiny
from torchpruner_tpu.models.llama import (
    llama,
    llama3_8b,
    llama_moe,
    llama_moe_tiny,
    llama_tiny,
    mfu_llama,
)

__all__ = [
    "max_model", "mnist_fc", "cifar10_fc", "digits_fc", "digits_convnet",
    "fmnist_convnet",
    "vgg16_bn",
    "resnet18", "resnet20_cifar", "resnet50",
    "vit", "vit_b16", "vit_tiny",
    "bert", "bert_base", "bert_tiny",
    "llama", "llama3_8b", "llama_moe", "llama_moe_tiny", "llama_tiny",
    "mfu_llama",
]

"""Vision Transformer family — the "ViT-B/16 attention-head + MLP pruning"
config of BASELINE.json.

No transformer exists in the reference (vision CNNs only, SURVEY.md §5.7);
this family exercises the two transformer prune-group kinds the framework
adds beyond reference parity: self-contained attention-head groups
(:class:`~torchpruner_tpu.core.layers.MultiHeadAttention`) and in-block MLP
hidden-channel groups (fc1 pruned with fc2 as consumer), both derived by the
static pruning graph inside :class:`~torchpruner_tpu.core.layers.Residual`
bodies.

Pre-LN encoder (Dosovitskiy et al., 2021): patchify conv, CLS token +
learned positions, ``depth`` blocks of ``[LN, MHA] + [LN, fc1, gelu, fc2]``
residuals, final LN, CLS-token head.
"""

from __future__ import annotations

from typing import Tuple

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def vit(
    *,
    image_size: int = 224,
    patch_size: int = 16,
    dim: int = 768,
    depth: int = 12,
    num_heads: int = 12,
    mlp_dim: int = 3072,
    n_classes: int = 1000,
    dropout: float = 0.0,
    pool: str = "cls",
) -> SegmentedModel:
    if image_size % patch_size:
        raise ValueError(
            f"image_size {image_size} not divisible by patch_size {patch_size}"
        )
    if dim % num_heads:
        raise ValueError(f"dim {dim} not divisible by num_heads {num_heads}")
    n_patches = (image_size // patch_size) ** 2
    seq = n_patches + (1 if pool == "cls" else 0)
    layers: list = [
        L.Conv(
            "patchify", dim, (patch_size, patch_size),
            (patch_size, patch_size), "VALID",
        ),
        L.Reshape("to_tokens", (n_patches, dim)),
    ]
    if pool == "cls":
        layers.append(L.ClsToken("cls"))
    layers.append(L.PosEmbed("pos", max_len=seq))
    if dropout:
        layers.append(L.Dropout("embed_drop", dropout))
    for i in range(1, depth + 1):
        attn_body: Tuple[L.LayerSpec, ...] = (
            L.LayerNorm("ln"),
            L.MultiHeadAttention(
                "attn", num_heads=num_heads, head_dim=dim // num_heads,
                use_bias=True,
            ),
        )
        mlp_body: Tuple[L.LayerSpec, ...] = (
            L.LayerNorm("ln"),
            L.Dense("fc1", mlp_dim),
            L.Activation("gelu", "gelu"),
        ) + ((L.Dropout("drop", dropout),) if dropout else ()) + (
            L.Dense("fc2", dim),
        )
        layers.append(L.Residual(f"block{i}_attn", attn_body))
        layers.append(L.Residual(f"block{i}_mlp", mlp_body))
    layers += [
        L.LayerNorm("final_ln"),
        L.GlobalPool("pool", "cls" if pool == "cls" else "seq_mean"),
        L.Dense("head", n_classes),
    ]
    return SegmentedModel(
        tuple(layers), (image_size, image_size, 3)
    )


def vit_b16(n_classes: int = 1000, image_size: int = 224) -> SegmentedModel:
    """ViT-B/16: 12 blocks, dim 768, 12 heads, MLP 3072 — the BASELINE.json
    head+MLP pruning target (Shapley, sv_samples=5)."""
    return vit(
        image_size=image_size, patch_size=16, dim=768, depth=12,
        num_heads=12, mlp_dim=3072, n_classes=n_classes,
    )


def vit_tiny(
    n_classes: int = 10,
    image_size: int = 16,
    patch_size: int = 4,
    dim: int = 32,
    depth: int = 2,
    num_heads: int = 4,
    mlp_dim: int = 64,
) -> SegmentedModel:
    """Miniature ViT with the full block structure — tests / CPU smoke."""
    return vit(
        image_size=image_size, patch_size=patch_size, dim=dim, depth=depth,
        num_heads=num_heads, mlp_dim=mlp_dim, n_classes=n_classes,
    )

"""VGG16-bn for CIFAR-10 — the flagship / north-star model.

Matches the reference's ``prunable_vgg16`` (reference experiments/models/
cifar10.py:62-76): torchvision ``vgg16_bn`` feature extractor (13 convs with
BatchNorm, 5 max-pools) with a CIFAR-sized 512-wide classifier.  On 32×32
inputs the feature map is 1×1×512 at the flatten, so the classifier is
512→512→512→10 with dropout.  15 prunable layers precede the output head
(the "15 prunable modules" of the layerwise-robustness experiment,
SURVEY.md §2.8).

Built as a flat ``SegmentedModel``, the pruning graph — which the reference
hand-writes in ``get_vgg_pruning_graph`` (reference torchpruner/utils/
graph.py:37-61) — is *derived* by ``torchpruner_tpu.core.graph.pruning_graph``.
"""

from __future__ import annotations

from typing import Sequence, Tuple, Union

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel

# Standard VGG16 configuration: channel widths with 'M' = max-pool.
VGG16_CFG: Tuple[Union[int, str], ...] = (
    64, 64, "M",
    128, 128, "M",
    256, 256, 256, "M",
    512, 512, 512, "M",
    512, 512, 512, "M",
)


def vgg16_bn(
    n_classes: int = 10,
    input_shape: Tuple[int, int, int] = (32, 32, 3),
    classifier_width: int = 512,
    dropout: float = 0.5,
    width_multiplier: float = 1.0,
) -> SegmentedModel:
    """``width_multiplier`` scales every conv width (same 16-layer structure
    at a fraction of the size — used for multi-chip dryruns on tiny shapes).
    Must satisfy ``64 * width_multiplier >= 1`` so every layer keeps at
    least one channel; widths round down."""
    if width_multiplier <= 0 or 64 * width_multiplier < 1:
        raise ValueError(
            f"width_multiplier {width_multiplier} would produce empty conv "
            "layers (need 64 * width_multiplier >= 1)"
        )
    layers = []
    conv_i = 0
    pool_i = 0
    for v in VGG16_CFG:
        if v == "M":
            pool_i += 1
            layers.append(L.Pool(f"pool{pool_i}", "max", (2, 2)))
        else:
            conv_i += 1
            width = int(int(v) * width_multiplier)
            layers.append(L.Conv(f"conv{conv_i}", width, kernel_size=(3, 3)))
            layers.append(L.BatchNorm(f"bn{conv_i}"))
            layers.append(L.Activation(f"relu{conv_i}", "relu"))
    layers.append(L.Flatten("flatten"))
    layers += [
        L.Dense("fc1", classifier_width),
        L.Activation("relu_fc1", "relu"),
        L.Dropout("drop1", dropout),
        L.Dense("fc2", classifier_width),
        L.Activation("relu_fc2", "relu"),
        L.Dropout("drop2", dropout),
        L.Dense("out", n_classes),
    ]
    return SegmentedModel(tuple(layers), input_shape)

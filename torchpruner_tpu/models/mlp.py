"""Fully-connected model zoo entries.

Mirrors the reference's experiments/models/mnist.py:12-48 (784→2024→2024→10
LeakyReLU net) and the CIFAR-10 FC variant (experiments/models/cifar10.py:10-36)
used by the "Pruning Untrained Networks" experiment.
"""

from __future__ import annotations

from typing import Sequence, Tuple

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def fc_net(
    input_size: int,
    hidden: Sequence[int] = (2024, 2024),
    n_classes: int = 10,
    activation: str = "leaky_relu",
) -> SegmentedModel:
    layers = []
    for i, h in enumerate(hidden):
        layers.append(L.Dense(f"fc{i + 1}", h))
        layers.append(L.Activation(f"act{i + 1}", activation))
    layers.append(L.Dense("out", n_classes))
    return SegmentedModel(tuple(layers), (input_size,))


def mnist_fc() -> SegmentedModel:
    """784-2024-2024-10 LeakyReLU (reference experiments/models/mnist.py:14-23).
    Input is the flattened 28×28 image."""
    return fc_net(784)


def cifar10_fc() -> SegmentedModel:
    """Same architecture for flattened 32×32×3 CIFAR-10 input (reference
    experiments/models/cifar10.py:10-36)."""
    return fc_net(32 * 32 * 3)


def digits_fc() -> SegmentedModel:
    """The reference MNIST-FC architecture scaled to the 8×8 sklearn digits
    (the always-available REAL dataset): 64-512-512-10 LeakyReLU.  Same
    depth/activation/overparameterization regime as reference
    experiments/models/mnist.py:14-23, ~8× input downscale."""
    return fc_net(64, hidden=(512, 512))

"""BERT family — the "BERT-base Linear-layer pruning on GLUE (Sensitivity
criterion)" config of BASELINE.json.

Post-LN encoder (Devlin et al., 2019): token + learned-position embeddings,
``depth`` blocks of ``Residual[MHA] -> LN -> Residual[fc1, gelu, fc2] -> LN``,
CLS pooler (tanh), classification head.  Single-segment inputs (token-type
embeddings add nothing to pruning behavior and are omitted; the CLS/SEP
convention lives in the tokenizer, so pooling is first-token select).

The Linear-layer pruning target is each block's ``fc1`` (hidden 3072),
pruned with its ``fc2`` consumer inside the residual body — the same group
shape the reference handles for Linear->Linear chains with the NaN trick
(reference tests/test_pruner.py:72-81), here derived statically.
"""

from __future__ import annotations

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def bert(
    *,
    vocab_size: int = 30522,
    max_len: int = 512,
    dim: int = 768,
    depth: int = 12,
    num_heads: int = 12,
    mlp_dim: int = 3072,
    n_classes: int = 2,
    dropout: float = 0.1,
    seq_len: int = 128,
) -> SegmentedModel:
    if dim % num_heads:
        raise ValueError(f"dim {dim} not divisible by num_heads {num_heads}")
    layers: list = [
        L.Embedding("tok_emb", vocab_size, dim),
        L.PosEmbed("pos", max_len=max_len),
        L.LayerNorm("emb_ln"),
    ]
    if dropout:
        layers.append(L.Dropout("emb_drop", dropout))
    for i in range(1, depth + 1):
        attn_body = (
            L.MultiHeadAttention(
                "attn", num_heads=num_heads, head_dim=dim // num_heads,
                use_bias=True,
            ),
        ) + ((L.Dropout("drop", dropout),) if dropout else ())
        mlp_body = (
            L.Dense("fc1", mlp_dim),
            L.Activation("gelu", "gelu"),
            L.Dense("fc2", dim),
        ) + ((L.Dropout("drop", dropout),) if dropout else ())
        layers += [
            L.Residual(f"block{i}_attn", attn_body),
            L.LayerNorm(f"block{i}_attn_ln"),
            L.Residual(f"block{i}_mlp", mlp_body),
            L.LayerNorm(f"block{i}_mlp_ln"),
        ]
    layers += [
        L.GlobalPool("cls_pool", "cls"),
        L.Dense("pooler", dim),
        L.Activation("pooler_tanh", "tanh"),
        L.Dense("head", n_classes),
    ]
    return SegmentedModel(tuple(layers), (seq_len,), input_dtype="int32")


def bert_base(n_classes: int = 2, seq_len: int = 128) -> SegmentedModel:
    """BERT-base: 12 blocks, dim 768, 12 heads, FFN 3072 — the GLUE
    Sensitivity-pruning target of BASELINE.json."""
    return bert(n_classes=n_classes, seq_len=seq_len)


def bert_tiny(
    n_classes: int = 2,
    seq_len: int = 16,
    vocab_size: int = 128,
    dim: int = 32,
    depth: int = 2,
    num_heads: int = 4,
    mlp_dim: int = 64,
) -> SegmentedModel:
    """Miniature BERT with the full block structure — tests / CPU smoke."""
    return bert(
        vocab_size=vocab_size, max_len=seq_len, dim=dim, depth=depth,
        num_heads=num_heads, mlp_dim=mlp_dim, n_classes=n_classes,
        dropout=0.0, seq_len=seq_len,
    )

"""Import torch/torchvision checkpoints into the framework's pytrees.

The reference's headline experiment loads a pretrained CIFAR10-VGG16
state_dict (92.5 % accuracy, VGG notebook cells 3-4) — a user migrating
from the reference brings exactly such a file.  This module maps a
torchvision-layout ``state_dict`` (a flat ``{qualified_name: tensor}``
dict; torch tensors or numpy arrays both accepted, so ``torch.load`` on
CPU or a pre-converted npz both work) onto this framework's
``(params, state)`` trees, with the layout conversions TPU-native code
needs:

- Conv weights ``OIHW -> HWIO`` (we run channels-last NHWC).
- Linear weights ``(out, in) -> (in, out)``.
- BatchNorm ``weight/bias/running_mean/running_var`` ->
  ``scale/bias`` params + ``mean/var`` state.
- The flatten boundary: torch flattens ``(C, H, W)`` C-major, we flatten
  ``(H, W, C)`` — the first Linear's input axis is permuted accordingly
  (identity when the final feature map is 1×1, as in CIFAR VGG16).
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel


def _to_np(t) -> np.ndarray:
    if hasattr(t, "detach"):  # torch tensor, no torch import needed
        t = t.detach().cpu()
        if "bfloat16" in str(t.dtype):
            # numpy has no torch-bf16 bridge (real llama3 checkpoints
            # ship bf16); widen to f32 first
            t = t.float()
        t = t.numpy()
    return np.asarray(t)


def _grouped(state_dict) -> Dict[Tuple[str, int], Dict[str, np.ndarray]]:
    """``{(section, index): {param_name: array}}`` from flat torch keys
    like ``features.0.weight`` / ``classifier.4.bias``."""
    groups: Dict[Tuple[str, int], Dict[str, np.ndarray]] = {}
    for key, value in state_dict.items():
        parts = key.split(".")
        if len(parts) < 3 or not parts[-2].isdigit():
            continue
        sec, idx, name = ".".join(parts[:-2]), int(parts[-2]), parts[-1]
        groups.setdefault((sec, idx), {})[name] = _to_np(value)
    return groups


def _classify(groups):
    """Split ordered module groups into conv / bn / linear lists."""
    convs, bns, linears = [], [], []
    for key in sorted(groups, key=lambda t: (t[0], t[1])):
        g = groups[key]
        if "running_mean" in g:
            bns.append(g)
        elif "weight" in g and g["weight"].ndim == 4:
            convs.append(g)
        elif "weight" in g and g["weight"].ndim == 2:
            linears.append(g)
    return convs, bns, linears


def _flatten_perm(pre_flatten_shape: Tuple[int, ...]) -> np.ndarray:
    """Index permutation taking torch's C-major flatten order to our
    channels-last (H, W, C) flatten order for a (H, W, C) feature map."""
    H, W, C = pre_flatten_shape
    idx = np.arange(C * H * W).reshape(C, H, W)  # torch layout
    return idx.transpose(1, 2, 0).reshape(-1)  # our layout positions


def import_torch_vgg16_bn(
    state_dict,
    model: Optional[SegmentedModel] = None,
) -> Tuple[SegmentedModel, Dict[str, Any], Dict[str, Any]]:
    """Map a torchvision-layout VGG16-bn ``state_dict`` (the reference's
    pretrained-checkpoint format, reference VGG notebook cell 4) onto
    ``(model, params, state)``.

    ``model`` defaults to :func:`~torchpruner_tpu.models.vgg16_bn` sized
    from the checkpoint's classifier; every mapped array is shape-checked
    against the spec.
    """
    from torchpruner_tpu.models import vgg16_bn

    convs, bns, linears = _classify(_grouped(state_dict))
    if len(convs) != 13 or len(bns) != 13:
        raise ValueError(
            f"expected 13 conv + 13 bn module groups (VGG16-bn), got "
            f"{len(convs)} + {len(bns)}"
        )
    if len(linears) != 3:
        raise ValueError(
            f"expected 3 classifier Linears (reference cifar10.py:62-74), "
            f"got {len(linears)}"
        )
    if model is None:
        model = vgg16_bn(
            n_classes=linears[-1]["weight"].shape[0],
            classifier_width=linears[0]["weight"].shape[0],
        )

    params: Dict[str, Any] = {}
    state: Dict[str, Any] = {}
    for i, g in enumerate(convs, 1):
        entry = {"w": g["weight"].transpose(2, 3, 1, 0)}  # OIHW -> HWIO
        if "bias" in g:
            entry["b"] = g["bias"]
        params[f"conv{i}"] = entry
    for i, g in enumerate(bns, 1):
        params[f"bn{i}"] = {"scale": g["weight"], "bias": g["bias"]}
        state[f"bn{i}"] = {"mean": g["running_mean"], "var": g["running_var"]}

    # flatten-boundary permutation for the first Linear's input axis
    for name, g in zip(("fc1", "fc2", "out"), linears):
        w = g["weight"].T  # (in, out)
        if name == "fc1":
            h_w_c = _pre_flatten_shape(model)
            if int(np.prod(h_w_c)) != w.shape[0]:
                raise ValueError(
                    f"flatten width mismatch: model {h_w_c} vs checkpoint "
                    f"{w.shape[0]}"
                )
            w = w[_flatten_perm(h_w_c)]
        params[name] = {"w": w, "b": g["bias"]}

    _validate_shapes(model, params, state)
    return model, _as_jnp(params), _as_jnp(state)


def _pre_flatten_shape(model: SegmentedModel) -> Tuple[int, ...]:
    for (in_shape, _out), spec in zip(model.shapes, model.layers):
        if isinstance(spec, L.Flatten):
            return tuple(in_shape)
    raise ValueError("model has no Flatten layer")


def _named_leaves(tree):
    import jax

    from torchpruner_tpu.core.plan import _key_name

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return {
        "/".join(_key_name(k) for k in path): leaf for path, leaf in flat
    }


def _validate_shapes(model: SegmentedModel, params, state):
    import jax

    from torchpruner_tpu.core.segment import init_model

    ref_p, ref_s = jax.eval_shape(
        lambda k: init_model(model, seed=0), jax.random.PRNGKey(0)
    )
    for tree, ref, what in ((params, ref_p, "params"), (state, ref_s, "state")):
        if what == "state" and not tree:
            continue  # stateless import (RMSNorm-only models)
        got, want = _named_leaves(tree), _named_leaves(ref)
        if set(got) != set(want):
            raise ValueError(
                f"{what} tree mismatch: missing {sorted(set(want) - set(got))[:5]}, "
                f"unexpected {sorted(set(got) - set(want))[:5]}"
            )
        for name, arr in got.items():
            if tuple(arr.shape) != tuple(want[name].shape):
                raise ValueError(
                    f"{what} {name}: checkpoint shape {arr.shape} vs "
                    f"model {tuple(want[name].shape)}"
                )


def _as_jnp(tree):
    import jax.numpy as jnp

    def conv(v):
        if isinstance(v, dict):
            return {k: conv(x) for k, x in v.items()}
        return jnp.asarray(v, jnp.float32)

    return {k: conv(v) for k, v in tree.items()}


def import_hf_llama(
    state_dict,
    *,
    vocab_size: int,
    dim: int,
    depth: int,
    num_heads: int,
    num_kv_heads: int,
    ffn_dim: int,
    rope_theta: float = 500000.0,
    seq_len: int = 2048,
) -> Tuple[SegmentedModel, Dict[str, Any], Dict[str, Any]]:
    """Map a HuggingFace ``LlamaForCausalLM`` ``state_dict`` onto this
    framework's :func:`~torchpruner_tpu.models.llama` trees — the
    migration path for the llama3_8b BASELINE config.

    Layout conversions (HF stores every projection as a torch Linear
    ``(out, in)``):

    - ``q_proj (H*Dh, d)`` -> ``wq (d, H, Dh)``; ``k/v_proj (KV*Dh, d)``
      -> ``wk/wv (d, KV, Dh)``; ``o_proj (d, H*Dh)`` -> ``wo (H, Dh, d)``.
    - ``gate_proj``/``up_proj`` -> ``GatedDense wg/wu (d, F)``;
      ``down_proj`` -> ``down.w (F, d)``.
    - ``input_layernorm`` -> the attention block's RMSNorm;
      ``post_attention_layernorm`` -> the FFN block's; ``model.norm`` ->
      ``final_norm``; ``embed_tokens``/``lm_head`` pass through
      (``lm_head`` may be absent when tied — the embedding is reused).

    Both frameworks apply the same half-split rotary embedding
    (``rotate_half``), so no permutation of head channels is needed.
    """
    from torchpruner_tpu.models import llama

    model = llama(
        vocab_size=vocab_size, dim=dim, depth=depth, num_heads=num_heads,
        num_kv_heads=num_kv_heads, head_dim=dim // num_heads,
        ffn_dim=ffn_dim, rope_theta=rope_theta, seq_len=seq_len,
    )
    import jax.numpy as jnp

    # Tensors convert LAZILY, one at a time: torch -> f32 numpy -> jax
    # buffer, with the source entry popped as it is consumed.  An 8B bf16
    # checkpoint is ~16 GB; eager whole-dict conversion would hold ~3 full
    # f32 copies (~96 GB) in host RAM at peak, this holds ~1 copy + one
    # tensor.
    raw = {k.removeprefix("model."): v for k, v in state_dict.items()}
    H, KV = num_heads, num_kv_heads
    Dh = dim // num_heads

    def take(key) -> np.ndarray:
        return _to_np(raw.pop(key))

    def j(arr) -> "jnp.ndarray":
        return jnp.asarray(arr, jnp.float32)

    def lin(key):  # torch Linear weight -> (in, out)
        return j(take(key).T)

    emb = take("embed_tokens.weight")
    head = raw.pop("lm_head.weight", None)
    params: Dict[str, Any] = {
        "tok_emb": {"emb": j(emb)},
        "final_norm": {"scale": j(take("norm.weight"))},
        "lm_head": {
            # tied embeddings when lm_head is absent
            "w": j(_to_np(head).T) if head is not None else j(emb.T)
        },
    }
    del emb, head
    for i in range(1, depth + 1):
        p = f"layers.{i - 1}."
        params[f"block{i}_attn"] = {
            "norm": {"scale": j(take(p + "input_layernorm.weight"))},
            "attn": {
                "wq": j(take(p + "self_attn.q_proj.weight").T
                        .reshape(dim, H, Dh)),
                "wk": j(take(p + "self_attn.k_proj.weight").T
                        .reshape(dim, KV, Dh)),
                "wv": j(take(p + "self_attn.v_proj.weight").T
                        .reshape(dim, KV, Dh)),
                # o_proj (d, H*Dh) -> transpose -> (H*Dh, d) -> (H, Dh, d)
                "wo": j(take(p + "self_attn.o_proj.weight").T
                        .reshape(H, Dh, dim)),
            },
        }
        params[f"block{i}_ffn"] = {
            "norm": {"scale": j(take(p + "post_attention_layernorm.weight"))},
            "gate": {
                "wg": lin(p + "mlp.gate_proj.weight"),
                "wu": lin(p + "mlp.up_proj.weight"),
            },
            "down": {"w": lin(p + "mlp.down_proj.weight")},
        }
    _validate_shapes(model, params, {})
    return model, params, {}

"""Dtype helpers shared by the mixed-precision paths (train loop,
attribution scoring)."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def cast_floats(tree, dtype):
    """Cast every floating leaf of a pytree to ``dtype`` (ints/bools pass
    through)."""
    return jax.tree_util.tree_map(
        lambda a: a.astype(dtype)
        if jnp.issubdtype(jnp.result_type(a), jnp.floating)
        else a,
        tree,
    )


def float_dtype_of(tree, default=jnp.float32):
    """Dtype of the first floating leaf (the activation dtype a model with
    integer inputs will compute in), or ``default`` if none."""
    for leaf in jax.tree_util.tree_leaves(tree):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            return jnp.result_type(leaf)
    return default

"""Tracing and timing utilities.

Replaces the reference's ``timeit.default_timer`` spot checks and ``%%time``
cells (reference experiments/utils/train.py:16, SURVEY.md §5.1) with the
TPU-native equivalents: ``jax.profiler`` traces viewable in
XProf/TensorBoard, and steady-state wall-clock timing that respects async
dispatch (``block_until_ready`` fencing — naive timing measures only the
Python dispatch of a TPU computation, not its execution).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/torchpruner_tpu_trace"):
    """Capture a profiler trace of the enclosed block::

        with profiling.trace("logs/trace"):
            trainer.step(x, y)

    View with TensorBoard's profile plugin / XProf.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def time_fn(
    fn: Callable,
    *args,
    iters: int = 10,
    warmup: int = 2,
    **kwargs,
) -> Dict[str, float]:
    """Steady-state wall-clock of ``fn(*args, **kwargs)``.

    Warms up (compile + cache), then times ``iters`` calls with a
    ``block_until_ready`` fence on each result.  Returns
    ``{"mean_s", "min_s", "p50_s", "compile_s"}``.
    """
    t0 = time.perf_counter()
    out = None
    for _ in range(max(1, warmup)):
        out = fn(*args, **kwargs)
    jax.block_until_ready(out)
    compile_s = time.perf_counter() - t0

    times: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        jax.block_until_ready(out)
        times.append(time.perf_counter() - t0)
    times.sort()
    return {
        "mean_s": sum(times) / len(times),
        "min_s": times[0],
        "p50_s": times[len(times) // 2],
        "compile_s": compile_s,
    }


def time_train_step(trainer, *args, iters: int = 10, warmup: int = 2):
    """:func:`time_fn` over a ``Trainer.step`` call, fenced on the UPDATED
    params rather than only the returned loss.

    A train step is one compiled program whose outputs are (params, state,
    opt_state, loss), but ``step()`` hands back just the scalar loss.  On
    the tunnelled TPU backend that scalar's buffer can report ready before
    the program retires, so fencing the loss alone undercounts the step —
    observed as 2.4 ms "steps" (implied 12 PFLOP/s) on a ~200M-param model.
    Fencing the new params pins the measurement to program completion on
    every backend.
    """

    def step_fenced(*a):
        loss = trainer.step(*a)
        return loss, trainer.params

    return time_fn(step_fenced, *args, iters=iters, warmup=warmup)


@dataclass
class StepTimer:
    """Accumulates per-phase wall-clock inside experiment loops (score /
    prune / recompile / finetune) — the breakdown the north-star metric
    needs (SURVEY.md §7 'recompilation economics')."""

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {"total_s": v, "calls": self.counts[k],
                "mean_s": v / self.counts[k]}
            for k, v in self.totals.items()
        }

"""Tracing and timing utilities.

Replaces the reference's ``timeit.default_timer`` spot checks and ``%%time``
cells (reference experiments/utils/train.py:16, SURVEY.md §5.1) with the
TPU-native equivalents: ``jax.profiler`` traces viewable in
XProf/TensorBoard, and steady-state wall-clock timing that respects async
dispatch (``block_until_ready`` fencing — naive timing measures only the
Python dispatch of a TPU computation, not its execution).
"""

from __future__ import annotations

import contextlib
import time
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str = "/tmp/torchpruner_tpu_trace"):
    """Capture a profiler trace of the enclosed block::

        with profiling.trace("logs/trace"):
            trainer.step(x, y)

    View with TensorBoard's profile plugin / XProf.
    """
    jax.profiler.start_trace(log_dir)
    try:
        yield log_dir
    finally:
        jax.profiler.stop_trace()


def hard_fence(out) -> None:
    """Block until ``out`` has ACTUALLY been computed, on every backend.

    ``jax.block_until_ready`` waits on buffer readiness *events*.  On the
    tunnelled axon TPU backend those events can signal before the program
    retires, so a readiness fence undercounts wildly (observed: 1.6 ms
    "train steps" on a ~200M-param model — an implied 24 PFLOP/s on a
    ~0.4 PFLOP/s chip).  A device→host copy has no such loophole: the
    bytes of an output cannot arrive on the host before the program that
    writes them finishes executing on the device stream.

    To keep the fence cheap even when the outputs are large (e.g. timed
    attention gradients — MBs per leaf), fetch a one-element *canary*:
    eagerly index the smallest leaf (a tiny dependent program that the
    device cannot run before the producer retires) and ``device_get`` its
    4-byte result.  Host-side event signalling may lie; the device-stream
    ordering and the D2H bytes cannot.
    """
    jax.block_until_ready(out)
    leaves = [l for l in jax.tree_util.tree_leaves(out)
              if hasattr(l, "ravel") and getattr(l, "size", 0)]
    if leaves:
        smallest = min(leaves, key=lambda l: l.size)
        jax.device_get(smallest.ravel()[0])


def time_fn(
    fn: Callable,
    *args,
    iters: int = 10,
    warmup: int = 2,
    chained: bool = False,
    **kwargs,
) -> Dict[str, float]:
    """Steady-state wall-clock of ``fn(*args, **kwargs)``.

    Warms up (compile + cache), then measures two ways:

    - **per-call** (``p50_s``/``min_s``/``mean_s``): each call is followed
      by a :func:`hard_fence` — a device→host fetch of a one-element
      canary, because event-based readiness fences lie on the tunnelled
      backend (see :func:`hard_fence`).  The fetch adds one tunnel round
      trip per call, which *over*counts small steps by the RTT.
    - **chained** (``chained_mean_s``, only when ``chained=True`` — it
      costs a second full ``iters`` pass): all ``iters`` calls dispatched
      back-to-back with ONE fence at the end.  A TPU core executes its
      program stream in order, so fencing the last call's output fences
      them all; the RTT is amortized 1/iters.  This is how a real
      training loop behaves (async dispatch, no per-step sync — see
      train/loop.py's 8-step-back fence), so chained is the honest
      steady-state throughput number on a tunnelled device; the fenced
      p50 is its conservative upper bound.
    """
    t0 = time.perf_counter()
    out = None
    for _ in range(max(1, warmup)):
        out = fn(*args, **kwargs)
    hard_fence(out)
    compile_s = time.perf_counter() - t0

    times: List[float] = []
    for _ in range(iters):
        t0 = time.perf_counter()
        out = fn(*args, **kwargs)
        hard_fence(out)
        times.append(time.perf_counter() - t0)
    times.sort()

    stats = {
        "mean_s": sum(times) / len(times),
        "min_s": times[0],
        "p50_s": times[len(times) // 2],
        "compile_s": compile_s,
    }
    if chained:
        t0 = time.perf_counter()
        for _ in range(iters):
            out = fn(*args, **kwargs)
        hard_fence(out)
        stats["chained_mean_s"] = (time.perf_counter() - t0) / iters
    return stats


def steady_s(stats: Dict[str, float]) -> float:
    """The steady-state seconds from a :func:`time_fn` result: the
    chained mean when measured (async-dispatch behavior, RTT amortized),
    else the per-call fenced p50 — ONE definition for every bench leg."""
    if stats.get("chained_mean_s"):
        return stats["chained_mean_s"]
    return stats["p50_s"]


def _params_probe(trainer, scalar):
    """A scalar that data-depends on the trainer's UPDATED params:
    fetching it (time_fn's hard_fence device_gets the smallest leaf)
    cannot complete before the step program has written params'.  The
    probe is its own tiny eager dispatch — nanoseconds next to the
    step, and it keeps the D2H payload at 4 bytes instead of
    round-tripping a params leaf over the tunnel."""
    leaf = jax.tree_util.tree_leaves(trainer.params)[0]
    return scalar.astype(jax.numpy.float32) + 0.0 * leaf.ravel()[0].astype(
        jax.numpy.float32)


def time_train_step(trainer, *args, iters: int = 10, warmup: int = 2,
                    chained: bool = False):
    """:func:`time_fn` over a ``Trainer.step`` call, fenced on the UPDATED
    params rather than only the returned loss.

    A train step is one compiled program whose outputs are (params, state,
    opt_state, loss), but ``step()`` hands back just the scalar loss.  On
    the tunnelled TPU backend that scalar's buffer can report ready before
    the program retires, so fencing the loss alone undercounts the step —
    observed as 2.4 ms "steps" (implied 12 PFLOP/s) on a ~200M-param model.
    Fencing the new params (:func:`_params_probe`) pins the measurement to
    program completion on every backend.
    """

    def step_fenced(*a):
        return _params_probe(trainer, trainer.step(*a))

    return time_fn(step_fenced, *args, iters=iters, warmup=warmup,
                   chained=chained)


def time_train_multi_step(trainer, xs, ys, iters: int = 5, warmup: int = 2,
                          chained: bool = True):
    """:func:`time_fn` over ``Trainer.multi_step`` (K optimizer steps in
    ONE dispatched program — the per-program dispatch cost amortizes 1/K
    on top of chaining's 1/iters fence amortization), fenced on the
    updated params like :func:`time_train_step`.  Divide
    :func:`steady_s` by ``xs.shape[0]`` for the per-step seconds."""

    def fenced(xs_, ys_):
        return _params_probe(trainer, trainer.multi_step(xs_, ys_)[-1])

    return time_fn(fenced, xs, ys, iters=iters, warmup=warmup,
                   chained=chained)


@dataclass
class StepTimer:
    """Accumulates per-phase wall-clock inside experiment loops (score /
    prune / recompile / finetune) — the breakdown the north-star metric
    needs (SURVEY.md §7 'recompilation economics').

    For new code prefer ``obs.span`` (same accounting plus JSONL events,
    trace annotations and compile attribution); :meth:`from_span_jsonl`
    rebuilds a StepTimer from an obs event stream so existing consumers
    of ``summary()`` can read either source.
    """

    totals: Dict[str, float] = field(default_factory=dict)
    counts: Dict[str, int] = field(default_factory=dict)

    @contextlib.contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.counts[name] = self.counts.get(name, 0) + 1

    @classmethod
    def from_span_jsonl(cls, path: str) -> "StepTimer":
        """A StepTimer whose phases are the span names of an obs
        ``events.jsonl`` (every ``span_end``'s duration, keyed by name;
        latest run only — see :func:`load_span_events`)."""
        timer = cls()
        for name, v in span_phase_summary(path).items():
            timer.totals[name] = v["total_s"]
            timer.counts[name] = v["calls"]
        return timer

    def summary(self) -> Dict[str, Dict[str, float]]:
        return {
            k: {"total_s": v, "calls": self.counts[k],
                "mean_s": v / self.counts[k]}
            for k, v in self.totals.items()
        }


def load_span_events(path: str, latest_run: bool = True) -> List[dict]:
    """Parse an obs ``events.jsonl`` (one JSON object per line; malformed
    lines — e.g. the torn last line of a killed run — are skipped).

    Rotation-aware: a size-capped session renames the stream to
    ``events.jsonl.1`` … as it grows (``obs.configure(rotate_bytes=…)``),
    so the rotated backups are read first, oldest to newest, then the
    live file — one continuous stream.

    The stream is append-mode across sessions; every session opens with
    an ``obs_init`` marker.  ``latest_run`` (default) returns only the
    events after the LAST marker, so re-using an ``--obs-dir`` doesn't
    double-count earlier runs in phase summaries (same contract as
    ``trace_analysis.find_trace_files``)."""
    import glob
    import json
    import os
    import re

    rotated = []
    for p in glob.glob(path + ".*"):
        m = re.match(re.escape(path) + r"\.(\d+)$", p)
        if m:
            rotated.append((int(m.group(1)), p))
    # highest suffix = oldest; read oldest → newest → live file
    paths = [p for _, p in sorted(rotated, reverse=True)]
    if os.path.exists(path) or not paths:
        paths.append(path)

    events: List[dict] = []
    for p in paths:
        with open(p) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except ValueError:
                    continue
                if not isinstance(ev, dict):
                    continue
                if latest_run and ev.get("event") == "obs_init":
                    events = []  # a new session starts: drop the earlier one
                events.append(ev)
    return events


def span_phase_summary(path: str) -> Dict[str, Dict[str, float]]:
    """Aggregate an obs event stream into per-phase runtime totals —
    the join key for offline trace summaries
    (``trace_analysis.summarize_trace(..., spans_jsonl=...)``)::

        {name: {"total_s", "calls", "compile_s", "compile_count",
                "trace_count"}}
    """
    out: Dict[str, Dict[str, float]] = {}
    for ev in load_span_events(path):
        if ev.get("event") != "span_end":
            continue
        agg = out.setdefault(ev.get("name", "?"), {
            "total_s": 0.0, "calls": 0, "compile_s": 0.0,
            "compile_count": 0, "trace_count": 0,
        })
        agg["total_s"] += float(ev.get("dur_s", 0.0))
        agg["calls"] += 1
        agg["compile_s"] += float(ev.get("compile_s", 0.0))
        agg["compile_count"] += int(ev.get("compile_count", 0))
        agg["trace_count"] += int(ev.get("trace_count", 0))
    return out

"""Named reductions over per-example attribution rows ``(N, n_units)``.

``mean`` / ``sum`` / ``none`` plus callables mirror the reference
(attributions.py:91-106); ``mean_plus_2std`` is the custom reduction the VGG
notebook passes as a lambda ("SV mean+2std", the best-performing criterion in
BASELINE.md) promoted to a named, distributable reduction: both forms are
computable from the moments (Σx, Σx², N), which is what the distributed
scorer psum-reduces across hosts (SURVEY.md §5.8).
"""

from __future__ import annotations

import numpy as np


def mean_plus_2std(rows: np.ndarray) -> np.ndarray:
    return np.mean(rows, 0) + 2.0 * np.std(rows, 0)


def from_moments(reduction, s1, s2, n):
    """Evaluate a moment-computable reduction from (Σx, Σx², N) per unit."""
    mean = s1 / n
    if reduction == "mean":
        return mean
    if reduction == "sum":
        return s1
    var = np.maximum(s2 / n - mean**2, 0.0)
    if reduction in ("mean+2std", mean_plus_2std):
        return mean + 2.0 * np.sqrt(var)
    raise ValueError(f"reduction {reduction!r} is not moment-computable")

"""Experiment configuration — the single config system the reference lacks
(hyperparameters are hardcoded per model file and a phantom ``args`` object,
SURVEY.md §5.6).  One dataclass, JSON round-trippable, covering the five
named configs in BASELINE.json.
"""

from __future__ import annotations

import dataclasses
import json
from dataclasses import dataclass, field
from typing import Any, Dict, Optional, Tuple


@dataclass
class ExperimentConfig:
    name: str = "experiment"
    model: str = "mnist_fc"          # model-zoo entry point name
    dataset: str = "synthetic"       # data module entry
    n_classes: int = 10
    loss: str = "cross_entropy"      # cross_entropy|lm_cross_entropy|nll|mse
    experiment: str = "prune_retrain"  # see __post_init__ for the set
    #: restrict pruning to targets containing any of these substrings
    #: (e.g. ["_ffn/", "_mlp/"] for FFN-channel-only pruning); empty = all
    target_filter: Tuple[str, ...] = ()

    # attribution
    method: str = "shapley"          # random|weight_norm|apoz|sensitivity|taylor|shapley
    method_kwargs: Dict[str, Any] = field(default_factory=dict)
    reduction: str = "mean"          # mean|sum|none|mean+2std
    find_best_evaluation_layer: bool = True
    #: one-pass sweep capture (robustness experiments): ONE compiled
    #: program computes every eval site's activation per batch and all
    #: methods/runs/ablation walks share it (O(L²)→O(L) prefix work;
    #: attributions.base.ActivationCache).  Disable to A/B the engine or
    #: to trade the cached activations' device memory back for compute.
    capture: bool = True

    # pruning schedule
    policy: str = "negative"         # negative|fraction
    fraction: float = 0.5
    #: per-layer prune-fraction overrides (substring match against the
    #: target name, like target_filter; FIRST match wins in insertion
    #: order).  A matching target prunes by the fraction policy at the
    #: mapped fraction regardless of ``policy``; non-matching targets
    #: keep ``policy``/``fraction``.  The sparsity-search campaign's
    #: per-layer-ratio axis (search/grid.py)
    layer_fractions: Dict[str, float] = field(default_factory=dict)
    bucket: int = 1                  # round kept widths up to a multiple
                                     # (8/128 = TPU sublane/lane alignment;
                                     # bounds recompile diversity)
    prune_order: str = "reverse"     # outermost layer first (reference recipe)
    score_examples: int = 1000       # val examples used for scoring

    # fine-tune / training loop
    finetune_epochs: int = 0
    epochs: int = 0                  # from-scratch training length ("train")
    batch_size: int = 64
    eval_batch_size: int = 250
    lr: float = 0.01
    #: "sgd" (reference recipe, momentum/weight_decay below), "adam", or
    #: "adamw" (decoupled weight_decay)
    optimizer: str = "sgd"
    momentum: float = 0.0
    weight_decay: float = 0.0
    #: constant | multistep | cosine | warmup_cosine.  "multistep" is the
    #: reference's MultiStepLR (cifar10.py:94-99: milestones in epochs,
    #: lr *= gamma at each); cosine variants cover the transformer configs.
    lr_schedule: str = "constant"
    lr_milestones: Tuple[int, ...] = (30, 60, 90, 120, 150)
    lr_gamma: float = 0.5
    lr_warmup_epochs: int = 0

    # distribution
    mesh: Dict[str, int] = field(default_factory=dict)  # e.g. {"data": 4, "model": 2}
    #: parameter partitioning over the mesh: "fsdp" or "tp" (pruning-
    #: graph-derived tensor parallelism); used when mesh is non-empty
    partition: str = "fsdp"
    #: ZeRO-style cross-replica weight-update sharding (composes with
    #: either partition): optimizer state lives sharded over the DATA
    #: axis, gradients reduce-scatter, the update applies to the local
    #: 1/N shard, params all-gather for the next forward — frees
    #: ~(1 - 1/data) of optimizer HBM per chip for larger batches.
    #: Requires a mesh with a "data" axis.  CLI: --zero
    zero: bool = False

    #: float32 | bfloat16 — bf16 runs the fwd/bwd at MXU rate with f32
    #: master params/updates (mixed precision, the TPU-native default for
    #: large models; see train.loop.make_train_step)
    compute_dtype: str = "float32"
    #: float32 | bfloat16 — dtype of the ATTRIBUTION scoring forwards,
    #: independent of the training dtype (bf16 scoring shifts rankings at
    #: bf16 noise level; opt in separately)
    score_dtype: str = "float32"
    #: checkpoint composite blocks during training (recompute-in-backward;
    #: the activation-memory lever for deep transformer stacks)
    remat: bool = False
    #: >1 = gradient accumulation: the batch scans through this many
    #: microbatches inside one jitted step (peak activation memory divides
    #: by the factor; same update as the full batch)
    accum_steps: int = 1
    #: >0 adds that multiple of the MoE load-balancing auxiliary loss
    #: (Switch-style; no-op for models without MoE layers)
    moe_aux_weight: float = 0.0
    #: simulated pruning: the prune loop MASKS the dropped slices (same
    #: policy, same plan) instead of re-instantiating — zero recompiles
    #: across the whole sweep; incompatible with finetune_epochs (chain
    #: core.masking.masked_update into a custom loop for that)
    simulate: bool = False

    # data pipeline / checkpointing
    augment: bool = False            # flip + pad/crop image augmentation
    prefetch: bool = True            # native background batch assembly
    #: batches kept device-resident ahead of the step (async device_put
    #: overlaps host->device transfer with compute); 0 disables
    device_prefetch: int = 2
    checkpoint_path: str = ""        # save/resume training checkpoints here
    checkpoint_every_epochs: int = 0  # 0 = only at the end

    # resilience (torchpruner_tpu.resilience; CLI --resume / --chaos)
    #: resumable-run directory: manifest.json (pipeline position) +
    #: digest-verified ckpt-* checkpoints.  Non-empty = the run is
    #: preemption-safe: SIGTERM/SIGKILL mid-run, then re-run with the
    #: same run_dir (CLI ``--resume DIR``) restarts mid-round
    run_dir: str = ""
    #: mid-epoch checkpoint cadence in OPTIMIZER STEPS (train runs; for
    #: prune_retrain it additionally checkpoints after every retrain
    #: epoch).  0 = round/epoch boundaries only.  CLI --checkpoint-every
    checkpoint_every_steps: int = 0
    #: compile the non-finite step guard into the train step: NaN/Inf
    #: loss-or-grad steps are skipped inside the program (params held),
    #: counted (``resilience_nan_skips_total``), and after
    #: ``max_bad_steps`` consecutive skips the run rolls back to the
    #: last checkpoint with the LR scaled by ``lr_backoff``.  Reading
    #: the guard flag fences each step — off by default
    guard_nonfinite: bool = False
    #: consecutive non-finite steps before rollback (guard_nonfinite)
    max_bad_steps: int = 3
    #: LR multiplier applied at each rollback (0 < lr_backoff <= 1)
    lr_backoff: float = 0.5
    #: rollback-recovery budget per run (NaN streaks; OOM retries have
    #: their own implicit cap at accum_steps == batch_size)
    max_rollbacks: int = 3
    #: deterministic fault injection (resilience.chaos knob dict, e.g.
    #: {"nan_at_step": 5, "kill_at_step": 12}); {} = chaos off.  Also
    #: settable via CLI --chaos / TORCHPRUNER_CHAOS env
    chaos: Dict[str, Any] = field(default_factory=dict)

    #: opt-in runtime telemetry: the train step also computes the global
    #: gradient norm, recorded as an obs gauge (one extra fused reduction
    #: in the compiled step; off by default — see torchpruner_tpu.obs)
    obs_grad_norm: bool = False

    seed: int = 0
    log_path: str = "logs/experiment.csv"
    #: when set, the robustness sweep writes its figures here (per-layer
    #: curves + the AUC summary; utils/plotting)
    plot_dir: str = ""
    #: when set, the robustness sweep dumps its full results (per-layer ×
    #: method curves, scores, AUCs) as JSON here — the durable artifact
    #: the reference keeps as a pickle (VGG notebook cell 8)
    results_path: str = ""

    def __post_init__(self):
        if self.experiment not in (
            "prune_retrain", "robustness", "train", "train_robustness"
        ):
            raise ValueError(
                f"unknown experiment {self.experiment!r} "
                "(use 'prune_retrain', 'robustness', 'train' or "
                "'train_robustness')"
            )
        if self.optimizer not in ("sgd", "adam", "adamw"):
            raise ValueError(
                f"unknown optimizer {self.optimizer!r} "
                "(use 'sgd', 'adam' or 'adamw')"
            )
        # reject silently-ignored combinations up front: momentum is an
        # sgd concept, and plain adam has no decay term (adamw does)
        if self.optimizer != "sgd" and self.momentum:
            raise ValueError(
                f"momentum is only meaningful with optimizer='sgd' "
                f"(got {self.optimizer!r})"
            )
        if self.optimizer == "adam" and self.weight_decay:
            raise ValueError(
                "optimizer='adam' ignores weight_decay — use 'adamw' "
                "for decoupled decay"
            )
        if self.lr_schedule not in (
            "constant", "multistep", "cosine", "warmup_cosine"
        ):
            raise ValueError(
                f"unknown lr_schedule {self.lr_schedule!r} (use 'constant', "
                "'multistep', 'cosine' or 'warmup_cosine')"
            )
        if self.partition not in ("fsdp", "tp"):
            raise ValueError(
                f"unknown partition {self.partition!r} (use 'fsdp' or 'tp')"
            )
        if self.zero and "data" not in (self.mesh or {}):
            raise ValueError(
                "zero=True shards the weight update over the mesh's "
                "'data' axis — set mesh={'data': N, ...} (N > 1) too"
            )
        for k, v in (self.layer_fractions or {}).items():
            if not 0.0 <= float(v) < 1.0:
                raise ValueError(
                    f"layer_fractions[{k!r}] = {v} is outside [0, 1) — "
                    "a fraction of 1 would empty the layer"
                )
        for fld in ("compute_dtype", "score_dtype"):
            if getattr(self, fld) not in ("float32", "bfloat16"):
                raise ValueError(
                    f"unknown {fld} {getattr(self, fld)!r} "
                    "(use 'float32' or 'bfloat16')"
                )
        if not 0.0 < self.lr_backoff <= 1.0:
            raise ValueError(
                f"lr_backoff must be in (0, 1], got {self.lr_backoff}"
            )
        if self.max_bad_steps < 1:
            raise ValueError(
                f"max_bad_steps must be >= 1, got {self.max_bad_steps}"
            )
        if self.checkpoint_every_steps < 0 or self.max_rollbacks < 0:
            raise ValueError(
                "checkpoint_every_steps and max_rollbacks must be >= 0"
            )
        if self.chaos:
            # fail at config time, not at injection time mid-run
            from torchpruner_tpu.resilience.chaos import ChaosConfig

            ChaosConfig.from_any(self.chaos)
        if self.simulate and self.finetune_epochs:
            raise ValueError(
                "simulate=True masks parameters without pinning them in "
                "the optimizer, so fine-tuning would regrow them — chain "
                "core.masking.masked_update into a custom loop instead"
            )

    def to_json(self, path: str):
        with open(path, "w") as f:
            json.dump(dataclasses.asdict(self), f, indent=2)

    @classmethod
    def from_json(cls, path: str) -> "ExperimentConfig":
        with open(path) as f:
            raw = json.load(f)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(raw) - known
        if unknown:
            raise ValueError(f"unknown config keys: {sorted(unknown)}")
        for key in ("target_filter", "lr_milestones"):  # JSON has no tuples
            if key in raw:
                raw[key] = tuple(raw[key])
        return cls(**raw)

"""Utility subpackage: losses, reductions, logging, FLOPs accounting."""

from torchpruner_tpu.utils.losses import mse_loss, cross_entropy_loss, nll_loss
from torchpruner_tpu.utils.reductions import mean_plus_2std
from torchpruner_tpu.utils.compilation_cache import enable_persistent_cache

__all__ = [
    "mse_loss",
    "cross_entropy_loss",
    "nll_loss",
    "mean_plus_2std",
    "enable_persistent_cache",
]

"""Figure helpers for the robustness experiments.

The reference ships plot machinery for its paper figures — a method →
(label, color) mapping, per-layer robustness curves, and the AUC summary
(reference experiments/utils/utils.py:77-113, VGG notebook cells 10-11).
This is the same deliverable for the TPU framework, driven by
:func:`~torchpruner_tpu.experiments.robustness.layerwise_robustness`
results.

Design rules applied (colorblind-validated 8-slot categorical palette,
fixed hue order so a method keeps its color across figures, one axis per
chart, recessive grid, 2px lines, labels in neutral ink):

matplotlib is an optional dependency (present in the reference's setup.py
install_requires); every entry point raises a clear ImportError when it is
missing and never imports it at module load.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

#: fixed method -> (display label, color) assignment; the order is the
#: palette's canonical hue order and NEVER re-flows when a subset of
#: methods is plotted (color follows the method, not its rank).
METHOD_STYLE: Dict[str, tuple] = {
    "sv": ("Shapley value", "#2a78d6"),
    "sv_mean+2std": ("Shapley value (mean+2std)", "#eb6834"),
    "taylor": ("Taylor", "#1baf7a"),
    "sensitivity": ("Sensitivity", "#eda100"),
    "weight_norm": ("Weight norm", "#e87ba4"),
    "random": ("Random", "#008300"),
    "apoz": ("APoZ", "#4a3aa7"),
    "taylor_signed": ("Taylor (signed)", "#e34948"),
}
_TEXT = "#52514e"
_GRID = "#e6e5e1"


def method_style(name: str) -> tuple:
    """(label, color) for a method name; unknown methods get a neutral."""
    return METHOD_STYLE.get(name, (name, "#6b6a66"))


def _plt():
    try:
        import matplotlib

        matplotlib.use("Agg")
        import matplotlib.pyplot as plt

        return plt
    except ImportError as e:  # pragma: no cover - matplotlib is installed
        raise ImportError(
            "plotting needs matplotlib (pip install matplotlib)"
        ) from e


def _style_axis(ax):
    ax.grid(True, color=_GRID, linewidth=0.6, axis="y")
    ax.set_axisbelow(True)
    for side in ("top", "right"):
        ax.spines[side].set_visible(False)
    for side in ("left", "bottom"):
        ax.spines[side].set_color(_GRID)
    ax.tick_params(colors=_TEXT, labelsize=8)


def plot_robustness_curves(
    results,
    layer: str,
    *,
    metric: str = "loss",
    save_path: Optional[str] = None,
):
    """One layer's robustness curves: test loss (or accuracy) as units are
    removed in ascending-score order, one line per method — the per-layer
    panel of the reference's figure (VGG notebook cell 10).  Stochastic
    methods show the mean across runs with a shaded min-max band."""
    plt = _plt()
    fig, ax = plt.subplots(figsize=(5.4, 3.4), dpi=150)
    for name, runs in results[layer].items():
        label, color = method_style(name)
        curves = np.stack([np.asarray(r[metric]) for r in runs])
        xs = np.arange(1, curves.shape[1] + 1)
        ax.plot(xs, curves.mean(0), color=color, linewidth=2, label=label)
        if len(runs) > 1:
            ax.fill_between(
                xs, curves.min(0), curves.max(0), color=color, alpha=0.15,
                linewidth=0,
            )
    base = next(iter(results[layer].values()))[0][f"base_{metric}"]
    ax.axhline(base, color=_GRID, linewidth=1, linestyle="--")
    ax.set_xlabel("units removed (ascending score)", color=_TEXT, fontsize=9)
    ax.set_ylabel(f"test {metric}", color=_TEXT, fontsize=9)
    ax.set_title(layer, color="#0b0b0b", fontsize=10)
    _style_axis(ax)
    ax.legend(fontsize=7, frameon=False, labelcolor=_TEXT)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path)
        plt.close(fig)  # saved figures don't accumulate in the manager
    return fig


def plot_auc_summary(
    aucs: Dict[str, float],
    *,
    reference: Optional[Dict[str, float]] = None,
    save_path: Optional[str] = None,
):
    """The loss-increase-AUC comparison (reference notebook cell 11):
    horizontal bars, best (lowest) method on top, each bar in its method's
    fixed color with the value direct-labeled.  ``reference`` optionally
    overlays the reference's numbers as markers for a parity figure."""
    plt = _plt()
    order = sorted(aucs, key=aucs.get)
    fig, ax = plt.subplots(figsize=(5.4, 0.42 * len(order) + 1.2), dpi=150)
    ys = np.arange(len(order))[::-1]
    vals = [aucs[m] for m in order]
    colors = [method_style(m)[1] for m in order]
    ax.barh(ys, vals, height=0.62, color=colors)
    span = max(vals) - min(min(vals), 0) or 1.0
    for y, v in zip(ys, vals):
        ax.text(v + 0.02 * span, y, f"{v:.3f}", va="center",
                fontsize=7, color=_TEXT)
    if reference:
        for y, m in zip(ys, order):
            if m in reference:
                ax.plot(reference[m], y, marker="D", markersize=5,
                        color="#0b0b0b", linestyle="none")
        ax.plot([], [], marker="D", markersize=5, color="#0b0b0b",
                linestyle="none", label="reference")
        ax.legend(fontsize=7, frameon=False, labelcolor=_TEXT)
    ax.set_yticks(ys)
    ax.set_yticklabels([method_style(m)[0] for m in order], fontsize=8,
                       color=_TEXT)
    ax.set_xlabel("avg. test-loss increase per unit removed (lower = "
                  "better ranking)", color=_TEXT, fontsize=8)
    _style_axis(ax)
    ax.grid(False, axis="y")
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path)
        plt.close(fig)  # saved figures don't accumulate in the manager
    return fig


def plot_prune_history(
    records: Sequence,
    *,
    save_path: Optional[str] = None,
):
    """Accuracy and parameter count across the prune-retrain loop
    (:class:`~torchpruner_tpu.experiments.prune_retrain.PruneStepRecord`
    list) — two stacked single-axis panels, never a dual-axis chart."""
    plt = _plt()
    fig, (ax1, ax2) = plt.subplots(
        2, 1, figsize=(5.4, 4.2), dpi=150, sharex=True
    )
    xs = np.arange(len(records))
    pre = [r.pre_acc for r in records]
    post = [r.post_acc for r in records]
    ax1.plot(xs, pre, color="#2a78d6", linewidth=2, label="before prune")
    ax1.plot(xs, post, color="#eb6834", linewidth=2, label="after prune")
    ax1.set_ylabel("test accuracy", color=_TEXT, fontsize=9)
    ax1.legend(fontsize=7, frameon=False, labelcolor=_TEXT)
    _style_axis(ax1)
    ax2.plot(xs, [r.n_params for r in records], color="#1baf7a", linewidth=2)
    ax2.set_ylabel("parameters", color=_TEXT, fontsize=9)
    ax2.set_xlabel("prune step", color=_TEXT, fontsize=9)
    ax2.set_xticks(xs)
    ax2.set_xticklabels([r.layer for r in records], rotation=30,
                        ha="right", fontsize=7)
    _style_axis(ax2)
    fig.tight_layout()
    if save_path:
        fig.savefig(save_path)
        plt.close(fig)  # saved figures don't accumulate in the manager
    return fig

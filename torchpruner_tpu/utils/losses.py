"""Per-example loss functions.

The attribution contract is per-example-first (SURVEY.md §2.1): every loss
here maps ``(preds, targets) -> (batch,)`` — the equivalent of calling a torch
criterion with ``reduction="none"`` (reference attributions.py:40-56).  Mean
over the batch gives the training loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def _f32(x):
    """Loss math runs in f32 regardless of the activation dtype (bf16
    logits from a mixed-precision forward would otherwise round the
    softmax/log and the small marginal deltas attribution relies on)."""
    return x.astype(jnp.float32) if jnp.issubdtype(
        jnp.result_type(x), jnp.floating) else x


def mse_loss(preds, targets):
    """Mean-squared error, averaged over non-batch dims -> (batch,)."""
    d = (_f32(preds) - _f32(targets)) ** 2
    return d.reshape(d.shape[0], -1).mean(axis=1)


def cross_entropy_loss(logits, labels):
    """Softmax cross-entropy with integer labels -> (batch,)."""
    logp = jax.nn.log_softmax(_f32(logits), axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]


def nll_loss(log_probs, labels):
    """Negative log-likelihood on log-probabilities (reference
    experiments/models/fmnist.py:80-81 pairs NLL with an in-model
    log_softmax) -> (batch,)."""
    return -jnp.take_along_axis(
        _f32(log_probs), labels[:, None], axis=-1)[:, 0]


def lm_cross_entropy_loss(logits, tokens):
    """Next-token cross-entropy for causal LMs -> (batch,).

    ``logits``: (B, S, V); ``tokens``: (B, S) int.  Position ``t`` predicts
    token ``t+1``; the last position has no target and is dropped.  The
    per-example value is the mean over the S-1 predicted positions, keeping
    the per-example-first attribution contract (SURVEY.md §2.1).
    """
    logp = jax.nn.log_softmax(_f32(logits[:, :-1]), axis=-1)
    tgt = tokens[:, 1:]
    nll = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]
    return nll.mean(axis=-1)


def accuracy(logits, labels):
    """Fraction of argmax-correct predictions (scalar)."""
    return jnp.mean(jnp.argmax(logits, axis=-1) == labels)


def prediction_counts(out, y):
    """``(n_correct, n_predictions)`` for accuracy accounting, shared by all
    eval paths.

    Classification (``out`` (B, C), ``y`` (B,)): argmax over classes, B
    predictions.  Language modeling (``out`` (B, S, V), ``y`` (B, S) int):
    next-token aligned — position t predicts token t+1, B*(S-1)
    predictions — matching :func:`lm_cross_entropy_loss`.
    ``n_predictions`` is a static Python int.
    """
    if out.ndim == y.ndim + 1 and y.ndim >= 2:
        pred = jnp.argmax(out[:, :-1], axis=-1)
        tgt = y[:, 1:]
        return jnp.sum(pred == tgt), pred.size
    return jnp.sum(jnp.argmax(out, axis=-1) == y), y.shape[0]

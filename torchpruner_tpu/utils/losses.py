"""Per-example loss functions.

The attribution contract is per-example-first (SURVEY.md §2.1): every loss
here maps ``(preds, targets) -> (batch,)`` — the equivalent of calling a torch
criterion with ``reduction="none"`` (reference attributions.py:40-56).  Mean
over the batch gives the training loss.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def mse_loss(preds, targets):
    """Mean-squared error, averaged over non-batch dims -> (batch,)."""
    d = (preds - targets) ** 2
    return d.reshape(d.shape[0], -1).mean(axis=1)


def cross_entropy_loss(logits, labels):
    """Softmax cross-entropy with integer labels -> (batch,)."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=-1)[:, 0]


def nll_loss(log_probs, labels):
    """Negative log-likelihood on log-probabilities (reference
    experiments/models/fmnist.py:80-81 pairs NLL with an in-model
    log_softmax) -> (batch,)."""
    return -jnp.take_along_axis(log_probs, labels[:, None], axis=-1)[:, 0]


def accuracy(logits, labels):
    """Fraction of argmax-correct predictions (scalar)."""
    return jnp.mean(jnp.argmax(logits, axis=-1) == labels)

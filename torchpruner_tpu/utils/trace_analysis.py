"""Offline summarizer for ``jax.profiler`` traces.

``jax.profiler.start_trace`` writes a Chrome-trace JSON
(``plugins/profile/<run>/<host>.trace.json.gz``) whose complete events
('ph' == 'X') carry per-op device timings.  This module turns that into
the table PERF.md needs — top ops by total self time, grouped into
categories (convolution / matmul / fusion / copy / collective / infeed)
— without TensorBoard or XProf in the loop.

Python-frame events (names starting with ``$``) and PjRt runtime
plumbing are excluded; when the trace contains device tracks (TPU runs:
process names like ``/device:TPU:0``), only those are counted, so host
overhead doesn't dilute the device breakdown.

CLI: ``python -m torchpruner_tpu.utils.trace_analysis LOG_DIR [--top N]``
(pass the directory given to ``profiling.trace`` / the CLI's
``--profile``).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
from typing import Dict, List, Optional

#: runtime-internal event names that are not XLA ops
_RUNTIME_NOISE = (
    "PjRt", "PjitFunction", "Handle inputs", "ParseArguments",
    "CommonPjRtBuffer", "copy_to_host", "TransferFromDevice", "Await",
    "thread_", "process_", "ThunkExecutor", "ThreadpoolListener",
    "TfrtCpu", "ExecuteHelper", "BufferFromHostBuffer",
)

#: (category, name-prefix) in match order
_CATEGORIES = (
    ("convolution", ("convolution", "wrapped_conv", "conv_general")),
    ("matmul", ("dot_general", "dot", "wrapped_dot")),
    ("collective", ("all-reduce", "all-gather", "all-to-all",
                    "collective", "reduce-scatter", "ppermute",
                    "psum", "fusion.all")),
    ("copy/layout", ("copy", "transpose", "bitcast", "reshape",
                     "wrapped_transpose")),
    ("infeed/outfeed", ("infeed", "outfeed")),
    ("reduce", ("reduce", "wrapped_reduce")),
    ("fusion/elementwise", ("fusion", "wrapped_", "loop_", "select",
                            "broadcast", "compare", "add", "multiply")),
)


def categorize(name: str) -> str:
    low = name.lower()
    for cat, prefixes in _CATEGORIES:
        if any(low.startswith(p) for p in prefixes):
            return cat
    return "other"


def find_trace_files(log_dir: str, latest_run: bool = True) -> List[str]:
    """Trace files under ``log_dir``.  ``jax.profiler`` writes one
    timestamped ``plugins/profile/<run>/`` per session; with
    ``latest_run`` (default) only the newest run is returned, so re-using
    a trace directory doesn't double-count earlier sessions."""
    run_dirs = sorted(glob.glob(
        os.path.join(log_dir, "plugins", "profile", "*")
    ))
    if latest_run and run_dirs:
        return sorted(glob.glob(
            os.path.join(run_dirs[-1], "**", "*.trace.json.gz"),
            recursive=True,
        ))
    return sorted(glob.glob(
        os.path.join(log_dir, "**", "*.trace.json.gz"), recursive=True
    ))


def file_op_events(path: str) -> List[dict]:
    """The FILTERED per-op complete events of one ``*.trace.json.gz``:
    ``[{"name", "ts", "dur", "pid", "tid"}, ...]`` (µs), with runtime
    noise, Python frames, and non-op tracks excluded — ONE filtering
    rule shared by :func:`summarize_trace` and the Perfetto merge
    (``obs.trace_export``).

    A device process carries several stacked tracks: "Steps" (one span
    per step number), "XLA Modules" (one span per program execution,
    duplicating its ops' time), and "XLA Ops" (the per-op events this
    is about).  Counting all three triple-counts; restrict to the op
    tracks when they exist.  Host-only traces (CPU backend) have no
    device tracks — there the XLA thunk events ARE the op events, but
    they live on the runtime's executor threads (``tf_XLAEigen`` /
    ``tf_XLATfrtCpuClient``); the ``python`` thread carries tracing /
    lowering / span-annotation events that are NOT ops (a capture
    window spanning a recompile would otherwise report
    ``trace_to_jaxpr`` as the hottest "kernel"), so when thread names
    are present, host-only filtering keeps only the ``tf_*`` threads.
    """
    with gzip.open(path, "rt") as f:
        data = json.load(f)
    events = data.get("traceEvents", [])
    proc_names = {
        e["pid"]: (e.get("args") or {}).get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "process_name"
        and "pid" in e
    }
    device_pids = {
        pid for pid, name in proc_names.items()
        if "device:" in name.lower() or "tpu" in name.lower()
    }
    thread_names = {
        (e["pid"], e["tid"]): (e.get("args") or {}).get("name", "")
        for e in events
        if e.get("ph") == "M" and e.get("name") == "thread_name"
        and "pid" in e and "tid" in e
    }
    op_tids = {
        key for key, name in thread_names.items()
        if key[0] in device_pids and name in ("XLA Ops", "Async XLA Ops")
    }
    host_exec_tids = {
        key for key, name in thread_names.items()
        if name.startswith("tf_")
    }
    out: List[dict] = []
    for e in events:
        if e.get("ph") != "X" or "dur" not in e:
            continue
        key = (e.get("pid"), e.get("tid"))
        if op_tids:
            if key not in op_tids:
                continue
        elif device_pids:
            if e.get("pid") not in device_pids:
                continue
        elif host_exec_tids and key not in host_exec_tids:
            continue
        name = e.get("name") or ""
        if not name:  # nameless events can't be categorized — skip
            continue
        # '$...' = Python frames; 'end: <op>' = nested completion
        # markers on host-only traces (counting them double-counts
        # the enclosing op)
        if name.startswith(("$", "end: ")) or any(
            tok in name for tok in _RUNTIME_NOISE
        ):
            continue
        out.append({"name": name, "ts": float(e.get("ts", 0.0)),
                    "dur": float(e["dur"]), "pid": e.get("pid", 0),
                    "tid": e.get("tid", 0)})
    return out


def summarize_trace(log_dir: str, top: int = 25,
                    latest_run: bool = True,
                    spans_jsonl: Optional[str] = None) -> Dict:
    """Aggregate the ``*.trace.json.gz`` of ``log_dir``'s newest profiler
    run (all runs with ``latest_run=False``).

    Returns ``{"total_ms", "by_category": {cat: ms}, "top_ops":
    [{"name", "ms", "pct", "category", "count"}, ...], "files"}``.

    ``spans_jsonl`` joins an obs runtime event stream (the CLI's
    ``--obs-dir``/``events.jsonl``) into the summary as a ``"phases"``
    block — per-phase wall seconds and compile accounting next to the
    device op table, so "where did the run spend its time" and "what ops
    dominated" come from ONE artifact pair.  Malformed trace events
    (missing pid/tid/name — seen on partial host-only captures) are
    skipped, not KeyError'd.
    """
    files = find_trace_files(log_dir, latest_run=latest_run)
    if not files:
        raise FileNotFoundError(
            f"no *.trace.json.gz under {log_dir!r} — pass the directory "
            f"given to profiling.trace()/--profile"
        )
    durs: Dict[str, float] = {}
    counts: Dict[str, int] = {}
    for path in files:
        for e in file_op_events(path):
            name = e["name"]
            durs[name] = durs.get(name, 0.0) + e["dur"]  # microseconds
            counts[name] = counts.get(name, 0) + 1
    total_us = sum(durs.values()) or 1.0
    by_cat: Dict[str, float] = {}
    for name, us in durs.items():
        cat = categorize(name)
        by_cat[cat] = by_cat.get(cat, 0.0) + us
    top_ops = [
        {
            "name": name,
            "ms": round(us / 1e3, 3),
            "pct": round(100.0 * us / total_us, 1),
            "category": categorize(name),
            "count": counts[name],
        }
        for name, us in sorted(durs.items(), key=lambda kv: -kv[1])[:top]
    ]
    out = {
        "total_ms": round(total_us / 1e3, 3),
        "by_category": {
            k: round(v / 1e3, 3)
            for k, v in sorted(by_cat.items(), key=lambda kv: -kv[1])
        },
        "top_ops": top_ops,
        "files": files,
    }
    if spans_jsonl:
        from torchpruner_tpu.utils.profiling import span_phase_summary

        out["phases"] = {
            k: {"total_s": round(v["total_s"], 3), "calls": v["calls"],
                "compile_s": round(v["compile_s"], 3),
                "compile_count": v["compile_count"]}
            for k, v in sorted(span_phase_summary(spans_jsonl).items(),
                               key=lambda kv: -kv[1]["total_s"])
        }
    return out


def markdown_summary(summary: Dict, top: Optional[int] = None) -> str:
    lines = [
        f"Total op time: {summary.get('total_ms', 0.0):.1f} ms",
        "",
        "| category | ms | % |",
        "|---|---|---|",
    ]
    total = summary.get("total_ms") or 1.0
    for cat, ms in summary.get("by_category", {}).items():
        lines.append(f"| {cat} | {ms:.1f} | {100 * ms / total:.1f} |")
    lines += ["", "| op | category | ms | % | calls |", "|---|---|---|---|---|"]
    for op in summary.get("top_ops", [])[: top or None]:
        lines.append(
            f"| `{op.get('name', '?')}` | {op.get('category', 'other')} "
            f"| {op.get('ms', 0)} | {op.get('pct', 0)} "
            f"| {op.get('count', 0)} |"
        )
    if summary.get("phases"):
        lines += ["", "| phase (runtime spans) | wall s | calls | "
                      "compile s | compiles |", "|---|---|---|---|---|"]
        for name, v in summary["phases"].items():
            lines.append(
                f"| {name} | {v['total_s']} | {v['calls']} "
                f"| {v['compile_s']} | {v['compile_count']} |"
            )
    return "\n".join(lines)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("log_dir")
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--json", action="store_true")
    ap.add_argument(
        "--spans", metavar="EVENTS_JSONL",
        help="obs runtime event stream (--obs-dir's events.jsonl) to join "
             "as a per-phase timing table",
    )
    args = ap.parse_args(argv)
    summary = summarize_trace(args.log_dir, top=args.top,
                              spans_jsonl=args.spans)
    if args.json:
        print(json.dumps(summary, indent=1))
    else:
        print(markdown_summary(summary))


if __name__ == "__main__":
    main()

"""Persistent XLA compilation cache.

Pruning changes static shapes, so every prune step retraces and recompiles
its train step and scorers (SURVEY.md §7 "recompilation economics") — on
small workloads compilation dominates wall-clock (the untrained-MNIST prune
spends most of its 15 s in two Shapley-scan compiles).  A persistent on-disk
cache makes every *repeated* shape free: re-running an experiment, resuming
after preemption, or sweeping a config grid that revisits widths all hit the
cache instead of XLA.

The reference has no analog (eager PyTorch never compiles); this is the
TPU-native cost being paid down the TPU-native way — ``jax``'s built-in
persistent cache pointed at a stable location.

Opt-in per entry point (the bench, the CLI, ``train_model``) rather than at
import, so library users keep full control of their jax config.
"""

from __future__ import annotations

import os

import jax

#: environment override for the cache location (shared across runs/users)
ENV_VAR = "TORCHPRUNER_TPU_COMPILATION_CACHE"

_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "torchpruner_tpu", "xla"
)


def _install_atomic_cache_writes() -> None:
    """Make jax's persistent-cache entry writes crash-safe.

    jax 0.4.x's ``LRUCache.put`` is a bare ``Path.write_bytes`` — a
    process killed mid-write (exactly what preemption does) leaves a
    TORN cache entry, and the next run deserializes it into a garbage
    XLA executable: observed as glibc heap corruption aborts and as
    silently-diverging (NaN) train steps on resume.  Found by the
    resilience chaos drill's ``kill_at_step`` injection (bench.py
    resilience leg / tests).  The patch rewrites ``put`` to the standard
    tmp + fsync + ``os.replace`` in the same directory, preserving the
    existing skip-if-present and eviction behavior.  Best-effort: if the
    internals moved in a newer jax, the patch silently stands down (the
    newer versions write atomically themselves).
    """
    try:
        from jax._src import lru_cache as _lru

        if getattr(_lru.LRUCache.put, "_tpt_atomic", False):
            return
        suffix = _lru._CACHE_SUFFIX
        atime_suffix = _lru._ATIME_SUFFIX
        orig_evict = _lru.LRUCache._evict_if_needed

        swept = [False]

        def put(self, key: str, val: bytes) -> None:
            if not key:
                raise ValueError("key cannot be empty")
            if self.eviction_enabled and len(val) > self.max_size:
                return
            cache_path = self.path / f"{key}{suffix}"
            atime_path = self.path / f"{key}{atime_suffix}"
            if self.eviction_enabled:
                self.lock.acquire(timeout=self.lock_timeout_secs)
            try:
                if cache_path.exists():
                    return
                if not swept[0]:
                    # once per process: stale tmps from earlier killed
                    # writers (nothing else ever cleans them)
                    swept[0] = True
                    for stale in self.path.glob(".cctmp.*"):
                        try:
                            stale.unlink()
                        except OSError:
                            pass
                orig_evict(self, additional_size=len(val))
                # tmp name must NOT end with the cache suffix: jax's
                # eviction pass globs f"*{suffix}" and reads each
                # match's sibling -atime file, so a suffix-matching tmp
                # (from a kill mid-write, or a concurrent put) would
                # make every later eviction raise FileNotFoundError
                tmp = self.path / f".cctmp.{os.getpid()}.{key}"
                with open(tmp, "wb") as f:
                    f.write(val)
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, cache_path)
                import time as _time

                atime_path.write_bytes(
                    _time.time_ns().to_bytes(8, "little"))
            finally:
                if self.eviction_enabled:
                    self.lock.release()

        put._tpt_atomic = True
        _lru.LRUCache.put = put
    except Exception:  # noqa: BLE001 - hardening, never fatal
        pass


def quarantine_for_resume() -> bool:
    """Disable the persistent cache for THIS process when resuming on
    the CPU backend.  Returns True when it disabled anything.

    Empirical finding from the resilience chaos drill (kill→resume
    cycles on the digits preset, jax/jaxlib 0.4.37): a resumed process
    that restores a checkpoint and then loads executables from the
    persistent cache corrupts its heap ~60% of the time — glibc aborts
    ("corrupted double-linked list"), segfaults inside subsequent jit
    TRACING, or silently-NaN train steps.  With the cache disabled the
    same cycles are 10/10 clean and bit-identical to uninterrupted
    runs; uninterrupted warm-cache runs are also clean — only the
    resume+deserialize combination is unstable, pointing at the CPU
    ``deserialize_executable`` path upstream.  Correctness beats a few
    seconds of recompilation, so resumable pipelines call this before
    their first compile.  TPU backends keep the cache (the instability
    is CPU-specific and resume-after-preemption is the cache's headline
    use case there)."""
    try:
        if jax.default_backend() != "cpu":
            return False
        if jax.config.jax_compilation_cache_dir is None:
            return False
        jax.config.update("jax_compilation_cache_dir", None)
        return True
    except Exception:  # noqa: BLE001 - never fatal
        return False


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point jax's persistent compilation cache at ``path`` (default:
    ``$TORCHPRUNER_TPU_COMPILATION_CACHE`` or ``~/.cache/torchpruner_tpu/xla``).

    Returns the cache directory, or None if it could not be created (the
    cache is an optimization — failure to enable it must never break a
    run).  Thresholds are lowered so even sub-second compiles are cached:
    the prune loop's many small recompiles are exactly the target.
    Entry writes are patched atomic (tmp + fsync + replace) so a
    preemption SIGKILL mid-write cannot poison later runs — see
    :func:`_install_atomic_cache_writes`.
    """
    path = path or os.environ.get(ENV_VAR) or _DEFAULT
    try:
        os.makedirs(path, exist_ok=True)
        # thresholds first, the cache dir LAST: if a threshold option is
        # missing on this jax version, the failure must leave the cache
        # disabled (returning None while the cache is active would let
        # benchmark compile timings silently measure cache hits)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 - optional optimization, never fatal
        return None
    _install_atomic_cache_writes()
    return path

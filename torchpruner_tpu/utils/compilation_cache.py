"""Persistent XLA compilation cache.

Pruning changes static shapes, so every prune step retraces and recompiles
its train step and scorers (SURVEY.md §7 "recompilation economics") — on
small workloads compilation dominates wall-clock (the untrained-MNIST prune
spends most of its 15 s in two Shapley-scan compiles).  A persistent on-disk
cache makes every *repeated* shape free: re-running an experiment, resuming
after preemption, or sweeping a config grid that revisits widths all hit the
cache instead of XLA.

The reference has no analog (eager PyTorch never compiles); this is the
TPU-native cost being paid down the TPU-native way — ``jax``'s built-in
persistent cache pointed at a stable location.

Opt-in per entry point (the bench, the CLI, ``train_model``) rather than at
import, so library users keep full control of their jax config.
"""

from __future__ import annotations

import os

import jax

#: environment override for the cache location (shared across runs/users)
ENV_VAR = "TORCHPRUNER_TPU_COMPILATION_CACHE"

_DEFAULT = os.path.join(
    os.path.expanduser("~"), ".cache", "torchpruner_tpu", "xla"
)


def enable_persistent_cache(path: str | None = None) -> str | None:
    """Point jax's persistent compilation cache at ``path`` (default:
    ``$TORCHPRUNER_TPU_COMPILATION_CACHE`` or ``~/.cache/torchpruner_tpu/xla``).

    Returns the cache directory, or None if it could not be created (the
    cache is an optimization — failure to enable it must never break a
    run).  Thresholds are lowered so even sub-second compiles are cached:
    the prune loop's many small recompiles are exactly the target.
    """
    path = path or os.environ.get(ENV_VAR) or _DEFAULT
    try:
        os.makedirs(path, exist_ok=True)
        # thresholds first, the cache dir LAST: if a threshold option is
        # missing on this jax version, the failure must leave the cache
        # disabled (returning None while the cache is active would let
        # benchmark compile timings silently measure cache hits)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.1)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)
        jax.config.update("jax_compilation_cache_dir", path)
    except Exception:  # noqa: BLE001 - optional optimization, never fatal
        return None
    return path

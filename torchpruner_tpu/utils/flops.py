"""Parameter and FLOPs accounting via XLA cost analysis.

Replaces the reference's ``thop.profile`` on a 2-sample random input
(reference experiments/utils/utils.py:30-36) with the compiler's own cost
model: ``jit(...).lower(...).compile().cost_analysis()`` — exact for the
compiled graph, no tracing heuristics.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core.segment import SegmentedModel


def param_count(params) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))


def model_cost(
    model: SegmentedModel, params, state=None, batch_size: int = 2
) -> Tuple[int, Optional[float]]:
    """Returns ``(n_params, forward_flops)`` for a ``batch_size`` forward
    (the reference uses batch 2 because of BatchNorm, utils.py:33-34; here
    eval-mode BN has no batch constraint but we keep the convention)."""
    state = state if state is not None else {}
    x = model.example_input(batch_size)

    def fwd(p, s, x):
        return model.apply(p, x, state=s, train=False)[0]

    flops = None
    try:
        # the ONE cost_analysis() normalizer (the return type changed
        # shape across jax releases) — every consumer routes through it
        from torchpruner_tpu.analysis.cost_model import cost_analysis_dict

        compiled = jax.jit(fwd).lower(params, state, x).compile()
        ca = cost_analysis_dict(compiled)
        if ca:
            flops = float(ca.get("flops", 0.0)) or None
    except Exception:  # cost analysis is best-effort on some backends
        flops = None
    return param_count(params), flops


def prefix_flops_estimate(
    model: SegmentedModel, params, eval_layer: str, batch_size: int = 1
) -> float:
    """Analytic forward-FLOPs estimate of the prefix input → ``eval_layer``
    (inclusive, top-level boundary), for the capture engine's
    ``prefix_flops_saved`` accounting (attributions.base.ActivationCache).

    Matmul-dominated estimate: every ≥2-D float weight applied at each of
    its layer's output positions costs ``2 · positions · weight_size``
    MACs-as-FLOPs (exact for Dense/Conv/attention projections; attention's
    S² score term and elementwise ops are ignored — this is a savings
    gauge, not a cost model, so it errs low).  Embedding lookups are
    gathers, not matmuls, and count zero.
    """
    from torchpruner_tpu.core import layers as L

    stop = model.index(model.top_level_of(eval_layer))
    total = 0.0

    def weight_sizes(spec, p):
        if isinstance(spec, L.Embedding) or not isinstance(p, dict):
            return 0.0
        n = 0.0
        for v in p.values():
            if isinstance(v, dict):  # composite child
                n += weight_sizes(spec, v)
            elif hasattr(v, "ndim") and getattr(v, "ndim", 0) >= 2:
                n += float(np.prod(v.shape))
        return n

    for i, (spec, (_, out_shape)) in enumerate(
        zip(model.layers, model.shapes)
    ):
        if i > stop:
            break
        p = params.get(spec.name)
        if p is None:
            continue
        positions = float(np.prod(out_shape[:-1])) if len(out_shape) > 1 \
            else 1.0
        total += 2.0 * batch_size * positions * weight_sizes(spec, p)
    return total


#: bf16 peak FLOP/s per chip by ``device_kind`` prefix (public spec
#: sheets) — longest prefix wins.  Shared by bench.py's MFU legs and the
#: step-trace device-MFU computation so the denominators agree.
PEAK_BF16_FLOPS = {
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "TPU7": 2307e12,
}


#: HBM bandwidth per chip by ``device_kind`` prefix (public spec sheets,
#: bytes/s) — the roofline's memory ceiling, paired with PEAK_BF16_FLOPS
#: so the ridge intensity (peak FLOP/s ÷ peak bytes/s) uses one source.
PEAK_HBM_BYTES_PER_S = {
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5p": 2765e9,
    "TPU v5": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
    "TPU7": 7370e9,
}


#: Per-axis one-way ICI bandwidth per chip (bytes/s, approximate public
#: aggregates divided across torus directions) — the communication
#: ceiling of the static cost model (analysis/cost_model.py).  These are
#: lint-grade constants: good enough to rank comm-bound vs compute-bound
#: and to predict step time within the <30% on-chip target the capture
#: script asserts, not a substitute for a measured profile.
PEAK_ICI_BYTES_PER_S = {
    "TPU v3": 70e9,
    "TPU v4": 100e9,
    "TPU v5 lite": 66e9,
    "TPU v5e": 66e9,
    "TPU v5p": 200e9,
    "TPU v5": 200e9,
    "TPU v6 lite": 150e9,
    "TPU v6e": 150e9,
    "TPU7": 400e9,
}


#: Order-of-magnitude host constants the cost model falls back to on the
#: CPU backend, so smoke runs produce DETERMINISTIC (if rough)
#: predictions the golden predicted-vs-measured tests can pin.  Each is
#: env-overridable (TORCHPRUNER_COST_CPU_FLOPS / _BW / _ICI); on-chip
#: predictions never consult these.
CPU_COST_DEFAULTS = {"flops": 5e10, "hbm": 2e10, "ici": 1e10}

#: Deterministic stand-in HBM capacity for hosts whose device kind has
#: no spec-sheet entry (the CPU backend) — big enough that smoke
#: configs are never spuriously infeasible, small enough that a planted
#: budget (TORCHPRUNER_PLAN_HBM_BYTES) can undercut it in tests.
CPU_HBM_CAPACITY_BYTES = 8 * 2 ** 30

#: env override for the per-chip HBM capacity the planner budgets
#: against — the planted-infeasible CI drill shrinks it to prove the
#: planner excludes over-budget candidates loudly.
PLAN_HBM_ENV = "TORCHPRUNER_PLAN_HBM_BYTES"


def hbm_capacity(device=None) -> float:
    """Per-chip HBM capacity in bytes for ``device`` (a Device, a
    device-kind string, or None for this host's first device) — the
    denominator of the planner's feasibility gate.  Spec-sheet table
    (``parallel.memory.HBM_BYTES``) by device-kind prefix; unknown kinds
    (the CPU backend) fall back to :data:`CPU_HBM_CAPACITY_BYTES`.
    ``TORCHPRUNER_PLAN_HBM_BYTES`` overrides everything (calibrated
    hosts, and the CI planted-infeasible drill)."""
    import os

    env = os.environ.get(PLAN_HBM_ENV)
    if env:
        return float(env)
    from torchpruner_tpu.parallel.memory import HBM_BYTES

    if device is None:
        device = jax.devices()[0]
    cap = _by_kind_prefix(HBM_BYTES, device)
    return float(cap) if cap is not None else float(CPU_HBM_CAPACITY_BYTES)


def predicted_hbm_bytes_per_chip(
    model,
    mesh_axes: dict,
    *,
    partition: str = "fsdp",
    zero: bool = False,
    tx=None,
    batch_per_chip: int = 1,
    compute_dtype=None,
    remat: bool = False,
    params=None,
    min_shard_size: int = 2 ** 14,
) -> int:
    """Predicted per-chip HBM watermark (bytes) for training ``model``
    at a placement — params + grads + optimizer slots (at their ZeRO
    placement when ``zero``) + the coarse activation estimate, all from
    ``parallel.memory.training_memory`` over an ``AbstractMesh`` (pure
    shape math, no devices, no materialized parameter).

    This is the static HBM twin of the cost model's predicted step
    time: it lands as the ``predicted_hbm_bytes_per_chip`` gauge next
    to ``predicted_step_ms`` in every run's report.json, and it is the
    number the planner's feasibility gate compares against
    :func:`hbm_capacity`.  ``mesh_axes`` may be empty (single-device
    placement: everything replicated-on-one-chip)."""
    from torchpruner_tpu.analysis.sharding_lint import abstract_mesh
    from torchpruner_tpu.parallel.memory import training_memory
    from torchpruner_tpu.parallel.sharding import fsdp_sharding, tp_sharding

    axes = dict(mesh_axes or {"data": 1})
    if "data" not in axes:
        axes["data"] = 1
    mesh = abstract_mesh(axes)
    if params is None:
        from torchpruner_tpu.analysis.plan_lint import abstract_trees

        params, _ = abstract_trees(model)
    if partition == "tp" and "model" in axes:
        sh = tp_sharding(model, params, mesh, min_size=min_shard_size)
    else:
        sh = fsdp_sharding(params, mesh, min_size=min_shard_size)
    budget = training_memory(
        model, sh, axes, tx=tx, batch_per_chip=max(1, batch_per_chip),
        compute_dtype=compute_dtype, remat=remat, params=params,
        zero=zero,
    )
    return int(budget.total_bytes)


def _by_kind_prefix(table: dict, device) -> float | None:
    kind = device if isinstance(device, str) else \
        (getattr(device, "device_kind", "") or "")
    for prefix in sorted(table, key=len, reverse=True):
        if kind.startswith(prefix):
            return table[prefix]
    return None


def peak_bf16_flops(device) -> float | None:
    """Spec-sheet bf16 peak for ``device`` (a Device or a device-kind
    string; None when unknown)."""
    return _by_kind_prefix(PEAK_BF16_FLOPS, device)


def peak_hbm_bw(device) -> float | None:
    """Spec-sheet HBM bandwidth (bytes/s) for ``device`` (a Device or a
    device-kind string; None when unknown — e.g. the CPU backend, where
    DRAM bandwidth is not a chip constant worth pretending to know)."""
    return _by_kind_prefix(PEAK_HBM_BYTES_PER_S, device)


def peak_ici_bw(device) -> float | None:
    """Per-axis one-way ICI bandwidth (bytes/s) for ``device`` (a Device
    or a device-kind string; None when unknown)."""
    return _by_kind_prefix(PEAK_ICI_BYTES_PER_S, device)


def roofline_position(flops: float | None, bytes_moved: float | None,
                      time_s: float | None,
                      peak_flops: float | None = None,
                      peak_bw: float | None = None) -> dict:
    """One kernel's roofline coordinates from *estimates* (the profile
    subsystem's per-op FLOPs/bytes attributions — see
    ``obs.profile.kernels``): achieved FLOP/s and bytes/s, arithmetic
    intensity, fraction of each peak, and a compute/memory-bound
    classification against the ridge intensity ``peak_flops/peak_bw``.
    Every field degrades to ``None`` when its inputs are unknown rather
    than guessing — a position with ``bound: "unknown"`` is still a
    position, it just says the estimator had nothing to stand on."""
    t = float(time_s) if time_s else None
    f = float(flops) if flops else None
    b = float(bytes_moved) if bytes_moved else None
    achieved_f = (f / t) if f and t else None
    achieved_b = (b / t) if b and t else None
    intensity = (f / b) if f and b else None
    ridge = (peak_flops / peak_bw) if peak_flops and peak_bw else None
    bound = "unknown"
    if intensity is not None and ridge is not None:
        bound = "compute" if intensity >= ridge else "memory"
    elif f and not b:
        bound = "compute"
    elif b and not f:
        bound = "memory"
    return {
        "flops_est": f,
        "bytes_est": b,
        "achieved_flops_per_s": achieved_f,
        "achieved_bytes_per_s": achieved_b,
        "intensity_flops_per_byte": intensity,
        "ridge_intensity": ridge,
        "pct_peak_flops": (100.0 * achieved_f / peak_flops
                           if achieved_f and peak_flops else None),
        "pct_peak_bw": (100.0 * achieved_b / peak_bw
                        if achieved_b and peak_bw else None),
        "bound": bound,
    }


def flag_implausible_mfu(r: dict, *keys) -> dict:
    """An MFU reading above 1.0 means the stopwatch or the trace failed,
    not that the chip beat its spec — mark the record so no downstream
    table can quote it as clean.  ``keys`` defaults to ("mfu",)."""
    for k in keys or ("mfu",):
        if r.get(k) is not None and r[k] > 1.0:
            r["implausible"] = f"{k} > 1.0: timing fence or trace failed"
    return r

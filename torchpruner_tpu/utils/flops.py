"""Parameter and FLOPs accounting via XLA cost analysis.

Replaces the reference's ``thop.profile`` on a 2-sample random input
(reference experiments/utils/utils.py:30-36) with the compiler's own cost
model: ``jit(...).lower(...).compile().cost_analysis()`` — exact for the
compiled graph, no tracing heuristics.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import numpy as np
import jax.numpy as jnp

from torchpruner_tpu.core.segment import SegmentedModel


def param_count(params) -> int:
    return int(sum(np.prod(l.shape) for l in jax.tree_util.tree_leaves(params)))


def model_cost(
    model: SegmentedModel, params, state=None, batch_size: int = 2
) -> Tuple[int, Optional[float]]:
    """Returns ``(n_params, forward_flops)`` for a ``batch_size`` forward
    (the reference uses batch 2 because of BatchNorm, utils.py:33-34; here
    eval-mode BN has no batch constraint but we keep the convention)."""
    state = state if state is not None else {}
    x = model.example_input(batch_size)

    def fwd(p, s, x):
        return model.apply(p, x, state=s, train=False)[0]

    flops = None
    try:
        compiled = jax.jit(fwd).lower(params, state, x).compile()
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if ca:
            flops = float(ca.get("flops", 0.0)) or None
    except Exception:  # cost analysis is best-effort on some backends
        flops = None
    return param_count(params), flops

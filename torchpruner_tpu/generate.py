"""Autoregressive decoding with a KV cache — inference for the LM families.

The reference is a vision-only pruning library with no inference loop; this
framework's LM families (Llama/GQA, MoE decoders — BASELINE.json configs
3-5) need one so *pruned* models can actually be served and sampled.  The
design is TPU-first:

- **Static shapes everywhere**: the cache is a fixed ``(B, max_len, H, Dh)``
  buffer per attention layer, written at position ``pos`` with
  ``lax.dynamic_update_slice``; attention masks positions ``> pos`` instead
  of slicing a dynamic length, so one compiled step serves every position.
- **One jitted computation**: prefill runs the WHOLE prompt in one
  forward (S-long matmuls feed the MXU, causal within the block) and
  generation is a ``lax.scan`` of the single-token step — no per-token
  retrace, no host round-trips inside the loop; sampling (greedy or
  temperature) happens on-device.
- **Layer reuse**: position-independent layers (norms, Dense/GatedDense,
  MoE, activations) run through the SAME ``apply_layer`` rules as training
  (core/layers.py), so decode automatically tracks pruning — a model with
  pruned heads/FFN channels/experts decodes at the pruned shapes.  Only
  attention (cache read/write, RoPE at an offset) and position embeddings
  have decode-specific paths.

Decode-vs-forward parity (every position's logits equal the full causal
forward's) is the correctness contract — tests/test_generate.py checks it
for dense, pruned, and MoE models.
"""

from __future__ import annotations

import functools
from typing import Any, Dict, Optional

import jax
import numpy as np
import jax.numpy as jnp
from jax import lax

from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.segment import SegmentedModel
from torchpruner_tpu.ops.decode_attention import decode_attention
from torchpruner_tpu.ops.quant import oscale, qdot, wval

_NEG_INF = -1e30


def _attn_layers(layers, prefix=()):
    """Yield (path, spec) for every attention layer, recursing residuals."""
    for spec in layers:
        path = prefix + (spec.name,)
        if isinstance(spec, L.MultiHeadAttention):
            yield path, spec
        elif isinstance(spec, L.Residual):
            yield from _attn_layers(spec.body, path)
            yield from _attn_layers(spec.shortcut, path)


def init_cache(
    model: SegmentedModel, batch: int, max_len: int, dtype=jnp.float32
) -> Dict[str, Any]:
    """Zeroed KV buffers for every attention layer.

    K/V are cached *expanded to the query-head count* (post-GQA take), so
    irregular pruned groupings need no per-step gather; memory per layer is
    ``2 * B * max_len * H * Dh``.
    """
    cache: Dict[str, Any] = {}
    for path, spec in _attn_layers(model.layers):
        shape = (batch, max_len, spec.num_heads, spec.head_dim)
        cache["/".join(path)] = {
            "k": jnp.zeros(shape, dtype),
            "v": jnp.zeros(shape, dtype),
        }
    return cache


def _decode_attention(spec, params, entry, x, pos):
    """Attention for a token block against the cache.

    ``x``: (B, s, d) — s = 1 for decode steps, s = prompt length for the
    one-shot prefill; ``entry``: this layer's {"k", "v"} cache buffers;
    ``pos``: absolute position of the block's FIRST token — a scalar
    (every sequence at the same position: the static-batch path), or a
    ``(B,)`` vector giving every batch row its OWN position (the
    continuous-batching slot array, where concurrently-served requests
    sit at different decode depths).  The block's K/V are written at
    ``pos..pos+s-1`` (per row, for the vector form) and attention is
    causal within the block.  Returns (y, entry').
    """
    # qdot: leading-axis contraction — int4 q/k/v projections ride the
    # fused-unpack kernel (their (d, H, Dh) weights flatten to the
    # kernel's 2-D layout); float weights take the same tensordot
    q = oscale(qdot(x, params["wq"]), params["wq"])
    k = oscale(qdot(x, params["wk"]), params["wk"])
    v = oscale(qdot(x, params["wv"]), params["wv"])
    if "bq" in params:
        q = q + params["bq"]
        k = k + params["bk"]
        v = v + params["bv"]
    if spec.rope:
        q = L._rope(q, spec.rope_theta, offset=pos)
        k = L._rope(k, spec.rope_theta, offset=pos)
    if spec.kv_heads != spec.num_heads or spec.kv_group is not None:
        idx = jnp.asarray(spec.head_kv_index())
        k = jnp.take(k, idx, axis=2)
        v = jnp.take(v, idx, axis=2)
    ragged = jnp.ndim(pos) > 0  # per-slot positions (static branch)
    if ragged:
        # each row writes its block at its OWN position: vmap the
        # per-sequence (max_len, H, Dh) update over the slot axis
        write = jax.vmap(
            lambda buf, blk, p: lax.dynamic_update_slice(buf, blk, (p, 0, 0))
        )
        k_cache = write(entry["k"], k.astype(entry["k"].dtype), pos)
        v_cache = write(entry["v"], v.astype(entry["v"].dtype), pos)
    else:
        k_cache = lax.dynamic_update_slice(
            entry["k"], k.astype(entry["k"].dtype), (0, pos, 0, 0)
        )
        v_cache = lax.dynamic_update_slice(
            entry["v"], v.astype(entry["v"].dtype), (0, pos, 0, 0)
        )
    # attention against the static buffer: single-token steps (s == 1,
    # both the scalar-pos generate scan and the vector-pos slot array)
    # dispatch the decode-shaped Pallas kernel, which streams KV blocks
    # up to each row's own pos instead of scoring-then-masking the whole
    # cache; prefill blocks (s > 1) and non-blocking cache lengths take
    # the masked-einsum path inside the same dispatcher.  Both paths are
    # deterministic functions of the cache length, which is what keeps
    # slot-array decode bit-identical to solo decode (the serve
    # --verify contract; see ops/decode_attention.py).
    ctx = decode_attention(q, k_cache, v_cache, pos)
    y = oscale(jnp.einsum("bshk,hkd->bsd", ctx,
                          wval(params["wo"], ctx.dtype)), params["wo"])
    if "bo" in params:
        y = y + params["bo"]
    return y, {"k": k_cache, "v": v_cache}


def _decode_seq(layers, params, cache, x, pos, prefix=()):
    """A token block (s = 1 decode step, s = S prompt prefill) through a
    layer sequence in decode mode; returns ``(y, cache')`` with the
    attention entries replaced functionally."""
    for spec in layers:
        path = prefix + (spec.name,)
        key = "/".join(path)
        p = params.get(spec.name, {}) if params else {}
        if isinstance(spec, L.MultiHeadAttention):
            x, entry = _decode_attention(spec, p, cache[key], x, pos)
            cache = {**cache, key: entry}
        elif isinstance(spec, L.Residual):
            y, cache = _decode_seq(spec.body, p, cache, x, pos, path)
            if spec.shortcut:
                sc, cache = _decode_seq(spec.shortcut, p, cache, x, pos, path)
            else:
                sc = x
            x = y + sc
        elif isinstance(spec, L.PosEmbed):
            if jnp.ndim(pos) > 0:  # per-slot positions: (B, s) gather
                x = x + jnp.take(
                    p["emb"],
                    pos[:, None] + jnp.arange(x.shape[1])[None, :], axis=0,
                )
            else:
                x = x + jnp.take(
                    p["emb"], pos + jnp.arange(x.shape[1]), axis=0
                )[None]
        elif isinstance(spec, L.BatchNorm):
            raise NotImplementedError(
                "BatchNorm in decode mode (LM families use LayerNorm/RMSNorm)"
            )
        else:
            # position-independent layers: the training apply rules work
            # unchanged on a length-1 sequence (eval mode, no taps)
            x, _ = L.apply_layer(
                spec, p, {}, x, train=False, rng=None, taps=None, path=path
            )
    return x, cache


def make_decode_step(model: SegmentedModel):
    """jit: ``(params, cache, tok (B, 1) int32, pos scalar) ->
    (logits (B, vocab), cache')`` — the single-token decode step."""

    @jax.jit
    def step(params, cache, tok, pos):
        x, cache = _decode_seq(model.layers, params, cache, tok, pos)
        return x[:, 0], cache

    return step


def make_slot_decode_step(model: SegmentedModel):
    """jit: ``(params, cache, tok (B, 1) int32, pos (B,) int32) ->
    (logits (B, vocab), cache')`` — the CONTINUOUS-BATCHING decode step:
    one compiled program advances every slot one token at its own
    absolute position (admitted/evicted requests sit at different
    depths).  The per-slot correctness contract — each row's logits are
    bit-identical to decoding that sequence alone — is what
    tests/test_generate.py's ragged parity tests pin: attention reads
    only positions ``<= pos[b]`` of row ``b``'s cache, so neighbouring
    slots (and stale K/V left by a previous occupant of a recycled
    slot) can never leak into a row's result."""

    @jax.jit
    def step(params, cache, tok, pos):
        x, cache = _decode_seq(model.layers, params, cache, tok, pos)
        return x[:, 0], cache

    return step


def generate(
    model: SegmentedModel,
    params,
    prompt,
    n_new: int,
    *,
    max_len: Optional[int] = None,
    temperature: float = 0.0,
    top_k: Optional[int] = None,
    top_p: Optional[float] = None,
    rng=None,
    cache_dtype=jnp.float32,
) -> jnp.ndarray:
    """Sample ``n_new`` tokens after ``prompt`` (B, S) — returns (B, n_new).

    Greedy at ``temperature=0`` (default), else softmax sampling at the
    given temperature (``rng`` required), optionally truncated to the
    ``top_k`` highest-probability tokens and/or the ``top_p`` nucleus
    (smallest probability mass >= top_p).  Prefill is one whole-prompt
    forward and generation a ``lax.scan`` of the single-token step,
    inside one jit per (shape, n_new) — the decode loop never leaves the
    device.

    ``cache_dtype=jnp.bfloat16`` halves KV-cache bytes and reads;
    measured +12% decode throughput on a ~200M model on one v5e
    (bench llama_decode leg) at bf16-rounding cost in the cache.  The
    f32 default preserves exact decode-equals-full-forward parity.
    """
    prompt = jnp.asarray(prompt, jnp.int32)
    B, S = prompt.shape
    total = S + n_new
    max_len = max_len or total
    if max_len < total:
        raise ValueError(f"max_len {max_len} < prompt + n_new = {total}")
    if temperature > 0.0 and rng is None:
        raise ValueError("temperature sampling needs an rng")
    if top_k is not None and top_k < 1:
        raise ValueError(f"top_k must be >= 1, got {top_k}")
    if top_p is not None and not (0.0 < top_p <= 1.0):
        raise ValueError(f"top_p must be in (0, 1], got {top_p}")
    cache = init_cache(model, B, max_len, cache_dtype)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    run = _generate_fn(model, S, n_new, float(temperature), top_k,
                       top_p)
    return run(params, cache, prompt, rng)


def _truncate_logits(logits, top_k: Optional[int], top_p: Optional[float]):
    """Mask logits outside the top-k set / the top-p nucleus to -inf."""
    neg = jnp.finfo(logits.dtype).min
    if top_k is not None and top_k < logits.shape[-1]:
        kth = jnp.sort(logits, axis=-1)[..., -top_k][..., None]
        logits = jnp.where(logits >= kth, logits, neg)
    if top_p is not None and top_p < 1.0:
        sorted_ = jnp.sort(logits, axis=-1)[..., ::-1]  # descending
        probs = jax.nn.softmax(sorted_, axis=-1)
        csum = jnp.cumsum(probs, axis=-1)
        # keep the smallest prefix with mass >= top_p: a token stays if
        # the mass BEFORE it is < top_p
        keep_sorted = (csum - probs) < top_p
        # threshold = smallest kept logit
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_, jnp.inf), axis=-1, keepdims=True
        )
        logits = jnp.where(logits >= thresh, logits, neg)
    return logits


def clear_generate_cache():
    """Drop all cached generate programs (and, via GC of their jit
    wrappers, the XLA executables they pin).  Call between long pruning
    sweeps that generate from many distinct pruned specs — each distinct
    (spec, lengths, sampling config) combination is one cache entry, so a
    sweep mixing prompt lengths or temperatures fills the 64-entry LRU
    well before 64 specs."""
    _generate_fn.cache_clear()


@functools.lru_cache(maxsize=64)
def _generate_fn(model: SegmentedModel, S: int, n_new: int,
                 temperature: float, top_k: Optional[int] = None,
                 top_p: Optional[float] = None):
    """Compiled prefill+generate program, cached per (model spec, lengths,
    sampling config) so repeated generate() calls reuse the jit executable
    (the model spec is hashable; B/max_len specialize via jit's own
    shape-keyed cache).  LRU-bounded at 64 entries; evicted entries free
    their executables once unreferenced (see :func:`clear_generate_cache`
    for explicit eviction during pruned-variant sweeps)."""

    @jax.jit
    def run(params, cache, prompt, rng):
        def step_body(cache, tok, pos):
            x, cache = _decode_seq(model.layers, params, cache, tok, pos)
            return x[:, 0], cache

        # one-shot prefill: the whole prompt in ONE forward (S-long
        # matmuls feed the MXU) with causal-within-block cache attention,
        # instead of S sequential single-token steps
        x, cache_f = _decode_seq(model.layers, params, cache, prompt, 0)
        logits = x[:, -1]

        def sample(logits, r):
            if temperature == 0.0:
                return jnp.argmax(logits, axis=-1).astype(jnp.int32)
            # temperature FIRST: the nucleus must reflect the distribution
            # actually sampled from (top_k is scale-invariant, top_p isn't)
            logits = _truncate_logits(logits / temperature, top_k, top_p)
            return jax.random.categorical(r, logits, axis=-1).astype(
                jnp.int32
            )

        def gen(carry, pos):
            cache, logits, r = carry
            r, sub = jax.random.split(r)
            tok = sample(logits, sub)
            new_logits, cache = step_body(cache, tok[:, None], pos)
            return (cache, new_logits, r), tok

        _, toks = lax.scan(gen, (cache_f, logits, rng), S + jnp.arange(n_new))
        return jnp.moveaxis(toks, 0, 1)  # (B, n_new)

    return run



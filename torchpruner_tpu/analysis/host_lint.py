"""tpu-lint pass 6: host-side concurrency & durability lint.

Passes 1–5 check the *compiled* half of the system — plans, shardings,
jaxprs, collectives, rooflines.  The other half of the codebase is
ordinary threaded, multi-process Python: the serving engine's background
swap threads, the fleet router's health monitor, the search driver's
worker pool, the obs emitters every one of them writes through.  The
post-review hardening lists of PRs 6–14 show the same mechanically
detectable bug classes recurring there: shared state mutated without the
lock (PR 7's SLOMonitor), blocking work performed while holding a hot
lock, and durable artifacts written with a raw ``open(path, "w")``
instead of the ``resilience.manifest.atomic_write_json`` discipline that
PR 4 introduced after torn checkpoints corrupted resumes.

This pass hunts exactly those, from the AST alone — pure stdlib, no jax
import, no tracing; a whole-package scan takes well under a second, so
it runs on every ``--lint`` and in CI.  Checks (stable ids, severity in
parentheses):

``host/unlocked-shared-write`` (error)
    Within a class, any attribute ever touched (read OR written) inside
    a ``with self._lock:`` block is treated as lock-guarded shared
    state: the lock exists precisely because some other thread consults
    it.  A WRITE to the same attribute anywhere else without holding a
    lock is a data race — including from a ``threading.Thread`` target
    (just another method), and including cross-object writes
    (``self.scheduler.closed = True`` from the engine races
    ``Scheduler.submit``'s locked read; receivers are matched to
    scanned classes by name).  ``__init__`` / ``__post_init__`` /
    ``__new__`` are exempt (no peer thread can hold a reference yet).
    Both plain assignment and mutating method calls
    (``self._q.append(...)``, ``self._d.update(...)``) count as writes.

``host/blocking-under-lock`` (warning)
    ``time.sleep``, subprocess spawns/waits, ``urlopen``/socket dials,
    file IO (``open``, ``os.fsync``, ``atomic_write_json``), event
    waits, and thread ``.join()`` while holding a lock: every other
    thread contending on that lock inherits the latency.  Sometimes the
    point (a journal flushed under the lock IS the durability
    contract) — that is what the waiver file is for.

``host/lock-order`` (error)
    A cycle in the per-class lock-acquisition graph (lock B taken while
    holding A in one path, A while holding B in another — including one
    level through same-class method calls) can deadlock.

``host/torn-write`` (error)
    ``open(path, "w")`` / ``json.dump`` / ``Path.write_text`` aimed at
    a durable-artifact path (journal / manifest / ledger / frontier /
    campaign / snapshot / goldens) outside
    ``resilience.manifest.atomic_write_json``: a crash mid-write leaves
    a truncated hybrid that poisons the next resume.  Append-mode
    streams (``"a"`` — the JSONL event/ledger streams, whose readers
    tolerate a torn last line) are exempt.

``host/daemon-leak`` (warning)
    A ``threading.Thread``/``Timer`` constructed with neither
    ``daemon=True`` nor any visible ``.join()``/``.daemon = True`` on
    its binding: process exit blocks on it forever.

``host/wallclock-in-digest`` (error)
    ``time.time()`` / ``random.*`` / ``uuid.uuid4`` feeding a
    digest-carrying determinism path (a function, assignment target, or
    hash call whose name mentions ``digest`` or ``trial_id``): kill -9
    → resume must reproduce identical artifacts, and wall clocks never
    reproduce.

Intentional exceptions live in a committed, reason-carrying waiver file
(``results/host_lint_waivers.json`` by default).  A waiver downgrades
its matches to ``info`` (still printed, never silently gone); a waiver
whose file was scanned but which matched **nothing** is itself an error
(``host/stale-waiver``) so waivers cannot rot, and a waiver without a
reason is an error (``host/bad-waiver``).

Entry points: :func:`lint_host` (library), ``runner.lint_config(...,
host=True)`` (pass 6 of ``--lint``), and the standalone ``python -m
torchpruner_tpu lint-host [paths]`` (:func:`host_lint_main`) which
needs no preset so CI can scan the whole package.  The CI drill plants
a synthetic violation via ``TORCHPRUNER_LINT_PLANT=unlocked_write``
(the existing ``collective_lint.env_plant`` mechanism) — the scan must
then exit 1 naming ``host/unlocked-shared-write``.
"""

from __future__ import annotations

import ast
import json
import os
import textwrap
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from torchpruner_tpu.analysis.findings import Finding

# ---------------------------------------------------------------------------
# vocabulary
# ---------------------------------------------------------------------------

#: path fragments that mark a durable artifact (the resume/report/CI
#: surface) — a torn write to one of these is never acceptable
DURABLE_KEYWORDS = ("journal", "manifest", "ledger", "frontier",
                    "campaign", "snapshot", "golden")

#: mutating container-method names that count as writes to the receiver
MUTATOR_METHODS = frozenset({
    "append", "extend", "add", "insert", "remove", "discard", "pop",
    "popleft", "appendleft", "clear", "update", "setdefault",
})

#: dotted call names (exact) that block while holding a lock
_BLOCKING_EXACT = frozenset({
    "time.sleep", "sleep", "urlopen", "open", "io.open", "os.fsync",
    "socket.create_connection", "atomic_write_json",
})
#: dotted-name prefixes that block
_BLOCKING_PREFIXES = ("subprocess.", "urllib.request.", "requests.",
                      "shutil.")
#: attribute method names that block (``x.wait(...)``, ``conn.recv()``)
_BLOCKING_METHODS = frozenset({
    "wait", "getresponse", "recv", "sendall", "accept", "urlopen",
    "atomic_write_json", "fsync",
})

#: wall-clock / entropy sources that must not feed determinism paths
_WALLCLOCK_EXACT = frozenset({
    "time.time", "time.time_ns", "time.monotonic", "uuid.uuid4",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
})
_WALLCLOCK_PREFIXES = ("random.",)

#: methods exempt from the unlocked-write check: construction happens
#: before any peer thread can hold a reference
_INIT_METHODS = frozenset({"__init__", "__post_init__", "__new__"})

_SEVERITY = {
    "host/unlocked-shared-write": "error",
    "host/blocking-under-lock": "warning",
    "host/lock-order": "error",
    "host/torn-write": "error",
    "host/daemon-leak": "warning",
    "host/wallclock-in-digest": "error",
    "host/stale-waiver": "error",
    "host/bad-waiver": "error",
}

#: the one module allowed to spell the raw write dance (it IS the
#: atomic writer)
_TORN_WRITE_EXEMPT_FILES = ("resilience/manifest.py",)

#: planted-violation sources for the CI drill (consumed via
#: ``collective_lint.env_plant()`` by the lint drivers only)
_PLANTS = {
    "unlocked_write": textwrap.dedent(
        """
        import threading

        class PlantedCounter:
            def __init__(self):
                self._lock = threading.Lock()
                self.count = 0

            def guarded(self):
                with self._lock:
                    self.count += 1

            def racy(self):   # the planted hazard: no lock
                self.count += 1
        """
    ),
    "torn_write": textwrap.dedent(
        """
        import json

        def save(journal_path, obj):
            with open(journal_path, "w") as f:   # planted torn write
                json.dump(obj, f)
        """
    ),
}


# ---------------------------------------------------------------------------
# AST helpers
# ---------------------------------------------------------------------------


def _dotted(node: ast.AST) -> str:
    """``"time.sleep"`` for an Attribute chain, ``"sleep"`` for a bare
    Name, ``""`` for anything else (calls, subscripts...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _lock_key(expr: ast.AST) -> Optional[str]:
    """The lock identity a ``with`` context expression acquires, or
    None when it is not a lock.  Anything whose (attribute) name
    contains ``lock`` counts: ``self._lock``, ``self._journal_lock``,
    a module-level ``_lock``."""
    if isinstance(expr, ast.Attribute):
        if "lock" in expr.attr.lower():
            base = _dotted(expr.value) or "<expr>"
            return f"{base}.{expr.attr}"
        return None
    if isinstance(expr, ast.Name) and "lock" in expr.id.lower():
        return expr.id
    return None


def _str_fragments(node: ast.AST) -> List[str]:
    """Every string literal, identifier, and attribute name reachable
    inside an expression — the haystack the durable-path keywords are
    matched against."""
    out: List[str] = []
    for n in ast.walk(node):
        if isinstance(n, ast.Constant) and isinstance(n.value, str):
            out.append(n.value)
        elif isinstance(n, ast.Name):
            out.append(n.id)
        elif isinstance(n, ast.Attribute):
            out.append(n.attr)
        elif isinstance(n, ast.keyword) and n.arg:
            out.append(n.arg)
    return out


def _durable_fragment(node: ast.AST) -> Optional[str]:
    for frag in _str_fragments(node):
        low = frag.lower()
        for kw in DURABLE_KEYWORDS:
            if kw in low:
                return frag
    return None


def _self_attr_target(node: ast.AST) -> Optional[str]:
    """The attribute name a store-target writes on ``self`` (plain
    ``self.x`` or item store ``self.x[k]``)."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    if isinstance(node, ast.Subscript):
        return _self_attr_target(node.value)
    return None


def _ext_write_target(node: ast.AST) -> Optional[Tuple[str, str]]:
    """``(receiver ident, attr)`` for a store THROUGH another object —
    ``self.scheduler.closed`` -> ("scheduler", "closed"),
    ``sched.closed`` -> ("sched", "closed") — or None for plain
    ``self.x`` / local-name targets."""
    if isinstance(node, ast.Subscript):
        return _ext_write_target(node.value)
    if not isinstance(node, ast.Attribute):
        return None
    base = node.value
    if isinstance(base, ast.Name) and base.id not in ("self", "cls"):
        return (base.id, node.attr)
    if isinstance(base, ast.Attribute) and \
            isinstance(base.value, ast.Name) and \
            base.value.id in ("self", "cls"):
        return (base.attr, node.attr)
    return None


# ---------------------------------------------------------------------------
# per-class / per-module accumulation
# ---------------------------------------------------------------------------


@dataclass
class _Write:
    attr: str
    func: str
    line: int
    locked: bool
    kind: str  # "assign" | "mutate"


@dataclass
class _ThreadSite:
    line: int
    func: str
    ident: Optional[str]  # binding name ("X" of self.X / local x)
    daemon: bool


@dataclass
class _Scope:
    """One lint scope: a class body, or the module's top level."""

    name: str
    writes: List[_Write] = field(default_factory=list)
    guarded_attrs: Set[str] = field(default_factory=set)
    #: attrs READ on self while holding a lock — part of the guarded
    #: invariant too (the lock exists because someone else consults it)
    read_guarded: Set[str] = field(default_factory=set)
    #: cross-object writes: (receiver ident, attr, func, line, locked,
    #: kind) for ``self.scheduler.closed = True`` / ``sched.closed = x``
    ext_writes: List[Tuple[str, str, str, int, bool, str]] = \
        field(default_factory=list)
    blocking: List[Tuple[str, str, str, int]] = field(default_factory=list)
    #: (outer lock, inner lock) -> first line observed
    edges: Dict[Tuple[str, str], int] = field(default_factory=dict)
    #: method name -> locks it acquires directly
    acquires: Dict[str, Set[str]] = field(default_factory=dict)
    #: (held locks, self-method called, line)
    calls_under_lock: List[Tuple[Tuple[str, ...], str, int]] = \
        field(default_factory=list)
    threads: List[_ThreadSite] = field(default_factory=list)
    joined_idents: Set[str] = field(default_factory=set)
    torn: List[Tuple[str, str, str, int]] = field(default_factory=list)
    wallclock: List[Tuple[str, str, str, int]] = field(default_factory=list)


class _ModuleScanner:
    """Walks one module's AST, accumulating per-scope evidence."""

    def __init__(self, tree: ast.Module, relpath: str):
        self.relpath = relpath
        self.scopes: List[_Scope] = []
        module_scope = _Scope("<module>")
        self.scopes.append(module_scope)
        for node in tree.body:
            if isinstance(node, ast.ClassDef):
                scope = _Scope(node.name)
                self.scopes.append(scope)
                for item in node.body:
                    self._walk_class_item(item, scope)
            else:
                self._walk_stmt(node, module_scope, (), "<module>",
                                in_digest=False)

    # -- statement walking -------------------------------------------------

    def _walk_class_item(self, node: ast.stmt, scope: _Scope) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            digest = self._digesty(node.name)
            # the ``_locked`` suffix is the caller-holds-the-lock
            # convention (``check()`` wraps ``_check_locked()``): the
            # body executes under a lock it does not itself acquire
            held: Tuple[str, ...] = ("<held at entry>",) \
                if node.name.endswith("_locked") else ()
            for stmt in node.body:
                self._walk_stmt(stmt, scope, held, node.name,
                                in_digest=digest)
        else:
            self._walk_stmt(node, scope, (), "<class body>",
                            in_digest=False)

    @staticmethod
    def _digesty(name: str) -> bool:
        low = name.lower()
        return "digest" in low or "trial_id" in low

    def _walk_stmt(self, node: ast.stmt, scope: _Scope,
                   held: Tuple[str, ...], func: str,
                   in_digest: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a nested def is a closure: it runs later (often on another
            # thread), NOT under the enclosing lock
            digest = in_digest or self._digesty(node.name)
            qual = node.name if func == "<module>" \
                else f"{func}.{node.name}"
            for stmt in node.body:
                self._walk_stmt(stmt, scope, (), qual, in_digest=digest)
            return
        if isinstance(node, ast.ClassDef):
            inner = _Scope(f"{scope.name}.{node.name}")
            self.scopes.append(inner)
            for item in node.body:
                self._walk_class_item(item, inner)
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            new_held = list(held)
            for item in node.items:
                key = _lock_key(item.context_expr)
                if key is not None:
                    for outer in new_held:
                        if outer != key:
                            scope.edges.setdefault(
                                (outer, key), node.lineno)
                    scope.acquires.setdefault(func, set()).add(key)
                    new_held.append(key)
                else:
                    self._walk_expr(item.context_expr, scope, held,
                                    func, in_digest)
            for stmt in node.body:
                self._walk_stmt(stmt, scope, tuple(new_held), func,
                                in_digest)
            return
        if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            digest_target = in_digest
            for t in targets:
                for leaf in self._flat_targets(t):
                    attr = _self_attr_target(leaf)
                    if attr is not None:
                        scope.writes.append(_Write(
                            attr, func, node.lineno, bool(held),
                            "assign"))
                        if held:
                            scope.guarded_attrs.add(attr)
                    else:
                        ext = _ext_write_target(leaf)
                        if ext is not None:
                            scope.ext_writes.append(
                                (ext[0], ext[1], func, node.lineno,
                                 bool(held), "assign"))
                    name = leaf.attr if isinstance(leaf, ast.Attribute) \
                        else leaf.id if isinstance(leaf, ast.Name) else ""
                    if self._digesty(name):
                        digest_target = True
                    # ``x.daemon = True`` on a thread binding
                    if isinstance(leaf, ast.Attribute) and \
                            leaf.attr == "daemon":
                        base = _self_attr_target(leaf.value)
                        if base is None:
                            base = leaf.value.id \
                                if isinstance(leaf.value, ast.Name) \
                                else None
                        if base:
                            scope.joined_idents.add(base)
            value = getattr(node, "value", None)
            if value is not None:
                bound = self._thread_binding(node)
                self._walk_expr(value, scope, held, func,
                                digest_target, thread_bound=bound)
            return
        # generic statement: walk child statements with the same lock
        # context, child expressions through the expression visitor
        for fld, child in ast.iter_fields(node):
            if isinstance(child, list):
                for c in child:
                    if isinstance(c, ast.stmt):
                        self._walk_stmt(c, scope, held, func, in_digest)
                    elif isinstance(c, ast.expr):
                        self._walk_expr(c, scope, held, func, in_digest)
            elif isinstance(child, ast.stmt):
                self._walk_stmt(child, scope, held, func, in_digest)
            elif isinstance(child, ast.expr):
                self._walk_expr(child, scope, held, func, in_digest)

    @staticmethod
    def _flat_targets(t: ast.expr) -> List[ast.expr]:
        if isinstance(t, (ast.Tuple, ast.List)):
            out = []
            for e in t.elts:
                out.extend(_ModuleScanner._flat_targets(e))
            return out
        return [t]

    @staticmethod
    def _thread_binding(node: ast.stmt) -> Optional[str]:
        """When an Assign's value is (or contains) a Thread ctor, the
        name it is bound to — ``"X"`` for ``self.X = Thread(...)`` /
        ``x = Thread(...)``."""
        if not isinstance(node, ast.Assign) or len(node.targets) != 1:
            return None
        t = node.targets[0]
        attr = _self_attr_target(t)
        if attr is not None:
            return attr
        if isinstance(t, ast.Name):
            return t.id
        return None

    # -- expression walking ------------------------------------------------

    def _walk_expr(self, node: ast.expr, scope: _Scope,
                   held: Tuple[str, ...], func: str, in_digest: bool,
                   thread_bound: Optional[str] = None) -> None:
        if held and isinstance(node, ast.Attribute) and \
                isinstance(node.value, ast.Name) and \
                node.value.id == "self" and \
                "lock" not in node.attr.lower():
            # an attribute CONSULTED under the lock is part of the
            # guarded invariant — unlocked writes to it race this read
            scope.read_guarded.add(node.attr)
        if isinstance(node, ast.Lambda):
            self._walk_expr(node.body, scope, (), f"{func}.<lambda>",
                            in_digest)
            return
        if isinstance(node, ast.Call):
            self._visit_call(node, scope, held, func, in_digest,
                             thread_bound)
            dig = in_digest or self._is_digest_call(node)
            for a in node.args:
                self._walk_expr(a, scope, held, func, dig)
            for kw in node.keywords:
                self._walk_expr(kw.value, scope, held, func, dig)
            self._walk_expr(node.func, scope, held, func, in_digest)
            return
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._walk_expr(child, scope, held, func, in_digest)
            elif isinstance(child, ast.stmt):  # pragma: no cover
                self._walk_stmt(child, scope, held, func, in_digest)

    @staticmethod
    def _is_digest_call(node: ast.Call) -> bool:
        name = _dotted(node.func)
        low = name.lower()
        return "digest" in low or "sha" in low or "hash" in low or \
            "md5" in low or "blake" in low

    def _visit_call(self, node: ast.Call, scope: _Scope,
                    held: Tuple[str, ...], func: str, in_digest: bool,
                    thread_bound: Optional[str]) -> None:
        name = _dotted(node.func)
        line = node.lineno

        # thread construction (daemon-leak bookkeeping)
        if name in ("threading.Thread", "Thread", "threading.Timer",
                    "Timer"):
            daemon = any(
                kw.arg == "daemon" and
                isinstance(kw.value, ast.Constant) and
                bool(kw.value.value)
                for kw in node.keywords
            )
            scope.threads.append(
                _ThreadSite(line, func, thread_bound, daemon))

        # ``x.join()`` — thread join (str.join takes exactly one
        # iterable positional; a thread join takes none, or a numeric /
        # ``timeout=`` argument)
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr == "join":
            timeout_kw = any(kw.arg == "timeout" for kw in node.keywords)
            numeric = (
                len(node.args) == 1
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, (int, float))
            )
            if not node.args and not node.keywords or timeout_kw \
                    or numeric:
                base = _self_attr_target(node.func.value)
                if base is None and isinstance(node.func.value, ast.Name):
                    base = node.func.value.id
                if base:
                    scope.joined_idents.add(base)
                if held:
                    scope.blocking.append(
                        (held[-1], f"{base or '?'}.join()", func, line))

        # mutating container methods on self attributes are writes
        if isinstance(node.func, ast.Attribute) and \
                node.func.attr in MUTATOR_METHODS:
            attr = _self_attr_target(node.func.value)
            if attr is not None:
                scope.writes.append(
                    _Write(attr, func, line, bool(held), "mutate"))
                if held:
                    scope.guarded_attrs.add(attr)
            else:
                ext = _ext_write_target(node.func.value)
                if ext is not None:
                    scope.ext_writes.append(
                        (ext[0], ext[1], func, line, bool(held),
                         "mutate"))

        # blocking work under a lock
        if held:
            blocking = (
                name in _BLOCKING_EXACT
                or name.startswith(_BLOCKING_PREFIXES)
                or (isinstance(node.func, ast.Attribute)
                    and node.func.attr in _BLOCKING_METHODS)
            )
            if blocking:
                scope.blocking.append((held[-1], name or
                                       f".{node.func.attr}(...)",
                                       func, line))

        # same-class method call while holding a lock (one-level
        # lock-order closure)
        if held and isinstance(node.func, ast.Attribute) and \
                isinstance(node.func.value, ast.Name) and \
                node.func.value.id == "self":
            scope.calls_under_lock.append((held, node.func.attr, line))

        # torn durable writes
        self._check_torn(node, name, scope, func, line)

        # wall clock / entropy feeding a determinism path
        wallclock = name in _WALLCLOCK_EXACT or \
            name.startswith(_WALLCLOCK_PREFIXES)
        if wallclock and (in_digest or self._digesty(func)):
            scope.wallclock.append((name, func, "", line))

    def _check_torn(self, node: ast.Call, name: str, scope: _Scope,
                    func: str, line: int) -> None:
        if name in ("open", "io.open"):
            mode = None
            if len(node.args) >= 2:
                mode = node.args[1]
            for kw in node.keywords:
                if kw.arg == "mode":
                    mode = kw.value
            if not (isinstance(mode, ast.Constant)
                    and isinstance(mode.value, str)
                    and "w" in mode.value):
                return
            if not node.args:
                return
            frag = _durable_fragment(node.args[0])
            if frag:
                scope.torn.append(
                    (f"open(..., {mode.value!r})", frag, func, line))
        elif name in ("json.dump",) or name.endswith(".dump"):
            frag = _durable_fragment(node)
            if frag and (name == "json.dump" or name == "dump"):
                scope.torn.append(("json.dump", frag, func, line))
        elif isinstance(node.func, ast.Attribute) and \
                node.func.attr in ("write_text", "write_bytes"):
            frag = _durable_fragment(node.func.value)
            if frag:
                scope.torn.append(
                    (f".{node.func.attr}()", frag, func, line))


# ---------------------------------------------------------------------------
# findings from scopes
# ---------------------------------------------------------------------------


def _cycle_of(edges: Dict[Tuple[str, str], int]) -> Optional[List[str]]:
    """One lock-order cycle (as a node list) if the digraph has any."""
    graph: Dict[str, Set[str]] = {}
    for (a, b) in edges:
        graph.setdefault(a, set()).add(b)
        graph.setdefault(b, set())
    WHITE, GRAY, BLACK = 0, 1, 2
    color = {n: WHITE for n in graph}
    stack: List[str] = []

    def dfs(n: str) -> Optional[List[str]]:
        color[n] = GRAY
        stack.append(n)
        for m in sorted(graph[n]):
            if color[m] == GRAY:
                return stack[stack.index(m):] + [m]
            if color[m] == WHITE:
                cyc = dfs(m)
                if cyc is not None:
                    return cyc
        stack.pop()
        color[n] = BLACK
        return None

    for n in sorted(graph):
        if color[n] == WHITE:
            cyc = dfs(n)
            if cyc is not None:
                return cyc
    return None


def _scope_findings(scope: _Scope, relpath: str) -> List[Finding]:
    out: List[Finding] = []

    def emit(check: str, line: int, where: str, message: str) -> None:
        out.append(Finding(
            _SEVERITY[check], "host", check,
            f"{relpath}:{line} {where}", message,
        ))

    # unlocked writes to lock-guarded attributes
    guarded = scope.guarded_attrs | scope.read_guarded
    for w in scope.writes:
        if w.locked or w.attr not in guarded:
            continue
        if w.func.split(".")[0] in _INIT_METHODS:
            continue
        verb = "mutated" if w.kind == "mutate" else "written"
        emit("host/unlocked-shared-write", w.line,
             f"{scope.name}.{w.func}",
             f"self.{w.attr} is lock-guarded elsewhere in "
             f"{scope.name} but {verb} here without the lock — a "
             f"peer thread can interleave mid-update")

    # blocking work under a lock
    for lock, what, func, line in scope.blocking:
        emit("host/blocking-under-lock", line, f"{scope.name}.{func}",
             f"{what} while holding {lock} — every thread contending "
             f"on the lock inherits this latency")

    # lock-order cycles (direct nesting + one level through same-class
    # method calls)
    edges = dict(scope.edges)
    for held, method, line in scope.calls_under_lock:
        for inner in scope.acquires.get(method, ()):
            for outer in held:
                if outer != inner:
                    edges.setdefault((outer, inner), line)
    cyc = _cycle_of(edges)
    if cyc is not None:
        line = min(edges[(a, b)] for (a, b) in edges
                   if a in cyc and b in cyc)
        emit("host/lock-order", line, scope.name,
             "lock-acquisition cycle " + " -> ".join(cyc) +
             " — two threads entering from opposite ends deadlock")

    # torn durable writes
    for what, frag, func, line in scope.torn:
        emit("host/torn-write", line, f"{scope.name}.{func}",
             f"{what} targets durable artifact path ({frag!r}) without "
             f"resilience.manifest.atomic_write_json — a crash "
             f"mid-write leaves a truncated file that poisons the next "
             f"resume")

    # daemon leaks
    for t in scope.threads:
        if t.daemon:
            continue
        if t.ident and t.ident in scope.joined_idents:
            continue
        bound = f"bound to {t.ident!r}" if t.ident else "unbound"
        emit("host/daemon-leak", t.line, f"{scope.name}.{t.func}",
             f"thread {bound} has neither daemon=True nor a visible "
             f".join()/.daemon on its shutdown path — process exit can "
             f"hang on it")

    # wall clock in digests
    for name, func, _, line in scope.wallclock:
        emit("host/wallclock-in-digest", line, f"{scope.name}.{func}",
             f"{name}() feeds a digest-carrying determinism path — "
             f"kill -9 -> resume cannot reproduce the artifact")

    return out


def _cross_findings(
        scopes: List[Tuple[_Scope, str]]) -> List[Finding]:
    """Cross-object unlocked writes: a write THROUGH a receiver whose
    name matches a scanned class (``self.scheduler.closed = True`` vs
    class ``Scheduler``) to an attribute that class guards under its
    lock.  The receiver-to-class match is by identifier (stripped of
    leading underscores, substring either way, >= 4 chars) — the same
    name discipline the codebase already follows."""
    guarded_by_class: Dict[str, Set[str]] = {}
    for scope, _rel in scopes:
        if scope.name.startswith("<"):
            continue
        g = scope.guarded_attrs | scope.read_guarded
        if g:
            guarded_by_class.setdefault(scope.name, set()).update(g)
    out: List[Finding] = []
    for scope, rel in scopes:
        for recv, attr, func, line, locked, kind in scope.ext_writes:
            if locked:
                continue
            if func.split(".")[0] in _INIT_METHODS:
                continue
            rname = recv.lstrip("_").lower()
            if len(rname) < 4:
                continue
            for cname in sorted(guarded_by_class):
                cl = cname.lower()
                if attr in guarded_by_class[cname] and \
                        (rname in cl or cl in rname):
                    verb = "mutated" if kind == "mutate" else "written"
                    out.append(Finding(
                        _SEVERITY["host/unlocked-shared-write"],
                        "host", "host/unlocked-shared-write",
                        f"{rel}:{line} {scope.name}.{func}",
                        f"{recv}.{attr} is lock-guarded inside class "
                        f"{cname} but {verb} here without that lock — "
                        f"this cross-object write races every locked "
                        f"reader",
                    ))
                    break
    return out


# ---------------------------------------------------------------------------
# scanning
# ---------------------------------------------------------------------------


def _scan_module(
        src: str, relpath: str,
) -> Tuple[List[Tuple[_Scope, str]], List[Finding]]:
    """``(scopes, per-module findings)`` for one module's source; the
    scopes feed the whole-scan cross-object phase."""
    for exempt in _TORN_WRITE_EXEMPT_FILES:
        if relpath.replace(os.sep, "/").endswith(exempt):
            return [], []
    try:
        tree = ast.parse(src, filename=relpath)
    except SyntaxError as e:  # pragma: no cover - committed tree parses
        return [], [Finding("warning", "host", "host/unparsable",
                            f"{relpath}:{e.lineno or 0}",
                            f"could not parse: {e.msg}")]
    scanner = _ModuleScanner(tree, relpath)
    findings: List[Finding] = []
    for scope in scanner.scopes:
        findings += _scope_findings(scope, relpath)
    return [(s, relpath) for s in scanner.scopes], findings


def scan_source(src: str, relpath: str) -> List[Finding]:
    """All pass-6 findings for one module's source text (cross-object
    matching restricted to classes within the module)."""
    scopes, findings = _scan_module(src, relpath)
    return findings + _cross_findings(scopes)


def _package_root() -> str:
    import torchpruner_tpu

    return os.path.dirname(os.path.abspath(torchpruner_tpu.__file__))


def host_lint_default_paths() -> Tuple[str, ...]:
    """The host-side serving-plane directories pass 6 scans by default
    (``fleet/``, ``serve/``, ``search/``, ``obs/``, ``resilience/``) —
    exported so callers (runner, CI, tests) never hardcode the package
    root.  Pass explicit paths (e.g. the whole package) to
    :func:`lint_host` / ``lint-host`` to scan more."""
    root = _package_root()
    return tuple(
        os.path.join(root, d)
        for d in ("fleet", "serve", "search", "obs", "resilience")
    )


def default_waivers_path() -> str:
    """``results/host_lint_waivers.json`` next to the package (the
    committed, reason-carrying exception list)."""
    repo = os.path.dirname(_package_root())
    return os.path.join(repo, "results", "host_lint_waivers.json")


def _iter_py_files(paths: Sequence[str]) -> List[str]:
    files: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            files.append(p)
        else:
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames
                               if d != "__pycache__"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        files.append(os.path.join(dirpath, fn))
    # stable order, no duplicates
    seen: Set[str] = set()
    out = []
    for f in sorted(files):
        if f not in seen:
            seen.add(f)
            out.append(f)
    return out


def _relpath(path: str) -> str:
    """Package-anchored display path (``torchpruner_tpu/fleet/...``) so
    findings and waivers are stable across checkouts."""
    path = os.path.abspath(path)
    repo = os.path.dirname(_package_root())
    if path.startswith(repo + os.sep):
        return os.path.relpath(path, repo).replace(os.sep, "/")
    return path.replace(os.sep, "/")


# -- waivers -----------------------------------------------------------------


@dataclass
class Waiver:
    check: str
    file: str
    reason: str
    match: str = ""
    hits: int = 0

    def matches(self, f: Finding) -> bool:
        if f.check != self.check:
            return False
        if self.file and self.file not in f.path:
            return False
        if self.match and self.match not in f.path and \
                self.match not in f.message:
            return False
        return True


def load_waivers(path: str) -> Tuple[List[Waiver], List[Finding]]:
    """``(waivers, findings)`` — malformed entries become
    ``host/bad-waiver`` errors instead of silently vanishing."""
    if not os.path.exists(path):
        return [], []
    with open(path) as f:
        raw = json.load(f)
    entries = raw.get("waivers", raw) if isinstance(raw, dict) else raw
    waivers: List[Waiver] = []
    findings: List[Finding] = []
    for i, e in enumerate(entries):
        check = (e or {}).get("check", "")
        file = (e or {}).get("file", "")
        reason = (e or {}).get("reason", "")
        if not check or not file or not str(reason).strip():
            findings.append(Finding(
                "error", "host", "host/bad-waiver",
                f"{_relpath(path)}[{i}]",
                "waiver entries need non-empty 'check', 'file', and "
                "'reason' fields — an exception without a reason is "
                "not an exception, it is rot",
            ))
            continue
        waivers.append(Waiver(check, file,
                              str(reason).strip(), (e or {}).get(
                                  "match", "")))
    return waivers, findings


def apply_waivers(findings: List[Finding], waivers: List[Waiver],
                  scanned_files: Sequence[str]) -> List[Finding]:
    """Waived findings degrade to ``info`` (annotated with the reason,
    never silently dropped); a waiver whose file WAS scanned but which
    matched nothing becomes a ``host/stale-waiver`` error so the file
    cannot rot."""
    import dataclasses as _dc

    out: List[Finding] = []
    for f in findings:
        waived = None
        for w in waivers:
            if w.matches(f):
                w.hits += 1
                waived = w
                break
        if waived is None:
            out.append(f)
        else:
            out.append(_dc.replace(
                f, severity="info",
                message=f"waived ({waived.reason}): {f.message}"))
    scanned_rel = [_relpath(p) for p in scanned_files]
    for w in waivers:
        if w.hits:
            continue
        covered = any(w.file in rel for rel in scanned_rel)
        if covered:
            out.append(Finding(
                "error", "host", "host/stale-waiver", w.file,
                f"waiver for {w.check} matched no finding — the code "
                f"it excused is gone or fixed; delete the entry "
                f"(reason was: {w.reason})",
            ))
    return out


# -- entry points ------------------------------------------------------------


_scan_cache: Dict[Tuple, Tuple[float, List[Finding], List[str]]] = {}


def lint_host(paths: Optional[Sequence[str]] = None, *,
              waivers_path: Optional[str] = None,
              plant: Optional[str] = None) -> List[Finding]:
    """Pass 6 over ``paths`` (default: :func:`host_lint_default_paths`),
    waivers applied, planted-violation drill honored.  Results are
    cached per (paths, waivers, plant) keyed on file mtimes — the
    preset sweep lints many configs against one unchanged tree."""
    paths = tuple(paths) if paths else host_lint_default_paths()
    wpath = waivers_path if waivers_path is not None \
        else default_waivers_path()
    files = _iter_py_files(paths)
    stamp = max(
        (os.path.getmtime(f) for f in files
         if os.path.exists(f)), default=0.0)
    if os.path.exists(wpath):
        stamp = max(stamp, os.path.getmtime(wpath))
    key = (paths, wpath, plant, len(files))
    cached = _scan_cache.get(key)
    if cached is not None and cached[0] == stamp:
        return list(cached[1])

    findings: List[Finding] = []
    all_scopes: List[Tuple[_Scope, str]] = []
    for f in files:
        try:
            with open(f, encoding="utf-8") as fh:
                src = fh.read()
        except OSError:
            continue
        scopes, fs = _scan_module(src, _relpath(f))
        all_scopes += scopes
        findings += fs
    findings += _cross_findings(all_scopes)
    if plant:
        # the TORCHPRUNER_LINT_PLANT namespace is SHARED with the
        # collective drill (pass 4's replicated_allreduce etc.) — a
        # plant this pass doesn't own is someone else's drill, not an
        # error, matching how the placement planner ignores ours
        src = _PLANTS.get(plant)
        if src is not None:
            findings += scan_source(src, f"<planted:{plant}>")
    waivers, wfindings = load_waivers(wpath)
    findings = apply_waivers(findings, waivers, files) + wfindings
    _scan_cache[key] = (stamp, list(findings), files)
    return findings


def record_gauges(findings: Iterable[Finding]) -> None:
    """``host_lint_findings_total`` (+ an error-count twin) into the
    active obs session so report.json carries the scan and ``obs
    diff`` can gate it (``host_lint_`` rides the dynamic prefixes)."""
    from torchpruner_tpu import obs

    if obs.get() is None:
        return
    fs = list(findings)
    obs.gauge_set("host_lint_findings_total", len(fs),
                  help="host-side concurrency/durability lint findings")
    obs.gauge_set("host_lint_errors_total",
                  sum(1 for f in fs if f.severity == "error"),
                  help="error-severity host lint findings")


def host_lint_main(argv: Optional[Sequence[str]] = None) -> int:
    """``python -m torchpruner_tpu lint-host [paths...]`` — the
    standalone entry that needs no preset, so CI can scan the whole
    package.  Exits 1 on error-severity findings (after waivers)."""
    import argparse

    from torchpruner_tpu.analysis.collective_lint import env_plant
    from torchpruner_tpu.analysis.findings import merge_reports

    p = argparse.ArgumentParser(
        prog="torchpruner_tpu lint-host",
        description="tpu-lint pass 6: host-side concurrency & "
                    "durability lint (AST-only, no jax) — races, "
                    "blocking-under-lock, lock-order cycles, torn "
                    "durable writes, daemon leaks, wall clocks in "
                    "digests",
    )
    p.add_argument(
        "paths", nargs="*",
        help="files/directories to scan (default: the serving-plane "
             "dirs fleet/ serve/ search/ obs/ resilience/)")
    p.add_argument(
        "--waivers", metavar="PATH", default=None,
        help="waiver file (default results/host_lint_waivers.json); "
             "entries carry check/file/reason and downgrade matches "
             "to info — a waiver matching nothing is an error")
    p.add_argument(
        "--json", metavar="PATH", default=None,
        help="additionally write the findings as JSON (atomic) — the "
             "CI artifact")
    args = p.parse_args(list(argv) if argv is not None else None)

    findings = lint_host(args.paths or None,
                         waivers_path=args.waivers,
                         plant=env_plant())
    name = "host (" + (", ".join(args.paths) if args.paths
                       else "serving plane") + ")"
    report = merge_reports(name, findings)
    print(report.format())
    if args.json:
        from torchpruner_tpu.resilience.manifest import atomic_write_json

        atomic_write_json(args.json, {
            "findings": [vars(f) for f in report.findings],
            "errors": len(report.errors),
            "warnings": len(report.warnings),
        })
    record_gauges(report.findings)
    return 0 if report.ok else 1

"""The tpu-lint driver: run every pass over one experiment config.

``lint_config`` resolves the config's model from the experiment registry
and runs

1. **plan lint** over every prune group the static graph derives
   (analysis/plan_lint.py),
2. **sharding lint** for configs with a mesh — the config's own
   mesh/partition/fraction/bucket, simulated over the config's filtered
   targets (analysis/sharding_lint.py); explicit ``plans`` are matched
   back to their graph groups and linted under the same mesh,
3. **jaxpr hazard lint** on the config's train step — its real
   loss/optimizer/compute_dtype/remat (analysis/jaxpr_lint.py),
4. **collective-contract lint** on the COMPILED step programs — the
   collectives the SPMD partitioner actually emits, checked against
   what the config's mode (zero/fsdp/tp) promises
   (analysis/collective_lint.py),
5. **static cost model** — roofline compute/HBM/ICI step-time
   prediction per compiled program, comm-bound configs flagged
   (analysis/cost_model.py),

merges the findings under the active severity config, and returns a
:class:`~torchpruner_tpu.analysis.findings.LintReport`.  Passes 1–3 are
pure abstract evaluation (an 8B-param mesh preset lints on a laptop CPU
in seconds, zero parameter bytes materialized); passes 4–5 compile the
real programs over abstract avals, bounded by a param budget
(``collective_lint.compile_budget``) so oversized programs degrade to an
info finding instead of a minutes-long host compile.
"""

from __future__ import annotations

import jax.numpy as jnp

from torchpruner_tpu.analysis.findings import LintReport, merge_reports
from torchpruner_tpu.analysis.jaxpr_lint import lint_jaxpr, trace_step
from torchpruner_tpu.analysis.plan_lint import (
    abstract_trees,
    lint_model_plans,
    lint_plan,
)
from torchpruner_tpu.analysis.sharding_lint import lint_sharding
from torchpruner_tpu.utils.config import ExperimentConfig


def _match_plan_targets(model, plans) -> tuple:
    """``(matched_targets, unmatched_count)`` — each explicit plan
    matched back to the graph group that produces it (PrunePlan equality
    against ``plan_for_group``), so the sharding pass can simulate the
    prune the plan actually describes."""
    from torchpruner_tpu.core.graph import pruning_graph
    from torchpruner_tpu.core.pruner import plan_for_group

    by_plan = {}
    for g in pruning_graph(model):
        try:
            by_plan[plan_for_group(model, g)] = g.target
        except Exception:  # noqa: BLE001 — unmatchable group, skip
            continue
    matched = [by_plan[p] for p in plans if p in by_plan]
    return matched, len(plans) - len(matched)


def lint_config(
    cfg: ExperimentConfig,
    *,
    model=None,
    plans=None,
    jaxpr: bool = True,
    collectives: bool = True,
    cost: bool = True,
    host: bool = True,
) -> LintReport:
    """Full tpu-lint run for one config.

    ``model`` may be injected (tests / custom zoos); ``plans`` (explicit
    :class:`~torchpruner_tpu.core.plan.PrunePlan` objects) are linted
    INSTEAD of the graph-derived groups when given — the entry point for
    validating hand-written or deserialized plans.  The sharding pass
    simulates the CONFIG's sweep (its targets/fraction/bucket); with
    explicit ``plans`` it simulates exactly the plans' own targets
    (matched back to their graph groups) under the config mesh.  It is
    skipped only when the plan pass already found errors (a broken plan
    cannot be meaningfully simulated).  ``jaxpr=False`` skips every
    abstract trace of the step — pass 3 AND the jaxpr-collective half
    of pass 4 (with ``jaxpr=True`` they share ONE trace);
    ``collectives=False`` / ``cost=False`` skip the compile-based
    passes (4/5 — the only passes that invoke XLA).  ``host=False``
    skips pass 6, the host-side concurrency/durability lint — a pure
    AST scan of the serving-plane packages that needs neither the
    model nor XLA (``analysis.host_lint``).
    """
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        MODEL_REGISTRY,
        filter_targets,
        make_optimizer,
    )
    from torchpruner_tpu.core.graph import pruning_graph

    if model is None:
        model_fn, _ = MODEL_REGISTRY[cfg.model]
        model = model_fn()

    findings: list = []

    # -- pass 1: plan lint ------------------------------------------------
    if plans is not None:
        params, state = abstract_trees(model)
        for plan in plans:
            findings += lint_plan(plan, params, state)
    else:
        findings += lint_model_plans(model)

    # -- pass 2: sharding lint (mesh configs only; skipped when the plan
    # pass already errored — a broken plan cannot be simulated) -----------
    plan_errors = any(f.severity == "error" for f in findings)
    if cfg.mesh and not plan_errors:
        from torchpruner_tpu.analysis.findings import Finding

        if plans is None:
            targets = filter_targets(
                [g.target for g in pruning_graph(model)], cfg
            )
        else:
            targets, unmatched = _match_plan_targets(model, plans)
            if unmatched:
                findings.append(Finding(
                    "info", "sharding", "sharding/plan-unmatched",
                    "<plans>",
                    f"{unmatched} explicit plan(s) match no current "
                    f"graph group (stale serialization or a custom "
                    f"surgery path) — the sharding pass simulates only "
                    f"the {len(targets)} matched target(s)",
                ))
        fraction = cfg.fraction if cfg.policy == "fraction" else 0.5
        if cfg.policy != "fraction":
            findings.append(Finding(
                "info", "sharding", "sharding/fraction-stand-in",
                "<policy>",
                f"policy {cfg.policy!r} picks drop counts from scores "
                f"at runtime, which abstract evaluation cannot know — "
                f"the sharding pass simulates a fraction={fraction} "
                f"stand-in prune; divisibility findings below describe "
                f"THAT width, not necessarily the runtime one",
            ))
        if targets:
            data = cfg.mesh.get("data", 1)
            cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" \
                else None
            findings += lint_sharding(
                model, dict(cfg.mesh), partition=cfg.partition,
                targets=targets, fraction=fraction, bucket=cfg.bucket,
                tx=make_optimizer(cfg),
                batch_per_chip=max(1, cfg.batch_size // max(1, data)),
                compute_dtype=cdtype, remat=cfg.remat, zero=cfg.zero,
            )

    # -- pass 3: jaxpr hazards --------------------------------------------
    closed_step = None  # pass 3's trace, shared with pass 4's jaxpr half
    closed_site = "train step"
    if jaxpr:
        loss_fn = LOSS_REGISTRY[cfg.loss]
        cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
        train = bool(
            cfg.finetune_epochs or cfg.epochs
            or cfg.experiment in ("train", "train_robustness")
        )
        closed_site = "train step" if train else "eval step"
        closed_step = trace_step(
            model, loss_fn, tx=make_optimizer(cfg) if train else None,
            train=train, compute_dtype=cdtype, remat=cfg.remat,
            lm=cfg.loss == "lm_cross_entropy",
        )
        findings += lint_jaxpr(
            closed_step, compute_dtype=cdtype, site=closed_site,
        )

    # -- passes 4/5: collective contracts + cost model over the REAL
    # compiled programs (the only passes that invoke XLA; param-budgeted
    # inside build_programs) ----------------------------------------------
    if collectives or cost:
        from torchpruner_tpu.analysis import cost_model
        from torchpruner_tpu.analysis.collective_lint import (
            build_programs,
            env_plant,
            lint_collectives,
        )

        if collectives:
            cfindings, records = lint_collectives(
                cfg, model=model, closed=closed_step,
                closed_site=closed_site, trace=jaxpr)
            findings += cfindings
        else:
            records, bfindings = build_programs(
                cfg, model, plant=env_plant())
            findings += bfindings
        if cost:
            preds = cost_model.predict_programs(records)
            findings += cost_model.cost_findings(preds)
            cost_model.record_gauges(preds)

    # -- pass 6: host-side concurrency & durability lint (pure AST over
    # the serving-plane packages; mtime-cached, so preset sweeps pay the
    # parse once) ---------------------------------------------------------
    if host:
        from torchpruner_tpu.analysis.collective_lint import env_plant
        from torchpruner_tpu.analysis import host_lint

        hfindings = host_lint.lint_host(plant=env_plant())
        host_lint.record_gauges(hfindings)
        findings += hfindings

    return merge_reports(cfg.name, findings)


def lint_preset(name: str, smoke: bool = False, **kw) -> LintReport:
    """``lint_config`` over a named preset."""
    from torchpruner_tpu.experiments.presets import get_preset

    return lint_config(get_preset(name, smoke=smoke), **kw)


def plan_preset(name: str, smoke: bool = False, **kw) -> dict:
    """``analysis.planner.plan_auto`` over a named preset — the search
    twin of :func:`lint_preset` (lint answers "is this config sound",
    the planner answers "which config should it be").  Returns the plan
    artifact dict; ``kw`` passes through (``probe_top``,
    ``n_devices``, ``hbm_budget``, ...)."""
    from torchpruner_tpu.analysis.planner import plan_auto
    from torchpruner_tpu.experiments.presets import get_preset

    return plan_auto(get_preset(name, smoke=smoke), **kw)

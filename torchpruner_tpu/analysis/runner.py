"""The tpu-lint driver: run every pass over one experiment config.

``lint_config`` resolves the config's model from the experiment registry
and runs

1. **plan lint** over every prune group the static graph derives
   (analysis/plan_lint.py),
2. **sharding lint** for configs with a mesh — the config's own
   mesh/partition/fraction/bucket, simulated over the config's filtered
   targets (analysis/sharding_lint.py),
3. **jaxpr hazard lint** on the config's train step — its real
   loss/optimizer/compute_dtype/remat (analysis/jaxpr_lint.py),

merges the findings under the active severity config, and returns a
:class:`~torchpruner_tpu.analysis.findings.LintReport`.  Everything is
abstract evaluation: an 8B-param mesh preset lints on a laptop CPU in
seconds, with zero bytes of parameters materialized.
"""

from __future__ import annotations

import jax.numpy as jnp

from torchpruner_tpu.analysis.findings import LintReport, merge_reports
from torchpruner_tpu.analysis.jaxpr_lint import lint_step
from torchpruner_tpu.analysis.plan_lint import (
    abstract_trees,
    lint_model_plans,
    lint_plan,
)
from torchpruner_tpu.analysis.sharding_lint import lint_sharding
from torchpruner_tpu.utils.config import ExperimentConfig


def lint_config(
    cfg: ExperimentConfig,
    *,
    model=None,
    plans=None,
    jaxpr: bool = True,
) -> LintReport:
    """Full tpu-lint run for one config.

    ``model`` may be injected (tests / custom zoos); ``plans`` (explicit
    :class:`~torchpruner_tpu.core.plan.PrunePlan` objects) are linted
    INSTEAD of the graph-derived groups when given — the entry point for
    validating hand-written or deserialized plans.  The sharding pass
    simulates the CONFIG's sweep (its targets/fraction/bucket), so it is
    skipped when explicit ``plans`` are given (its findings would
    describe a different prune) and when the plan pass already found
    errors (a broken plan cannot be meaningfully simulated).
    ``jaxpr=False`` skips the (most expensive) trace pass.
    """
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        MODEL_REGISTRY,
        filter_targets,
        make_optimizer,
    )
    from torchpruner_tpu.core.graph import pruning_graph

    if model is None:
        model_fn, _ = MODEL_REGISTRY[cfg.model]
        model = model_fn()

    findings: list = []

    # -- pass 1: plan lint ------------------------------------------------
    if plans is not None:
        params, state = abstract_trees(model)
        for plan in plans:
            findings += lint_plan(plan, params, state)
    else:
        findings += lint_model_plans(model)

    # -- pass 2: sharding lint (mesh configs only; see docstring for the
    # two skip conditions) ------------------------------------------------
    plan_errors = any(f.severity == "error" for f in findings)
    if cfg.mesh and plans is None and not plan_errors:
        targets = filter_targets(
            [g.target for g in pruning_graph(model)], cfg
        )
        fraction = cfg.fraction if cfg.policy == "fraction" else 0.5
        data = cfg.mesh.get("data", 1)
        cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
        findings += lint_sharding(
            model, dict(cfg.mesh), partition=cfg.partition,
            targets=targets, fraction=fraction, bucket=cfg.bucket,
            tx=make_optimizer(cfg),
            batch_per_chip=max(1, cfg.batch_size // max(1, data)),
            compute_dtype=cdtype, remat=cfg.remat, zero=cfg.zero,
        )

    # -- pass 3: jaxpr hazards --------------------------------------------
    if jaxpr:
        loss_fn = LOSS_REGISTRY[cfg.loss]
        cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
        train = bool(
            cfg.finetune_epochs or cfg.epochs
            or cfg.experiment in ("train", "train_robustness")
        )
        findings += lint_step(
            model, loss_fn, tx=make_optimizer(cfg) if train else None,
            train=train, compute_dtype=cdtype, remat=cfg.remat,
            lm=cfg.loss == "lm_cross_entropy",
        )

    return merge_reports(cfg.name, findings)


def lint_preset(name: str, smoke: bool = False, **kw) -> LintReport:
    """``lint_config`` over a named preset."""
    from torchpruner_tpu.experiments.presets import get_preset

    return lint_config(get_preset(name, smoke=smoke), **kw)

"""tpu-lint — static analysis for prune plans, sharding specs, and jaxpr
hazards.

Design note: every pass is abstract-eval-only, by construction
=================================================================

The failure modes this analyzer hunts share one property: they are all
**decidable from shapes and dtypes alone**, yet in practice they surface
minutes into an expensive pjit run on real chips — a pruned FFN width
that stops dividing the mesh silently replicates 14 GiB of weights onto
every device; a plan whose fan-out is off by the flatten factor raises a
shape error out of ``jnp.take`` with no mention of which slice was
wrong; a weak-typed scalar quietly promotes a bf16 matmul to f32 and
halves MXU throughput.  JAX's abstract interpretation machinery exposes
exactly the information needed to catch all of them up front:

- ``jax.eval_shape`` runs ``model.init`` and ``apply_plan`` over
  ``ShapeDtypeStruct`` trees — the REAL init and the REAL surgery code
  paths, so the shapes the lint validates are the shapes production will
  see, at zero FLOPs and zero bytes of parameters;
- ``jax.sharding.AbstractMesh`` stands in for a device mesh, so the
  production sharding rules (``fsdp_sharding`` / ``tp_sharding``) assign
  the same PartitionSpecs they would on a 64-chip slice — on a laptop;
- ``jax.make_jaxpr`` over abstract arguments yields the exact program
  XLA would compile — every operand dtype, every closed-over constant —
  without a device ever initializing.

Because no pass touches an accelerator, the whole analyzer runs in CI on
CPU in seconds (``python -m torchpruner_tpu --lint <preset>``), as a
pre-flight inside ``apply_plan`` (raising
:class:`~torchpruner_tpu.core.plan.PlanError` on error findings), and as
a library (:func:`lint_config` / :func:`lint_preset`).  Findings are
structured :class:`Finding` records with an error/warning/info split;
per-check severities are re-gradeable through :data:`severity_config`.
"""

from torchpruner_tpu.analysis.findings import (
    Finding,
    LintReport,
    SeverityConfig,
    active_severity,
    merge_reports,
    severity_config,
)
from torchpruner_tpu.analysis.jaxpr_lint import lint_jaxpr, lint_step, trace_step
from torchpruner_tpu.analysis.plan_lint import (
    abstract_trees,
    lint_group,
    lint_model_plans,
    lint_plan,
)
from torchpruner_tpu.analysis.sharding_lint import (
    abstract_mesh,
    lint_sharding,
    simulate_prune,
)
from torchpruner_tpu.analysis.runner import lint_config, lint_preset

__all__ = [
    "Finding", "LintReport", "SeverityConfig", "severity_config",
    "active_severity", "merge_reports",
    "lint_plan", "lint_group", "lint_model_plans", "abstract_trees",
    "lint_sharding", "simulate_prune", "abstract_mesh",
    "lint_jaxpr", "lint_step", "trace_step",
    "lint_config", "lint_preset",
]

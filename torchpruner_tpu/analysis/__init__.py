"""tpu-lint — static analysis for prune plans, sharding specs, and jaxpr
hazards.

Design note: every pass is abstract-eval-only, by construction
=================================================================

The failure modes this analyzer hunts share one property: they are all
**decidable from shapes and dtypes alone**, yet in practice they surface
minutes into an expensive pjit run on real chips — a pruned FFN width
that stops dividing the mesh silently replicates 14 GiB of weights onto
every device; a plan whose fan-out is off by the flatten factor raises a
shape error out of ``jnp.take`` with no mention of which slice was
wrong; a weak-typed scalar quietly promotes a bf16 matmul to f32 and
halves MXU throughput.  JAX's abstract interpretation machinery exposes
exactly the information needed to catch all of them up front:

- ``jax.eval_shape`` runs ``model.init`` and ``apply_plan`` over
  ``ShapeDtypeStruct`` trees — the REAL init and the REAL surgery code
  paths, so the shapes the lint validates are the shapes production will
  see, at zero FLOPs and zero bytes of parameters;
- ``jax.sharding.AbstractMesh`` stands in for a device mesh, so the
  production sharding rules (``fsdp_sharding`` / ``tp_sharding``) assign
  the same PartitionSpecs they would on a 64-chip slice — on a laptop;
- ``jax.make_jaxpr`` over abstract arguments yields the exact program
  XLA would compile — every operand dtype, every closed-over constant —
  without a device ever initializing.

Because no pass touches an accelerator, the whole analyzer runs in CI on
CPU in seconds (``python -m torchpruner_tpu --lint <preset>``), as a
pre-flight inside ``apply_plan`` (raising
:class:`~torchpruner_tpu.core.plan.PlanError` on error findings), and as
a library (:func:`lint_config` / :func:`lint_preset`).  Findings are
structured :class:`Finding` records with an error/warning/info split;
per-check severities are re-gradeable through :data:`severity_config`.

Passes 4 and 5 go one level deeper than abstract evaluation: they
COMPILE the step programs the framework actually runs — over abstract
``ShapeDtypeStruct`` trees, so still zero parameter bytes — and check
the post-partitioning HLO itself.  The collective-contract pass
(analysis/collective_lint.py) extracts every collective the SPMD
partitioner emitted and verifies the communication structure the
configured mode promises (``zero=True`` ⇒ reduce-scatter → sharded
update → all-gather, never a replicated gradient all-reduce; FSDP ⇒
model-axis gathers exist; TP decode ⇒ the KV cache is never
reassembled), plus jaxpr-level deadlock hazards (cond-divergent
collective sequences, collectives over undefined mesh axes).  The cost
pass (analysis/cost_model.py) turns the same compiled programs into
roofline step-time predictions — max(compute, HBM, ICI) from the
executable's own FLOP/byte counts and the extracted wire bytes — that
land as ``predicted_step_ms``/``predicted_comm_ms`` gauges in
``report.json`` and flag comm-bound configs.  Both passes degrade to
info findings (never a host-melting compile) via a param budget
(``collective_lint.compile_budget``).

Pass 6 (analysis/host_lint.py) leaves the compiled program entirely and
lints the HOST side — the threaded serving plane the compiled step is
embedded in.  It is a pure-stdlib AST scan (no jax import, whole
package in ~1 s) for the bug classes the post-review hardening lists
kept re-finding by hand: shared attributes written without the lock
that guards them elsewhere, blocking IO under a held lock, lock-order
cycles, durable artifacts written without ``atomic_write_json``,
non-daemon threads with no shutdown join, and wall-clock/randomness
feeding determinism digests.  Intentional exceptions live in a
committed reason-carrying waiver file
(``results/host_lint_waivers.json``); a waiver matching nothing is
itself an error, so waivers cannot rot.  Standalone entry:
``python -m torchpruner_tpu lint-host [paths]``; default scan surface
is :func:`host_lint_default_paths`.
"""

from torchpruner_tpu.analysis.findings import (
    Finding,
    LintReport,
    SeverityConfig,
    active_severity,
    merge_reports,
    severity_config,
)
from torchpruner_tpu.analysis.jaxpr_lint import lint_jaxpr, lint_step, trace_step
from torchpruner_tpu.analysis.plan_lint import (
    abstract_trees,
    lint_group,
    lint_model_plans,
    lint_plan,
)
from torchpruner_tpu.analysis.sharding_lint import (
    abstract_mesh,
    lint_sharding,
    simulate_prune,
)
from torchpruner_tpu.analysis.collective_lint import (
    build_programs,
    hlo_collectives,
    lint_collective_jaxpr,
    lint_collectives,
)
from torchpruner_tpu.analysis.cost_model import (
    cost_findings,
    device_peaks,
    predict_programs,
    predict_record,
    record_config_predictions,
    record_hbm_prediction,
)
from torchpruner_tpu.analysis.planner import (
    enumerate_candidates,
    format_plan,
    plan_auto,
    probe_candidate,
)
from torchpruner_tpu.analysis.host_lint import (
    host_lint_default_paths,
    lint_host,
    scan_source,
)
from torchpruner_tpu.analysis.runner import (
    lint_config,
    lint_preset,
    plan_preset,
)

__all__ = [
    "Finding", "LintReport", "SeverityConfig", "severity_config",
    "active_severity", "merge_reports",
    "lint_plan", "lint_group", "lint_model_plans", "abstract_trees",
    "lint_sharding", "simulate_prune", "abstract_mesh",
    "lint_jaxpr", "lint_step", "trace_step",
    "hlo_collectives", "lint_collective_jaxpr", "lint_collectives",
    "build_programs",
    "predict_record", "predict_programs", "cost_findings",
    "device_peaks", "record_config_predictions",
    "record_hbm_prediction",
    "plan_auto", "enumerate_candidates", "probe_candidate",
    "format_plan",
    "lint_host", "host_lint_default_paths", "scan_source",
    "lint_config", "lint_preset", "plan_preset",
]

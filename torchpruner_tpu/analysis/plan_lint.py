"""Pass 1 — plan lint: validate PruneGroups/PrunePlans against the real
pytree shapes, entirely abstractly.

The param/state trees a plan is checked against come from
``jax.eval_shape`` over ``model.init`` — no parameter is ever
materialized, so linting an 8B-param model costs milliseconds of shape
arithmetic.  Checks, per slice:

- the pytree path resolves in the named collection (missing optional
  slices — a bias under ``use_bias=False`` — are legitimate and skipped);
- the axis is in range for the resolved array's rank;
- ``fan_out`` divides the axis length (the channels-last flatten map is
  only meaningful on an exact multiple);
- the surviving-unit count implied by the axis (``shape[axis] / fan_out``)
  equals the plan's ``n_units`` — the single check that keeps a
  producer's out-slices, its attached-norm slices, and its consumers'
  in-slices all agreeing on how many units exist;
- no two slices claim the same ``(collection, path, axis)`` — overlapping
  slices would double-take and silently mis-shape.

Group-level lint additionally resolves the group's layer names against
the model spec (unknown layer / unprunable target) before the plan is
even built.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Set, Tuple

import jax

from torchpruner_tpu.analysis.findings import Finding
from torchpruner_tpu.core import layers as L
from torchpruner_tpu.core.plan import Path, PruneGroup, PrunePlan

PASS = "plan"


def path_str(path: Path) -> str:
    return "/".join(str(k) for k in path)


def abstract_trees(model) -> Tuple[Any, Any]:
    """``(params, state)`` as ShapeDtypeStruct pytrees — the shapes a plan
    is validated against, without materializing a single parameter."""
    from torchpruner_tpu.core.segment import init_model

    return jax.eval_shape(
        lambda _k: init_model(model, seed=0), jax.random.PRNGKey(0)
    )


def _resolve(tree, path: Path):
    node = tree
    for k in path:
        node = node[k]
    return node


def lint_plan(plan: PrunePlan, params, state=None) -> List[Finding]:
    """Findings for one resolved plan against params/state trees (concrete
    arrays or ShapeDtypeStructs — only ``.shape`` is read)."""
    findings: List[Finding] = []
    seen: Set[Tuple[str, Tuple[str, ...], int]] = set()
    if plan.n_units <= 0:
        findings.append(Finding(
            "error", PASS, "plan/empty-plan", "<plan>",
            f"plan has n_units={plan.n_units}; nothing can be pruned",
        ))
        return findings

    for s in plan.slices:
        p = path_str(s.path)
        tree = params if s.collection == "params" else state
        if tree is None:
            if not s.optional:
                findings.append(Finding(
                    "error", PASS, "plan/missing-collection", p,
                    f"slice targets collection {s.collection!r}, but no "
                    f"such tree was provided",
                ))
            continue
        try:
            arr = _resolve(tree, s.path)
            shape = tuple(arr.shape)
        except (KeyError, IndexError, TypeError, AttributeError):
            if not s.optional:
                findings.append(Finding(
                    "error", PASS, "plan/missing-path", p,
                    f"path does not resolve in the {s.collection} tree",
                ))
            continue
        if not 0 <= s.axis < len(shape):
            findings.append(Finding(
                "error", PASS, "plan/axis-out-of-range", p,
                f"axis {s.axis} out of range for shape {shape}",
            ))
            continue
        key = (s.collection, tuple(str(k) for k in s.path), s.axis)
        if key in seen:
            findings.append(Finding(
                "error", PASS, "plan/overlapping-slices", p,
                f"two slices claim axis {s.axis} of the same array — "
                f"they would double-slice",
            ))
            continue
        seen.add(key)
        if s.fan_out <= 0 or shape[s.axis] % s.fan_out:
            findings.append(Finding(
                "error", PASS, "plan/fanout-indivisible", p,
                f"fan_out {s.fan_out} does not divide axis {s.axis} of "
                f"length {shape[s.axis]}",
            ))
            continue
        implied = shape[s.axis] // s.fan_out
        if implied != plan.n_units:
            findings.append(Finding(
                "error", PASS, "plan/unit-count-mismatch", p,
                f"axis {s.axis} of length {shape[s.axis]} / fan_out "
                f"{s.fan_out} implies {implied} units, but the plan "
                f"prunes a {plan.n_units}-unit producer",
            ))
    return findings


def lint_group(
    model, group: PruneGroup, params=None, state=None
) -> List[Finding]:
    """Resolve one group's layer names against the model, then lint the
    plan it implies.  ``params``/``state`` default to abstract trees."""
    from torchpruner_tpu.core.pruner import plan_for_group

    findings: List[Finding] = []
    names = [("target", group.target)]
    names += [("attached norm", bn.layer) for bn in group.attached_bn]
    names += [("attached dropout", d) for d in group.attached_dropout]
    names += [("consumer", c.layer) for c in group.consumers]
    resolvable = True
    for role, name in names:
        try:
            model.layer(name)
        except KeyError:
            findings.append(Finding(
                "error", PASS, "plan/unknown-layer", name,
                f"group {role} names a layer the model does not have",
            ))
            resolvable = False
    if resolvable:
        try:
            L.n_units(model.layer(group.target))
        except TypeError:
            findings.append(Finding(
                "error", PASS, "plan/not-prunable", group.target,
                f"group target is a "
                f"{type(model.layer(group.target)).__name__}, which has "
                f"no prunable units",
            ))
            resolvable = False
    if not resolvable:
        return findings

    if params is None:
        params, state = abstract_trees(model)
    try:
        plan = plan_for_group(model, group)
    except (TypeError, KeyError) as e:
        findings.append(Finding(
            "error", PASS, "plan/unresolvable-group", group.target,
            f"group does not resolve to a plan: {e}",
        ))
        return findings
    return lint_plan(plan, params, state)


def lint_model_plans(
    model, targets: Optional[Sequence[str]] = None
) -> List[Finding]:
    """Lint every prune group the static graph derives for ``model``
    (``targets`` restricts to those layer paths) — the per-model half of
    the preset sweep."""
    from torchpruner_tpu.core.graph import pruning_graph

    params, state = abstract_trees(model)
    findings: List[Finding] = []
    wanted = set(targets) if targets is not None else None
    for g in pruning_graph(model, include_output=True):
        if wanted is not None and g.target not in wanted:
            continue
        findings += lint_group(model, g, params, state)
    return findings

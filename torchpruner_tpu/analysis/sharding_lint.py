"""Pass 2 — sharding lint: find the arrays a prune step silently
de-shards, the attention groups it breaks for tensor parallelism, and
what the per-chip HBM budget does — all on an abstract mesh.

Post-prune shapes are recomputed the honest way: the SAME
``apply_plan`` that executes real surgery runs under ``jax.eval_shape``
over abstract param/state trees (ShapeDtypeStructs in, ShapeDtypeStructs
out), and the SAME sharding rules (``fsdp_sharding`` / ``tp_sharding``,
parallel/sharding.py) assign specs over a ``jax.sharding.AbstractMesh``
— so the lint can disagree with production behavior only if production
itself changes.  No device, no TPU, no materialized parameter.

Reported hazards:

- ``sharding/replicated-fallback`` (warning): an array that was sharded
  before the prune whose surviving axis no longer divides the mesh — the
  FSDP rule then silently replicates it onto every chip (the fallback
  documented in parallel/sharding.py), multiplying its HBM cost by the
  mesh size;
- ``sharding/tp-fallback`` (warning): a param the pruning-graph TP rule
  claims whose post-prune shape fails the divisibility check, demoting a
  column/row-parallel matmul to the FSDP rule;
- ``sharding/gqa-indivisible`` (error): a GQA attention layer whose
  surviving query heads no longer spread evenly over their KV heads (or
  no longer divide the mesh axis) — head-axis TP sharding would misalign
  query heads with the KV heads they read;
- ``sharding/hbm-delta`` (info) / ``sharding/hbm-overflow`` (error): the
  per-chip parameter/grad/optimizer/activation byte budget before and
  after the prune (parallel/memory.py), and whether it fits a given HBM
  size.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from torchpruner_tpu.analysis.findings import Finding
from torchpruner_tpu.analysis.plan_lint import abstract_trees
from torchpruner_tpu.core import layers as L

PASS = "sharding"


def abstract_mesh(axes: Dict[str, int]):
    """An ``AbstractMesh`` from ``{axis: size}`` — shape/name metadata
    only, buildable on any host regardless of attached devices (the
    constructor signature moved across JAX releases; support both)."""
    from jax.sharding import AbstractMesh

    try:
        return AbstractMesh(tuple(axes.items()))
    except TypeError:
        return AbstractMesh(tuple(axes.values()), tuple(axes.keys()))


def simulate_prune(
    model, params, state, target: str, drop: Sequence[int]
) -> Tuple[Any, Any, Any]:
    """``(model', params', state')`` after pruning ``drop`` from
    ``target`` — the spec rebuilt in Python, the trees re-shaped through
    ``apply_plan`` under ``eval_shape`` (nothing materialized)."""
    from torchpruner_tpu.core.graph import group_for
    from torchpruner_tpu.core.plan import apply_plan
    from torchpruner_tpu.core.pruner import plan_for_group, pruned_model_spec

    group = group_for(model, target)
    plan = plan_for_group(model, group)
    drop = np.unique(np.asarray(drop, dtype=np.int64).reshape(-1))
    new_params, new_state = jax.eval_shape(
        lambda p, s: apply_plan(plan, drop, p, state=s)[:2], params, state
    )
    return pruned_model_spec(model, group, drop), new_params, new_state


def uniform_drops(
    model, targets: Sequence[str], fraction: float, bucket: int = 1
) -> Dict[str, np.ndarray]:
    """The lowest-index ``fraction`` of each target's units (bucket-
    rounded like the real policy) — the shape template a fraction-policy
    sweep will produce, with the actual (score-dependent) indices replaced
    by a deterministic stand-in.  Shapes, and therefore every check in
    this pass, depend only on the COUNT."""
    from torchpruner_tpu.core.pruner import score_drop_indices

    out = {}
    for t in targets:
        n = L.n_units(model.layer(t))
        out[t] = score_drop_indices(
            np.arange(n, dtype=np.float64), policy="fraction",
            fraction=fraction, bucket=bucket,
        )
    return out


def _shardings(model, params, mesh, partition: str, min_size: int):
    from torchpruner_tpu.parallel.sharding import fsdp_sharding, tp_sharding

    if partition == "tp":
        return tp_sharding(model, params, mesh, min_size=min_size)
    return fsdp_sharding(params, mesh, min_size=min_size)


def _spec_leaves(shardings) -> List[Tuple[str, Any]]:
    from torchpruner_tpu.core.plan import key_path_str

    leaves, _ = jax.tree_util.tree_flatten_with_path(
        shardings, is_leaf=lambda x: hasattr(x, "spec")
    )
    return [(key_path_str(path), sh.spec) for path, sh in leaves]


def lint_sharding(
    model,
    mesh_axes: Dict[str, int],
    *,
    partition: str = "fsdp",
    targets: Optional[Sequence[str]] = None,
    drops: Optional[Dict[str, Sequence[int]]] = None,
    fraction: float = 0.25,
    bucket: int = 1,
    min_size: int = 2 ** 14,
    tx=None,
    batch_per_chip: int = 1,
    param_dtype=jnp.float32,
    compute_dtype=None,
    remat: bool = False,
    hbm_bytes: Optional[int] = None,
    zero: bool = False,
) -> List[Finding]:
    """Findings for pruning ``targets`` of ``model`` (by ``fraction``, or
    explicit per-target ``drops``) under a ``mesh_axes`` mesh.

    ``targets=None`` prunes every group the static graph derives (the
    classifier head excluded), mirroring a full sweep.  ``zero`` counts
    optimizer slots at their ZeRO weight-update placement
    (``ShardedTrainer(zero=True)``) in the HBM budget, so the
    hbm-delta/overflow findings match what the trainer will plan.
    """
    from torchpruner_tpu.core.graph import pruning_graph

    mesh = abstract_mesh(mesh_axes)
    params, state = abstract_trees(model)
    findings: List[Finding] = []

    if targets is None:
        targets = [g.target for g in pruning_graph(model)]
    if drops is None:
        drops = uniform_drops(model, targets, fraction, bucket)

    pre_model, pre_params = model, params
    post_model, post_params, post_state = model, params, state
    for t in targets:
        if not len(np.asarray(drops.get(t, ()), dtype=np.int64)):
            continue
        post_model, post_params, post_state = simulate_prune(
            post_model, post_params, post_state, t, drops[t]
        )

    pre_sh = _shardings(pre_model, pre_params, mesh, partition, min_size)
    post_sh = _shardings(post_model, post_params, mesh, partition, min_size)

    # --- replication fallback: sharded before, replicated after ---------
    from torchpruner_tpu.core.plan import key_path_str

    pre_specs = dict(_spec_leaves(pre_sh))
    post_leaves, _ = jax.tree_util.tree_flatten_with_path(post_params)
    post_shapes = {
        key_path_str(path): tuple(leaf.shape) for path, leaf in post_leaves
    }
    for path, spec in _spec_leaves(post_sh):
        pre = pre_specs.get(path)
        was_sharded = pre is not None and any(a is not None for a in pre)
        now_replicated = all(a is None for a in spec)
        if was_sharded and now_replicated:
            shape = post_shapes.get(path, ())
            findings.append(Finding(
                "warning", PASS, "sharding/replicated-fallback", path,
                f"was sharded {tuple(pre)} pre-prune; post-prune shape "
                f"{shape} divides no mesh axis, so it silently replicates "
                f"onto all {int(np.prod(list(mesh_axes.values())))} chips",
            ))

    # --- TP claims that no longer hold ---------------------------------
    if partition == "tp":
        from torchpruner_tpu.parallel.sharding import tp_specs

        claimed = tp_specs(post_model, mesh)
        actual = dict(_spec_leaves(post_sh))
        for (layer, pname), spec in claimed.items():
            path = f"{layer}/{pname}"
            got = actual.get(path)
            if got is not None and tuple(got) != tuple(spec):
                findings.append(Finding(
                    "warning", PASS, "sharding/tp-fallback", path,
                    f"pruning-graph TP wants {tuple(spec)} but the "
                    f"post-prune shape {post_shapes.get(path, ())} fails "
                    f"the divisibility check — demoted to the FSDP rule",
                ))
        findings += _lint_gqa(post_model, mesh_axes)

    # --- per-chip HBM budget -------------------------------------------
    from torchpruner_tpu.parallel.memory import training_memory

    budgets = []
    for m, p, sh in (
        (pre_model, pre_params, pre_sh),
        (post_model, post_params, post_sh),
    ):
        budgets.append(training_memory(
            m, sh, dict(mesh_axes), tx=tx, batch_per_chip=batch_per_chip,
            param_dtype=param_dtype, compute_dtype=compute_dtype,
            remat=remat, params=p, zero=zero,
        ))
    pre_b, post_b = budgets
    gib = 2.0 ** 30
    findings.append(Finding(
        "info", PASS, "sharding/hbm-delta", "<per-chip>",
        f"{pre_b.total_bytes / gib:.3f} GiB -> "
        f"{post_b.total_bytes / gib:.3f} GiB "
        f"({(post_b.total_bytes - pre_b.total_bytes) / gib:+.3f} GiB); "
        f"post-prune: {post_b.report()}",
    ))
    if hbm_bytes is not None and not post_b.fits(hbm_bytes):
        findings.append(Finding(
            "error", PASS, "sharding/hbm-overflow", "<per-chip>",
            f"post-prune budget {post_b.total_bytes / gib:.2f} GiB exceeds "
            f"85% of {hbm_bytes / gib:.0f} GiB HBM",
        ))
    return findings


def _lint_gqa(model, mesh_axes: Dict[str, int]) -> List[Finding]:
    """GQA hazards of the CURRENT (already-pruned) model spec under
    head-axis tensor parallelism."""
    size = mesh_axes.get("model", 1)
    if size <= 1:
        return []
    findings: List[Finding] = []
    for path, spec in _walk_layers(model.layers, ()):
        if not isinstance(spec, L.MultiHeadAttention):
            continue
        if spec.num_heads % size:
            findings.append(Finding(
                "warning", PASS, "sharding/tp-head-indivisible", path,
                f"{spec.num_heads} query heads do not divide the model "
                f"axis ({size}) — the whole attention group falls back to "
                f"the FSDP rule",
            ))
            continue
        if spec.kv_heads == spec.num_heads and spec.kv_group is None:
            continue  # MHA proper: KV sliced alongside Q, always aligned
        assigned = Counter(spec.head_kv_index())
        # count over ALL kv heads: one left with zero surviving query
        # heads is as broken as an overloaded one
        counts = {k: assigned.get(k, 0) for k in range(spec.kv_heads)}
        uneven = len(set(counts.values())) > 1
        if uneven or spec.kv_heads % size:
            findings.append(Finding(
                "error", PASS, "sharding/gqa-indivisible", path,
                f"surviving query heads map onto KV heads as {counts}"
                + (" (uneven groups)" if uneven else "")
                + f"; head-axis sharding over {size} chips would misalign "
                f"query heads with the KV heads they read — re-prune with "
                f"a KV-group-respecting drop set",
            ))
    return findings


def _walk_layers(layers, prefix) -> List[Tuple[str, Any]]:
    out = []
    for l in layers:
        path = prefix + (l.name,)
        if isinstance(l, L.Residual):
            out += _walk_layers(l.body + l.shortcut, path)
        else:
            out.append(("/".join(path), l))
    return out

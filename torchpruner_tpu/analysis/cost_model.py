"""Pass 5 — static step-time cost model over the real compiled programs.

Roofline prediction per program: compute time from the compiled
executable's own FLOP count (``compiled.cost_analysis()`` — XLA's
analysis of the exact partitioned module, no tracing heuristics) against
the chip's MXU peak, memory time from its bytes-accessed count against
HBM bandwidth (``utils/flops.py`` spec-sheet constants, PR 7's roofline),
and communication time from the collective pass's extracted per-chip
wire bytes (ring-algorithm cost) against per-axis ICI bandwidth
(``utils.flops.peak_ici_bw``).  Predicted step time is
``max(compute, hbm, ici)`` — the roofline ceiling that binds — and a
config whose ICI term wins is flagged comm-bound.

Predictions are *lint-grade*: good enough to rank what binds and to hold
performance claims honest between on-chip capture windows (the capture
script's staged lint leg asserts <30% error on-chip), not a profiler.
On the CPU backend the chip constants don't exist, so deterministic
order-of-magnitude defaults (:data:`~torchpruner_tpu.utils.flops.CPU_COST_DEFAULTS`,
env-overridable) keep smoke predictions stable for the golden
predicted-vs-measured tests.

Wiring: predictions land as obs gauges — ``predicted_step_ms`` /
``predicted_comm_ms`` for the train step, ``predicted_step_ms_decode`` /
``predicted_comm_ms_decode`` for serve's slot-decode program, and
``..._capture`` / ``..._prefill`` siblings — so every ``report.json``
carries them, ``obs diff`` renders prediction-vs-measured drift rows
(``predicted_vs_measured_*`` scalars, obs/report.py), and bench legs
print predicted next to measured.  ``TORCHPRUNER_COST_PREDICT=0``
disables the driver-side recording (it AOT-compiles a twin of the step
program, bounded by the collective pass's param budget).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence

import jax

from torchpruner_tpu.analysis.findings import Finding
from torchpruner_tpu.utils.flops import (
    CPU_COST_DEFAULTS,
    peak_bf16_flops,
    peak_hbm_bw,
    peak_ici_bw,
)

PASS = "cost"

#: gauge names per program: the bare ``predicted_step_ms`` /
#: ``predicted_comm_ms`` pair belongs to the train step (the headline
#: program); every other program gets a suffixed sibling.
_BARE_PROGRAM = "train_step"


@dataclass(frozen=True)
class CostPrediction:
    """One program's roofline prediction (per optimizer step / token)."""

    program: str
    device_kind: str
    flops: float
    hbm_bytes: float
    ici_bytes: float
    compute_ms: float
    hbm_ms: float
    ici_ms: float

    @property
    def step_ms(self) -> float:
        return max(self.compute_ms, self.hbm_ms, self.ici_ms)

    @property
    def comm_ms(self) -> float:
        return self.ici_ms

    @property
    def bound(self) -> str:
        terms = {"compute": self.compute_ms, "hbm": self.hbm_ms,
                 "ici": self.ici_ms}
        return max(terms, key=terms.get)


def _env_float(name: str, default: float) -> float:
    v = os.environ.get(name)
    return float(v) if v else default


def device_peaks(device=None) -> Dict[str, Any]:
    """``{kind, flops, hbm, ici}`` for ``device`` (default: this host's
    first device).  TPU kinds read the spec-sheet tables; the CPU
    backend (and unknown kinds) fall back to the deterministic
    :data:`CPU_COST_DEFAULTS`, each env-overridable
    (TORCHPRUNER_COST_CPU_FLOPS / _BW / _ICI) so a calibrated host can
    pin better numbers without a code change."""
    if device is None:
        device = jax.devices()[0]
    kind = device if isinstance(device, str) else \
        (getattr(device, "device_kind", "") or
         getattr(device, "platform", "cpu"))
    flops = peak_bf16_flops(kind)
    hbm = peak_hbm_bw(kind)
    ici = peak_ici_bw(kind)
    if flops is None or hbm is None:
        kind = f"{kind} (cpu-default cost constants)"
        flops = _env_float("TORCHPRUNER_COST_CPU_FLOPS",
                           CPU_COST_DEFAULTS["flops"])
        hbm = _env_float("TORCHPRUNER_COST_CPU_BW", CPU_COST_DEFAULTS["hbm"])
        ici = _env_float("TORCHPRUNER_COST_CPU_ICI",
                         CPU_COST_DEFAULTS["ici"])
    elif ici is None:
        ici = hbm / 10.0  # ICI is always well under HBM; rough floor
    return {"kind": kind, "flops": float(flops), "hbm": float(hbm),
            "ici": float(ici)}


def cost_analysis_dict(compiled) -> Dict[str, float]:
    """``compiled.cost_analysis()`` normalized to one flat dict (the
    return type changed shape across jax releases)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca or {})


def predict_record(record, device=None) -> Optional[CostPrediction]:
    """Roofline prediction for one
    :class:`~torchpruner_tpu.analysis.collective_lint.ProgramRecord`
    (None when the program didn't compile)."""
    if record.compiled is None:
        return None
    peaks = device_peaks(device)
    ca = cost_analysis_dict(record.compiled)
    flops = float(ca.get("flops") or 0.0)
    hbm_bytes = float(ca.get("bytes accessed") or 0.0)
    ici_bytes = float(sum(c.wire_bytes() for c in record.collectives))
    k = max(1, int(record.steps_per_call))
    return CostPrediction(
        program=record.name,
        device_kind=peaks["kind"],
        flops=flops / k,
        hbm_bytes=hbm_bytes / k,
        ici_bytes=ici_bytes / k,
        compute_ms=1e3 * flops / peaks["flops"] / k,
        hbm_ms=1e3 * hbm_bytes / peaks["hbm"] / k,
        ici_ms=1e3 * ici_bytes / peaks["ici"] / k,
    )


def predict_programs(records: Sequence, device=None) -> List[CostPrediction]:
    return [p for p in (predict_record(r, device) for r in records)
            if p is not None]


def cost_findings(preds: Sequence[CostPrediction]) -> List[Finding]:
    """The cost pass's findings: one ``cost/predicted-step`` info row per
    program (the breakdown the CLI prints), plus ``cost/comm-bound``
    (warning) when a program's ICI term is its roofline ceiling — the
    config buys chips and spends them waiting on the wire."""
    findings: List[Finding] = []
    for p in preds:
        findings.append(Finding(
            "info", PASS, "cost/predicted-step", p.program,
            f"predicted {p.step_ms:.3f} ms/step on {p.device_kind} "
            f"[{p.bound}-bound: compute {p.compute_ms:.3f} ms "
            f"({p.flops / 1e9:.3f} GFLOP), hbm {p.hbm_ms:.3f} ms "
            f"({p.hbm_bytes / 2**20:.2f} MiB), ici {p.ici_ms:.3f} ms "
            f"({p.ici_bytes / 2**20:.2f} MiB wire)]",
        ))
        if p.bound == "ici" and p.ici_ms > 0:
            findings.append(Finding(
                "warning", PASS, "cost/comm-bound", p.program,
                f"predicted comm-bound: ici {p.ici_ms:.3f} ms exceeds "
                f"compute {p.compute_ms:.3f} ms and hbm "
                f"{p.hbm_ms:.3f} ms — the mesh spends its step waiting "
                f"on {p.ici_bytes / 2**20:.2f} MiB of wire traffic "
                f"(grow per-chip batch, shrink the sharded axis, or "
                f"accept and overlap)",
            ))
    return findings


def gauge_names(program: str) -> tuple:
    """``(step_gauge, comm_gauge)`` for one program."""
    if program == _BARE_PROGRAM:
        return "predicted_step_ms", "predicted_comm_ms"
    suffix = program.replace("_step", "")
    return (f"predicted_step_ms_{suffix}", f"predicted_comm_ms_{suffix}")


def record_gauges(preds: Sequence[CostPrediction]) -> None:
    """Predictions → obs gauges (no-op without an active session)."""
    from torchpruner_tpu import obs

    if obs.get() is None:
        return
    for p in preds:
        step_g, comm_g = gauge_names(p.program)
        obs.gauge_set(step_g, p.step_ms,
                      help="static cost-model predicted step time (ms)")
        obs.gauge_set(comm_g, p.comm_ms,
                      help="static cost-model predicted comm time (ms)")


def _predict_enabled() -> bool:
    return os.environ.get("TORCHPRUNER_COST_PREDICT", "1") != "0"


def record_config_predictions(cfg, model=None) -> List[CostPrediction]:
    """Driver-side wiring: build the config's programs, predict, and
    land the ``predicted_*`` gauges in the active obs session — so every
    obs run's ``report.json`` carries prediction next to measurement.

    Best-effort by contract: any failure (unbuildable program, exotic
    config) degrades to no gauges, never to a dead run.  The twin
    compile is bounded by the collective pass's param budget and
    switched off entirely with ``TORCHPRUNER_COST_PREDICT=0``.  Only
    the gauge-carrying programs compile here — the contract-check-only
    twins (``multi_step``, ``decode_tp``) are the lint's business, not
    the run's startup latency."""
    from torchpruner_tpu import obs

    if obs.get() is None or not _predict_enabled():
        return []
    try:
        from torchpruner_tpu.analysis.collective_lint import build_programs

        with obs.span("cost_predict"):
            records, _ = build_programs(
                cfg, model,
                programs=("train_step", "capture", "decode", "prefill"))
            preds = predict_programs(records)
            record_gauges(preds)
            record_hbm_prediction(cfg, model)
        return preds
    except Exception:  # noqa: BLE001 — telemetry must never kill a run
        return []


def record_hbm_prediction(cfg, model=None) -> Optional[int]:
    """The HBM twin of the step-time gauges: predicted per-chip
    watermark (``utils.flops.predicted_hbm_bytes_per_chip``) at the
    config's own placement, landed as the
    ``predicted_hbm_bytes_per_chip`` gauge — so ``obs diff`` carries
    HBM drift (``predicted_vs_measured_hbm_pct`` against the live
    device watermark) the same way it carries step-time drift.  Same
    best-effort contract as every other telemetry hook."""
    import jax.numpy as jnp

    from torchpruner_tpu import obs

    if obs.get() is None:
        return None
    try:
        from torchpruner_tpu.experiments.prune_retrain import (
            MODEL_REGISTRY,
            make_optimizer,
        )
        from torchpruner_tpu.utils.flops import predicted_hbm_bytes_per_chip

        if model is None:
            model = MODEL_REGISTRY[cfg.model][0]()
        data = max(1, (cfg.mesh or {}).get("data", 1))
        hbm = predicted_hbm_bytes_per_chip(
            model, cfg.mesh or {},
            partition=cfg.partition, zero=cfg.zero,
            tx=make_optimizer(cfg),
            batch_per_chip=max(
                1, cfg.batch_size // data // max(1, cfg.accum_steps)),
            compute_dtype=jnp.bfloat16
            if cfg.compute_dtype == "bfloat16" else None,
            remat=cfg.remat,
        )
        obs.gauge_set("predicted_hbm_bytes_per_chip", hbm,
                      help="static cost-model predicted per-chip HBM "
                           "watermark (bytes)")
        return int(hbm)
    except Exception:  # noqa: BLE001
        return None


def predict_decode(model, *, n_slots: int, max_len: int,
                   cache_dtype=None,
                   device=None) -> Optional[CostPrediction]:
    """Prediction for the slot-decode step at an explicit geometry
    (slots × max_len × cache dtype) — the serve engine's program shape.
    Compiles a twin of ``generate.make_slot_decode_step`` over abstract
    avals; None above the param budget.  Used by the serve engine's
    gauge recording and the bench decode leg's predicted-vs-measured
    row."""
    import jax.numpy as jnp

    from torchpruner_tpu.analysis.collective_lint import (
        ProgramRecord,
        _tree_bytes,
        _tree_param_count,
        compile_budget,
        hlo_collectives,
    )
    from torchpruner_tpu.analysis.plan_lint import abstract_trees
    from torchpruner_tpu.generate import init_cache, make_slot_decode_step

    params, _ = abstract_trees(model)
    if _tree_param_count(params) > compile_budget():
        return None
    cache_dtype = jnp.float32 if cache_dtype is None else cache_dtype
    cache = jax.eval_shape(
        lambda: init_cache(model, n_slots, max_len, cache_dtype))
    tok = jax.ShapeDtypeStruct((n_slots, 1), jnp.int32)
    pos = jax.ShapeDtypeStruct((n_slots,), jnp.int32)
    compiled = make_slot_decode_step(model).lower(
        params, cache, tok, pos).compile()
    rec = ProgramRecord(
        name="decode", compiled=compiled,
        collectives=tuple(hlo_collectives(compiled, None)),
        param_bytes=_tree_bytes(params),
        meta={"slots": n_slots, "max_len": max_len})
    return predict_record(rec, device)


def predict_train_step(model, tx, loss_fn, *, batch: int,
                       compute_dtype=None, accum_steps: int = 1,
                       device=None) -> Optional[CostPrediction]:
    """Prediction for the single-device train step at an explicit batch
    — the bench train legs' predicted-vs-measured row.  None above the
    param budget."""
    import jax.numpy as jnp

    from torchpruner_tpu.analysis.collective_lint import (
        ProgramRecord,
        _tree_bytes,
        _tree_param_count,
        compile_budget,
        hlo_collectives,
    )
    from torchpruner_tpu.analysis.plan_lint import abstract_trees
    from torchpruner_tpu.train.loop import make_loss_closure, make_step_body

    params, state = abstract_trees(model)
    if _tree_param_count(params) > compile_budget():
        return None
    opt = jax.eval_shape(tx.init, params)
    step = jax.jit(make_step_body(
        make_loss_closure(model, loss_fn, compute_dtype, False),
        tx, max(1, accum_steps)))
    x = jax.eval_shape(lambda: model.example_input(batch=batch))
    lm = getattr(model, "input_dtype", "").startswith("int")
    y = x if lm else jax.ShapeDtypeStruct((batch,), jnp.int32)
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    compiled = step.lower(params, state, opt, x, y, rng).compile()
    rec = ProgramRecord(
        name="train_step", compiled=compiled,
        collectives=tuple(hlo_collectives(compiled, None)),
        param_bytes=_tree_bytes(params), meta={"batch": batch})
    return predict_record(rec, device)


def record_decode_prediction(model, *, n_slots: int, max_len: int,
                             cache_dtype=None) -> Optional[CostPrediction]:
    """Serve-side wiring: predict the slot-decode step at the ENGINE's
    real geometry (slots × max_len × cache dtype) and land the
    ``predicted_step_ms_decode`` / ``predicted_comm_ms_decode`` gauges.
    Same best-effort/budget/off-switch contract as
    :func:`record_config_predictions`."""
    from torchpruner_tpu import obs

    if obs.get() is None or not _predict_enabled():
        return None
    try:
        with obs.span("cost_predict", program="decode"):
            pred = predict_decode(model, n_slots=n_slots, max_len=max_len,
                                  cache_dtype=cache_dtype)
            if pred is not None:
                record_gauges([pred])
        return pred
    except Exception:  # noqa: BLE001
        return None

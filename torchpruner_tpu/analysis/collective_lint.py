"""Pass 4 — collective-contract lint: extract every collective the
compiler ACTUALLY emits for the step programs the framework runs, and
check the communication structure the configured mode promises.

Passes 1–3 reason about plans, shardings, and the traced jaxpr; nothing
there verifies what the SPMD partitioner does with them.  That gap is
exactly where ZeRO-class regressions live: drop one
``with_sharding_constraint`` under a refactor and ``zero=True`` quietly
reverts to a replicated all-reduce + replicated update while every
numeric test still passes (the update is mathematically identical — only
the HBM and the wire traffic changed).  This pass closes the gap by
compiling the REAL programs — the shared trainer factories
(``parallel.train.make_sharded_train_step`` / ``make_sharded_multi_step``
over the shared :func:`~torchpruner_tpu.parallel.train.plan_placements`
planner), the one-pass capture program (``core.segment.capture_fn``),
and the decode/prefill programs (``generate`` / ``serve.engine``) — over
abstract ``ShapeDtypeStruct`` trees (zero parameters materialized) and
walking the post-partitioning HLO text for ``all-reduce`` /
``all-gather`` / ``reduce-scatter`` / ``collective-permute`` /
``all-to-all`` ops, with byte counts from their shapes and mesh axes
recovered from their replica groups.

Checked contracts:

- ``collective/zero-replicated-allreduce`` (error): a ``zero=True``
  train program whose gradients take a full all-reduce over the data
  axis with NO sharded-update evidence — no reduce-scatter and no
  param-scale all-gather over the data axis.  (The CPU backend lowers
  reduce-scatter as all-reduce + dynamic-slice, so the robust update-
  domain signal is the param all-gather; a true reduce-scatter — what
  TPU emits — counts as evidence too.)
- ``collective/fsdp-missing-gather`` (error): parameters PLANNED onto
  the model axis but a compiled program containing no model-axis
  collective at all — the sharding specs were dropped on the floor
  (e.g. ``in_shardings`` lost under a refactor).
- ``collective/tp-kv-allgather`` (error): a TP decode program that
  all-gathers KV-cache-scale tensors over the model axis — decode's
  memory-bound inner loop must stream the LOCAL cache shard, never
  reassemble it.
- ``collective/branch-divergence`` (error): ``lax.cond`` branches whose
  collective sequences differ — on a real mesh one shard taking the
  psum-branch while another takes the empty branch is a deadlock.
- ``collective/unknown-axis`` (error): a collective naming a mesh axis
  the config's mesh does not define (shard_map regions included).
- ``collective/replication-leak`` (warning): arrays above a size
  threshold the mode was supposed to shard but that stay replicated —
  ZeRO opt-state slots whose dims stopped dividing the data axis, and
  TP decode cache entries whose head axis does not divide the model
  axis.
- ``collective/mesh-downscaled`` / ``collective/skipped`` (info): the
  pass compiled over fewer devices than the config's mesh (the axis
  STRUCTURE is preserved, so the contract checks still bind), or could
  not run at all (single device / program too large for this host —
  raise ``TORCHPRUNER_LINT_COMPILE_BUDGET`` or run on-chip).
"""

from __future__ import annotations

import re
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from torchpruner_tpu.analysis.findings import Finding

PASS = "collective"

#: HLO collective op names this pass extracts (async ``-start`` variants
#: are normalized onto the same kind; ``-done`` ops carry no shape work).
KINDS = ("all-reduce", "all-gather", "reduce-scatter", "collective-permute",
         "all-to-all")

#: jaxpr-level collective primitives (explicit collectives inside
#: shard_map regions — ring/sp/ulysses — and anything hand-written).
_JAXPR_COLLECTIVES = {
    "psum", "pmax", "pmin", "ppermute", "pshuffle", "all_gather",
    "all_to_all", "psum_scatter", "reduce_scatter", "axis_index",
}
#: of those, the ones that synchronize (axis_index is local — it names
#: an axis but never blocks, so it is axis-checked but not a deadlock
#: participant)
_SYNCING = _JAXPR_COLLECTIVES - {"axis_index"}

#: params above this many params skip the compile-based half of the pass
#: on this host (the jaxpr half still runs) — an 8B-param program is a
#: minutes-long CPU compile; lint it on-chip (capture_tpu.sh's lint leg)
#: or raise TORCHPRUNER_LINT_COMPILE_BUDGET.
COMPILE_PARAM_BUDGET = int(5e7)


def compile_budget() -> int:
    """The active compile budget (params), env-overridable."""
    import os

    v = os.environ.get("TORCHPRUNER_LINT_COMPILE_BUDGET")
    return int(float(v)) if v else COMPILE_PARAM_BUDGET

_DTYPE_BYTES = {
    "pred": 1, "s4": 1, "u4": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2,
    "f16": 2, "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8,
    "u64": 8, "f64": 8, "c64": 8, "c128": 16, "f8e4m3fn": 1,
    "f8e5m2": 1,
}

_SHAPE_RE = re.compile(r"([a-z0-9]+)\[([0-9,]*)\]")
_COLL_RE = re.compile(
    r"=\s*(?P<shape>\([^)]*\)|[a-z0-9]+\[[0-9,]*\](?:\{[^}]*\})?)\s*"
    r"(?P<kind>" + "|".join(KINDS) + r")(?:-start)?\("
)
_GROUPS_RE = re.compile(
    r"replica_groups=(?:(?P<explicit>\{\{[0-9,{} ]*\}\})"
    r"|(?P<iota>\[[0-9,]+\](?:<=\[[0-9,]+\])?(?:T\([0-9,]+\))?))"
)
_IOTA_RE = re.compile(
    r"\[(?P<dims>[0-9,]+)\](?:<=\[(?P<reshape>[0-9,]+)\])?"
    r"(?:T\((?P<perm>[0-9,]+)\))?"
)


@dataclass(frozen=True)
class Collective:
    """One extracted collective: HLO ``kind``, the op's result byte
    count (local, post-partitioning shapes — what this chip holds),
    participant ``group_size``, and the mesh ``axes`` the replica groups
    span (None when the groups match no single axis combination, e.g.
    hierarchical groups on an unknown layout)."""

    kind: str
    bytes: int
    group_size: int
    axes: Optional[Tuple[str, ...]]

    def wire_bytes(self) -> float:
        """Approximate per-chip wire traffic: ring-algorithm cost in
        units of the op's LOCAL result bytes."""
        n = max(1, self.group_size)
        if self.kind == "all-reduce":
            return 2.0 * self.bytes * (n - 1) / n
        if self.kind == "all-gather":
            return self.bytes * (n - 1) / n
        if self.kind == "reduce-scatter":
            # result is the 1/n shard; the full operand transits the ring
            return float(self.bytes) * (n - 1)
        if self.kind == "all-to-all":
            return self.bytes * (n - 1) / n
        return float(self.bytes)  # collective-permute: one hop


def _shape_bytes(shape_text: str) -> int:
    """Bytes of an HLO shape string (``f32[8,512]{1,0}`` or a tuple)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(shape_text):
        size = _DTYPE_BYTES.get(dtype)
        if size is None:
            continue
        n = 1
        for d in dims.split(","):
            if d:
                n *= int(d)
        total += n * size
    return total


def _parse_groups(line: str) -> Optional[List[List[int]]]:
    m = _GROUPS_RE.search(line)
    if not m:
        return None
    if m.group("explicit"):
        return [
            [int(x) for x in g.split(",") if x.strip()]
            for g in re.findall(r"\{([0-9, ]*)\}", m.group("explicit"))
            if g.strip()
        ]
    im = _IOTA_RE.match(m.group("iota"))
    if not im:
        return None
    dims = [int(x) for x in im.group("dims").split(",")]
    n = int(np.prod(dims))
    ids = np.arange(n)
    if im.group("reshape"):
        rdims = [int(x) for x in im.group("reshape").split(",")]
        ids = ids.reshape(rdims)
        if im.group("perm"):
            ids = ids.transpose([int(x) for x in im.group("perm").split(",")])
        ids = ids.reshape(-1)
    return ids.reshape(dims).tolist()


def _axes_of_groups(groups: Optional[List[List[int]]],
                    mesh) -> Optional[Tuple[str, ...]]:
    """The mesh axes a replica-group list spans: the set of axes whose
    coordinate varies within a group, when every group agrees."""
    if not groups or mesh is None:
        return None
    coords: Dict[int, Tuple[int, ...]] = {}
    for idx, dev in np.ndenumerate(mesh.devices):
        coords[int(dev.id)] = tuple(int(i) for i in idx)
    names = tuple(mesh.axis_names)
    spans = set()
    for g in groups:
        cs = [coords.get(i) for i in g]
        if any(c is None for c in cs):
            return None
        varying = tuple(
            names[d] for d in range(len(names))
            if len({c[d] for c in cs}) > 1
        )
        spans.add(varying)
    if len(spans) == 1:
        return spans.pop()
    return None


def hlo_collectives(compiled, mesh=None) -> List[Collective]:
    """Every collective in a compiled program's optimized HLO, with
    byte counts and (when ``mesh`` is given) mesh-axis attribution."""
    out: List[Collective] = []
    for line in compiled.as_text().splitlines():
        m = _COLL_RE.search(line)
        if not m:
            continue
        groups = _parse_groups(line)
        out.append(Collective(
            kind=m.group("kind"),
            bytes=_shape_bytes(m.group("shape")),
            group_size=max((len(g) for g in groups), default=1)
            if groups else 1,
            axes=_axes_of_groups(groups, mesh),
        ))
    return out


# ---------------------------------------------------------------------------
# jaxpr half: explicit collectives (shard_map regions), deadlock hazards
# ---------------------------------------------------------------------------


def _norm_axes(axis_name) -> Tuple[str, ...]:
    if axis_name is None:
        return ()
    if isinstance(axis_name, (tuple, list)):
        return tuple(str(a) for a in axis_name)
    return (str(axis_name),)


def _eqn_axes(eqn) -> Tuple[str, ...]:
    for key in ("axis_name", "axes", "axis_index_groups_axis"):
        if key in eqn.params:
            v = eqn.params[key]
            if key == "axes" and eqn.primitive.name in ("psum", "pmax",
                                                        "pmin"):
                return _norm_axes(v)
            if key == "axis_name":
                return _norm_axes(v)
    return ()


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, jax.core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, jax.core.Jaxpr):
                yield x


def _collective_signature(jaxpr) -> Tuple[Tuple[str, Tuple[str, ...]], ...]:
    """Ordered (prim, axes) sequence of SYNCING collectives in a jaxpr,
    recursing through non-branching sub-jaxprs (cond branches are the
    divergence points and are compared, not flattened)."""
    sig: List[Tuple[str, Tuple[str, ...]]] = []
    for eqn in jaxpr.eqns:
        name = eqn.primitive.name
        if name in _SYNCING:
            sig.append((name, _eqn_axes(eqn)))
        if name == "cond":
            # a cond whose branches agree contributes its (common)
            # signature; divergence is reported separately
            branches = [
                _collective_signature(b.jaxpr)
                for b in eqn.params.get("branches", ())
            ]
            if branches and all(b == branches[0] for b in branches):
                sig.extend(branches[0])
            else:
                sig.append(("cond<divergent>", ()))
            continue
        for sub in _sub_jaxprs(eqn):
            sig.extend(_collective_signature(sub))
    return tuple(sig)


def lint_collective_jaxpr(closed, mesh_axes: Dict[str, int],
                          site: str = "<program>") -> List[Finding]:
    """jaxpr-level hazards: collectives over axes absent from the mesh,
    and ``cond`` branches with diverging collective sequences (one shard
    enters the collective, its neighbour doesn't — deadlock on a real
    mesh, silent wrong answer on one host)."""
    findings: List[Finding] = []
    seen = set()

    def once(check, key, severity, message):
        if (check, key) not in seen:
            seen.add((check, key))
            findings.append(Finding(severity, PASS, check, site, message))

    def walk(jaxpr):
        for eqn in jaxpr.eqns:
            name = eqn.primitive.name
            if name in _JAXPR_COLLECTIVES:
                for ax in _eqn_axes(eqn):
                    if ax not in mesh_axes:
                        once(
                            "collective/unknown-axis", f"{name}:{ax}",
                            "error",
                            f"{name} over axis {ax!r}, which the config "
                            f"mesh {dict(mesh_axes)} does not define — "
                            f"this program cannot run on the configured "
                            f"mesh",
                        )
            if name == "cond":
                branches = eqn.params.get("branches", ())
                sigs = [_collective_signature(b.jaxpr) for b in branches]
                if sigs and any(s != sigs[0] for s in sigs):
                    once(
                        "collective/branch-divergence",
                        str(sigs), "error",
                        f"cond branches have diverging collective "
                        f"sequences {list(sigs)} — shards taking "
                        f"different branches deadlock on a real mesh",
                    )
            for sub in _sub_jaxprs(eqn):
                walk(sub)

    walk(closed.jaxpr)
    return findings


# ---------------------------------------------------------------------------
# contract checks over extracted collectives
# ---------------------------------------------------------------------------


def _sum_bytes(colls: Sequence[Collective], kind: str, axis: str,
               min_bytes: int = 0) -> int:
    return sum(
        c.bytes for c in colls
        if c.kind == kind and c.axes is not None and axis in c.axes
        and c.bytes >= min_bytes
    )


def check_zero_contract(colls: Sequence[Collective], *,
                        param_bytes: int, data_axis: str = "data",
                        site: str = "train step") -> List[Finding]:
    """``zero=True`` must compile to reduce-scatter → sharded update →
    all-gather.  Evidence of the sharded update domain: a true
    reduce-scatter over the data axis (TPU lowering) or param-scale
    all-gathers over the data axis (the CPU lowering decomposes the
    reduce-scatter into all-reduce + dynamic-slice, but the update-domain
    param gather survives either way).  A param-scale all-reduce over
    data WITHOUT that evidence is the replicated-all-reduce regression.
    """
    findings: List[Finding] = []
    big = max(4096, param_bytes // 20)  # ignore loss/grad-norm scalars
    rs = _sum_bytes(colls, "reduce-scatter", data_axis)
    gather = _sum_bytes(colls, "all-gather", data_axis)
    allreduce = _sum_bytes(colls, "all-reduce", data_axis, min_bytes=big)
    evidence = rs + gather
    if evidence < max(1, param_bytes // 10) and allreduce:
        findings.append(Finding(
            "error", PASS, "collective/zero-replicated-allreduce", site,
            f"zero=True but the compiled program all-reduces "
            f"{allreduce / 2**20:.2f} MiB of gradients over the "
            f"{data_axis!r} axis with no sharded-update evidence "
            f"(reduce-scatter bytes {rs}, update-domain all-gather "
            f"bytes {gather}, params {param_bytes / 2**20:.2f} MiB) — "
            f"the ZeRO weight-update transform is not in this program; "
            f"optimizer state and the update replicate on every chip",
        ))
    elif evidence < max(1, param_bytes // 10) and not allreduce:
        findings.append(Finding(
            "warning", PASS, "collective/zero-no-collectives", site,
            f"zero=True but the compiled program shows neither a "
            f"gradient reduction nor a sharded-update gather over "
            f"{data_axis!r} — the data axis may not be in this program "
            f"at all",
        ))
    return findings


def check_fsdp_contract(colls: Sequence[Collective], *,
                        sharded_paths: Sequence[str],
                        model_axis: str = "model",
                        site: str = "train step") -> List[Finding]:
    """Params planned onto the model axis ⇒ the program must communicate
    over it (all-gather of params/activations or partial-sum
    all-reduce); zero model-axis collectives mean the placement was
    dropped and every chip holds full arrays."""
    if not sharded_paths:
        return []
    over_model = [
        c for c in colls if c.axes is not None and model_axis in c.axes
    ]
    if over_model:
        return []
    k = len(sharded_paths)
    sample = ", ".join(list(sharded_paths)[:4]) + ("…" if k > 4 else "")
    return [Finding(
        "error", PASS, "collective/fsdp-missing-gather", site,
        f"{k} param(s) are planned sharded over {model_axis!r} "
        f"({sample}) but the compiled program contains NO collective "
        f"over that axis — the sharding specs were dropped (params "
        f"effectively replicated, or the program was compiled without "
        f"its in_shardings)",
    )]


def check_tp_decode_contract(colls: Sequence[Collective], *,
                             cache_entry_bytes: int,
                             model_axis: str = "model",
                             site: str = "decode step") -> List[Finding]:
    """TP decode must stream the LOCAL KV shard: an all-gather at cache
    scale over the model axis reassembles the cache every token."""
    if cache_entry_bytes <= 0:
        return []
    threshold = max(4096, cache_entry_bytes // 2)
    offenders = [
        c for c in colls
        if c.kind == "all-gather" and c.axes is not None
        and model_axis in c.axes and c.bytes >= threshold
    ]
    if not offenders:
        return []
    total = sum(c.bytes for c in offenders)
    return [Finding(
        "error", PASS, "collective/tp-kv-allgather", site,
        f"decode all-gathers {total / 2**20:.2f} MiB of KV-cache-scale "
        f"tensors over {model_axis!r} every token ({len(offenders)} "
        f"op(s) ≥ {threshold} bytes; one layer's cache entry is "
        f"{cache_entry_bytes / 2**20:.2f} MiB) — the memory-bound "
        f"decode loop must read only the local head shard (shard the "
        f"cache's head axis, or keep KV heads divisible by the mesh)",
    )]


def replication_leaks(placements, *, axis: str, min_bytes: int = 2 ** 20,
                      what: str = "optimizer state",
                      site: str = "train step") -> List[Finding]:
    """Leaves of a placement tree ≥ ``min_bytes`` whose spec does not
    use ``axis`` — the arrays a mode promised to shard but left
    replicated over it (e.g. ZeRO slots whose pruned dims stopped
    dividing the data axis)."""
    from torchpruner_tpu.core.plan import key_path_str

    findings: List[Finding] = []
    flat, _ = jax.tree_util.tree_flatten_with_path(
        placements, is_leaf=lambda x: isinstance(x, tuple)
        and len(x) == 2 and hasattr(x[1], "spec")
    )
    for path, (leaf, sh) in flat:
        shape = np.shape(leaf)
        nbytes = int(np.prod(shape or (1,))) * jnp.dtype(
            getattr(leaf, "dtype", jnp.float32)).itemsize
        if nbytes < min_bytes:
            continue
        used = set()
        for e in sh.spec:
            for a in (e if isinstance(e, tuple) else (e,)):
                if a is not None:
                    used.add(a)
        if axis not in used:
            findings.append(Finding(
                "warning", PASS, "collective/replication-leak",
                key_path_str(path),
                f"{what} {shape} ({nbytes / 2**20:.2f} MiB) stays "
                f"replicated over the {axis!r} axis — above the "
                f"{min_bytes} B threshold, this multiplies HBM by the "
                f"axis size (no dim divides it; re-bucket the prune or "
                f"accept the cost explicitly)",
            ))
    return findings


# ---------------------------------------------------------------------------
# program builders: compile the REAL step programs over abstract avals
# ---------------------------------------------------------------------------


@dataclass
class ProgramRecord:
    """One compiled step program plus everything the contract and cost
    passes need: the extracted collectives, the (possibly downscaled)
    mesh it compiled over, and placement trees for the leak checks.
    ``steps_per_call`` normalizes multi-step programs back to one
    optimizer step."""

    name: str
    compiled: Any
    collectives: Tuple[Collective, ...]
    mesh: Any = None
    mesh_axes: Dict[str, int] = None
    downscaled: bool = False
    param_bytes: int = 0
    steps_per_call: int = 1
    meta: Dict[str, Any] = None


def downscale_axes(axes: Dict[str, int],
                   n_devices: int) -> Optional[Dict[str, int]]:
    """The config's mesh shrunk onto this host's devices with the axis
    STRUCTURE preserved: every >1 axis stays >= 2 (its collectives still
    exist in the lowering, over the same axis names), sizes grow back
    toward the config greedily while they fit.  None when even the
    minimal structure does not fit (e.g. a single-device host)."""
    sizes = {a: (2 if s > 1 else 1) for a, s in axes.items()}
    prod = int(np.prod(list(sizes.values()))) if sizes else 1
    if prod > n_devices:
        return None
    grew = True
    while grew:
        grew = False
        for a in sizes:
            if sizes[a] * 2 <= axes[a] and prod * 2 <= n_devices:
                sizes[a] *= 2
                prod *= 2
                grew = True
    return sizes


def build_mesh(axes: Dict[str, int]):
    """A real (not abstract) Mesh over this host's devices — the
    collective pass compiles actual SPMD programs, so it needs actual
    devices (CPU ones from --xla_force_host_platform_device_count are
    fine; the partitioner emits the same collectives)."""
    from jax.sharding import Mesh

    n = int(np.prod(list(axes.values()))) if axes else 1
    devs = np.array(jax.devices()[:n]).reshape(
        [axes[a] for a in axes] or [1])
    return Mesh(devs, tuple(axes) or ("data",))


def _tree_param_count(tree) -> int:
    return sum(int(np.prod(l.shape or (1,)))
               for l in jax.tree_util.tree_leaves(tree))


def _tree_bytes(tree) -> int:
    return sum(
        int(np.prod(l.shape or (1,))) * jnp.dtype(l.dtype).itemsize
        for l in jax.tree_util.tree_leaves(tree)
    )


def _spec_paths_on_axis(shardings, axis: str) -> List[str]:
    """Pytree paths whose NamedSharding spec uses ``axis``."""
    from torchpruner_tpu.core.plan import key_path_str

    flat, _ = jax.tree_util.tree_flatten_with_path(
        shardings, is_leaf=lambda x: hasattr(x, "spec"))
    out = []
    for path, sh in flat:
        used = set()
        for e in sh.spec:
            for a in (e if isinstance(e, tuple) else (e,)):
                if a is not None:
                    used.add(a)
        if axis in used:
            out.append(key_path_str(path))
    return out


def _round_up(n: int, multiple: int) -> int:
    return ((n + multiple - 1) // multiple) * multiple


def env_plant() -> Optional[str]:
    """The CI drill's planted-hazard env — read ONLY by the lint
    drivers (:func:`lint_collectives`, ``runner.lint_config``), never
    by the trainer or the telemetry cost predictor: a stale shell
    export must not silently skew a real run's ``predicted_*`` gauges."""
    import os

    return os.environ.get("TORCHPRUNER_LINT_PLANT")


def build_programs(cfg, model=None, programs=None, plant=None):
    """``(records, findings)`` — the step programs this config actually
    runs, compiled over abstract avals (zero parameters materialized):

    - ``train_step``: the Trainer/ShardedTrainer step with the config's
      real partition/zero/remat/accum/compute_dtype, placed by the SAME
      :func:`~torchpruner_tpu.parallel.train.plan_placements` the
      trainer uses (mesh configs compile over a structure-preserving
      downscale of the config mesh onto this host's devices);
    - ``capture``: the one-pass sweep capture program
      (``core.segment.capture_fn``) for robustness experiments;
    - ``decode`` / ``prefill``: serve's slot-decode and bucketed prefill
      programs for attention LMs (plus a TP-placed decode variant when
      the config asks for tensor parallelism — the program the KV-cache
      contract check inspects).

    Builds are fault-isolated: a program that fails to build degrades to
    a ``collective/build-failed`` warning instead of killing the pass.

    ``programs`` (an iterable of record names) restricts which programs
    compile — the cost-model's driver wiring passes the gauge-carrying
    subset so a run's telemetry never pays for the contract-check-only
    twins (``multi_step``, ``decode_tp``).  ``None`` builds everything.
    ``plant`` feeds the planted-hazard drill into the placement planner;
    only the lint drivers pass it (via :func:`env_plant`) — telemetry
    callers leave it ``None`` so the env cannot touch real runs.
    """
    from torchpruner_tpu.analysis.plan_lint import abstract_trees
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        MODEL_REGISTRY,
        make_optimizer,
    )

    findings: List[Finding] = []
    records: List[ProgramRecord] = []
    want = None if programs is None else set(programs)

    def _want(name: str) -> bool:
        return want is None or name in want

    if model is None:
        model_fn, _ = MODEL_REGISTRY[cfg.model]
        model = model_fn()

    params, state = abstract_trees(model)
    n_params = _tree_param_count(params)
    budget = compile_budget()
    if n_params > budget:
        findings.append(Finding(
            "info", PASS, "collective/skipped", "<programs>",
            f"{n_params / 1e6:.0f}M params exceed the "
            f"{budget / 1e6:.0f}M-param compile budget on this host — "
            f"the compile-based collective/cost passes are skipped "
            f"(raise TORCHPRUNER_LINT_COMPILE_BUDGET or lint on-chip "
            f"via scripts/capture_tpu.sh's lint leg)",
        ))
        return records, findings

    tx = make_optimizer(cfg)
    loss_fn = LOSS_REGISTRY[cfg.loss]
    opt = jax.eval_shape(tx.init, params)
    cdtype = jnp.bfloat16 if cfg.compute_dtype == "bfloat16" else None
    lm = cfg.loss == "lm_cross_entropy"
    param_bytes = _tree_bytes(params)
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    mesh = None
    axes_c: Dict[str, int] = {}
    downscaled = False
    if cfg.mesh:
        axes_c = downscale_axes(dict(cfg.mesh), len(jax.devices()))
        if axes_c is None:
            findings.append(Finding(
                "info", PASS, "collective/skipped", "<mesh>",
                f"config mesh {dict(cfg.mesh)} needs at least "
                f"{int(np.prod([2 if s > 1 else 1 for s in cfg.mesh.values()]))} "
                f"devices to preserve its axis structure; this host has "
                f"{len(jax.devices())} — run under "
                f"XLA_FLAGS=--xla_force_host_platform_device_count=8 "
                f"(CI does) or on-chip",
            ))
            # the mesh programs degrade to skipped, but the meshless
            # ones (decode/prefill/capture) must still build below
            axes_c = {}
        else:
            downscaled = axes_c != dict(cfg.mesh)
            if downscaled:
                findings.append(Finding(
                    "info", PASS, "collective/mesh-downscaled", "<mesh>",
                    f"compiling over {axes_c} instead of the config's "
                    f"{dict(cfg.mesh)} ({len(jax.devices())} local "
                    f"device(s)) — axis structure is preserved, so the "
                    f"contract checks still bind; byte counts and cost "
                    f"predictions describe the downscaled program",
                ))
            mesh = build_mesh(axes_c)

    # -- train step --------------------------------------------------------
    accum = max(1, cfg.accum_steps)
    mesh_parts = None  # (ps, ss, os_, zs, B) when the mesh build succeeds
    if _want("train_step"):
        try:
            if mesh is not None:
                from torchpruner_tpu.parallel.train import (
                    make_sharded_train_step,
                    plan_placements,
                )

                data_c = axes_c.get("data", 1)
                per_chip = max(1, cfg.batch_size
                               // max(1, dict(cfg.mesh).get("data", 1)))
                B = _round_up(per_chip * data_c, accum * data_c)
                ps, ss, os_, zs = plan_placements(
                    model, params, state, opt, tx, mesh,
                    partition=cfg.partition, zero=cfg.zero, plant=plant)
                step = make_sharded_train_step(
                    model, tx, loss_fn, mesh, ps, ss, os_,
                    compute_dtype=cdtype, remat=cfg.remat,
                    accum_steps=accum, zero_shardings=zs)
                meta = {"param_placements": ps, "opt_placements": os_,
                        "opt_avals": opt, "zero_placements": zs,
                        "batch": B}
                mesh_parts = (ps, ss, os_, zs, B)
            else:
                from torchpruner_tpu.train.loop import (
                    make_loss_closure,
                    make_step_body,
                )

                B = _round_up(max(1, cfg.batch_size), accum)
                step = jax.jit(make_step_body(
                    make_loss_closure(model, loss_fn, cdtype, cfg.remat),
                    tx, accum))
                meta = {"batch": B}
            x = jax.eval_shape(lambda: model.example_input(batch=B))
            y = x if lm else jax.ShapeDtypeStruct((B,), jnp.int32)
            compiled = step.lower(params, state, opt, x, y, rng).compile()
            records.append(ProgramRecord(
                name="train_step", compiled=compiled,
                collectives=tuple(hlo_collectives(compiled, mesh)),
                mesh=mesh, mesh_axes=axes_c, downscaled=downscaled,
                param_bytes=param_bytes, meta=meta))
        except Exception as e:  # noqa: BLE001 — fault-isolated build
            findings.append(Finding(
                "warning", PASS, "collective/build-failed", "train step",
                f"could not compile the train-step program for this "
                f"config: {type(e).__name__}: {e}"))

    # -- multi_step (mesh configs): the scanned K-steps-per-dispatch twin
    # shares the step body, but its zero/gather constraints ride INSIDE
    # a lax.scan — a regression that drops them only there would pass
    # the single-step contract, so it gets its own compiled record
    if mesh is not None and mesh_parts is not None and _want("multi_step"):
        try:
            from torchpruner_tpu.parallel.train import make_sharded_multi_step

            ps, ss, os_, zs, B = mesh_parts
            K = 2
            multi = make_sharded_multi_step(
                model, tx, loss_fn, mesh, ps, ss, os_,
                compute_dtype=cdtype, remat=cfg.remat, accum_steps=accum,
                zero_shardings=zs)
            xs = jax.eval_shape(
                lambda: jnp.stack([model.example_input(batch=B)] * K))
            ys = xs if lm else jax.ShapeDtypeStruct((K, B), jnp.int32)
            compiled = multi.lower(params, state, opt, xs, ys,
                                   rng).compile()
            # steps_per_call stays 1: XLA's cost_analysis (and the HLO
            # text the collective extraction walks) counts a scan/while
            # BODY once regardless of trip count, so the compiled
            # multi_step's numbers already describe one optimizer step
            # (verified: scan over K=4 matmuls reports ~1 matmul's
            # flops) — dividing by K would undercount K-fold
            records.append(ProgramRecord(
                name="multi_step", compiled=compiled,
                collectives=tuple(hlo_collectives(compiled, mesh)),
                mesh=mesh, mesh_axes=axes_c, downscaled=downscaled,
                param_bytes=param_bytes,
                meta={"batch": B, "k": K}))
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                "warning", PASS, "collective/build-failed", "multi_step",
                f"could not compile the multi-step program: "
                f"{type(e).__name__}: {e}"))

    # -- one-pass capture program (robustness sweeps) ----------------------
    if cfg.experiment in ("robustness", "train_robustness") \
            and _want("capture"):
        try:
            from torchpruner_tpu.attributions.base import needs_taps
            from torchpruner_tpu.core.graph import pruning_graph
            from torchpruner_tpu.core.segment import capture_fn

            sites = tuple(
                g.target for g in pruning_graph(model)
                if not needs_taps(model, g.target))
            if sites:
                fn = capture_fn(model, sites)
                xB = jax.eval_shape(
                    lambda: model.example_input(batch=max(1, cfg.batch_size)))
                compiled = fn.lower(params, state, xB).compile()
                records.append(ProgramRecord(
                    name="capture", compiled=compiled,
                    collectives=tuple(hlo_collectives(compiled, None)),
                    param_bytes=param_bytes,
                    meta={"sites": len(sites),
                          "batch": max(1, cfg.batch_size)}))
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                "warning", PASS, "collective/build-failed", "capture",
                f"could not compile the one-pass capture program: "
                f"{type(e).__name__}: {e}"))

    # -- decode / prefill (attention LMs) ----------------------------------
    from torchpruner_tpu.generate import _attn_layers

    attn = list(_attn_layers(model.layers)) \
        if getattr(model, "input_dtype", "") == "int32" else []
    if attn:
        from torchpruner_tpu.generate import init_cache, make_slot_decode_step

        B_slots, T = 4, 128
        entry_bytes = max(
            2 * B_slots * T * int(s.num_heads) * int(s.head_dim) * 4
            for _, s in attn)
        if _want("decode"):
            try:
                cache = jax.eval_shape(
                    lambda: init_cache(model, B_slots, T))
                tok = jax.ShapeDtypeStruct((B_slots, 1), jnp.int32)
                pos = jax.ShapeDtypeStruct((B_slots,), jnp.int32)
                compiled = make_slot_decode_step(model).lower(
                    params, cache, tok, pos).compile()
                records.append(ProgramRecord(
                    name="decode", compiled=compiled,
                    collectives=tuple(hlo_collectives(compiled, None)),
                    param_bytes=param_bytes,
                    meta={"slots": B_slots, "max_len": T,
                          "cache_entry_bytes": entry_bytes}))
            except Exception as e:  # noqa: BLE001
                findings.append(Finding(
                    "warning", PASS, "collective/build-failed",
                    "decode step",
                    f"could not compile the slot-decode program: "
                    f"{type(e).__name__}: {e}"))
        if _want("prefill"):
            try:
                from torchpruner_tpu.generate import _decode_seq

                cache1 = jax.eval_shape(lambda: init_cache(model, 1, T))
                prompt = jax.ShapeDtypeStruct((1, T), jnp.int32)
                p0 = jax.ShapeDtypeStruct((), jnp.int32)

                def _prefill(p, c, xx, pp):
                    out, c = _decode_seq(model.layers, p, c, xx, pp)
                    return out[:, -1], c

                compiled = jax.jit(_prefill).lower(
                    params, cache1, prompt, p0).compile()
                records.append(ProgramRecord(
                    name="prefill", compiled=compiled,
                    collectives=tuple(hlo_collectives(compiled, None)),
                    param_bytes=param_bytes, meta={"bucket": T}))
            except Exception as e:  # noqa: BLE001
                findings.append(Finding(
                    "warning", PASS, "collective/build-failed", "prefill",
                    f"could not compile the prefill program: "
                    f"{type(e).__name__}: {e}"))

        # TP-placed decode: the program a tensor-parallel serve would
        # run — params under the TP rule, the KV cache sharded on its
        # head axis.  THIS is the program the KV-cache contract check
        # inspects; it only exists when the config asks for TP and the
        # downscaled mesh kept a model axis.
        model_c = axes_c.get("model", 1)
        if cfg.partition == "tp" and mesh is not None and model_c > 1 \
                and _want("decode_tp") \
                and not all(int(s.num_heads) % model_c == 0
                            for _, s in attn):
            # the configs MOST at risk of KV replication are exactly the
            # ones whose decode program can't be formed — never skip
            # the contract check silently
            heads = sorted({int(s.num_heads) for _, s in attn})
            findings.append(Finding(
                "warning", PASS, "collective/tp-decode-unsharded",
                "tp decode step",
                f"attention head counts {heads} do not all divide the "
                f"model axis ({model_c}) — the TP decode program cannot "
                f"shard the KV cache evenly, so the KV-cache contract "
                f"check (collective/tp-kv-allgather) CANNOT run; the "
                f"real TP serve would replicate/reassemble the cache"))
        if cfg.partition == "tp" and mesh is not None and model_c > 1 \
                and _want("decode_tp") \
                and all(int(s.num_heads) % model_c == 0 for _, s in attn):
            try:
                from jax.sharding import NamedSharding
                from jax.sharding import PartitionSpec as P

                from torchpruner_tpu.generate import _decode_seq
                from torchpruner_tpu.parallel.sharding import (
                    replicate,
                    tp_sharding,
                )

                rep = replicate(mesh)
                tps = tp_sharding(model, params, mesh)
                cache = jax.eval_shape(
                    lambda: init_cache(model, B_slots, T))
                cs = jax.tree_util.tree_map(
                    lambda l: NamedSharding(
                        mesh, P(None, None, "model", None))
                    if l.shape[2] % model_c == 0 else rep, cache)

                def _dstep(p, c, t_, po):
                    out, c = _decode_seq(model.layers, p, c, t_, po)
                    return out[:, 0], c

                step = jax.jit(_dstep, in_shardings=(tps, cs, rep, rep),
                               out_shardings=(rep, cs))
                tok = jax.ShapeDtypeStruct((B_slots, 1), jnp.int32)
                pos = jax.ShapeDtypeStruct((B_slots,), jnp.int32)
                compiled = step.lower(params, cache, tok, pos).compile()
                records.append(ProgramRecord(
                    name="decode_tp", compiled=compiled,
                    collectives=tuple(hlo_collectives(compiled, mesh)),
                    mesh=mesh, mesh_axes=axes_c, downscaled=downscaled,
                    param_bytes=param_bytes,
                    meta={"slots": B_slots, "max_len": T,
                          "cache_entry_bytes": entry_bytes}))
            except Exception as e:  # noqa: BLE001
                findings.append(Finding(
                    "warning", PASS, "collective/build-failed",
                    "tp decode step",
                    f"could not compile the TP decode program: "
                    f"{type(e).__name__}: {e}"))

    return records, findings


def lint_collectives(cfg, model=None, records=None, closed=None,
                     closed_site="train step", trace=True):
    """Pass 4 driver: build (or adopt) the config's compiled programs
    and run every contract check that applies.  Returns ``(findings,
    records)`` — the records are handed on to the cost pass so the
    programs compile exactly once.

    The jaxpr half (branch-divergence / unknown-axis) adopts a prebuilt
    ``closed`` step jaxpr when given — ``lint_config`` shares pass 3's
    trace (train OR eval, labelled by ``closed_site``) so the step
    never traces twice per lint.  With ``closed=None`` it traces its
    own train step unless ``trace=False`` (the runner's ``jaxpr=False``
    contract: no abstract trace at all)."""
    if records is None:
        records, findings = build_programs(cfg, model, plant=env_plant())
    else:
        findings = []

    by_name = {r.name: r for r in records}
    train = by_name.get("train_step")
    if train is not None and train.mesh is not None:
        axes_c = train.mesh_axes or {}
        ps = (train.meta or {}).get("param_placements")
        if cfg.zero and axes_c.get("data", 1) > 1:
            findings += check_zero_contract(
                train.collectives, param_bytes=train.param_bytes,
                data_axis="data", site="train step")
            multi = by_name.get("multi_step")
            if multi is not None:
                # the scanned twin must carry the same per-step sharded
                # update; its loop body's collectives are in the HLO
                findings += check_zero_contract(
                    multi.collectives, param_bytes=multi.param_bytes,
                    data_axis="data", site="multi_step")
            os_ = (train.meta or {}).get("opt_placements")
            oa = (train.meta or {}).get("opt_avals")
            if os_ is not None and oa is not None:
                combined = jax.tree_util.tree_map(
                    lambda l, s: (l, s), oa, os_,
                    is_leaf=lambda x: isinstance(x, jax.ShapeDtypeStruct))
                findings += replication_leaks(
                    combined, axis="data", what="optimizer state",
                    site="train step")
        if axes_c.get("model", 1) > 1 and ps is not None:
            findings += check_fsdp_contract(
                train.collectives,
                sharded_paths=_spec_paths_on_axis(ps, "model"),
                model_axis="model", site="train step")

    tp_dec = by_name.get("decode_tp")
    if tp_dec is not None:
        findings += check_tp_decode_contract(
            tp_dec.collectives,
            cache_entry_bytes=(tp_dec.meta or {})
            .get("cache_entry_bytes", 0),
            model_axis="model", site="decode step")

    # jaxpr half: explicit collectives (shard_map code paths) checked
    # against the CONFIG's mesh axes — unknown axes and cond-divergent
    # collective sequences are deadlocks regardless of the downscale
    if cfg.mesh and (closed is not None or trace):
        try:
            if closed is None:
                from torchpruner_tpu.analysis.jaxpr_lint import trace_step
                from torchpruner_tpu.experiments.prune_retrain import (
                    LOSS_REGISTRY,
                    MODEL_REGISTRY,
                    make_optimizer,
                )

                if model is None:
                    model = MODEL_REGISTRY[cfg.model][0]()
                closed = trace_step(
                    model, LOSS_REGISTRY[cfg.loss],
                    tx=make_optimizer(cfg), train=True,
                    compute_dtype=jnp.bfloat16
                    if cfg.compute_dtype == "bfloat16" else None,
                    remat=cfg.remat,
                    lm=cfg.loss == "lm_cross_entropy")
                closed_site = "train step"
            findings += lint_collective_jaxpr(
                closed, dict(cfg.mesh), site=closed_site)
        except Exception as e:  # noqa: BLE001
            findings.append(Finding(
                "warning", PASS, "collective/build-failed",
                "train step (jaxpr)",
                f"could not trace the step for the jaxpr-collective "
                f"half: {type(e).__name__}: {e}"))

    return findings, records

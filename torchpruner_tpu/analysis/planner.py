"""``--plan auto`` — cost-model-driven auto-parallelism planner.

PR 9/10 left the repo able to RUN every parallelism mode (zero/fsdp/tp ×
remat × accum over any mesh, placed by the one shared
``parallel.train.plan_placements``) and able to PRICE a config statically
(pass-5 roofline cost model, pass-4 collective-contract lint, the
``training_memory`` HBM budget) — but a human still hand-picks the
config per preset.  This module closes the predict→search→validate loop
(ROADMAP item 3):

1. **Enumerate** the discrete candidate space: every mesh factorization
   of the device count (``parallel.train.mesh_factorizations``) ×
   partition (fsdp / tp where a model axis exists) × zero (where a data
   axis > 1 exists) × a global-batch ladder — plus the hand-written
   config itself as the baseline candidate, and kernel block sizes
   seeded from the PR 8 autotune cache attached as winner metadata.
   ``accum_steps`` and ``remat`` enter as *feasibility repairs*: they
   are memory levers, never predicted-step-time winners, so the search
   generates an accum-doubled and a remat-on variant exactly for the
   candidates the HBM gate excluded (one repair generation — no
   recursion).
2. **Gate** statically, cheapest check first: the predicted per-chip
   HBM watermark (``utils.flops.predicted_hbm_bytes_per_chip`` at the
   candidate's FULL mesh — pure shape math) against the device HBM
   budget (``utils.flops.hbm_capacity``, env-overridable for the CI
   planted-infeasible drill); survivors compile their real train-step
   program (pass 4's ``build_programs`` over abstract avals, downscaled
   onto local devices when the target mesh is larger) and must pass the
   collective-contract checks.  Excluded candidates are kept in the
   plan artifact with their reason — exclusion is always loud.
3. **Price** every survivor with the pass-5 roofline (predicted step
   time and the compute/HBM/ICI term that binds) and **rank** by
   predicted ms per example (``step_ms / global batch`` of the compiled
   program — the system-throughput ordering; candidates at one device
   count compare exactly, downscaled targets approximately, flagged by
   the pass-4 ``collective/mesh-downscaled`` info finding).
4. **Validate** (optional): short measured probes of the top-K
   candidates — a real (downscaled) trainer stepped a few times — gated
   by the same predicted-vs-measured drift scalar ``obs diff`` carries:
   a candidate whose |drift| exceeds the gate keeps its row but is
   demoted below every in-tolerance candidate (its prediction is not
   trustworthy enough to win on).

The search prices everything BEFORE compiling anything at full scale,
so it runs in CI on 8 virtual devices in seconds — and per ROADMAP
item 4 it is the trial-pruning front end the future Pareto sweep driver
feeds candidate configs through.

CLI::

    python -m torchpruner_tpu <preset> --plan auto [--plan-probe K]
        [--plan-out plan.json] [--plan-devices N]
    python -m torchpruner_tpu <preset> --plan report   # re-render

The plan lands as a JSON artifact (and, under ``--obs-dir``, as
``plan_*`` gauges plus a ledger ``plan`` record rendered by
``obs report`` and diffed by ``obs diff``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence

import numpy as np

from torchpruner_tpu.analysis.collective_lint import _round_up
from torchpruner_tpu.analysis.findings import Finding

PASS = "planner"

#: compile cap: candidates that survive the HBM gate beyond this many
#: are not compiled/linted this run — truncation is loud
#: (``planner/truncated`` names every dropped label).
MAX_COMPILE = 32

#: |predicted-vs-measured| probe drift (percent) above which a probed
#: candidate's prediction is not trusted to win — the same scalar
#: family the capture script gates at 30% on-chip.
DRIFT_GATE_PCT = 30.0


def _env_int(name: str, default: int) -> int:
    v = os.environ.get(name)
    return int(float(v)) if v else default


@dataclass
class Candidate:
    """One point of the discrete config space, plus its pricing."""

    mesh: Dict[str, int]
    partition: str
    zero: bool
    batch_size: int
    accum_steps: int
    remat: bool
    baseline: bool = False
    repair_of: Optional[str] = None
    kernel_blocks: Dict[str, Any] = field(default_factory=dict)
    # -- pricing results --
    feasible: bool = False
    excluded_by: Optional[str] = None  # "hbm" | "lint" | "build" | "cap"
    reasons: List[str] = field(default_factory=list)
    hbm: Dict[str, Any] = field(default_factory=dict)
    predicted: Optional[Dict[str, Any]] = None
    lint: Dict[str, List[str]] = field(
        default_factory=lambda: {"errors": [], "warnings": []})
    probe: Optional[Dict[str, Any]] = None

    @property
    def label(self) -> str:
        mesh = "x".join(f"{a[0]}{s}" for a, s in self.mesh.items()) \
            if self.mesh else "single"
        bits = [mesh, self.partition if self.mesh else "local"]
        if self.zero:
            bits.append("zero")
        bits.append(f"b{self.batch_size}")
        if self.accum_steps > 1:
            bits.append(f"a{self.accum_steps}")
        if self.remat:
            bits.append("remat")
        return "/".join(bits)

    def config(self, cfg):
        """The candidate as a runnable ExperimentConfig."""
        return dataclasses.replace(
            cfg, mesh=dict(self.mesh), partition=self.partition,
            zero=self.zero, batch_size=self.batch_size,
            accum_steps=self.accum_steps, remat=self.remat,
        )

    def to_dict(self) -> Dict[str, Any]:
        return {
            "label": self.label,
            "mesh": dict(self.mesh),
            "partition": self.partition,
            "zero": self.zero,
            "batch_size": self.batch_size,
            "accum_steps": self.accum_steps,
            "remat": self.remat,
            "baseline": self.baseline,
            "repair_of": self.repair_of,
            "kernel_blocks": dict(self.kernel_blocks),
            "feasible": self.feasible,
            "excluded_by": self.excluded_by,
            "reasons": list(self.reasons),
            "hbm": dict(self.hbm),
            "predicted": self.predicted,
            "lint": {k: list(v) for k, v in self.lint.items()},
            "probe": self.probe,
        }


def _fmt_bytes(n: float) -> str:
    for unit, div in (("GiB", 2 ** 30), ("MiB", 2 ** 20), ("KiB", 2 ** 10)):
        if abs(n) >= div:
            return f"{n / div:.2f} {unit}"
    return f"{n:.0f} B"


def _kernel_seeds(model, cfg) -> Dict[str, Any]:
    """Autotuned kernel block sizes for this model's attention geometry
    (the PR 8 cache) — attached to every candidate so the winning config
    pins the blocks its kernels would actually run with.  Empty for
    models without attention or without a cache entry (the dispatch
    heuristics then apply, which is also worth knowing)."""
    try:
        from torchpruner_tpu.generate import _attn_layers
        from torchpruner_tpu.ops import autotune

        if getattr(model, "input_dtype", "") != "int32":
            return {}
        attn = list(_attn_layers(model.layers))
        if not attn:
            return {}
        head_dim = int(attn[0][1].head_dim)
        S = int(model.input_shape[0])
        dtype = "bfloat16" if cfg.compute_dtype == "bfloat16" else "float32"
        out = {}
        for kind in (autotune.KIND_FLASH, autotune.KIND_DECODE):
            blocks = autotune.lookup(kind, head_dim, S, dtype)
            if blocks:
                out[kind] = list(blocks)
        return out
    except Exception:  # noqa: BLE001 — seeds are metadata, never a failure
        return {}


def enumerate_candidates(cfg, n_devices: int, *,
                         batch_ladder: Sequence[int] = (1, 2),
                         max_model: Optional[int] = None,
                         model=None) -> List[Candidate]:
    """The base candidate set: the hand-written config first (the
    baseline every assertion compares against), then every mesh
    factorization × partition × zero × batch-ladder point.  accum/remat
    variants are NOT enumerated here — they are generated as feasibility
    repairs by :func:`plan_auto` for exactly the candidates the HBM gate
    excludes."""
    from torchpruner_tpu.parallel.train import mesh_factorizations

    seeds = _kernel_seeds(model, cfg) if model is not None else {}
    out = [Candidate(
        mesh=dict(cfg.mesh or {}), partition=cfg.partition, zero=cfg.zero,
        batch_size=cfg.batch_size, accum_steps=max(1, cfg.accum_steps),
        remat=cfg.remat, baseline=True, kernel_blocks=dict(seeds),
    )]
    seen = {(tuple(sorted((cfg.mesh or {}).items())), cfg.partition,
             cfg.zero, cfg.batch_size, max(1, cfg.accum_steps), cfg.remat)}
    for mesh in mesh_factorizations(n_devices, max_model=max_model):
        data = mesh.get("data", 1)
        model_ax = mesh.get("model", 1)
        partitions = ["fsdp"] + (["tp"] if model_ax > 1 else [])
        zeros = [False] + ([True] if data > 1 else [])
        for partition in partitions:
            for zero in zeros:
                for k in batch_ladder:
                    # a mesh candidate keeps the config's accum/remat;
                    # batch rounds up so every microbatch shards evenly
                    accum = max(1, cfg.accum_steps)
                    batch = _round_up(
                        max(data, int(cfg.batch_size * k)), data * accum)
                    key = (tuple(sorted(mesh.items())), partition, zero,
                           batch, accum, cfg.remat)
                    if key in seen:
                        continue
                    seen.add(key)
                    out.append(Candidate(
                        mesh=dict(mesh), partition=partition, zero=zero,
                        batch_size=batch, accum_steps=accum,
                        remat=cfg.remat, kernel_blocks=dict(seeds),
                    ))
    return out


def _repairs(cand: Candidate) -> List[Candidate]:
    """Memory-lever variants of an HBM-infeasible candidate: double the
    accumulation (per-microbatch activations halve; the batch re-rounds
    up to the new ``data * accum`` multiple so every microbatch still
    shards evenly — the same invariant the enumerator maintains) and
    switch remat on (saved activations shrink to block boundaries).
    One generation — a candidate whose repairs still don't fit is
    genuinely over budget for this model on this chip."""
    out = []
    data = max(1, cand.mesh.get("data", 1))
    per_chip = cand.batch_size // data
    if per_chip // (2 * cand.accum_steps) >= 1:
        accum = 2 * cand.accum_steps
        out.append(dataclasses.replace(
            cand, accum_steps=accum,
            batch_size=_round_up(cand.batch_size, data * accum),
            baseline=False, repair_of=cand.label, feasible=False,
            excluded_by=None, reasons=[], hbm={}, predicted=None,
            lint={"errors": [], "warnings": []}, probe=None))
    if not cand.remat:
        out.append(dataclasses.replace(
            cand, remat=True, baseline=False, repair_of=cand.label,
            feasible=False, excluded_by=None, reasons=[], hbm={},
            predicted=None, lint={"errors": [], "warnings": []},
            probe=None))
    return out


def price_hbm(cand: Candidate, cfg, model, tx, *,
              hbm_budget: float, headroom: float = 0.85) -> bool:
    """The static feasibility gate: predicted per-chip HBM watermark at
    the candidate's FULL mesh (no downscale — HBM is per chip, and shape
    math needs no devices) against ``headroom`` of the budget.  Fills
    ``cand.hbm`` and returns whether the candidate fits."""
    import jax.numpy as jnp

    from torchpruner_tpu.utils.flops import predicted_hbm_bytes_per_chip

    data = max(1, cand.mesh.get("data", 1))
    watermark = predicted_hbm_bytes_per_chip(
        model, cand.mesh,
        partition=cand.partition, zero=cand.zero, tx=tx,
        batch_per_chip=max(1, cand.batch_size // data // cand.accum_steps),
        compute_dtype=jnp.bfloat16 if cfg.compute_dtype == "bfloat16"
        else None,
        remat=cand.remat,
    )
    fits = watermark <= hbm_budget * headroom
    cand.hbm = {
        "watermark_bytes_per_chip": int(watermark),
        "budget_bytes": int(hbm_budget),
        "headroom": headroom,
        "fits": bool(fits),
    }
    if not fits:
        cand.excluded_by = "hbm"
        cand.reasons.append(
            f"predicted HBM watermark {_fmt_bytes(watermark)}/chip "
            f"exceeds {100 * headroom:.0f}% of the "
            f"{_fmt_bytes(hbm_budget)} budget")
    return fits


def price_candidate(cand: Candidate, cfg, model) -> None:
    """Compile the candidate's real train-step program (downscaled onto
    local devices when needed), run the pass-4 contract checks, and fill
    the pass-5 roofline prediction.  A candidate that fails to build or
    fails the lint is excluded with the findings as its reasons."""
    from torchpruner_tpu.analysis import cost_model
    from torchpruner_tpu.analysis.collective_lint import (
        build_programs,
        lint_collectives,
    )

    ccfg = cand.config(cfg)
    records, bfindings = build_programs(
        ccfg, model, programs=("train_step",))
    train = next((r for r in records if r.name == "train_step"), None)
    if train is None:
        cand.excluded_by = "build"
        cand.reasons += [f.message for f in bfindings] or \
            ["train-step program did not build"]
        return
    lfindings, _ = lint_collectives(
        ccfg, model=model, records=records, trace=False)
    cand.lint = {
        "errors": [f"{f.check}: {f.message}" for f in lfindings
                   if f.severity == "error"],
        "warnings": [f"{f.check}: {f.message}" for f in lfindings
                     if f.severity == "warning"],
    }
    if cand.lint["errors"]:
        cand.excluded_by = "lint"
        cand.reasons += cand.lint["errors"]
        return
    pred = cost_model.predict_record(train)
    if pred is None:
        cand.excluded_by = "build"
        cand.reasons.append("cost model produced no prediction")
        return
    batch_c = int((train.meta or {}).get("batch") or cand.batch_size)
    data_c = int((train.mesh_axes or {}).get("data", 1)) \
        if train.mesh_axes else 1
    cand.predicted = {
        "step_ms": pred.step_ms,
        "step_ms_per_example": pred.step_ms / max(1, batch_c),
        "compute_ms": pred.compute_ms,
        "hbm_ms": pred.hbm_ms,
        "ici_ms": pred.ici_ms,
        "bound": pred.bound,
        "flops": pred.flops,
        "hbm_bytes": pred.hbm_bytes,
        "ici_bytes": pred.ici_bytes,
        "device_kind": pred.device_kind,
        "batch_compiled": batch_c,
        "batch_per_chip": batch_c // max(1, data_c),
        "downscaled": bool(train.downscaled),
    }
    cand.feasible = True


def _model_flops_per_example(model) -> Optional[float]:
    """Forward model-FLOPs per example (XLA cost analysis of a
    single-device batch-2 forward) — the SAME denominator convention as
    the bench/telemetry MFU (3 × forward FLOPs per example), so a probe
    MFU is comparable to the vgg16 plateau number.  None when cost
    analysis is unavailable."""
    try:
        from torchpruner_tpu.core.segment import init_model
        from torchpruner_tpu.utils.flops import model_cost

        params, state = init_model(model, seed=0)
        _, fwd = model_cost(model, params, state, batch_size=2)
        return fwd / 2.0 if fwd else None
    except Exception:  # noqa: BLE001 — MFU is probe garnish, not a gate
        return None


def probe_candidate(cand: Candidate, cfg, model, *, steps: int = 6,
                    warmup: int = 2,
                    drift_gate_pct: float = None,
                    flops_per_example: Optional[float] = None
                    ) -> Dict[str, Any]:
    """Short measured probe: step a REAL trainer at the candidate's
    (downscaled) placement on synthetic data and compare measured
    ms/step against the prediction — the same predicted-vs-measured
    drift scalar ``obs diff`` carries, used here as the validation gate.
    Fills and returns ``cand.probe``.

    ``flops_per_example`` (forward model FLOPs, see
    :func:`_model_flops_per_example`) makes the probe report an MFU in
    the bench convention — 3 × forward FLOPs per example over the chip
    peak — comparable to the hand-tuned plateau numbers; hardware
    cost-analysis FLOPs would overcount remat recompute and optimizer
    work."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.analysis import cost_model
    from torchpruner_tpu.analysis.collective_lint import (
        build_mesh,
        downscale_axes,
    )
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        make_optimizer,
    )

    if drift_gate_pct is None:
        drift_gate_pct = DRIFT_GATE_PCT
    ccfg = cand.config(cfg)
    tx = make_optimizer(ccfg)
    loss_fn = LOSS_REGISTRY[ccfg.loss]
    cdtype = jnp.bfloat16 if ccfg.compute_dtype == "bfloat16" else None
    lm = ccfg.loss == "lm_cross_entropy"

    if cand.mesh:
        from torchpruner_tpu.parallel.train import ShardedTrainer

        axes_c = downscale_axes(dict(cand.mesh), len(jax.devices()))
        if axes_c is None:
            cand.probe = {"skipped": "mesh does not fit this host"}
            return cand.probe
        mesh = build_mesh(axes_c)
        data_c = axes_c.get("data", 1)
        per_chip = max(1, cand.batch_size
                       // max(1, cand.mesh.get("data", 1)))
        B = _round_up(per_chip * data_c, cand.accum_steps * data_c)
        trainer = ShardedTrainer.create(
            model, tx, loss_fn, mesh, partition=cand.partition,
            zero=cand.zero and data_c > 1, compute_dtype=cdtype,
            remat=cand.remat, accum_steps=cand.accum_steps,
        )
    else:
        from torchpruner_tpu.train.loop import Trainer

        B = _round_up(max(1, cand.batch_size), cand.accum_steps)
        trainer = Trainer.create(
            model, tx, loss_fn, compute_dtype=cdtype, remat=cand.remat,
            accum_steps=cand.accum_steps,
        )
    x = model.example_input(batch=B)
    y = x if lm else jax.random.randint(
        jax.random.PRNGKey(1), (B,), 0, max(2, cfg.n_classes), jnp.int32)
    for _ in range(max(1, warmup)):
        float(trainer.step(x, y))
    t0 = time.perf_counter()
    for _ in range(max(1, steps)):
        float(trainer.step(x, y))
    measured_ms = (time.perf_counter() - t0) / max(1, steps) * 1e3
    probe: Dict[str, Any] = {"measured_ms": measured_ms,
                             "steps": int(steps), "batch": int(B)}
    pred = (cand.predicted or {}).get("step_ms")
    if pred:
        probe["drift_pct"] = 100.0 * (pred - measured_ms) / measured_ms
        probe["gated"] = abs(probe["drift_pct"]) > drift_gate_pct
        probe["drift_gate_pct"] = drift_gate_pct
    if flops_per_example is None:
        flops_per_example = _model_flops_per_example(model)
    if flops_per_example:
        peaks = cost_model.device_peaks()
        n_used = int(np.prod(list(axes_c.values()))) if cand.mesh else 1
        ex_per_s_per_chip = B / max(1, n_used) / (measured_ms / 1e3)
        probe["mfu"] = (3.0 * flops_per_example * ex_per_s_per_chip
                        / peaks["flops"])
    probe["measured_ms_per_example"] = measured_ms / max(1, B)
    cand.probe = probe
    return probe


def plan_auto(cfg, *, model=None, n_devices: Optional[int] = None,
              probe_top: int = 0, probe_steps: int = 6,
              batch_ladder: Sequence[int] = (1, 2),
              max_model: Optional[int] = None,
              max_compile: Optional[int] = None,
              hbm_budget: Optional[float] = None,
              drift_gate_pct: Optional[float] = None) -> Dict[str, Any]:
    """The full search: enumerate → HBM-gate (+ repairs) → compile/lint
    → price → rank → (optionally) probe.  Returns the plan artifact
    dict; obs gauges and a ledger ``plan`` record land when a session is
    active.  Every exclusion survives into the artifact with its reason
    and a ``planner/*`` finding — nothing is dropped silently."""
    import jax

    from torchpruner_tpu.analysis import cost_model
    from torchpruner_tpu.experiments.prune_retrain import (
        MODEL_REGISTRY,
        make_optimizer,
    )
    from torchpruner_tpu.utils.flops import hbm_capacity

    t_start = time.perf_counter()
    if model is None:
        model = MODEL_REGISTRY[cfg.model][0]()
    tx = make_optimizer(cfg)
    if n_devices is None:
        n_devices = int(np.prod(list(cfg.mesh.values()))) if cfg.mesh \
            else len(jax.devices())
    if hbm_budget is None:
        hbm_budget = hbm_capacity()
    if max_compile is None:
        max_compile = _env_int("TORCHPRUNER_PLAN_MAX_COMPILE", MAX_COMPILE)

    findings: List[Finding] = []
    cands = enumerate_candidates(
        cfg, n_devices, batch_ladder=batch_ladder, max_model=max_model,
        model=model)

    # -- static HBM gate (pure shape math) — a worklist so repairs ride
    # the SAME price/finding bookkeeping as base candidates; the
    # one-generation rule is the ``repair_of is None`` guard (a repair
    # that still doesn't fit is genuinely over budget, not re-repaired)
    survivors: List[Candidate] = []
    pending = list(cands)
    while pending:
        cand = pending.pop(0)
        try:
            fits = price_hbm(cand, cfg, model, tx, hbm_budget=hbm_budget)
        except Exception as e:  # noqa: BLE001 — fault-isolated pricing
            cand.excluded_by = "build"
            cand.reasons.append(
                f"HBM pricing failed: {type(e).__name__}: {e}")
            findings.append(Finding(
                "warning", PASS, "planner/build-failed", cand.label,
                cand.reasons[-1]))
            continue
        if fits:
            survivors.append(cand)
            continue
        findings.append(Finding(
            "warning", PASS, "planner/over-hbm", cand.label,
            cand.reasons[-1]))
        if cand.repair_of is None:
            for rep in _repairs(cand):
                cands.append(rep)
                pending.append(rep)

    # -- compile cap (loud truncation) ----------------------------------
    if len(survivors) > max_compile:
        dropped = survivors[max_compile:]
        survivors = survivors[:max_compile]
        for cand in dropped:
            cand.excluded_by = "cap"
            cand.reasons.append(
                f"beyond the {max_compile}-candidate compile cap "
                f"(raise TORCHPRUNER_PLAN_MAX_COMPILE)")
        findings.append(Finding(
            "info", PASS, "planner/truncated", "<cap>",
            f"{len(dropped)} candidate(s) beyond the {max_compile}-"
            f"compile cap were not priced: "
            + ", ".join(c.label for c in dropped)))

    # -- compile + contract lint + roofline pricing ---------------------
    for cand in survivors:
        try:
            price_candidate(cand, cfg, model)
        except Exception as e:  # noqa: BLE001 — fault-isolated build
            cand.excluded_by = "build"
            cand.reasons.append(f"{type(e).__name__}: {e}")
        if cand.excluded_by == "lint":
            findings.append(Finding(
                "warning", PASS, "planner/lint-failed", cand.label,
                "; ".join(cand.lint["errors"])))
        elif cand.excluded_by == "build":
            findings.append(Finding(
                "warning", PASS, "planner/build-failed", cand.label,
                "; ".join(cand.reasons)))

    feasible = [c for c in cands if c.feasible]
    ranked = sorted(
        feasible, key=lambda c: c.predicted["step_ms_per_example"])

    # -- measured probes of the top-K (drift-gated) ---------------------
    if probe_top and ranked:
        fpe = _model_flops_per_example(model)  # once — shared by probes
        for cand in ranked[:probe_top]:
            try:
                probe_candidate(cand, cfg, model, steps=probe_steps,
                                drift_gate_pct=drift_gate_pct,
                                flops_per_example=fpe)
            except Exception as e:  # noqa: BLE001 — a probe failure is
                # data (the config may genuinely not run), not a crash
                cand.probe = {"error": f"{type(e).__name__}: {e}"}
            p = cand.probe or {}
            if p.get("gated"):
                findings.append(Finding(
                    "warning", PASS, "planner/probe-drift", cand.label,
                    f"measured {p['measured_ms']:.3f} ms/step vs "
                    f"predicted {cand.predicted['step_ms']:.3f} ms "
                    f"({p['drift_pct']:+.0f}% drift exceeds the "
                    f"{p['drift_gate_pct']:.0f}% gate) — prediction "
                    f"not trusted to rank this candidate"))
        # drift-gated candidates demote below every in-tolerance one
        ranked = sorted(ranked, key=lambda c: (
            bool((c.probe or {}).get("gated")),
            c.predicted["step_ms_per_example"]))

    if not ranked:
        findings.append(Finding(
            "error", PASS, "planner/no-feasible", cfg.name,
            f"no candidate fits the {hbm_budget / 2**30:.2f} GiB HBM "
            f"budget and passes the collective-contract lint — see the "
            f"per-candidate exclusion reasons"))

    winner = ranked[0] if ranked else None
    baseline = next(c for c in cands if c.baseline)
    margin_pct = None
    if len(ranked) > 1 and winner is not None:
        a = winner.predicted["step_ms_per_example"]
        b = ranked[1].predicted["step_ms_per_example"]
        margin_pct = 100.0 * (b - a) / a if a else None
    baseline_margin_pct = None
    if winner is not None and baseline.feasible:
        a = winner.predicted["step_ms_per_example"]
        b = baseline.predicted["step_ms_per_example"]
        baseline_margin_pct = 100.0 * (b - a) / a if a else None

    peaks = cost_model.device_peaks()
    plan = {
        "version": 1,
        "config": cfg.name,
        "model": cfg.model,
        "experiment": cfg.experiment,
        "device_kind": peaks["kind"],
        "n_devices_target": int(n_devices),
        "n_devices_local": len(jax.devices()),
        "hbm_budget_bytes": int(hbm_budget),
        "candidates": [c.to_dict() for c in cands],
        "ranked": [c.label for c in ranked],
        "winner": winner.label if winner else None,
        "baseline": baseline.label,
        "margin_over_runner_up_pct": margin_pct,
        "margin_over_baseline_pct": baseline_margin_pct,
        "findings": [{"severity": f.severity, "check": f.check,
                      "path": f.path, "message": f.message}
                     for f in findings],
        "wall_s": round(time.perf_counter() - t_start, 3),
    }
    _record_obs(plan, winner, baseline)
    return plan


def _record_obs(plan: Dict[str, Any], winner: Optional[Candidate],
                baseline: Candidate) -> None:
    """Planner telemetry: ``plan_*`` gauges (they ride ``obs diff`` via
    the dynamic-scalar prefix) and one ledger ``plan`` record that the
    ``obs report`` plan section renders.  Best-effort — telemetry must
    never kill a plan."""
    try:
        from torchpruner_tpu import obs

        if obs.get() is None:
            return
        n_feasible = sum(1 for c in plan["candidates"] if c["feasible"])
        obs.gauge_set("plan_candidates_total", len(plan["candidates"]),
                      help="planner: enumerated candidates")
        obs.gauge_set("plan_feasible_total", n_feasible,
                      help="planner: candidates past HBM + lint gates")
        if winner is not None:
            obs.gauge_set("plan_winner_step_ms",
                          winner.predicted["step_ms"],
                          help="planner: winner predicted step ms")
            obs.gauge_set("plan_winner_step_ms_per_example",
                          winner.predicted["step_ms_per_example"],
                          help="planner: winner predicted ms/example")
        if baseline.feasible:
            obs.gauge_set("plan_baseline_step_ms_per_example",
                          baseline.predicted["step_ms_per_example"],
                          help="planner: baseline predicted ms/example")
        obs.record_plan(
            winner=plan["winner"], baseline=plan["baseline"],
            ranked=plan["ranked"][:5],
            candidates=len(plan["candidates"]), feasible=n_feasible,
            margin_over_runner_up_pct=plan["margin_over_runner_up_pct"],
            margin_over_baseline_pct=plan["margin_over_baseline_pct"],
            winner_predicted=(winner.predicted if winner else None),
            winner_probe=(winner.probe if winner else None),
            device_kind=plan["device_kind"],
            n_devices=plan["n_devices_target"],
        )
    except Exception:  # noqa: BLE001
        pass


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------


def format_plan(plan: Dict[str, Any]) -> str:
    """The ranked candidate table plus the loud exclusion list — what
    ``--plan auto`` prints and ``--plan report`` re-renders."""
    lines: List[str] = []
    lines.append(
        f"plan: {plan['config']} on {plan['n_devices_target']} × "
        f"{plan['device_kind']} "
        f"(HBM budget {plan['hbm_budget_bytes'] / 2**30:.2f} GiB/chip, "
        f"{len(plan['candidates'])} candidate(s), "
        f"{len(plan['ranked'])} feasible, {plan['wall_s']:.1f}s)")
    lines.append("")
    by_label = {c["label"]: c for c in plan["candidates"]}
    if plan["ranked"]:
        lines.append("| # | candidate | pred ms/step | ms/example | bound "
                     "| compute/hbm/ici ms | HBM GiB/chip | probe |")
        lines.append("|---|---|---|---|---|---|---|---|")
        for i, label in enumerate(plan["ranked"], 1):
            c = by_label[label]
            p = c["predicted"]
            probe = ""
            if c.get("probe"):
                pr = c["probe"]
                if "measured_ms" in pr:
                    probe = f"{pr['measured_ms']:.3f} ms"
                    if "drift_pct" in pr:
                        probe += f" ({pr['drift_pct']:+.0f}%" + \
                            (" GATED)" if pr.get("gated") else ")")
                elif "error" in pr:
                    probe = "failed"
                elif "skipped" in pr:
                    probe = "skipped"
            tag = "".join(
                [" ←baseline" if c["baseline"] else "",
                 " ←winner" if label == plan["winner"] else ""])
            lines.append(
                f"| {i} | `{label}`{tag} | {p['step_ms']:.3f} "
                f"| {p['step_ms_per_example']:.4f} | {p['bound']} "
                f"| {p['compute_ms']:.3f}/{p['hbm_ms']:.3f}"
                f"/{p['ici_ms']:.3f} "
                f"| {c['hbm']['watermark_bytes_per_chip'] / 2**30:.3f} "
                f"| {probe} |")
        lines.append("")
        if plan["winner"]:
            bits = [f"winner: `{plan['winner']}`"]
            if plan["margin_over_runner_up_pct"] is not None:
                bits.append(f"{plan['margin_over_runner_up_pct']:+.1f}% "
                            f"over the runner-up")
            if plan["margin_over_baseline_pct"] is not None:
                bits.append(f"{plan['margin_over_baseline_pct']:+.1f}% "
                            f"over the hand-written baseline")
            w = by_label[plan["winner"]]
            if w.get("kernel_blocks"):
                bits.append(f"kernel blocks {w['kernel_blocks']} "
                            f"(autotune cache)")
            lines.append(", ".join(bits))
            lines.append("")
    excluded = [c for c in plan["candidates"] if c["excluded_by"]]
    if excluded:
        lines.append("excluded:")
        for c in excluded:
            lines.append(f"- `{c['label']}` [{c['excluded_by']}]: "
                         + "; ".join(c["reasons"]))
        lines.append("")
    for f in plan["findings"]:
        if f["severity"] in ("error", "warning") \
                and not f["check"].startswith(("planner/over-hbm",
                                               "planner/lint-failed",
                                               "planner/build-failed")):
            lines.append(f"{f['severity'].upper()} {f['check']} "
                         f"{f['path']}: {f['message']}")
    return "\n".join(lines).rstrip() + "\n"


def default_plan_path(cfg) -> str:
    return os.path.join("logs", f"plan_{cfg.name}.json")


def write_plan(plan: Dict[str, Any], path: str) -> None:
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    from torchpruner_tpu.resilience.manifest import atomic_write_json

    atomic_write_json(path, plan, indent=1)


def plan_main(cfg, args) -> int:
    """The CLI driver behind ``--plan auto`` / ``--plan report``.
    ``auto`` runs the search, prints the table, writes the plan
    artifact, and exits 0 when at least 3 feasible candidates ranked
    (1-2 still exit 0 with a warning; none exits 1).  ``report``
    re-renders a previously written artifact."""
    import sys

    out_path = args.plan_out or default_plan_path(cfg)
    if args.plan == "report":
        with open(out_path) as f:
            plan = json.load(f)
        print(format_plan(plan))
        return 0
    plan = plan_auto(
        cfg,
        n_devices=args.plan_devices,
        probe_top=args.plan_probe,
    )
    write_plan(plan, out_path)
    print(format_plan(plan))
    print(f"plan written to {out_path}", file=sys.stderr)
    if not plan["ranked"]:
        return 1
    if len(plan["ranked"]) < 3:
        print(f"warning: only {len(plan['ranked'])} feasible "
              f"candidate(s) — the search space may be too tight for "
              f"this device count", file=sys.stderr)
    return 0

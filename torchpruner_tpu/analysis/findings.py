"""Finding records and the severity configuration for tpu-lint.

A finding is one diagnosed hazard: which pass produced it, a stable check
id (``"plan/missing-path"`` style), the pytree/layer path it anchors to,
and a human message.  Severities are ``"error"`` (the run WILL fail or
silently corrupt — lint exits nonzero), ``"warning"`` (the run degrades —
silent replication, f32 promotion off the MXU fast path), and ``"info"``
(measurements worth seeing, e.g. per-chip HBM deltas).

:class:`SeverityConfig` lets deployments re-grade individual checks —
e.g. a single-host run that *wants* replicated small models downgrades
``sharding/replicated-fallback`` to ``"ignore"``.  The module-level
:data:`severity_config` is what integration points (``shard_params``'s
one-line warning, ``apply_plan``'s pre-flight) consult, so one knob
controls both the batch analyzer and the inline checks.

This module is dependency-free (stdlib only) on purpose: integration
points deep in ``core``/``parallel`` import it lazily without pulling the
analysis passes (and their jax tracing) into their import graph.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Sequence, Tuple

#: severity order, most severe first; "ignore" suppresses a finding.
SEVERITIES = ("error", "warning", "info", "ignore")


@dataclass(frozen=True)
class Finding:
    """One diagnosed hazard.

    ``lint`` names the pass (``"plan"`` | ``"sharding"`` | ``"jaxpr"`` |
    ``"collective"`` | ``"cost"`` | ``"host"`` | ``"planner"`` —
    ``"host"`` is the pass-6 concurrency/durability scan over the
    serving plane (analysis/host_lint.py), ``"planner"`` the
    auto-parallelism planner's candidate-exclusion findings,
    analysis/planner.py), ``check`` is the stable id severity overrides
    key on, ``path`` the pytree path / layer path / jaxpr site /
    program name / ``file:line`` source site / candidate label the
    finding anchors to.
    """

    severity: str
    lint: str
    check: str
    path: str
    message: str

    def __post_init__(self):
        if self.severity not in SEVERITIES:
            raise ValueError(
                f"unknown severity {self.severity!r} (use one of {SEVERITIES})"
            )

    def format(self) -> str:
        return (
            f"{self.severity.upper():7s} {self.check:30s} "
            f"{self.path}: {self.message}"
        )


@dataclass
class SeverityConfig:
    """Per-check severity overrides: ``{check_id: severity}``.

    ``"ignore"`` drops the finding entirely.  Unlisted checks keep the
    severity the pass assigned.
    """

    overrides: Dict[str, str] = field(default_factory=dict)

    def severity_for(self, check: str, default: str) -> str:
        sev = self.overrides.get(check, default)
        if sev not in SEVERITIES:
            raise ValueError(
                f"unknown severity {sev!r} for check {check!r} "
                f"(use one of {SEVERITIES})"
            )
        return sev

    def apply(self, findings: Iterable[Finding]) -> Tuple[Finding, ...]:
        out = []
        for f in findings:
            sev = self.severity_for(f.check, f.severity)
            if sev == "ignore":
                continue
            out.append(
                f if sev == f.severity else dataclasses.replace(f, severity=sev)
            )
        return tuple(out)


#: The active severity configuration.  Mutate ``severity_config.overrides``
#: (or swap the object) to re-grade checks process-wide — both the batch
#: analyzer (:func:`torchpruner_tpu.analysis.runner.lint_config`) and the
#: inline integration points (``shard_params``, ``apply_plan``) read it.
severity_config = SeverityConfig()


def active_severity(check: str, default: str) -> str:
    """The effective severity of ``check`` under the active config."""
    return severity_config.severity_for(check, default)


@dataclass(frozen=True)
class LintReport:
    """The findings of one analyzer run, plus formatting helpers."""

    name: str
    findings: Tuple[Finding, ...]

    @property
    def errors(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings if f.severity == "error")

    @property
    def warnings(self) -> Tuple[Finding, ...]:
        return tuple(f for f in self.findings if f.severity == "warning")

    @property
    def ok(self) -> bool:
        return not self.errors

    def format(self) -> str:
        head = (
            f"tpu-lint: {self.name} — {len(self.errors)} error(s), "
            f"{len(self.warnings)} warning(s), "
            f"{len(self.findings) - len(self.errors) - len(self.warnings)} "
            f"info"
        )
        order = {s: i for i, s in enumerate(SEVERITIES)}
        body = [
            "  " + f.format()
            for f in sorted(self.findings, key=lambda f: order[f.severity])
        ]
        return "\n".join([head] + body)


def merge_reports(name: str, *parts: Sequence[Finding]) -> LintReport:
    """One report out of several passes' findings, with the active
    severity overrides applied."""
    merged: List[Finding] = []
    for p in parts:
        merged.extend(p)
    return LintReport(name, severity_config.apply(merged))

"""Pass 3 — jaxpr hazard lint: trace the train/eval step abstractly and
flag the dtype/retrace hazards that only surface on hardware.

``jax.make_jaxpr`` over ``ShapeDtypeStruct`` arguments gives the exact
program XLA would compile — operand dtypes, constants, sub-jaxprs —
without touching a device or materializing an array.  Hazards:

- ``jaxpr/float64`` (error): a float64 value anywhere in the trace.  TPUs
  have no f64 ALU path (XLA emulates at >10x cost) — an accidentally
  enabled ``jax_enable_x64`` or a stray np.float64 constant poisons every
  downstream op;
- ``jaxpr/mixed-precision-matmul`` (warning): a matmul/conv with one
  bf16 and one f32 float operand — promotion runs the contraction at f32
  rate, silently forfeiting the MXU bf16 fast path the compute_dtype
  asked for (weak-typed Python scalars are the classic source);
- ``jaxpr/quant-dtype-drift`` (warning): an int8/int4 quantized weight
  dequantized to a dtype other than the activation compute dtype — the
  convert then cannot fuse into the dot's operand read and a full-width
  float copy of the weight materializes per step (the exact failure mode
  ops/quant.py's formulation exists to avoid);
- ``jaxpr/const-capture`` (warning): a concrete array closed over by the
  traced function (a jaxpr constvar) above a size threshold — it is baked
  into the compiled program, so every new value forces a retrace and a
  recompile (pass it as an argument instead).
"""

from __future__ import annotations

from typing import List, Optional, Set, Tuple

import numpy as np
import jax
import jax.numpy as jnp

from torchpruner_tpu.analysis.findings import Finding

PASS = "jaxpr"

#: contraction primitives whose operand dtypes must agree for MXU rate
_MATMUL_PRIMS = {"dot_general", "conv_general_dilated"}

#: constvars above this many bytes are flagged as retrace bait
CONST_BYTES_THRESHOLD = 2 ** 12


def _sub_jaxprs(eqn):
    for v in eqn.params.values():
        if isinstance(v, jax.core.ClosedJaxpr):
            yield v.jaxpr
        elif isinstance(v, jax.core.Jaxpr):
            yield v
        elif isinstance(v, (tuple, list)):
            for x in v:
                if isinstance(x, jax.core.ClosedJaxpr):
                    yield x.jaxpr
                elif isinstance(x, jax.core.Jaxpr):
                    yield x


#: primitives whose sub-jaxprs ARE kernel bodies: inside them f32
#: contractions are the DESIGN (Pallas kernels upcast bf16 operands in
#: VMEM and accumulate on the MXU in f32 — ops/flash_attention.py,
#: ops/blocksparse.py, ops/fused_matmul.py), and integer payloads widen
#: to whatever the in-register unpack needs — so the promoted-matmul /
#: mixed-precision / quant-drift checks do not apply.  float64 stays
#: flagged everywhere (no TPU kernel should ever see it).
_KERNEL_PRIMS = {"pallas_call", "tpu_custom_call", "mosaic"}


def _walk_eqns(jaxpr, in_kernel: bool = False):
    for eqn in jaxpr.eqns:
        yield eqn, in_kernel
        sub_kernel = in_kernel or eqn.primitive.name in _KERNEL_PRIMS
        for sub in _sub_jaxprs(eqn):
            yield from _walk_eqns(sub, sub_kernel)


def _aval(v):
    return getattr(v, "aval", None)


def lint_jaxpr(
    closed: jax.core.ClosedJaxpr,
    *,
    compute_dtype=None,
    const_bytes_threshold: int = CONST_BYTES_THRESHOLD,
    site: str = "<traced fn>",
) -> List[Finding]:
    """Findings for one traced program.  ``compute_dtype`` is the dtype
    the forward/backward is SUPPOSED to run in (quant-drift is judged
    against it); None skips that check."""
    findings: List[Finding] = []
    seen: Set[Tuple[str, str]] = set()

    def once(check: str, key: str, severity: str, message: str):
        if (check, key) not in seen:
            seen.add((check, key))
            findings.append(Finding(severity, PASS, check, site, message))

    for c in closed.consts:
        shape = np.shape(c)
        nbytes = getattr(c, "nbytes", None)
        if nbytes is None:
            nbytes = int(np.prod(shape or (1,))) * np.dtype(
                getattr(c, "dtype", np.float32)
            ).itemsize
        if nbytes >= const_bytes_threshold:
            once(
                "jaxpr/const-capture", str(shape), "warning",
                f"closed-over concrete array {shape} "
                f"({getattr(c, 'dtype', '?')}, {nbytes} bytes) is baked "
                f"into the compiled program — a new value forces a full "
                f"retrace/recompile; pass it as an argument instead",
            )

    for eqn, in_kernel in _walk_eqns(closed.jaxpr):
        prim = eqn.primitive.name
        out_avals = [a for a in map(_aval, eqn.outvars) if a is not None]
        in_avals = [a for a in map(_aval, eqn.invars) if a is not None]

        for a in out_avals:
            if getattr(a, "dtype", None) == jnp.float64:
                once(
                    "jaxpr/float64", prim, "error",
                    f"{prim} produces float64 {tuple(a.shape)} — TPUs "
                    f"have no f64 fast path (check jax_enable_x64 and "
                    f"np.float64 constants)",
                )

        if in_kernel:
            continue  # kernel internals: see _KERNEL_PRIMS

        bf16_policy = (
            compute_dtype is not None
            and jnp.dtype(compute_dtype) == jnp.dtype(jnp.bfloat16)
        )
        if prim in _MATMUL_PRIMS and len(in_avals) >= 2 and bf16_policy:
            # a correct bf16 mixed-precision step's every contraction is
            # pure bf16 (fwd casts params/inputs, bwd transposes through
            # the casts) — anything else forfeits the MXU bf16 rate
            fdts = {
                jnp.dtype(a.dtype) for a in in_avals
                if jnp.issubdtype(getattr(a, "dtype", jnp.int32),
                                  jnp.floating)
            }
            shapes = [tuple(a.shape) for a in in_avals]
            bf16, f32 = jnp.dtype(jnp.bfloat16), jnp.dtype(jnp.float32)
            if bf16 in fdts and f32 in fdts:
                once(
                    "jaxpr/mixed-precision-matmul", f"{prim}:{shapes}",
                    "warning",
                    f"{prim} mixes bfloat16 and float32 operands "
                    f"{shapes} — the contraction promotes to f32 and "
                    f"forfeits the MXU bf16 rate (weak-typed scalar or "
                    f"missing cast?)",
                )
            elif fdts == {f32}:
                once(
                    "jaxpr/promoted-matmul", f"{prim}:{shapes}",
                    "warning",
                    f"{prim} over {shapes} runs in float32 although the "
                    f"compute dtype is bfloat16 — a weak-typed scalar or "
                    f"stray f32 operand promoted the contraction off the "
                    f"MXU bf16 fast path",
                )

        if (
            prim == "convert_element_type"
            and compute_dtype is not None
            and in_avals
            and getattr(in_avals[0], "dtype", None) == jnp.int8
        ):
            new_dtype = eqn.params.get("new_dtype")
            if (
                new_dtype is not None
                and jnp.issubdtype(new_dtype, jnp.floating)
                and new_dtype != jnp.dtype(compute_dtype)
            ):
                once(
                    "jaxpr/quant-dtype-drift",
                    f"{in_avals[0].dtype}->{new_dtype}", "warning",
                    f"int8 quantized weight dequantizes to "
                    f"{jnp.dtype(new_dtype).name} while activations "
                    f"compute in {jnp.dtype(compute_dtype).name} — the "
                    f"convert cannot fuse into the dot and a full float "
                    f"weight copy materializes every step",
                )
    return findings


def trace_step(
    model,
    loss_fn,
    *,
    tx=None,
    train: bool = True,
    compute_dtype=None,
    remat: bool = False,
    batch: int = 2,
    lm: Optional[bool] = None,
) -> jax.core.ClosedJaxpr:
    """The train (or eval) step of ``model`` as a ClosedJaxpr, traced
    over abstract params/state/opt-state and an abstract example batch —
    pure CPU shape work, identical dtypes to the real step.

    ``lm`` selects the target shape: token targets = inputs (language
    modeling) vs per-example int class labels; default infers LM from an
    int input dtype (token classifiers like BERT pass ``lm=False``)."""
    from torchpruner_tpu.analysis.plan_lint import abstract_trees
    from torchpruner_tpu.train.loop import make_loss_closure, make_step_body

    params, state = abstract_trees(model)
    x = jax.eval_shape(lambda: model.example_input(batch=batch))
    if lm is None:
        lm = model.input_dtype.startswith("int")
    if lm:
        y = x  # LM targets are the inputs (next-token loss shifts inside)
    else:
        y = jax.ShapeDtypeStruct((batch,), jnp.int32)
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))

    loss_c = make_loss_closure(model, loss_fn, compute_dtype, remat)
    if train and tx is not None:
        opt_state = jax.eval_shape(tx.init, params)
        body = make_step_body(loss_c, tx)
        return jax.make_jaxpr(body)(params, state, opt_state, x, y, rng)
    if train:
        grad_fn = jax.value_and_grad(loss_c, has_aux=True)
        return jax.make_jaxpr(grad_fn)(params, state, x, y, rng)
    return jax.make_jaxpr(loss_c)(params, state, x, y, rng)


def lint_step(
    model,
    loss_fn,
    *,
    tx=None,
    train: bool = True,
    compute_dtype=None,
    remat: bool = False,
    batch: int = 2,
    lm: Optional[bool] = None,
) -> List[Finding]:
    """Trace + lint in one call (the runner's entry point)."""
    closed = trace_step(
        model, loss_fn, tx=tx, train=train, compute_dtype=compute_dtype,
        remat=remat, batch=batch, lm=lm,
    )
    dt = None
    if compute_dtype is not None:
        dt = compute_dtype
    return lint_jaxpr(
        closed, compute_dtype=dt,
        site="train step" if train else "eval step",
    )

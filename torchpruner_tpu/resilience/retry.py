"""Retry with exponential backoff and deterministic jitter.

For *transient* failures — flaky data loads, host-callback hiccups,
filesystem blips while writing a checkpoint — where the right response
is "wait a moment and try again", not "roll back to a checkpoint".
Persistent failures (the exception keeps coming) re-raise after the
budget is spent; non-retryable exception types pass straight through.

Jitter is deterministic (splitmix-style hash of ``seed`` + attempt), the
same policy the repo uses for data shuffling: two runs of the same
config produce the same sleep schedule, so retry behavior never makes a
resumed run diverge from an uninterrupted one.

:class:`Deadline` / :func:`with_retries` add the serving-plane half:
one attempt machine shared by data-stream retries AND the fleet
router's dispatch — max attempts, per-attempt timeout, and an overall
deadline budget, with pinned exhaustion-vs-deadline error ordering.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Optional, Tuple, Type

from torchpruner_tpu import obs


class DeadlineExceeded(TimeoutError):
    """The :class:`Deadline` ran out before the call succeeded.  The
    last transient failure (when one happened) is chained as
    ``__cause__`` so the operator sees WHY the budget was spent, not
    just that it was."""


@dataclass(frozen=True)
class Deadline:
    """An absolute time budget shared across retry attempts.

    A per-attempt timeout bounds one try; the deadline bounds the WHOLE
    operation (attempts + backoff sleeps) — the budget a caller with an
    SLA actually has.  Monotonic-clock based; create with
    :meth:`after`."""

    t_end: float
    budget_s: float

    @classmethod
    def after(cls, budget_s: float) -> "Deadline":
        return cls(t_end=time.monotonic() + float(budget_s),
                   budget_s=float(budget_s))

    def remaining(self) -> float:
        return max(0.0, self.t_end - time.monotonic())

    @property
    def expired(self) -> bool:
        return time.monotonic() >= self.t_end

    def clamp(self, timeout_s: Optional[float]) -> float:
        """The per-attempt timeout: ``timeout_s`` bounded by what is
        left of the budget (never negative)."""
        rem = self.remaining()
        return rem if timeout_s is None else min(float(timeout_s), rem)

#: exception types considered transient by default: data-loading /
#: host-callback I/O.  Deliberately narrow — an OOM or a NaN streak must
#: NOT be retried blindly (they have their own recovery paths in
#: ``guards`` / ``runner``).
DEFAULT_TRANSIENT: Tuple[Type[BaseException], ...] = (
    OSError, IOError, ConnectionError, TimeoutError,
)


def _jitter01(seed: int, attempt: int) -> float:
    """Deterministic uniform-ish [0, 1) from (seed, attempt) — splitmix64
    finalizer, matching the repo's shuffle hashing idiom."""
    z = (seed * 0x9E3779B97F4A7C15 + attempt * 0xBF58476D1CE4E5B9) \
        & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & 0xFFFFFFFFFFFFFFFF
    z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & 0xFFFFFFFFFFFFFFFF
    return ((z ^ (z >> 31)) & 0xFFFFFFFFFFFFFFFF) / 2.0**64


@dataclass(frozen=True)
class RetryPolicy:
    tries: int = 4                 # total attempts (1 = no retry)
    base_delay_s: float = 0.05     # delay before the 1st retry
    factor: float = 2.0            # exponential growth per retry
    max_delay_s: float = 2.0       # backoff ceiling
    jitter: float = 0.5            # +- fraction of the delay randomized
    seed: int = 0                  # jitter determinism

    def delay(self, attempt: int) -> float:
        """Sleep before retry ``attempt`` (1-based)."""
        d = min(self.base_delay_s * self.factor ** (attempt - 1),
                self.max_delay_s)
        if self.jitter:
            d *= 1.0 + self.jitter * (2.0 * _jitter01(self.seed, attempt)
                                      - 1.0)
        return max(0.0, d)


def with_retries(
    fn: Callable[[Optional[float]], object],
    *,
    policy: RetryPolicy = RetryPolicy(),
    deadline: Optional[Deadline] = None,
    attempt_timeout_s: Optional[float] = None,
    retry_on: Tuple[Type[BaseException], ...] = DEFAULT_TRANSIENT,
    label: str = "call",
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
):
    """The shared attempt machine under both the data-stream retries
    (:func:`retry_call`) and the fleet router's dispatch: bounded
    attempts, per-attempt timeout, deterministic-jitter exponential
    backoff, and an overall :class:`Deadline`.

    ``fn(timeout_s)`` is called with the per-attempt timeout — the
    caller's ``attempt_timeout_s`` clamped to the deadline's remaining
    budget (``None`` when neither bound is set; transports pass it to
    their socket timeout, plain calls may ignore it).

    Error ordering (test-pinned):

    - the deadline already expired before an attempt → raise
      :class:`DeadlineExceeded` (chained from the last failure, if any)
      WITHOUT burning another attempt;
    - the LAST allowed attempt fails → re-raise its exception unchanged
      (exhaustion wins over a simultaneous deadline expiry: the caller
      sees the real failure, not a wrapper);
    - a mid-budget failure whose backoff sleep would cross the deadline
      → :class:`DeadlineExceeded` chained from that failure (never
      sleep past the budget just to fail on arrival).
    """
    last: Optional[BaseException] = None
    for attempt in range(1, policy.tries + 1):
        if deadline is not None and deadline.expired:
            obs.inc("resilience_deadline_exceeded_total",
                    help="retry budgets cut short by their deadline")
            raise DeadlineExceeded(
                f"{label}: deadline ({deadline.budget_s:.3f}s) expired "
                f"after {attempt - 1} attempt(s)") from last
        timeout = (deadline.clamp(attempt_timeout_s)
                   if deadline is not None else attempt_timeout_s)
        try:
            return fn(timeout)
        except retry_on as e:  # noqa: PERF203 - retry loop by design
            last = e
            if attempt == policy.tries:
                raise
            obs.inc("resilience_retries_total",
                    help="transient-failure retries (retry_call)")
            if label != "call":
                # per-site breakdown: checkpoint-FS retries vs data-
                # stream retries are different operational signals
                obs.inc(f"resilience_retries_{label}_total",
                        help=f"transient-failure retries ({label})")
            if on_retry is not None:
                on_retry(attempt, e)
            delay = policy.delay(attempt)
            if deadline is not None and delay >= deadline.remaining():
                obs.inc("resilience_deadline_exceeded_total",
                        help="retry budgets cut short by their deadline")
                raise DeadlineExceeded(
                    f"{label}: deadline ({deadline.budget_s:.3f}s) "
                    f"leaves no room for the {delay:.3f}s backoff after "
                    f"attempt {attempt}") from e
            sleep(delay)
    raise last  # unreachable; keeps type checkers honest


def retry_call(
    fn: Callable,
    *args,
    policy: RetryPolicy = RetryPolicy(),
    retry_on: Tuple[Type[BaseException], ...] = DEFAULT_TRANSIENT,
    label: str = "call",
    sleep: Callable[[float], None] = time.sleep,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    **kwargs,
):
    """Call ``fn(*args, **kwargs)``, retrying transient failures with
    exponential backoff.  Each retry bumps ``resilience_retries_total``;
    exhausting the budget re-raises the LAST exception unchanged (the
    caller sees the real failure, not a wrapper).  Timeout-less facade
    over :func:`with_retries`."""
    return with_retries(
        lambda _timeout_s: fn(*args, **kwargs), policy=policy,
        retry_on=retry_on, label=label, sleep=sleep, on_retry=on_retry)


def retriable(policy: RetryPolicy = RetryPolicy(),
              retry_on: Tuple[Type[BaseException], ...] = DEFAULT_TRANSIENT,
              label: str = "call"):
    """Decorator form of :func:`retry_call`::

        @retriable(RetryPolicy(tries=3))
        def fetch_shard(i): ...
    """

    def deco(fn):
        import functools

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return retry_call(fn, *args, policy=policy, retry_on=retry_on,
                              label=label, **kwargs)

        return wrapped

    return deco

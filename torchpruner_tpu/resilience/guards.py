"""Step guards: non-finite detection/skip, OOM classification, preemption.

The compiled half of the non-finite guard lives in
``train.loop.make_step_body(guard=True)``: the step computes
``ok = isfinite(loss) & isfinite(global_norm(grads))`` and applies the
optimizer update, BN-state update, and opt-state transition only under
``ok`` (``jnp.where`` — the skip is inside the jitted program, so a NaN
step costs one wasted forward/backward, never a poisoned parameter).
This module holds the host half:

- :class:`StepGuard` counts skips, and after ``max_bad_steps``
  CONSECUTIVE bad steps raises :class:`NonFiniteStreakError` — the
  signal the resilient runner turns into rollback-to-last-checkpoint +
  LR backoff.  (One bad step is usually a data/numerics fluke the skip
  absorbs; a streak means the params or LR are already unhealthy, so
  skipping forever would silently stop training.)
- :func:`is_oom_error` classifies RESOURCE_EXHAUSTED / out-of-memory
  failures from any backend (and the chaos-injected synthetic one), the
  trigger for the runner's retry-with-doubled-``accum_steps`` path.
- :class:`PreemptionHandler` converts SIGTERM (the preemption notice TPU
  VMs get before the SIGKILL) into a flag checked at step boundaries, so
  the runner snapshots once, mesh-consistently, instead of dying
  mid-step.
"""

from __future__ import annotations

import signal
import threading
from dataclasses import dataclass, field
from typing import Optional

from torchpruner_tpu import obs


class NonFiniteStreakError(RuntimeError):
    """``max_bad_steps`` consecutive steps produced non-finite loss or
    gradients; the in-program skip is no longer enough."""

    def __init__(self, streak: int, total: int):
        self.streak = streak
        self.total = total
        super().__init__(
            f"{streak} consecutive non-finite train steps "
            f"({total} skipped total) — params are being held at their "
            "last finite values but training is not progressing; roll "
            "back to the last checkpoint and back off the LR"
        )


@dataclass
class StepGuard:
    """Host-side tracker fed one bool per guarded step."""

    max_bad_steps: int = 3
    consecutive: int = 0
    total_skips: int = 0

    def observe(self, bad: bool) -> bool:
        """Record one step's guard flag; returns ``bad``.  Raises
        :class:`NonFiniteStreakError` when the streak limit is hit."""
        if not bad:
            self.consecutive = 0
            return False
        self.consecutive += 1
        self.total_skips += 1
        obs.inc("resilience_nan_skips_total",
                help="train steps skipped by the non-finite guard")
        if self.max_bad_steps and self.consecutive >= self.max_bad_steps:
            raise NonFiniteStreakError(self.consecutive, self.total_skips)
        return True

    def reset(self) -> None:
        self.consecutive = 0


#: message fragments that identify an allocation failure across backends
#: (TPU/GPU XlaRuntimeError, CPU allocator, and the chaos injection)
_OOM_MARKERS = ("RESOURCE_EXHAUSTED", "Out of memory", "out of memory",
                "OOM", "failed to allocate")


def is_oom_error(e: BaseException) -> bool:
    """True when ``e`` is an allocation failure worth retrying with a
    smaller memory footprint (doubled ``accum_steps`` → halved
    microbatch activations)."""
    if isinstance(e, MemoryError):
        return True
    msg = str(e)
    return any(m in msg for m in _OOM_MARKERS)


def next_accum_for_oom(accum: int, batch_size: int) -> Optional[int]:
    """The ONE degradation policy after an OOM: double ``accum_steps``
    (halved microbatch activations), or ``None`` when nothing is left
    to degrade to (already at per-example microbatches, or the batch
    stops dividing).  Shared by the train runner and the prune-retrain
    recovery path so the cap logic cannot drift between them."""
    new = max(1, accum) * 2
    if new > batch_size or batch_size % new:
        return None
    return new


class Preempted(Exception):
    """Raised (by runner code, never by the handler itself) after a
    preemption snapshot commits — unwinds the pipeline cleanly."""


class PreemptionHandler:
    """SIGTERM → "snapshot at the next step boundary" flag.

    Use as a context manager around the training loop; poll
    :meth:`should_snapshot` at step boundaries.  Multi-process meshes
    must all snapshot at the SAME boundary: process 0's flag is the
    decision, broadcast through
    ``jax.experimental.multihost_utils.broadcast_one_to_all`` when more
    than one process is attached (every process checkpoints its region
    consistently; only process 0 writes the manifest).  The broadcast is
    a collective, so multi-process callers should poll at checkpoint
    boundaries, not every step; single-process polling is a plain flag
    read.  A second SIGTERM during a slow snapshot still terminates via
    the default handler once the context exits.
    """

    def __init__(self, signals=(signal.SIGTERM,)):
        self._signals = signals
        self._old = {}
        self._flag = threading.Event()
        self.installed = False

    # -- context management ------------------------------------------------

    def __enter__(self) -> "PreemptionHandler":
        try:
            for s in self._signals:
                self._old[s] = signal.signal(s, self._on_signal)
            self.installed = True
        except ValueError:
            # not the main thread (tests, embedded use): stay poll-only
            self.installed = False
        return self

    def __exit__(self, *exc) -> bool:
        for s, old in self._old.items():
            try:
                signal.signal(s, old)
            except ValueError:
                pass
        self._old.clear()
        return False

    def _on_signal(self, signum, _frame) -> None:
        self._flag.set()
        obs.inc("resilience_preemptions_total",
                help="preemption signals observed (SIGTERM)")

    # -- polling -----------------------------------------------------------

    @property
    def requested(self) -> bool:
        """This process's local view (no collective)."""
        return self._flag.is_set()

    def request(self) -> None:
        """Programmatic preemption (tests; in-process drain)."""
        self._flag.set()

    def should_snapshot(self) -> bool:
        """Mesh-consistent decision: in a multi-process runtime, process
        0's flag wins (broadcast); single-process reads the local flag."""
        local = self._flag.is_set()
        try:
            import jax

            if jax.process_count() <= 1:
                return local
            import numpy as np
            from jax.experimental import multihost_utils

            agreed = bool(
                multihost_utils.broadcast_one_to_all(np.asarray(local))
            )
            if agreed:
                self._flag.set()  # every process commits to the snapshot
            return agreed
        except Exception:
            return local

"""Resumable pipeline drivers: the wiring between checkpoints, manifests,
guards, chaos, and the experiment loops.

Commit protocol (everything else follows from it):

1. write a NEW digest-sealed checkpoint directory ``ckpt-…`` (atomic
   within itself — ``checkpoint.save_checkpoint``);
2. atomically replace ``manifest.json`` to point at it (position: epoch,
   data cursor, completed rounds, LR backoff, accum override);
3. garbage-collect superseded checkpoint dirs.

A SIGKILL between any two instructions leaves the manifest referencing a
complete checkpoint; resume = load manifest → restore its checkpoint →
fast-forward the deterministic data stream past ``batch_cursor`` → keep
going.  The resumed trajectory is the uninterrupted one (same rng, same
shuffle, same batches), which is what the crash-resume test pins.

Recovery paths on top of the same machinery:

- **NaN/Inf streak** (``StepGuard`` raising ``NonFiniteStreakError``):
  roll back to the manifest's checkpoint, multiply the LR by
  ``cfg.lr_backoff`` (an ``optax.scale`` stage whose factor changes but
  whose treedef doesn't, so restored opt-state stays valid), retry.
- **OOM** (``is_oom_error``): roll back, double ``accum_steps`` (halved
  microbatch activations), recompile, retry — the classic graceful
  degradation for a batch that stopped fitting after a config change.
- **Preemption** (SIGTERM): snapshot at the next step boundary
  (process 0 writes; the flag is broadcast so a mesh snapshots one
  consistent boundary), mark the manifest ``preempted``, unwind.

Single-writer note: checkpoint/manifest writes are gated on
``jax.process_index() == 0``.  Multi-host sharded array trees would need
orbax's collective save; the manifest/commit protocol is already
host-agnostic.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from torchpruner_tpu import obs
from torchpruner_tpu.resilience import chaos
from torchpruner_tpu.resilience.guards import (
    NonFiniteStreakError,
    Preempted,
    PreemptionHandler,
    StepGuard,
    is_oom_error,
    next_accum_for_oom,
)
from torchpruner_tpu.resilience.manifest import RunManifest, atomic_write_json
from torchpruner_tpu.resilience.retry import (
    DEFAULT_TRANSIENT,
    RetryPolicy,
    retry_call,
)

_CKPT_RETRY = RetryPolicy(tries=3, base_delay_s=0.1)


def rng_to_list(rng) -> list:
    import jax

    return np.asarray(jax.device_get(rng), dtype=np.uint32).tolist()


def rng_from_list(lst):
    import jax.numpy as jnp

    return jnp.asarray(np.asarray(lst, dtype=np.uint32))


def _is_writer() -> bool:
    try:
        import jax

        return jax.process_index() == 0
    except Exception:
        return True


def _preempt_agreed(pre: PreemptionHandler, at_boundary: bool = True) -> bool:
    """Mesh-safe preemption poll.  Single-process: the local flag, any
    time.  Multi-process: ONLY at ``at_boundary`` points that every
    process reaches deterministically (checkpoint cadence, epoch/round
    ends) — the broadcast inside ``should_snapshot`` is a collective,
    so gating it on the process-LOCAL flag would have the signalled
    process enter the collective while the others skip it, hanging the
    mesh.  Here every process either calls it or doesn't, together."""
    try:
        import jax

        multi = jax.process_count() > 1
    except Exception:
        multi = False
    if multi:
        return at_boundary and pre.should_snapshot()
    return pre.requested


def _quarantine_cache_on_resume(verbose: bool) -> None:
    """CPU resume processes must not read the persistent XLA cache —
    see ``utils.compilation_cache.quarantine_for_resume`` for the
    chaos-drill evidence (heap corruption in cache deserialize)."""
    from torchpruner_tpu.utils.compilation_cache import quarantine_for_resume

    if quarantine_for_resume() and verbose:
        print(
            "[resilience] resume on CPU: persistent XLA compilation "
            "cache disabled for this process (deserialize instability; "
            "recompiles instead)", flush=True,
        )


def scaled_optimizer(cfg, steps_per_epoch: int, lr_scale: float,
                     total_epochs: Optional[int] = None):
    """The config's optimizer with the rollback LR-backoff stage chained
    on.  ``optax.scale``'s state is empty, so every ``lr_scale`` value
    yields the SAME opt-state treedef — a checkpoint saved before a
    backoff restores cleanly after it."""
    import optax

    from torchpruner_tpu.experiments.prune_retrain import make_optimizer

    return optax.chain(
        make_optimizer(cfg, steps_per_epoch=steps_per_epoch,
                       total_epochs=total_epochs),
        optax.scale(lr_scale),
    )


def commit_checkpoint(run_dir: str, manifest: RunManifest, trainer, *,
                      epoch: int, batch_cursor: int,
                      stage: Optional[Dict[str, Any]] = None,
                      records: Optional[List[dict]] = None,
                      status: str = "running") -> None:
    """The 3-step commit described in the module docstring.  Timed into
    ``checkpoint_write_seconds``; the checkpoint write itself is
    retry-wrapped (transient FS errors happen exactly when a preempting
    node is being drained).  No-op on non-writer processes."""
    if not _is_writer():
        return
    from torchpruner_tpu.checkpoint import save_checkpoint

    manifest.commits = getattr(manifest, "commits", 0) + 1
    name = f"ckpt-{manifest.commits:06d}-s{int(trainer.step_count):08d}"
    path = os.path.join(run_dir, name)
    t0 = time.perf_counter()
    with obs.span("checkpoint_write", ckpt=name):
        retry_call(
            save_checkpoint, path, trainer.model, trainer.params,
            trainer.state, trainer.opt_state,
            step=int(trainer.step_count),
            extra={"rng": rng_to_list(trainer.rng), "epoch": epoch,
                   "batch_cursor": batch_cursor},
            policy=_CKPT_RETRY, label="checkpoint_write",
        )
    obs.observe("checkpoint_write_seconds", time.perf_counter() - t0,
                help="wall seconds per committed checkpoint write")
    if chaos.active():
        # fault injection AFTER the write: the digest must catch it
        chaos.corrupt_checkpoint_bytes(path)
    manifest.checkpoint = name
    manifest.step = int(trainer.step_count)
    manifest.epoch = epoch
    manifest.batch_cursor = batch_cursor
    if stage is not None:
        manifest.stage = stage
    if records is not None:
        manifest.records = records
    manifest.status = status
    retry_call(manifest.save, run_dir, policy=_CKPT_RETRY,
               label="manifest_write")
    manifest.gc_checkpoints(run_dir)


def restore_committed(run_dir: str, manifest: RunManifest, tx):
    """Load the manifest's checkpoint → ``(model, params, state,
    opt_state, meta)`` (digest-verified; raises CheckpointCorruptError on
    damage)."""
    from torchpruner_tpu.checkpoint import restore_checkpoint

    return restore_checkpoint(os.path.join(run_dir, manifest.checkpoint),
                              tx=tx)


# -- the resumable from-scratch training driver -----------------------------


_SENTINEL = object()


def _floats(losses) -> List[float]:
    """Fence + filter: device scalars → finite floats (guard-skipped
    steps report NaN loss and are excluded from epoch means)."""
    out = []
    for v in losses:
        f = float(v)
        if np.isfinite(f):
            out.append(f)
    return out


def run_resilient_train(cfg, *, model=None, datasets=None,
                        verbose: bool = True):
    """``experiments.train_model.run_train`` semantics with the full
    resilience loop (activated by ``cfg.run_dir``; ``run_train``
    delegates here).  Returns ``(trainer, history)`` where ``history``
    spans ALL attempts — a resumed run returns the epochs its
    predecessors completed too."""
    import jax
    import jax.numpy as jnp

    from torchpruner_tpu.data.native import device_prefetch
    from torchpruner_tpu.experiments.prune_retrain import (
        LOSS_REGISTRY,
        resolve_model_and_data,
    )
    from torchpruner_tpu.experiments.train_model import epoch_batches
    from torchpruner_tpu.train.logger import CSVLogger
    from torchpruner_tpu.train.loop import trainer_from_config

    if cfg.chaos:
        chaos.configure(cfg.chaos)
    run_dir = os.path.abspath(cfg.run_dir)
    os.makedirs(run_dir, exist_ok=True)
    manifest = RunManifest.load_or_new(run_dir, kind="train",
                                       experiment=cfg.name)
    resuming = bool(manifest.checkpoint)

    model, (train, _val, test) = resolve_model_and_data(cfg, model, datasets)
    spe = max(1, len(train) // cfg.batch_size)
    loss_fn = LOSS_REGISTRY[cfg.loss]
    accum = manifest.accum_steps or cfg.accum_steps
    guard = StepGuard(cfg.max_bad_steps) if cfg.guard_nonfinite else None
    mesh = None
    data_size = 1
    if cfg.mesh:
        # SPMD resilient training: the same manifest/commit protocol over
        # a ShardedTrainer (FSDP/TP placement, optional ZeRO update
        # sharding) — checkpoints gather to host on save, and restore
        # re-places every tree (opt state included, at the ZeRO
        # placement when cfg.zero) through rebuild()
        from torchpruner_tpu.parallel import make_mesh

        mesh = make_mesh(cfg.mesh)
        data_size = int(dict(mesh.shape).get("data", 1))

    def build_trainer(params=None, state=None, opt_state=None):
        # restored trees are ADOPTED at their actual (possibly pruned)
        # shapes — on the mesh path the opt state lands directly at its
        # sharded placement (the ZeRO domain when cfg.zero)
        return trainer_from_config(
            cfg, model, scaled_optimizer(cfg, spe, manifest.lr_scale),
            loss_fn, mesh=mesh, params=params, state=state,
            opt_state=opt_state, accum_steps=accum,
            grad_norm=cfg.obs_grad_norm, guard=guard,
        )

    def restore_trainer():
        nonlocal model
        tx = scaled_optimizer(cfg, spe, manifest.lr_scale)
        m2, p2, s2, o2, meta = restore_committed(run_dir, manifest, tx)
        model = m2
        t = build_trainer(params=p2, state=s2, opt_state=o2)
        rng = meta.get("extra", {}).get("rng")
        if rng is not None:
            t.rng = rng_from_list(rng)
        t.step_count = int(meta.get("step", 0))
        return t

    if resuming:
        _quarantine_cache_on_resume(verbose)
        # injections already survived before the commit stay dead — a
        # kill step coinciding with a commit boundary must not re-kill
        chaos.disarm_through(manifest.step)
        trainer = restore_trainer()
        manifest.resumes += 1
        obs.inc("resilience_resumes_total",
                help="runs resumed from a manifest + checkpoint")
        if verbose:
            print(
                f"[{cfg.name}] resumed from {manifest.checkpoint} "
                f"(epoch {manifest.epoch}, step {manifest.step}, "
                f"cursor {manifest.batch_cursor}, "
                f"resume #{manifest.resumes})", flush=True,
            )
    else:
        trainer = build_trainer()

    logger = CSVLogger(cfg.log_path, experiment=cfg.name)
    test_batches = test.batches(cfg.eval_batch_size)
    history: List[dict] = [dict(r) for r in manifest.records]
    # ledger continuity: epochs committed before the kill rehydrate from
    # the manifest (deduped against a reused obs dir's own records)
    if history:
        obs.ledger_backfill(history, kind="epoch")
    epoch = manifest.epoch
    cursor = manifest.batch_cursor
    losses: List[Any] = list(manifest.stage.get("losses", []))
    every = cfg.checkpoint_every_steps
    data_retry = RetryPolicy(tries=4, base_delay_s=0.02, seed=cfg.seed)

    def snapshot(status: str = "running") -> None:
        losses[:] = _floats(losses)
        commit_checkpoint(
            run_dir, manifest, trainer, epoch=epoch, batch_cursor=cursor,
            stage={"losses": list(losses)},
            records=list(history), status=status,
        )

    def rollback(reason: str):
        nonlocal trainer, epoch, cursor, losses
        if not manifest.checkpoint:
            raise RuntimeError(
                f"cannot roll back ({reason}): no checkpoint committed "
                "yet — set checkpoint_every_steps > 0 for early coverage"
            )
        obs.inc("resilience_rollbacks_total",
                help="rollback-to-checkpoint recoveries")
        trainer = restore_trainer()
        if guard is not None:
            guard.reset()
        epoch = manifest.epoch
        cursor = manifest.batch_cursor
        losses = list(manifest.stage.get("losses", []))
        if verbose:
            print(f"[{cfg.name}] rolled back to {manifest.checkpoint} "
                  f"({reason})", flush=True)

    try:
        with PreemptionHandler() as pre:
            while epoch < cfg.epochs:
                try:
                    t0 = time.perf_counter()

                    def open_stream():
                        """(Re)establish this epoch's batch stream
                        fast-forwarded to the current cursor — the
                        shuffle is deterministic, so re-opening after a
                        transient failure replays the exact remaining
                        batches."""
                        s = epoch_batches(train, cfg, epoch)
                        for _ in range(cursor):
                            next(s)
                        if cfg.device_prefetch:
                            s = device_prefetch(
                                s, size=cfg.device_prefetch)
                        return iter(s)

                    def next_batch(it):
                        """One fetch, with REAL transient-data retry: a
                        generator that raised is closed for good, so
                        recovery re-opens the stream at the cursor
                        rather than re-polling the corpse (which would
                        silently truncate the epoch)."""
                        attempt = 0
                        while True:
                            try:
                                if chaos.active():
                                    chaos.maybe_fail_data(
                                        trainer.step_count)
                                    chaos.maybe_delay()
                                return it, next(it, _SENTINEL)
                            except DEFAULT_TRANSIENT:
                                attempt += 1
                                if attempt >= data_retry.tries:
                                    raise
                                obs.inc("resilience_retries_total",
                                        help="transient-failure "
                                             "retries (retry_call)")
                                obs.inc(
                                    "resilience_retries_data_fetch_total",
                                    help="transient-failure retries "
                                         "(data_fetch)")
                                time.sleep(data_retry.delay(attempt))
                                it = open_stream()

                    it = open_stream()
                    with obs.span("train", epoch=epoch):
                        while True:
                            it, batch = next_batch(it)
                            if batch is _SENTINEL:
                                break
                            x, y = batch
                            if accum > 1 and x.shape[0] % accum:
                                # OOM-degraded accumulation can't split a
                                # ragged tail batch; drop it (counted —
                                # never silently) and keep the cursor
                                # aligned with the stream
                                cursor += 1
                                obs.inc(
                                    "resilience_ragged_drops_total",
                                    help="tail batches dropped because "
                                         "they don't divide the degraded "
                                         "accum_steps")
                                continue
                            if data_size > 1 and x.shape[0] % data_size:
                                # shard_batch requires the example dim to
                                # divide the data axis; the epoch's ragged
                                # tail can't — drop it, counted, cursor
                                # still aligned with the stream
                                cursor += 1
                                obs.inc(
                                    "resilience_mesh_ragged_drops_total",
                                    help="tail batches dropped because "
                                         "they don't divide the mesh's "
                                         "data axis")
                                continue
                            losses.append(trainer.step(x, y))
                            cursor += 1
                            if len(losses) % 8 == 0:
                                # bound async run-ahead without draining
                                jax.block_until_ready(losses[-8])
                            due = bool(every
                                       and trainer.step_count % every == 0)
                            if _preempt_agreed(pre, at_boundary=due):
                                snapshot(status="preempted")
                                raise Preempted()
                            if due:
                                snapshot()
                    epoch_losses = _floats(losses)
                    with obs.span("eval", epoch=epoch):
                        test_loss, test_acc = trainer.evaluate(test_batches)
                    rec = {
                        "epoch": epoch,
                        "train_loss": float(np.mean(epoch_losses))
                        if epoch_losses else float("nan"),
                        "test_loss": test_loss,
                        "test_acc": test_acc,
                        "seconds": time.perf_counter() - t0,
                    }
                    history.append(rec)
                    obs.record_epoch(**rec)
                    logger.log_epoch(
                        epoch=epoch, train_loss=rec["train_loss"],
                        test_loss=test_loss, test_acc=test_acc,
                        seconds=rec["seconds"],
                    )
                    if verbose:
                        print(
                            f"[{cfg.name}] epoch {epoch}: train "
                            f"{rec['train_loss']:.4f} test {test_loss:.4f} "
                            f"acc {test_acc:.4f} "
                            f"({rec['seconds']:.1f}s)", flush=True,
                        )
                    epoch += 1
                    cursor = 0
                    losses = []
                    # epoch boundaries always commit: the manifest must
                    # never point BEHIND completed work.  They are also
                    # the multi-process preemption boundary when no step
                    # cadence is configured.
                    if _preempt_agreed(pre, at_boundary=True):
                        snapshot(status="preempted")
                        raise Preempted()
                    snapshot()
                except NonFiniteStreakError as e:
                    manifest.rollbacks += 1
                    if manifest.rollbacks > cfg.max_rollbacks:
                        raise
                    manifest.lr_scale *= cfg.lr_backoff
                    rollback(f"{e.streak} consecutive non-finite steps; "
                             f"lr_scale -> {manifest.lr_scale:g}")
                except Exception as e:  # noqa: BLE001 - classified below
                    if not is_oom_error(e):
                        raise
                    new_accum = next_accum_for_oom(accum, cfg.batch_size)
                    if new_accum is None:
                        raise  # nothing left to degrade to
                    obs.inc("resilience_oom_retries_total",
                            help="OOM recoveries via doubled accum_steps")
                    accum = new_accum
                    manifest.accum_steps = accum
                    rollback(f"OOM; accum_steps -> {accum} "
                             f"(microbatch {cfg.batch_size // accum})")
    except Preempted:
        if verbose:
            print(f"[{cfg.name}] preempted: snapshot committed at step "
                  f"{manifest.step}; re-run with --resume {run_dir} to "
                  "continue", flush=True)
        logger.close()
        return trainer, history

    manifest.status = "done"
    if _is_writer():
        manifest.save(run_dir)
    logger.close()
    return trainer, history


# -- prune-retrain journal ---------------------------------------------------


class PruneJournal:
    """Round-granular resume for ``run_prune_retrain``: which targets
    completed (with their full :class:`PruneStepRecord` payloads), and —
    mid-round — whether the prune was applied and how many retrain
    epochs ran, so a kill during fine-tuning resumes at the next epoch
    of the SAME target instead of re-scoring it."""

    def __init__(self, cfg):
        self.cfg = cfg
        self.run_dir = os.path.abspath(cfg.run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.manifest = RunManifest.load_or_new(
            self.run_dir, kind="prune_retrain", experiment=cfg.name)
        self.resuming = bool(self.manifest.checkpoint)
        self.pre = PreemptionHandler().__enter__()
        if self.resuming:
            _quarantine_cache_on_resume(verbose=True)
            chaos.disarm_through(self.manifest.step)
            self.manifest.resumes += 1
            obs.inc("resilience_resumes_total",
                    help="runs resumed from a manifest + checkpoint")

    @property
    def completed(self) -> List[str]:
        return self.manifest.completed

    @property
    def lr_scale(self) -> float:
        return self.manifest.lr_scale

    def records(self) -> List[dict]:
        return [dict(r) for r in self.manifest.records]

    def stage_for(self, target: str) -> Optional[Dict[str, Any]]:
        """Mid-round state for ``target`` if the run died during its
        retrain phase (prune already applied)."""
        st = self.manifest.stage
        if st.get("phase") == "retrain" and st.get("target") == target:
            return st
        return None

    def restore(self, tx):
        return restore_committed(self.run_dir, self.manifest, tx)

    # -- commits ----------------------------------------------------------

    def _commit(self, trainer, stage, status="running"):
        # persist OOM degradation: without this a resumed run would
        # rebuild at the config's accum_steps and re-OOM on its first
        # retrain step, paying a rollback cycle per resume
        acc = int(getattr(trainer, "accum_steps", 0) or 0)
        self.manifest.accum_steps = \
            acc if acc != self.cfg.accum_steps else 0
        commit_checkpoint(
            self.run_dir, self.manifest, trainer,
            epoch=len(self.manifest.completed), batch_cursor=0,
            stage=stage, records=self.manifest.records, status=status,
        )

    def pruned(self, trainer, target: str, stage: Dict[str, Any]) -> None:
        """Prune applied, retrain not started — the mid-round anchor."""
        stage = dict(stage, phase="retrain", target=target,
                     retrain_epoch=0)
        self._commit(trainer, stage)

    def retrain_epoch_done(self, trainer, target: str, epoch: int) -> None:
        if not self.cfg.checkpoint_every_steps:
            # round-boundary-only cadence: no per-epoch checkpoint, and
            # the stage's retrain_epoch deliberately stays at the last
            # COMMITTED anchor (advancing it without a checkpoint would
            # make resume skip epochs the checkpoint never saw)
            return
        stage = dict(self.manifest.stage, retrain_epoch=epoch)
        self._commit(trainer, stage)

    def round_done(self, trainer, target: str, record: dict) -> None:
        self.manifest.completed.append(target)
        self.manifest.records.append(record)
        self._commit(trainer, stage={})

    def check_preempt(self, trainer,
                      stage: Optional[Dict[str, Any]] = None) -> None:
        """Target/retrain-epoch boundaries — deterministic across the
        mesh, so the multi-process agreement can poll here.  ``stage``
        must describe the trainer being snapshotted: a mid-retrain call
        passes its current ``retrain_epoch``, otherwise the (possibly
        stale) last-committed stage would make the resumed run redo
        epochs on top of already-retrained params."""
        if _preempt_agreed(self.pre, at_boundary=True):
            self._commit(trainer,
                         stage=(stage if stage is not None
                                else dict(self.manifest.stage)),
                         status="preempted")
            raise Preempted()

    def on_streak(self, e: NonFiniteStreakError) -> None:
        """Budget + LR backoff bookkeeping; caller restores the trainer."""
        self.manifest.rollbacks += 1
        if self.manifest.rollbacks > self.cfg.max_rollbacks:
            raise e
        self.manifest.lr_scale *= self.cfg.lr_backoff
        obs.inc("resilience_rollbacks_total",
                help="rollback-to-checkpoint recoveries")

    def close(self) -> None:
        """Restore the SIGTERM handler (idempotent) — MUST run on every
        exit path, or later code in the process silently swallows
        preemption notices."""
        self.pre.__exit__(None, None, None)

    def done(self) -> None:
        self.manifest.status = "done"
        if _is_writer():
            self.manifest.save(self.run_dir)
        self.close()


# -- robustness-sweep journal ------------------------------------------------


class SweepJournal:
    """Layer-granular resume for the robustness sweep: completed layers'
    full results persist (atomically) in ``sweep_results.json`` inside
    the run dir; a resumed sweep skips them and merges at the end.  The
    sweep holds no optimizer state, so there is no checkpoint — the
    results file IS the durable artifact."""

    RESULTS_NAME = "sweep_results.json"

    def __init__(self, cfg):
        self.cfg = cfg
        self.run_dir = os.path.abspath(cfg.run_dir)
        os.makedirs(self.run_dir, exist_ok=True)
        self.manifest = RunManifest.load_or_new(
            self.run_dir, kind="robustness", experiment=cfg.name)
        self.pre = PreemptionHandler().__enter__()
        self.results_path = os.path.join(self.run_dir, self.RESULTS_NAME)
        self.saved: Dict[str, Any] = {}
        if os.path.exists(self.results_path):
            from torchpruner_tpu.resilience.manifest import read_json

            self.saved = read_json(self.results_path)
        self.resuming = bool(self.manifest.completed)
        if self.resuming:
            self.manifest.resumes += 1
            obs.inc("resilience_resumes_total",
                    help="runs resumed from a manifest + checkpoint")
            if _is_writer():
                self.manifest.save(self.run_dir)

    def remaining(self, layers: List[str]) -> List[str]:
        done = set(self.manifest.completed)
        return [l for l in layers if l not in done]

    def on_layer(self, layer: str, layer_results: Dict[str, list]) -> None:
        """Persist one finished layer (listified for JSON) and advance
        the manifest — then honor a pending preemption at this
        boundary."""
        self.saved[layer] = {
            m: [
                {k: (np.asarray(v).tolist()
                     if hasattr(v, "__array__") else v)
                 for k, v in r.items()}
                for r in runs
            ]
            for m, runs in layer_results.items()
        }
        if _is_writer():
            retry_call(atomic_write_json, self.results_path, self.saved,
                       policy=_CKPT_RETRY, label="sweep_results")
            self.manifest.completed.append(layer)
            self.manifest.save(self.run_dir)
        if _preempt_agreed(self.pre, at_boundary=True):
            self.manifest.status = "preempted"
            if _is_writer():
                self.manifest.save(self.run_dir)
            raise Preempted()

    def merged(self, fresh: Dict[str, Dict[str, list]]):
        out = dict(self.saved)
        for layer, methods in fresh.items():
            out[layer] = methods
        return out

    def close(self) -> None:
        """See PruneJournal.close — idempotent handler restore."""
        self.pre.__exit__(None, None, None)

    def done(self) -> None:
        self.manifest.status = "done"
        if _is_writer():
            self.manifest.save(self.run_dir)
        self.close()

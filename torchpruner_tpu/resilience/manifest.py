"""Run manifests: the durable record of *pipeline position*.

A checkpoint (``checkpoint.py``) captures the training *state* — params,
optimizer slots, BN statistics, model widths.  It does not say where the
PIPELINE was: which prune round, which retrain epoch, how many batches of
the current epoch were consumed, what LR backoff is in force.  The
:class:`RunManifest` records exactly that, as a small JSON file written
atomically (tmp + fsync + ``os.replace``) next to the checkpoints it
points at, so a preempted or killed run re-enters ``run_prune_retrain`` /
``run_train`` / the robustness sweep mid-round instead of from scratch.

Commit protocol (see ``resilience.runner``): write the new checkpoint
directory first, then atomically replace ``manifest.json`` to point at
it.  A crash at ANY instant leaves the manifest referencing a complete,
digest-verified checkpoint — the half-written one is garbage-collected on
the next resume.
"""

from __future__ import annotations

import dataclasses
import json
import os
import tempfile
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

MANIFEST_NAME = "manifest.json"
MANIFEST_VERSION = 1


def fsync_dir(path: str) -> None:
    """Best-effort directory fsync — makes the rename durable on POSIX
    filesystems that need it; silently skipped where unsupported."""
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_json(path: str, obj: Any, *, indent: Optional[int] = 2,
                      default=None) -> None:
    """Write ``obj`` as JSON such that ``path`` is either the old complete
    file or the new complete file — never a truncated hybrid.  The
    standard tmp-in-same-dir + flush + fsync + ``os.replace`` dance.
    Shared by the manifest, checkpoint specs, and the obs exporters
    (``default`` hooks non-JSON leaf types; ``indent=None`` for compact
    payloads like trace.json)."""
    path = os.path.abspath(path)
    d = os.path.dirname(path)
    os.makedirs(d, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=d, prefix=".tmp.", suffix=".json")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(obj, f, indent=indent, default=default)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
        fsync_dir(d)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def read_json(path: str) -> Any:
    with open(path) as f:
        return json.load(f)


@dataclass
class RunManifest:
    """Pipeline position for one resumable run directory.

    ``kind`` names the driver that owns the run ("train",
    "prune_retrain", "robustness") — resuming under a different driver is
    refused, since their ``stage`` payloads are not interchangeable.
    ``checkpoint`` is the run-dir-relative name of the last COMMITTED
    checkpoint directory ("" before the first commit).  ``stage`` is the
    driver's own mid-round position (retrain epoch, partial-epoch loss
    list, pre-prune eval stats, ...), opaque to this module.
    """

    kind: str = "train"
    experiment: str = "experiment"
    version: int = MANIFEST_VERSION
    #: run-dir-relative directory name of the last committed checkpoint
    checkpoint: str = ""
    #: global optimizer-step count at the last commit
    step: int = 0
    #: epoch (train) / round index (prune_retrain) at the last commit
    epoch: int = 0
    #: batches of the CURRENT epoch already consumed at the last commit —
    #: the data cursor a resume fast-forwards the deterministic shuffle
    #: stream past
    batch_cursor: int = 0
    #: completed unit-of-work names (prune targets / sweep layers)
    completed: List[str] = field(default_factory=list)
    #: serialized per-round records (PruneStepRecord dicts / epoch rows) so
    #: a resumed run returns the FULL history, not just its own tail
    records: List[Dict[str, Any]] = field(default_factory=list)
    #: LR backoff multiplier currently in force (rollback halves it)
    lr_scale: float = 1.0
    #: accum_steps override after OOM degradation (0 = use the config's)
    accum_steps: int = 0
    #: monotone commit counter (names checkpoint dirs uniquely even when
    #: two commits land at the same optimizer step)
    commits: int = 0
    #: how many times this run has been resumed
    resumes: int = 0
    #: how many rollback-to-checkpoint recoveries have fired
    rollbacks: int = 0
    #: "running" | "preempted" | "done"
    status: str = "running"
    #: driver-specific mid-round position (opaque here)
    stage: Dict[str, Any] = field(default_factory=dict)

    # -- persistence -------------------------------------------------------

    @staticmethod
    def path_in(run_dir: str) -> str:
        return os.path.join(os.path.abspath(run_dir), MANIFEST_NAME)

    @classmethod
    def exists_in(cls, run_dir: str) -> bool:
        return os.path.exists(cls.path_in(run_dir))

    @classmethod
    def load(cls, run_dir: str) -> "RunManifest":
        raw = read_json(cls.path_in(run_dir))
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in raw.items() if k in known})

    @classmethod
    def load_or_new(cls, run_dir: str, *, kind: str,
                    experiment: str) -> "RunManifest":
        """Resume semantics: an existing manifest is loaded (and must have
        been written by the same ``kind`` of driver); otherwise a fresh
        one is created in memory (committed on the first checkpoint)."""
        if cls.exists_in(run_dir):
            m = cls.load(run_dir)
            if m.kind != kind:
                raise ValueError(
                    f"run dir {run_dir!r} holds a {m.kind!r} manifest — "
                    f"refusing to resume it as a {kind!r} run (their "
                    "stage payloads are not interchangeable; use a fresh "
                    "directory)"
                )
            return m
        return cls(kind=kind, experiment=experiment)

    def save(self, run_dir: str) -> None:
        atomic_write_json(self.path_in(run_dir), dataclasses.asdict(self))

    # -- checkpoint dir bookkeeping ---------------------------------------

    def gc_checkpoints(self, run_dir: str, keep: int = 2) -> None:
        """Delete ``ckpt-*`` directories not among the ``keep`` most
        recently committed (the manifest's current pointer is always
        kept).  A half-written checkpoint from a crash is itself a
        ``ckpt-*`` dir, so it ages out here too; intra-checkpoint
        ``.arrays.*`` litter is swept by ``save_checkpoint``.
        Best-effort: GC failure never fails a commit."""
        import shutil

        try:
            entries = sorted(
                (e for e in os.listdir(run_dir) if e.startswith("ckpt-")),
                key=lambda e: os.path.getmtime(os.path.join(run_dir, e)),
            )
        except OSError:
            return
        survivors = set(entries[-keep:]) | {self.checkpoint}
        for e in entries:
            if e not in survivors:
                shutil.rmtree(os.path.join(run_dir, e), ignore_errors=True)

"""Resilience layer: preemption-safe resumable pipelines, deterministic
fault injection, non-finite/OOM step guards with rollback, and
retry/backoff for transient failures.

The north-star workload — attribution → prune → retrain on preemptible
TPU slices — dies mid-run as a matter of course: SIGTERM'd by the
scheduler, NaN'd by an unlucky LR, RESOURCE_EXHAUSTED by a batch that no
longer fits.  This package makes every one of those a *resume*, not a
*restart*:

- :mod:`~torchpruner_tpu.resilience.manifest` — :class:`RunManifest`:
  atomically-written JSON pipeline position (prune round, epoch, data
  cursor, rng, LR backoff) next to digest-verified checkpoints.
- :mod:`~torchpruner_tpu.resilience.chaos` — deterministic fault
  injection (NaN grads at step k, SIGKILL, synthetic OOM, corrupt
  checkpoint bytes, data-load failures) so recovery paths are *tested*
  code, not hope.
- :mod:`~torchpruner_tpu.resilience.guards` — host half of the compiled
  non-finite guard (:class:`StepGuard` → rollback + LR backoff after M
  consecutive skips), OOM classification, SIGTERM → snapshot handling.
- :mod:`~torchpruner_tpu.resilience.retry` — exponential backoff with
  deterministic jitter for transient data/host-callback errors.
- :mod:`~torchpruner_tpu.resilience.runner` — the resumable drivers
  wiring all of it through ``run_train`` / ``run_prune_retrain`` / the
  robustness sweep (imported lazily: it depends on the train loop, which
  itself uses the chaos hooks above).

Everything emits obs counters/spans (``resilience_nan_skips_total``,
``resilience_resumes_total``, ``resilience_rollbacks_total``,
``checkpoint_write_seconds``, ``chaos:*``), so recovery is visible in
the same telemetry stream as the work it saves.

Design refs: JaxPruner's checkpointable-sparsity-state argument
(arXiv:2304.14082) and the TPU structured-pruning study's long
prune/retrain schedules (arXiv:2107.04191) — see PAPERS.md.
"""

from torchpruner_tpu.resilience import chaos
from torchpruner_tpu.resilience.guards import (
    NonFiniteStreakError,
    Preempted,
    PreemptionHandler,
    StepGuard,
    is_oom_error,
)
from torchpruner_tpu.resilience.manifest import (
    RunManifest,
    atomic_write_json,
)
from torchpruner_tpu.resilience.retry import (
    Deadline,
    DeadlineExceeded,
    RetryPolicy,
    retriable,
    retry_call,
    with_retries,
)

__all__ = [
    "chaos",
    "ChaosConfig",
    "NonFiniteStreakError",
    "Preempted",
    "PreemptionHandler",
    "StepGuard",
    "is_oom_error",
    "RunManifest",
    "atomic_write_json",
    "Deadline",
    "DeadlineExceeded",
    "RetryPolicy",
    "retriable",
    "retry_call",
    "with_retries",
]

ChaosConfig = chaos.ChaosConfig

"""Deterministic fault injection ("chaos") for recovery-path testing.

Real failures — NaN gradients, preemption SIGKILLs, RESOURCE_EXHAUSTED,
flaky data loads — are rare and nondeterministic, which makes recovery
code the least-tested code in a training stack.  This module turns each
failure mode into a *deterministic, step-indexed* event driven by config
(``ExperimentConfig.chaos``), the CLI (``--chaos``), or the
``TORCHPRUNER_CHAOS`` env var (JSON), so tests, the CI chaos smoke, and
the ``bench.py`` resilience leg exercise every recovery path on demand:

    {"nan_at_step": 5}          # poison step 5's batch with NaNs
    {"kill_at_step": 12}        # SIGKILL the process at step 12's boundary
    {"oom_at_step": 3}          # synthetic RESOURCE_EXHAUSTED at step 3
    {"fail_data_at_step": 2}    # transient OSError from the batch stream
    {"corrupt_checkpoint": true} # flip bytes in the next saved checkpoint
    {"delay_callback_s": 0.05}  # stall host callbacks / data fetch once
    {"slow_steps_ms": 5}        # stall EVERY serve decode step (slow replica)

Hooks are wired into ``Trainer.step`` / ``ShardedTrainer.step`` and the
resilient runner; every hook is a single module-global ``None`` check
when chaos is not configured, so production paths pay nothing.  Step
indices are GLOBAL optimizer-step counts (``trainer.step_count``), and
each injection fires at most once per process by default (``once``).
A resumed process has a fresh fired-set, so the resilient runners call
:func:`disarm_through` with the restored step count — injections at or
before the cursor stay dead even when a commit boundary coincides with
the injection step (without this, config/env-persisted ``kill_at_step``
could re-kill every resume and never progress).

Every firing emits an obs ``chaos:*`` span and bumps
``chaos_injections_total``, so recovery shows up in the telemetry stream
right next to the ``resilience_*`` counters it should trigger.
"""

from __future__ import annotations

import dataclasses
import json
import os
import signal
import time
from dataclasses import dataclass
from typing import Any, Dict, Optional

import numpy as np

from torchpruner_tpu import obs

ENV_VAR = "TORCHPRUNER_CHAOS"


class InjectedResourceExhausted(RuntimeError):
    """Synthetic OOM — message matches what ``guards.is_oom_error``
    looks for in a real ``XlaRuntimeError``."""

    def __init__(self, step: int):
        super().__init__(
            f"RESOURCE_EXHAUSTED: chaos-injected allocation failure at "
            f"step {step} (out of memory simulation)"
        )


class InjectedDataError(OSError):
    """Synthetic transient data-loading failure (retryable)."""


@dataclass
class ChaosConfig:
    """All knobs default to 'never fires'."""

    #: poison this global step's batch with NaNs (→ NaN loss/grads)
    nan_at_step: int = -1
    #: SIGKILL the process at this step's boundary (before it computes)
    kill_at_step: int = -1
    #: raise a synthetic RESOURCE_EXHAUSTED at this step's boundary
    oom_at_step: int = -1
    #: raise a transient OSError from the data stream at this step
    fail_data_at_step: int = -1
    #: flip bytes inside the next checkpoint written after this is set
    corrupt_checkpoint: bool = False
    #: one-shot sleep injected into host callbacks / data fetch
    delay_callback_s: float = 0.0
    #: per-decode-step sleep injected into a SERVING engine's loop (ms)
    #: — the "slow replica" fleet fault: the process stays live and
    #: correct but its tail latency degrades until the router's
    #: health/SLO view routes around it.  Fires every step (not once).
    slow_steps_ms: float = 0.0
    #: each injection fires at most once per process (default) — set
    #: False only in unit tests that want repeat fires
    once: bool = True

    @classmethod
    def from_any(cls, spec) -> "ChaosConfig":
        """Build from a dict, JSON string, JSON file path, or None."""
        if spec is None:
            return cls()
        if isinstance(spec, cls):
            return spec
        if isinstance(spec, str):
            if os.path.exists(spec):
                with open(spec) as f:
                    spec = json.load(f)
            else:
                spec = json.loads(spec)
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(spec) - known
        if unknown:
            raise ValueError(
                f"unknown chaos keys: {sorted(unknown)} "
                f"(known: {sorted(known)})"
            )
        return cls(**spec)

    def any_active(self) -> bool:
        return (
            self.nan_at_step >= 0 or self.kill_at_step >= 0
            or self.oom_at_step >= 0 or self.fail_data_at_step >= 0
            or self.corrupt_checkpoint or self.delay_callback_s > 0
            or self.slow_steps_ms > 0
        )


_cfg: Optional[ChaosConfig] = None
_fired: set = set()


def configure(spec=None) -> Optional[ChaosConfig]:
    """Install a process-wide chaos config (dict / JSON string / path /
    ChaosConfig / None).  Falls back to the ``TORCHPRUNER_CHAOS`` env var
    when ``spec`` is empty; installs nothing when neither names an
    active injection.  Returns the installed config (or None)."""
    global _cfg
    if not spec:
        spec = os.environ.get(ENV_VAR) or None
    cfg = ChaosConfig.from_any(spec) if spec else None
    if cfg is not None and not cfg.any_active():
        cfg = None
    _cfg = cfg
    _fired.clear()
    return _cfg


def disable() -> None:
    """Uninstall chaos unconditionally — unlike ``configure({})``, this
    does NOT fall back to the ``TORCHPRUNER_CHAOS`` env var, so cleanup
    code (bench legs, test fixtures) cannot accidentally re-arm an
    env-configured injection with a fresh fired-set."""
    global _cfg
    _cfg = None
    _fired.clear()


def active() -> bool:
    return _cfg is not None


def disarm_through(step: int) -> None:
    """Mark every step-indexed injection at or before ``step`` as fired.

    Resume safety: chaos persisted in a config file / env survives into
    the resumed process with a fresh ``_fired`` set.  When a commit
    boundary coincides with ``kill_at_step``, the restored step counter
    re-enters exactly the kill step and the run would die on every
    resume, never progressing.  The resilient runners call this with the
    restored step count so already-survived injections stay behind the
    cursor."""
    if _cfg is None:
        return
    for kind, at in (("nan", _cfg.nan_at_step), ("kill", _cfg.kill_at_step),
                     ("oom", _cfg.oom_at_step),
                     ("data", _cfg.fail_data_at_step)):
        if 0 <= at <= step:
            _fired.add(kind)


def get() -> Optional[ChaosConfig]:
    return _cfg


def _fires(kind: str, at: int, step: int) -> bool:
    if at < 0 or step != at:
        return False
    if _cfg.once and kind in _fired:
        return False
    _fired.add(kind)
    obs.inc("chaos_injections_total", help="chaos faults injected")
    return True


# -- hooks (call sites guard on active() for zero-cost no-ops) --------------


def maybe_kill(step: int) -> None:
    """SIGKILL this process at the configured step boundary — the
    unhandleable death a preempted TPU VM actually gets."""
    if _cfg is None or not _fires("kill", _cfg.kill_at_step, step):
        return
    with obs.span("chaos:kill", step=step):
        pass
    # flush whatever telemetry exists; SIGKILL allows no atexit
    obs.shutdown()
    os.kill(os.getpid(), signal.SIGKILL)


def maybe_oom(step: int) -> None:
    if _cfg is None or not _fires("oom", _cfg.oom_at_step, step):
        return
    with obs.span("chaos:oom", step=step):
        pass
    raise InjectedResourceExhausted(step)


def poison_batch(step: int, x):
    """Return ``x`` NaN-poisoned at the configured step — the forward
    then produces a NaN loss and NaN gradients, exercising the compiled
    non-finite guard end to end (detection, skip, rollback).

    Integer batches (LM token ids) cannot carry a NaN: ``full_like``
    would silently unsafe-cast to INT_MIN, embedding gathers clamp it,
    the loss stays finite, and the drill would report success while
    testing nothing.  That case logs a loud warning and leaves the
    batch untouched (the injection still counts as fired, keeping the
    step schedule deterministic)."""
    if _cfg is None or not _fires("nan", _cfg.nan_at_step, step):
        return x
    arr = np.asarray(x)
    if not np.issubdtype(arr.dtype, np.floating):
        import logging

        logging.getLogger("torchpruner_tpu").warning(
            "[chaos] nan_at_step=%d: batch dtype %s cannot represent "
            "NaN — injection skipped (poison a float input, or use "
            "oom_at_step/kill_at_step for integer-input models)",
            step, arr.dtype,
        )
        return x
    with obs.span("chaos:nan_grads", step=step):
        pass
    return np.full_like(arr, np.nan)


def maybe_fail_data(step: int) -> None:
    """Raise a transient OSError out of the data stream — what the
    ``retry`` wrapper around batch fetching exists to absorb."""
    if _cfg is None or not _fires("data", _cfg.fail_data_at_step, step):
        return
    with obs.span("chaos:data_fail", step=step):
        pass
    raise InjectedDataError(
        f"chaos: transient data-loading failure at step {step}"
    )


def maybe_slow_step() -> None:
    """Per-step stall for a SERVING engine (``slow_steps_ms``) — unlike
    :func:`maybe_delay` this fires on EVERY decode step, degrading the
    replica's per-token latency without killing it.  The fleet drill
    injects it into one replica's env to exercise SLO-driven routing."""
    if _cfg is None or _cfg.slow_steps_ms <= 0:
        return
    if "slow" not in _fired:
        # count the injection once; the sleeps themselves are the fault
        _fired.add("slow")
        obs.inc("chaos_injections_total", help="chaos faults injected")
    time.sleep(_cfg.slow_steps_ms / 1e3)


def maybe_delay() -> None:
    """One-shot host-callback stall (prefetch hiccup, slow NFS read)."""
    if _cfg is None or _cfg.delay_callback_s <= 0:
        return
    if _cfg.once and "delay" in _fired:
        return
    _fired.add("delay")
    with obs.span("chaos:delay", seconds=_cfg.delay_callback_s):
        time.sleep(_cfg.delay_callback_s)


def corrupt_checkpoint_bytes(path: str, *, force: bool = False) -> bool:
    """Flip bytes in the largest array file under checkpoint ``path`` —
    the torn-write/bitrot case ``restore_checkpoint``'s digest must
    catch.  Fires when the installed config's ``corrupt_checkpoint`` is
    set (once); ``force=True`` corrupts unconditionally (tests/bench
    calling it directly on a checkpoint dir).  Returns True when
    something was corrupted."""
    if not force:
        if _cfg is None or not _cfg.corrupt_checkpoint:
            return False
        if _cfg.once and "corrupt" in _fired:
            return False
        _fired.add("corrupt")
        obs.inc("chaos_injections_total", help="chaos faults injected")
    arrays = os.path.join(path, "arrays")
    if os.path.isdir(arrays):
        path = arrays  # corrupt the digest-sealed payload, not spec.json
    victim, size = None, 0
    for root, _dirs, files in os.walk(path):
        for fn in files:
            fp = os.path.join(root, fn)
            try:
                s = os.path.getsize(fp)
            except OSError:
                continue
            if s > size:
                victim, size = fp, s
    if victim is None or size == 0:
        return False
    with obs.span("chaos:corrupt_checkpoint", file=os.path.basename(victim)):
        with open(victim, "r+b") as f:
            f.seek(size // 2)
            chunk = f.read(64) or b"\0"
            f.seek(size // 2)
            f.write(bytes(b ^ 0xFF for b in chunk))
    return True
